"""IMPALA/APPO (async actor-learner, V-trace) and offline RL (BC/CQL).

Acceptance per VERDICT round-3 #3: IMPALA learns CartPole DISTRIBUTED
(async env-runner actors streaming rollouts to the learner), and an
offline algorithm trains from a parquet dataset. References:
``rllib/algorithms/impala/impala.py``, ``rllib/algorithms/appo/appo.py``,
``rllib/offline/offline_data.py``.
"""

import numpy as np
import pytest

from ray_tpu.rllib import (
    APPOConfig,
    BCConfig,
    CartPole,
    CQLConfig,
    IMPALAConfig,
    collect_offline_data,
)
from ray_tpu.rllib.impala import make_vtrace_loss
from ray_tpu.rllib.models import init_policy

import jax


def _cartpole_heuristic(obs: np.ndarray) -> np.ndarray:
    """A decent hand policy: push toward the pole's lean (return ~100+)."""
    return (obs[:, 2] + 0.5 * obs[:, 3] > 0).astype(np.int64)


def test_vtrace_loss_shapes_and_on_policy_sanity():
    """On-policy (behavior == target) with unclipped ratios, V-trace's rho
    is ~1 and the loss is finite with sane metrics."""
    key = jax.random.PRNGKey(0)
    params = init_policy(key, 4, 2, 32)
    T, N = 8, 3
    rng = np.random.default_rng(0)
    obs = rng.normal(size=(T, N, 4)).astype(np.float32)
    from ray_tpu.rllib.models import forward

    logits, _ = forward(params, obs.reshape(T * N, -1))
    logits = np.asarray(logits).reshape(T, N, -1)
    logp_all = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
    actions = rng.integers(0, 2, (T, N))
    logp_old = np.take_along_axis(logp_all, actions[..., None], axis=2)[..., 0]
    batch = {
        "obs": obs,
        "actions": actions,
        "logp_old": logp_old.astype(np.float32),
        "rewards": np.ones((T, N), np.float32),
        "dones": np.zeros((T, N), np.bool_),
        "trunc_values": np.zeros((T, N), np.float32),
        "last_obs": rng.normal(size=(N, 4)).astype(np.float32),
    }
    loss_fn = make_vtrace_loss(0.99, 0.5, 0.01, 1.0, 1.0)
    loss, metrics = loss_fn(params, batch)
    assert np.isfinite(float(loss))
    assert abs(float(metrics["mean_rho"]) - 1.0) < 1e-4
    assert float(metrics["clipped_rho_frac"]) <= 0.51


def test_impala_cartpole_learns_distributed(ray_cluster):
    """The flagship async test: remote env runners sample continuously;
    the learner consumes completions out of order; returns improve."""
    algo = (
        IMPALAConfig()
        .environment(CartPole)
        .env_runners(num_env_runners=2, num_envs_per_runner=8, rollout_len=64)
        .training(lr=2e-3, num_batches_per_iteration=4)
        .seeding(0)
        .build()
    )
    try:
        first = algo.train()["episode_return_mean"]
        result = {}
        for _ in range(24):
            result = algo.train()
    finally:
        algo.stop()
    assert result["episode_return_mean"] > max(60.0, 2 * max(first, 10.0)), (
        f"no learning: {first} -> {result['episode_return_mean']}"
    )


def test_appo_smoke(ray_cluster):
    """APPO (clipped surrogate on V-trace) completes async iterations."""
    algo = (
        APPOConfig()
        .environment(CartPole)
        .env_runners(num_env_runners=2, num_envs_per_runner=4, rollout_len=32)
        .training(num_batches_per_iteration=2)
        .build()
    )
    try:
        m = algo.train()
        assert "policy_loss" in m and np.isfinite(m["policy_loss"])
        assert algo.train()["training_iteration"] == 2
    finally:
        algo.stop()


def test_impala_rejects_learner_sharding():
    with pytest.raises(ValueError, match="num_learners=0"):
        IMPALAConfig().environment(CartPole).learners(num_learners=2).build()


@pytest.fixture(scope="module")
def offline_dataset(tmp_path_factory):
    path = str(tmp_path_factory.mktemp("offline") / "cartpole")
    n = collect_offline_data(
        CartPole, 4000, path, num_envs=8, seed=0,
        policy_fn=_cartpole_heuristic, epsilon=0.2)
    assert n >= 4000
    return path


def test_bc_learns_from_parquet(ray_cluster, offline_dataset):
    """Behavior cloning from recorded parquet transitions recovers a
    policy clearly better than random (~20 on CartPole)."""
    algo = (
        BCConfig()
        .environment(None)
        .offline_data(dataset_path=offline_dataset, batch_size=256,
                      updates_per_iteration=64)
        .evaluation(eval_env_cls=CartPole, eval_episodes=4)
        .training(lr=3e-3)
        .build()
    )
    result = {}
    for _ in range(8):
        result = algo.train()
    algo.stop()
    assert result["action_accuracy"] > 0.85
    assert result["episode_return_mean"] > 60.0, result


def test_cql_trains_from_parquet(ray_cluster, offline_dataset):
    """Discrete CQL: TD + conservative regularizer train to finite losses
    and a policy above random from the same dataset."""
    algo = (
        CQLConfig()
        .environment(None)
        .offline_data(dataset_path=offline_dataset, batch_size=256,
                      updates_per_iteration=64)
        .evaluation(eval_env_cls=CartPole, eval_episodes=4)
        .training(gamma=0.99, cql_alpha=1.0)
        .build()
    )
    result = {}
    for _ in range(10):
        result = algo.train()
    algo.stop()
    assert np.isfinite(result["td_loss"]) and np.isfinite(result["cql_regularizer"])
    assert result["episode_return_mean"] > 35.0, result


def test_marwil_trains_from_parquet(ray_cluster, offline_dataset):
    """MARWIL (advantage-weighted BC, ref rllib/algorithms/marwil):
    trains to finite losses from the same transitions and reaches a
    policy above random; the advantage norm adapts from its 1.0 init."""
    from ray_tpu.rllib import MARWILConfig

    algo = (
        MARWILConfig()
        .environment(None)
        .offline_data(dataset_path=offline_dataset, batch_size=256,
                      updates_per_iteration=64)
        .evaluation(eval_env_cls=CartPole, eval_episodes=4)
        .training(lr=3e-3, beta=1.0)
        .build()
    )
    result = {}
    for _ in range(8):
        result = algo.train()
    algo.stop()
    assert np.isfinite(result["marwil_loss"]) and np.isfinite(result["vf_loss"])
    assert result["adv_norm"] != 1.0  # the moving c actually updates
    assert result["episode_return_mean"] > 35.0, result
