"""Compiled-loop training (round 15): the structured step spec rides the
persistent graph (``train/loop.py``) — loop-vs-eager byte parity,
checkpoint-commit overlap, and chaos-killed stage recovery from the
GCS-registered async checkpoint."""

import os

import pytest

from ray_tpu.train import (
    DataParallelTrainer,
    FailureConfig,
    RunConfig,
    ScalingConfig,
    TrainLoopConfig,
)


def _make_fns(slow=False):
    """Closure-built step spec fns: cloudpickle ships closures by VALUE,
    so the stage actors never need this test module importable."""
    import numpy as np

    def init_fn(config):
        rng = np.random.default_rng(config.get("seed", 0))
        return {"w": rng.standard_normal(config.get("dim", 64)), "count": 0}

    def data_fn(config):
        def gen():
            rng = np.random.default_rng(123)
            while True:
                yield rng.standard_normal(config.get("dim", 64))
        return gen()

    def step_fn(state, batch):
        if slow:
            import time

            time.sleep(0.05)
        w = state["w"] - 0.01 * (state["w"] - batch)
        count = state["count"] + 1
        loss = float(np.square(w - batch).mean())
        return ({"w": w, "count": count},
                {"loss": loss, "step": count - 1, "count": count})

    return init_fn, data_fn, step_fn


def _spec(num_steps=6, snapshot_every=2, hook=None, slow=False, credits=2):
    init_fn, data_fn, step_fn = _make_fns(slow=slow)
    return TrainLoopConfig(
        step_fn=step_fn, init_fn=init_fn, data_fn=data_fn,
        num_steps=num_steps, snapshot_every=snapshot_every,
        credits=credits, stage_init_hook=hook)


def _fit(tmp_path, name, use_loop, spec, max_failures=0, config=None):
    trainer = DataParallelTrainer(
        spec,
        train_loop_config=config or {"seed": 7},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name=name, storage_path=str(tmp_path),
                             failure_config=FailureConfig(
                                 max_failures=max_failures)),
        use_compiled_loop=use_loop,
    )
    return trainer.fit()


def test_loop_vs_eager_byte_parity(ray_cluster, tmp_path):
    """The parity contract: both drive modes run the SAME stage actors
    in the SAME order, so at a fixed seed the step metrics AND the final
    committed state are byte-identical — the compiled loop changes the
    dispatch path, never the math."""
    from ray_tpu.resilience.checkpoint import load_checkpoint

    spec_e = _spec(num_steps=6, snapshot_every=2)
    spec_l = _spec(num_steps=6, snapshot_every=2)
    res_e = _fit(tmp_path, "tl_parity_eager", False, spec_e)
    res_l = _fit(tmp_path, "tl_parity_loop", True, spec_l)
    assert res_e.error is None, res_e.error
    assert res_l.error is None, res_l.error
    assert len(res_e.metrics_history) == 6
    # metrics byte-identical, step for step
    assert res_l.metrics_history == res_e.metrics_history
    assert res_e.loop_stats["mode"] == "eager"
    assert res_l.loop_stats["mode"] == "loop"
    # final committed state byte-identical
    assert res_e.checkpoint is not None and res_l.checkpoint is not None
    tree_e, meta_e = load_checkpoint(res_e.checkpoint.path)
    tree_l, meta_l = load_checkpoint(res_l.checkpoint.path)
    assert meta_e["step"] == meta_l["step"] == 5
    assert tree_e["count"] == tree_l["count"] == 6
    assert tree_e["w"].tobytes() == tree_l["w"].tobytes()


def test_ckpt_commit_overlaps_compute(ray_cluster, tmp_path):
    """The checkpoint stage commits while the step stage computes the
    NEXT steps (pipelined over the ring credits): loop-mode
    train_ckpt_overlap_frac must be positive, while the eager drive —
    one serialized dispatch chain per step — is structurally zero."""
    cfg = {"seed": 7, "dim": 1 << 18}  # ~2 MB f64 state: a real commit
    spec_l = _spec(num_steps=6, snapshot_every=1, slow=True, credits=4)
    res_l = _fit(tmp_path, "tl_overlap_loop", True, spec_l, config=cfg)
    assert res_l.error is None, res_l.error
    stats = res_l.loop_stats
    assert stats["ckpt_commits"] == 6
    assert stats["train_ckpt_overlap_frac"] is not None
    assert stats["train_ckpt_overlap_frac"] > 0.0, stats
    # the step never blocked on the write: host-snapshot block only
    assert stats["ckpt_save_block_ms"] < 1000.0

    spec_e = _spec(num_steps=6, snapshot_every=1, slow=True, credits=4)
    res_e = _fit(tmp_path, "tl_overlap_eager", False, spec_e, config=cfg)
    assert res_e.error is None, res_e.error
    # eager serializes commit against the next dispatch: zero overlap
    assert res_e.loop_stats["train_ckpt_overlap_frac"] == 0.0


def _chaos_hook(marker_path):
    def hook(stage_name, config):
        if stage_name != "step" or os.path.exists(marker_path):
            return
        open(marker_path, "w").write("x")
        from ray_tpu import chaos as _chaos

        plan = {"name": "train-step-kill", "faults": [
            {"kind": "kill_loop_stage", "nth": 4, "max_injections": 1}]}
        _chaos.install(_chaos.FaultPlan.from_dict(plan), 0, publish=False)
    return hook


@pytest.mark.chaos
def test_step_stage_death_resumes_from_gcs_ckpt(ray_cluster, tmp_path):
    """kill_loop_stage fired inside the TRAIN-STEP stage mid-run: the
    loop tears down within the dag-loop cascade bounds, the controller's
    failure policy restarts the stage group, and the resumed attempt
    continues from the latest GCS-registered async checkpoint — the
    ckpt lag is bounded by snapshot_every + the in-flight credit window.
    RecoveryVerifier must come back green."""
    from ray_tpu.chaos.verifier import RecoveryVerifier

    verifier = RecoveryVerifier(timeout_s=60)
    baseline = verifier.snapshot_baseline()

    marker = str(tmp_path / "chaos_installed_once")
    spec = _spec(num_steps=8, snapshot_every=1, hook=_chaos_hook(marker),
                 credits=2)
    res = _fit(tmp_path, "tl_chaos", True, spec, max_failures=1)
    assert res.error is None, res.error
    # the run completed all 8 global steps across the two attempts:
    # `count` rides the checkpointed state, so a lossless resume ends at
    # exactly 8 regardless of how many steps replayed
    assert res.metrics_history[-1]["count"] == 8
    # exactly one recovery, stamped and resumed
    assert len(res.recovery_events) == 1
    ev = res.recovery_events[0]
    assert ev["resume_path"], "resume did not come from a registered ckpt"
    assert ev["resumed_clock"] is not None
    # ckpt lag bound: the kill fired at the 4th step tick (steps 0-2
    # complete); the committed horizon can trail by at most the credit
    # window, so the resumed attempt restarts no earlier than step 1
    assert ev["resume_step"] is not None and ev["resume_step"] >= 1, ev
    assert ev["resume_step"] <= 4, ev
    result = verifier.verify(baseline)
    assert result.ok, result.violations


def test_loop_spec_requires_structured_mode(ray_cluster, tmp_path):
    """A closure train_fn with use_compiled_loop=True is ignored (eager
    closure mode stays the default fallback path untouched)."""
    from ray_tpu import train

    def train_fn(config):
        train.report({"ok": 1})

    trainer = DataParallelTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="tl_closure", storage_path=str(tmp_path)),
        use_compiled_loop=True,
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics == {"ok": 1}
    assert result.loop_stats is None
