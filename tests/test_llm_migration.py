"""Disaggregated prefill/decode serving + live KV-page migration (round 11).

The object-manager idea applied to the KV cache: a prefill replica's
pages MOVE to a decode replica (chunked stream over a credit-based TCP
loop channel) instead of being recomputed, an affinity spill migrates
the group's hot pages instead of throwing them away (PR-10 residue b),
and refcount-0 trie pages evicted under pressure spill to host RAM and
restore on a later hit. Every path's acceptance bar is greedy BYTE
PARITY against full recompute, and every failure mode (pressure,
source death mid-migration) must degrade to a clean cold prefill.
"""

import dataclasses
import json
import threading
import time
import urllib.request

import jax
import jax.numpy as jnp
import pytest

import ray_tpu
from ray_tpu.llm.engine import InferenceEngine, Request
from ray_tpu.llm.migration import KVMigrationSource, receive_kv_stream
from ray_tpu.models.llama import PRESETS, forward, init_params


@pytest.fixture(scope="module")
def small_model():
    cfg = dataclasses.replace(PRESETS["debug"], dtype=jnp.float32,
                              attn_impl="reference")
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def naive_greedy(params, cfg, prompt, n):
    toks, out = list(prompt), []
    for _ in range(n):
        logits = forward(params, jnp.asarray([toks]), cfg)[0, -1]
        t = int(jnp.argmax(logits))
        out.append(t)
        toks.append(t)
    return out


def _drain(eng, req):
    while not req.done:
        eng.step()


def test_prefill_only_retires_without_sampling(small_model):
    """A prefill_only request computes the prompt's KV, registers it in
    the trie, and retires with finish_reason 'prefilled' — no token is
    ever sampled, and pin_for_export keeps the pages refcounted until
    the exporter releases them."""
    cfg, params = small_model
    eng = InferenceEngine(cfg, params, max_slots=2, max_len=64, page_size=8)
    prompt = list(range(1, 20))
    r = Request("p", list(prompt), max_new_tokens=1,
                prefill_only=True, pin_for_export=True)
    eng.add_request(r)
    _drain(eng, r)
    assert r.finish_reason == "prefilled" and not r.generated
    assert r.export_pinned, "retire must pin pages for the exporter"
    # pages are registered: a follow-up maps them as ordinary hits
    b = Request("b", list(prompt), max_new_tokens=4)
    eng.add_request(b)
    _drain(eng, b)
    assert b.cached_prefix_tokens == 18
    assert b.generated == naive_greedy(params, cfg, prompt, 4)
    eng.release_export_pins(r)
    assert not r.export_pinned


def test_export_import_roundtrip_parity(small_model):
    """ISSUE 11 acceptance: byte-parity roundtrip of the page payload —
    full blocks AND the partial tail block — between two engines, for a
    uniform resend and a mid-tail divergence."""
    cfg, params = small_model
    a = InferenceEngine(cfg, params, max_slots=2, max_len=64, page_size=8)
    prompt = list(range(1, 20))  # 2 full pages + 3-row tail
    r = Request("p", list(prompt), max_new_tokens=1,
                prefill_only=True, pin_for_export=True)
    a.add_request(r)
    _drain(a, r)
    payload = a.export_prefix_kv(prompt)
    a.release_export_pins(r)
    assert payload["full_pages"] == 2 and payload["partial_len"] == 2
    assert payload["k"].shape[1] == 3  # 2 full + 1 tail page

    b = InferenceEngine(cfg, params, max_slots=2, max_len=64, page_size=8)
    assert b.import_prefix_kv(payload) == 18
    assert b.metrics["kv_migrations_in"] == 1
    rb = Request("b", list(prompt), max_new_tokens=4)
    b.add_request(rb)
    _drain(b, rb)
    assert rb.cached_prefix_tokens == 18
    assert rb.generated == naive_greedy(params, cfg, prompt, 4)

    # Mid-tail divergence: the imported partial still COW-forks safely.
    div = prompt[:17] + [99, 98]
    rc = Request("c", list(div), max_new_tokens=4)
    b.add_request(rc)
    _drain(b, rc)
    assert rc.cached_prefix_tokens == 17
    assert rc.generated == naive_greedy(params, cfg, div, 4)

    # Duplicate import: already-resident links free straight back.
    free_before = len(b.allocator.free) + sum(
        1 for p in b.allocator.page_hash
        if b.allocator.refcount.get(p, 0) == 0)
    assert b.import_prefix_kv(a.export_prefix_kv(prompt)) == 18
    free_after = len(b.allocator.free) + sum(
        1 for p in b.allocator.page_hash
        if b.allocator.refcount.get(p, 0) == 0)
    assert free_after == free_before  # no pages leaked to duplicates


def test_import_under_pressure_falls_back_cold(small_model):
    """A reservation failure on import is a clean no-op: the payload is
    dropped, the metric counts it, and the request cold-prefills with
    full parity."""
    cfg, params = small_model
    a = InferenceEngine(cfg, params, max_slots=2, max_len=64, page_size=8)
    prompt = list(range(1, 20))
    r = Request("p", list(prompt), max_new_tokens=1,
                prefill_only=True, pin_for_export=True)
    a.add_request(r)
    _drain(a, r)
    payload = a.export_prefix_kv(prompt)
    a.release_export_pins(r)

    tiny = InferenceEngine(cfg, params, max_slots=2, max_len=64,
                           page_size=8, num_pages=2)
    assert tiny.import_prefix_kv(payload) == 0
    assert tiny.metrics["kv_import_failures"] == 1
    assert tiny.metrics["kv_pages_imported"] == 0
    short = prompt[:12]  # fits the 2-page pool
    rc = Request("c", list(short), max_new_tokens=3)
    tiny.add_request(rc)
    _drain(tiny, rc)
    assert rc.cached_prefix_tokens == 0
    assert rc.generated == naive_greedy(params, cfg, short, 3)


def test_streamed_migration_overlaps_prefill(small_model):
    """The migration source streams pages WHILE later chunks are still
    prefilling; the importer lands them chunk-by-chunk and the follow-up
    request decodes byte-identically."""
    cfg, params = small_model
    prompt = list(range(1, 40))  # 4 full pages + 7-row tail
    a = InferenceEngine(cfg, params, max_slots=2, max_len=64, page_size=8,
                        prefill_chunk_size=8)
    r = Request("p", list(prompt), max_new_tokens=1,
                prefill_only=True, pin_for_export=True)
    a.add_request(r)
    src = KVMigrationSource(a, r, chunk_pages=1)
    driver = threading.Thread(target=_drain, args=(a, r))
    driver.start()
    b = InferenceEngine(cfg, params, max_slots=2, max_len=64, page_size=8)
    stats = receive_kv_stream(b, src.address, timeout_s=30)
    driver.join()
    src.close()
    assert stats["complete"] and stats["cached_tokens"] == 39, stats
    assert stats["pages"] == 5 and stats["bytes"] > 0
    rb = Request("b", list(prompt), max_new_tokens=4)
    b.add_request(rb)
    _drain(b, rb)
    assert rb.cached_prefix_tokens == 38  # match caps at len-1
    assert rb.generated == naive_greedy(params, cfg, prompt, 4)
    assert not r.export_pinned  # source released its pins


def test_source_death_mid_migration_imports_prefix(small_model):
    """Chaos: the source dies mid-stream (the channel drops exactly as a
    killed prefill replica's would). The importer keeps the contiguous
    prefix it received — a prefix of a valid chain is a valid chain —
    and the request cold-prefills only the rest, byte-identically."""
    cfg, params = small_model
    prompt = list(range(1, 40))
    a = InferenceEngine(cfg, params, max_slots=2, max_len=64, page_size=8,
                        prefill_chunk_size=8)
    r = Request("p", list(prompt), max_new_tokens=1,
                prefill_only=True, pin_for_export=True)
    a.add_request(r)
    src = KVMigrationSource(a, r, chunk_pages=1, _die_after_chunks=2)
    driver = threading.Thread(target=_drain, args=(a, r))
    driver.start()
    c = InferenceEngine(cfg, params, max_slots=2, max_len=64, page_size=8)
    stats = receive_kv_stream(c, src.address, timeout_s=10)
    driver.join()
    assert not stats["complete"]
    assert 0 < stats["cached_tokens"] < 39, stats
    rc = Request("c", list(prompt), max_new_tokens=4)
    c.add_request(rc)
    _drain(c, rc)
    assert rc.cached_prefix_tokens == stats["cached_tokens"]
    assert rc.generated == naive_greedy(params, cfg, prompt, 4)
    # the dead source's engine still releases its export pins
    deadline = time.monotonic() + 10
    while r.export_pinned and time.monotonic() < deadline:
        time.sleep(0.05)
    assert not r.export_pinned


def test_spill_stream_exports_cached_prefix(small_model):
    """PR-11 residue (b) closed: the spill pull rides the CHUNKED
    migration stream — a static KVMigrationSource over already-cached
    trie pages, wire-identical to the live handoff (full blocks, tail,
    end), with the pins released when the stream drains."""
    cfg, params = small_model
    prompt = list(range(1, 40))  # 4 full pages + 7-row tail
    a = InferenceEngine(cfg, params, max_slots=2, max_len=64, page_size=8)
    r = Request("prime", list(prompt), max_new_tokens=1)
    a.add_request(r)
    _drain(a, r)  # retire registers the chain in the trie
    src = KVMigrationSource.for_cached_prefix(a, prompt, chunk_pages=1)
    assert src is not None
    b = InferenceEngine(cfg, params, max_slots=2, max_len=64, page_size=8)
    stats = receive_kv_stream(b, src.address, timeout_s=30)
    src.close()
    assert stats["complete"], stats
    # the trie match caps at len-1 (the last token's hidden state seeds
    # sampling), so 4 full pages + a 6-row tail = 38 tokens travel
    assert stats["cached_tokens"] == 38, stats
    rb = Request("b", list(prompt), max_new_tokens=4)
    b.add_request(rb)
    _drain(b, rb)
    assert rb.cached_prefix_tokens == 38
    assert rb.generated == naive_greedy(params, cfg, prompt, 4)
    # pins released: every exported page is refcount-0 cached again
    assert all(a.allocator.refcount.get(p, 0) == 0
               for p in a.allocator.page_hash)
    # nothing cached for an unknown prompt -> no stream
    assert KVMigrationSource.for_cached_prefix(a, [99, 98, 97]) is None


def test_spill_stream_source_death_serves_partial_plus_cold(small_model):
    """Regression (ISSUE 12 satellite): source death mid-SPILL-pull
    degrades exactly like the disaggregation path — the target keeps the
    contiguous prefix received, cold-prefills the suffix, and the
    output is byte-identical to a full recompute."""
    cfg, params = small_model
    prompt = list(range(1, 40))
    a = InferenceEngine(cfg, params, max_slots=2, max_len=64, page_size=8)
    r = Request("prime", list(prompt), max_new_tokens=1)
    a.add_request(r)
    _drain(a, r)
    src = KVMigrationSource.for_cached_prefix(a, prompt, chunk_pages=1,
                                              _die_after_chunks=2)
    c = InferenceEngine(cfg, params, max_slots=2, max_len=64, page_size=8)
    stats = receive_kv_stream(c, src.address, timeout_s=10)
    assert not stats["complete"]
    assert 0 < stats["cached_tokens"] < 38, stats
    rc = Request("c", list(prompt), max_new_tokens=4)
    c.add_request(rc)
    _drain(c, rc)
    assert rc.cached_prefix_tokens == stats["cached_tokens"]
    assert rc.generated == naive_greedy(params, cfg, prompt, 4)
    # the dying source still released its export pins
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and any(
            a.allocator.refcount.get(p, 0)
            for p in a.allocator.page_hash):
        time.sleep(0.05)
    assert all(a.allocator.refcount.get(p, 0) == 0
               for p in a.allocator.page_hash)


def test_tiered_kv_host_spill_and_restore(small_model):
    """Stretch (d): refcount-0 trie pages evicted under pressure spill
    to host RAM keyed by chain hash and restore on a later match_prefix
    hit instead of dying — with byte parity."""
    cfg, params = small_model
    eng = InferenceEngine(cfg, params, max_slots=2, max_len=64, page_size=8,
                          num_pages=4, host_kv_cache_pages=8)
    first = list(range(1, 18))
    r1 = Request("x", list(first), max_new_tokens=1)
    eng.add_request(r1)
    _drain(eng, r1)
    # pressure: a second long prompt evicts x's cached pages
    r2 = Request("y", [50 + i for i in range(17)], max_new_tokens=1)
    eng.add_request(r2)
    _drain(eng, r2)
    assert eng.metrics["host_kv_spilled_pages"] > 0
    r3 = Request("x2", list(first), max_new_tokens=3)
    eng.add_request(r3)
    _drain(eng, r3)
    assert eng.metrics["host_kv_restored_pages"] > 0
    assert r3.cached_prefix_tokens >= 8  # ≥ one restored page
    assert r3.generated == naive_greedy(params, cfg, first, 3)
    # disabled tier spills nothing
    off = InferenceEngine(cfg, params, max_slots=2, max_len=64, page_size=8,
                          num_pages=4)
    assert off.allocator.on_evict is None


def test_router_ships_migrate_from_on_spill():
    """Router unit: a load-aware spill (and a saturation spill) reports
    the still-alive previous replica through spill_out; a repick of the
    affine replica or a dead one reports nothing."""
    from ray_tpu.core.config import get_config
    from ray_tpu.serve.router import Router

    from collections import OrderedDict

    cfg = get_config()
    saved = cfg.serve_affinity_spill_margin
    cfg.serve_affinity_spill_margin = 1
    try:
        class _A:  # stand-in actor with an id
            def __init__(self, b):
                self._actor_id = b

        ids = {"r1": b"\x01" * 8, "r2": b"\x02" * 8}
        router = Router.__new__(Router)
        router._key = "replicas::app::dep"
        router._lock = threading.Lock()
        router._cond = threading.Condition(router._lock)
        router._replicas = {rid: {"actor": _A(b), "max_ongoing": 8}
                            for rid, b in ids.items()}
        router._inflight = {"r1": 0, "r2": 0}
        router._model_affinity = {}
        router._group_affinity = OrderedDict()
        router.affinity_stats = {"hits": 0, "misses": 0, "spills": 0,
                                 "new_groups": 0}
        router.spill_migrations = 0
        router._init_overload_state()
        spill = {}
        first, _ = router.assign_replica(prefix_group="g", spill_out=spill)
        assert "migrate_from" not in spill  # new group: nothing to migrate
        router.release(first)
        other = "r2" if first == "r1" else "r1"
        with router._cond:
            router._inflight[first] += 2  # past margin 1
        spill = {}
        rid, _ = router.assign_replica(prefix_group="g", spill_out=spill)
        assert rid == other
        assert spill["migrate_from"] == first
        assert spill["actor_id"] == ids[first].hex()
        # dead previous replica: purged, no source shipped
        router.release(rid)
        router.remove_replica(other)  # the group's new affine dies
        spill = {}
        rid2, _ = router.assign_replica(prefix_group="g", spill_out=spill)
        assert rid2 == first and "migrate_from" not in spill
        router.release(rid2)
    finally:
        cfg.serve_affinity_spill_margin = saved


def test_disaggregated_serve_end_to_end(ray_cluster):
    """ISSUE 11 acceptance: a request admitted at a prefill replica
    streams its first token from a decode replica through the REAL
    proxy, pool membership shows in serve.status(), the response is
    byte-identical to a unified deployment's, and the handoff leaves an
    ``llm.kv_migrate`` span in the trace."""
    from ray_tpu import serve
    from ray_tpu.llm import build_llm_app

    try:
        serve.run(build_llm_app("debug-128", max_slots=4, max_len=128),
                  name="llm-uni", route_prefix="/uni")
        addr = serve.http_address()
        body = json.dumps({"prompt": "hello disaggregated world",
                           "max_tokens": 8}).encode()

        def post(path, data):
            req = urllib.request.Request(
                addr + path, data=data,
                headers={"Content-Type": "application/json"})
            return urllib.request.urlopen(req, timeout=120)

        ref = json.loads(post("/uni/v1/completions", body).read())
        ref_text = ref["choices"][0]["text"]

        serve.run(build_llm_app("debug-128", max_slots=4, max_len=128,
                                serve_disaggregation="prefill_decode",
                                num_replicas=1, prefill_replicas=1),
                  name="llm-disagg", route_prefix="/dis")
        st = serve.status()["llm-disagg"]
        pools = {name: d.get("pool") for name, d in st.items()}
        assert pools == {"llm-decode": "decode", "llm-prefill": "prefill"}

        out = json.loads(post("/dis/v1/completions", body).read())
        assert out["choices"][0]["text"] == ref_text

        stream_body = json.dumps({"prompt": "hello disaggregated world",
                                  "max_tokens": 8, "stream": True}).encode()
        text = ""
        with post("/dis/v1/completions", stream_body) as resp:
            for line in resp:
                line = line.decode().strip()
                if line.startswith("data: ") and line != "data: [DONE]":
                    text += json.loads(line[6:])["choices"][0]["text"]
        assert text == ref_text

        # the handoff recorded llm.kv_migrate spans (flush ≈ every 5 s)
        from ray_tpu.util.state import list_spans

        deadline = time.monotonic() + 20
        spans = []
        while time.monotonic() < deadline and not spans:
            spans = [s for s in list_spans()
                     if s.get("name") == "llm.kv_migrate"
                     and s.get("attrs", {}).get("kind") == "disagg_handoff"]
            time.sleep(0.5)
        assert spans, "no llm.kv_migrate span reached the trace store"
        assert any(s["attrs"].get("cached_tokens", 0) > 0 for s in spans)
    finally:
        serve.shutdown()


def test_spill_migration_end_to_end(ray_cluster):
    """PR-10 residue (b) closed: with disaggregation OFF, an affinity
    spill's target imports the group's hot pages from the previous
    replica instead of cold-prefilling (router counter + engine
    metrics + byte parity)."""
    from ray_tpu import serve
    from ray_tpu.core.config import get_config
    from ray_tpu.llm import build_llm_app

    cfg = get_config()
    try:
        serve.run(build_llm_app("debug-128", num_replicas=2, max_slots=4,
                                max_len=256, page_size=16),
                  name="llm-spill", route_prefix="/spill")
        h = serve.get_app_handle("llm-spill").options(
            method_name="completions", prefix_group="grp-mig")
        prompt = "You are a helpful assistant. " * 4 + " tail"
        body = {"prompt": prompt, "max_tokens": 6}
        out1 = h.remote(body).result(timeout=120)
        router = h._get_router()
        affine = router._group_affinity["grp-mig"]
        bump = cfg.serve_affinity_spill_margin + 1
        with router._cond:
            router._inflight[affine] += bump
        try:
            out2 = h.remote(body).result(timeout=120)
        finally:
            with router._cond:
                router._inflight[affine] -= bump
        assert out2["choices"][0]["text"] == out1["choices"][0]["text"]
        assert router.spill_migrations == 1
        assert router._group_affinity["grp-mig"] != affine
        # the spill target's engine actually imported the pages
        m = h.options(method_name="engine_metrics",
                      prefix_group="grp-mig").remote().result(timeout=60)
        assert m["kv_migrations_in"] >= 1
        assert m["kv_pages_imported"] >= 1
    finally:
        serve.shutdown()


@pytest.mark.chaos
def test_prefill_replica_death_mid_migration_retries_cold(ray_cluster):
    """Chaos: kill the prefill replica while a handoff's migration
    stream is in flight. The client's retry must complete with correct
    bytes (served through the replacement prefill replica onto the
    decode pool) and the RecoveryVerifier must come back green."""
    from ray_tpu import serve
    from ray_tpu.chaos.verifier import RecoveryVerifier
    from ray_tpu.core.api import ActorHandle
    from ray_tpu.core.config import get_config
    from ray_tpu.llm import build_llm_app

    cfg = get_config()
    saved_chunk = cfg.kv_migration_chunk_pages
    cfg.kv_migration_chunk_pages = 1  # widen the mid-migration window
    verifier = RecoveryVerifier(timeout_s=90)
    baseline = verifier.snapshot_baseline()
    try:
        serve.run(build_llm_app("debug-128", max_slots=4, max_len=256,
                                page_size=16,
                                serve_disaggregation="prefill_decode",
                                num_replicas=1, prefill_replicas=1),
                  name="llm-chaos", route_prefix="/chaos")
        addr = serve.http_address()
        prompt = "c" * 180  # several chunks: the stream stays open a while
        body = json.dumps({"prompt": prompt, "max_tokens": 6,
                           "stream": True}).encode()

        def run_once(timeout=120.0):
            req = urllib.request.Request(
                addr + "/chaos/v1/completions", data=body,
                headers={"Content-Type": "application/json"})
            text = ""
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                for line in resp:
                    line = line.decode().strip()
                    if line.startswith("data: ") and line != "data: [DONE]":
                        text += json.loads(line[6:])["choices"][0]["text"]
            return text

        expected = run_once()  # healthy reference (also warms compiles)

        # resolve the prefill replica's actor from the routing table
        controller = ray_tpu.get_actor("SERVE_CONTROLLER")
        table = ray_tpu.get(controller.get_snapshot.remote(
            "replicas::llm-chaos::llm-prefill"), timeout=30)
        prefill_actor = ActorHandle(bytes.fromhex(table[0]["actor_id"]))

        # fire the request and kill the prefill replica mid-flight
        result: dict = {}

        def client():
            try:
                result["text"] = run_once()
            except Exception as e:
                result["error"] = f"{type(e).__name__}: {e}"

        t = threading.Thread(target=client)
        t.start()
        time.sleep(0.15)  # let admission + the migration stream begin
        ray_tpu.kill(prefill_actor)
        t.join(timeout=150)
        assert not t.is_alive()

        # Retry until the controller's replacement replica serves it.
        deadline = time.monotonic() + 120
        text, last_err = result.get("text"), result.get("error")
        while (text is None or text != expected) \
                and time.monotonic() < deadline:
            try:
                text = run_once(timeout=60.0)
            except Exception as e:
                last_err = f"{type(e).__name__}: {e}"
                time.sleep(1.0)
        assert text == expected, (text, last_err)
        result = verifier.verify(baseline)
        assert result.ok, result.violations
    finally:
        cfg.kv_migration_chunk_pages = saved_chunk
        serve.shutdown()
