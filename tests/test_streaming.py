"""Streaming generators (``num_returns="streaming"``).

Mirrors the reference's ``python/ray/tests/test_streaming_generator.py``:
items are consumable BEFORE the task finishes, errors propagate at the
failing index, backpressure pauses the producer, and a worker death
mid-stream retries the generator.
"""

import time

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(autouse=True)
def _cluster(ray_cluster):
    yield


def test_basic_streaming_task():
    @ray_tpu.remote(num_returns="streaming")
    def gen(n):
        for i in range(n):
            yield i * 10

    out = [ray_tpu.get(ref, timeout=60) for ref in gen.remote(5)]
    assert out == [0, 10, 20, 30, 40]


def test_items_arrive_before_task_finishes():
    @ray_tpu.remote(num_returns="streaming")
    def slow_gen():
        yield "first"
        time.sleep(5.0)
        yield "second"

    g = slow_gen.remote()
    t0 = time.monotonic()
    first = ray_tpu.get(next(g), timeout=30)
    elapsed = time.monotonic() - t0
    assert first == "first"
    # The first item must be visible well before the 5s sleep completes.
    assert elapsed < 4.0
    assert ray_tpu.get(next(g), timeout=30) == "second"
    with pytest.raises(StopIteration):
        next(g)


def test_streaming_empty_generator():
    @ray_tpu.remote(num_returns="streaming")
    def empty():
        return
        yield  # pragma: no cover

    assert list(empty.remote()) == []


def test_streaming_large_items_via_shm():
    @ray_tpu.remote(num_returns="streaming")
    def arrays():
        for i in range(3):
            yield np.full(300_000, i, dtype=np.float32)

    for i, ref in enumerate(arrays.remote()):
        arr = ray_tpu.get(ref, timeout=60)
        assert arr.shape == (300_000,)
        assert float(arr[0]) == float(i)


def test_streaming_error_mid_generation():
    @ray_tpu.remote(num_returns="streaming", max_retries=0)
    def bad_gen():
        yield 1
        yield 2
        raise ValueError("boom at index 2")

    g = bad_gen.remote()
    assert ray_tpu.get(next(g), timeout=60) == 1
    assert ray_tpu.get(next(g), timeout=60) == 2
    with pytest.raises(ValueError, match="boom"):
        next(g)


def test_streaming_not_a_generator():
    @ray_tpu.remote(num_returns="streaming", max_retries=0)
    def not_gen():
        return 42

    g = not_gen.remote()
    with pytest.raises(TypeError):
        next(g)


def test_streaming_actor_method():
    @ray_tpu.remote
    class Producer:
        def tokens(self, n):
            for i in range(n):
                yield f"tok{i}"

    p = Producer.remote()
    out = [ray_tpu.get(r, timeout=60) for r in p.tokens.options(num_returns="streaming").remote(4)]
    assert out == ["tok0", "tok1", "tok2", "tok3"]


def test_streaming_async_actor_generator():
    @ray_tpu.remote
    class AsyncProducer:
        async def tokens(self, n):
            import asyncio

            for i in range(n):
                await asyncio.sleep(0.01)
                yield i

    p = AsyncProducer.remote()
    out = [ray_tpu.get(r, timeout=60) for r in p.tokens.options(num_returns="streaming").remote(3)]
    assert out == [0, 1, 2]


def test_streaming_backpressure():
    """With backpressure=2 the producer must pause until items are consumed."""

    @ray_tpu.remote(num_returns="streaming", _generator_backpressure_num_objects=2)
    def gen():
        for i in range(6):
            yield (i, time.time())

    g = gen.remote()
    refs = []
    # Let the producer run ahead; it may produce at most ~backpressure items.
    time.sleep(2.0)
    t_consume_start = time.time()
    items = [ray_tpu.get(r, timeout=60) for r in g]
    assert [i for i, _ in items] == list(range(6))
    # Items beyond the backpressure window must be produced AFTER we began
    # consuming (the producer was paused during the 2s sleep).
    produced_late = [i for i, ts in items if ts >= t_consume_start]
    assert any(i >= 3 for i in produced_late), items


def test_streaming_retry_mid_items():
    """Kill the worker mid-stream: the generator retries and the consumer
    still sees every item (at-least-once re-report, deterministic ids)."""
    import os

    marker = "/tmp/raytpu_test_stream_mid_%d" % os.getpid()

    @ray_tpu.remote(num_returns="streaming", max_retries=2)
    def fragile(marker):
        for i in range(5):
            if i == 3 and not os.path.exists(marker):
                open(marker, "w").close()
                os._exit(1)
            yield i

    try:
        out = [ray_tpu.get(r, timeout=120) for r in fragile.remote(marker)]
    finally:
        if os.path.exists(marker):
            os.unlink(marker)
    assert out == [0, 1, 2, 3, 4]


def test_streaming_bad_args_surface_error():
    """Errors BEFORE the generator starts (wrong arity) must fail the
    stream, not silently complete it empty."""

    @ray_tpu.remote(num_returns="streaming", max_retries=0)
    def gen(n):
        yield n

    g = gen.remote(1, 2, 3)  # wrong arity -> TypeError before iteration
    with pytest.raises(TypeError):
        next(g)


def test_streaming_abandoned_consumer_cancels_producer():
    """Dropping the generator mid-stream cancels the (backpressured)
    producer instead of leaving it blocked forever."""

    @ray_tpu.remote(num_returns="streaming", _generator_backpressure_num_objects=1)
    def gen(path):
        import os

        try:
            for i in range(10_000):
                yield i
        finally:
            open(path, "w").write("closed")

    import os
    import tempfile

    path = tempfile.mktemp(prefix="raytpu_stream_cancel_")
    g = gen.remote(path)
    assert ray_tpu.get(next(g), timeout=60) == 0
    g.close()  # abandon
    deadline = time.monotonic() + 30
    while not os.path.exists(path) and time.monotonic() < deadline:
        time.sleep(0.2)
    try:
        assert os.path.exists(path), "producer was not cancelled within 30s"
    finally:
        if os.path.exists(path):
            os.unlink(path)


def test_streaming_state_released_after_exhaustion():
    @ray_tpu.remote(num_returns="streaming")
    def gen():
        yield 1

    from ray_tpu.core.worker import global_worker

    g = gen.remote()
    tid = g.task_id
    assert list(g) is not None
    assert tid not in global_worker()._streams


def test_streaming_async_consumption():
    """ObjectRefGenerator supports `async for` (used by Serve/LLM)."""
    import asyncio

    @ray_tpu.remote(num_returns="streaming")
    def gen():
        for i in range(4):
            yield i

    async def consume():
        out = []
        async for ref in gen.remote():
            out.append(ray_tpu.get(ref, timeout=60))
        return out

    assert asyncio.run(consume()) == [0, 1, 2, 3]


def test_streaming_abandon_drops_refcounter_entries():
    """Releasing a partially-consumed stream must also drop the
    owned-object refcounter bookkeeping for the unconsumed items
    (regression: each abandoned stream leaked refcounter entries)."""
    import time

    from ray_tpu.core.ids import ObjectID, TaskID
    from ray_tpu.core.worker import global_worker

    @ray_tpu.remote(num_returns="streaming")
    def gen():
        for i in range(8):
            yield i

    w = global_worker()
    g = gen.remote()
    tid = g.task_id
    assert ray_tpu.get(next(g), timeout=60) == 0
    # Let a few more items arrive at the owner before abandoning.
    deadline = time.time() + 30
    while time.time() < deadline:
        s = w._streams.get(tid)
        if s is not None and s.num_items >= 4:
            break
        time.sleep(0.05)
    g.close()
    unconsumed = [ObjectID.for_task_return(TaskID(tid), i + 1) for i in range(1, 8)]
    # Retry briefly: an in-flight ReportGeneratorItem racing the close drops
    # its entry microseconds after the handler's post-store re-check.
    deadline = time.time() + 10
    while time.time() < deadline and any(w.refcounter.has_ref(o) for o in unconsumed):
        time.sleep(0.05)
    assert not any(w.refcounter.has_ref(o) for o in unconsumed)
