"""Deterministic chaos subsystem: seeded fault plans, virtual time, and
automated recovery verification.

Mirrors the reference's chaos tests (``rpc_chaos.h`` +
``python/ray/tests/test_network_failure*.py`` style) with the
FoundationDB/Jepsen twist this build adds: every fault comes from a
seeded FaultPlan whose compiled schedule is byte-identical across runs,
and every scenario must end RecoveryVerifier-green.
"""

import os
import signal
import time

import pytest

import ray_tpu
from ray_tpu import chaos
from ray_tpu.core.config import get_config
from ray_tpu.core.rpc import RpcChaos, get_chaos, set_chaos
from ray_tpu.util import state

pytestmark = pytest.mark.chaos


def _wait_for(predicate, timeout=30.0, interval=0.2):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        result = predicate()
        if result:
            return result
        time.sleep(interval)
    return predicate()


@pytest.fixture(autouse=True)
def _clean_chaos():
    """Every test leaves no chaos engine, no virtual clock, and the
    config entries it touched restored."""
    cfg = get_config()
    saved = {k: getattr(cfg, k) for k in (
        "worker_register_timeout_s", "lease_orphan_timeout_s",
        "lease_wedge_threshold_s", "lease_wedge_check_interval_s",
        "memory_leak_check_interval_s", "memory_leak_intervals",
        "memory_leak_min_growth_refs", "memory_leak_min_growth_bytes",
        "memory_report_interval_ms", "task_events_flush_interval_ms",
        "rpc_max_retries", "rpc_retry_jitter", "task_max_retries",
        "lease_grant_batch_size")}
    yield
    set_chaos(None)
    chaos.set_clock(None)
    for key, value in saved.items():
        setattr(cfg, key, value)


# --------------------------------------------------------------- unit layer
def test_rpc_chaos_spec_modes():
    """Env-spec grammar: legacy positional probs stay compatible;
    nth-mode is deterministic; delay parses; same seed => same draws."""
    legacy = RpcChaos("Foo=1.0,0.0", seed=1)
    assert legacy.should_fail_request("Foo")
    assert not legacy.should_fail_response("Bar")

    nth = RpcChaos("Foo=nth:3,max:2", seed=1)
    hits = [nth.should_fail_request("Foo") for _ in range(9)]
    # deterministic: every 3rd call, capped at 2 injections
    assert hits == [False, False, True, False, False, True,
                    False, False, False]

    delay = RpcChaos("Foo=req:0.0,delay:50")
    assert delay.request_delay_s("Foo") == pytest.approx(0.05)
    assert delay.request_delay_s("Other") == 0.0

    a = RpcChaos("Foo=0.5,0.5", seed=42)
    b = RpcChaos("Foo=0.5,0.5", seed=42)
    draws_a = [a.should_fail_request("Foo") for _ in range(32)]
    draws_b = [b.should_fail_request("Foo") for _ in range(32)]
    assert draws_a == draws_b  # seeded: reproducible
    assert any(draws_a) and not all(draws_a)

    wild = RpcChaos("*=nth:1,max:1")
    assert wild.should_fail_request("Anything")
    assert not wild.should_fail_request("Anything")  # max hit
    assert ("rpc_request_drop", "Anything") in wild.injections_total


def test_retry_backoff_full_jitter(monkeypatch):
    """RetryableRpcClient: jitter ON samples U(0, base*2^n) windows;
    OFF keeps the legacy deterministic doubling (config flag)."""
    import asyncio

    from ray_tpu.core import rpc as rpc_mod

    cfg = get_config()
    cfg.rpc_max_retries = 3
    saved_base = cfg.rpc_retry_base_delay_ms
    cfg.rpc_retry_base_delay_ms = 20

    uniform_calls: list[tuple] = []
    real_uniform = rpc_mod.random.uniform

    def recording_uniform(a, b):
        uniform_calls.append((a, b))
        return 0.0 if a == 0.0 else real_uniform(a, b)

    monkeypatch.setattr(rpc_mod.random, "uniform", recording_uniform)

    def drive():
        async def _run():
            client = rpc_mod.RetryableRpcClient("127.0.0.1:1")  # dead port
            with pytest.raises(rpc_mod.RpcError):
                await client.call("Nope", {})
            await client.close()

        loop = asyncio.new_event_loop()
        t0 = time.monotonic()
        try:
            loop.run_until_complete(_run())
        finally:
            loop.close()
        return time.monotonic() - t0

    try:
        cfg.rpc_retry_jitter = True
        drive()
        base = cfg.rpc_retry_base_delay_ms / 1000.0
        # filter to the full-jitter windows this client sampled (a == 0)
        windows = [b for a, b in uniform_calls if a == 0.0][:3]
        assert windows == [base, base * 2, base * 4]

        uniform_calls.clear()
        cfg.rpc_retry_jitter = False
        elapsed = drive()
        assert not [c for c in uniform_calls if c[0] == 0.0]  # no sampling
        # legacy deterministic doubling: 20+40+80 ms of sleeps, minimum
        assert elapsed >= 0.13
    finally:
        cfg.rpc_retry_base_delay_ms = saved_base


def test_virtual_clock():
    clock = chaos.VirtualClock(rate=0.0)
    t0 = clock.now()
    time.sleep(0.05)
    assert clock.now() == t0  # frozen until advanced
    clock.advance(10.0)
    assert clock.now() == pytest.approx(t0 + 10.0)

    scaled = chaos.VirtualClock(rate=100.0)
    s0 = scaled.now()
    time.sleep(0.05)
    assert scaled.now() - s0 > 1.0  # 100x wall


def test_fault_schedule_byte_identical(capsys):
    """`cli chaos run <plan> --seed N --dry-run` prints a byte-identical
    schedule across runs; a different seed changes probabilistic plans."""
    from ray_tpu.cli import main

    assert main(["chaos", "run", "mixed-seeded", "--seed", "7",
                 "--dry-run"]) == 0
    first = capsys.readouterr().out
    assert main(["chaos", "run", "mixed-seeded", "--seed", "7",
                 "--dry-run"]) == 0
    second = capsys.readouterr().out
    assert first == second and first.strip()

    assert main(["chaos", "run", "mixed-seeded", "--seed", "8",
                 "--dry-run"]) == 0
    other_seed = capsys.readouterr().out
    assert other_seed != first

    assert main(["chaos", "plans"]) == 0
    listing = capsys.readouterr().out
    assert "lease-reply-drop" in listing and "gcs-blackout" in listing


# --------------------------------------------------------- cluster scenarios
@pytest.fixture()
def chaos_cluster(ray_cluster, _clean_chaos):
    """Shared local cluster with lease/watchdog knobs tightened so the
    fault scenarios resolve in seconds, not default-production minutes."""
    cfg = get_config()
    cfg.worker_register_timeout_s = 5.0
    cfg.lease_orphan_timeout_s = 1.0
    cfg.lease_wedge_check_interval_s = 0.2
    cfg.lease_wedge_threshold_s = 1.0
    yield


def test_run_plan_rpc_drop_task_retry_succeeds(chaos_cluster):
    """Bundled `push-client-drop`: owner-side PushTask drops; every task
    must settle successfully via retry, injections must be recorded and
    chaos-tagged, and recovery must verify green."""
    report = chaos.run_plan("push-client-drop", seed=1, verify_timeout_s=60)
    assert report["verify"]["ok"], report["verify"]["violations"]
    assert report["workload"]["failures"] == 0, report["workload"]
    assert any(k.startswith("rpc_client_drop") for k in report["injections"])
    # injected faults are distinguishable from organic failures
    tagged = [e for e in state.list_errors(limit=1000)
              if e.get("source") == "chaos"
              and (e.get("extra") or {}).get("chaos")
              and e.get("extra", {}).get("plan") == "push-client-drop"]
    assert tagged, "chaos injections never reached list_errors()"


def test_run_plan_worker_kill_lease_retry(chaos_cluster):
    """Bundled `worker-kill`: the first lease's worker is SIGKILLed at
    grant; the owner retries on a fresh worker and the run verifies."""
    report = chaos.run_plan("worker-kill", seed=0, verify_timeout_s=90)
    assert report["verify"]["ok"], report["verify"]["violations"]
    assert report["workload"]["failures"] == 0, report["workload"]
    assert report["injections"].get("kill_worker:kill_worker", 0) >= 1


def test_lease_reply_drop_orphan_reclaim(chaos_cluster):
    """Bundled `lease-reply-drop` (the ROADMAP-1c trigger): grant replies
    die on the wire. The owner's lease retry budget rides it out AND the
    raylet reclaims the stranded (never-acked) grants — before the
    AckLease/orphan-reclaim fix each dropped reply permanently stranded a
    CPU reservation and the suite cascaded into lease timeouts."""

    @ray_tpu.remote(max_retries=5)
    def probe(i):
        return i * i

    def workload():
        refs = [probe.remote(i) for i in range(8)]
        return {"results": ray_tpu.get(refs, timeout=120)}

    report = chaos.run_plan("lease-reply-drop", seed=3, workload=workload,
                            verify_timeout_s=90)
    assert report["verify"]["ok"], report["verify"]["violations"]
    assert report["workload"]["results"] == [i * i for i in range(8)]
    if report["injections"].get("rpc_response_drop:RequestWorkerLease"):
        # A grant reply was actually dropped: its reservation must have
        # been reclaimed (visible in debug state + the error channel).
        orphans = _wait_for(lambda: state.list_errors(
            error_type="lease_orphan", limit=1000))
        assert orphans, "stranded lease was never reclaimed"
        diag = state.cluster_diagnostics(error_limit=0)
        assert any(n.get("orphan_leases_total", 0) >= 1
                   for n in diag["nodes"])


def test_worker_kill_lineage_reconstruction(chaos_cluster):
    """Object lost from plasma after its worker finished: the owner
    resubmits the producing task from pinned lineage on get()."""
    import numpy as np

    from ray_tpu.core import api as core_api

    @ray_tpu.remote(max_retries=2)
    def make_blob():
        import numpy as np

        return np.arange(65536, dtype=np.float32)

    ref = make_blob.remote()
    first = ray_tpu.get(ref, timeout=60)
    assert first.shape == (65536,)
    del first  # release the zero-copy read pin before deleting the copy

    node = core_api._node
    oid = ref.id().binary()
    _wait_for(lambda: node.raylet.store.ref_count(oid) == 0, timeout=10)
    node.services_loop.run_sync(
        node.raylet.handle_PlasmaDelete({"id": oid, "force": True}))

    value = ray_tpu.get(ref, timeout=60)  # lineage reconstruction
    assert isinstance(value, np.ndarray) and value[-1] == 65535.0


def test_gcs_blackout_client_reconnects(chaos_cluster):
    """Bundled `gcs-blackout`: the GCS endpoint is unreachable for the
    window; RetryableRpcClient backoff rides it out and the driver
    reconnects — tasks submitted during the blackout still complete."""
    cfg = get_config()
    cfg.rpc_max_retries = 12  # enough backoff budget to cross the window

    @ray_tpu.remote(max_retries=5)
    def ping(i):
        return i + 1

    def workload():
        t0 = time.monotonic()
        refs = [ping.remote(i) for i in range(4)]
        results = ray_tpu.get(refs, timeout=120)
        return {"results": results, "elapsed_s": time.monotonic() - t0}

    report = chaos.run_plan("gcs-blackout", seed=0, workload=workload,
                            verify_timeout_s=90)
    assert report["verify"]["ok"], report["verify"]["violations"]
    assert report["workload"]["results"] == [1, 2, 3, 4]
    assert any(k.startswith("gcs_blackout") for k in report["injections"]), \
        report["injections"]
    # after the window the control plane answers again
    assert state.list_nodes()


def test_spill_write_error_object_survives(chaos_cluster):
    """Spill-disk write errors (bundled `spill-disk-error`): the disk
    write fails but the blob is retained in the pending buffer — the
    object restores from memory, degraded but never lost."""
    import numpy as np

    from ray_tpu.core import api as core_api

    value = np.arange(131072, dtype=np.float32)  # ~512 KB: plasma-sized
    ref = ray_tpu.put(value)
    node = core_api._node
    oid = ref.id().binary()
    _wait_for(lambda: node.raylet.store.ref_count(oid) == 0, timeout=10)

    engine = chaos.install("spill-disk-error", seed=0)
    try:
        async def _force_spill():
            return node.raylet._spill_objects(value.nbytes)

        freed = node.services_loop.run_sync(_force_spill())
        assert freed >= value.nbytes
        # the (async, executor-thread) disk write must have hit the fault
        assert _wait_for(lambda: engine.injections_total.get(
            ("spill_error", "spill_error")), timeout=10)
        # shm copy is gone, disk write failed -> pending buffer serves it
        assert node.raylet.store.contains(oid) == 0
        assert oid in node.raylet._spill_pending
    finally:
        chaos.uninstall()
    restored = ray_tpu.get(ref, timeout=60)
    assert np.array_equal(restored, value)


def test_fault_plan_kills_loop_stage_mid_loop(chaos_cluster):
    """Compiled-loop chaos (round 8): a `kill_loop_stage` FaultPlan rule
    kills one stage actor at EXACTLY its Nth tick (deterministic —
    between consuming the tick's inputs and producing its output). The
    driver must surface the death on a bounded get(), teardown must
    cascade through the surviving stages within a clock-bounded window
    (no stage left parked on a dead peer's channel), and recovery must
    verify green."""
    from ray_tpu.chaos.verifier import RecoveryVerifier
    from ray_tpu.dag import InputNode, compile_loop

    @ray_tpu.remote
    class Stage:
        def __init__(self, k):
            self.k = k

        def f(self, x):
            return x + self.k

    verifier = RecoveryVerifier(timeout_s=60)
    baseline = verifier.snapshot_baseline()
    a, b = Stage.remote(1), Stage.remote(10)
    plan = {"name": "loop-stage-kill", "faults": [
        {"kind": "kill_loop_stage", "nth": 3, "max_injections": 1}]}

    def _install_in_actor(instance, plan_dict, seed):
        # Runs IN the stage actor process: loop-tick faults fire where
        # the resident executor runs, not on the driver.
        from ray_tpu import chaos as _chaos

        _chaos.install(_chaos.FaultPlan.from_dict(plan_dict), seed,
                       publish=False)
        return True

    assert ray_tpu.get(
        a.__ray_call__.remote(_install_in_actor, plan, 0), timeout=60)

    with InputNode() as inp:
        dag = b.f.bind(a.f.bind(inp))
    loop = compile_loop(dag, credits=2)
    try:
        # ticks 1 and 2 stream normally; tick 3 kills stage `a` mid-tick
        assert loop.run(1) == 12
        assert loop.run(2) == 13
        loop.put(3)
        t0 = time.monotonic()
        with pytest.raises(Exception):
            loop.get(timeout=45.0)
        assert time.monotonic() - t0 < 60.0, "stage death never surfaced"
    finally:
        loop.teardown()
    # cascade completed within the (chaos-clock-measured) window: the
    # surviving stage exited via the force-closed ring, not a hang
    assert loop.torn_down_in_s < 30.0
    result = verifier.verify(baseline)
    assert result.ok, result.violations


def test_serve_replica_kill_request_retried(chaos_cluster):
    """A replica SIGKILLed under load: the in-flight request is re-routed
    to a live replica (router purges the corpse; the controller replaces
    it) instead of surfacing ActorDiedError to the caller."""
    from ray_tpu import serve

    @serve.deployment(num_replicas=1)
    class Echo:
        def pid(self):
            import os

            return os.getpid()

        def hello(self, x):
            return f"hello {x}"

    handle = serve.run(Echo.bind(), name="chaosapp", route_prefix=None,
                       _blocking=False)
    try:
        assert _wait_for(
            lambda: handle.hello.remote("a").result(timeout=30) == "hello a",
            timeout=60)
        pid = handle.pid.remote().result(timeout=30)
        os.kill(pid, signal.SIGKILL)
        # the request that lands on the corpse is retried on the
        # controller's replacement replica
        assert handle.hello.remote("b").result(timeout=90) == "hello b"
    finally:
        try:
            serve.delete("chaosapp")
        except Exception:
            pass


def test_affinity_map_survives_replica_death(chaos_cluster):
    """ISSUE 10: a prefix-group's affine replica SIGKILLed under it —
    the router purges the corpse's groups, the retried request lands on
    the replacement (riding the existing replica-death retry path), and
    the group's state there is COLD (fresh instance, no carried KV)."""
    import uuid as _uuid

    from ray_tpu import serve

    @serve.deployment(num_replicas=1)
    class Sticky:
        def __init__(self):
            self.instance = _uuid.uuid4().hex
            self.seen = 0

        def pid(self):
            import os

            return os.getpid()

        def ask(self, x):
            self.seen += 1
            return {"instance": self.instance, "seen": self.seen,
                    "answer": f"ok {x}"}

    handle = serve.run(Sticky.bind(), name="affchaos", route_prefix=None,
                       _blocking=False)
    session = handle.options(prefix_group="sess:chaos")
    try:
        first = None
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline and first is None:
            try:
                first = session.ask.remote("a").result(timeout=30)
            except Exception:
                time.sleep(0.5)
        assert first and first["answer"] == "ok a"
        router = handle._get_router()
        affine = router._group_affinity.get("sess:chaos")
        assert affine is not None
        pid = session.pid.remote().result(timeout=30)
        os.kill(pid, signal.SIGKILL)
        # retried on the controller's replacement; the router must have
        # purged the corpse's group before re-routing
        second = session.ask.remote("b").result(timeout=90)
        assert second["answer"] == "ok b"
        assert second["instance"] != first["instance"]  # state died: cold
        assert second["seen"] == 1
        remapped = router._group_affinity.get("sess:chaos")
        assert remapped is not None and remapped != affine
    finally:
        try:
            serve.delete("affchaos")
        except Exception:
            pass


def test_cli_doctor_reports_active_fault_plan(chaos_cluster, capsys):
    """Operators must be able to tell injected pain from real pain:
    `cli doctor` shows the registered FaultPlan while one is installed."""
    from ray_tpu.cli import main

    chaos.install("worker-kill", seed=9)
    try:
        assert main(["doctor"]) == 0
        out = capsys.readouterr().out
        assert "ACTIVE FAULT PLAN" in out and "worker-kill" in out
        assert "seed=9" in out
    finally:
        chaos.uninstall()
    assert main(["doctor"]) == 0
    assert "ACTIVE FAULT PLAN" not in capsys.readouterr().out


def test_roadmap_1c_cascade_repro_under_virtual_clock(chaos_cluster):
    """ROADMAP 1c: the mid-suite lease-timeout cascade, reproduced
    deterministically — lease-RPC reply drops strand CPU reservations
    while leaked-ref pressure builds, under accelerated VirtualClock.

    Asserts the full diagnosis chain fires (lease_orphan reclaim, the
    wedge watchdog, the GCS memory_leak watcher) AND that the cluster
    heals: with the AckLease/orphan-reclaim fix every task completes and
    RecoveryVerifier ends green. Without the fix (revert the AckLease
    handshake) the stranded reservations never return and this test
    times out exactly like the original round-5 cascade."""
    import numpy as np

    cfg = get_config()
    cfg.worker_register_timeout_s = 4.0
    # Pin the serial one-lease-per-RPC protocol this cascade repro was
    # built on: owner-side lease multiplexing/coalescing (PR 6) issues
    # far fewer RequestWorkerLease RPCs for a same-shape burst, so the
    # admission queue never backs up behind the stranded grants and the
    # wedge stage of the diagnosis chain (correctly) has nothing to
    # report. The multiplexed path's recovery under the same fault is
    # covered by test_core_throughput.py::test_multiplexed_lease_recovers_from_dropped_reply.
    cfg.lease_grant_batch_size = 1
    cfg.lease_orphan_timeout_s = 2.0          # virtual seconds
    cfg.lease_wedge_threshold_s = 1.0         # virtual seconds
    cfg.lease_wedge_check_interval_s = 0.2
    cfg.memory_leak_check_interval_s = 0.3
    cfg.memory_leak_intervals = 2
    cfg.memory_leak_min_growth_refs = 10
    cfg.memory_leak_min_growth_bytes = 1
    cfg.memory_report_interval_ms = 150
    cfg.task_events_flush_interval_ms = 100

    # Virtual time at 5x: the multi-second watchdog thresholds replay in
    # fractions of real seconds, deterministically ordered by the clock.
    chaos.set_clock(chaos.VirtualClock(rate=5.0))

    plan = {
        "name": "roadmap-1c-cascade",
        "faults": [
            {"kind": "rpc", "method": "RequestWorkerLease",
             "where": "response", "nth": 2, "max_injections": 3},
        ],
    }

    @ray_tpu.remote(max_retries=5)
    def busy(i):
        time.sleep(0.2)
        return i

    leaked = []

    def workload():
        refs = [busy.remote(i) for i in range(8)]
        # leaked-ref pressure: the driver's refcount table grows
        # monotonically across memory reports while the cascade runs
        deadline = time.monotonic() + 4.0
        while time.monotonic() < deadline:
            leaked.extend(ray_tpu.put(np.zeros(256)) for _ in range(8))
            time.sleep(0.1)
        results = ray_tpu.get(refs, timeout=120)
        return {"results": results}

    report = chaos.run_plan(plan, seed=2, workload=workload,
                            verify=False)
    assert report["workload"]["results"] == list(range(8))
    assert report["injections"].get("rpc_response_drop:RequestWorkerLease"), \
        report["injections"]

    # the full diagnosis chain fired
    assert _wait_for(lambda: state.list_errors(
        error_type="lease_orphan", limit=1000), timeout=20), \
        "orphan-lease reclaim never fired"
    assert _wait_for(lambda: state.list_errors(
        error_type="lease_wedge", limit=1000), timeout=20), \
        "wedge watchdog never fired on the cascade"
    assert _wait_for(lambda: state.list_errors(
        error_type="memory_leak", limit=1000), timeout=30), \
        "memory_leak watcher never flagged the leaked-ref pressure"

    # drop the pressure and verify the cluster healed completely
    leaked.clear()
    verifier = chaos.RecoveryVerifier(timeout_s=60)
    result = verifier.verify({"ref_ids": set(), "num_errors": 0})
    assert result.checks["tasks_terminal"], result.violations
    assert result.checks["lease_queues_drained"], result.violations


@pytest.mark.slow
def test_randomized_seed_sweep(chaos_cluster):
    """Longer randomized sweeps: the seeded probabilistic mix must end
    RecoveryVerifier-green for every seed (reproducible on failure by
    re-running with the printed seed)."""
    for seed in range(4):
        report = chaos.run_plan("mixed-seeded", seed=seed,
                                verify_timeout_s=120)
        assert report["verify"]["ok"], (
            f"seed {seed}: {report['verify']['violations']}")
        assert report["workload"]["failures"] == 0, (
            f"seed {seed}: {report['workload']}")


@pytest.mark.slow
def test_bundled_plans_all_verify_green(chaos_cluster):
    """Acceptance sweep: every bundled FaultPlan ends verifier-green."""
    cfg = get_config()
    cfg.rpc_max_retries = 12
    for name in chaos.BUILTIN_PLANS:
        if name in ("spill-disk-error",):  # exercised by its own test
            continue
        report = chaos.run_plan(name, seed=1, verify_timeout_s=120)
        assert report["verify"]["ok"], (
            f"{name}: {report['verify']['violations']}")
