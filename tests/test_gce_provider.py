"""GCE TPU NodeProvider (reference gcp/node_provider.py): REST calls,
label-scoped listing, reconciliation, and end-to-end reconciler drive —
all against an injected transport (this environment has zero egress)."""

import pytest

from ray_tpu.autoscaler import Autoscaler, NodeTypeConfig
from ray_tpu.autoscaler.gce import GceTpuNodeProvider


class FakeTransport:
    """Records TPU REST calls and mimics the node lifecycle."""

    def __init__(self):
        self.calls = []
        self.nodes = {}  # instance_id -> node dict

    def request(self, method, url, body=None):
        self.calls.append((method, url, body))
        if method == "POST":
            iid = url.rsplit("nodeId=", 1)[-1]
            self.nodes[iid] = {
                "name": f"{url.split('?')[0].rsplit('/nodes', 1)[0]}/nodes/{iid}",
                "state": "READY",
                "labels": body["labels"],
                "acceleratorType": body["acceleratorType"],
            }
            return {"name": f"operations/create-{iid}"}
        if method == "DELETE":
            iid = url.rsplit("/", 1)[-1]
            self.nodes.pop(iid, None)
            return {"name": f"operations/delete-{iid}"}
        if method == "GET":
            return {"nodes": list(self.nodes.values())}
        raise AssertionError(method)


def make_provider(transport=None):
    return GceTpuNodeProvider(
        project="proj", zone="us-central2-b",
        gcs_address="10.0.0.2:6379",
        node_types={
            "v5e-16": {"accelerator_type": "v5litepod-16",
                       "resources": {"CPU": 16.0, "TPU": 16.0,
                                     "TPU-v5litepod-16-head": 1.0}},
        },
        transport=transport or FakeTransport(),
    )


def test_create_list_terminate_lifecycle():
    t = FakeTransport()
    p = make_provider(t)
    iid = p.create_node("v5e-16", {})
    method, url, body = t.calls[0]
    assert method == "POST" and "tpu.googleapis.com/v2" in url
    assert "projects/proj/locations/us-central2-b/nodes" in url
    assert body["acceleratorType"] == "v5litepod-16"
    assert body["labels"]["raytpu-cluster"] == "raytpu"
    assert "ray_tpu.cli start --address=10.0.0.2:6379" in body["metadata"]["startup-script"]

    assert p.non_terminated_nodes() == {iid: "v5e-16"}
    p.terminate_node(iid)
    assert p.non_terminated_nodes() == {}
    assert any(m == "DELETE" for m, _, _ in t.calls)


def test_listing_reconciles_externally_died_nodes():
    """A slice preempted/deleted outside our control disappears from
    non_terminated_nodes so the reconciler can relaunch."""
    t = FakeTransport()
    p = make_provider(t)
    iid = p.create_node("v5e-16", {})
    t.nodes[iid]["state"] = "PREEMPTED"
    assert p.non_terminated_nodes() == {}


def test_listing_ignores_foreign_clusters():
    t = FakeTransport()
    p = make_provider(t)
    t.nodes["other"] = {"name": ".../nodes/other", "state": "READY",
                        "labels": {"raytpu-cluster": "someone-else"}}
    assert p.non_terminated_nodes() == {}


def test_unknown_node_type_rejected():
    p = make_provider()
    with pytest.raises(ValueError, match="unknown node_type"):
        p.create_node("v9-mega", {})


def test_reconciler_launches_tpu_slices_for_demand():
    """The autoscaler reconciler drives the GCE provider end-to-end: TPU
    slice-head demand -> create_node REST calls for matching slices."""
    t = FakeTransport()
    provider = make_provider(t)

    nodes = [{
        "node_id": "head", "state": "ALIVE",
        "resources": {"total": {"CPU": 4.0}, "available": {"CPU": 4.0}},
        "pending_demand": [
            {"shape": {"TPU-v5litepod-16-head": 1.0}, "count": 2},
        ],
    }]

    def gcs_call(method, payload):
        if method == "GetAllNodes":
            return {"nodes": nodes}
        if method == "ListPlacementGroups":
            return {"placement_groups": []}
        if method == "KvGet":
            return {"value": None}
        raise AssertionError(method)

    scaler = Autoscaler(
        gcs_call, provider,
        [NodeTypeConfig("v5e-16",
                        {"CPU": 16.0, "TPU": 16.0, "TPU-v5litepod-16-head": 1.0},
                        max_workers=4)],
        launch_cooldown_s=0.0,
    )
    decision = scaler.reconcile_once()
    assert decision.launch == ["v5e-16", "v5e-16"]
    creates = [c for c in t.calls if c[0] == "POST"]
    assert len(creates) == 2
    assert all(c[2]["acceleratorType"] == "v5litepod-16" for c in creates)
    # pending launches count as capacity: a second pass must not relaunch
    decision2 = scaler.reconcile_once()
    assert decision2.launch == []


class LaggyTransport(FakeTransport):
    """Create succeeds but the node does not appear in listings yet
    (the TPU list API is eventually consistent)."""

    def __init__(self):
        super().__init__()
        self.visible = False

    def request(self, method, url, body=None):
        if method == "GET" and not self.visible:
            self.calls.append((method, url, body))
            return {"nodes": []}
        return super().request(method, url, body)


def test_creating_node_survives_listing_lag():
    """A just-created node missing from the eventually-consistent list API
    stays tracked (and counts as live) until it appears or the grace
    period expires — pruning it would double-create the slice."""
    t = LaggyTransport()
    p = make_provider(t)
    iid = p.create_node("v5e-16", {})
    # Listing lags: node must still be reported, not pruned.
    assert p.non_terminated_nodes() == {iid: "v5e-16"}
    assert p.non_terminated_nodes() == {iid: "v5e-16"}
    # Node becomes visible: tracked normally from now on.
    t.visible = True
    assert p.non_terminated_nodes() == {iid: "v5e-16"}
    # Grace expired + still absent => pruned.
    t.visible = False
    p._instances[iid]["state"] = "CREATING"
    p._instances[iid]["created_at"] = 0.0
    assert p.non_terminated_nodes() == {}


class QuotaTransport(FakeTransport):
    """POST fails with a RESOURCE_EXHAUSTED quota error for a given
    accelerator type until ``relent()`` is called."""

    def __init__(self, blocked_type="v5litepod-16"):
        super().__init__()
        self.blocked_type = blocked_type

    def relent(self):
        self.blocked_type = None

    def request(self, method, url, body=None):
        if (method == "POST" and body
                and body.get("acceleratorType") == self.blocked_type):
            self.calls.append((method, url, body))
            raise RuntimeError(
                "HTTP 429: RESOURCE_EXHAUSTED: quota exceeded for "
                "TPU v5 litepod cores in zone us-central2-b")
        return super().request(method, url, body)


def test_quota_stockout_backs_off_and_routes_to_other_type():
    """A quota/stockout launch failure (the dominant real TPU failure)
    must not abort the round or hammer the API: the failing type goes
    into exponential backoff, demand routes to the next fitting type,
    and the type is retried after the backoff expires (VERDICT r3 weak
    #7; ref autoscaler/v2/instance_manager allocation retry)."""
    import time as _time

    t = QuotaTransport(blocked_type="v5litepod-16")
    provider = GceTpuNodeProvider(
        project="proj", zone="us-central2-b", gcs_address="10.0.0.2:6379",
        node_types={
            "v5e-16": {"accelerator_type": "v5litepod-16",
                       "resources": {"CPU": 16.0, "TPU": 16.0,
                                     "TPU-head": 1.0}},
            "v5e-32": {"accelerator_type": "v5litepod-32",
                       "resources": {"CPU": 32.0, "TPU": 32.0,
                                     "TPU-head": 1.0}},
        },
        transport=t, cluster_name="raytpu")

    nodes = [{
        "node_id": "head", "state": "ALIVE",
        "resources": {"total": {"CPU": 4.0}, "available": {"CPU": 4.0}},
        "pending_demand": [{"shape": {"TPU-head": 1.0}, "count": 1}],
    }]

    def gcs_call(method, payload):
        if method == "GetAllNodes":
            return {"nodes": nodes}
        if method == "ListPlacementGroups":
            return {"placement_groups": []}
        if method == "KvGet":
            return {"value": None}
        raise AssertionError(method)

    scaler = Autoscaler(
        gcs_call, provider,
        [NodeTypeConfig("v5e-16", {"CPU": 16.0, "TPU": 16.0, "TPU-head": 1.0},
                        max_workers=4),
         NodeTypeConfig("v5e-32", {"CPU": 32.0, "TPU": 32.0, "TPU-head": 1.0},
                        max_workers=4)],
        launch_cooldown_s=0.0,
        launch_backoff_base_s=0.3,
    )
    # Round 1: v5e-16 fails on quota -> backoff; round survives.
    d1 = scaler.reconcile_once()
    assert d1.launch == []          # the attempted launch failed
    assert scaler._in_backoff("v5e-16")

    # Round 2 (still in backoff): demand routes to the OTHER type.
    d2 = scaler.reconcile_once()
    assert d2.launch == ["v5e-32"]
    big = [c for c in t.calls if c[0] == "POST"
           and c[2]["acceleratorType"] == "v5litepod-32"]
    assert len(big) == 1

    # After quota relents and the backoff expires, v5e-16 launches again.
    t.relent()
    nodes[0]["pending_demand"] = [{"shape": {"TPU-head": 1.0}, "count": 3}]
    _time.sleep(0.4)
    d3 = scaler.reconcile_once()
    assert "v5e-16" in d3.launch, d3.launch
