"""GCE TPU NodeProvider (reference gcp/node_provider.py): REST calls,
label-scoped listing, reconciliation, and end-to-end reconciler drive —
all against an injected transport (this environment has zero egress)."""

import pytest

from ray_tpu.autoscaler import Autoscaler, NodeTypeConfig
from ray_tpu.autoscaler.gce import GceTpuNodeProvider


class FakeTransport:
    """Records TPU REST calls and mimics the node lifecycle."""

    def __init__(self):
        self.calls = []
        self.nodes = {}  # instance_id -> node dict

    def request(self, method, url, body=None):
        self.calls.append((method, url, body))
        if method == "POST":
            iid = url.rsplit("nodeId=", 1)[-1]
            self.nodes[iid] = {
                "name": f"{url.split('?')[0].rsplit('/nodes', 1)[0]}/nodes/{iid}",
                "state": "READY",
                "labels": body["labels"],
                "acceleratorType": body["acceleratorType"],
            }
            return {"name": f"operations/create-{iid}"}
        if method == "DELETE":
            iid = url.rsplit("/", 1)[-1]
            self.nodes.pop(iid, None)
            return {"name": f"operations/delete-{iid}"}
        if method == "GET":
            return {"nodes": list(self.nodes.values())}
        raise AssertionError(method)


def make_provider(transport=None):
    return GceTpuNodeProvider(
        project="proj", zone="us-central2-b",
        gcs_address="10.0.0.2:6379",
        node_types={
            "v5e-16": {"accelerator_type": "v5litepod-16",
                       "resources": {"CPU": 16.0, "TPU": 16.0,
                                     "TPU-v5litepod-16-head": 1.0}},
        },
        transport=transport or FakeTransport(),
    )


def test_create_list_terminate_lifecycle():
    t = FakeTransport()
    p = make_provider(t)
    iid = p.create_node("v5e-16", {})
    method, url, body = t.calls[0]
    assert method == "POST" and "tpu.googleapis.com/v2" in url
    assert "projects/proj/locations/us-central2-b/nodes" in url
    assert body["acceleratorType"] == "v5litepod-16"
    assert body["labels"]["raytpu-cluster"] == "raytpu"
    assert "ray_tpu.cli start --address=10.0.0.2:6379" in body["metadata"]["startup-script"]

    assert p.non_terminated_nodes() == {iid: "v5e-16"}
    p.terminate_node(iid)
    assert p.non_terminated_nodes() == {}
    assert any(m == "DELETE" for m, _, _ in t.calls)


def test_listing_reconciles_externally_died_nodes():
    """A slice preempted/deleted outside our control disappears from
    non_terminated_nodes so the reconciler can relaunch."""
    t = FakeTransport()
    p = make_provider(t)
    iid = p.create_node("v5e-16", {})
    t.nodes[iid]["state"] = "PREEMPTED"
    assert p.non_terminated_nodes() == {}


def test_listing_ignores_foreign_clusters():
    t = FakeTransport()
    p = make_provider(t)
    t.nodes["other"] = {"name": ".../nodes/other", "state": "READY",
                        "labels": {"raytpu-cluster": "someone-else"}}
    assert p.non_terminated_nodes() == {}


def test_unknown_node_type_rejected():
    p = make_provider()
    with pytest.raises(ValueError, match="unknown node_type"):
        p.create_node("v9-mega", {})


def test_reconciler_launches_tpu_slices_for_demand():
    """The autoscaler reconciler drives the GCE provider end-to-end: TPU
    slice-head demand -> create_node REST calls for matching slices."""
    t = FakeTransport()
    provider = make_provider(t)

    nodes = [{
        "node_id": "head", "state": "ALIVE",
        "resources": {"total": {"CPU": 4.0}, "available": {"CPU": 4.0}},
        "pending_demand": [
            {"shape": {"TPU-v5litepod-16-head": 1.0}, "count": 2},
        ],
    }]

    def gcs_call(method, payload):
        if method == "GetAllNodes":
            return {"nodes": nodes}
        if method == "ListPlacementGroups":
            return {"placement_groups": []}
        if method == "KvGet":
            return {"value": None}
        raise AssertionError(method)

    scaler = Autoscaler(
        gcs_call, provider,
        [NodeTypeConfig("v5e-16",
                        {"CPU": 16.0, "TPU": 16.0, "TPU-v5litepod-16-head": 1.0},
                        max_workers=4)],
        launch_cooldown_s=0.0,
    )
    decision = scaler.reconcile_once()
    assert decision.launch == ["v5e-16", "v5e-16"]
    creates = [c for c in t.calls if c[0] == "POST"]
    assert len(creates) == 2
    assert all(c[2]["acceleratorType"] == "v5litepod-16" for c in creates)
    # pending launches count as capacity: a second pass must not relaunch
    decision2 = scaler.reconcile_once()
    assert decision2.launch == []


class LaggyTransport(FakeTransport):
    """Create succeeds but the node does not appear in listings yet
    (the TPU list API is eventually consistent)."""

    def __init__(self):
        super().__init__()
        self.visible = False

    def request(self, method, url, body=None):
        if method == "GET" and not self.visible:
            self.calls.append((method, url, body))
            return {"nodes": []}
        return super().request(method, url, body)


def test_creating_node_survives_listing_lag():
    """A just-created node missing from the eventually-consistent list API
    stays tracked (and counts as live) until it appears or the grace
    period expires — pruning it would double-create the slice."""
    t = LaggyTransport()
    p = make_provider(t)
    iid = p.create_node("v5e-16", {})
    # Listing lags: node must still be reported, not pruned.
    assert p.non_terminated_nodes() == {iid: "v5e-16"}
    assert p.non_terminated_nodes() == {iid: "v5e-16"}
    # Node becomes visible: tracked normally from now on.
    t.visible = True
    assert p.non_terminated_nodes() == {iid: "v5e-16"}
    # Grace expired + still absent => pruned.
    t.visible = False
    p._instances[iid]["state"] = "CREATING"
    p._instances[iid]["created_at"] = 0.0
    assert p.non_terminated_nodes() == {}
