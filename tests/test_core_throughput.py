"""Core task-path throughput machinery (ISSUE 6): lease multiplexing,
same-shape lease coalescing, task-event flush coalescing, the adaptive
push-batch invariants, and the per-call lease-denial-reason contract.

These are the SEMANTIC-EQUIVALENCE nets for the perf work: every
batched/coalesced path must produce the same grants, the same task
records, and the same recovery behavior as the serial path it replaces.
"""

from __future__ import annotations

import asyncio
import time

import pytest

import ray_tpu
from ray_tpu.core.config import get_config
from ray_tpu.core.task_events import (
    GcsTaskEventStore,
    TaskEventBuffer,
    coalesce_events,
    expand_event,
)
from ray_tpu.core.task_spec import TaskSpec
from ray_tpu.core.worker import (
    _next_push_batch,
    _pop_push_batch,
    global_worker,
)


@pytest.fixture()
def _knobs():
    """Snapshot/restore the config entries these tests tune."""
    cfg = get_config()
    keys = ("lease_grant_batch_size", "task_event_coalesce_ms",
            "worker_register_timeout_s", "task_push_batch_size",
            "rpc_max_retries")
    saved = {k: getattr(cfg, k) for k in keys}
    yield cfg
    for k, v in saved.items():
        setattr(cfg, k, v)


# ------------------------------------------------- push-batch invariants


def _spec(name: str, args: list | None = None) -> TaskSpec:
    return TaskSpec(task_id=name.encode(), job_id=b"j", name=name,
                    function_id=b"f", args=args or [])


def _inline_arg() -> dict:
    return {"t": "v", "meta": b"", "blob": b"x"}


def _ref_arg() -> dict:
    return {"t": "r", "id": b"o" * 28, "owner": "addr"}


def test_pop_push_batch_short_queue_never_batches():
    # A queue no deeper than the pipeline cap is parallel opportunity:
    # other pipelines can run those specs concurrently on other workers.
    queue = [_spec(f"t{i}") for i in range(5)]
    assert len(_pop_push_batch(queue, cur_batch=16, pipeline_cap=10)) == 1
    assert len(queue) == 4


def test_pop_push_batch_deep_queue_batches_to_cur_batch():
    queue = [_spec(f"t{i}") for i in range(30)]
    assert len(_pop_push_batch(queue, cur_batch=8, pipeline_cap=10)) == 8
    assert len(queue) == 22


def test_pop_push_batch_objectref_arg_ships_alone():
    # A ref-arg spec's dependency may be produced by an earlier spec of
    # the same batch, whose result only reaches the owner with the reply
    # — batching them would deadlock the chain.
    queue = ([_spec("a"), _spec("b")]
             + [_spec("r", [_ref_arg()])]
             + [_spec(f"c{i}") for i in range(20)])
    first = _pop_push_batch(queue, cur_batch=16, pipeline_cap=2)
    assert [s.name for s in first] == ["a", "b"]
    second = _pop_push_batch(queue, cur_batch=16, pipeline_cap=2)
    assert [s.name for s in second] == ["r"]
    # and a ref-arg spec at the head goes out alone too
    queue2 = [_spec("r2", [_ref_arg()])] + [_spec(f"d{i}") for i in range(20)]
    assert [s.name for s in _pop_push_batch(queue2, 16, 2)] == ["r2"]


def test_pop_push_batch_mixed_args_only_ref_matters():
    queue = ([_spec("v", [_inline_arg()])]
             + [_spec(f"w{i}") for i in range(20)])
    batch = _pop_push_batch(queue, cur_batch=4, pipeline_cap=2)
    assert len(batch) == 4  # inline args batch normally


def test_next_push_batch_ramps_and_resets():
    # fast batches ramp 1 -> 4 -> 16 (capped)
    assert _next_push_batch(1, 0.001, 16) == 4
    assert _next_push_batch(4, 0.001, 16) == 16
    assert _next_push_batch(16, 0.001, 16) == 16
    # ANY slow batch resets to 1 — a batch serializes execution on one
    # worker while other leased workers idle
    assert _next_push_batch(16, 0.25, 16) == 1
    assert _next_push_batch(4, 0.006, 16) == 1


# ------------------------------------- task-event coalescing equivalence


def _stage_recorder():
    calls: list[tuple] = []
    return calls, lambda stage, ms, node: calls.append((stage, round(ms, 6), node))


def test_event_coalescing_store_equivalence():
    """The acceptance net: a coalesced flush must produce byte-identical
    task records AND identical lease-stage histogram observations to the
    unbatched flush."""
    buf = TaskEventBuffer("w1", "n1")
    t0 = time.time()
    for i in range(20):
        tid = bytes([i]) * 4
        buf.record(tid, f"task{i}", "SUBMITTED")
        buf.record(tid, f"task{i}", "LEASED",
                   extra={"queue_wait_ms": 1.5, "spawn_ms": 0.25,
                          "worker_id": f"lease-worker-{i}"})
        buf.record(tid, f"task{i}", "RUNNING")
        buf.record(tid, f"task{i}", "FINISHED")
    raw, _ = buf.drain(coalesce_window_ms=0)
    assert len(raw) == 80
    coalesced = coalesce_events([dict(e) for e in raw], window_ms=60_000)
    assert len(coalesced) == 20  # one wire event per task
    assert all(len(e["transitions"]) == 4 for e in coalesced)

    plain_calls, plain_cb = _stage_recorder()
    co_calls, co_cb = _stage_recorder()
    plain_store = GcsTaskEventStore(on_stage=plain_cb)
    co_store = GcsTaskEventStore(on_stage=co_cb)
    plain_store.add_events(raw)
    co_store.add_events(coalesced)

    assert plain_store.list_tasks(limit=100) == co_store.list_tasks(limit=100)
    assert plain_calls == co_calls
    assert plain_store.count_by_state() == co_store.count_by_state()
    # timestamps survived exactly (records already compared equal, but be
    # explicit about the thing the histograms are computed from)
    for rec in co_store.list_tasks(limit=100):
        assert rec["events"]["SUBMITTED"] >= t0


def test_event_coalescing_window_splits_groups():
    events = [
        {"task_id": "a", "name": "t", "status": "SUBMITTED", "ts": 0.0,
         "worker_id": "w", "node_id": "n", "kind": 0},
        {"task_id": "a", "name": "t", "status": "RUNNING", "ts": 10.0,
         "worker_id": "w", "node_id": "n", "kind": 0},
    ]
    out = coalesce_events([dict(e) for e in events], window_ms=1000)
    assert len(out) == 2  # 10s apart: beyond the window, two wire events


def test_event_coalescing_passes_span_and_memory_through():
    events = [
        {"task_id": "a", "name": "t", "status": "SUBMITTED", "ts": 1.0,
         "worker_id": "w", "node_id": "n", "kind": 0},
        {"task_id": "tr1", "name": "s", "status": "SPAN", "ts": 1.0,
         "worker_id": "w", "node_id": "n", "kind": 0, "span": {"name": "s"}},
        {"task_id": "", "name": "memory_summary", "status": "MEMORY",
         "ts": 1.0, "worker_id": "w", "node_id": "n", "kind": 0,
         "memory": {"worker_id": "w"}},
        {"task_id": "a", "name": "t", "status": "FINISHED", "ts": 1.1,
         "worker_id": "w", "node_id": "n", "kind": 0},
    ]
    out = coalesce_events([dict(e) for e in events], window_ms=60_000)
    statuses = sorted(e["status"] for e in out)
    assert statuses == ["FINISHED", "MEMORY", "SPAN"]
    merged = [e for e in out if e.get("transitions")][0]
    assert [t["status"] for t in merged["transitions"]] == [
        "SUBMITTED", "FINISHED"]
    # expansion inverts exactly
    back = expand_event(merged)
    assert [e["status"] for e in back] == ["SUBMITTED", "FINISHED"]
    assert back[0]["task_id"] == "a" and back[0]["ts"] == 1.0


def test_event_coalescing_preserves_per_transition_extras():
    events = [
        {"task_id": "a", "name": "t", "status": "SUBMITTED", "ts": 1.0,
         "worker_id": "w", "node_id": "n", "kind": 0, "trace_id": "tr"},
        {"task_id": "a", "name": "t", "status": "LEASED", "ts": 1.1,
         "worker_id": "lease-w", "node_id": "n", "kind": 0,
         "queue_wait_ms": 3.5},
        {"task_id": "a", "name": "t", "status": "FAILED", "ts": 1.2,
         "worker_id": "w", "node_id": "n", "kind": 0, "error": "boom"},
    ]
    [merged] = coalesce_events([dict(e) for e in events], window_ms=60_000)
    back = expand_event(merged)
    assert back[0]["trace_id"] == "tr"
    assert back[1]["worker_id"] == "lease-w"  # per-transition override
    assert back[1]["queue_wait_ms"] == 3.5
    assert back[2]["error"] == "boom"
    assert merged["status"] == "FAILED"  # wire dict doubles as last status


# ------------------------------------------ lease denial reason contract


def test_lease_denial_reason_returned_per_call(ray_cluster, _knobs):
    """Regression for the `_last_lease_denial` race: two concurrent
    acquires for DIFFERENT scheduling shapes, replies interleaved so the
    second denial lands while the first is still in flight — each caller
    must see ITS OWN reason, and no shared instance attribute may exist."""
    w = global_worker()
    real_raylet = w.raylet

    class _StubRaylet:
        address = real_raylet.address

        async def call(self, method, payload=None, timeout=None):
            if method == "RequestWorkerLease":
                res = (payload["spec"].get("resources") or {})
                if "ShapeA" in res:
                    # A's denial arrives AFTER B's has been processed —
                    # the exact overwrite window of the old attribute.
                    await asyncio.sleep(0.3)
                    return {"granted": False, "reason": "reason-A"}
                return {"granted": False, "reason": "reason-B"}
            return await real_raylet.call(method, payload, timeout)

    spec_a = _spec("a")
    spec_a.resources = {"ShapeA": 1.0}
    spec_b = _spec("b")
    spec_b.resources = {"ShapeB": 1.0}
    w.raylet = _StubRaylet()
    try:
        async def _both():
            return await asyncio.gather(
                w._acquire_lease(spec_a), w._acquire_lease(spec_b))

        (la, ra), (lb, rb) = w.io.run_sync(_both())
    finally:
        w.raylet = real_raylet
    assert la is None and lb is None
    assert ra == "reason-A"
    assert rb == "reason-B"
    # the racy shared attribute is gone for good
    assert not hasattr(w, "_last_lease_denial")


def test_infeasible_lease_error_names_raylet_reason(ray_cluster, _knobs):
    cfg = _knobs
    cfg.worker_register_timeout_s = 1.5

    @ray_tpu.remote(max_retries=0, resources={"NoSuchThing": 1})
    def f():
        return 1

    with pytest.raises(Exception, match="infeasible"):
        ray_tpu.get(f.remote(), timeout=60)


# --------------------------------------- lease multiplexing equivalence


def test_multiplexed_lease_grants_equivalent_results(ray_cluster, _knobs):
    """Same workload under lease_grant_batch_size 1 (serial protocol) and
    4 (multiplexed): identical results, every task FINISHED — the grants
    differ only in how many round trips they cost."""
    cfg = _knobs

    @ray_tpu.remote
    def sq(i):
        return i * i

    for batch in (1, 4):
        cfg.lease_grant_batch_size = batch
        assert ray_tpu.get([sq.remote(i) for i in range(40)],
                           timeout=90) == [i * i for i in range(40)]


def test_raylet_extra_grants_lease_state(ray_cluster, _knobs):
    """Raylet-level contract: extra grants are real leases — resources
    acquired per grant, workers marked leased and un-acked until AckLease,
    everything released by ReturnWorker."""
    from ray_tpu.core import api as core_api

    node = core_api._node
    raylet = node.raylet

    # make sure a couple of idle default-env workers exist
    @ray_tpu.remote
    def warm():
        return None

    ray_tpu.get([warm.remote() for _ in range(8)])
    time.sleep(0.3)

    async def _run():
        idle_before = sum(1 for wid in raylet._idle
                          if raylet._workers[wid].env_hash == "")
        avail_before = raylet.resources.available.get("CPU")
        spec = {"task_id": b"mux-test", "name": "mux", "kind": 0,
                "resources": {"CPU": 1.0}, "max_retries": 1}
        reply = await raylet.handle_RequestWorkerLease(
            {"spec": spec, "num_workers": 3})
        assert reply["granted"], reply
        grants = [reply["worker_id"]] + [
            g["worker_id"] for g in reply.get("extra_grants") or ()]
        if idle_before >= 2:
            assert len(grants) >= 2, (idle_before, reply)
        for wid in grants:
            h = raylet._workers[wid]
            assert h.state == "leased"
            assert h.lease_resources.get("CPU") == 1.0
            assert h.lease_acked is False
        assert raylet.resources.available.get("CPU") == \
            avail_before - len(grants)
        await raylet.handle_AckLease({"worker_id": grants[0],
                                      "worker_ids": grants[1:]})
        assert all(raylet._workers[wid].lease_acked for wid in grants)
        for wid in grants:
            await raylet.handle_ReturnWorker({"worker_id": wid})
        assert raylet.resources.available.get("CPU") == avail_before
        return len(grants)

    assert node.services_loop.run_sync(_run(), timeout=30) >= 1
    # cluster still fully usable afterwards
    assert ray_tpu.get(warm.remote(), timeout=30) is None


def test_multiplexed_lease_recovers_from_dropped_reply(ray_cluster, _knobs):
    """ISSUE 6 acceptance: `rpc drop RequestWorkerLease` still recovers
    WITH multiplexing on — dropped grant replies strand multi-grants,
    the orphan watchdog reclaims them, retries land, every task settles."""
    from ray_tpu import chaos
    from ray_tpu.core.rpc import set_chaos

    cfg = _knobs
    cfg.lease_grant_batch_size = 4
    cfg.worker_register_timeout_s = 5.0
    saved_orphan = cfg.lease_orphan_timeout_s
    cfg.lease_orphan_timeout_s = 1.0

    @ray_tpu.remote(max_retries=5)
    def val(i):
        return i

    plan = {"name": "mux-lease-drop",
            "faults": [{"kind": "rpc", "method": "RequestWorkerLease",
                        "where": "response", "nth": 2,
                        "max_injections": 2}]}
    try:
        report = chaos.run_plan(
            plan, seed=7, verify=False,
            workload=lambda: ray_tpu.get(
                [val.remote(i) for i in range(24)], timeout=120))
        assert report["workload"] == list(range(24))
    finally:
        set_chaos(None)
        # Drain the stranded un-acked leases NOW, while the orphan
        # timeout is still 1 s: left behind, they age out ~10 s later
        # inside whatever test shares the cluster next — the cross-file
        # test_lease_wedge_watchdog_fires flake was exactly this test's
        # strands meeting that test's injected wedge entries.
        from ray_tpu.core import api as core_api

        raylet = core_api._node.raylet

        def _drained() -> bool:
            stale = any(
                w.state in ("leased", "dedicated") and not w.lease_acked
                and not w.loop_pinned for w in raylet._workers.values())
            waiting = any(not e["fut"].done()
                          for e in raylet._admission_queue)
            return not stale and not waiting

        deadline = time.monotonic() + 30
        while not _drained() and time.monotonic() < deadline:
            time.sleep(0.2)
        assert _drained(), "stranded un-acked leases were not reclaimed"
        cfg.lease_orphan_timeout_s = saved_orphan


def test_lease_coalesce_degrade_is_config_knob(ray_cluster, _knobs):
    """ISSUE 14 small fix: the stuck-leader de-coalesce window is the
    `lease_coalesce_degrade_ms` config entry (was a hard-coded 0.5 s) —
    a follower parked on a wedged leader's gate must degrade to its own
    lease RPC after the configured window."""
    cfg = get_config()
    saved = cfg.lease_coalesce_degrade_ms
    cfg.lease_coalesce_degrade_ms = 120.0
    w = global_worker()
    key = ("degrade-test", 0)
    acquires: list[int] = []

    async def scenario():
        # A leader holds the gate and NEVER resolves its waiters (the
        # stuck-leader shape: dropped reply / wedged spawn).
        w._lease_gates[key] = {"waiters": []}
        real_acquire = w._acquire_lease

        async def stub_acquire(spec, num_workers=1):
            acquires.append(num_workers)
            return None, "stub-denied"

        w._acquire_lease = stub_acquire
        try:
            t0 = time.monotonic()
            leases, reason = await w._acquire_lease_shared(key, _spec("d"))
            waited = time.monotonic() - t0
        finally:
            w._acquire_lease = real_acquire
            w._lease_gates.pop(key, None)
        return leases, reason, waited

    leases, reason, waited = w.io.run_sync(scenario(), timeout=30)
    # degraded: issued its OWN acquire after ~the configured window, not
    # the old 0.5 s constant and not the full RPC timeout
    assert leases is None and reason == "stub-denied"
    assert acquires, "follower never de-coalesced"
    assert 0.08 <= waited < 0.45, waited
    cfg.lease_coalesce_degrade_ms = saved


def test_lease_coalesce_degrade_reads_chaos_clock(ray_cluster, _knobs):
    """The degrade deadline rides the chaos clock: under a FROZEN
    VirtualClock the follower never degrades on wall time alone; an
    explicit advance() past the window fires it deterministically."""
    from ray_tpu.chaos import clock as chaos_clock

    cfg = get_config()
    saved = cfg.lease_coalesce_degrade_ms
    cfg.lease_coalesce_degrade_ms = 1000.0
    w = global_worker()
    key = ("degrade-vclock", 0)
    vclock = chaos_clock.VirtualClock(rate=0.0)  # frozen: manual advance only

    async def scenario():
        w._lease_gates[key] = {"waiters": []}
        real_acquire = w._acquire_lease
        degraded = asyncio.Event()

        async def stub_acquire(spec, num_workers=1):
            degraded.set()
            return None, "vclock-denied"

        w._acquire_lease = stub_acquire
        chaos_clock.set_clock(vclock)
        try:
            waiter = asyncio.ensure_future(
                w._acquire_lease_shared(key, _spec("v")))
            # Frozen clock: 0.4 real seconds (wall would NOT have degraded
            # yet anyway at 1000 ms — but virtual time hasn't moved at all).
            await asyncio.sleep(0.4)
            assert not degraded.is_set()
            vclock.advance(2.0)  # virtual 2 s > the 1 s window
            await asyncio.wait_for(degraded.wait(), timeout=10.0)
            leases, reason = await asyncio.wait_for(waiter, timeout=10.0)
            return leases, reason
        finally:
            chaos_clock.set_clock(None)
            w._acquire_lease = real_acquire
            w._lease_gates.pop(key, None)

    leases, reason = w.io.run_sync(scenario(), timeout=60)
    assert leases is None and reason == "vclock-denied"
    cfg.lease_coalesce_degrade_ms = saved


def test_node_table_refresh_is_shared(ray_cluster):
    """Concurrent refreshers ride one in-flight GetAllNodes, and a
    max_age hit skips the RPC entirely."""
    from ray_tpu.core import api as core_api

    node = core_api._node
    raylet = node.raylet
    calls = {"n": 0}
    real_gcs = raylet._gcs
    cfg = get_config()
    saved_hb = cfg.health_check_period_ms
    # Park the heartbeat loop (it refreshes the node table on its own
    # cadence and would race the counters); in-flight beat drains below.
    cfg.health_check_period_ms = 120_000
    time.sleep(1.3)

    class _CountingGcs:
        async def call(self, method, payload=None, timeout=None):
            if method == "GetAllNodes":
                calls["n"] += 1
                await asyncio.sleep(0.05)
            return await real_gcs.call(method, payload, timeout)

    async def _run():
        raylet._gcs = _CountingGcs()
        try:
            await asyncio.gather(*[raylet._refresh_node_table()
                                   for _ in range(8)])
            shared = calls["n"]
            await raylet._refresh_node_table(max_age_s=60.0)
            return shared, calls["n"]
        finally:
            raylet._gcs = real_gcs

    try:
        shared, after_cached = node.services_loop.run_sync(_run(), timeout=30)
    finally:
        cfg.health_check_period_ms = saved_hb
    assert shared == 1, f"8 concurrent refreshes paid {shared} RPCs"
    assert after_cached == shared  # max_age hit: no extra RPC


def test_actor_call_batching_equivalence(ray_cluster, _knobs):
    """A burst of calls to serialized actors (batched PushActorTasks) and
    a concurrency>1 actor (never batched) both keep per-actor order and
    exact results."""

    @ray_tpu.remote
    class Seq:
        def __init__(self):
            self.log = []

        def add(self, i):
            self.log.append(i)
            return i

        def get_log(self):
            return list(self.log)

    actors = [Seq.remote() for _ in range(3)]
    refs = [a.add.remote(i) for i in range(30) for a in actors]
    assert ray_tpu.get(refs, timeout=60) == [
        i for i in range(30) for _ in actors]
    for a in actors:
        # strict submission order per actor: the batched path must not
        # reorder (log ends with the add() calls in order, after them
        # the get_log call itself is serialized too)
        assert ray_tpu.get(a.get_log.remote(), timeout=30) == list(range(30))

    @ray_tpu.remote(max_concurrency=4)
    class Conc:
        def val(self, i):
            return i * 3

    c = Conc.remote()
    assert ray_tpu.get([c.val.remote(i) for i in range(20)],
                       timeout=60) == [i * 3 for i in range(20)]
