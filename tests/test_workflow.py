"""Durable workflows: checkpointed steps, crash resume, exactly-once.

Reference surface: ``python/ray/workflow/tests/test_basic_workflows.py``
(run/resume/get_output/list_all semantics).
"""

import os

import pytest

from ray_tpu import workflow


def test_workflow_runs_dag_and_persists_output(ray_cluster, tmp_path):
    def load():
        return [1, 2, 3]

    def double(xs):
        return [2 * x for x in xs]

    def total(xs):
        return sum(xs)

    dag = workflow.step(total)(workflow.step(double)(workflow.step(load)()))
    result = workflow.run(dag, workflow_id="wf-basic", storage=str(tmp_path))
    assert result == 12
    assert workflow.get_output("wf-basic", storage=str(tmp_path)) == 12
    assert workflow.get_status("wf-basic", storage=str(tmp_path)) == "SUCCESSFUL"
    assert ("wf-basic", "SUCCESSFUL") in workflow.list_all(storage=str(tmp_path))


def test_workflow_resume_skips_completed_steps(ray_cluster, tmp_path):
    """A step that crashed mid-workflow is retried on resume; steps that
    already checkpointed must NOT re-execute (exactly-once side effects)."""
    marker_dir = tmp_path / "markers"
    marker_dir.mkdir()

    def effect(name):
        # counts executions via filesystem side effect
        path = marker_dir / name
        with open(path, "a") as f:
            f.write("x")
        return name

    def fragile(dep):
        if not os.path.exists(marker_dir / "fixed"):
            raise RuntimeError("transient failure")
        return dep + "-done"

    dag = workflow.step(fragile)(workflow.step(effect)("a"))
    with pytest.raises(RuntimeError, match="transient"):
        workflow.run(dag, workflow_id="wf-crash", storage=str(tmp_path))
    assert workflow.get_status("wf-crash", storage=str(tmp_path)) == "FAILED"
    assert (marker_dir / "a").stat().st_size == 1  # step "a" ran once

    (marker_dir / "fixed").touch()
    result = workflow.resume("wf-crash", storage=str(tmp_path))
    assert result == "a-done"
    assert (marker_dir / "a").stat().st_size == 1  # NOT re-executed on resume
    assert workflow.get_status("wf-crash", storage=str(tmp_path)) == "SUCCESSFUL"


def test_workflow_diamond_shares_upstream(ray_cluster, tmp_path):
    """A diamond DAG evaluates the shared upstream once (memoized) and
    checkpoints each step separately."""
    calls = tmp_path / "calls"

    def src():
        with open(calls, "a") as f:
            f.write("s")
        return 10

    def left(x):
        return x + 1

    def right(x):
        return x + 2

    def join(a, b):
        return a * b

    shared = workflow.step(src)()
    dag = workflow.step(join)(workflow.step(left)(shared), workflow.step(right)(shared))
    assert workflow.run(dag, workflow_id="wf-diamond", storage=str(tmp_path)) == 11 * 12
    assert calls.stat().st_size == 1


def test_workflow_nested_container_steps_resolve(ray_cluster, tmp_path):
    """StepNodes nested in lists/dicts are dependencies too."""

    def make(v):
        return v

    def merge(items, named):
        return sum(items) + named["extra"]

    dag = workflow.step(merge)(
        [workflow.step(make)(1), workflow.step(make)(2)],
        {"extra": workflow.step(make)(10)},
    )
    assert workflow.run(dag, workflow_id="wf-nested", storage=str(tmp_path)) == 13


def test_workflow_listing_ignores_stray_files(ray_cluster, tmp_path):
    (tmp_path / "README.md").write_text("not a workflow")
    dag = workflow.step(lambda: 1)().options("one")
    workflow.run(dag, workflow_id="wf-real", storage=str(tmp_path))
    listing = workflow.list_all(storage=str(tmp_path))
    assert listing == [("wf-real", "SUCCESSFUL")]
    # read-only status probe must not create directories for unknown ids
    assert workflow.get_status("never-existed", storage=str(tmp_path)) is None
    assert not (tmp_path / "never-existed").exists()


def test_workflow_rerun_same_id_returns_checkpointed(ray_cluster, tmp_path):
    ticks = tmp_path / "ticks"

    def effect():
        with open(ticks, "a") as f:
            f.write("t")
        return 7

    dag = workflow.step(effect)()
    assert workflow.run(dag, workflow_id="wf-idem", storage=str(tmp_path)) == 7
    assert workflow.run(dag, workflow_id="wf-idem", storage=str(tmp_path)) == 7
    assert ticks.stat().st_size == 1  # second run fully served from storage


def test_independent_branches_run_concurrently(ray_cluster, tmp_path):
    """Two independent 1.2s branches must finish in ~max, not ~sum —
    the executor schedules every ready step (reference
    workflow_executor.py:32), not one at a time."""
    import time as _time

    @workflow.step
    def slow(tag):
        import time

        time.sleep(1.2)
        return tag

    @workflow.step
    def join(a, b):
        return a + b

    dag = join(slow("a"), slow("b"))
    t0 = _time.monotonic()
    out = workflow.run(dag, workflow_id=f"wf-par-{_time.time_ns()}",
                       storage=str(tmp_path))
    elapsed = _time.monotonic() - t0
    assert out == "ab"
    assert elapsed < 2.2, f"branches serialized: {elapsed:.1f}s"


def test_continuation_extends_workflow(ray_cluster, tmp_path):
    """A step returning workflow.continuation(sub_dag) dynamically extends
    the DAG; the sub-DAG's result becomes the step's result (reference
    workflow.continuation)."""

    @workflow.step
    def double(x):
        return x * 2

    @workflow.step
    def decide(x):
        if x < 10:
            return workflow.continuation(double(x + 3))
        return x

    @workflow.step
    def plus_one(x):
        return x + 1

    dag = plus_one(decide(2))
    out = workflow.run(dag, workflow_id="wf-cont", storage=str(tmp_path))
    assert out == (2 + 3) * 2 + 1  # continuation ran, parent saw its result


def test_recursive_continuations_checkpoint(ray_cluster, tmp_path):
    """Recursion via continuations (the reference's factorial example):
    each level checkpoints in its parent step's namespace."""

    @workflow.step
    def fact(n, acc=1):
        if n <= 1:
            return acc
        return workflow.continuation(fact(n - 1, acc * n))

    out = workflow.run(fact(5), workflow_id="wf-fact", storage=str(tmp_path))
    assert out == 120
    # rerun is fully served from checkpoints
    assert workflow.run(fact(5), workflow_id="wf-fact", storage=str(tmp_path)) == 120


def test_resume_inside_continuation_never_reruns_step_body(ray_cluster, tmp_path):
    """Crash between a step finishing (returning a continuation) and the
    sub-DAG completing: resume continues INSIDE the continuation; the
    step's own side effect happens exactly once."""
    body_runs = tmp_path / "body_runs"
    flaky_flag = tmp_path / "fail_once"
    flaky_flag.write_text("1")

    @workflow.step
    def sub(x):
        if os.path.exists(str(flaky_flag)):
            os.unlink(str(flaky_flag))
            raise RuntimeError("simulated crash inside the continuation")
        return x * 10

    @workflow.step
    def body():
        with open(str(body_runs), "a") as f:
            f.write("x")
        return workflow.continuation(sub(4))

    dag = body()
    with pytest.raises(Exception):
        workflow.run(dag, workflow_id="wf-cont-crash", storage=str(tmp_path))
    out = workflow.resume("wf-cont-crash", storage=str(tmp_path))
    assert out == 40
    assert body_runs.stat().st_size == 1, "step body re-ran on resume"


def test_event_step_unblocks_on_trigger(ray_cluster, tmp_path):
    """wait_for_event parks a step until trigger_event fires; the payload
    checkpoints like any result (reference workflow/event_listener.py)."""
    import threading
    import time as _time

    @workflow.step
    def combine(payload, tag):
        return f"{payload}-{tag}"

    key = f"approval-{_time.time_ns()}"
    dag = combine(workflow.wait_for_event(key), "done")

    def fire():
        _time.sleep(1.0)
        workflow.trigger_event(key, "approved")

    t = threading.Thread(target=fire)
    t.start()
    out = workflow.run(dag, workflow_id="wf-event", storage=str(tmp_path))
    t.join()
    assert out == "approved-done"
    # resume serves the event payload from its checkpoint (no re-listen)
    assert workflow.run(dag, workflow_id="wf-event", storage=str(tmp_path)) == "approved-done"


def test_deep_continuation_chain_is_iterative(ray_cluster, tmp_path):
    """A long tail-continuation chain must not exhaust the driver stack:
    the loop grafts each level into the ONE driver loop (no nested
    executors), and sibling branches keep checkpointing meanwhile."""
    import sys

    @workflow.step
    def count_down(n):
        if n <= 0:
            return "done"
        return workflow.continuation(count_down(n - 1))

    depth = 60
    limit = sys.getrecursionlimit()
    try:
        sys.setrecursionlimit(200)  # far below depth * frames-per-level
        out = workflow.run(count_down(depth), workflow_id="wf-deep",
                           storage=str(tmp_path), step_timeout_s=120)
    finally:
        sys.setrecursionlimit(limit)
    assert out == "done"
