"""Autoscaler v2: typed instance lifecycle + GKE/KubeRay provider.

References: ``python/ray/autoscaler/v2/instance_manager/`` (typed FSM,
stuck-instance reconciliation) and
``python/ray/autoscaler/_private/kuberay/node_provider.py`` (CR-patching
scale semantics, precise scale-down, multi-host replicaIndex).
"""

import pytest

from ray_tpu.autoscaler.gke import GkeTpuNodeProvider
from ray_tpu.autoscaler.instance_manager import (
    ALLOCATED,
    ALLOCATION_FAILED,
    RAY_RUNNING,
    REQUESTED,
    TERMINATED,
    TERMINATING,
    InstanceManager,
    InvalidTransition,
)


class FakeCloud:
    """NodeProvider test double with controllable visibility/failures."""

    def __init__(self):
        self.created = []
        self.terminated = []
        self.visible = set()
        self.fail_create = False
        self.ignore_terminate = False
        self._n = 0
        self.preempted = {}  # iid -> node_type (GCE spot-reclaim notices)

    def create_node(self, node_type, resources):
        if self.fail_create:
            raise RuntimeError("stockout")
        self._n += 1
        iid = f"vm-{self._n}"
        self.created.append(iid)
        self.visible.add(iid)
        return iid

    def terminate_node(self, iid):
        self.terminated.append(iid)
        if not self.ignore_terminate:
            self.visible.discard(iid)

    def non_terminated_nodes(self):
        return {iid: "t" for iid in self.visible}

    def node_id_of(self, iid):
        return None

    def preempt(self, iid):
        """The cloud reclaims a spot VM: it leaves the listing and a
        preemption notice surfaces (GceTpuNodeProvider semantics)."""
        self.visible.discard(iid)
        self.preempted[iid] = "t"

    def preemption_notices(self):
        return dict(self.preempted)

    def ack_preemption(self, iid):
        self.preempted.pop(iid, None)


def test_instance_lifecycle_happy_path():
    cloud = FakeCloud()
    mgr = InstanceManager(cloud)
    iid = mgr.create_node("t", {"CPU": 1})
    (inst,) = mgr.instances()
    assert inst.state == REQUESTED and inst.cloud_instance_id == iid

    mgr.reconcile([])
    assert mgr.instances()[0].state == ALLOCATED

    mgr.reconcile([{"node_id": "gcs-node-1", "state": "ALIVE"}])
    inst = mgr.instances()[0]
    assert inst.state == RAY_RUNNING and inst.node_id == "gcs-node-1"

    mgr.terminate_node(iid)
    assert mgr.instances()[0].state == TERMINATING
    mgr.reconcile([])
    assert mgr.instances()[0].state == TERMINATED


def test_allocation_failure_retries_then_gives_up():
    cloud = FakeCloud()
    cloud.fail_create = True
    mgr = InstanceManager(cloud, max_allocation_retries=2)
    mgr.create_node("t", {"CPU": 1})
    assert mgr.instances()[0].state == ALLOCATION_FAILED
    repairs = mgr.reconcile([])
    assert repairs["allocation_retried"] == 1
    assert mgr.instances()[0].state == ALLOCATION_FAILED  # retry also failed
    mgr.reconcile([])
    repairs = mgr.reconcile([])
    assert repairs["allocation_failed"] == 1
    assert mgr.instances()[0].state == TERMINATED

    # ...but a recovered cloud lets a retry succeed
    cloud2 = FakeCloud()
    cloud2.fail_create = True
    mgr2 = InstanceManager(cloud2, max_allocation_retries=2)
    mgr2.create_node("t", {"CPU": 1})
    cloud2.fail_create = False
    mgr2.reconcile([])
    assert mgr2.instances()[0].state == REQUESTED
    assert mgr2.instances()[0].retries == 1


def test_stuck_ray_boot_replaced():
    cloud = FakeCloud()
    mgr = InstanceManager(cloud, ray_boot_timeout_s=0.0)
    mgr.create_node("t", {})
    mgr.reconcile([])  # -> ALLOCATED
    repairs = mgr.reconcile([])  # boot timeout immediately (0s)
    assert repairs["ray_boot_timeout"] == 1
    inst = mgr.instances()[0]
    assert inst.state == TERMINATING
    assert cloud.terminated == [inst.cloud_instance_id]


def test_stuck_terminate_reissued():
    cloud = FakeCloud()
    cloud.ignore_terminate = True
    mgr = InstanceManager(cloud, terminate_timeout_s=0.0)
    iid = mgr.create_node("t", {})
    mgr.reconcile([])
    mgr.terminate_node(iid)
    repairs = mgr.reconcile([])
    assert repairs["terminate_reissued"] == 1
    assert cloud.terminated.count(iid) == 2


def test_preexisting_gcs_nodes_never_claimed():
    """The head node (alive before any managed instance) must not be
    matched to an ALLOCATED instance."""
    cloud = FakeCloud()
    mgr = InstanceManager(cloud)
    mgr.reconcile([{"node_id": "head", "state": "ALIVE"}])  # snapshot
    mgr.create_node("t", {})
    mgr.reconcile([{"node_id": "head", "state": "ALIVE"}])
    assert mgr.instances()[0].state == ALLOCATED  # not RAY_RUNNING via head
    mgr.reconcile([{"node_id": "head", "state": "ALIVE"},
                   {"node_id": "w1", "state": "ALIVE"}])
    inst = mgr.instances()[0]
    assert inst.state == RAY_RUNNING and inst.node_id == "w1"


def test_preempted_instance_detected_and_replaced():
    """ISSUE 9 satellite: a RAY_RUNNING instance the cloud preempts is
    terminated AND a same-shape replacement is requested in the SAME
    reconcile round (GCE spot-reclaim semantics)."""
    cloud = FakeCloud()
    mgr = InstanceManager(cloud)
    iid = mgr.create_node("t", {"CPU": 4, "TPU": 8})
    mgr.reconcile([])  # -> ALLOCATED
    mgr.reconcile([{"node_id": "n1", "state": "ALIVE"}])  # -> RAY_RUNNING
    assert mgr.instances()[0].state == RAY_RUNNING

    cloud.preempt(iid)
    repairs = mgr.reconcile([{"node_id": "n1", "state": "ALIVE"}])
    assert repairs["preempt_replaced"] == 1
    by_state = {i.state: i for i in mgr.instances()}
    # the preempted instance is on its way out...
    assert by_state.get(TERMINATING) or by_state.get(TERMINATED)
    # ...and the replacement was REQUESTED with the same shape
    replacement = by_state[REQUESTED]
    assert replacement.node_type == "t"
    assert replacement.resources == {"CPU": 4, "TPU": 8}
    assert len(cloud.created) == 2
    # the notice was acked: a second round must not replace again
    repairs = mgr.reconcile([{"node_id": "n1", "state": "ALIVE"}])
    assert repairs["preempt_replaced"] == 0
    assert len(cloud.created) == 2


def test_preemption_replacement_disabled():
    """replace_preempted=False: the preempted instance still terminates
    (it left the listing) but no replacement is requested."""
    cloud = FakeCloud()
    mgr = InstanceManager(cloud, replace_preempted=False)
    iid = mgr.create_node("t", {})
    mgr.reconcile([])
    mgr.reconcile([{"node_id": "n1", "state": "ALIVE"}])
    cloud.preempt(iid)
    repairs = mgr.reconcile([])
    assert repairs["preempt_replaced"] == 0
    assert len(cloud.created) == 1
    assert mgr.instances()[0].state == TERMINATED  # listing-vanish path


def test_gce_provider_surfaces_preemption_notices():
    """The GCE provider turns a node LISTED as PREEMPTED into a typed
    notice (and out of the live listing) until the reconciler acks it."""
    from ray_tpu.autoscaler.gce import GceTpuNodeProvider

    class FakeTransport:
        def __init__(self):
            self.nodes = []

        def request(self, method, url, body=None):
            if method == "GET":
                return {"nodes": self.nodes}
            return {}

    transport = FakeTransport()
    p = GceTpuNodeProvider(
        "proj", "zone", gcs_address="host:1",
        node_types={"v5e-16": {"accelerator_type": "v5litepod-16"}},
        transport=transport)
    iid = p.create_node("v5e-16", {})
    transport.nodes = [{
        "name": f"projects/proj/locations/zone/nodes/{iid}",
        "state": "READY",
        "labels": {"raytpu-cluster": "raytpu", "raytpu-node-type": "v5e-16"},
    }]
    assert iid in p.non_terminated_nodes()
    assert p.preemption_notices() == {}

    transport.nodes[0]["state"] = "PREEMPTED"
    assert iid not in p.non_terminated_nodes()
    assert p.preemption_notices() == {iid: "v5e-16"}
    p.ack_preemption(iid)
    assert p.preemption_notices() == {}


def test_invalid_transition_rejected():
    cloud = FakeCloud()
    mgr = InstanceManager(cloud)
    mgr.create_node("t", {})
    inst = mgr.instances()[0]
    with pytest.raises(InvalidTransition):
        mgr._transition(inst, RAY_RUNNING and TERMINATED)  # REQUESTED -> TERMINATED


# ---------------------------------------------------------------- GKE/KubeRay


class FakeK8s:
    """Mimics the RayCluster CR + the operator's pod actuation: the
    operator deletes exactly the named workers and creates fresh replicas
    to reach the requested count (KubeRay semantics)."""

    def __init__(self, groups):
        self.cr = {"spec": {"workerGroupSpecs": [
            {"groupName": name, "replicas": 0, "numOfHosts": hosts}
            for name, hosts in groups.items()
        ]}}
        self.live: dict[str, list[str]] = {name: [] for name in groups}
        self._next: dict[str, int] = {name: 0 for name in groups}
        self.patches = []

    def _operate(self):
        """The operator's reconcile: actuate pods to match the CR."""
        for g in self.cr["spec"]["workerGroupSpecs"]:
            name = g["groupName"]
            deleted = set((g.get("scaleStrategy") or {}).get("workersToDelete") or [])
            self.live[name] = [r for r in self.live[name] if r not in deleted]
            while len(self.live[name]) < int(g.get("replicas") or 0):
                self.live[name].append(f"{name}-r{self._next[name]}")
                self._next[name] += 1

    def request(self, method, path, body=None):
        if method == "GET" and "/rayclusters/" in path:
            return self.cr
        if method == "PATCH":
            self.patches.append(body)
            for op in body:
                parts = op["path"].strip("/").split("/")
                target = self.cr
                for p in parts[:-1]:
                    target = target[int(p)] if p.isdigit() else target[p]
                target[parts[-1]] = op["value"]
            self._operate()
            return {}
        if method == "GET" and "/pods" in path:
            items = []
            for g in self.cr["spec"]["workerGroupSpecs"]:
                for rid in self.live[g["groupName"]]:
                    for h in range(int(g.get("numOfHosts") or 1)):
                        items.append({
                            "metadata": {
                                "name": f"{rid}-host{h}",
                                "labels": {
                                    "ray.io/node-type": "worker",
                                    "ray.io/group": g["groupName"],
                                    "replicaIndex": rid,
                                },
                            },
                            "status": {"phase": "Running"},
                        })
            return {"items": items}
        raise AssertionError((method, path))


def make_gke(groups=None):
    k8s = FakeK8s(groups or {"tpu-v5e-16": 4})
    return GkeTpuNodeProvider("ns", "rc", transport=k8s), k8s


def test_gke_scale_up_patches_replicas():
    p, k8s = make_gke()
    p.create_node("tpu-v5e-16", {})
    assert k8s.cr["spec"]["workerGroupSpecs"][0]["replicas"] == 1
    # one REPLICA (multi-host slice) == one node, though numOfHosts=4 pods
    nodes = p.non_terminated_nodes()
    assert nodes == {"tpu-v5e-16-r0": "tpu-v5e-16"}


def test_gke_precise_scale_down():
    p, k8s = make_gke()
    p.create_node("tpu-v5e-16", {})
    p.create_node("tpu-v5e-16", {})
    assert len(p.non_terminated_nodes()) == 2
    p.terminate_node("tpu-v5e-16-r0")
    spec = k8s.cr["spec"]["workerGroupSpecs"][0]
    assert spec["replicas"] == 1
    assert spec["scaleStrategy"]["workersToDelete"] == ["tpu-v5e-16-r0"]
    assert list(p.non_terminated_nodes()) == ["tpu-v5e-16-r1"]


def test_gke_unknown_group_rejected():
    p, _ = make_gke()
    with pytest.raises(ValueError, match="worker group"):
        p.create_node("nope", {})


def test_gke_under_instance_manager():
    """The v2 lifecycle wraps the GKE provider transparently."""
    p, _ = make_gke()
    mgr = InstanceManager(p)
    mgr.create_node("tpu-v5e-16", {})
    assert mgr.instances()[0].state == REQUESTED
    mgr.reconcile([])
    # the synthetic launch id is not a live replica id; the replica list
    # has the real one — the instance stays REQUESTED until its timeout
    # (identity-free clouds converge via the autoscaler's pending-launch
    # expiry), while the REPLICA is visible as capacity:
    assert p.non_terminated_nodes() == {"tpu-v5e-16-r0": "tpu-v5e-16"}
