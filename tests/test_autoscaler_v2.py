"""Autoscaler v2: typed instance lifecycle + GKE/KubeRay provider.

References: ``python/ray/autoscaler/v2/instance_manager/`` (typed FSM,
stuck-instance reconciliation) and
``python/ray/autoscaler/_private/kuberay/node_provider.py`` (CR-patching
scale semantics, precise scale-down, multi-host replicaIndex).
"""

import pytest

from ray_tpu.autoscaler.gke import GkeTpuNodeProvider
from ray_tpu.autoscaler.instance_manager import (
    ALLOCATED,
    ALLOCATION_FAILED,
    RAY_RUNNING,
    REQUESTED,
    TERMINATED,
    TERMINATING,
    InstanceManager,
    InvalidTransition,
)


class FakeCloud:
    """NodeProvider test double with controllable visibility/failures."""

    def __init__(self):
        self.created = []
        self.terminated = []
        self.visible = set()
        self.fail_create = False
        self.ignore_terminate = False
        self._n = 0

    def create_node(self, node_type, resources):
        if self.fail_create:
            raise RuntimeError("stockout")
        self._n += 1
        iid = f"vm-{self._n}"
        self.created.append(iid)
        self.visible.add(iid)
        return iid

    def terminate_node(self, iid):
        self.terminated.append(iid)
        if not self.ignore_terminate:
            self.visible.discard(iid)

    def non_terminated_nodes(self):
        return {iid: "t" for iid in self.visible}

    def node_id_of(self, iid):
        return None


def test_instance_lifecycle_happy_path():
    cloud = FakeCloud()
    mgr = InstanceManager(cloud)
    iid = mgr.create_node("t", {"CPU": 1})
    (inst,) = mgr.instances()
    assert inst.state == REQUESTED and inst.cloud_instance_id == iid

    mgr.reconcile([])
    assert mgr.instances()[0].state == ALLOCATED

    mgr.reconcile([{"node_id": "gcs-node-1", "state": "ALIVE"}])
    inst = mgr.instances()[0]
    assert inst.state == RAY_RUNNING and inst.node_id == "gcs-node-1"

    mgr.terminate_node(iid)
    assert mgr.instances()[0].state == TERMINATING
    mgr.reconcile([])
    assert mgr.instances()[0].state == TERMINATED


def test_allocation_failure_retries_then_gives_up():
    cloud = FakeCloud()
    cloud.fail_create = True
    mgr = InstanceManager(cloud, max_allocation_retries=2)
    mgr.create_node("t", {"CPU": 1})
    assert mgr.instances()[0].state == ALLOCATION_FAILED
    repairs = mgr.reconcile([])
    assert repairs["allocation_retried"] == 1
    assert mgr.instances()[0].state == ALLOCATION_FAILED  # retry also failed
    mgr.reconcile([])
    repairs = mgr.reconcile([])
    assert repairs["allocation_failed"] == 1
    assert mgr.instances()[0].state == TERMINATED

    # ...but a recovered cloud lets a retry succeed
    cloud2 = FakeCloud()
    cloud2.fail_create = True
    mgr2 = InstanceManager(cloud2, max_allocation_retries=2)
    mgr2.create_node("t", {"CPU": 1})
    cloud2.fail_create = False
    mgr2.reconcile([])
    assert mgr2.instances()[0].state == REQUESTED
    assert mgr2.instances()[0].retries == 1


def test_stuck_ray_boot_replaced():
    cloud = FakeCloud()
    mgr = InstanceManager(cloud, ray_boot_timeout_s=0.0)
    mgr.create_node("t", {})
    mgr.reconcile([])  # -> ALLOCATED
    repairs = mgr.reconcile([])  # boot timeout immediately (0s)
    assert repairs["ray_boot_timeout"] == 1
    inst = mgr.instances()[0]
    assert inst.state == TERMINATING
    assert cloud.terminated == [inst.cloud_instance_id]


def test_stuck_terminate_reissued():
    cloud = FakeCloud()
    cloud.ignore_terminate = True
    mgr = InstanceManager(cloud, terminate_timeout_s=0.0)
    iid = mgr.create_node("t", {})
    mgr.reconcile([])
    mgr.terminate_node(iid)
    repairs = mgr.reconcile([])
    assert repairs["terminate_reissued"] == 1
    assert cloud.terminated.count(iid) == 2


def test_preexisting_gcs_nodes_never_claimed():
    """The head node (alive before any managed instance) must not be
    matched to an ALLOCATED instance."""
    cloud = FakeCloud()
    mgr = InstanceManager(cloud)
    mgr.reconcile([{"node_id": "head", "state": "ALIVE"}])  # snapshot
    mgr.create_node("t", {})
    mgr.reconcile([{"node_id": "head", "state": "ALIVE"}])
    assert mgr.instances()[0].state == ALLOCATED  # not RAY_RUNNING via head
    mgr.reconcile([{"node_id": "head", "state": "ALIVE"},
                   {"node_id": "w1", "state": "ALIVE"}])
    inst = mgr.instances()[0]
    assert inst.state == RAY_RUNNING and inst.node_id == "w1"


def test_invalid_transition_rejected():
    cloud = FakeCloud()
    mgr = InstanceManager(cloud)
    mgr.create_node("t", {})
    inst = mgr.instances()[0]
    with pytest.raises(InvalidTransition):
        mgr._transition(inst, RAY_RUNNING and TERMINATED)  # REQUESTED -> TERMINATED


# ---------------------------------------------------------------- GKE/KubeRay


class FakeK8s:
    """Mimics the RayCluster CR + the operator's pod actuation: the
    operator deletes exactly the named workers and creates fresh replicas
    to reach the requested count (KubeRay semantics)."""

    def __init__(self, groups):
        self.cr = {"spec": {"workerGroupSpecs": [
            {"groupName": name, "replicas": 0, "numOfHosts": hosts}
            for name, hosts in groups.items()
        ]}}
        self.live: dict[str, list[str]] = {name: [] for name in groups}
        self._next: dict[str, int] = {name: 0 for name in groups}
        self.patches = []

    def _operate(self):
        """The operator's reconcile: actuate pods to match the CR."""
        for g in self.cr["spec"]["workerGroupSpecs"]:
            name = g["groupName"]
            deleted = set((g.get("scaleStrategy") or {}).get("workersToDelete") or [])
            self.live[name] = [r for r in self.live[name] if r not in deleted]
            while len(self.live[name]) < int(g.get("replicas") or 0):
                self.live[name].append(f"{name}-r{self._next[name]}")
                self._next[name] += 1

    def request(self, method, path, body=None):
        if method == "GET" and "/rayclusters/" in path:
            return self.cr
        if method == "PATCH":
            self.patches.append(body)
            for op in body:
                parts = op["path"].strip("/").split("/")
                target = self.cr
                for p in parts[:-1]:
                    target = target[int(p)] if p.isdigit() else target[p]
                target[parts[-1]] = op["value"]
            self._operate()
            return {}
        if method == "GET" and "/pods" in path:
            items = []
            for g in self.cr["spec"]["workerGroupSpecs"]:
                for rid in self.live[g["groupName"]]:
                    for h in range(int(g.get("numOfHosts") or 1)):
                        items.append({
                            "metadata": {
                                "name": f"{rid}-host{h}",
                                "labels": {
                                    "ray.io/node-type": "worker",
                                    "ray.io/group": g["groupName"],
                                    "replicaIndex": rid,
                                },
                            },
                            "status": {"phase": "Running"},
                        })
            return {"items": items}
        raise AssertionError((method, path))


def make_gke(groups=None):
    k8s = FakeK8s(groups or {"tpu-v5e-16": 4})
    return GkeTpuNodeProvider("ns", "rc", transport=k8s), k8s


def test_gke_scale_up_patches_replicas():
    p, k8s = make_gke()
    p.create_node("tpu-v5e-16", {})
    assert k8s.cr["spec"]["workerGroupSpecs"][0]["replicas"] == 1
    # one REPLICA (multi-host slice) == one node, though numOfHosts=4 pods
    nodes = p.non_terminated_nodes()
    assert nodes == {"tpu-v5e-16-r0": "tpu-v5e-16"}


def test_gke_precise_scale_down():
    p, k8s = make_gke()
    p.create_node("tpu-v5e-16", {})
    p.create_node("tpu-v5e-16", {})
    assert len(p.non_terminated_nodes()) == 2
    p.terminate_node("tpu-v5e-16-r0")
    spec = k8s.cr["spec"]["workerGroupSpecs"][0]
    assert spec["replicas"] == 1
    assert spec["scaleStrategy"]["workersToDelete"] == ["tpu-v5e-16-r0"]
    assert list(p.non_terminated_nodes()) == ["tpu-v5e-16-r1"]


def test_gke_unknown_group_rejected():
    p, _ = make_gke()
    with pytest.raises(ValueError, match="worker group"):
        p.create_node("nope", {})


def test_gke_under_instance_manager():
    """The v2 lifecycle wraps the GKE provider transparently."""
    p, _ = make_gke()
    mgr = InstanceManager(p)
    mgr.create_node("tpu-v5e-16", {})
    assert mgr.instances()[0].state == REQUESTED
    mgr.reconcile([])
    # the synthetic launch id is not a live replica id; the replica list
    # has the real one — the instance stays REQUESTED until its timeout
    # (identity-free clouds converge via the autoscaler's pending-launch
    # expiry), while the REPLICA is visible as capacity:
    assert p.non_terminated_nodes() == {"tpu-v5e-16-r0": "tpu-v5e-16"}
