"""Cluster memory observability: `ray memory`-style reference debugging
(ref types + creation callsites through the TaskEventBuffer→GCS path),
object-store/HBM accounting gauges, the GCS leak watcher, and on-demand
profiling capture.

Mirrors the reference's ``python/ray/tests/test_memstat.py`` /
``test_metrics_agent.py`` surfaces, TPU-scoped.
"""

import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util import state


def _poll(fn, timeout=30.0, interval=0.3):
    deadline = time.monotonic() + timeout
    value = fn()
    while not value and time.monotonic() < deadline:
        time.sleep(interval)
        value = fn()
    return value


@pytest.fixture(autouse=True)
def _cluster(ray_cluster):
    yield


# ----------------------------------------------------------------- unit layer


def test_callsite_names_user_frame():
    from ray_tpu.observability.memory import capture_callsite

    site = capture_callsite()
    assert "test_memory_observability.py" in site
    assert "test_callsite_names_user_frame" in site


def test_classify_ref_priorities():
    from ray_tpu.observability import memory as m

    assert m.classify_ref(local=1, submitted=1, contained_in=0, borrowers=0,
                          pinned=False) == m.USED_BY_PENDING_TASK
    assert m.classify_ref(local=1, submitted=0, contained_in=1, borrowers=0,
                          pinned=False) == m.CAPTURED_IN_OBJECT
    assert m.classify_ref(local=2, submitted=0, contained_in=0, borrowers=0,
                          pinned=False) == m.LOCAL_REFERENCE
    assert m.classify_ref(local=0, submitted=0, contained_in=0, borrowers=0,
                          pinned=True) == m.PINNED_IN_STORE


def test_leak_detector_unit():
    """Injected monotonic growth fires exactly once, names the top holder
    by callsite, and re-arms after the trend flattens."""
    from ray_tpu.observability.memory import GcsMemoryStore, leak_event_message

    store = GcsMemoryStore()

    def summary(n):
        return {
            "worker_id": "w1", "node_id": "n1", "ts": time.time(),
            "num_refs": n, "total_bytes": n * 100,
            "entries": [{"object_id": f"o{i}", "size": 100,
                         "ref_type": "LOCAL_REFERENCE",
                         "callsite": "leaky.py:7 in hoard"} for i in range(n)],
        }

    for n in (10, 20, 30, 40, 50):
        store.report(summary(n))
    leaks = store.detect_leaks(intervals=4, min_growth_bytes=1 << 40,
                               min_growth_refs=20)
    assert len(leaks) == 1 and leaks[0]["worker_id"] == "w1"
    assert leaks[0]["top_holders"][0]["callsite"] == "leaky.py:7 in hoard"
    assert "leaky.py:7 in hoard" in leak_event_message(leaks[0])
    # already reported: silent while growth continues
    store.report(summary(60))
    assert store.detect_leaks(intervals=4, min_growth_bytes=1 << 40,
                              min_growth_refs=20) == []
    # flat trend re-arms, a fresh monotonic run fires again
    for n in (60, 60, 60, 60, 60):
        store.report(summary(n))
    assert store.detect_leaks(intervals=4, min_growth_bytes=1 << 40,
                              min_growth_refs=20) == []
    for n in (80, 110, 140, 170, 200):
        store.report(summary(n))
    assert len(store.detect_leaks(intervals=4, min_growth_bytes=1 << 40,
                                  min_growth_refs=20)) == 1
    # node pinned-bytes trend uses the same machinery
    for b in (1 << 20, 2 << 20, 3 << 20, 4 << 20, 5 << 20):
        store.report_node("node-a", b)
    node_leaks = store.detect_leaks(intervals=4, min_growth_bytes=1 << 20,
                                    min_growth_refs=1 << 30)
    assert any(s["kind"] == "node_pinned_bytes" for s in node_leaks)


# ------------------------------------------------------- reference debugging


def test_leaked_ref_attributed_end_to_end(tmp_path, capsys):
    """Acceptance: a deliberately leaked ObjectRef is attributable — the
    memory summary (and `cli memory`) shows its size, a
    USED_BY_PENDING_TASK→LOCAL_REFERENCE ref type, and this file as the
    creation callsite."""
    leaked = ray_tpu.put(np.arange(1024, dtype=np.int64))  # deliberately kept

    marker = str(tmp_path / "release")

    @ray_tpu.remote
    def hold(x, path):
        while not os.path.exists(path):
            time.sleep(0.05)
        return int(x[0])

    pending = hold.remote(leaked, marker)
    oid_hex = leaked.id().hex()

    def _entry():
        for w in state.memory_summary().get("workers", []):
            for e in w.get("entries", []):
                if e["object_id"] == oid_hex:
                    return e
        return None

    entry = _poll(lambda: (e := _entry()) and e["ref_type"] == "USED_BY_PENDING_TASK" and e)
    assert entry, f"pending-task ref never reported: {_entry()}"
    assert entry["size"] >= 1024 * 8
    assert "test_memory_observability.py" in entry["callsite"]

    with open(marker, "w") as f:
        f.write("go")
    assert ray_tpu.get(pending, timeout=60) == 0

    entry = _poll(lambda: (e := _entry()) and e["ref_type"] == "LOCAL_REFERENCE" and e)
    assert entry, f"leaked ref never settled to LOCAL_REFERENCE: {_entry()}"
    assert entry["age_s"] >= 0.0

    # the CLI view renders the same attribution
    from ray_tpu.cli import main

    assert main(["memory"]) == 0
    out = capsys.readouterr().out
    assert "OBJECT_ID" in out and "REF_TYPE" in out
    assert oid_hex[:28] in out and "LOCAL_REFERENCE" in out
    assert "test_memory_observability.py" in out
    assert main(["memory", "--group-by-callsite"]) == 0
    out = capsys.readouterr().out
    assert "CALLSITE" in out and "test_memory_observability.py" in out


def test_list_objects_enriched_and_warns():
    ref = ray_tpu.put(np.zeros(200_000, dtype=np.float32))  # plasma-sized
    oid_hex = ref.id().hex()

    def _row():
        rows = state.list_objects()
        for r in rows:
            if r["object_id"] == oid_hex and r.get("ref_type"):
                return r
        return None

    row = _poll(_row)
    assert row, "plasma object never enriched with ref info"
    assert row["size"] >= 800_000
    assert row["ref_type"] == "LOCAL_REFERENCE"
    assert "test_memory_observability.py" in row["callsite"]

    # plasma-sized so they land in the raylet's store listing
    extra = [ray_tpu.put(np.zeros(200_000, dtype=np.float32)) for _ in range(3)]
    with pytest.warns(UserWarning, match="truncated"):
        state.list_objects(limit=1)
    del extra, ref


# ----------------------------------------------------------- node accounting


def test_spill_counters_and_memory_gauges():
    """Satellite: a spill round-trip moves the spill/restore counters in
    debug_state AND the ray_tpu_spill_* / object-store gauges; acceptance:
    used/spill/hbm gauges all appear in prometheus_text()."""
    from ray_tpu.core import api
    from ray_tpu.util.metrics import get_metrics, prometheus_text

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2, object_store_memory=8 * 1024 * 1024)
    try:
        arrays = [np.full(1024 * 1024 // 8, i, dtype=np.int64) for i in range(16)]
        refs = [ray_tpu.put(a) for a in arrays]  # 16 MiB = 2x capacity
        raylet = api._node.raylet
        assert raylet._spilled_objects_total > 0
        for i, ref in enumerate(refs):
            np.testing.assert_array_equal(ray_tpu.get(ref), arrays[i])
        assert raylet._restored_objects_total > 0

        snap = raylet._debug_state_snapshot()
        store = snap["store"]
        assert store["spilled_objects_total"] > 0
        assert store["restored_objects_total"] > 0
        assert store["spilled_bytes_total"] > 0
        assert store["pinned_bytes"] > 0
        assert store["used_peak"] >= store["used"]
        assert "hbm" in snap and "worker_rss_bytes" in snap

        def _rows():
            rows = {m["name"]: m for m in get_metrics()}
            want = ("ray_tpu_object_store_used_bytes",
                    "ray_tpu_spill_bytes_total",
                    "ray_tpu_restore_bytes_total",
                    "ray_tpu_hbm_used_bytes",
                    "ray_tpu_worker_rss_bytes")
            if not all(n in rows for n in want):
                return None
            # gauges exist from registration; wait for the heartbeat that
            # carries the non-zero spill totals
            if rows["ray_tpu_spill_bytes_total"]["value"] <= 0:
                return None
            return rows

        rows = _poll(_rows)
        assert rows, "memory gauges never reached GetMetrics"
        assert rows["ray_tpu_spill_bytes_total"]["value"] > 0
        assert rows["ray_tpu_object_store_used_bytes"]["value"] > 0
        text = prometheus_text(list(rows.values()))
        for name in ("ray_tpu_object_store_used_bytes",
                     "ray_tpu_spill_bytes_total", "ray_tpu_hbm_used_bytes"):
            assert name in text
        del refs
    finally:
        ray_tpu.shutdown()


# --------------------------------------------------------------- leak watcher


def test_leak_watcher_fires_error_event():
    """Acceptance: injected monotonic refcount growth in the driver makes
    the GCS leak watcher publish a memory_leak ErrorEvent naming the
    hoarding callsite."""
    from ray_tpu.core.config import get_config

    cfg = get_config()
    saved = (cfg.memory_report_interval_ms, cfg.memory_leak_check_interval_s,
             cfg.memory_leak_intervals, cfg.memory_leak_min_growth_bytes,
             cfg.memory_leak_min_growth_refs)
    cfg.memory_report_interval_ms = 300
    cfg.memory_leak_check_interval_s = 0.5
    cfg.memory_leak_intervals = 3
    cfg.memory_leak_min_growth_bytes = 1 << 40  # trip on refs, not bytes
    cfg.memory_leak_min_growth_refs = 5
    hoard = []
    try:
        def _leaked():
            events = state.list_errors(error_type="memory_leak", limit=50)
            return [e for e in events
                    if "test_memory_observability.py" in e.get("message", "")]

        deadline = time.monotonic() + 45
        events = []
        while time.monotonic() < deadline and not events:
            hoard.append(ray_tpu.put(np.ones(8192, dtype=np.int64)))
            time.sleep(0.1)
            events = _leaked()
        assert events, "leak watcher never fired for the injected growth"
        ev = events[-1]
        assert ev["source"] == "gcs"
        assert "Top holders" in ev["message"]
        suspect = (ev.get("extra") or {}).get("suspect") or {}
        assert suspect.get("growth_refs", 0) > 0
    finally:
        (cfg.memory_report_interval_ms, cfg.memory_leak_check_interval_s,
         cfg.memory_leak_intervals, cfg.memory_leak_min_growth_bytes,
         cfg.memory_leak_min_growth_refs) = saved
        hoard.clear()


# ------------------------------------------------------------------ profiling


def test_profile_capture_and_listing(capsys):
    """cli profile triggers a jax.profiler capture on a worker via RPC;
    the artifact lands on disk and registers under list_profiles()."""
    reply = _poll(
        lambda: (r := state.capture_profile(duration=0.3)).get("path") and r,
        timeout=90.0, interval=1.0)
    assert reply, f"profile capture never succeeded: {state.capture_profile(duration=0.3)}"
    assert os.path.isdir(reply["path"])
    # jax writes plugins/profile/<ts>/*.xplane.pb under the trace dir
    found = []
    for root, _dirs, files in os.walk(reply["path"]):
        found.extend(os.path.join(root, f) for f in files)
    assert found, f"no profiler artifacts under {reply['path']}"

    profiles = _poll(lambda: [p for p in state.list_profiles()
                              if p.get("path") == reply["path"]])
    assert profiles and profiles[-1]["node_id"]

    from ray_tpu.cli import main

    assert main(["profile", "--list"]) == 0
    out = capsys.readouterr().out
    assert "PATH" in out and reply["path"][:48] in out


# ------------------------------------------------------------ tier-1 CI smoke


def test_cli_memory_and_doctor_smoke(capsys):
    """Satellite CI guard: `cli memory` and `cli doctor` both render
    against a live local cluster without error."""
    from ray_tpu.cli import main

    assert ray_tpu.get(ray_tpu.put(1), timeout=30) == 1
    assert _poll(lambda: state.memory_summary().get("num_workers", 0) >= 1)

    assert main(["memory"]) == 0
    out = capsys.readouterr().out
    assert "workers" in out and "OBJECT_ID" in out

    assert main(["doctor"]) == 0
    out = capsys.readouterr().out
    assert "per-node lease queues" in out and "GCS:" in out

    # dashboard endpoints behind /api/memory and /api/profiles
    from ray_tpu.dashboard import _collect

    summary = _collect("memory")
    assert "workers" in summary
    assert isinstance(_collect("profiles"), list)
