"""Pipeline (pp) and expert (ep) parallelism: numerical equivalence on the
virtual CPU mesh (SURVEY §7.2-6; the reference delegates both to vLLM,
``vllm_models.py:117-168``)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import PRESETS, init_params, loss_fn, param_axes
from ray_tpu.models.llama import forward_hidden
from ray_tpu.models.moe import init_moe_params, moe_block
from ray_tpu.parallel import MeshConfig, create_mesh
from ray_tpu.parallel.sharding import shard_params

CFG = dataclasses.replace(
    PRESETS["debug"], attn_impl="reference", dtype=jnp.float32, remat=False,
    pipeline_microbatches=2,
)


def test_pipeline_forward_matches_scan():
    mesh = create_mesh(MeshConfig(pp=2, dp=2, fsdp=2))
    params = init_params(CFG, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, CFG.vocab_size)
    ref = forward_hidden(params, tokens, CFG, mesh=None)
    sharded = shard_params(params, param_axes(CFG), mesh)
    out = jax.jit(lambda p, t: forward_hidden(p, t, CFG, mesh=mesh))(sharded, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_pipeline_grads_match():
    mesh = create_mesh(MeshConfig(pp=4, dp=2))
    cfg = dataclasses.replace(CFG, n_layers=4)  # one layer per stage
    params = init_params(cfg, jax.random.PRNGKey(0))
    batch = {"tokens": jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, cfg.vocab_size)}

    ref_loss, ref_grads = jax.value_and_grad(
        lambda p: loss_fn(p, batch, cfg, mesh=None)
    )(params)
    sharded = shard_params(params, param_axes(cfg), mesh)
    pp_loss, pp_grads = jax.jit(
        jax.value_and_grad(lambda p: loss_fn(p, batch, cfg, mesh=mesh))
    )(sharded)
    np.testing.assert_allclose(float(pp_loss), float(ref_loss), rtol=1e-4)
    flat_ref = jax.tree_util.tree_leaves(ref_grads)
    flat_pp = jax.tree_util.tree_leaves(pp_grads)
    for a, b in zip(flat_pp, flat_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=3e-3, atol=3e-3)


def _moe_reference(x, params, top_k):
    """Per-token loop reference for the dense dispatch path."""
    b, s, e = x.shape
    tokens = np.asarray(x, np.float32).reshape(-1, e)
    router = np.asarray(params["router"], np.float32)
    logits = tokens @ router
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    out = np.zeros_like(tokens)
    for i, row in enumerate(probs):
        idx = np.argsort(-row)[:top_k]
        gates = row[idx] / row[idx].sum()
        for g, xi in zip(gates, idx):
            h = tokens[i] @ np.asarray(params["w_gate"][xi], np.float32)
            u = tokens[i] @ np.asarray(params["w_up"][xi], np.float32)
            act = (h / (1 + np.exp(-h))) * u
            out[i] += g * (act @ np.asarray(params["w_down"][xi], np.float32))
    return out.reshape(b, s, e)


def test_moe_block_matches_reference():
    key = jax.random.PRNGKey(0)
    params = init_moe_params(key, hidden=16, expert_mlp=32, n_experts=4, dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 16), jnp.float32)
    # capacity large enough that nothing is dropped
    out, aux = moe_block(x, params, top_k=2, capacity_factor=4.0)
    assert float(aux) >= 1.0
    ref = _moe_reference(x, params, top_k=2)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-4)


def test_moe_llama_trains_on_ep_mesh():
    """MoE llama preset: jit path with experts sharded over ep."""
    mesh = create_mesh(MeshConfig(ep=2, dp=2, fsdp=2))
    cfg = dataclasses.replace(
        PRESETS["llama-moe-debug"], attn_impl="reference", dtype=jnp.float32, remat=False
    )
    params = init_params(cfg, jax.random.PRNGKey(0))
    params = shard_params(params, param_axes(cfg), mesh)
    batch = {"tokens": jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, cfg.vocab_size)}
    loss, grads = jax.jit(
        jax.value_and_grad(lambda p: loss_fn(p, batch, cfg, mesh=mesh))
    )(params)
    assert jnp.isfinite(loss)
    assert all(jnp.all(jnp.isfinite(g)) for g in jax.tree_util.tree_leaves(grads))


def test_pp_ep_composed():
    """Pipeline over pp with MoE experts sharded over ep inside the
    shard_map — the composed strategy the dryrun exercises."""
    mesh = create_mesh(MeshConfig(pp=2, ep=2, dp=2))
    cfg = dataclasses.replace(
        PRESETS["llama-moe-debug"], attn_impl="reference", dtype=jnp.float32,
        remat=False, pipeline_microbatches=2,
        # no token drops: per-microbatch capacity differs from the global
        # one, so equivalence needs headroom
        moe_capacity_factor=4.0,
        # the pipelined path does not thread the aux loss yet; zero it for
        # exact equivalence with the scan path
        moe_aux_weight=0.0,
    )
    params = init_params(cfg, jax.random.PRNGKey(0))
    batch = {"tokens": jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab_size)}
    ref_loss = loss_fn(params, batch, cfg, mesh=None)
    sharded = shard_params(params, param_axes(cfg), mesh)
    loss = jax.jit(lambda p: loss_fn(p, batch, cfg, mesh=mesh))(sharded)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-4)


def test_moe_aux_loss_applied_in_loss():
    """The load-balancing aux term must reach the training loss."""
    cfg = dataclasses.replace(
        PRESETS["llama-moe-debug"], attn_impl="reference", dtype=jnp.float32, remat=False
    )
    params = init_params(cfg, jax.random.PRNGKey(0))
    batch = {"tokens": jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)}
    base = float(loss_fn(params, batch, dataclasses.replace(cfg, moe_aux_weight=0.0)))
    weighted = float(loss_fn(params, batch, dataclasses.replace(cfg, moe_aux_weight=0.1)))
    assert weighted > base  # aux >= 1 by Cauchy-Schwarz, so weight must raise loss
