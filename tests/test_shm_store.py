import os

import pytest

from ray_tpu.native.store import ObjectExistsError, ShmClient, ShmStore, StoreFullError


@pytest.fixture
def store(tmp_path):
    path = "/dev/shm/raytpu_test_" + os.urandom(4).hex()
    s = ShmStore(path, 1 << 20)
    yield s
    s.close()


def oid(i: int) -> bytes:
    return i.to_bytes(28, "little")


def test_create_seal_get(store):
    off = store.create(oid(1), 128, 8)
    store.write(off, b"d" * 128)
    store.write(off + 128, b"m" * 8)
    store.seal(oid(1))
    store.release(oid(1))
    info = store.get_info(oid(1))
    assert info is not None
    offset, dsz, msz = info
    assert (dsz, msz) == (128, 8)
    assert bytes(store.read(offset, 128)) == b"d" * 128


def test_unsealed_not_gettable(store):
    store.create(oid(2), 64, 0)
    assert store.get_info(oid(2)) is None
    assert store.contains(oid(2)) == 1


def test_duplicate_create(store):
    store.create(oid(3), 64, 0)
    with pytest.raises(ObjectExistsError):
        store.create(oid(3), 64, 0)


def test_lru_eviction(store):
    # Fill beyond capacity; sealed refcount-0 objects must be evicted.
    for i in range(40):
        store.put_sealed(oid(100 + i), b"z" * (40 * 1024))
    assert store.used() <= 1 << 20
    assert store.num_objects() < 40
    # Most recent object survives.
    assert store.contains(oid(139)) == 2


def test_pinned_objects_not_evicted(store):
    store.put_sealed(oid(4), b"a" * (200 * 1024))
    store.add_ref(oid(4))  # pin
    for i in range(40):
        store.put_sealed(oid(200 + i), b"z" * (40 * 1024))
    assert store.contains(oid(4)) == 2


def test_store_full_when_all_pinned(store):
    store.create(oid(5), 900 * 1024, 0)  # unsealed = pinned by creator
    with pytest.raises(StoreFullError):
        store.create(oid(6), 900 * 1024, 0)


def test_delete_and_reuse(store):
    off1 = store.create(oid(7), 1024, 0)
    store.seal(oid(7))
    store.release(oid(7))
    assert store.delete(oid(7))
    assert store.contains(oid(7)) == 0
    off2 = store.create(oid(8), 1024, 0)
    assert off2 == off1  # space reused (best-fit allocator)


def test_cross_process_view(store):
    data = os.urandom(4096)
    store.put_sealed(oid(9), data)
    client = ShmClient(store.path, store.capacity)
    offset, dsz, _ = store.get_info(oid(9))
    assert bytes(client.read(offset, dsz)) == data
    client.close()
