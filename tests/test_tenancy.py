"""Multi-tenant LoRA multiplexing (round 16): one routing key across
header spellings, HBM adapter LRU with pinned-in-flight safety,
mixed-adapter decode in ONE dispatch, weighted-fair queueing, and
per-tenant quota/shed enforcement through the real proxy.

The regime under test: many tenants (adapters) share one replica fleet.
A noisy tenant's storm must shed ITS OWN work (fair-share preemption,
quota 429s with honest Retry-After) while a quiet tenant keeps its SLO;
a decode batch mixing distinct adapters must cost exactly the dispatches
of a single-adapter batch.
"""

import dataclasses
import json
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.core.config import get_config
from ray_tpu.llm.engine import InferenceEngine, Request
from ray_tpu.llm.tenancy import (AdapterCapacityError, AdapterPool,
                                 QuotaExceeded, TenancyConfig, TenantLedger,
                                 TokenBucket, WeightedFairQueue, tenant_of)
from ray_tpu.models.llama import PRESETS, init_params
from ray_tpu.serve.multiplex import resolve_model_id
from ray_tpu.serve.router import RequestShed


@pytest.fixture(scope="module")
def small_model():
    cfg = dataclasses.replace(PRESETS["debug"], dtype=jnp.float32,
                              attn_impl="reference")
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _make_adapter(cfg, rng, scale=0.5):
    """Random rank-2 adapter arrays for every attention projection."""
    L, E, H, KH, D = (cfg.n_layers, cfg.hidden, cfg.n_heads,
                      cfg.n_kv_heads, cfg.head_dim)
    r = 2
    dims = {"wq": (E, H * D), "wk": (E, KH * D), "wv": (E, KH * D),
            "wo": (H * D, E)}
    out = {}
    for p, (ein, eout) in dims.items():
        out[f"{p}.A"] = (rng.standard_normal((L, ein, r)) * scale / ein ** 0.5
                         ).astype(np.float32)
        out[f"{p}.B"] = (rng.standard_normal((L, r, eout)) * scale
                         ).astype(np.float32)
    return out


# ------------------------------------------------------------ adapter pool
def test_adapter_pool_evicts_lru_under_pressure():
    """The residency cap (max_loaded_adapters) triggers LRU eviction of
    the oldest UNPINNED adapter; stack capacity above the cap stays
    unused headroom."""
    pool = AdapterPool(capacity=4, max_resident=2)
    for aid in ("a", "b"):
        slot = pool.begin_load(aid)
        pool.commit_load(aid, 1.0)
        pool.unpin(aid)
        assert 1 <= slot <= 4
    assert list(pool.resident()) == ["a", "b"]
    # touching "a" refreshes its LRU position: "b" is now the victim
    assert pool.lookup("a") is not None
    pool.unpin("a")
    pool.begin_load("c")
    pool.commit_load("c", 1.0)
    pool.unpin("c")
    st = pool.stats()
    assert list(pool.resident()) == ["a", "c"]
    assert st["evictions"] == 1 and st["resident_count"] == 2
    assert st["max_resident"] == 2 and st["capacity"] == 4


def test_adapter_pool_pins_protect_inflight_adapters():
    """An adapter pinned by an in-flight request is never evicted: with
    every resident slot pinned, a cold load raises AdapterCapacityError
    (the engine turns that into admission deferral, not a failure)."""
    pool = AdapterPool(capacity=2)
    pool.begin_load("a")          # pinned by the load itself
    pool.begin_load("b")
    with pytest.raises(AdapterCapacityError):
        pool.begin_load("c")
    # a finishing request unpins -> the load proceeds by evicting "a"
    pool.unpin("a")
    slot_c = pool.begin_load("c")
    pool.commit_load("c", 1.0)
    assert "a" not in pool.resident() and slot_c >= 1
    assert pool.stats()["evictions"] == 1


def test_adapter_pool_reload_after_evict():
    """An evicted adapter re-loads into a fresh slot on next use (the
    hot-load path), and the loads counter records it."""
    pool = AdapterPool(capacity=1)
    pool.begin_load("a")
    pool.commit_load("a", 2.0)
    pool.unpin("a")
    pool.begin_load("b")          # evicts a
    pool.commit_load("b", 2.0)
    pool.unpin("b")
    assert pool.lookup("a") is None     # miss: caller must begin_load
    pool.begin_load("a")
    pool.commit_load("a", 2.0)
    st = pool.stats()
    assert list(pool.resident()) == ["a"]
    assert st["loads"] == 3 and st["evictions"] == 2


# ------------------------------------------------------------- quotas / wfq
def test_token_bucket_honest_retry_after():
    """A refused acquire reports WHEN the bucket will actually cover the
    request at the sustained rate — not a constant."""
    bucket = TokenBucket(rate=10.0, burst=50.0)
    ok, _ = bucket.try_acquire(50)
    assert ok
    ok, retry = bucket.try_acquire(30)
    assert not ok
    # deficit = 30 tokens at 10 tok/s -> ~3s (refill during the test can
    # shave a second off)
    assert 2 <= retry <= 3
    ok, retry = bucket.try_acquire(10)
    assert not ok and retry == 1


def test_ledger_quota_exceeded_carries_http_fields():
    cfg = TenancyConfig.from_dict(
        {"tenants": {"t": {"tokens_per_s": 5.0, "burst_tokens": 10.0}}})
    ledger = TenantLedger(cfg)
    ledger.admit("t", 10)
    with pytest.raises(QuotaExceeded) as ei:
        ledger.admit("t", 10)
    assert ei.value.http_status.startswith("429")
    assert ei.value.reason == "quota_exhausted"
    assert 1 <= ei.value.retry_after <= 60
    row = ledger.snapshot()["t"]
    assert row["admitted"] == 1 and row["quota_rejects"] == 1
    assert row["tokens_in"] == 10 and "quota_remaining" in row
    # unmetered tenants never raise
    ledger.admit("free", 10 ** 6)


def test_wfq_two_to_one_weights_admit_two_to_one():
    """ISSUE 16 satellite: under saturation (both tenants always have a
    waiter queued), a 2:1 weight split admits work in a 2:1 ratio."""
    wfq = WeightedFairQueue({"gold": 2.0, "bronze": 1.0})
    tickets = {"gold": [], "bronze": []}
    admitted = {"gold": 0, "bronze": 0}
    for t in ("gold", "bronze"):
        for _ in range(3):                       # standing backlog
            tickets[t].append(wfq.enqueue(t))
    for _ in range(300):
        head = next(tk for t in tickets for tk in tickets[t]
                    if wfq.is_head(tk))
        tenant = "gold" if head in tickets["gold"] else "bronze"
        wfq.complete(head)
        tickets[tenant].remove(head)
        admitted[tenant] += 1
        tickets[tenant].append(wfq.enqueue(tenant))   # stay saturated
    ratio = admitted["gold"] / admitted["bronze"]
    assert 1.8 <= ratio <= 2.2, admitted


def test_wfq_cancel_rolls_back_and_idle_share_flows():
    wfq = WeightedFairQueue({"a": 1.0, "b": 1.0})
    t1 = wfq.enqueue("a")
    t2 = wfq.enqueue("a")
    wfq.cancel(t2)        # shed: must not penalize a's next arrival
    t3 = wfq.enqueue("b")
    assert wfq.is_head(t1)
    wfq.complete(t1)
    assert wfq.is_head(t3)
    wfq.complete(t3)
    assert len(wfq) == 0
    # an idle tenant doesn't bank credit: after b worked alone, a's next
    # stamp starts at the current virtual clock, not at zero
    for _ in range(5):
        wfq.complete(wfq.enqueue("b"))
    ta = wfq.enqueue("a")
    tb = wfq.enqueue("b")
    assert wfq.is_head(ta) and not wfq.is_head(tb)
    wfq.complete(ta)
    wfq.complete(tb)


# ------------------------------------------------------------- routing key
def test_resolve_model_id_unifies_spellings():
    """Satellite: serve_multiplexed_model_id, x-raytpu-model, and the
    OpenAI body `model` field resolve to ONE routing key, in that
    precedence, case-insensitively."""
    assert resolve_model_id({"serve_multiplexed_model_id": "m1",
                             "x-raytpu-model": "m2"}, {"model": "m3"}) == "m1"
    assert resolve_model_id({"X-RayTPU-Model": "m2"}, {"model": "m3"}) == "m2"
    assert resolve_model_id({}, {"model": "m3"}) == "m3"
    assert resolve_model_id({}, {}) == ""
    assert resolve_model_id(None) == ""
    assert tenant_of("") == "default" and tenant_of("m1") == "m1"


# ------------------------------------------------------- engine mixed decode
def test_mixed_adapter_batch_one_dispatch_and_parity(small_model, tmp_path):
    """Tentpole (c): a decode batch mixing DISTINCT adapters produces
    byte-identical greedy tokens to serving the same requests
    sequentially, and consumes EXACTLY as many decode dispatches as a
    single-adapter batch of the same shape — decode cost must not scale
    with the number of distinct adapters."""
    from ray_tpu.llm.lora import LoRAServingConfig, save_adapter

    cfg, params = small_model
    rng = np.random.default_rng(16)
    for name in ("t1", "t2", "t3"):
        save_adapter(str(tmp_path / f"{name}.npz"), _make_adapter(cfg, rng))
    lora = LoRAServingConfig(max_loras=4, max_rank=4,
                             dynamic_lora_loading_path=str(tmp_path))
    prompts = [[3, 1, 4, 1, 5, 9, 2, 6], [2, 7, 1, 8, 2, 8],
               [1, 6, 1, 8, 0, 3], [5, 5, 5, 9, 7]]

    def run(models, concurrent):
        eng = InferenceEngine(cfg, params, max_slots=4, max_len=64,
                              lora_config=lora, enable_prefix_cache=False)
        assert eng.mixed_dispatch_enabled, \
            "a LoRA stack must no longer disable mixed dispatch"
        reqs = [Request(f"r{i}", p, max_new_tokens=6, model=m)
                for i, (p, m) in enumerate(zip(prompts, models))]
        d0 = eng.metrics["decode_dispatches"]
        for r in reqs:
            eng.add_request(r)
            if not concurrent:
                while not r.done:
                    eng.step()
        while any(not r.done for r in reqs):
            eng.step()
        return ([list(r.generated) for r in reqs],
                eng.metrics["decode_dispatches"] - d0)

    mix = [None, "t1", "t2", "t3"]
    batch_toks, _ = run(mix, concurrent=True)
    seq_toks, _ = run(mix, concurrent=False)
    assert batch_toks == seq_toks
    # dispatch-count flatness: same shapes, 3 distinct adapters vs 1
    _, mixed_d = run(["t1", "t2", "t3", "t1"], concurrent=True)
    _, single_d = run(["t1", "t1", "t1", "t1"], concurrent=True)
    assert mixed_d == single_d, (mixed_d, single_d)


def test_engine_defers_admission_when_adapters_pinned(small_model, tmp_path):
    """When every resident adapter slot is pinned by in-flight requests,
    a cold-adapter request DEFERS (head-of-line wait, adapter_defers
    metric) and completes once a slot unpins — never a client error."""
    from ray_tpu.llm.lora import LoRAServingConfig, save_adapter

    cfg, params = small_model
    rng = np.random.default_rng(3)
    for name in ("ad1", "ad2"):
        save_adapter(str(tmp_path / f"{name}.npz"), _make_adapter(cfg, rng))
    lora = LoRAServingConfig(max_loras=2, max_rank=4,
                             max_loaded_adapters=1,
                             dynamic_lora_loading_path=str(tmp_path))
    eng = InferenceEngine(cfg, params, max_slots=4, max_len=64,
                          lora_config=lora, enable_prefix_cache=False)
    r1 = Request("r1", [3, 1, 4, 1, 5], max_new_tokens=8, model="ad1")
    r2 = Request("r2", [2, 7, 1, 8], max_new_tokens=4, model="ad2")
    eng.add_request(r1)
    eng.step()                    # r1 admitted, ad1 pinned in the 1 slot
    eng.add_request(r2)
    deadline = time.monotonic() + 60
    while not (r1.done and r2.done):
        assert time.monotonic() < deadline
        eng.step()
    assert eng.metrics["adapter_defers"] >= 1
    assert len(r1.generated) == 8 and len(r2.generated) == 4
    assert list(eng.lora_manager.resident()) == ["ad2"]


# ------------------------------------------------------------- router units
def _bare_router(replicas: dict[str, int]):
    """Router skeleton for tenancy-policy unit tests (same shape as
    test_overload's): real assign/release/shed logic, no controller."""
    from collections import OrderedDict

    from ray_tpu.serve.router import Router

    r = Router.__new__(Router)
    r._key = "replicas::app::dep"
    r._lock = threading.Lock()
    r._cond = threading.Condition(r._lock)
    r._replicas = {rid: {"actor": f"actor-{rid}", "max_ongoing": cap}
                   for rid, cap in replicas.items()}
    r._inflight = {rid: 0 for rid in replicas}
    r._model_affinity = {}
    r._group_affinity = OrderedDict()
    r.affinity_stats = {"hits": 0, "misses": 0, "spills": 0,
                        "new_groups": 0}
    r.spill_migrations = 0
    r._init_overload_state()
    return r


@pytest.fixture()
def overload_cfg():
    cfg = get_config()
    saved = (cfg.serve_max_queued_requests, cfg.serve_shed_policy)
    yield cfg
    cfg.serve_max_queued_requests, cfg.serve_shed_policy = saved


def test_router_quiet_tenant_jumps_noisy_backlog(overload_cfg):
    """WFQ at the router: a quiet tenant's first waiter lands near the
    HEAD of a noisy tenant's standing backlog (virtual start = current
    vclock), instead of behind it in arrival order."""
    overload_cfg.serve_max_queued_requests = 16
    router = _bare_router({"r1": 1})
    router.assign_replica()                      # saturate the only slot
    router._update_tenancy({"weights": {"quiet": 1.0, "noisy": 1.0}})
    admitted: list[str] = []
    alock = threading.Lock()

    def wait_one(tenant):
        try:
            router.assign_replica(timeout=30.0, model_id=tenant)
            with alock:
                admitted.append(tenant)
        except Exception:
            with alock:
                admitted.append(f"{tenant}-failed")

    threads = []
    for i in range(4):                           # noisy backlog first
        t = threading.Thread(target=wait_one, args=("noisy",), daemon=True)
        t.start()
        threads.append(t)
    deadline = time.monotonic() + 5
    while router.overload_snapshot()["queued"] < 4:
        assert time.monotonic() < deadline
        time.sleep(0.01)
    t = threading.Thread(target=wait_one, args=("quiet",), daemon=True)
    t.start()
    threads.append(t)
    deadline = time.monotonic() + 5
    while router.overload_snapshot()["queued"] < 5:
        assert time.monotonic() < deadline
        time.sleep(0.01)
    for _ in range(5):                           # serve them one by one
        router.release("r1")
        n = len(admitted)
        deadline = time.monotonic() + 10
        while len(admitted) == n:
            assert time.monotonic() < deadline
            time.sleep(0.005)
    for t in threads:
        t.join(timeout=10)
    # the quiet waiter arrived LAST but is admitted within the first two
    # slots (its virtual finish time ties the noisy head's, ticket order
    # breaks the tie) — strict FIFO would admit it fifth.
    assert "quiet" in admitted[:2], admitted
    assert all(not a.endswith("failed") for a in admitted)


def test_router_fair_share_shed_prefers_noisy_waiter(overload_cfg):
    """Tenant-aware shedding: a full queue held by one tenant gives a
    slot to an under-share tenant by preempting the NOISY tenant's
    newest waiter — and a single-tenant flood still sheds the incoming
    request (queue_full), exactly the pre-tenancy behavior."""
    overload_cfg.serve_max_queued_requests = 2
    overload_cfg.serve_shed_policy = "cost"
    router = _bare_router({"r1": 1})
    router.assign_replica()
    outcomes: dict[str, list] = {"noisy": [], "quiet": []}
    olock = threading.Lock()

    def wait_one(tenant):
        try:
            r = router.assign_replica(timeout=20.0, model_id=tenant)
            with olock:
                outcomes[tenant].append(r)
        except Exception as e:
            with olock:
                outcomes[tenant].append(e)

    threads = [threading.Thread(target=wait_one, args=("noisy",),
                                daemon=True) for _ in range(2)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 5
    while router.overload_snapshot()["queued"] < 2:
        assert time.monotonic() < deadline
        time.sleep(0.01)
    # same-tenant overflow: incoming noisy request is shed, waiters stay
    with pytest.raises(RequestShed) as ei:
        router.assign_replica(timeout=10.0, model_id="noisy")
    assert ei.value.reason == "queue_full"
    # under-share quiet tenant: preempts the newest noisy waiter instead
    tq = threading.Thread(target=wait_one, args=("quiet",), daemon=True)
    tq.start()
    threads.append(tq)
    deadline = time.monotonic() + 10
    while not any(isinstance(o, RequestShed) for o in outcomes["noisy"]):
        assert time.monotonic() < deadline
        time.sleep(0.01)
    shed = next(o for o in outcomes["noisy"] if isinstance(o, RequestShed))
    assert shed.reason == "preempted"
    snap = router.overload_snapshot()
    assert snap["shed_by_tenant"].get("noisy") == 2
    assert "quiet" not in snap["shed_by_tenant"]
    # drain one slot at a time: quiet + the surviving noisy waiter both
    # get served
    for _ in range(2):
        served = sum(1 for outs in outcomes.values() for o in outs
                     if not isinstance(o, Exception))
        router.release("r1")
        deadline = time.monotonic() + 10
        while sum(1 for outs in outcomes.values() for o in outs
                  if not isinstance(o, Exception)) == served:
            assert time.monotonic() < deadline
            time.sleep(0.005)
    for t in threads:
        t.join(timeout=15)
    assert len(outcomes["quiet"]) == 1 \
        and not isinstance(outcomes["quiet"][0], Exception)


# ------------------------------------------------------------------- e2e http
@pytest.fixture()
def serve_instance(ray_cluster):
    yield
    serve.shutdown()


def _post(addr, path, body: dict, headers: dict | None = None,
          timeout: float = 60.0):
    """Returns (status_code_or_error_name, raw_body, headers)."""
    req = urllib.request.Request(
        addr + path, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            raw = r.read()
            return r.status, raw, dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)
    except Exception as e:
        return type(e).__name__, b"", {}


def test_multiplex_header_unification_e2e(serve_instance):
    """Satellite: all three routing-key spellings reach the replica as
    the SAME multiplexed model id through the real proxy."""

    @serve.deployment(num_replicas=1)
    class Echo:
        def __call__(self, request):
            from ray_tpu.serve.multiplex import get_multiplexed_model_id

            return {"model_id": get_multiplexed_model_id()}

    serve.run(Echo.bind(), name="mux", route_prefix="/mux")
    addr = serve.http_address()
    for headers, body in (
        ({"serve_multiplexed_model_id": "m1"}, {}),
        ({"x-raytpu-model": "m1"}, {}),
        ({"X-RayTPU-Model": "m1"}, {}),
        ({}, {"model": "m1"}),
    ):
        status, raw, _h = _post(addr, "/mux", body, headers=headers)
        assert status == 200, (headers, body, status)
        assert json.loads(raw)["model_id"] == "m1", (headers, body)
    serve.delete("mux")


def test_quota_429_and_tenant_rows_e2e(serve_instance):
    """Tentpole (d) e2e: a quota-exhausted tenant gets an honest 429 +
    Retry-After through the real proxy (SSE error envelope), the quiet
    tenant rides on untouched, and the per-tenant rows reach
    serve.status() via the controller probe path."""
    from ray_tpu.llm import build_llm_app

    app = build_llm_app(
        "debug-128", max_slots=4, max_len=128, page_size=16,
        prefill_chunk_size=64, num_replicas=1, max_ongoing_requests=8,
        tenancy_config={"tenants": {
            "metered": {"tokens_per_s": 1.0, "burst_tokens": 40.0},
            "free": {"weight": 2.0},
        }})
    serve.run(app, name="quota", route_prefix="/quota", timeout_s=240.0)
    addr = serve.http_address()
    body = {"prompt": "hello quota world", "max_tokens": 4}
    status, raw, _h = _post(addr, "/quota/v1/completions", body,
                            headers={"x-raytpu-model": "metered"},
                            timeout=180.0)
    assert status == 200, raw[:200]
    # burst exhausted (cost ≈ 17 prompt + 4 gen ≈ 21 of the 40-token
    # burst): the second/third request cannot be covered
    saw_429 = None
    for _ in range(3):
        status, raw, h = _post(addr, "/quota/v1/completions", body,
                               headers={"x-raytpu-model": "metered"},
                               timeout=60.0)
        if status == 429:
            saw_429 = h
            break
    assert saw_429 is not None, "quota never produced a 429"
    retry = int(saw_429.get("Retry-After", "0"))
    # honest: ~20-token deficit at 1 tok/s, never the constant 1
    assert 2 <= retry <= 60, retry
    # the quiet tenant is untouched by the metered tenant's quota
    status, _raw, _h = _post(addr, "/quota/v1/completions", body,
                             headers={"x-raytpu-model": "free"},
                             timeout=120.0)
    assert status == 200
    # per-tenant rows reach serve.status() through the probe fold
    deadline = time.monotonic() + 45
    tenants = {}
    while time.monotonic() < deadline:
        st = serve.status().get("quota", {})
        slot = next(iter(st.values()), {})
        tenants = (slot.get("tenancy") or {}).get("tenants") or {}
        if "metered" in tenants and "free" in tenants:
            break
        time.sleep(1.0)
    assert tenants.get("metered", {}).get("quota_rejects", 0) >= 1
    assert tenants["metered"]["admitted"] >= 1
    assert "quota_remaining" in tenants["metered"]
    assert tenants["free"]["admitted"] >= 1 \
        and "quota_remaining" not in tenants["free"]
    serve.delete("quota")


def test_tenant_aware_shed_quiet_tenant_clean_e2e(serve_instance):
    """Satellite: through the real proxy, a noisy tenant's flood over
    the router queue bound sheds NOISY waiters; the quiet tenant's
    requests all return 200 (quiet 503 rate ~ 0). The bound lives in
    the PROXY process, so it is tuned through its live-config seam."""

    @serve.deployment(num_replicas=1, max_ongoing_requests=1)
    class Slow:
        def __call__(self, request):
            time.sleep(0.25)
            return {"ok": True}

    saved = None
    proxy = None
    try:
        serve.run(Slow.bind(), name="shed", route_prefix="/shed")
        addr = serve.http_address()
        proxy = ray_tpu.get_actor("SERVE_PROXY")
        saved = ray_tpu.get(proxy.apply_config.remote(
            {"serve_max_queued_requests": 2}), timeout=30)
        results = {"noisy": [], "quiet": []}
        rlock = threading.Lock()

        def client(tenant, n):
            for _ in range(n):
                status, _raw, _h = _post(
                    addr, "/shed", {}, headers={"x-raytpu-model": tenant},
                    timeout=60.0)
                with rlock:
                    results[tenant].append(status)

        noisy = [threading.Thread(target=client, args=("noisy", 4),
                                  daemon=True) for _ in range(4)]
        for t in noisy:
            t.start()
        time.sleep(0.3)                  # let the flood fill the queue
        quiet = threading.Thread(target=client, args=("quiet", 3),
                                 daemon=True)
        quiet.start()
        quiet.join(timeout=90)
        for t in noisy:
            t.join(timeout=90)
        assert results["quiet"] == [200, 200, 200], results["quiet"]
        assert any(s == 503 for s in results["noisy"]), results["noisy"]
    finally:
        if proxy is not None and saved:
            ray_tpu.get(proxy.apply_config.remote(saved), timeout=30)
        serve.delete("shed")


def test_wfq_token_cost_equalizes_skewed_request_sizes():
    """ISSUE 18 satellite: WFQ charges ESTIMATED TOKENS, not 1.0 per
    request. With equal weights, a tenant sending 100x-larger requests
    admits ~100x fewer of them — the admitted TOKEN throughput is what
    equalizes. (Under the old cost=1.0 charging, request counts
    equalized and the big tenant took ~100x the token share.)"""
    wfq = WeightedFairQueue({"big": 1.0, "small": 1.0})
    cost = {"big": 400.0, "small": 4.0}
    tickets = {"big": [], "small": []}
    admitted_tok = {"big": 0.0, "small": 0.0}
    admitted_req = {"big": 0, "small": 0}
    for t in ("big", "small"):
        for _ in range(3):                       # standing backlog
            tickets[t].append(wfq.enqueue(t, cost=cost[t]))
    for _ in range(606):
        head = next(tk for t in tickets for tk in tickets[t]
                    if wfq.is_head(tk))
        tenant = "big" if head in tickets["big"] else "small"
        wfq.complete(head)
        tickets[tenant].remove(head)
        admitted_req[tenant] += 1
        admitted_tok[tenant] += cost[tenant]
        tickets[tenant].append(wfq.enqueue(tenant, cost=cost[tenant]))
    tok_ratio = admitted_tok["big"] / admitted_tok["small"]
    assert 0.8 <= tok_ratio <= 1.25, admitted_tok
    req_ratio = admitted_req["small"] / admitted_req["big"]
    assert 80 <= req_ratio <= 125, admitted_req


def test_ledger_cost_correction_ewma_and_clamp():
    """Retire-time correction: tenants that systematically stop far
    short of max_tokens get their estimates scaled DOWN (EWMA of
    actual/estimated, clamped to [0.01, 100])."""
    ledger = TenantLedger(TenancyConfig.from_dict(
        {"tenants": {"early-stopper": {}}}))
    ledger.note_actual("early-stopper", estimated=1000.0, actual=100.0)
    row = ledger.snapshot()["early-stopper"]
    assert row["cost_correction"] == 0.1       # first sample sets it
    for _ in range(40):
        ledger.note_actual("early-stopper", estimated=1000.0, actual=100.0)
    row = ledger.snapshot()["early-stopper"]
    assert abs(row["cost_correction"] - 0.1) < 0.01   # EWMA converges
    ledger.note_actual("early-stopper", estimated=1.0, actual=10_000.0)
    st = ledger._tenants["early-stopper"]
    assert st.cost_ratio <= 100.0              # clamp survives outliers
    ledger.note_actual("early-stopper", estimated=0.0, actual=5.0)  # no-op


def test_ledger_slo_burn_tracks_breaches_and_recovers():
    """ttft_slo_ms: note_ttft returns True on breach, the burn fraction
    is windowed (recovers as healthy samples roll the window), and the
    snapshot row carries slo fields only for tenants WITH an SLO."""
    ledger = TenantLedger(TenancyConfig.from_dict(
        {"tenants": {"slo": {"ttft_slo_ms": 100.0}, "free": {}}}))
    assert ledger.note_ttft("slo", 250.0) is True
    assert ledger.note_ttft("slo", 50.0) is False
    assert ledger.note_ttft("free", 10_000.0) is False  # no SLO, no breach
    assert ledger.slo_burn_frac("slo") == 0.5
    for _ in range(6):
        ledger.note_ttft("slo", 50.0)
    assert ledger.slo_burn_frac("slo") == 1 / 8
    rows = ledger.snapshot()
    assert rows["slo"]["ttft_slo_ms"] == 100.0
    assert rows["slo"]["slo_breaches"] == 1
    assert rows["slo"]["slo_burn_frac"] == round(1 / 8, 4)
    assert "slo_burn_frac" not in rows["free"]
    assert ledger.slo_burn_frac("free") == 0.0


# ----------------------- round 19: HBM-slot accounting + live reweight
def test_adapter_pool_explicit_evict_accounting():
    """Satellite: an explicit eviction returns the slot to the FREE list
    (not merely the recyclable pool), fires the device-release hook, and
    counts as a device_unload — while pinned adapters stay untouchable."""
    pool = AdapterPool(capacity=4)
    fired = []
    pool.on_evict = lambda aid, slot: fired.append((aid, slot))
    for aid in ("a", "b"):
        pool.begin_load(aid)
        pool.commit_load(aid, 1.0)
        pool.unpin(aid)
    st0 = pool.stats()
    assert st0["free_slots"] == 2 and st0["device_unloads"] == 0
    slot = pool.evict("a")
    assert slot is not None and fired == [("a", slot)]
    st = pool.stats()
    assert st["free_slots"] == 3 and st["device_unloads"] == 1
    assert list(pool.resident()) == ["b"]
    assert pool.evict("missing") is None
    pool.begin_load("c")                 # pinned by the in-flight load
    assert pool.evict("c") is None
    assert pool.stats()["device_unloads"] == 1


def test_adapter_pool_evict_idle_skips_pinned():
    """evict_idle (the scale-to-zero HBM reclaim) releases every
    UNPINNED adapter and leaves in-flight ones resident."""
    pool = AdapterPool(capacity=4)
    for aid in ("a", "b", "c"):
        pool.begin_load(aid)
        pool.commit_load(aid, 1.0)
    pool.unpin("a")
    pool.unpin("b")                      # "c" stays pinned
    released = pool.evict_idle()
    assert sorted(aid for aid, _ in released) == ["a", "b"]
    st = pool.stats()
    assert st["free_slots"] == 3 and st["device_unloads"] == 2
    assert list(pool.resident()) == ["c"]


def test_lora_manager_unload_idle_zeroes_device_slot(small_model, tmp_path):
    """Satellite: unloading an idle adapter actually zeroes its device
    stack slot (HBM holds the identity adapter again, not stale weights)
    and the slot accounting shows the release; the adapter hot-reloads
    cleanly on next use."""
    from ray_tpu.llm.lora import LoRAServingConfig, save_adapter

    cfg, params = small_model
    rng = np.random.default_rng(7)
    save_adapter(str(tmp_path / "ad1.npz"), _make_adapter(cfg, rng))
    lora = LoRAServingConfig(max_loras=2, max_rank=4,
                             dynamic_lora_loading_path=str(tmp_path))
    eng = InferenceEngine(cfg, params, max_slots=2, max_len=64,
                          lora_config=lora, enable_prefix_cache=False)
    r = Request("r1", [3, 1, 4, 1, 5], max_new_tokens=4, model="ad1")
    eng.add_request(r)
    while not r.done:
        eng.step()
    (aid, slot), = eng.lora_manager.resident().items()
    assert aid == "ad1"
    stack = eng.executor.lora_stack
    assert any(np.asarray(stack[k][:, slot]).any() for k in stack), \
        "adapter install left the stack slot empty"
    assert eng.lora_manager.unload_idle() == 1
    stack = eng.executor.lora_stack
    for k in stack:
        assert not np.asarray(stack[k][:, slot]).any(), \
            f"{k} slot {slot} still holds weights after unload"
    st = eng.lora_manager.stats()
    assert st["device_unloads"] == 1 and st["resident_count"] == 0
    assert st["free_slots"] == 2
    r2 = Request("r2", [3, 1, 4, 1, 5], max_new_tokens=4, model="ad1")
    eng.add_request(r2)
    while not r2.done:
        eng.step()
    assert list(eng.lora_manager.resident()) == ["ad1"]
    assert list(r2.generated) == list(r.generated)


def test_live_wfq_reweight_midrun_e2e(serve_instance):
    """Satellite: serve.update_tenancy_config flips tenant WFQ weights
    MID-RUN — the controller re-publishes the ``tenancy::`` long-poll
    key, a live router picks the new shares up without a redeploy, and
    the same replica keeps serving."""
    from ray_tpu.llm import build_llm_app
    from ray_tpu.serve.router import Router

    app = build_llm_app(
        "debug-128", max_slots=2, max_len=64, page_size=8,
        prefill_chunk_size=32, num_replicas=1, max_ongoing_requests=4,
        tenancy_config={"tenants": {"gold": {"weight": 3.0},
                                    "free": {"weight": 1.0}}})
    serve.run(app, name="wfq", route_prefix="/wfq", timeout_s=240.0)
    addr = serve.http_address()
    body = {"prompt": "hello weights", "max_tokens": 4}
    status, raw, _h = _post(addr, "/wfq/v1/completions", body, timeout=180.0)
    assert status == 200, raw[:200]

    router = Router("wfq", "LLMDeployment")  # live, like the proxy's
    try:
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline and not router._tenant_weights:
            time.sleep(0.2)
        assert router._tenant_weights == {"gold": 3.0, "free": 1.0}

        out = serve.update_tenancy_config(
            {"tenants": {"gold": {"weight": 8.0}, "free": {"weight": 1.0}}},
            app_name="wfq")
        assert out["updated"] == ["LLMDeployment"]
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline \
                and router._tenant_weights.get("gold") != 8.0:
            time.sleep(0.2)
        assert router._tenant_weights == {"gold": 8.0, "free": 1.0}
        # No redeploy: the same single replica answers after the flip.
        status, raw, _h = _post(addr, "/wfq/v1/completions", body,
                                timeout=60.0)
        assert status == 200, raw[:200]
        st = next(iter(serve.status().get("wfq", {}).values()), {})
        assert st.get("running_replicas") == 1
    finally:
        router._long_poll.stop()
    serve.delete("wfq")
