"""Multi-node fault-tolerance tests on the Cluster harness.

Mirrors the reference's ``python/ray/tests/test_multi_node*.py`` /
``test_failure*.py`` strategy (SURVEY.md §4.1): many raylets + one GCS on
one host, real worker subprocesses, abrupt node kills.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture()
def cluster():
    """Driver on a 0-CPU node → every task must spill to a peer node."""
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()  # replace the shared single-node cluster
    c = Cluster(
        initialize_head=True,
        head_node_args={"num_cpus": 2},
        _system_config={"health_check_failure_threshold": 3},
    )
    ray_tpu.init(address=c.address, num_cpus=0)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


@ray_tpu.remote
def node_of_task():
    return ray_tpu.get_runtime_context().node_id


def test_spillback_to_remote_node(cluster):
    """Driver node has 0 CPUs: the lease must spill to the head node."""
    node_id = ray_tpu.get(node_of_task.remote(), timeout=60)
    assert node_id == cluster.head_node.node_id.hex()


def test_spread_across_nodes(cluster):
    n2 = cluster.add_node(num_cpus=2)
    seen = set(
        ray_tpu.get(
            [node_of_task.options(scheduling_strategy={"type": "spread"}).remote() for _ in range(8)],
            timeout=90,
        )
    )
    assert len(seen) == 2, f"spread used only {seen}"


def test_cross_node_object_fetch(cluster):
    """Large return lives in plasma on the executing node; the driver's node
    pulls it chunk-by-chunk (PullManager path, raylet FetchObjectChunk)."""

    @ray_tpu.remote
    def big():
        return np.arange(500_000, dtype=np.float32)

    out = ray_tpu.get(big.remote(), timeout=90)
    np.testing.assert_array_equal(out, np.arange(500_000, dtype=np.float32))


def test_cross_node_large_arg(cluster):
    """Large put on the driver's node consumed by a task on another node."""
    arr = np.ones(400_000, dtype=np.float32)
    ref = ray_tpu.put(arr)

    @ray_tpu.remote
    def total(x):
        return float(x.sum())

    assert ray_tpu.get(total.remote(ref), timeout=90) == 400_000.0


def test_node_death_detected(cluster):
    n2 = cluster.add_node(num_cpus=1)
    cluster.remove_node(n2)
    cluster.wait_for_node_death(n2, timeout=30)
    states = {n["node_id"]: n["state"] for n in ray_tpu.nodes()}
    assert states[n2.node_id.hex()] == "DEAD"


def test_lineage_reconstruction_after_node_death(cluster):
    """Sole plasma copy dies with its node → owner resubmits the creating
    task via lineage (object_recovery_manager.h:90,106)."""
    n2 = cluster.add_node(num_cpus=1, resources={"side": 1.0})

    @ray_tpu.remote(resources={"side": 0.001}, max_retries=2)
    def big_on_side():
        return np.full(300_000, 7.0, dtype=np.float32)

    ref = big_on_side.remote()
    first = ray_tpu.get(ref, timeout=90)
    assert first[0] == 7.0
    cluster.remove_node(n2)
    cluster.wait_for_node_death(n2, timeout=30)
    # give the head resources to host the reconstruction
    cluster.add_node(num_cpus=1, resources={"side": 1.0})
    out = ray_tpu.get(ref, timeout=120)
    assert out.shape == (300_000,) and out[0] == 7.0


def test_actor_restart_after_node_death(cluster):
    n2 = cluster.add_node(num_cpus=1, resources={"side": 1.0})

    @ray_tpu.remote(max_restarts=1, resources={"side": 0.001})
    class Stateful:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

        def where(self):
            return ray_tpu.get_runtime_context().node_id

    a = Stateful.remote()
    assert ray_tpu.get(a.bump.remote(), timeout=90) == 1
    assert ray_tpu.get(a.where.remote(), timeout=60) == n2.node_id.hex()
    n3 = cluster.add_node(num_cpus=1, resources={"side": 1.0})
    cluster.remove_node(n2)
    cluster.wait_for_node_death(n2, timeout=30)
    # restarted actor loses state but must serve again on the other node
    deadline = time.monotonic() + 90
    while True:
        try:
            v = ray_tpu.get(a.bump.remote(), timeout=30)
            break
        except Exception:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.5)
    assert v == 1
    assert ray_tpu.get(a.where.remote(), timeout=60) == n3.node_id.hex()


def test_actor_restart_after_worker_kill(cluster):
    @ray_tpu.remote(max_restarts=1)
    class Phoenix:
        def pid(self):
            import os

            return os.getpid()

        def die(self):
            import os

            os._exit(1)

    a = Phoenix.remote()
    pid1 = ray_tpu.get(a.pid.remote(), timeout=90)
    a.die.remote()
    deadline = time.monotonic() + 90
    while True:
        try:
            pid2 = ray_tpu.get(a.pid.remote(), timeout=30)
            break
        except Exception:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.5)
    assert pid2 != pid1


def test_pg_strict_spread_two_nodes(cluster):
    from ray_tpu.util import (
        PlacementGroupSchedulingStrategy,
        placement_group,
        remove_placement_group,
    )

    cluster.add_node(num_cpus=2)
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD")
    assert pg.wait(timeout_seconds=60)
    locations = [
        ray_tpu.get(
            node_of_task.options(
                scheduling_strategy=PlacementGroupSchedulingStrategy(
                    placement_group=pg, placement_group_bundle_index=i
                )
            ).remote(),
            timeout=90,
        )
        for i in range(2)
    ]
    assert locations[0] != locations[1]
    remove_placement_group(pg)


def test_pg_task_spills_to_bundle_node(cluster):
    """A PG task submitted via the driver's bundle-less node must land on
    the node holding the bundle."""
    from ray_tpu.util import (
        PlacementGroupSchedulingStrategy,
        placement_group,
        remove_placement_group,
    )

    n2 = cluster.add_node(num_cpus=1, resources={"only_here": 1.0})
    pg = placement_group([{"CPU": 1, "only_here": 0.5}], strategy="PACK")
    assert pg.wait(timeout_seconds=60)
    where = ray_tpu.get(
        node_of_task.options(
            scheduling_strategy=PlacementGroupSchedulingStrategy(
                placement_group=pg, placement_group_bundle_index=0
            )
        ).remote(),
        timeout=90,
    )
    assert where == n2.node_id.hex()
    remove_placement_group(pg)


def test_task_retry_after_node_death(cluster):
    """In-flight task on a dying node is retried elsewhere (task FT)."""
    n2 = cluster.add_node(num_cpus=1, resources={"side": 1.0})

    @ray_tpu.remote(resources={"side": 0.001}, max_retries=2)
    def slow_id():
        import time as _t

        _t.sleep(3)
        return ray_tpu.get_runtime_context().node_id

    ref = slow_id.remote()
    time.sleep(1.0)  # let it start on n2
    cluster.remove_node(n2)
    cluster.add_node(num_cpus=1, resources={"side": 1.0})
    out = ray_tpu.get(ref, timeout=120)
    assert out != n2.node_id.hex()


def test_rpc_chaos_cluster_still_works(cluster):
    """Deterministic RPC failure injection (rpc_chaos.h:23-37): dropped
    Heartbeat requests/responses must not break task execution."""
    from ray_tpu.core.rpc import RpcChaos, set_chaos

    set_chaos(RpcChaos("Heartbeat=0.3,0.3"))
    try:
        vals = ray_tpu.get([node_of_task.remote() for _ in range(6)], timeout=120)
        assert len(vals) == 6
    finally:
        set_chaos(RpcChaos(""))


def test_shuffle_exchange_multinode(cluster):
    """A shuffle whose data exceeds any single block runs as a map-reduce
    exchange across a multi-raylet cluster: map partitions on arrival,
    reduces merge one partition each — no task ever holds the dataset
    (the VERDICT round-3 acceptance for Data shuffle at scale)."""
    cluster.add_node(num_cpus=2)
    from ray_tpu import data as rd

    n = 20_000
    ds = rd.range(n, parallelism=16).random_shuffle(seed=11)
    refs = list(ds.iter_internal_ref_bundles())
    assert len(refs) > 1  # partitioned output, not one consolidation block
    blocks = [ray_tpu.get(r, timeout=120) for r in refs]
    rows = [v for b in blocks for v in b.column("id").to_pylist()]
    assert sorted(rows) == list(range(n))
    assert rows != sorted(rows)
    # every block is a strict subset of the data: bounded task memory
    assert max(b.num_rows for b in blocks) < n


def test_cross_node_compiled_dag(cluster):
    """A compiled DAG whose stages live on DIFFERENT nodes: edges between
    co-located endpoints stay shm; cross-node edges ride TCP channels
    (reference experimental/channel cross-node transport + dag/collective
    pipelines). The driver (its own 0-CPU node) feeds input and reads
    output across nodes."""
    from ray_tpu.dag import InputNode

    cluster.add_node(num_cpus=2, resources={"left": 2.0})
    cluster.add_node(num_cpus=2, resources={"side": 2.0})

    @ray_tpu.remote
    class Stage:
        def __init__(self, add):
            self.add_v = add

        def add(self, x):
            return x + self.add_v

        def where(self):
            return ray_tpu.get_runtime_context().node_id

    a = Stage.options(resources={"left": 1.0}).remote(1)
    b = Stage.options(resources={"side": 1.0}).remote(10)
    node_a = ray_tpu.get(a.where.remote(), timeout=60)
    node_b = ray_tpu.get(b.where.remote(), timeout=60)
    assert node_a != node_b, "stages must land on different nodes"

    with InputNode() as inp:
        dag = b.add.bind(a.add.bind(inp))
    compiled = dag.experimental_compile()
    try:
        # at least the a->b edge and the b->driver edge are cross-node
        assert len(compiled._cross_node) >= 2
        for i in range(5):
            assert compiled.execute(i, timeout=60) == i + 11
        # error propagation still works across TCP edges
    finally:
        compiled.teardown()
    # actors serve normal calls again after teardown
    assert ray_tpu.get(a.add.remote(5), timeout=60) == 6


def test_broadcast_push_fans_out(cluster):
    """Broadcasting one object to several nodes: holders PUSH chunks
    (pipelined, no per-chunk round trip), each receiver registers its copy
    with the owner, and later pullers prefer SECONDARY holders — the
    primary does not serve every transfer (reference push_manager.h:30 +
    ownership-based directory fan-out)."""
    nodes = [cluster.add_node(num_cpus=1, resources={f"slot{i}": 1.0})
             for i in range(3)]

    blob = np.random.randint(0, 255, size=(12 << 20,), dtype=np.uint8)
    ref = ray_tpu.put(blob)  # primary on the driver's node

    @ray_tpu.remote(num_cpus=1)
    def consume(x):
        return int(x[0]) + x.nbytes

    expected = int(blob[0]) + blob.nbytes
    # Sequential waves pinned HARD to each node (custom resource, not soft
    # affinity — a fallback to a node that already holds the object would
    # skip a transfer): receivers become sources for the next wave.
    for i in range(3):
        out = ray_tpu.get(
            consume.options(resources={f"slot{i}": 0.5}).remote(ref),
            timeout=120)
        assert out == expected

    from ray_tpu.core.worker import global_worker

    w = global_worker()
    locations = w.io.run_sync(w.handle_GetObjectLocations({"id": ref.id().binary()}))
    assert len(locations["locations"]) >= 3, locations

    # After wave 1, later pullers must be served by NON-primary receivers
    # (the primary is the driver's raylet, which is not in `nodes`): if the
    # primary served every wave, no consumer node pushed anything.
    pushes = {r.node_id.hex()[:8]: r.transfer_stats["pushes_served"]
              for r in [cluster.head_node] + nodes}
    secondary_pushes = sum(r.transfer_stats["pushes_served"] for r in nodes)
    assert secondary_pushes >= 1, f"primary served every transfer: {pushes}"


def test_pull_admission_orders_get_before_task_arg(cluster):
    """Pull admission classes: a ray.get-blocked pull admitted ahead of
    earlier-queued task-arg prefetches (reference pull_manager.h:51
    get > wait > task-arg bundle priority)."""
    import asyncio

    from ray_tpu.core.config import get_config

    r = cluster.head_node
    cap = get_config().pull_manager_max_concurrent

    async def scenario():
        for _ in range(cap):
            await r._admit_pull("task_arg")  # saturate the slots
        order = []

        async def waiter(cls, tag):
            await r._admit_pull(cls)
            order.append(tag)
            r._release_pull()

        t_arg = asyncio.ensure_future(waiter("task_arg", "arg"))
        await asyncio.sleep(0.05)
        t_get = asyncio.ensure_future(waiter("get", "get"))  # arrives LATER
        await asyncio.sleep(0.05)
        for _ in range(cap):
            r._release_pull()
        await asyncio.gather(t_arg, t_get)
        return order

    order = cluster._loop.run_sync(scenario())
    assert order == ["get", "arg"], order
