"""DreamerV3: world-model learning + imagination actor-critic.

Mirrors the reference's DreamerV3 test strategy
(``rllib/algorithms/dreamerv3/``): unit checks on the distribution
utilities, a world-model-loss learning curve, and an end-to-end
learning assertion on a vector-obs control task.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from ray_tpu.rllib import CartPole, DreamerV3Config
from ray_tpu.rllib.dreamerv3 import (
    symlog, symexp, twohot, twohot_decode, _lambda_returns)


def _tiny_config(**overrides):
    kw = dict(deter=64, stoch_groups=4, stoch_classes=8, hidden=64,
              seq_len=16, batch_size=8, imag_horizon=8,
              rollout_len=32, updates_per_iteration=4,
              learning_starts=128, buffer_size=1024,
              entropy_scale=3e-3)
    kw.update(overrides)
    return (DreamerV3Config()
            .environment(CartPole)
            .env_runners(num_envs_per_runner=8)
            .seeding(1)
            .training(**kw))


def test_symlog_twohot_roundtrip():
    x = jnp.array([-50.0, -1.0, 0.0, 0.5, 3.0, 200.0])
    np.testing.assert_allclose(symexp(symlog(x)), x, rtol=1e-5, atol=1e-5)
    # twohot encode -> expected-value decode is the identity on the
    # support (x enters/leaves in raw space, bins live in symlog space)
    y = symlog(x)
    dec = symexp(twohot(y) @ jnp.linspace(-15.0, 15.0, 63))
    np.testing.assert_allclose(dec, x, rtol=1e-3, atol=1e-3)
    probs = twohot(y)
    assert probs.shape == (6, 63)
    np.testing.assert_allclose(probs.sum(-1), 1.0, rtol=1e-6)


def test_lambda_returns_hand_computed():
    # H=2, N=1; conts are per-transition. gamma=1, lam=1 -> pure
    # Monte Carlo + bootstrap
    rews = jnp.array([[1.0], [2.0]])
    conts = jnp.array([[1.0], [1.0]])
    values = jnp.array([[10.0], [20.0], [30.0]])
    rets = _lambda_returns(rews, conts, values, gamma=1.0, lam=1.0)
    np.testing.assert_allclose(rets[:, 0], [1.0 + 2.0 + 30.0, 2.0 + 30.0])
    # lam=0 -> one-step TD targets
    rets0 = _lambda_returns(rews, conts, values, gamma=0.5, lam=0.0)
    np.testing.assert_allclose(rets0[:, 0], [1.0 + 0.5 * 20.0,
                                             2.0 + 0.5 * 30.0])
    # a terminating first transition masks everything after step 0
    conts_t = jnp.array([[0.0], [1.0]])
    rets_t = _lambda_returns(rews, conts_t, values, gamma=0.9, lam=1.0)
    np.testing.assert_allclose(rets_t[0, 0], 1.0)
    np.testing.assert_allclose(rets_t[1, 0], 2.0 + 0.9 * 30.0)


def test_world_model_loss_decreases():
    algo = _tiny_config().build()
    first = last = None
    for _ in range(12):
        m = algo.training_step()
        if "wm_loss" in m:
            first = m["wm_loss"] if first is None else first
            last = m["wm_loss"]
    assert first is not None, "updates never started"
    assert last < first, (first, last)
    assert np.isfinite(last)


def test_dreamerv3_cartpole_learns():
    # Seed-1 curve on a 1-core CPU host: random ~17 at iter 0, crosses
    # 60 around iter 55-60, 100+ by iter 80 (~25 s wall after compile).
    algo = _tiny_config(updates_per_iteration=8).build()
    first = None
    result = {}
    for i in range(80):
        result = algo.training_step()
        r = result.get("episode_return_mean")
        if r is not None and first is None:
            first = r
        if r is not None and r > 60.0 and i > 5:
            break
    assert first is not None
    assert result["episode_return_mean"] > max(45.0, 1.5 * first), result


def test_dreamerv3_checkpoint_roundtrip(tmp_path):
    algo = _tiny_config().build()
    for _ in range(5):
        algo.training_step()
    path = str(tmp_path / "ckpt")
    algo.save(path)
    it = algo.iteration
    algo2 = _tiny_config().build()
    algo2.restore(path)
    assert algo2.iteration == it
    a = algo.state["wm"]["prior"][0]["w"]
    b = algo2.state["wm"]["prior"][0]["w"]
    np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    # evaluation harness runs with restored weights
    ev = algo2.evaluate()
    assert ev["evaluation"]["num_episodes"] >= 1
