"""Observability: task events → state API + timeline; metrics; CLI.

Mirrors the reference's state-API tests (``python/ray/tests/test_state_api*``)
and ``ray.timeline`` (``_private/state.py:965``).
"""

import json
import time

import pytest

import ray_tpu
from ray_tpu.util import state


@pytest.fixture(autouse=True)
def _cluster(ray_cluster):
    yield


def test_task_events_reach_state_api():
    @ray_tpu.remote
    def traced_task(x):
        return x * 2

    assert ray_tpu.get(traced_task.remote(21), timeout=60) == 42
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        tasks = [t for t in state.list_tasks() if t["name"] == "traced_task"]
        if tasks and tasks[-1]["state"] == "FINISHED":
            break
        time.sleep(0.3)
    assert tasks, "task events never reached the GCS"
    t = tasks[-1]
    assert t["state"] == "FINISHED"
    assert "SUBMITTED" in t["events"] and "FINISHED" in t["events"]


def test_failed_task_recorded():
    @ray_tpu.remote
    def exploder():
        raise ValueError("recorded")

    with pytest.raises(ValueError):
        ray_tpu.get(exploder.remote(), timeout=60)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        tasks = [t for t in state.list_tasks() if t["name"] == "exploder"]
        if tasks and tasks[-1]["state"] == "FAILED":
            break
        time.sleep(0.3)
    assert tasks and tasks[-1]["state"] == "FAILED"
    assert "recorded" in tasks[-1]["error"]


def test_timeline_dump(tmp_path):
    @ray_tpu.remote
    def timed():
        time.sleep(0.05)
        return 1

    ray_tpu.get([timed.remote() for _ in range(3)], timeout=60)
    time.sleep(1.5)  # let the flusher run
    path = ray_tpu.timeline(str(tmp_path / "trace.json"))
    trace = json.load(open(path))
    assert isinstance(trace, list) and trace
    timed_events = [e for e in trace if e["name"] == "timed"]
    assert len(timed_events) >= 3
    for e in timed_events:
        assert e["ph"] == "X" and e["dur"] > 0 and "pid" in e and "tid" in e


def test_state_api_nodes_workers_objects():
    nodes = state.list_nodes()
    assert any(n["state"] == "ALIVE" for n in nodes)
    workers = state.list_workers()
    assert workers, "no workers listed"
    import numpy as np

    ref = ray_tpu.put(np.zeros(200_000, dtype=np.float32))
    objs = state.list_objects()
    assert any(o["state"] == "SEALED" for o in objs)
    del ref


def test_metrics_roundtrip():
    from ray_tpu.util.metrics import Counter, Gauge, get_metrics, prometheus_text

    c = Counter("test_requests_total", tag_keys=("kind",))
    c.inc(3, {"kind": "a"})
    g = Gauge("test_queue_len")
    g.set(7)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        metrics = {m["name"]: m for m in get_metrics()}
        if "test_requests_total" in metrics and "test_queue_len" in metrics:
            break
        time.sleep(0.5)
    assert metrics["test_requests_total"]["value"] == 3
    assert metrics["test_queue_len"]["value"] == 7
    text = prometheus_text(list(metrics.values()))
    assert 'test_requests_total{kind="a"} 3' in text


def test_cli_list_and_status(capsys):
    from ray_tpu.cli import main

    assert main(["list", "nodes"]) == 0
    out = capsys.readouterr().out
    assert "NODE_ID" in out
    assert main(["status"]) == 0
    out = capsys.readouterr().out
    assert "alive" in out and "CPU" in out


def test_summarize_tasks():
    @ray_tpu.remote
    def summary_probe():
        return 1

    ray_tpu.get([summary_probe.remote() for _ in range(2)], timeout=60)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        summary = state.summarize_tasks()
        if summary.get("summary_probe", {}).get("FINISHED", 0) >= 2:
            break
        time.sleep(0.3)
    assert summary["summary_probe"]["FINISHED"] >= 2


def test_worker_logs_stream_to_driver(ray_cluster, capfd):
    """Worker prints surface on the driver's stderr with a worker/node
    prefix (reference log_monitor + print_logs)."""
    import time

    @ray_tpu.remote
    def speak():
        print("log-monitor-test-line")
        return True

    assert ray_tpu.get(speak.remote(), timeout=60)
    deadline = time.time() + 10
    seen = ""
    while time.time() < deadline:
        seen += capfd.readouterr().err
        if "log-monitor-test-line" in seen:
            break
        time.sleep(0.25)
    assert "log-monitor-test-line" in seen
    assert "node=" in seen.split("log-monitor-test-line")[0].rsplit("(", 1)[-1]
