"""Observability: task events → state API + timeline; metrics; CLI;
distributed tracing (span propagation, LEASED transitions, TTFT and
lease-stage histograms).

Mirrors the reference's state-API tests (``python/ray/tests/test_state_api*``)
and ``ray.timeline`` (``_private/state.py:965``).
"""

import json
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu.observability import tracing
from ray_tpu.util import state


def _poll(fn, timeout=30.0, interval=0.3):
    """Poll fn() until it returns a truthy value (task-event/metric
    flushers run on ~1-5s intervals); returns the last value."""
    deadline = time.monotonic() + timeout
    value = fn()
    while not value and time.monotonic() < deadline:
        time.sleep(interval)
        value = fn()
    return value


@pytest.fixture(autouse=True)
def _cluster(ray_cluster):
    yield


def test_task_events_reach_state_api():
    @ray_tpu.remote
    def traced_task(x):
        return x * 2

    assert ray_tpu.get(traced_task.remote(21), timeout=60) == 42
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        tasks = [t for t in state.list_tasks() if t["name"] == "traced_task"]
        # Owner (SUBMITTED/terminal) and executor (RUNNING/FINISHED)
        # events ride two DIFFERENT processes' flush cadences: poll until
        # the record is COMPLETE, not merely terminal — breaking on the
        # executor's FINISHED alone raced the owner's flush by up to one
        # interval (pre-existing flake, seen whenever the phase aligned).
        if (tasks and tasks[-1]["state"] == "FINISHED"
                and "SUBMITTED" in tasks[-1]["events"]):
            break
        time.sleep(0.3)
    assert tasks, "task events never reached the GCS"
    t = tasks[-1]
    assert t["state"] == "FINISHED"
    assert "SUBMITTED" in t["events"] and "FINISHED" in t["events"]


def test_failed_task_recorded():
    @ray_tpu.remote
    def exploder():
        raise ValueError("recorded")

    with pytest.raises(ValueError):
        ray_tpu.get(exploder.remote(), timeout=60)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        tasks = [t for t in state.list_tasks() if t["name"] == "exploder"]
        if tasks and tasks[-1]["state"] == "FAILED":
            break
        time.sleep(0.3)
    assert tasks and tasks[-1]["state"] == "FAILED"
    assert "recorded" in tasks[-1]["error"]


def test_timeline_dump(tmp_path):
    @ray_tpu.remote
    def timed():
        time.sleep(0.05)
        return 1

    ray_tpu.get([timed.remote() for _ in range(3)], timeout=60)
    time.sleep(1.5)  # let the flusher run
    path = ray_tpu.timeline(str(tmp_path / "trace.json"))
    trace = json.load(open(path))
    assert isinstance(trace, list) and trace
    timed_events = [e for e in trace if e["name"] == "timed"]
    assert len(timed_events) >= 3
    for e in timed_events:
        assert e["ph"] == "X" and e["dur"] > 0 and "pid" in e and "tid" in e


def test_state_api_nodes_workers_objects():
    nodes = state.list_nodes()
    assert any(n["state"] == "ALIVE" for n in nodes)
    workers = state.list_workers()
    assert workers, "no workers listed"
    import numpy as np

    ref = ray_tpu.put(np.zeros(200_000, dtype=np.float32))
    objs = state.list_objects()
    assert any(o["state"] == "SEALED" for o in objs)
    del ref


def test_metrics_roundtrip():
    from ray_tpu.util.metrics import Counter, Gauge, get_metrics, prometheus_text

    c = Counter("test_requests_total", tag_keys=("kind",))
    c.inc(3, {"kind": "a"})
    g = Gauge("test_queue_len")
    g.set(7)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        metrics = {m["name"]: m for m in get_metrics()}
        if "test_requests_total" in metrics and "test_queue_len" in metrics:
            break
        time.sleep(0.5)
    assert metrics["test_requests_total"]["value"] == 3
    assert metrics["test_queue_len"]["value"] == 7
    text = prometheus_text(list(metrics.values()))
    assert 'test_requests_total{kind="a"} 3' in text


def test_cli_list_and_status(capsys):
    from ray_tpu.cli import main

    assert main(["list", "nodes"]) == 0
    out = capsys.readouterr().out
    assert "NODE_ID" in out
    assert main(["status"]) == 0
    out = capsys.readouterr().out
    assert "alive" in out and "CPU" in out


def test_summarize_tasks():
    @ray_tpu.remote
    def summary_probe():
        return 1

    ray_tpu.get([summary_probe.remote() for _ in range(2)], timeout=60)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        summary = state.summarize_tasks()
        if summary.get("summary_probe", {}).get("FINISHED", 0) >= 2:
            break
        time.sleep(0.3)
    assert summary["summary_probe"]["FINISHED"] >= 2


def test_leased_transition_recorded():
    """Remote tasks pass through LEASED between SUBMITTED and RUNNING
    (ROADMAP 1c: lease-stage timestamps for the cascade investigation)."""

    @ray_tpu.remote
    def leased_probe():
        return 1

    assert ray_tpu.get(leased_probe.remote(), timeout=60) == 1

    def _find():
        tasks = [t for t in state.list_tasks() if t["name"] == "leased_probe"
                 and t["state"] == "FINISHED" and "LEASED" in t["events"]]
        return tasks

    tasks = _poll(_find)
    assert tasks, "no finished leased_probe task with a LEASED event"
    events = tasks[-1]["events"]
    assert events["SUBMITTED"] <= events["LEASED"] <= events["FINISHED"]


def test_task_span_propagation():
    """submit → lease → execute → get hops share one trace and form a
    connected parent/child tree."""

    @ray_tpu.remote
    def traced_child(x):
        return x + 1

    with tracing.span("test-root", kind="test") as ctx:
        assert ray_tpu.get(traced_child.remote(1), timeout=60) == 2
    trace_id = ctx.trace_id

    def _spans():
        spans = state.list_spans(trace_id=trace_id)
        names = {s["name"] for s in spans}
        if ("test-root" in names
                and "task traced_child" in names
                and "execute traced_child" in names
                and any(n.startswith("lease ") for n in names)):
            return spans
        return None

    spans = _poll(_spans)
    assert spans, f"incomplete span tree: {state.list_spans(trace_id=trace_id)}"
    by_id = {s["span_id"]: s for s in spans}
    task = next(s for s in spans if s["name"] == "task traced_child")
    execute = next(s for s in spans if s["name"] == "execute traced_child")
    root = next(s for s in spans if s["name"] == "test-root")
    assert execute["parent_id"] == task["span_id"]
    assert task["parent_id"] == root["span_id"]
    assert all(s["trace_id"] == trace_id for s in spans)
    # lease span (recorded by the raylet) parents onto the task span
    lease = next(s for s in spans if s["name"].startswith("lease "))
    assert by_id[lease["parent_id"]]["name"].startswith("task ")
    # ray.get inside the root context shows up as a child hop
    assert any(s["name"].startswith("get ") for s in spans)


def test_timeline_merges_spans(tmp_path):
    """Spans appear in the chrome trace as per-trace slices + flow links."""
    with tracing.span("timeline-span-probe", kind="test") as ctx:
        pass
    path = str(tmp_path / "trace_spans.json")

    def _dump():
        ray_tpu.timeline(path)
        trace = json.load(open(path))
        slices = [e for e in trace if e.get("cat") == "span"
                  and e.get("args", {}).get("trace_id") == ctx.trace_id]
        return slices

    slices = _poll(_dump)
    assert slices and slices[0]["ph"] == "X"
    assert slices[0]["pid"].startswith("trace:")


def test_lease_stage_histograms():
    """The GCS exports per-raylet lease-stage duration histograms fed by
    LEASED events (submit→lease, queue wait, spawn, lease→run)."""

    @ray_tpu.remote
    def stage_probe():
        return 1

    ray_tpu.get([stage_probe.remote() for _ in range(3)], timeout=60)

    def _rows():
        from ray_tpu.util.metrics import get_metrics

        rows = [m for m in get_metrics() if m["name"] == "ray_tpu_lease_stage_ms"]
        stages = {m["tags"].get("stage") for m in rows if m.get("count")}
        if {"lease_queue_wait", "worker_spawn"} <= stages:
            return rows
        return None

    rows = _poll(_rows)
    assert rows, "lease-stage histograms never populated"
    assert all(m["type"] == "histogram" for m in rows)


def test_serve_request_span_tree_and_ttft():
    """Acceptance: one traced serve request yields a connected span tree
    (proxy → router → replica task → engine prefill/decode) and a
    non-empty serve_ttft_ms histogram."""
    from ray_tpu import serve
    from ray_tpu.llm import build_llm_app

    try:
        serve.run(build_llm_app("debug-128", max_slots=4, max_len=128), name="llm")
        addr = serve.http_address()
        body = json.dumps({"prompt": "hello trace", "max_tokens": 6}).encode()
        req = urllib.request.Request(addr + "/v1/completions", data=body,
                                     headers={"Content-Type": "application/json"})
        resp = urllib.request.urlopen(req, timeout=120)
        out = json.loads(resp.read())
        assert out["usage"]["completion_tokens"] == 6
        trace_id = resp.headers.get("x-raytpu-trace-id")
        assert trace_id, "proxy did not echo the trace id"

        def _spans():
            spans = state.list_spans(trace_id=trace_id)
            names = {s["name"] for s in spans}
            want_prefixes = ("http ", "router.queue ", "task ", "execute ")
            if all(any(n.startswith(p) for n in names) for p in want_prefixes) \
                    and {"llm.prefill", "llm.decode"} <= names:
                return spans
            return None

        spans = _poll(_spans)
        assert spans, (
            f"incomplete serve span tree: "
            f"{[s['name'] for s in state.list_spans(trace_id=trace_id)]}")
        # prefill's ancestry must reach the proxy's http root span
        by_id = {s["span_id"]: s for s in spans}
        hop = next(s for s in spans if s["name"] == "llm.prefill")
        seen = []
        while hop is not None:
            seen.append(hop["name"])
            hop = by_id.get(hop["parent_id"])
        assert any(n.startswith("http ") for n in seen), seen
        prefill = next(s for s in spans if s["name"] == "llm.prefill")
        assert prefill["attrs"]["prompt_tokens"] > 0

        def _ttft():
            from ray_tpu.util.metrics import get_metrics

            return [m for m in get_metrics()
                    if m["name"] == "serve_ttft_ms" and m.get("count", 0) > 0]

        rows = _poll(_ttft)
        assert rows, "serve_ttft_ms histogram never populated"
        assert rows[0]["tags"]["deployment"]  # tagged per deployment
        from ray_tpu.util.metrics import histogram_quantile

        assert histogram_quantile(rows[0], 0.5) is not None
    finally:
        serve.shutdown()


def test_cli_trace_and_timeline_smoke(tmp_path, capsys):
    """Tier-1 smoke for the CLI tracing surfaces against a live cluster:
    `cli timeline`, `cli trace` (list) and `cli trace <id>` (tree)."""
    from ray_tpu.cli import main

    @ray_tpu.remote
    def cli_probe():
        return 1

    with tracing.span("cli-smoke-root", kind="test") as ctx:
        assert ray_tpu.get(cli_probe.remote(), timeout=60) == 1

    def _ready():
        names = {s["name"] for s in state.list_spans(trace_id=ctx.trace_id)}
        return {"cli-smoke-root", "task cli_probe"} <= names

    assert _poll(_ready), "root/task spans never flushed"

    out_path = str(tmp_path / "cli_timeline.json")
    assert main(["timeline", "-o", out_path]) == 0
    assert json.load(open(out_path))
    capsys.readouterr()

    assert main(["trace"]) == 0
    out = capsys.readouterr().out
    assert "TRACE_ID" in out and ctx.trace_id[:12] in out

    assert main(["trace", ctx.trace_id]) == 0
    out = capsys.readouterr().out
    assert "cli-smoke-root" in out and "task cli_probe" in out


def test_prometheus_help_type_and_quantile():
    from ray_tpu.util.metrics import (
        LATENCY_MS_BOUNDARIES, Histogram, histogram_quantile, prometheus_text)

    h = Histogram("obs_test_latency_ms", "A test latency histogram",
                  tag_keys=("kind",), register=False)
    assert h.boundaries == LATENCY_MS_BOUNDARIES  # ms-scale default
    for v in (3, 30, 300):
        h.observe(v, {"kind": "a"})
    snap = h.snapshot()[0]
    text = prometheus_text([snap])
    assert "# HELP obs_test_latency_ms A test latency histogram" in text
    assert "# TYPE obs_test_latency_ms histogram" in text
    assert 'obs_test_latency_ms_bucket{kind="a",le="+Inf"} 3' in text
    q = histogram_quantile(snap, 0.5)
    assert 2.0 <= q <= 100.0
    # counter/gauge families get TYPE lines too
    text = prometheus_text([
        {"name": "obs_test_total", "type": "counter", "desc": "c", "tags": {}, "value": 1}])
    assert "# TYPE obs_test_total counter" in text


def test_train_step_gauges():
    from ray_tpu.train.session import TrainContext, _Session
    from ray_tpu.util.metrics import snapshot_all

    ctx = TrainContext(world_rank=0, world_size=1, local_rank=0,
                       local_world_size=1, node_rank=0,
                       experiment_name="obs-test", storage_path="/tmp")
    session = _Session(ctx, None)
    session.report({"tokens_per_sec_per_chip": 1234.0, "mfu": 0.45})
    session.report({"tokens_per_sec_per_chip": 2345.0, "mfu": 0.5})
    snap = {(m["name"], m["tags"].get("experiment")): m for m in snapshot_all()}
    assert snap[("train_tokens_per_s", "obs-test")]["value"] == 2345.0
    assert snap[("train_mfu", "obs-test")]["value"] == 0.5
    assert snap[("train_step_time_s", "obs-test")]["value"] >= 0.0


def test_worker_logs_stream_to_driver(ray_cluster, capfd):
    """Worker prints surface on the driver's stderr with a worker/node
    prefix (reference log_monitor + print_logs)."""
    import time

    @ray_tpu.remote
    def speak():
        print("log-monitor-test-line")
        return True

    assert ray_tpu.get(speak.remote(), timeout=60)
    deadline = time.time() + 10
    seen = ""
    while time.time() < deadline:
        seen += capfd.readouterr().err
        if "log-monitor-test-line" in seen:
            break
        time.sleep(0.25)
    assert "log-monitor-test-line" in seen
    assert "node=" in seen.split("log-monitor-test-line")[0].rsplit("(", 1)[-1]
