"""TPU device-release fence.

The libtpu device lock is per-process and exclusive; the kernel releases
it only on process death. The raylet therefore kills a worker whose lease
held the ``TPU`` resource and re-grants that resource only once the
process is confirmed dead — otherwise the next TPU lease (e.g. a serve
replica starting right after a training job) crash-loops on device init
while the old holder drains (the round-3 serve-after-train failure).
"""

import os

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster


@pytest.fixture()
def tpu_cluster():
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()  # replace the shared single-node cluster
    c = Cluster(
        initialize_head=True,
        head_node_args={"num_cpus": 2, "resources": {"TPU": 1.0}},
    )
    ray_tpu.init(address=c.address, num_cpus=0)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def _alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except OSError:
        return False


def test_tpu_lease_pipeline_reuses_the_holder_process(tpu_cluster):
    """Same-shape TPU tasks share a lease pipeline and thus the SAME
    process — the holder keeps the device; no restart tax per task."""

    @ray_tpu.remote(resources={"TPU": 1.0}, num_cpus=0)
    def f():
        return os.getpid()

    pids = {ray_tpu.get(f.remote(), timeout=120) for _ in range(3)}
    assert len(pids) == 1, f"TPU tasks in one pipeline should share a process, got {pids}"


def test_tpu_handoff_waits_for_holder_death(tpu_cluster):
    """Once a TPU lease is RETURNED, the next grant (here: a different
    resource shape, so a fresh lease) happens only after the previous
    holder's process is dead — no crash-looping on a held device lock."""

    @ray_tpu.remote(resources={"TPU": 1.0}, num_cpus=0)
    def hold():
        return os.getpid()

    pid1 = ray_tpu.get(hold.remote(), timeout=120)

    @ray_tpu.remote(resources={"TPU": 1.0}, num_cpus=1)
    def second(prev_pid):
        try:
            os.kill(prev_pid, 0)
            prev_alive = True
        except OSError:
            prev_alive = False
        return os.getpid(), prev_alive

    pid2, prev_alive = ray_tpu.get(second.remote(pid1), timeout=120)
    assert pid2 != pid1
    assert not prev_alive, "previous TPU holder was still alive at grant time"


def test_tpu_handoff_after_actor_kill(tpu_cluster):
    """The serve-after-train pattern: a long-lived TPU actor is killed and
    the next TPU actor starts first-try, after the holder died."""

    @ray_tpu.remote(resources={"TPU": 1.0}, num_cpus=0)
    class Holder:
        def pid(self):
            return os.getpid()

    a = Holder.remote()
    pid1 = ray_tpu.get(a.pid.remote(), timeout=120)
    ray_tpu.kill(a)

    b = Holder.remote()
    pid2 = ray_tpu.get(b.pid.remote(), timeout=120)
    assert pid2 != pid1
    assert not _alive(pid1), "killed TPU actor still alive after next grant"
    ray_tpu.kill(b)


def test_non_tpu_workers_still_pooled(tpu_cluster):
    """The fence is TPU-specific: plain CPU workers keep being reused."""

    @ray_tpu.remote(num_cpus=1)
    def f():
        return os.getpid()

    pids = {ray_tpu.get(f.remote(), timeout=120) for _ in range(3)}
    assert len(pids) == 1, f"CPU workers should be pooled, got {pids}"


def test_tpu_fence_survives_pg_teardown(tpu_cluster):
    """Killing a bundle-leased TPU actor and removing its placement group
    immediately (the ShardedEngineExecutor.shutdown pattern) must NOT
    re-grant the chip before the holder process is dead — _drop_bundle
    withholds fenced TPU shares from its release."""
    from ray_tpu.util import (
        PlacementGroupSchedulingStrategy,
        placement_group,
        remove_placement_group,
    )

    pg = placement_group([{"TPU": 1.0, "CPU": 1.0}])
    assert pg.wait(timeout_seconds=60)

    @ray_tpu.remote(resources={"TPU": 1.0}, num_cpus=0)
    class Holder:
        def pid(self):
            return os.getpid()

    a = Holder.options(
        scheduling_strategy=PlacementGroupSchedulingStrategy(
            placement_group=pg, placement_group_bundle_index=0),
    ).remote()
    pid1 = ray_tpu.get(a.pid.remote(), timeout=120)
    ray_tpu.kill(a)
    remove_placement_group(pg)  # immediately, as multi-host teardown does

    @ray_tpu.remote(resources={"TPU": 1.0}, num_cpus=0)
    def next_lease(prev):
        try:
            os.kill(prev, 0)
            return os.getpid(), True
        except OSError:
            return os.getpid(), False

    pid2, prev_alive = ray_tpu.get(next_lease.remote(pid1), timeout=120)
    assert pid2 != pid1
    assert not prev_alive, "PG teardown re-granted the chip before holder death"


def test_tpu_grant_fence_waits_for_external_lock_holder(tmp_path, monkeypatch):
    """GRANT-side fence: the libtpu device lock may be held by a process
    the raylet never tracked (a benchmark phase, a stray trainer). The
    first TPU lease after such a handoff must wait for the lock, not
    start a worker that crash-loops on device init."""
    import fcntl
    import threading
    import time as _time

    lockfile = tmp_path / "libtpu_lockfile"
    monkeypatch.setenv("RAY_TPU_LOCKFILE", str(lockfile))
    # Simulate the external holder: take the flock in THIS process.
    fd = os.open(lockfile, os.O_CREAT | os.O_RDWR, 0o666)
    fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)

    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    c = Cluster(
        initialize_head=True,
        head_node_args={"num_cpus": 2, "resources": {"TPU": 1.0}},
    )
    ray_tpu.init(address=c.address, num_cpus=0)
    try:
        @ray_tpu.remote(resources={"TPU": 1.0}, num_cpus=0)
        def probe():
            return _time.time()

        released_at = [None]

        def release_later():
            _time.sleep(3.0)
            released_at[0] = _time.time()
            fcntl.flock(fd, fcntl.LOCK_UN)

        t = threading.Thread(target=release_later)
        t.start()
        ran_at = ray_tpu.get(probe.remote(), timeout=120)
        t.join()
        assert released_at[0] is not None
        assert ran_at >= released_at[0], (
            "TPU task ran while the external device lock was still held")
    finally:
        os.close(fd)
        ray_tpu.shutdown()
        c.shutdown()
