"""GCS store sharding (ISSUE 14 tentpole b) and pub/sub fan-out batching.

The acceptance net is the PR-6d equivalence treatment applied to
sharding: task-event records and lease-stage histogram observations must
be BYTE-IDENTICAL between the 1-shard and N-shard stores for the same
input, while concurrent flush batches stop convoying on one lock.
"""

from __future__ import annotations

import asyncio
import threading
import time

import pytest

from ray_tpu.core.store_client import ShardedKv, shard_index
from ray_tpu.core.task_events import GcsTaskEventStore


def _stage_recorder():
    calls: list[tuple] = []
    return calls, lambda stage, ms, node: calls.append((stage, round(ms, 6), node))


def _event_stream(n_tasks: int = 40) -> list[dict]:
    events = []
    for i in range(n_tasks):
        tid = bytes([i % 251]) * 3 + bytes([i // 251])
        base = {"task_id": tid.hex(), "name": f"t{i}", "kind": 0,
                "worker_id": f"w{i % 7}", "node_id": f"n{i % 3}"}
        events.append({**base, "status": "SUBMITTED", "ts": i * 0.001})
        events.append({**base, "status": "LEASED", "ts": i * 0.001 + 0.0005,
                       "queue_wait_ms": 0.1 * i, "spawn_ms": 0.25})
        events.append({**base, "status": "RUNNING", "ts": i * 0.001 + 0.001})
        events.append({**base, "status": "FINISHED", "ts": i * 0.001 + 0.002})
    return events


# ------------------------------------------------------ shard equivalence


def test_task_event_store_shard_equivalence():
    """1-shard vs 8-shard: identical list_tasks output (records AND
    order), identical stage-observer call sequence (the lease-stage
    histograms are built from it), identical state tallies."""
    events = _event_stream(40)
    one_calls, one_cb = _stage_recorder()
    many_calls, many_cb = _stage_recorder()
    one = GcsTaskEventStore(on_stage=one_cb, shards=1)
    many = GcsTaskEventStore(on_stage=many_cb, shards=8)
    one.add_events([dict(e) for e in events])
    many.add_events([dict(e) for e in events])

    assert one.list_tasks(limit=1000) == many.list_tasks(limit=1000)
    assert one_calls == many_calls
    assert one.count_by_state() == many.count_by_state()
    # and the limit window slices the same records in the same order
    assert one.list_tasks(limit=7) == many.list_tasks(limit=7)


def test_task_event_store_eviction_keeps_global_order():
    """Over capacity the N-shard store evicts the globally-oldest record
    — the same one the 1-shard ring would pop."""
    events = _event_stream(30)
    one = GcsTaskEventStore(max_tasks=10, shards=1)
    many = GcsTaskEventStore(max_tasks=10, shards=4)
    one.add_events([dict(e) for e in events])
    many.add_events([dict(e) for e in events])
    assert one.list_tasks(limit=100) == many.list_tasks(limit=100)
    assert len(many.list_tasks(limit=100)) == 10


def test_task_event_store_concurrent_ingest_threads():
    """Concurrent flush batches (the N-raylet shape) all land: every
    record present, per-task transitions complete."""
    store = GcsTaskEventStore(shards=8)
    streams = [_event_stream(25) for _ in range(6)]
    # re-key each stream so tasks are distinct across threads
    for si, stream in enumerate(streams):
        for e in stream:
            e["task_id"] = f"{si:02d}{e['task_id']}"

    def ingest(stream):
        for i in range(0, len(stream), 10):
            store.add_events(stream[i:i + 10])

    threads = [threading.Thread(target=ingest, args=(s,)) for s in streams]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    tasks = store.list_tasks(limit=10_000)
    assert len(tasks) == 6 * 25
    assert all(t["state"] == "FINISHED" for t in tasks)


# --------------------------------------------------------------- ShardedKv


def test_sharded_kv_mapping_semantics():
    kv = ShardedKv(8)
    for i in range(50):
        kv[f"k{i}"] = i
    assert len(kv) == 50
    assert kv["k17"] == 17
    assert kv.get("missing") is None
    assert "k3" in kv and "nope" not in kv
    # insertion order survives the shard split (persistence/restore path)
    assert list(kv.keys()) == [f"k{i}" for i in range(50)]
    assert kv.to_dict() == {f"k{i}": i for i in range(50)}
    # overwrite keeps position, like a dict
    kv["k0"] = 999
    assert list(kv.keys())[0] == "k0" and kv["k0"] == 999
    assert kv.pop("k1", None) == 1
    assert kv.pop("k1", None) is None
    assert len(kv) == 49
    assert kv.keys_with_prefix("k4") == ["k4"] + [f"k4{d}" for d in range(10)]
    # round-trips through a plain dict (the msgpack snapshot path)
    restored = ShardedKv(4, kv.to_dict())
    assert restored.to_dict() == kv.to_dict()


def test_shard_index_stable_and_bounded():
    for n in (1, 2, 8):
        for key in ("abc", b"abc", "task-123", ""):
            idx = shard_index(key, n)
            assert 0 <= idx < n
            assert idx == shard_index(key, n)  # deterministic
    # str and bytes spellings of the same key may differ; hex ids are str


# -------------------------------------------------------- pub/sub batching


def _run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def test_publisher_batches_notifies_and_bounds_replies():
    """N publishes inside the batch window share one subscriber wake,
    and one poll reply carries at most gcs_pubsub_max_batch_msgs per
    channel — the rest arrive on the next poll, cursor-contiguous."""
    from ray_tpu.core.config import get_config
    from ray_tpu.core.gcs import Publisher

    cfg = get_config()
    saved = (cfg.gcs_pubsub_batch_window_ms, cfg.gcs_pubsub_max_batch_msgs)
    cfg.gcs_pubsub_batch_window_ms = 5.0
    cfg.gcs_pubsub_max_batch_msgs = 40

    async def scenario():
        pub = Publisher()
        for i in range(100):
            await pub.publish("actor", {"i": i})
        got = await pub.poll({"actor": 0}, timeout=2.0)
        first = got["actor"]
        assert len(first) == 40  # bounded reply
        got2 = await pub.poll({"actor": first[-1][0]}, timeout=2.0)
        second = got2["actor"]
        got3 = await pub.poll({"actor": second[-1][0]}, timeout=2.0)
        third = got3["actor"]
        seqs = [s for s, _ in first + second + third]
        assert seqs == list(range(1, 101))  # nothing lost, nothing reordered
        assert [m["i"] for _, m in first + second + third] == list(range(100))
        # 100 publishes produced far fewer wakes than publishes
        await asyncio.sleep(0.02)  # let the last scheduled flush run
        assert pub.notify_batches_total < pub.publishes_total
        return pub

    try:
        pub = _run(scenario())
        assert pub.publishes_total == 100
    finally:
        cfg.gcs_pubsub_batch_window_ms, cfg.gcs_pubsub_max_batch_msgs = saved


def test_publisher_longpoll_wakes_within_window():
    """A parked long-poller is woken by a publish (within the batch
    window, not its full timeout)."""
    from ray_tpu.core.gcs import Publisher

    async def scenario():
        pub = Publisher()

        async def poller():
            t0 = time.perf_counter()
            out = await pub.poll({"node": 0}, timeout=10.0)
            return out, time.perf_counter() - t0

        task = asyncio.ensure_future(poller())
        await asyncio.sleep(0.05)
        await pub.publish("node", {"x": 1})
        out, waited = await asyncio.wait_for(task, timeout=5.0)
        assert out["node"] == [(1, {"x": 1})]
        assert waited < 2.0  # woke on publish, not on poll timeout
        # trimming keeps cursor arithmetic correct
        for i in range(2, 30):
            await pub.publish("node", {"x": i})
        got = await pub.poll({"node": 1}, timeout=2.0)
        assert [m["x"] for _, m in got["node"]] == list(range(2, 30))

    _run(scenario())


def test_gcs_tables_survive_sharding(tmp_path):
    """KV + actor tables ride ShardedKv: snapshot/restore round-trips
    byte-identically through the msgpack path."""
    from ray_tpu.core.gcs_storage import pack_tables, unpack_tables

    kv = ShardedKv(8)
    kv["function:abc"] = b"blob"
    kv["chaos:active_plan"] = b"{}"
    tables = {"kv": kv.to_dict()}
    assert unpack_tables(pack_tables(tables)) == {"kv": {
        "function:abc": b"blob", "chaos:active_plan": b"{}"}}
