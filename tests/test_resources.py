import pytest

from ray_tpu.core.resources import NodeResources, ResourceSet
from ray_tpu.core.scheduling import schedule_placement_group, select_node_for_resources


def test_fixed_point_no_drift():
    nr = NodeResources({"CPU": 1.0})
    req = ResourceSet({"CPU": 0.1})
    for _ in range(10):
        nr.acquire(req)
    assert nr.available.get("CPU") == 0.0
    for _ in range(10):
        nr.release(req)
    assert nr.available.get("CPU") == 1.0


def test_subset_and_algebra():
    a = ResourceSet({"CPU": 2, "TPU": 4})
    b = ResourceSet({"CPU": 1})
    assert b.subset_of(a)
    assert not a.subset_of(b)
    c = a.subtract(b)
    assert c.get("CPU") == 1 and c.get("TPU") == 4
    with pytest.raises(ValueError):
        b.subtract(a)


def make_nodes(*specs):
    out = {}
    for i, (total, avail) in enumerate(specs):
        nr = NodeResources(total)
        nr.available = ResourceSet(avail)
        out[f"node{i}"] = {"node_id": f"node{i}", "state": "ALIVE", "resources": nr.to_dict(), "address": f"a:{i}"}
    return out


def test_hybrid_packs_then_spreads():
    nodes = make_nodes(
        ({"CPU": 10}, {"CPU": 8}),   # util 0.2
        ({"CPU": 10}, {"CPU": 10}),  # util 0.0
    )
    # Pack: prefer the more-utilized node while under threshold.
    assert select_node_for_resources(nodes, {"CPU": 1}, {}) == "node0"
    # Over threshold: spread to least utilized.
    nodes2 = make_nodes(
        ({"CPU": 10}, {"CPU": 2}),   # util 0.8
        ({"CPU": 10}, {"CPU": 9}),   # util 0.1 — above 0.5? no
    )
    assert select_node_for_resources(nodes2, {"CPU": 1}, {}) == "node1"


def test_infeasible_returns_none():
    nodes = make_nodes(({"CPU": 2}, {"CPU": 2}))
    assert select_node_for_resources(nodes, {"TPU": 4}, {}) is None


def test_node_affinity():
    nodes = make_nodes(({"CPU": 4}, {"CPU": 4}), ({"CPU": 4}, {"CPU": 4}))
    strat = {"type": "node_affinity", "node_id": "node1"}
    assert select_node_for_resources(nodes, {"CPU": 1}, strat) == "node1"
    strat_bad = {"type": "node_affinity", "node_id": "nope", "soft": False}
    assert select_node_for_resources(nodes, {"CPU": 1}, strat_bad) is None


def test_pg_strict_spread():
    nodes = make_nodes(({"CPU": 4}, {"CPU": 4}), ({"CPU": 4}, {"CPU": 4}))
    placement = schedule_placement_group(nodes, [{"CPU": 2}, {"CPU": 2}], "STRICT_SPREAD")
    assert placement is not None and placement[0] != placement[1]
    assert schedule_placement_group(nodes, [{"CPU": 2}] * 3, "STRICT_SPREAD") is None


def test_pg_strict_pack():
    nodes = make_nodes(({"CPU": 4}, {"CPU": 4}), ({"CPU": 8}, {"CPU": 8}))
    placement = schedule_placement_group(nodes, [{"CPU": 3}, {"CPU": 3}], "STRICT_PACK")
    assert placement == ["node1", "node1"]
