"""Lease-admission fairness: actor creation must not be starved by task load.

Regression tests for the round-2 flake (`test_dag` executor loops timing
out under full-suite load): the raylet's resource admission is now a
priority+FIFO queue (`raylet._acquire_resources_queued`), so a flood of
task leases can never outrace a parked actor-creation lease.
"""

import time

import pytest

import ray_tpu


@pytest.fixture(autouse=True)
def _cluster(ray_cluster):
    yield


def test_actor_creation_under_task_flood():
    @ray_tpu.remote
    def busy(i):
        time.sleep(0.05)
        return i

    # Saturate the node with task leases (several scheduling categories so
    # multiple pipelines hold workers concurrently).
    refs = [busy.remote(i) for i in range(120)]
    refs += [busy.options(max_retries=0).remote(i) for i in range(120)]

    @ray_tpu.remote
    class A:
        def ping(self):
            return "pong"

    t0 = time.monotonic()
    actors = [A.remote() for _ in range(3)]
    out = [ray_tpu.get(a.ping.remote(), timeout=90) for a in actors]
    creation_s = time.monotonic() - t0
    assert out == ["pong"] * 3
    # Actor creation goes to the head of the admission queue: it must beat
    # the ~10s+ task backlog by a wide margin.
    assert creation_s < 45.0, f"actor creation took {creation_s:.1f}s under task flood"
    assert ray_tpu.get(refs, timeout=180) == list(range(120)) * 2


def test_dag_compiles_under_task_flood():
    """The exact round-2 flake shape: compile a DAG (actor creation +
    __ray_call__ loop install) while tasks churn."""
    from ray_tpu.dag import InputNode, MultiOutputNode

    @ray_tpu.remote
    def churn(i):
        time.sleep(0.02)
        return i

    refs = [churn.remote(i) for i in range(150)]

    @ray_tpu.remote
    class Worker:
        def double(self, x):
            return x * 2

    w = Worker.remote()
    with InputNode() as inp:
        dag = MultiOutputNode([w.double.bind(inp)])
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(21) == 42
    finally:
        compiled.teardown()
    ray_tpu.get(refs, timeout=120)
