"""Cluster diagnostics subsystem: the error-info channel
(``publish_error_to_driver`` → ``state.list_errors()``), debug-state
dumps, the lease-wedge watchdog, and the ``doctor`` aggregation.

Mirrors the reference's error-pubsub tests
(``python/ray/tests/test_failure*.py``: worker errors reach the driver
through the GCS channel) and the raylet's periodic ``debug_state.txt``.
"""

import glob
import os
import time

import pytest

import ray_tpu
from ray_tpu.util import state


@pytest.fixture(autouse=True)
def _cluster(ray_cluster):
    yield


def _wait_for(predicate, timeout=30.0, interval=0.25):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        result = predicate()
        if result:
            return result
        time.sleep(interval)
    return predicate()


def test_task_error_reaches_list_errors():
    """A raising remote task publishes a structured ErrorEvent with the
    full executor-side traceback; the driver's auto-subscriber caches it."""

    @ray_tpu.remote(max_retries=0)
    def diag_boom():
        raise ValueError("diagnostics boom")

    with pytest.raises(ValueError):
        ray_tpu.get(diag_boom.remote(), timeout=60)

    events = _wait_for(lambda: [
        e for e in state.list_errors(error_type="task_failure", limit=1000)
        if "diagnostics boom" in e.get("message", "")
    ])
    assert events, "task failure never reached list_errors()"
    e = events[-1]
    assert e["source"] == "worker"
    assert e["node_id"] and e["worker_id"]
    assert "ValueError" in e["traceback"] and "diagnostics boom" in e["traceback"]
    assert "diag_boom" in e["traceback"]  # the executing frame is visible

    # the driver auto-subscriber saw it too (not just the GCS table)
    from ray_tpu.core.worker import global_worker

    cached = _wait_for(lambda: [
        ev for ev in list(global_worker()._recent_errors)
        if "diagnostics boom" in ev.get("message", "")
    ])
    assert cached, "driver error-info subscriber never received the event"


def test_lease_wedge_watchdog_fires():
    """An admission-queue entry pending past the threshold while its
    resources COULD be granted (head-of-line blocked behind an
    unsatisfiable entry) fires a lease_wedge ErrorEvent carrying the full
    queue snapshot."""
    import asyncio

    from ray_tpu.core import api as core_api
    from ray_tpu.core.config import get_config
    from ray_tpu.core.resources import ResourceSet

    cfg = get_config()
    old_thr = cfg.lease_wedge_threshold_s
    old_int = cfg.lease_wedge_check_interval_s
    cfg.lease_wedge_threshold_s = 0.5
    cfg.lease_wedge_check_interval_s = 0.2
    node = core_api._node
    raylet = node.raylet
    injected = []

    async def _inject():
        loop = asyncio.get_running_loop()
        # Head entry that can never fit: strict head-of-line dispatch
        # wedges everything behind it — the round-5 cascade signature.
        blocker = {"prio": 0, "seq": 10**9, "request": ResourceSet({"CPU": 1e9}),
                   "fut": loop.create_future(),
                   "enqueued_at": time.monotonic() - 60.0}
        stalled = {"prio": 1, "seq": 10**9 + 1,
                   "request": ResourceSet({"CPU": 0.1}),
                   "fut": loop.create_future(),
                   "enqueued_at": time.monotonic() - 60.0}
        raylet._admission_queue.extend([blocker, stalled])
        injected.extend([blocker, stalled])

    node.services_loop.run_sync(_inject())
    try:
        events = _wait_for(
            lambda: state.list_errors(error_type="lease_wedge", limit=1000),
            timeout=20.0, interval=0.2)
        assert events, "lease-wedge watchdog never fired"
        e = events[-1]
        assert e["source"] == "raylet"
        assert "pending" in e["message"] and "free" in e["message"]
        snap = e["extra"]["debug_state"]
        assert snap["lease_queue_depth"] >= 2
        assert any(q["age_s"] >= 0.5 for q in snap["lease_queue"])
        assert snap["wedge_events_total"] >= 1
    finally:
        async def _cleanup():
            for entry in injected:
                if entry in raylet._admission_queue:
                    raylet._admission_queue.remove(entry)
                if not entry["fut"].done():
                    entry["fut"].cancel()

        node.services_loop.run_sync(_cleanup())
        cfg.lease_wedge_threshold_s = old_thr
        cfg.lease_wedge_check_interval_s = old_int


def test_lease_wedge_classification_robust_to_stale_leases():
    """Back-to-back-cluster regression (test_core_throughput then this
    file): an un-acked lease strand from a PREVIOUS workload being
    orphan-reclaimed mid-test must not re-classify a queue entry that
    could be granted from the free pool as "blocked behind an orphaned
    lease grant" — that message is reserved for a head the reclaim
    actually unblocks; a satisfiable entry keeps the watchdog's own
    "matching resources are free" report."""
    import asyncio

    from ray_tpu.core import api as core_api
    from ray_tpu.core.config import get_config
    from ray_tpu.core.resources import ResourceSet
    from ray_tpu import chaos as _chaos  # noqa: F401 (chaos clock import path)
    from ray_tpu.chaos import clock as chaos_clock

    cfg = get_config()
    saved = (cfg.lease_wedge_threshold_s, cfg.lease_wedge_check_interval_s,
             cfg.lease_orphan_timeout_s)
    cfg.lease_wedge_threshold_s = 0.5
    cfg.lease_wedge_check_interval_s = 0.2
    cfg.lease_orphan_timeout_s = 1.0
    node = core_api._node
    raylet = node.raylet

    # a couple of idle workers to lease without acking (the strand)
    @ray_tpu.remote
    def wedge_warm():
        return None

    ray_tpu.get([wedge_warm.remote() for _ in range(4)], timeout=60)
    time.sleep(0.3)
    injected = []
    strand = {}

    async def _inject():
        loop = asyncio.get_running_loop()
        spec = {"task_id": b"stale-strand", "name": "strand", "kind": 0,
                "resources": {"CPU": 1.0}, "max_retries": 1}
        reply = await raylet.handle_RequestWorkerLease({"spec": spec})
        assert reply.get("granted"), reply
        w = raylet._workers[reply["worker_id"]]
        w.lease_granted_at = chaos_clock.now() - 60.0  # long-stranded
        strand["worker_id"] = reply["worker_id"]
        # A satisfiable entry aged past the threshold: plenty of CPU is
        # still free, so its report must come from the watchdog loop.
        stalled = {"prio": 1, "seq": 10**9, "request": ResourceSet({"CPU": 0.37}),
                   "fut": loop.create_future(),
                   "enqueued_at": time.monotonic() - 60.0}
        raylet._admission_queue.append(stalled)
        injected.append(stalled)

    node.services_loop.run_sync(_inject())
    try:
        # the strand is reclaimed (two orphan-scan probes)...
        orphans = _wait_for(
            lambda: state.list_errors(error_type="lease_orphan", limit=1000),
            timeout=30.0, interval=0.2)
        assert orphans, "orphan reclaim never fired"
        # ...and every wedge report for the satisfiable entry names the
        # free resources; none blames the orphan for it.
        wedges = _wait_for(lambda: [
            e for e in state.list_errors(error_type="lease_wedge", limit=1000)
            if "0.37" in e.get("message", "")
        ], timeout=20.0, interval=0.2)
        assert wedges, "watchdog never reported the stalled entry"
        for e in wedges:
            assert "free" in e["message"], e["message"]
            assert "orphaned lease grant" not in e["message"], e["message"]
    finally:
        async def _cleanup():
            for entry in injected:
                if entry in raylet._admission_queue:
                    raylet._admission_queue.remove(entry)
                if not entry["fut"].done():
                    entry["fut"].cancel()

        node.services_loop.run_sync(_cleanup())
        (cfg.lease_wedge_threshold_s, cfg.lease_wedge_check_interval_s,
         cfg.lease_orphan_timeout_s) = saved


def test_debug_state_dumps_written():
    """Raylet and GCS periodically write debug_state_*.txt snapshots into
    the session dir (reference: raylet debug_state.txt dumps)."""
    from ray_tpu.core import api as core_api
    from ray_tpu.core.config import get_config

    cfg = get_config()
    old = cfg.debug_state_dump_interval_s
    cfg.debug_state_dump_interval_s = 0.3
    try:
        node = core_api._node
        raylet_path = os.path.join(
            node.session_dir,
            f"debug_state_{node.raylet.node_id.hex()[:12]}.txt")
        gcs_path = os.path.join(node.session_dir, "debug_state_gcs.txt")
        assert _wait_for(lambda: os.path.exists(raylet_path), timeout=15.0), \
            f"no raylet dump in {node.session_dir}: " \
            f"{glob.glob(os.path.join(node.session_dir, 'debug_state*'))}"
        assert _wait_for(lambda: os.path.exists(gcs_path), timeout=15.0)
        text = open(raylet_path).read()
        assert "lease_queue_depth" in text and "workers_by_state" in text
        gcs_text = open(gcs_path).read()
        assert "actors_by_state" in gcs_text and "nodes_by_state" in gcs_text
    finally:
        cfg.debug_state_dump_interval_s = old


def test_get_debug_state_rpc_and_cluster_diagnostics():
    """GetDebugState works over RPC on raylets AND the GCS, and
    ``state.cluster_diagnostics()`` aggregates both plus recent errors."""
    diag = state.cluster_diagnostics()
    assert diag["gcs"].get("nodes_by_state", {}).get("ALIVE", 0) >= 1
    nodes = [n for n in diag["nodes"] if "unreachable" not in n]
    assert nodes, diag["nodes"]
    for snap in nodes:
        assert "lease_queue_depth" in snap
        assert "workers_by_state" in snap
        assert "store" in snap and "capacity" in snap["store"]
    assert isinstance(diag["errors"], list)


def test_serve_replica_failure_surfaces(capfd):
    """A replica whose constructor raises: the exception text reaches the
    controller's 'failed to start' log line, the app status dict, and
    list_errors() — no more cause-less replica failures."""
    from ray_tpu import serve

    @serve.deployment(num_replicas=1)
    class BrokenReplica:
        def __init__(self):
            raise RuntimeError("replica init exploded")

    serve.run(BrokenReplica.bind(), name="brokenapp", route_prefix=None,
              _blocking=False)
    try:
        failure = _wait_for(
            lambda: (serve.status().get("brokenapp", {})
                     .get("BrokenReplica", {}) or {}).get("last_start_failure"),
            timeout=60.0)
        assert failure and "replica init exploded" in failure, failure

        # the error-info channel carries the replica's own traceback
        events = _wait_for(lambda: [
            e for e in state.list_errors(error_type="replica_start_failure",
                                         limit=1000)
            if "replica init exploded" in (e.get("traceback") or "")
            or "replica init exploded" in (e.get("message") or "")
        ])
        assert events, "replica failure never reached list_errors()"
        sources = {e["source"] for e in events}
        assert "serve_replica" in sources or "serve_controller" in sources

        # the controller's log line (streamed to the driver) names the cause
        seen = ""
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            seen += capfd.readouterr().err
            if "failed to start" in seen and "replica init exploded" in seen:
                break
            time.sleep(0.25)
        assert "failed to start" in seen and "replica init exploded" in seen
    finally:
        try:
            serve.delete("brokenapp")
        except Exception:
            pass


def test_cli_doctor(capsys):
    """``ray_tpu doctor`` prints per-node lease-queue depth + recent
    errors (the health-check / status CLI surface)."""
    from ray_tpu.cli import main

    assert main(["doctor"]) == 0
    out = capsys.readouterr().out
    assert "LEASE_QUEUE" in out  # per-node queue-depth column
    assert "recent errors" in out
    assert "GCS:" in out
