"""Actor tests. Mirrors reference ``python/ray/tests/test_actor.py`` basics."""

import pytest

import ray_tpu


@pytest.fixture(autouse=True)
def _cluster(ray_cluster):
    yield


@ray_tpu.remote
class Counter:
    def __init__(self, start=0):
        self.n = start

    def incr(self, k=1):
        self.n += k
        return self.n

    def read(self):
        return self.n


def test_actor_create_and_call():
    c = Counter.remote()
    assert ray_tpu.get(c.incr.remote(), timeout=60) == 1
    assert ray_tpu.get(c.incr.remote(5), timeout=60) == 6


def test_actor_ordering():
    c = Counter.remote()
    refs = [c.incr.remote() for _ in range(20)]
    assert ray_tpu.get(refs, timeout=60) == list(range(1, 21))


def test_actor_constructor_args():
    c = Counter.remote(100)
    assert ray_tpu.get(c.read.remote(), timeout=60) == 100


def test_two_actors_independent():
    a, b = Counter.remote(), Counter.remote()
    ray_tpu.get([a.incr.remote(), a.incr.remote(), b.incr.remote()], timeout=60)
    assert ray_tpu.get(a.read.remote(), timeout=60) == 2
    assert ray_tpu.get(b.read.remote(), timeout=60) == 1


def test_named_actor():
    # the creator's handle must stay alive: non-detached named actors are
    # GC'd with their creator's handles (reference actor.py lifetime rules)
    creator_handle = Counter.options(name="test_named_counter").remote(7)
    h = ray_tpu.get_actor("test_named_counter")
    assert ray_tpu.get(h.read.remote(), timeout=60) == 7
    del creator_handle


def test_named_actor_gc_on_handle_drop():
    Counter.options(name="test_named_gc").remote(1)
    import gc, time

    gc.collect()
    # death removes the name from the GCS registry → get_actor raises
    for _ in range(100):
        try:
            ray_tpu.get_actor("test_named_gc")
        except ValueError:
            break
        time.sleep(0.1)
    else:
        raise AssertionError("named actor not reclaimed after handle drop")


def test_actor_handle_passing():
    c = Counter.remote()

    @ray_tpu.remote
    def use(handle):
        return ray_tpu.get(handle.incr.remote(10), timeout=30)

    assert ray_tpu.get(use.remote(c), timeout=60) == 10
    assert ray_tpu.get(c.read.remote(), timeout=60) == 10


def test_actor_method_error():
    @ray_tpu.remote
    class Bad:
        def fail(self):
            raise RuntimeError("actor method error")

    b = Bad.remote()
    with pytest.raises(RuntimeError):
        ray_tpu.get(b.fail.remote(), timeout=60)


def test_kill_actor():
    c = Counter.remote()
    ray_tpu.get(c.incr.remote(), timeout=60)
    ray_tpu.kill(c)
    import time

    with pytest.raises(ray_tpu.exceptions.RayTpuError):
        for _ in range(50):
            ray_tpu.get(c.incr.remote(), timeout=30)
            time.sleep(0.1)


def test_concurrency_groups(ray_cluster):
    """Named per-method concurrency pools (reference
    concurrency_group_manager.cc): an "io" group with 2 permits runs two
    io calls concurrently while the default pool (max_concurrency=1)
    stays serialized, and groups never contend with each other."""
    import time

    import ray_tpu

    @ray_tpu.remote(concurrency_groups={"io": 2, "compute": 1})
    class Worker:
        def __init__(self):
            self.active = {"io": 0, "default": 0}
            self.peak = {"io": 0, "default": 0}
            import threading

            self.lock = threading.Lock()

        def _enter(self, group):
            with self.lock:
                self.active[group] += 1
                self.peak[group] = max(self.peak[group], self.active[group])

        def _exit(self, group):
            with self.lock:
                self.active[group] -= 1

        @ray_tpu.method(concurrency_group="io")
        def io_call(self):
            self._enter("io")
            time.sleep(0.4)
            self._exit("io")
            return "io"

        def default_call(self):
            self._enter("default")
            time.sleep(0.2)
            self._exit("default")
            return "d"

        def peaks(self):
            return dict(self.peak)

    w = Worker.remote()
    t0 = time.monotonic()
    refs = [w.io_call.remote() for _ in range(4)]
    refs += [w.default_call.remote() for _ in range(2)]
    out = ray_tpu.get(refs, timeout=120)
    wall = time.monotonic() - t0
    assert out == ["io"] * 4 + ["d"] * 2
    peaks = ray_tpu.get(w.peaks.remote(), timeout=60)
    # The peak counters are the precise check: the io pool reached
    # exactly its 2 permits while the default pool stayed serialized.
    # (No wall-clock assertion: dispatch overhead on the 1-core CI host
    # dwarfs the 0.4s sleeps.)
    assert peaks["io"] == 2, peaks
    assert peaks["default"] == 1, peaks
    del wall

    # call-time group override routes into the io pool
    r = w.default_call.options(concurrency_group="io").remote()
    assert ray_tpu.get(r, timeout=60) == "d"
