"""Actor tests. Mirrors reference ``python/ray/tests/test_actor.py`` basics."""

import pytest

import ray_tpu


@pytest.fixture(autouse=True)
def _cluster(ray_cluster):
    yield


@ray_tpu.remote
class Counter:
    def __init__(self, start=0):
        self.n = start

    def incr(self, k=1):
        self.n += k
        return self.n

    def read(self):
        return self.n


def test_actor_create_and_call():
    c = Counter.remote()
    assert ray_tpu.get(c.incr.remote(), timeout=60) == 1
    assert ray_tpu.get(c.incr.remote(5), timeout=60) == 6


def test_actor_ordering():
    c = Counter.remote()
    refs = [c.incr.remote() for _ in range(20)]
    assert ray_tpu.get(refs, timeout=60) == list(range(1, 21))


def test_actor_constructor_args():
    c = Counter.remote(100)
    assert ray_tpu.get(c.read.remote(), timeout=60) == 100


def test_two_actors_independent():
    a, b = Counter.remote(), Counter.remote()
    ray_tpu.get([a.incr.remote(), a.incr.remote(), b.incr.remote()], timeout=60)
    assert ray_tpu.get(a.read.remote(), timeout=60) == 2
    assert ray_tpu.get(b.read.remote(), timeout=60) == 1


def test_named_actor():
    # the creator's handle must stay alive: non-detached named actors are
    # GC'd with their creator's handles (reference actor.py lifetime rules)
    creator_handle = Counter.options(name="test_named_counter").remote(7)
    h = ray_tpu.get_actor("test_named_counter")
    assert ray_tpu.get(h.read.remote(), timeout=60) == 7
    del creator_handle


def test_named_actor_gc_on_handle_drop():
    Counter.options(name="test_named_gc").remote(1)
    import gc, time

    gc.collect()
    # death removes the name from the GCS registry → get_actor raises
    for _ in range(100):
        try:
            ray_tpu.get_actor("test_named_gc")
        except ValueError:
            break
        time.sleep(0.1)
    else:
        raise AssertionError("named actor not reclaimed after handle drop")


def test_actor_handle_passing():
    c = Counter.remote()

    @ray_tpu.remote
    def use(handle):
        return ray_tpu.get(handle.incr.remote(10), timeout=30)

    assert ray_tpu.get(use.remote(c), timeout=60) == 10
    assert ray_tpu.get(c.read.remote(), timeout=60) == 10


def test_actor_method_error():
    @ray_tpu.remote
    class Bad:
        def fail(self):
            raise RuntimeError("actor method error")

    b = Bad.remote()
    with pytest.raises(RuntimeError):
        ray_tpu.get(b.fail.remote(), timeout=60)


def test_kill_actor():
    c = Counter.remote()
    ray_tpu.get(c.incr.remote(), timeout=60)
    ray_tpu.kill(c)
    import time

    with pytest.raises(ray_tpu.exceptions.RayTpuError):
        for _ in range(50):
            ray_tpu.get(c.incr.remote(), timeout=30)
            time.sleep(0.1)
