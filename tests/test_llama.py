"""Flagship model: forward shape/grad sanity and sharded train-step compile
on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np

from conftest import requires_shard_map
from ray_tpu.models import LlamaConfig, PRESETS, forward, init_params, loss_fn, param_axes
from ray_tpu.parallel import MeshConfig, create_mesh
from ray_tpu.parallel.sharding import shard_params


def test_forward_shapes_and_finite():
    cfg = PRESETS["debug"]
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    logits = forward(params, tokens, cfg)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert bool(jnp.isfinite(logits).all())


def test_loss_decreases_under_sgd():
    cfg = LlamaConfig(vocab_size=64, hidden=32, n_layers=2, n_heads=2,
                      n_kv_heads=1, intermediate=64, head_dim=16,
                      dtype=jnp.float32, attn_impl="reference", remat=False)
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab_size)
    batch = {"tokens": tokens}

    @jax.jit
    def step(p):
        l, g = jax.value_and_grad(lambda p_: loss_fn(p_, batch, cfg))(p)
        return l, jax.tree.map(lambda a, b: a - 0.5 * b, p, g)

    l0, params = step(params)
    for _ in range(5):
        l1, params = step(params)
    assert float(l1) < float(l0)


def test_sharded_train_step_on_mesh():
    """DP×TP×SP sharded loss+grad compiles and runs on the CPU mesh."""
    mesh = create_mesh(MeshConfig(dp=2, tp=2, sp=2))
    cfg = PRESETS["debug-128"]
    params = init_params(cfg, jax.random.PRNGKey(0))
    params = shard_params(params, param_axes(cfg), mesh)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab_size)

    @jax.jit
    def step(p, toks):
        return jax.value_and_grad(
            lambda p_: loss_fn(p_, {"tokens": toks}, cfg, mesh=mesh)
        )(p)

    loss, grads = step(params, tokens)
    assert np.isfinite(float(loss))
    flat, _ = jax.tree.flatten(grads)
    assert all(bool(jnp.isfinite(g).all()) for g in flat)


@requires_shard_map
def test_ring_attention_model_matches_flash():
    mesh = create_mesh(MeshConfig(dp=2, sp=4))
    base = PRESETS["debug-128"]
    import dataclasses
    cfg_ring = dataclasses.replace(base, attn_impl="ring", dtype=jnp.float32)
    cfg_ref = dataclasses.replace(base, attn_impl="reference", dtype=jnp.float32)
    params = init_params(cfg_ref, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, base.vocab_size)
    ref = forward(params, tokens, cfg_ref)
    ring = forward(params, tokens, cfg_ring, mesh=mesh)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(ref), atol=1e-4, rtol=1e-4)
