"""Autoscaler reconciler: scale-up from demand, floors, idle scale-down.

Reference: ``python/ray/autoscaler/v2/scheduler.py:624`` and
``autoscaler/v2/tests/test_scheduler.py`` style — but end-to-end: the
LocalNodeProvider launches REAL raylets that join the GCS and run the
queued work.
"""

import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import Autoscaler, LocalNodeProvider, NodeTypeConfig
from ray_tpu.autoscaler.sdk import REQUEST_KEY
from ray_tpu.cluster_utils import Cluster


class _FakeProvider:
    def __init__(self):
        self.launched = []
        self.terminated = []

    def create_node(self, node_type, resources):
        self.launched.append(node_type)
        return f"i-{len(self.launched)}"

    def terminate_node(self, iid):
        self.terminated.append(iid)

    def non_terminated_nodes(self):
        return {f"i-{i+1}": t for i, t in enumerate(self.launched)
                if f"i-{i+1}" not in self.terminated}

    def node_id_of(self, iid):
        return None


def test_reconcile_unit_launches_for_unmet_demand():
    """Pure decision logic: pending shape with no capacity -> launch the
    smallest fitting type, respecting max_workers."""
    nodes = [{
        "node_id": "a", "state": "ALIVE",
        "resources": {"available": {"CPU": 0.0}, "total": {"CPU": 1.0}},
        "pending_demand": [{"shape": {"CPU": 2.0}, "count": 3}],
    }]

    def gcs_call(method, payload):
        if method == "GetAllNodes":
            return {"nodes": nodes}
        if method == "ListPlacementGroups":
            return {"placement_groups": []}
        if method == "KvGet":
            return {"value": None}
        raise AssertionError(method)

    provider = _FakeProvider()
    scaler = Autoscaler(
        gcs_call, provider,
        [NodeTypeConfig("small", {"CPU": 2.0}, max_workers=2),
         NodeTypeConfig("big", {"CPU": 8.0}, max_workers=1)],
        launch_cooldown_s=0.0,
    )
    decision = scaler.reconcile_once()
    # 3x CPU:2 demand -> two "small" (cap) then one "big" absorbs the rest.
    assert decision.launch == ["small", "small", "big"]
    assert provider.launched == ["small", "small", "big"]


@pytest.fixture()
def scaling_cluster():
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    c = Cluster(
        initialize_head=True,
        head_node_args={"num_cpus": 1},
        _system_config={"health_check_failure_threshold": 5},
    )
    ray_tpu.init(address=c.address, num_cpus=0)
    provider = LocalNodeProvider(c)

    def gcs_call(method, payload):
        return c._loop.run_sync(getattr(c.gcs, f"handle_{method}")(payload))

    yield c, provider, gcs_call
    ray_tpu.shutdown()
    c.shutdown()


def test_scale_up_runs_infeasible_tasks_then_scales_down(scaling_cluster):
    """Tasks too big for any live node report demand via heartbeats; the
    reconciler launches fitting nodes, the tasks run there, and the nodes
    are terminated once idle."""
    c, provider, gcs_call = scaling_cluster
    scaler = Autoscaler(
        gcs_call, provider,
        [NodeTypeConfig("cpu-4", {"CPU": 4.0}, min_workers=0, max_workers=2)],
        idle_timeout_s=2.0, launch_cooldown_s=0.5,
    )
    scaler.start(period_s=0.5)
    try:

        @ray_tpu.remote(resources={"CPU": 4.0})
        def heavy(i):
            return i * 10

        results = ray_tpu.get([heavy.remote(i) for i in range(3)], timeout=120)
        assert sorted(results) == [0, 10, 20]
        assert provider.non_terminated_nodes(), "autoscaler never launched a node"

        deadline = time.monotonic() + 40
        while provider.non_terminated_nodes() and time.monotonic() < deadline:
            time.sleep(0.5)
        assert not provider.non_terminated_nodes(), "idle nodes were not terminated"
    finally:
        scaler.stop()


def test_request_resources_floor(scaling_cluster):
    """An explicit capacity floor launches nodes with zero load, and
    clearing it lets them scale back down."""
    from ray_tpu.autoscaler import request_resources

    c, provider, gcs_call = scaling_cluster
    scaler = Autoscaler(
        gcs_call, provider,
        [NodeTypeConfig("cpu-2", {"CPU": 2.0}, max_workers=4)],
        idle_timeout_s=1.5, launch_cooldown_s=0.2,
    )
    scaler.start(period_s=0.4)
    try:
        request_resources([{"CPU": 2.0}, {"CPU": 2.0}])
        deadline = time.monotonic() + 30
        while len(provider.non_terminated_nodes()) < 2 and time.monotonic() < deadline:
            time.sleep(0.3)
        assert len(provider.non_terminated_nodes()) >= 2

        # Floor-held nodes must persist well past idle_timeout (no
        # launch/terminate churn while the floor stands).
        held = set(provider.non_terminated_nodes())
        time.sleep(3 * 1.5)
        assert held <= set(provider.non_terminated_nodes()), "floor nodes churned"

        request_resources([])  # clear the floor
        deadline = time.monotonic() + 40
        while provider.non_terminated_nodes() and time.monotonic() < deadline:
            time.sleep(0.5)
        assert not provider.non_terminated_nodes()
    finally:
        scaler.stop()
