"""Paged-attention v2 (staging-buffer) correctness.

Tier-1 (CPU) coverage for the kernel the TPU decode path defaults to:
the page pool is strictly READ-ONLY across a K-step fused dispatch,
tokens generated mid-dispatch accumulate in a small staging carry the
kernel folds into its online softmax, and ONE batched scatter commits
them back at the dispatch boundary (``ops/paged_attention.py``,
``llm/model.py::decode_loop``/``commit_staging``). The dense gather is
the numerical ground truth throughout.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.llm.engine import InferenceEngine, Request
from ray_tpu.llm.executor import resolve_attention_impl
from ray_tpu.models.llama import PRESETS, init_params
from conftest import HAS_SHARD_MAP, requires_shard_map


@pytest.fixture(scope="module")
def small_model():
    cfg = dataclasses.replace(PRESETS["debug"], dtype=jnp.float32,
                              attn_impl="reference")
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


# ------------------------------------------------------- kernel (staging)

def _dense_ref(q, kp, vp, bt, pos, page):
    n, kh, g, d = q.shape
    max_pages = bt.shape[1]
    gk = jnp.swapaxes(kp[bt], 1, 2).reshape(n, kh, -1, d)
    gv = jnp.swapaxes(vp[bt], 1, 2).reshape(n, kh, -1, d)
    live = jnp.arange(max_pages * page)[None] <= pos[:, None]
    s = jnp.einsum("nkgd,nktd->nkgt", q, gk).astype(jnp.float32) * d ** -0.5
    s = jnp.where(live[:, None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, -1).astype(q.dtype)
    return jnp.einsum("nkgt,nktd->nkgd", p, gv)


def test_kernel_staging_rows_fold_into_softmax():
    """Staged rows [0, stage_idx] must be attended exactly as if they
    lived in the pool — including pos == 0 (no pool context at all)."""
    from ray_tpu.ops.paged_attention import paged_decode_attention, stage_rows

    rng = np.random.default_rng(3)
    n, kh, g, d = 3, 2, 2, 32
    page, max_pages, pool = 16, 8, 32
    q = jnp.array(rng.standard_normal((n, kh, g, d)), jnp.float32)
    kp = jnp.array(rng.standard_normal((pool, kh, page, d)), jnp.float32)
    vp = jnp.array(rng.standard_normal((pool, kh, page, d)), jnp.float32)
    bt = jnp.array(rng.permutation(pool)[: n * max_pages].reshape(n, max_pages),
                   jnp.int32)
    # positions incl. a page-boundary crossing INSIDE the staged range
    # (pos 17 with stage_idx 2 -> staged rows span positions 15..17)
    pos = jnp.array([5, 17, 40], jnp.int32)
    si = 2
    ref = _dense_ref(q, kp, vp, bt, pos, page)

    # Move the last si+1 positions of each slot out of the pool and into
    # the staging rows; poison the vacated pool entries to prove the
    # kernel reads staging, not the pool, for those positions.
    sc = stage_rows(8)
    ks = jnp.zeros((1, n, kh, sc, d), jnp.float32)
    vs = jnp.zeros((1, n, kh, sc, d), jnp.float32)
    kp2, vp2 = kp, vp
    base = pos - si
    for j in range(si + 1):
        p_abs = base + j
        wp = jnp.take_along_axis(bt, (p_abs // page)[:, None], axis=1)[:, 0]
        ks = ks.at[0, :, :, j].set(kp[wp, :, p_abs % page])
        vs = vs.at[0, :, :, j].set(vp[wp, :, p_abs % page])
        kp2 = kp2.at[wp, :, p_abs % page].set(1e6)
        vp2 = vp2.at[wp, :, p_abs % page].set(1e6)
    out = paged_decode_attention(q, kp2, vp2, bt, pos, page_size=page,
                                 k_stage=ks, v_stage=vs,
                                 stage_idx=jnp.int32(si), interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=1e-4)

    # pos == 0: no pool block runs (m = -inf, l = 0); the normalize must
    # still produce exactly the staged row-0 value.
    out0 = paged_decode_attention(q, kp2, vp2, bt, jnp.zeros((n,), jnp.int32),
                                  page_size=page, k_stage=ks, v_stage=vs,
                                  stage_idx=jnp.int32(0), interpret=True)
    ref0 = jnp.broadcast_to(vs[0, :, :, 0][:, :, None, :], out0.shape)
    np.testing.assert_allclose(np.asarray(out0), np.asarray(ref0),
                               atol=2e-5, rtol=1e-4)


def test_stage_rows_padding():
    from ray_tpu.ops.paged_attention import stage_rows

    assert stage_rows(1) == 16
    assert stage_rows(16) == 16
    assert stage_rows(17) == 32
    assert stage_rows(32) == 32


# --------------------------------------------- decode_loop commit parity

def test_decode_loop_pool_commit_matches_dense(small_model):
    """After a K-step paged dispatch the pool must hold exactly what the
    dense path wrote step-by-step — the ONE batched commit scatter is the
    only pool write, and a SECOND dispatch decoding from that pool must
    stay token-identical (staging-carry wraparound across the K-step
    boundary: positions cross a page edge mid-dispatch)."""
    from ray_tpu.llm.model import decode_loop, init_pages

    cfg, params = small_model
    page, slots, max_pages = 8, 3, 6
    num_pages = slots + slots * max_pages
    pages0 = init_pages(cfg, num_pages, page)
    rng = np.random.default_rng(0)
    # pre-filled context: random K/V in the live prefix of each table
    pages0 = {k: jnp.array(rng.standard_normal(v.shape), jnp.float32)
              for k, v in pages0.items()}
    bt = np.arange(slots, slots + slots * max_pages,
                   dtype=np.int32).reshape(slots, max_pages)
    bt = jnp.asarray(bt)
    # mid-page, page-boundary, and deep positions; K=8 crosses a page
    # edge for every slot inside the dispatch
    pos = jnp.array([5, 8, 12], jnp.int32)
    tokens = jnp.array([3, 7, 11], jnp.int32)
    temps = jnp.zeros(slots, jnp.float32)
    eos = jnp.full(slots, -1, jnp.int32)
    remaining = jnp.full(slots, 100, jnp.int32)
    key = jax.random.PRNGKey(1)
    K = 8

    def run(paged, pages):
        return decode_loop(
            params, {k: v.copy() for k, v in pages.items()}, bt, tokens, pos,
            temps, eos, remaining, key, config=cfg, page_size=page,
            n_steps=K, paged=paged, live_pages=max_pages)

    toks_d, _, pages_d = run(False, pages0)
    toks_p, _, pages_p = run(True, pages0)
    assert np.array_equal(np.asarray(toks_d), np.asarray(toks_p))
    for name in ("k", "v"):
        np.testing.assert_allclose(np.asarray(pages_d[name]),
                                   np.asarray(pages_p[name]),
                                   atol=1e-5, rtol=1e-5)

    # dispatch 2 decodes FROM the committed pool — proves the commit is
    # what the next dispatch actually reads
    def run2(paged, pages, toks1):
        return decode_loop(
            params, pages, bt, toks1[-1], pos + K, temps, eos,
            remaining - K, jax.random.PRNGKey(2), config=cfg,
            page_size=page, n_steps=K, paged=paged, live_pages=max_pages)

    toks2_d, _, _ = run2(False, pages_d, toks_d)
    toks2_p, _, _ = run2(True, pages_p, toks_p)
    assert np.array_equal(np.asarray(toks2_d), np.asarray(toks2_p))


def test_decode_loop_eos_slots_commit_to_trash(small_model):
    """A slot finishing mid-dispatch must keep its pool pages frozen —
    its remaining staged rows commit to its private trash page."""
    from ray_tpu.llm.model import decode_loop, init_pages

    cfg, params = small_model
    page, slots, max_pages = 8, 2, 4
    pages0 = init_pages(cfg, slots + slots * max_pages, page)
    rng = np.random.default_rng(5)
    pages0 = {k: jnp.array(rng.standard_normal(v.shape), jnp.float32)
              for k, v in pages0.items()}
    bt = jnp.asarray(np.arange(slots, slots + slots * max_pages,
                               dtype=np.int32).reshape(slots, max_pages))
    pos = jnp.array([6, 6], jnp.int32)
    tokens = jnp.array([3, 7], jnp.int32)
    args = (jnp.zeros(slots, jnp.float32), jnp.full(slots, -1, jnp.int32))
    key = jax.random.PRNGKey(1)
    # slot 0 exhausts `remaining` after 2 steps; slot 1 keeps going
    remaining = jnp.array([2, 100], jnp.int32)
    toks_d, _, pages_d = decode_loop(
        params, {k: v.copy() for k, v in pages0.items()}, bt, tokens, pos,
        args[0], args[1], remaining, key, config=cfg, page_size=page,
        n_steps=6, paged=False, live_pages=max_pages)
    toks_p, _, pages_p = decode_loop(
        params, {k: v.copy() for k, v in pages0.items()}, bt, tokens, pos,
        args[0], args[1], remaining, key, config=cfg, page_size=page,
        n_steps=6, paged=True, live_pages=max_pages)
    # pre-EOS steps identical everywhere; the live slot identical to the
    # end (a done slot's surplus tokens are unspecified and discarded)
    assert np.array_equal(np.asarray(toks_d)[:2], np.asarray(toks_p)[:2])
    assert np.array_equal(np.asarray(toks_d)[:, 1], np.asarray(toks_p)[:, 1])
    for name in ("k", "v"):
        # real (non-trash) pages identical between the two paths
        np.testing.assert_allclose(np.asarray(pages_d[name])[:, slots:],
                                   np.asarray(pages_p[name])[:, slots:],
                                   atol=1e-5, rtol=1e-5)


# ------------------------------------------------- engine-level parity

def _run_engine(cfg, params, prompts, impl, *, K=8, page_size=8,
                max_new_tokens=6, max_len=64):
    eng = InferenceEngine(cfg, params, max_slots=max(4, len(prompts)),
                          max_len=max_len, page_size=page_size,
                          decode_steps_per_dispatch=K, attention_impl=impl)
    reqs = [Request(f"r{i}", list(p), max_new_tokens=max_new_tokens)
            for i, p in enumerate(prompts)]
    for r in reqs:
        eng.add_request(r)
    while any(not r.done for r in reqs):
        eng.step()
    return [r.generated for r in reqs]


def test_engine_greedy_parity_uniform(small_model):
    cfg, params = small_model
    prompts = [[1, 5, 9, 2], [2, 4, 6, 8], [3, 1, 4, 1], [9, 9, 9, 9]]
    assert (_run_engine(cfg, params, prompts, "paged")
            == _run_engine(cfg, params, prompts, "dense"))


def test_engine_greedy_parity_skewed(small_model):
    """The paged kernel's reason to exist: one long-context slot + many
    short ones in the same batch (the 1x8k + 7x256 shape, scaled to
    tier-1 sizes) must stay token-identical to dense."""
    cfg, params = small_model
    long = list(range(1, 49))             # 48 tokens: 6 pages at page 8
    shorts = [[7, 3], [2, 4, 6], [11, 13, 17, 19]]
    prompts = [long] + shorts
    assert (_run_engine(cfg, params, prompts, "paged", max_new_tokens=8)
            == _run_engine(cfg, params, prompts, "dense", max_new_tokens=8))


def test_engine_greedy_parity_stage_wraparound(small_model):
    """K=8 fused steps from a mid-page start: the staged rows cross the
    page boundary inside ONE dispatch and the commit lands them on two
    different pages; tokens must survive the K-step boundary into the
    next dispatch too (max_new_tokens > K)."""
    cfg, params = small_model
    prompts = [[1, 2, 3, 4, 5], [8, 6, 7]]   # decode starts at pos 5 / 3
    assert (_run_engine(cfg, params, prompts, "paged", K=8, max_new_tokens=12)
            == _run_engine(cfg, params, prompts, "dense", K=8, max_new_tokens=12))


# ------------------------------------------------- impl selection / tp

def test_resolve_attention_impl():
    """"auto" must pick the kernel exactly when a TPU backend is present —
    on EVERY mesh shape: round 8 lifted pure-pp, round 15 lifted the
    pp x tp composition (the decode loop flattens to one manual region
    over both axes), so no TPU mesh resolves dense anymore."""
    import types

    tp_mesh = types.SimpleNamespace(shape={"tp": 4, "dp": 1})
    pp_mesh = types.SimpleNamespace(shape={"pp": 2, "dp": 1})
    pp_tp_mesh = types.SimpleNamespace(shape={"pp": 2, "tp": 2})
    assert resolve_attention_impl("auto", backend="tpu") == "paged"
    assert resolve_attention_impl("auto", backend="axon") == "paged"
    assert resolve_attention_impl("auto", backend="cpu") == "dense"
    assert resolve_attention_impl("auto", backend="gpu") == "dense"
    assert resolve_attention_impl("auto", tp_mesh, backend="tpu") == "paged"
    # ROADMAP item 4 closed: pp meshes take the kernel too
    assert resolve_attention_impl("auto", pp_mesh, backend="tpu") == "paged"
    # ROADMAP item 6 closed: composed pp x tp takes the kernel too
    # (flattened {"pp","tp"} manual region — the round-8 residue)
    assert resolve_attention_impl("auto", pp_tp_mesh, backend="tpu") == "paged"
    # explicit choices pass through untouched
    assert resolve_attention_impl("dense", backend="tpu") == "dense"
    assert resolve_attention_impl("paged", backend="cpu") == "paged"
    with pytest.raises(ValueError, match="attention_impl"):
        resolve_attention_impl("fused")
    # this CPU test process must resolve to dense
    assert resolve_attention_impl() == "dense"


@requires_shard_map
def test_tensor_parallel_paged_parity(small_model):
    """attention_impl='paged' over a tp mesh (kernel shard_mapped over
    the KV-head axis) decodes token-identically to the single-device
    dense engine — the lifted mesh refusal of ROADMAP item 4."""
    from ray_tpu.parallel import MeshConfig, create_mesh

    cfg, params = small_model
    prompt = list(range(1, 22))
    expected = _run_engine(cfg, params, [prompt], "dense")[0]

    n = len(jax.devices())
    mesh = create_mesh(MeshConfig(tp=2, dp=max(1, n // 2)))
    eng = InferenceEngine(cfg, params, max_slots=2, max_len=64, page_size=8,
                          mesh=mesh, attention_impl="paged")
    assert eng.generate(list(prompt), max_new_tokens=6) == expected


@requires_shard_map
def test_pipeline_parallel_paged_parity(small_model):
    """attention_impl='paged' over a pp mesh: the v2 staging carry rides
    the pipeline tick loop (per-stage local-layer staging + one
    commit_staging per stage at the dispatch boundary) and must decode
    token-identically to the single-device dense engine — the second
    half of ROADMAP item 4's lifted mesh refusal. Covers multi-dispatch
    continuation (committed pool re-read by the next burst) and
    mid-flight EOS (trash-committed staging rows)."""
    from ray_tpu.parallel import MeshConfig, create_mesh

    cfg, params = small_model
    prompts = [[1, 5, 9], [2, 4, 6, 8, 10, 12, 14], list(range(1, 20)),
               [7, 3, 7]]
    expected = _run_engine(cfg, params, prompts, "dense", max_new_tokens=12)

    n = len(jax.devices())
    mesh = create_mesh(MeshConfig(pp=2, dp=max(1, n // 2)))
    eng = InferenceEngine(cfg, params, max_slots=4, max_len=64, page_size=8,
                          mesh=mesh, attention_impl="paged")
    reqs = [Request(f"r{i}", list(p), max_new_tokens=12)
            for i, p in enumerate(prompts)]
    for r in reqs:
        eng.add_request(r)
    while any(not r.done for r in reqs):
        eng.step()
    assert [r.generated for r in reqs] == expected


def test_decode_block_manual_tp_psum_parity(small_model):
    """The flattened pp×tp region's hand-written tp collectives
    (decode_block/_mlp ``tp_axis=``: psum after the row-parallel wo and
    w_down) must reproduce the unsharded block bit-for-bit in f32. Runs
    WITHOUT shard_map: ``jax.vmap(axis_name="tp")`` over hand-split
    KV-head/mlp shards gives the same manual-collective semantics, so
    the sandbox (jax 0.4.37) covers the math the composed-mesh parity
    test exercises end-to-end on the driver's jax."""
    from ray_tpu.llm.model import decode_block

    cfg, params = small_model
    tp = 2
    rng = np.random.default_rng(5)
    page, n, max_pages = 8, 3, 4
    pool = 32
    layer = {k: v[0] for k, v in params["layers"].items()}  # layer 0
    kf = jnp.array(rng.standard_normal(
        (1, pool, cfg.n_kv_heads, page, cfg.head_dim)), jnp.float32)
    vf = jnp.array(rng.standard_normal(kf.shape), jnp.float32)
    x = jnp.array(rng.standard_normal((n, 1, cfg.hidden)), jnp.float32)
    bt = jnp.array(rng.permutation(pool)[: n * max_pages].reshape(
        n, max_pages), jnp.int32)
    pos = jnp.array([5, 11, 17], jnp.int32)
    widx = jnp.take_along_axis(bt, (pos // page)[:, None], axis=1)[:, 0]
    l = jnp.int32(0)

    # Ground truth: the unsharded block.
    full_x2, full_kf, full_vf, _ = decode_block(
        x, layer, kf, vf, l, bt, pos, widx, cfg, page)

    # Hand-shard heads/mlp the way the manual region receives them.
    def split(a, axis):
        return jnp.stack(jnp.split(a, tp, axis=axis))

    layer_sh = {
        "attn_norm": layer["attn_norm"], "mlp_norm": layer["mlp_norm"],
        "wq": split(layer["wq"], 1), "wk": split(layer["wk"], 1),
        "wv": split(layer["wv"], 1), "wo": split(layer["wo"], 0),
        "w_gate": split(layer["w_gate"], 1),
        "w_up": split(layer["w_up"], 1),
        "w_down": split(layer["w_down"], 0),
    }
    kf_sh, vf_sh = split(kf, 2), split(vf, 2)

    def shard_block(layer_local, kf_l, vf_l):
        return decode_block(x, layer_local, kf_l, vf_l, l, bt, pos, widx,
                            cfg, page, tp_axis="tp")

    x2_sh, kf2_sh, vf2_sh, _ = jax.vmap(
        shard_block, axis_name="tp",
        in_axes=({"attn_norm": None, "mlp_norm": None, "wq": 0, "wk": 0,
                  "wv": 0, "wo": 0, "w_gate": 0, "w_up": 0, "w_down": 0},
                 0, 0))(layer_sh, kf_sh, vf_sh)

    # psum'd activations are replicated across shards and exact in f32
    np.testing.assert_allclose(np.asarray(x2_sh[0]), np.asarray(full_x2),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(x2_sh[0]),
                                  np.asarray(x2_sh[1]))
    # each shard wrote its local KV heads: concat == the unsharded pool
    np.testing.assert_array_equal(
        np.concatenate(list(np.asarray(kf2_sh)), axis=2),
        np.asarray(full_kf))
    np.testing.assert_array_equal(
        np.concatenate(list(np.asarray(vf2_sh)), axis=2),
        np.asarray(full_vf))


@requires_shard_map
def test_paged_composed_pp_tp_parity(small_model):
    """Round 15: the composed pp x tp mesh takes the kernel. The decode
    loop runs as ONE flattened manual region over {"pp","tp"} — pp
    manual on layers, tp manual on KV heads, Megatron psums after
    wo/w_down, tiled logits all_gather before sampling — and must stay
    greedy byte-identical to the single-device dense engine (the lifted
    round-8 residue: `resolve_attention_impl` no longer falls back dense
    on exactly the mesh shape a real v5p slice uses)."""
    from ray_tpu.parallel import MeshConfig, create_mesh

    cfg, params = small_model
    n = len(jax.devices())
    if n < 4:
        pytest.skip("needs 4 devices for a pp=2 x tp=2 mesh")
    prompts = [[1, 5, 9], [2, 4, 6, 8, 10, 12, 14], list(range(1, 20)),
               [7, 3, 7]]
    expected = _run_engine(cfg, params, prompts, "dense", max_new_tokens=12)

    mesh = create_mesh(MeshConfig(pp=2, tp=2, dp=max(1, n // 4)))
    eng = InferenceEngine(cfg, params, max_slots=4, max_len=64, page_size=8,
                          mesh=mesh, attention_impl="paged")
    assert eng.attention_impl == "paged"
    reqs = [Request(f"r{i}", list(p), max_new_tokens=12)
            for i, p in enumerate(prompts)]
    for r in reqs:
        eng.add_request(r)
    while any(not r.done for r in reqs):
        eng.step()
    assert [r.generated for r in reqs] == expected
