"""Overload protection (round 12): end-to-end request deadlines, bounded
queues with cost-aware load shedding, engine admission watermark, and
replica circuit breaking.

The regime under test is the millisecond one where offered load exceeds
capacity: the system must degrade gracefully — bounded TTFT for admitted
work, fast honest 503s (with Retry-After) for the rest, deadline
expiries that never burn engine capacity — instead of the classic
congestion collapse where every request's TTFT blows up together. The
chaos storm at the bottom must drain back to a RecoveryVerifier-green
state with page-pool refcounts at baseline.
"""

import dataclasses
import json
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.core.config import get_config
from ray_tpu.llm.engine import InferenceEngine, QueueFullError, Request
from ray_tpu.models.llama import PRESETS, forward, init_params
from ray_tpu.serve.router import DeadlineExceeded, RequestShed


@pytest.fixture(scope="module")
def small_model():
    cfg = dataclasses.replace(PRESETS["debug"], dtype=jnp.float32,
                              attn_impl="reference")
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def naive_greedy(params, cfg, prompt, n):
    toks, out = list(prompt), []
    for _ in range(n):
        logits = forward(params, jnp.asarray([toks]), cfg)[0, -1]
        t = int(jnp.argmax(logits))
        out.append(t)
        toks.append(t)
    return out


def _bare_router(replicas: dict[str, int]):
    """Router skeleton for overload-policy unit tests: real assign/
    release/shed/circuit logic, no controller or long-poll behind it."""
    from collections import OrderedDict

    from ray_tpu.serve.router import Router

    r = Router.__new__(Router)
    r._key = "replicas::app::dep"
    r._lock = threading.Lock()
    r._cond = threading.Condition(r._lock)
    r._replicas = {rid: {"actor": f"actor-{rid}", "max_ongoing": cap}
                   for rid, cap in replicas.items()}
    r._inflight = {rid: 0 for rid in replicas}
    r._model_affinity = {}
    r._group_affinity = OrderedDict()
    r.affinity_stats = {"hits": 0, "misses": 0, "spills": 0,
                        "new_groups": 0}
    r.spill_migrations = 0
    r._init_overload_state()
    return r


@pytest.fixture()
def overload_cfg():
    """Config sandbox: tests mutate the overload knobs freely."""
    cfg = get_config()
    saved = (cfg.serve_max_queued_requests, cfg.serve_shed_policy,
             cfg.serve_circuit_breaker_failures,
             cfg.serve_circuit_breaker_cooldown_s)
    yield cfg
    (cfg.serve_max_queued_requests, cfg.serve_shed_policy,
     cfg.serve_circuit_breaker_failures,
     cfg.serve_circuit_breaker_cooldown_s) = saved


# --------------------------------------------------------------- router units
def test_router_queue_bound_sheds_fast(overload_cfg):
    """ISSUE 12: over the router queue bound, the incoming request is
    shed with a FAST RequestShed (503 semantics) carrying a Retry-After,
    instead of joining an unbounded wait."""
    overload_cfg.serve_max_queued_requests = 2
    router = _bare_router({"r1": 1})
    router.assign_replica()  # saturate the single slot
    waiters, started = [], []

    def wait_one():
        started.append(1)
        try:
            waiters.append(router.assign_replica(timeout=10.0))
        except Exception as e:
            waiters.append(e)

    threads = [threading.Thread(target=wait_one, daemon=True)
               for _ in range(2)]
    for t in threads:
        t.start()
    deadline = time.monotonic() + 5
    while len(started) < 2 or router.overload_snapshot()["queued"] < 2:
        assert time.monotonic() < deadline, "waiters never queued"
        time.sleep(0.01)
    t0 = time.monotonic()
    with pytest.raises(RequestShed) as ei:
        router.assign_replica(timeout=10.0)
    fast_fail_ms = 1000 * (time.monotonic() - t0)
    assert fast_fail_ms < 100, f"shed took {fast_fail_ms:.0f}ms"
    assert ei.value.reason == "queue_full"
    assert ei.value.retry_after >= 1
    assert router.overload_snapshot()["shed"] == {"queue_full": 1}
    # free the slot: both queued waiters eventually get served (each
    # release lets exactly one through the 1-slot replica)
    router.release("r1")
    deadline = time.monotonic() + 10
    while sum(1 for w in waiters if isinstance(w, tuple)) < 1:
        assert time.monotonic() < deadline
        time.sleep(0.01)
    router.release("r1")
    for t in threads:
        t.join(timeout=10)
    assert sum(1 for w in waiters if isinstance(w, tuple)) == 2


def test_router_cost_aware_shed_prefers_cold(overload_cfg):
    """Cost-aware shedding: a request whose prefix group's KV is
    resident (cheap — small cold suffix) preempts a COLD waiter's queue
    slot; the cold waiter gets the fast 503, the cheap one is served."""
    overload_cfg.serve_max_queued_requests = 1
    overload_cfg.serve_shed_policy = "cost"
    router = _bare_router({"r1": 1})
    first, _ = router.assign_replica(prefix_group="sess:hot")  # maps group
    outcome = {}

    def cold_waiter():
        try:
            outcome["cold"] = router.assign_replica(timeout=10.0)
        except Exception as e:
            outcome["cold"] = e

    t_cold = threading.Thread(target=cold_waiter, daemon=True)
    t_cold.start()
    deadline = time.monotonic() + 5
    while router.overload_snapshot()["queued"] < 1:
        assert time.monotonic() < deadline
        time.sleep(0.01)

    def cheap_waiter():
        try:
            outcome["cheap"] = router.assign_replica(
                prefix_group="sess:hot", timeout=10.0)
        except Exception as e:
            outcome["cheap"] = e

    t_cheap = threading.Thread(target=cheap_waiter, daemon=True)
    t_cheap.start()
    t_cold.join(timeout=10)
    assert isinstance(outcome.get("cold"), RequestShed)
    assert outcome["cold"].reason == "preempted"
    router.release(first)
    t_cheap.join(timeout=10)
    assert isinstance(outcome.get("cheap"), tuple)
    shed = router.overload_snapshot()["shed"]
    assert shed.get("preempted") == 1
    # fifo policy: the incoming request sheds even when cheap
    overload_cfg.serve_shed_policy = "fifo"
    router2 = _bare_router({"r1": 1})
    router2.assign_replica(prefix_group="sess:h2")
    t = threading.Thread(
        target=lambda: router2.assign_replica(timeout=10.0), daemon=True)
    t.start()
    deadline = time.monotonic() + 5
    while router2.overload_snapshot()["queued"] < 1:
        assert time.monotonic() < deadline
        time.sleep(0.01)
    with pytest.raises(RequestShed):
        router2.assign_replica(prefix_group="sess:h2", timeout=10.0)
    router2.release("r1")
    t.join(timeout=10)


def test_router_deadline_expires_in_queue(overload_cfg):
    """A request whose deadline expires while WAITING in the router
    raises DeadlineExceeded (504 semantics) promptly and is counted."""
    router = _bare_router({"r1": 1})
    router.assign_replica()
    t0 = time.monotonic()
    with pytest.raises(DeadlineExceeded):
        router.assign_replica(timeout=30.0, deadline=time.time() + 0.3)
    assert time.monotonic() - t0 < 2.0
    assert router.overload_snapshot()["deadline_expired_queued"] == 1
    # an ALREADY-expired deadline fails without blocking at all
    with pytest.raises(DeadlineExceeded):
        router.assign_replica(timeout=30.0, deadline=time.time() - 1.0)


def test_circuit_breaker_open_half_open_close(overload_cfg):
    """ISSUE 12 circuit breaker: N consecutive handle timeouts open the
    replica's circuit (traffic reroutes), the cooldown admits ONE
    half-open probe, probe success closes, probe failure re-opens."""
    overload_cfg.serve_circuit_breaker_failures = 3
    overload_cfg.serve_circuit_breaker_cooldown_s = 0.2
    router = _bare_router({"bad": 4, "good": 4})
    # two timeouts: still closed (streak below N)
    router.note_request_failure("bad", timeout=True)
    router.note_request_failure("bad", timeout=True)
    assert router.circuit_state("bad") == "closed"
    # a success resets the streak
    router.note_request_success("bad")
    for _ in range(2):
        router.note_request_failure("bad", timeout=True)
    assert router.circuit_state("bad") == "closed"
    router.note_request_failure("bad", timeout=True)
    assert router.circuit_state("bad") == "open"
    assert router.overload_snapshot()["circuit_opens"] == 1
    # open: every assignment lands on the healthy replica
    for _ in range(6):
        rid, _a = router.assign_replica(timeout=1.0)
        assert rid == "good"
        router.release(rid)
    # non-timeout failures never trip the breaker
    router.note_request_failure("good", timeout=False)
    assert router.circuit_state("good") == "closed"
    # cooldown elapses -> half-open, ONE probe admitted at a time
    time.sleep(0.25)
    picks = set()
    a1 = router.assign_replica(timeout=1.0)  # may pick bad (the probe)
    picks.add(a1[0])
    if a1[0] == "bad":
        assert router.circuit_state("bad") == "half_open"
        # probe in flight: a second assignment must avoid the replica
        rid2, _ = router.assign_replica(timeout=1.0)
        assert rid2 == "good"
        router.release(rid2)
        # probe FAILS -> re-open immediately
        router.note_request_failure("bad", timeout=True)
        assert router.circuit_state("bad") == "open"
        router.release("bad")
        time.sleep(0.25)
    else:
        router.release(a1[0])
    # drive until the probe lands on bad, then let it SUCCEED
    deadline = time.monotonic() + 5
    while True:
        assert time.monotonic() < deadline
        rid, _a = router.assign_replica(timeout=1.0)
        if rid == "bad":
            router.note_request_success("bad")
            router.release("bad")
            break
        router.release(rid)
        time.sleep(0.05)
    assert router.circuit_state("bad") == "closed"
    snap = router.overload_snapshot()
    assert "bad" not in snap["circuit"]  # closed entries not reported


def test_all_replicas_circuit_open_sheds(overload_cfg):
    """When every replica's circuit is open (and still cooling), the
    request is shed immediately with reason circuit_open — queueing for
    a fleet of tripped replicas is the collapse we refuse."""
    overload_cfg.serve_circuit_breaker_failures = 1
    overload_cfg.serve_circuit_breaker_cooldown_s = 30.0
    router = _bare_router({"r1": 4, "r2": 4})
    router.note_request_failure("r1", timeout=True)
    router.note_request_failure("r2", timeout=True)
    t0 = time.monotonic()
    with pytest.raises(RequestShed) as ei:
        router.assign_replica(timeout=10.0)
    assert time.monotonic() - t0 < 1.0
    assert ei.value.reason == "circuit_open"


# --------------------------------------------------------------- engine units
def test_deadline_expiry_in_queue_never_reaches_engine(small_model):
    """ISSUE 12 deadline semantics: a request whose deadline expired
    while WAITING is settled by the sweep without a slot, a page, or a
    prefill chunk — it never touches the engine."""
    cfg, params = small_model
    eng = InferenceEngine(cfg, params, max_slots=2, max_len=64, page_size=8)
    free_before = len(eng.allocator.free)
    chunks_before = eng.metrics["prefill_chunks"]
    r = Request("dead", list(range(1, 20)), max_new_tokens=4,
                deadline=time.time() - 0.1)
    eng.add_request(r)
    events = eng.step()
    assert r.done and r.finish_reason == "deadline"
    assert [e for e in events if e["request_id"] == "dead"] == [
        {"request_id": "dead", "token": -1, "done": True,
         "finish_reason": "deadline"}]
    assert eng.metrics["deadline_expired_queued"] == 1
    assert eng.metrics["deadline_expired_running"] == 0
    assert eng.metrics["prefill_chunks"] == chunks_before
    assert len(eng.allocator.free) == free_before
    assert eng.pool_stats()["pinned"] == 0
    # a live request beside it is unaffected
    ok = Request("ok", list(range(1, 20)), max_new_tokens=4)
    eng.add_request(ok)
    while not ok.done:
        eng.step()
    assert ok.generated == naive_greedy(params, cfg, list(range(1, 20)), 4)


def test_deadline_mid_decode_aborts_and_frees_pages_same_tick(small_model):
    """A deadline that expires MID-DECODE aborts the slot the same tick:
    pages and pins return to the pool (accounting back to baseline), the
    stream gets a terminal 'deadline' event, and the freed capacity
    serves the next request."""
    cfg, params = small_model
    eng = InferenceEngine(cfg, params, max_slots=2, max_len=64, page_size=8,
                          decode_steps_per_dispatch=1)
    baseline = eng.pool_stats()
    prompt = list(range(1, 20))
    r = Request("mid", list(prompt), max_new_tokens=40)
    eng.add_request(r)
    # drive through prefill + a few decode ticks
    while r.slot < 0 or len(r.generated) < 2:
        eng.step()
    assert not r.done
    assert eng.pool_stats()["pinned"] > 0
    r.deadline = time.time() - 0.01
    events = eng.step()
    assert r.done and r.finish_reason == "deadline"
    assert any(e["request_id"] == "mid" and e["finish_reason"] == "deadline"
               for e in events)
    assert eng.metrics["deadline_expired_running"] == 1
    stats = eng.pool_stats()
    # Pages freed THIS tick: nothing pinned, no active slot; computed
    # pages enter the prefix cache (free + cached conserves the pool).
    assert stats["pinned"] == 0 and stats["active_slots"] == 0
    assert stats["free"] + stats["cached"] == \
        baseline["free"] + baseline["cached"]
    # byte parity for a follow-up that reuses the cached prefix
    b = Request("after", list(prompt), max_new_tokens=4)
    eng.add_request(b)
    while not b.done:
        eng.step()
    assert b.generated == naive_greedy(params, cfg, prompt, 4)


def test_deadline_mid_prefill_and_pending_first(small_model):
    """Expiry while chunk-prefilling (or awaiting the batched first
    sample) is a 'running' abort: retired, pages freed, handle dropped."""
    cfg, params = small_model
    eng = InferenceEngine(cfg, params, max_slots=2, max_len=64, page_size=8,
                          prefill_chunk_size=8)
    r = Request("pf", list(range(1, 30)), max_new_tokens=4)
    eng.add_request(r)
    eng.step()  # admit + first prefill chunk only (chunked)
    assert r.slot >= 0 and not r.done
    r.deadline = time.time() - 0.01
    eng.step()
    assert r.done and r.finish_reason == "deadline"
    assert eng.metrics["deadline_expired_running"] == 1
    assert eng.pool_stats()["pinned"] == 0
    assert eng.pool_stats()["active_slots"] == 0


def test_engine_queue_bound_sheds(small_model):
    """Per-replica bounded admission queue: over max_queued_requests,
    add_request sheds with QueueFullError (503 + Retry-After shape)."""
    cfg, params = small_model
    eng = InferenceEngine(cfg, params, max_slots=2, max_len=64, page_size=8,
                          max_queued_requests=2)
    for i in range(2):
        eng.add_request(Request(f"q{i}", [1, 2, 3], max_new_tokens=2))
    with pytest.raises(QueueFullError) as ei:
        eng.add_request(Request("q2", [1, 2, 3], max_new_tokens=2))
    assert ei.value.http_status.startswith("503")
    assert ei.value.retry_after >= 1
    assert eng.metrics["queue_rejects"] == 1
    # the bounded queue drains normally
    while eng.has_work:
        eng.step()
    assert eng.pool_stats()["pinned"] == 0


def test_admission_watermark_rejects_and_recovers(small_model):
    """Admission refuses (and counts) while free pages sit below the
    reserve — the request stays QUEUED, is never bounced to the client,
    and admits as soon as capacity frees."""
    cfg, params = small_model
    # Pool sized so one 24-token+growth request fits but two do not.
    eng = InferenceEngine(cfg, params, max_slots=2, max_len=64, page_size=8,
                          num_pages=8, enable_prefix_cache=False)
    a = Request("a", list(range(1, 25)), max_new_tokens=24)
    b = Request("b", list(range(30, 54)), max_new_tokens=24)
    eng.add_request(a)
    eng.add_request(b)
    eng.step()
    assert a.slot >= 0
    assert eng.metrics["admission_rejects"] >= 1
    with eng._lock:
        assert len(eng._waiting) == 1  # b queued, not failed
    assert not b.done
    while not a.done:
        eng.step()
    while not b.done:
        eng.step()
    assert b.finish_reason in ("length", "max_len", "stop")
    assert eng.pool_stats()["pinned"] == 0


def test_admission_watermark_reserve_pages(small_model):
    """A nonzero admission watermark holds back free-page headroom:
    admission that would dip into the reserve defers instead."""
    cfg, params = small_model
    eng = InferenceEngine(cfg, params, max_slots=2, max_len=64, page_size=8,
                          num_pages=8, enable_prefix_cache=False,
                          admission_watermark_pages=6)
    r = Request("w", list(range(1, 25)), max_new_tokens=24)  # needs 6 pages
    eng.add_request(r)
    eng.step()
    assert r.slot < 0 and not r.done  # 8 free - 6 needed < 6 reserve
    assert eng.metrics["admission_rejects"] >= 1
    eng.admission_watermark_pages = 0
    while not r.done:
        eng.step()
    assert eng.pool_stats()["pinned"] == 0


# ------------------------------------------------------------------- e2e http
@pytest.fixture()
def serve_instance(ray_cluster):
    yield
    serve.shutdown()


def _post(addr, path, body: dict, headers: dict | None = None,
          timeout: float = 60.0):
    """Returns (status_code_or_error_name, raw_body, headers)."""
    req = urllib.request.Request(
        addr + path, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            raw = r.read()
            return r.status, raw, dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)
    except Exception as e:
        return type(e).__name__, b"", {}


def test_deadline_rides_header_across_proxy_hop(serve_instance):
    """The x-raytpu-deadline-ms header stamped at ingress is visible to
    the user callable via serve.get_request_deadline(), absolute-clock."""

    @serve.deployment(num_replicas=1)
    class DeadlineEcho:
        def __call__(self, request):
            d = serve.get_request_deadline()
            return {"deadline": d, "now": time.time()}

    serve.run(DeadlineEcho.bind(), name="dl", route_prefix="/dl")
    addr = serve.http_address()
    status, raw, _h = _post(addr, "/dl", {},
                            headers={"x-raytpu-deadline-ms": "5000"})
    assert status == 200
    out = json.loads(raw)
    assert out["deadline"] is not None
    budget = out["deadline"] - out["now"]
    assert 1.0 < budget <= 5.5, budget
    # no header, no default -> no deadline
    status, raw, _h = _post(addr, "/dl", {})
    assert json.loads(raw)["deadline"] is None
    # a timeout_s body field works as the budget too
    status, raw, _h = _post(addr, "/dl", {"timeout_s": 3})
    out = json.loads(raw)
    assert out["deadline"] is not None and \
        0.5 < out["deadline"] - out["now"] <= 3.5
    serve.delete("dl")


def test_proxy_replica_death_returns_503_retry_after(serve_instance):
    """Satellite (b): when the routed replica is dead (retry path
    exhausted), the proxy answers 503 + Retry-After, not a bare 500."""

    @serve.deployment(num_replicas=1)
    class Pid:
        def __call__(self, request):
            import os

            return {"pid": os.getpid()}

    serve.run(Pid.bind(), name="die", route_prefix="/die")
    addr = serve.http_address()
    status, raw, _h = _post(addr, "/die", {})
    assert status == 200
    pid = json.loads(raw)["pid"]
    import os
    import signal

    os.kill(pid, signal.SIGKILL)
    # Until the controller replaces the replica, requests that land on
    # the corpse must see an honest 503 with Retry-After (and once the
    # replacement is up, 200 again).
    saw_503 = False
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        status, raw, headers = _post(addr, "/die", {}, timeout=30)
        if status == 503:
            saw_503 = True
            assert headers.get("Retry-After"), headers
            break
        if status == 200 and json.loads(raw)["pid"] != pid:
            break  # replaced before we caught the window — rerun the kill
        time.sleep(0.05)
    if not saw_503:
        # raced the replacement: kill again and catch the window
        status, raw, _h = _post(addr, "/die", {})
        os.kill(json.loads(raw)["pid"], signal.SIGKILL)
        status, raw, headers = _post(addr, "/die", {}, timeout=30)
        if status == 503:
            saw_503 = True
            assert headers.get("Retry-After"), headers
    assert saw_503, "replica death never surfaced as 503 + Retry-After"
    serve.delete("die")


def test_llm_engine_queue_shed_e2e_503(serve_instance):
    """Through the real proxy: a replica whose bounded engine queue is
    full sheds with 503 + Retry-After while admitted requests complete;
    serve.status() surfaces the shed/queue counters."""
    from ray_tpu.llm import build_llm_app

    serve.run(build_llm_app("debug-128", num_replicas=1, max_slots=1,
                            max_len=128, page_size=16,
                            prefill_chunk_size=32,
                            max_queued_requests=1,
                            max_ongoing_requests=32),
              name="shed", route_prefix="/shed")
    addr = serve.http_address()
    # warm the compile caches so the storm is about queueing, not XLA
    _post(addr, "/shed/v1/completions", {"prompt": "warm" * 10,
                                         "max_tokens": 2}, timeout=120)
    results = []
    lock = threading.Lock()

    def fire(i):
        status, _raw, headers = _post(
            addr, "/shed/v1/completions",
            {"prompt": f"storm {i}: " + "abcd" * 12, "max_tokens": 24,
             "stream": True},
            timeout=120)
        with lock:
            results.append((status, headers.get("Retry-After")))

    threads = [threading.Thread(target=fire, args=(i,), daemon=True)
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=150)
    statuses = [s for s, _ra in results]
    assert statuses.count(200) >= 1, results
    sheds = [(s, ra) for s, ra in results if s == 503]
    assert sheds, f"no 503 sheds under 8x concurrency on 1 slot: {results}"
    assert all(ra for _s, ra in sheds), "503 without Retry-After"
    # the engine-side counters reach serve.status() via the probe
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        st = serve.status()["shed"]["LLMDeployment"]
        if (st.get("overload") or {}).get("queue_rejects"):
            break
        time.sleep(0.5)
    assert (st.get("overload") or {}).get("queue_rejects", 0) >= 1, st
    serve.delete("shed")


def test_llm_deadline_e2e_504_and_mid_decode(serve_instance):
    """Deadline end to end through the proxy: a microscopic budget fails
    fast (504 from the router queue, or an SSE stream that ends with
    finish_reason 'deadline'), and the pool drains back to baseline."""
    from ray_tpu.llm import build_llm_app

    serve.run(build_llm_app("debug-128", num_replicas=1, max_slots=2,
                            max_len=128, page_size=16,
                            prefill_chunk_size=32,
                            max_ongoing_requests=16),
              name="dl-llm", route_prefix="/dlm")
    addr = serve.http_address()
    _post(addr, "/dlm/v1/completions", {"prompt": "warm" * 10,
                                        "max_tokens": 2}, timeout=120)
    # Tiny budget + long generation: the deadline expires mid-decode and
    # the stream ends with finish_reason "deadline" — or the request
    # fails fast before admission (504 from the router queue / 503 if
    # even the response head missed the budget).
    status, raw, _h = _post(
        addr, "/dlm/v1/completions",
        {"prompt": "deadline me " + "xyzw" * 10, "max_tokens": 64,
         "stream": True},
        headers={"x-raytpu-deadline-ms": "100"}, timeout=60)
    if status == 200:
        finishes = [json.loads(line[6:])["choices"][0].get("finish_reason")
                    for line in raw.decode().splitlines()
                    if line.startswith("data: ")
                    and line.strip() != "data: [DONE]"]
        assert finishes and finishes[-1] == "deadline", finishes
    else:
        assert status in (503, 504), (status, raw[:200])
    # engine settles: nothing pinned after the abort
    h = serve.get_app_handle("dl-llm")
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        stats = h.options(method_name="pool_stats").remote().result(
            timeout=30)
        if stats["pinned"] == 0 and stats["active_slots"] == 0:
            break
        time.sleep(0.2)
    assert stats["pinned"] == 0 and stats["active_slots"] == 0
    m = h.options(method_name="overload_stats").remote().result(timeout=30)
    assert m["deadline_expired_running"] + m["deadline_expired_queued"] >= 1
    serve.delete("dl-llm")


# ------------------------------------------------------------------ chaos
@pytest.mark.chaos
def test_overload_storm_chaos_recovers_green(ray_cluster):
    """ISSUE 12 acceptance: the overload chaos plan — a deterministic
    thundering-herd arrival schedule against an app with one DELAYED
    replica (the bundled overload-storm FaultPlan) — must leave the
    RecoveryVerifier green after the storm drains: no stuck requests,
    queues drained, page-pool refcounts at baseline after the mid-decode
    deadline aborts."""
    from ray_tpu import chaos as chaos_mod
    from ray_tpu.chaos.verifier import RecoveryVerifier
    from ray_tpu.llm import build_llm_app

    verifier = RecoveryVerifier(timeout_s=90)
    baseline = verifier.snapshot_baseline()
    serve.run(build_llm_app("debug-128", num_replicas=2, max_slots=2,
                            max_len=128, page_size=16,
                            prefill_chunk_size=32,
                            max_queued_requests=2,
                            max_ongoing_requests=16),
              name="overload", route_prefix="/ovl")
    addr = serve.http_address()

    def one(i, deadline_ms=None, max_tokens=24, timeout=120.0):
        headers = {"Content-Type": "application/json"}
        if deadline_ms:
            headers["x-raytpu-deadline-ms"] = str(deadline_ms)
        req = urllib.request.Request(
            addr + "/ovl/v1/completions",
            data=json.dumps({"prompt": f"storm {i}: " + "abcd" * 10,
                             "max_tokens": max_tokens,
                             "stream": True}).encode(),
            headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                body = r.read().decode()
                return 200, body
        except urllib.error.HTTPError as e:
            e.read()
            return e.code, ""
        except Exception as e:
            return type(e).__name__, ""

    # Warm both replicas' compile caches before the faults go in.
    warm = [threading.Thread(target=one, args=(f"w{i}",), daemon=True)
            for i in range(4)]
    for t in warm:
        t.start()
    for t in warm:
        t.join(timeout=150)

    # Install the plan in the driver AND inside every replica process —
    # the replica_delay fault fires where the handles execute.
    h = serve.get_app_handle("overload")
    router = h._get_router()
    deadline = time.monotonic() + 30
    while len(router._replicas) < 2:
        assert time.monotonic() < deadline
        time.sleep(0.1)

    def _install_in_replica(instance, seed):
        from ray_tpu import chaos as _c

        _c.install("overload-storm", seed, publish=False)
        return True

    def _uninstall_in_replica(instance):
        from ray_tpu import chaos as _c

        _c.uninstall()
        return True

    replicas = dict(router._replicas)
    for rid, r in replicas.items():
        assert ray_tpu.get(
            r["actor"].__ray_call__.remote(_install_in_replica, 0),
            timeout=60)
    chaos_mod.install("overload-storm", seed=0)
    statuses = []
    lock = threading.Lock()
    try:
        # Deterministic thundering herd: 3 bursts of 12 simultaneous
        # requests, each with a 1.5 s deadline, against 2 replicas x
        # (2 slots + 2 queued) with replica #2 stalling 400 ms per
        # handle — some complete, some shed 503, some expire 504 /
        # mid-decode.
        for burst in range(3):
            threads = []
            for i in range(12):
                t = threading.Thread(
                    target=lambda i=i: statuses.append(
                        one(f"b{burst}-{i}", deadline_ms=1500,
                            timeout=30.0)[0]),
                    daemon=True)
                threads.append(t)
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
    finally:
        chaos_mod.uninstall()
        for rid, r in replicas.items():
            try:
                ray_tpu.get(
                    r["actor"].__ray_call__.remote(_uninstall_in_replica),
                    timeout=60)
            except Exception:
                pass
    assert len(statuses) == 36
    assert statuses.count(200) >= 1, statuses
    # Every answer is HONEST: a completion, a fast 503 shed, or a 504
    # deadline — never a bare 500 or a client-side hang/timeout.
    assert all(s in (200, 503, 504) for s in statuses), statuses

    # ---- storm drains: every replica's pool back to baseline.
    deadline = time.monotonic() + 60
    pools = []
    while time.monotonic() < deadline:
        pools = [ray_tpu.get(r["actor"].handle_request.remote(
            "pool_stats", (), {}), timeout=30) for r in replicas.values()]
        if all(p["pinned"] == 0 and p["active_slots"] == 0
               and p["waiting"] == 0 and p["prefilling"] == 0
               for p in pools):
            break
        time.sleep(0.5)
    for p in pools:
        assert p["pinned"] == 0 and p["active_slots"] == 0, pools
        assert p["waiting"] == 0 and p["prefilling"] == 0, pools

    result = verifier.verify(baseline)
    assert result.ok, result.violations
    serve.shutdown()
