"""Tick-level stall attribution + per-request flight recorder (ISSUE 18).

Bounds tests for ``ray_tpu.observability.loop_recorder``: the stall ring
and request timeline are fixed-size, allocation-free on the hot path,
keep the newest-N with an ``overflowed`` flag when lapped, and the
engine dumps a breached request's timeline exactly once.
"""

import dataclasses
import time

import jax
import jax.numpy as jnp
import pytest

from ray_tpu.observability import loop_recorder
from ray_tpu.observability.loop_recorder import RequestTimeline, StallRing


def test_stall_ring_overflow_keeps_newest():
    ring = StallRing(capacity=8)
    assert not ring.overflowed
    for i in range(20):
        ring.record(float(i), 2.0 * i, 0.5)
    assert ring.ticks == 20
    assert ring.overflowed
    # drain caps at capacity and returns the NEWEST-N splits in order
    rows = ring.drain()
    assert len(rows) == 8
    assert [r[0] for r in rows] == [float(i) for i in range(12, 20)]
    # totals cover the full lifetime, not just the surviving window
    assert ring.totals_ms[loop_recorder.WAIT_UP] == sum(range(20))
    snap = ring.snapshot()
    assert snap["ticks"] == 20 and snap["overflowed"]
    assert abs(sum(snap["frac"].values()) - 1.0) < 0.01


def test_stall_ring_drain_is_incremental():
    ring = StallRing(capacity=16)
    for _ in range(5):
        ring.record(0.1, 0.8, 0.1)
    assert len(ring.drain()) == 5
    assert ring.drain() == []  # nothing new since the last flush
    for _ in range(3):
        ring.record(0.2, 0.7, 0.1)
    assert len(ring.drain()) == 3


def test_classify_stage_and_loop():
    compute = {"wait_up": 0.1, "compute": 0.8, "wait_down": 0.1}
    starved = {"wait_up": 0.7, "compute": 0.2, "wait_down": 0.1}
    backed = {"wait_up": 0.1, "compute": 0.2, "wait_down": 0.7}
    assert loop_recorder.classify_stage(compute, ticks=10) == "compute_bound"
    assert loop_recorder.classify_stage(starved, ticks=10) == "starved"
    assert loop_recorder.classify_stage(backed, ticks=10) == "backpressured"
    assert loop_recorder.classify_stage(None, ticks=0) == "idle"
    assert loop_recorder.classify_loop({
        "a": {"ticks": 10, "frac": starved},
        "b": {"ticks": 10, "frac": compute},
        "idle": {"ticks": 0, "frac": compute},
    }) == "b"


def test_stall_ring_registry_bounded():
    before = len(loop_recorder._rings)
    r1 = loop_recorder.get_stall_ring("loop-x", "s0", capacity=4)
    assert loop_recorder.get_stall_ring("loop-x", "s0") is r1
    r1.record(0.0, 1.0, 0.0)
    snaps = loop_recorder.stall_snapshots("loop-x")
    assert snaps["s0"]["ticks"] == 1
    # the registry never grows without bound (LRU-drops the oldest key)
    for i in range(loop_recorder._RINGS_MAX + 8):
        loop_recorder.get_stall_ring(f"loop-fill-{i}", "s")
    assert len(loop_recorder._rings) <= loop_recorder._RINGS_MAX
    assert before <= loop_recorder._RINGS_MAX


def test_request_timeline_overflow_keeps_newest_and_pins():
    tl = RequestTimeline(capacity=16)
    tl.add(loop_recorder.EV_ADMIT, 5, now=1.0)
    tl.add(loop_recorder.EV_PREFIX_HIT, 3, now=1.1)
    tl.add(loop_recorder.EV_FIRST_TOKEN, 5, now=1.2)
    for i in range(40):  # lap the ring with per-token events
        tl.add(loop_recorder.EV_TOKEN, i + 1, now=2.0 + i * 0.01)
    tl.add(loop_recorder.EV_RETIRE, 40, now=3.0)
    assert tl.overflowed
    payload = tl.to_payload()
    assert payload["overflowed"] and payload["n_events"] == 44
    assert payload["dropped"] == 44 - 16
    evs = payload["events"]
    # lapped pinned events are re-prepended so the story still opens at
    # admission; the tail keeps the newest events including the terminal
    names = [e["ev"] for e in evs]
    assert names[0] == "admit" and evs[0]["pinned"]
    assert "prefix_hit" in names[:3] and "first_token" in names[:3]
    assert names[-1] == "retire"
    # surviving window is newest-N: the last pre-retire token is present
    assert any(e["ev"] == "token" and e["v"] == 40 for e in evs)
    assert payload["start"] == 1.0 and payload["end"] == 3.0


def test_request_timeline_byte_budget_at_1k_requests():
    """1k concurrent always-on recorders stay within a ~1 MiB budget —
    the 'hundreds of bytes per request' claim, enforced."""
    timelines = [RequestTimeline() for _ in range(1000)]
    per = timelines[0].nbytes()
    assert per <= 1024, per  # each recorder: under 1 KiB of array storage
    assert sum(t.nbytes() for t in timelines) <= 1 << 20


def test_request_timeline_value_clamp_and_pin_cap():
    tl = RequestTimeline(capacity=8)
    tl.add(loop_recorder.EV_ADMIT, 2**40)  # out-of-range value clamps to 0
    assert tl.events()[0]["v"] == 0
    for _ in range(20):  # pinned mirror is capped, never grows unbounded
        tl.add(loop_recorder.EV_PREFIX_HIT, 1)
    assert len(tl._pinned) <= 8


@pytest.fixture(scope="module")
def small_model():
    from ray_tpu.models.llama import PRESETS, init_params

    cfg = dataclasses.replace(PRESETS["debug"], dtype=jnp.float32,
                              attn_impl="reference")
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def test_engine_dumps_timeline_once_per_request(small_model):
    from ray_tpu.llm.engine import InferenceEngine, Request

    cfg, params = small_model
    eng = InferenceEngine(cfg, params, max_slots=2, max_len=64)
    req = Request("dump-once", [1, 5, 9], max_new_tokens=4)
    eng.add_request(req)
    while not req.done:
        eng.step()
    assert eng.dump_timeline(req, "test_breach") is True
    assert eng.dump_timeline(req, "test_breach") is False  # dump-once
    assert eng.metrics["timeline_dumps"] == 1
    rows = eng.breach_samples()
    assert len(rows) == 1 and rows[0]["request_id"] == "dump-once"
    assert rows[0]["reason"] == "test_breach"


def test_deadline_breach_yields_complete_timeline_via_cli(
        small_model, ray_cluster, capsys):
    """Acceptance: an injected deadline breach dumps a COMPLETE
    ``llm.request_timeline`` span — admission through expiry — and
    ``cli trace --request <id>`` retrieves it."""
    from ray_tpu.cli import main
    from ray_tpu.llm.engine import InferenceEngine, Request
    from ray_tpu.util import state

    cfg, params = small_model
    eng = InferenceEngine(cfg, params, max_slots=2, max_len=64)
    req = Request("breach-req", [2, 4, 6, 8], max_new_tokens=32,
                  deadline=time.time() + 0.25)
    eng.add_request(req)
    eng.step()            # admit + start prefill before the deadline hits
    time.sleep(0.3)       # injected stall pushes the request past it
    deadline = time.monotonic() + 10.0
    while not req.done and time.monotonic() < deadline:
        eng.step()
    assert req.finish_reason == "deadline"
    assert eng.metrics["timeline_dumps"] >= 1

    # connected engines route spans through the worker's task-event
    # flusher (~5s cadence); standalone ones land in the local buffer —
    # find_request_timeline checks both, so just poll.
    span, poll_deadline = None, time.monotonic() + 30.0
    while span is None and time.monotonic() < poll_deadline:
        span = state.find_request_timeline("breach-req")
        if span is None:
            time.sleep(0.5)
    assert span is not None, "llm.request_timeline dump never surfaced"
    names = [e["ev"] for e in span["attrs"]["events"]]
    assert names[0] == "admit"                # complete: opens at admission
    assert "deadline_expired" in names        # ... and records the expiry
    assert span["attrs"]["reason"] == "deadline"

    assert main(["trace", "--request", "breach-req"]) == 0
    out = capsys.readouterr().out
    assert "admit" in out and "deadline_expired" in out
    assert "breach-req" in out
    # unknown request id: non-zero exit, no traceback
    assert main(["trace", "--request", "no-such-request"]) != 0


def test_engine_shed_dumps_timeline(small_model):
    from ray_tpu.llm.engine import InferenceEngine, QueueFullError, Request

    cfg, params = small_model
    eng = InferenceEngine(cfg, params, max_slots=1, max_len=64,
                          max_queued_requests=1)
    eng.add_request(Request("q0", [1, 2, 3], max_new_tokens=4))
    before = eng.metrics["timeline_dumps"]
    with pytest.raises(QueueFullError):
        eng.add_request(Request("shed-me", [1, 2, 3], max_new_tokens=4))
    assert eng.metrics["timeline_dumps"] == before + 1
    rows = [r for r in eng.breach_samples() if r["request_id"] == "shed-me"]
    assert rows and rows[0]["reason"] == "shed_queue_full"
