"""Core API integration tests on a shared local cluster.

Mirrors the reference's ``python/ray/tests/test_basic.py`` family.
"""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture(autouse=True)
def _cluster(ray_cluster):
    yield


def test_simple_task():
    @ray_tpu.remote
    def f(x):
        return x + 1

    assert ray_tpu.get(f.remote(1), timeout=60) == 2


def test_task_chaining():
    @ray_tpu.remote
    def f(x):
        return x + 1

    ref = f.remote(0)
    for _ in range(4):
        ref = f.remote(ref)
    assert ray_tpu.get(ref, timeout=60) == 5


def test_put_get_roundtrip():
    for value in [1, "abc", {"k": [1, 2]}, None]:
        assert ray_tpu.get(ray_tpu.put(value), timeout=30) == value


def test_large_object_via_shm():
    arr = np.random.rand(500_000).astype(np.float32)
    ref = ray_tpu.put(arr)
    np.testing.assert_array_equal(ray_tpu.get(ref, timeout=30), arr)


def test_large_arg_and_return():
    @ray_tpu.remote
    def double(x):
        return x * 2

    arr = np.ones(500_000, dtype=np.float32)
    out = ray_tpu.get(double.remote(arr), timeout=60)
    np.testing.assert_array_equal(out, arr * 2)


def test_multiple_returns():
    @ray_tpu.remote(num_returns=2)
    def two():
        return 1, 2

    a, b = two.remote()
    assert ray_tpu.get([a, b], timeout=60) == [1, 2]


def test_kwargs():
    @ray_tpu.remote
    def f(a, b=0, c=0):
        return a + b + c

    assert ray_tpu.get(f.remote(1, c=5), timeout=60) == 6


def test_error_propagation():
    @ray_tpu.remote
    def boom():
        raise KeyError("missing")

    with pytest.raises(KeyError):
        ray_tpu.get(boom.remote(), timeout=60)


def test_error_type_preserved():
    @ray_tpu.remote
    def boom():
        raise ValueError("v")

    with pytest.raises(ray_tpu.RayTaskError):
        ray_tpu.get(boom.remote(), timeout=60)


def test_wait():
    @ray_tpu.remote
    def quick(i):
        return i

    refs = [quick.remote(i) for i in range(8)]
    ready, not_ready = ray_tpu.wait(refs, num_returns=8, timeout=60)
    assert len(ready) == 8 and not not_ready


def test_nested_tasks():
    @ray_tpu.remote
    def inner(x):
        return x * 10

    @ray_tpu.remote
    def outer(x):
        return ray_tpu.get(inner.remote(x), timeout=30) + 1

    assert ray_tpu.get(outer.remote(4), timeout=60) == 41


def test_ref_passed_to_task():
    @ray_tpu.remote
    def consume(x):
        return x + 1

    ref = ray_tpu.put(10)
    assert ray_tpu.get(consume.remote(ref), timeout=60) == 11


def test_cluster_resources():
    res = ray_tpu.cluster_resources()
    assert res.get("CPU", 0) >= 4


def test_runtime_env_env_vars():
    """Tasks with runtime_env={"env_vars"} run in workers started with
    those vars (reference: runtime_env plugin env_vars; worker_pool
    runtime-env-hash matching)."""
    import os

    @ray_tpu.remote
    def read_env():
        return os.environ.get("RAY_TPU_TEST_FLAVOR", "unset")

    assert ray_tpu.get(read_env.remote(), timeout=60) == "unset"
    tagged = read_env.options(runtime_env={"env_vars": {"RAY_TPU_TEST_FLAVOR": "special"}})
    assert ray_tpu.get(tagged.remote(), timeout=60) == "special"
    # default-env tasks must not land on the special worker
    assert ray_tpu.get(read_env.remote(), timeout=60) == "unset"


def test_runtime_env_actor():
    import os

    @ray_tpu.remote
    class EnvActor:
        def flavor(self):
            return os.environ.get("RAY_TPU_TEST_FLAVOR", "unset")

    a = EnvActor.options(runtime_env={"env_vars": {"RAY_TPU_TEST_FLAVOR": "actorenv"}}).remote()
    assert ray_tpu.get(a.flavor.remote(), timeout=60) == "actorenv"


def test_cancel_queued_running_and_force(ray_cluster):
    """ray_tpu.cancel (reference _private/worker.py:3086): a queued task
    fails with TaskCancelledError without running; a running task is
    interrupted at its next bytecode; force=True kills a hard-blocked
    worker — and a cancelled task is never retried."""
    import time

    import pytest as _pytest

    import ray_tpu
    from ray_tpu import TaskCancelledError

    # -- running task: interrupted at the next bytecode ------------------
    @ray_tpu.remote(max_retries=3)
    def spin():
        t0 = time.time()
        while time.time() - t0 < 60:
            sum(range(1000))  # plenty of bytecode boundaries
        return "finished"

    ref = spin.remote()
    time.sleep(2.0)  # let it lease + start
    ray_tpu.cancel(ref)
    with _pytest.raises(TaskCancelledError):
        ray_tpu.get(ref, timeout=60)

    # -- queued task: dropped before it ever runs ------------------------
    @ray_tpu.remote(num_cpus=0)
    class Gate:
        def __init__(self):
            self.started = 0
            self.open = False

        def arrive(self):
            self.started += 1

        def count(self):
            return self.started

        def release(self):
            self.open = True

        def is_open(self):
            return self.open

    gate = Gate.remote()
    n_cpus = int(ray_tpu.cluster_resources().get("CPU", 4))

    @ray_tpu.remote(num_cpus=1)
    def blocker(g):
        ray_tpu.get(g.arrive.remote(), timeout=60)
        while not ray_tpu.get(g.is_open.remote(), timeout=60):
            time.sleep(0.05)
        return "done"

    @ray_tpu.remote(num_cpus=1)
    def never():
        return "ran"

    # hold EVERY cpu; wait until all blockers are confirmed running
    blockers = [blocker.remote(gate) for _ in range(n_cpus)]
    # generous: worker cold-start under full-suite load on 1 core
    deadline = time.time() + 120
    while ray_tpu.get(gate.count.remote(), timeout=120) < n_cpus:
        assert time.time() < deadline, "blockers never started"
        time.sleep(0.05)
    queued = never.remote()   # no CPU free: must queue
    time.sleep(0.3)
    ray_tpu.cancel(queued)
    ray_tpu.get(gate.release.remote(), timeout=60)
    with _pytest.raises(TaskCancelledError):
        ray_tpu.get(queued, timeout=30)
    assert ray_tpu.get(blockers[0], timeout=60) == "done"

    # -- force: a worker hard-blocked in a C call dies, no retry ---------
    @ray_tpu.remote(max_retries=2)
    def hard_block():
        time.sleep(120)  # C-level block: async exc can't land
        return "never"

    ref = hard_block.remote()
    time.sleep(2.0)
    ray_tpu.cancel(ref, force=True)
    with _pytest.raises(TaskCancelledError):
        ray_tpu.get(ref, timeout=60)

    # put objects are not cancellable
    with _pytest.raises(ValueError, match="task returns"):
        ray_tpu.cancel(ray_tpu.put(1))
