"""Dashboard HTTP endpoints over the state API.

Reference surface: ``dashboard/modules/*`` REST endpoints (+ the
timeline download the reference serves via ``ray timeline``).
"""

import json
import urllib.request

import pytest

import ray_tpu
from ray_tpu.dashboard import start_dashboard, stop_dashboard


def _get(url):
    with urllib.request.urlopen(url, timeout=30) as r:
        return json.loads(r.read())


@pytest.fixture()
def dashboard(ray_cluster):
    url = start_dashboard()
    yield url
    stop_dashboard()


def test_dashboard_state_endpoints(dashboard):
    @ray_tpu.remote
    class Pinger:
        def ping(self):
            return "pong"

    p = Pinger.options(name="dash-actor").remote()
    assert ray_tpu.get(p.ping.remote(), timeout=60) == "pong"

    nodes = _get(dashboard + "/api/nodes")
    assert nodes and any(n["state"] == "ALIVE" for n in nodes)

    actors = _get(dashboard + "/api/actors")
    assert any(a.get("name") == "dash-actor" for a in actors)

    resources = _get(dashboard + "/api/cluster_resources")
    assert resources.get("CPU", 0) > 0

    tasks = _get(dashboard + "/api/tasks")
    assert isinstance(tasks, list)

    assert _get(dashboard + "/-/healthz") == "ok"


def test_dashboard_timeline_is_chrome_trace(dashboard):
    @ray_tpu.remote
    def traced():
        return 1

    assert ray_tpu.get(traced.remote(), timeout=60) == 1
    trace = _get(dashboard + "/api/timeline")
    events = trace if isinstance(trace, list) else trace.get("traceEvents", [])
    assert isinstance(events, list)


def test_dashboard_unknown_endpoint_404(dashboard):
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(dashboard + "/api/nope")
    assert e.value.code == 404


def test_dashboard_serves_spa(dashboard):
    """`/` serves the single-file UI (reference: dashboard/client/)."""
    with urllib.request.urlopen(dashboard + "/", timeout=30) as r:
        body = r.read().decode()
        ctype = r.headers.get("Content-Type", "")
    assert "text/html" in ctype
    assert "ray_tpu dashboard" in body
    # the SPA drives the same JSON API the tests above cover
    assert "/api/" in body and "overview" in body


def test_dashboard_framework_metrics_and_prometheus(dashboard):
    """GetMetrics synthesizes ray_tpu_* cluster gauges; /metrics renders
    the Prometheus exposition incl. histogram bucket families."""
    metrics = _get(dashboard + "/api/metrics")
    names = {m["name"] for m in metrics}
    assert "ray_tpu_nodes" in names
    assert "ray_tpu_resource_total" in names
    assert "ray_tpu_object_store_used_bytes" in names

    with urllib.request.urlopen(dashboard + "/metrics", timeout=30) as r:
        text = r.read().decode()
    assert "ray_tpu_nodes{" in text

    # histogram exposition: _bucket/_sum/_count with cumulative le
    from ray_tpu.util.metrics import prometheus_text

    hist = [{
        "name": "t_ms", "type": "histogram", "tags": {"d": "x"},
        "value": 12.0, "count": 3, "buckets": [1, 2, 0],
        "boundaries": [10, 100],
    }]
    text = prometheus_text(hist)
    assert 't_ms_bucket{d="x",le="10"} 1' in text
    assert 't_ms_bucket{d="x",le="100"} 3' in text
    assert 't_ms_bucket{d="x",le="+Inf"} 3' in text
    assert 't_ms_sum{d="x"} 12.0' in text
    assert 't_ms_count{d="x"} 3' in text


def test_dashboard_grafana_dashboard_json(dashboard):
    """The generated Grafana dashboard (reference
    grafana_dashboard_factory.py) is served and structurally sound."""
    d = _get(dashboard + "/api/grafana_dashboard")
    assert d["uid"] == "ray-tpu-default"
    assert len(d["panels"]) >= 10
    assert d["templating"]["list"][0]["name"] == "datasource"
    for p in d["panels"]:
        assert p["targets"], p["title"]
