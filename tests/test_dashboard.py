"""Dashboard HTTP endpoints over the state API.

Reference surface: ``dashboard/modules/*`` REST endpoints (+ the
timeline download the reference serves via ``ray timeline``).
"""

import json
import urllib.request

import pytest

import ray_tpu
from ray_tpu.dashboard import start_dashboard, stop_dashboard


def _get(url):
    with urllib.request.urlopen(url, timeout=30) as r:
        return json.loads(r.read())


@pytest.fixture()
def dashboard(ray_cluster):
    url = start_dashboard()
    yield url
    stop_dashboard()


def test_dashboard_state_endpoints(dashboard):
    @ray_tpu.remote
    class Pinger:
        def ping(self):
            return "pong"

    p = Pinger.options(name="dash-actor").remote()
    assert ray_tpu.get(p.ping.remote(), timeout=60) == "pong"

    nodes = _get(dashboard + "/api/nodes")
    assert nodes and any(n["state"] == "ALIVE" for n in nodes)

    actors = _get(dashboard + "/api/actors")
    assert any(a.get("name") == "dash-actor" for a in actors)

    resources = _get(dashboard + "/api/cluster_resources")
    assert resources.get("CPU", 0) > 0

    tasks = _get(dashboard + "/api/tasks")
    assert isinstance(tasks, list)

    assert _get(dashboard + "/-/healthz") == "ok"


def test_dashboard_timeline_is_chrome_trace(dashboard):
    @ray_tpu.remote
    def traced():
        return 1

    assert ray_tpu.get(traced.remote(), timeout=60) == 1
    trace = _get(dashboard + "/api/timeline")
    events = trace if isinstance(trace, list) else trace.get("traceEvents", [])
    assert isinstance(events, list)


def test_dashboard_unknown_endpoint_404(dashboard):
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(dashboard + "/api/nope")
    assert e.value.code == 404
