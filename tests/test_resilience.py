"""Elastic resilience (round 9): preemption-aware async checkpointing and
chaos-verified recovery.

The scenario production TPU users actually fear, made a measured event:
a spot slice preempted mid-train must resume from the latest
async-committed checkpoint (lag bounded by ``every_n_steps``, loss curve
continuous), and mid-serve traffic must re-route with zero failed client
requests — both through the REAL notice→drain→grace-kill path and
verified green by the chaos RecoveryVerifier.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import chaos
from ray_tpu.core.config import get_config
from ray_tpu.resilience import (
    AsyncCheckpointManager,
    latest_committed,
    latest_registered,
    list_committed,
    load_checkpoint,
)
from ray_tpu.train.checkpoint import load_pytree, save_pytree
from ray_tpu.util import state

pytestmark = pytest.mark.chaos


def _wait_for(predicate, timeout=30.0, interval=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        result = predicate()
        if result:
            return result
        time.sleep(interval)
    return predicate()


@pytest.fixture(autouse=True)
def _clean_resilience():
    """No chaos engine, no virtual clock, and touched config restored."""
    cfg = get_config()
    saved = {k: getattr(cfg, k) for k in (
        "preempt_grace_s", "health_check_period_ms",
        "worker_register_timeout_s")}
    yield
    from ray_tpu.core.rpc import set_chaos

    set_chaos(None)
    chaos.set_clock(None)
    for key, value in saved.items():
        setattr(cfg, key, value)


# ------------------------------------------------------- async ckpt unit layer
def test_async_checkpoint_commit_and_keep_k(tmp_path):
    """Commits are atomic dirs with markers; keep-K GC retains the newest
    K committed versions; load_checkpoint returns tree + meta."""
    root = str(tmp_path / "ck")
    mgr = AsyncCheckpointManager(root, keep_k=2, register_with_gcs=False)
    try:
        for step in range(5):
            mgr.save(step, {"step": step, "w": np.full(16, float(step))},
                     metrics={"loss": 1.0 / (1 + step)})
            assert mgr.wait(20), "writer never drained"
        committed = list_committed(root)
        assert [s for s, _ in committed] == [3, 4]  # keep_k=2, newest win
        tree, meta = load_checkpoint(committed[-1][1])
        assert tree["step"] == 4 and float(tree["w"][0]) == 4.0
        assert meta["step"] == 4 and meta["metrics"]["loss"] == pytest.approx(0.2)
        # no half-commit debris
        assert not [d for d in os.listdir(root) if d.startswith(".tmp-")]
    finally:
        mgr.close()


def test_async_checkpoint_save_never_blocks(tmp_path):
    """The acceptance bound: with a writer that takes 300 ms per commit,
    save() must return in snapshot time (latest-wins coalescing absorbs
    the backlog) — async save adds no per-step blocking."""
    from ray_tpu.train.checkpoint import save_pytree as _real_save

    def slow_write(tree, path):
        time.sleep(0.3)
        _real_save(tree, path)

    mgr = AsyncCheckpointManager(str(tmp_path / "ck"), keep_k=None,
                                 register_with_gcs=False, write_fn=slow_write)
    try:
        blocks = [mgr.save(step, {"step": step, "w": np.zeros(4096)})
                  for step in range(4)]
        # each save blocked only for the host snapshot, not the 300 ms write
        assert max(blocks) < 150.0, blocks
        assert mgr.wait(20)
        assert mgr.last_committed["step"] == 3  # freshest state won
        assert mgr.metrics["dropped"] >= 1      # backlog was coalesced
        assert mgr.metrics["commits"] + mgr.metrics["dropped"] == 4
    finally:
        mgr.close()


def test_async_checkpoint_crash_mid_commit_invisible(tmp_path):
    """A writer death mid-commit (partial payload, no marker) leaves the
    PREVIOUS committed version visible — never a corrupt one."""
    root = str(tmp_path / "ck")

    def write(tree, path):
        from ray_tpu.train.checkpoint import save_pytree as real

        if tree["step"] == 1:
            with open(os.path.join(path, "state.pkl"), "wb") as f:
                f.write(b"\x80\x04partial")  # half-written, then death
            raise RuntimeError("simulated mid-commit kill")
        real(tree, path)

    mgr = AsyncCheckpointManager(root, register_with_gcs=False, write_fn=write)
    try:
        mgr.save(0, {"step": 0})
        assert mgr.wait(20)
        mgr.save(1, {"step": 1})
        assert mgr.wait(20)
        assert mgr.metrics["commit_errors"] == 1
        latest = latest_committed(root)
        assert latest["step"] == 0  # the dead commit is invisible
        tree, _ = load_checkpoint(latest["path"])
        assert tree["step"] == 0
        assert not [d for d in os.listdir(root) if d.startswith(".tmp-")]
    finally:
        mgr.close()


def test_load_checkpoint_refuses_uncommitted(tmp_path):
    d = tmp_path / "ckpt_00000007"
    d.mkdir()
    save_pytree({"step": 7}, str(d))  # payload present, marker absent
    with pytest.raises(FileNotFoundError, match="COMMITTED"):
        load_checkpoint(str(d))
    assert latest_committed(str(tmp_path)) is None


def test_save_pytree_atomic_kill_mid_write(tmp_path, monkeypatch):
    """Satellite regression: a kill mid-``save_pytree`` must leave the
    previous version (or none) — before the tmp+fsync+rename fix a
    truncated .pkl unpickled a prefix without complaint."""
    import pickle
    import sys

    # Force the pickle fallback (the path the fix hardens) even where
    # orbax — which brings its own tmp+rename commit — is installed.
    monkeypatch.setitem(sys.modules, "orbax.checkpoint", None)

    path = str(tmp_path / "ck")
    save_pytree({"step": 1, "w": np.arange(8)}, path)

    def dying_dump(obj, f, *a, **k):
        f.write(b"\x80\x04half-a-frame")  # partial bytes, then the kill
        raise KeyboardInterrupt

    monkeypatch.setattr(pickle, "dump", dying_dump)
    with pytest.raises(KeyboardInterrupt):
        save_pytree({"step": 2, "w": np.arange(8)}, path)
    # previous version intact (load uses pickle.load, unaffected)
    tree = load_pytree(path)
    assert tree["step"] == 1
    # no stray tmp files to mistake for checkpoints
    assert [f for f in os.listdir(path) if not f.startswith("state.pkl.tmp")] \
        == ["state.pkl"]
    # a fresh dir whose FIRST save dies yields nothing loadable-looking
    path2 = str(tmp_path / "ck2")
    with pytest.raises(KeyboardInterrupt):
        save_pytree({"step": 9}, path2)
    with pytest.raises(FileNotFoundError):
        load_pytree(path2)


# ------------------------------------------------------------ GCS registration
def test_checkpoint_registered_with_gcs(ray_cluster, tmp_path):
    """Every commit registers with the GCS; latest_registered resolves the
    newest version from the control plane (no worker-node state)."""
    import uuid

    run = f"regtest-{uuid.uuid4().hex[:6]}"
    mgr = AsyncCheckpointManager(str(tmp_path / "reg"), run_name=run, keep_k=2)
    try:
        mgr.save(3, {"step": 3})
        assert mgr.wait(20)
        entry = _wait_for(lambda: latest_registered(run), timeout=10)
        assert entry and entry["step"] == 3
        assert os.path.exists(os.path.join(entry["path"], "COMMITTED"))
        mgr.save(5, {"step": 5})
        assert mgr.wait(20)
        entry = _wait_for(
            lambda: (latest_registered(run) or {}).get("step") == 5
            and latest_registered(run), timeout=10)
        assert entry["step"] == 5
    finally:
        mgr.close()


# --------------------------------------------------------- preemption plumbing
class _CallCountClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        self.t += 1.0
        return self.t


def test_preemption_notice_drains_raylet_and_elastic_sees_it(tmp_path):
    """The notice plumbing end to end on a live 2-node cluster: the
    draining raylet refuses leases, the GCS flags the node + publishes
    ``node_preempted``, available_resources drops the capacity, and the
    elastic policy downsizes only after its two-check debounce."""
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.train import ElasticScalingPolicy, ScalingConfig

    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 2},
                _system_config={"health_check_period_ms": 200})
    n2 = c.add_node(num_cpus=2)
    ray_tpu.init(address=c.address, num_cpus=0)
    try:
        scaling = ScalingConfig(num_workers=4, min_workers=1,
                                resources_per_worker={"CPU": 1})
        before = ElasticScalingPolicy(scaling, clock=_CallCountClock())
        assert before.group_size() == 4  # both nodes count

        # long grace: the node stays ALIVE+draining for the whole test
        c._loop.run_sync(n2.handle_PreemptionNotice(
            {"reason": "spot reclaim", "grace_s": 60.0}))
        assert _wait_for(
            lambda: any(n.get("draining") for n in state.list_nodes()),
            timeout=15), "draining flag never reached the node table"
        assert _wait_for(
            lambda: state.list_errors(error_type="node_preempted", limit=10),
            timeout=15), "node_preempted event never published"
        # capacity view: the draining node's CPUs are gone
        assert _wait_for(
            lambda: ray_tpu.available_resources().get("CPU", 0) <= 2.0,
            timeout=10)
        # draining raylet refuses a direct lease, loudly
        reply = c._loop.run_sync(n2.handle_RequestWorkerLease(
            {"spec": {"resources": {"CPU": 1.0}}, "grant_only_local": True}))
        assert not reply.get("granted") and not reply.get("spillback")
        assert "draining" in reply.get("reason", "")
        # elastic debounce: the shrunken target must hold two checks
        after = ElasticScalingPolicy(scaling, check_interval_s=1.0,
                                     clock=_CallCountClock())
        assert after.group_size(current=0) == 2
        assert after.monitor(0) is None     # first sighting: pending
        assert after.monitor(0) == 2        # held: resize decision
    finally:
        ray_tpu.shutdown()
        c.shutdown()


# ---------------------------------------------------------- acceptance: train
def test_preempt_slice_mid_train_resumes_from_async_ckpt(tmp_path):
    """THE acceptance scenario: a `preempt_slice` FaultPlan kills the
    training slice mid-run; the controller rebuilds on a replacement node
    and resumes from the latest GCS-registered async checkpoint with
    ``recovery_ckpt_lag_steps <= every_n_steps``, a continuous loss
    curve, and RecoveryVerifier green."""
    from ray_tpu.chaos.verifier import RecoveryVerifier
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.train import (CheckpointConfig, DataParallelTrainer,
                               FailureConfig, RunConfig, ScalingConfig)

    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 4},
                _system_config={"health_check_period_ms": 200,
                                "preempt_grace_s": 0.4})
    spot = c.add_node(num_cpus=2, resources={"spot_slice": 1.0})
    ray_tpu.init(address=c.address, num_cpus=0)
    every_n = 2
    run_name = "resil_train"
    try:
        verifier = RecoveryVerifier(timeout_s=60)
        baseline = verifier.snapshot_baseline()

        def train_fn(config):  # nested: cloudpickled by value to workers
            import time as _t

            import numpy as _np

            from ray_tpu import train as tr
            from ray_tpu.resilience import load_checkpoint as _load

            start = 0
            ck = tr.get_checkpoint()
            if ck is not None:
                tree, _meta = _load(ck.path)
                start = int(tree["step"]) + 1
            for step in range(start, config["steps"]):
                # deterministic loss: continuity is checkable post-resume
                tr.report({"step": step, "loss": 1.0 / (1.0 + step),
                           "resumed_from": start},
                          state={"step": step,
                                 "w": _np.full(256, float(step),
                                               dtype=_np.float32)})
                _t.sleep(config.get("sleep_s", 0.1))

        trainer = DataParallelTrainer(
            train_fn,
            train_loop_config={"steps": 30, "sleep_s": 0.1},
            scaling_config=ScalingConfig(
                num_workers=1,
                resources_per_worker={"CPU": 1.0, "spot_slice": 1.0}),
            run_config=RunConfig(
                name=run_name, storage_path=str(tmp_path),
                checkpoint_config=CheckpointConfig(
                    async_save=True, every_n_steps=every_n, num_to_keep=3),
                failure_config=FailureConfig(max_failures=3)),
        )
        box = {}
        t = threading.Thread(target=lambda: box.update(result=trainer.fit()))
        t.start()
        # wait until training is underway AND committed a checkpoint, so
        # the preemption provably lands MID-train
        assert _wait_for(lambda: latest_registered(run_name), timeout=60), \
            "no async checkpoint was ever registered"
        engine = chaos.install({
            "name": "test-preempt-train",
            "faults": [{"kind": "preempt_slice", "nth": 3,
                        "max_injections": 1,
                        "node": spot.node_id.hex()[:16]}],
        }, seed=0)
        notice = _wait_for(
            lambda: state.list_errors(error_type="node_preempted", limit=10),
            timeout=60)
        assert notice, "the injected notice never drained the node"
        notice_clock = float((notice[0].get("extra") or {})
                             .get("notice_clock") or 0.0)
        # the replacement slice (in production: the autoscaler's
        # preempt_replaced launch; see test_autoscaler_v2)
        c.add_node(num_cpus=2, resources={"spot_slice": 1.0})
        t.join(timeout=240)
        assert not t.is_alive(), "fit() did not finish after the preemption"
        result = box["result"]
        assert result.error is None, result.error
        assert engine.injections_total.get(("preempt_slice", "preempt_slice"))

        steps = [m["step"] for m in result.metrics_history]
        assert steps[-1] == 29, steps[-5:]
        # the run restarted exactly once, resuming from a committed step:
        # the overlap (replayed steps) is the checkpoint lag
        restarts = [(prev, cur) for prev, cur in zip(steps, steps[1:])
                    if cur <= prev]
        assert len(restarts) == 1, restarts
        prev, cur = restarts[0]
        lag = prev - cur + 1
        assert 0 <= lag <= every_n, (prev, cur, lag)
        assert result.metrics["resumed_from"] == cur > 0
        # loss-curve continuity: every point sits on the one true curve
        for m in result.metrics_history:
            assert m["loss"] == pytest.approx(1.0 / (1.0 + m["step"]))
        # recovery stamped: resume bounded after the notice
        resumed = [e for e in result.recovery_events
                   if e.get("resumed_clock") is not None]
        assert resumed and resumed[0]["resume_path"], result.recovery_events
        resume_s = resumed[0]["resumed_clock"] - notice_clock
        assert 0.0 <= resume_s < 120.0, resume_s
        chaos.uninstall()
        verify = verifier.verify(baseline)
        assert verify.ok, verify.violations
    finally:
        try:
            chaos.uninstall()
        except Exception:
            pass
        ray_tpu.shutdown()
        c.shutdown()


# ---------------------------------------------------------- acceptance: serve
def test_preempt_mid_serve_proactive_reroute(tmp_path):
    """Preempt a node hosting a serve replica: the controller evicts it
    from the NOTICE (proactively — the replica is still alive), the
    router re-routes, and a client hammering the deployment sees ZERO
    failed requests; ``reroute_s`` is chaos-clock bounded."""
    from ray_tpu import serve
    from ray_tpu.cluster_utils import Cluster

    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    c = Cluster(initialize_head=True,
                head_node_args={"num_cpus": 3,
                                "resources": {"replica_slot": 1.0}},
                _system_config={"health_check_period_ms": 200,
                                "preempt_grace_s": 6.0})
    spot = c.add_node(num_cpus=2, resources={"replica_slot": 1.0})
    ray_tpu.init(address=c.address, num_cpus=0)
    try:
        @serve.deployment(num_replicas=2, ray_actor_options={
            "num_cpus": 0.1, "resources": {"replica_slot": 1.0}})
        class Echo:
            def hello(self, x):
                return f"hello {x}"

        handle = serve.run(Echo.bind(), name="resilapp", route_prefix=None,
                           _blocking=False)
        assert _wait_for(
            lambda: (serve.status().get("resilapp", {}).get("Echo", {})
                     .get("running_replicas") == 2),
            timeout=120), serve.status()
        # preempt a replica-hosting node that is NOT the controller's
        ctrl_node = next((a.get("node_id") for a in state.list_actors()
                          if a.get("name") == "SERVE_CONTROLLER"), "")
        victim = c.head_node if spot.node_id.hex() == ctrl_node else spot
        c._loop.run_sync(victim.handle_PreemptionNotice(
            {"reason": "spot reclaim", "grace_s": 6.0}))
        # client traffic across the eviction: zero failures allowed (the
        # replica-death retry may fire at most once per request, but the
        # PROACTIVE eviction should make even that unnecessary)
        failures = []
        for i in range(30):
            try:
                assert handle.hello.remote(i).result(timeout=30) == f"hello {i}"
            except Exception as e:  # pragma: no cover - the failure detail
                failures.append((i, repr(e)))
            time.sleep(0.05)
        assert not failures, failures
        evictions = _wait_for(
            lambda: (serve.status().get("resilapp", {}).get("Echo", {})
                     .get("preemption_evictions")),
            timeout=30)
        assert evictions, "no proactive eviction was recorded"
        ev = evictions[0]
        assert ev["node_id"] == victim.node_id.hex()
        # eviction happened off the NOTICE, inside the grace window —
        # i.e. before the node even died
        assert 0.0 <= ev["reroute_s"] < 6.0, ev
        # the corpse is out of the routing table; the survivor serves
        status = serve.status()["resilapp"]["Echo"]
        assert status["running_replicas"] >= 1
    finally:
        try:
            serve.delete("resilapp")
        except Exception:
            pass
        ray_tpu.shutdown()
        c.shutdown()


# ------------------------------------------------------------- cli chaos smoke
def test_cli_chaos_run_preempt_slice_smoke(tmp_path, capsys):
    """Tier-1 smoke (satellite): `cli chaos run` with a preempt_slice
    plan injects the notice deterministically, the workload survives on
    the remaining nodes, and recovery verifies green."""
    from ray_tpu.cli import main
    from ray_tpu.cluster_utils import Cluster

    # dry-run determinism of the bundled plan needs no cluster
    assert main(["chaos", "run", "slice-preempt", "--seed", "1",
                 "--dry-run"]) == 0
    first = capsys.readouterr().out
    assert main(["chaos", "run", "slice-preempt", "--seed", "1",
                 "--dry-run"]) == 0
    assert capsys.readouterr().out == first and "preempt_slice" in first

    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 4},
                _system_config={"health_check_period_ms": 100,
                                "preempt_grace_s": 0.3})
    n2 = c.add_node(num_cpus=2)
    ray_tpu.init(address=c.address, num_cpus=0)
    try:
        plan_path = tmp_path / "preempt.yaml"
        plan_path.write_text(
            "name: preempt-smoke\n"
            "description: tier-1 preempt_slice smoke\n"
            "faults:\n"
            "  - kind: preempt_slice\n"
            "    nth: 1\n"
            "    max_injections: 1\n"
            f"    node: \"{n2.node_id.hex()[:16]}\"\n")
        rc = main(["chaos", "run", str(plan_path), "--seed", "0",
                   "--verify-timeout", "90"])
        out = capsys.readouterr().out
        assert rc == 0, out
        report = json.loads(out)
        assert report["workload"]["failures"] == 0, report["workload"]
        assert any(k.startswith("preempt_slice")
                   for k in report["injections"]), report["injections"]
        assert report["verify"]["ok"], report["verify"]["violations"]
        # the preempted node really died through the full path
        assert _wait_for(
            lambda: any(n["node_id"] == n2.node_id.hex()
                        and n["state"] == "DEAD"
                        for n in state.list_nodes()), timeout=30)
    finally:
        try:
            chaos.uninstall()
        except Exception:
            pass
        ray_tpu.shutdown()
        c.shutdown()


# --------------------------------------------------------------------------
# Round 11: GCE metadata-server preemption watcher (ROADMAP item 10a)


class _FakeMetadataServer:
    """Minimal GCE instance-metadata stand-in: serves the `preempted`
    key, flipping FALSE -> TRUE after `flips_after` requests, and
    records whether clients sent the required Metadata-Flavor header."""

    def __init__(self, flips_after: int):
        import http.server

        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                server.requests += 1
                server.flavors.append(
                    self.headers.get("Metadata-Flavor", ""))
                body = (b"TRUE" if server.requests > flips_after
                        else b"FALSE")
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self.requests = 0
        self.flavors: list[str] = []
        self._httpd = http.server.HTTPServer(("127.0.0.1", 0), Handler)
        self.url = (f"http://127.0.0.1:{self._httpd.server_address[1]}"
                    "/computeMetadata/v1/instance/preempted")
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()


def test_metadata_watcher_fires_once_on_preempted():
    """The watcher polls the metadata `preempted` key with the
    Metadata-Flavor header, ignores FALSE reads, fires the callback
    EXACTLY once when it flips TRUE, then stops on its own."""
    from ray_tpu.resilience import GceMetadataPreemptionWatcher

    server = _FakeMetadataServer(flips_after=2)
    fired: list[str] = []
    try:
        watcher = GceMetadataPreemptionWatcher(
            fired.append, url=server.url, poll_s=0.05).start()
        assert _wait_for(lambda: watcher.fired, timeout=10)
        watcher._thread.join(timeout=5)          # one-shot: thread exits
        assert not watcher._thread.is_alive()
        assert fired == ["gce metadata: instance preempted"]
        assert watcher.polls >= 3                # saw FALSE before TRUE
        assert all(f == "Google" for f in server.flavors)
    finally:
        server.close()


def test_metadata_watcher_errors_never_fire():
    """An unreachable metadata server must never drain a healthy node:
    errors count, the callback stays silent, stop() is clean."""
    from ray_tpu.resilience import GceMetadataPreemptionWatcher

    fired: list[str] = []
    watcher = GceMetadataPreemptionWatcher(
        fired.append, url="http://127.0.0.1:9/computeMetadata",
        poll_s=0.05, timeout_s=0.2).start()
    assert _wait_for(lambda: watcher.errors >= 2, timeout=10)
    watcher.stop()
    assert not fired and not watcher.fired


def test_metadata_watcher_feeds_raylet_drain_path():
    """Wired end-to-end: a raylet started with preempt_metadata_watch
    polls the (fake) metadata endpoint and enters the SAME draining
    path a PreemptionNotice RPC triggers — node flagged draining in the
    GCS, node_preempted published, node DEAD after the grace window."""
    server = _FakeMetadataServer(flips_after=1)
    cfg = get_config()
    saved = (cfg.preempt_metadata_watch, cfg.preempt_metadata_url,
             cfg.preempt_metadata_poll_s, cfg.preempt_grace_s)
    cfg.preempt_metadata_watch = True
    cfg.preempt_metadata_url = server.url
    cfg.preempt_metadata_poll_s = 0.05
    cfg.preempt_grace_s = 1.0
    from ray_tpu.cluster_utils import Cluster

    c = Cluster()
    try:
        c.add_node(num_cpus=1)
        ray_tpu.init(address=c.address)
        n2 = c.add_node(num_cpus=1)  # watcher starts with the config on
        assert _wait_for(
            lambda: any(n["node_id"] == n2.node_id.hex()
                        and (n.get("draining") or n["state"] == "DEAD")
                        for n in state.list_nodes()), timeout=30), \
            "metadata TRUE never reached the drain path"
        assert _wait_for(
            lambda: any(n["node_id"] == n2.node_id.hex()
                        and n["state"] == "DEAD"
                        for n in state.list_nodes()), timeout=30)
    finally:
        (cfg.preempt_metadata_watch, cfg.preempt_metadata_url,
         cfg.preempt_metadata_poll_s, cfg.preempt_grace_s) = saved
        server.close()
        ray_tpu.shutdown()
        c.shutdown()
