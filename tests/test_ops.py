"""Kernel correctness: flash attention vs reference, ring attention vs
full attention, rope/rmsnorm sanity."""

import jax
import jax.numpy as jnp
import numpy as np
try:
    from jax import shard_map
except ImportError:  # jax < 0.6: keep the kernel tests collectable
    from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from ray_tpu.ops import (
    apply_rope,
    flash_attention,
    mha_reference,
    ring_attention,
    rms_norm,
)
from ray_tpu.parallel import MeshConfig, create_mesh


def _qkv(key, b=2, hq=4, hkv=2, s=256, d=64, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    q = jax.random.normal(ks[0], (b, hq, s, d), dtype)
    k = jax.random.normal(ks[1], (b, hkv, s, d), dtype)
    v = jax.random.normal(ks[2], (b, hkv, s, d), dtype)
    return q, k, v


def test_flash_matches_reference_causal():
    q, k, v = _qkv(jax.random.PRNGKey(0))
    out = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_flash_matches_reference_noncausal():
    q, k, v = _qkv(jax.random.PRNGKey(1), s=128)
    out = flash_attention(q, k, v, causal=False, block_q=64, block_k=64)
    ref = mha_reference(q, k, v, causal=False)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_flash_odd_seq_falls_back():
    q, k, v = _qkv(jax.random.PRNGKey(2), s=100)
    out = flash_attention(q, k, v, causal=True)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_ring_attention_matches_full():
    mesh = create_mesh(MeshConfig(sp=8))
    b, h, s, d = 2, 4, 128, 32
    key = jax.random.PRNGKey(3)
    q, k, v = _qkv(key, b=b, hq=h, hkv=h, s=s, d=d)

    spec = P(None, None, "sp", None)
    fn = shard_map(
        lambda q_, k_, v_: ring_attention(q_, k_, v_, axis="sp", causal=True),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False,
    )
    out = fn(q, k, v)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ring_attention_gqa():
    mesh = create_mesh(MeshConfig(dp=2, sp=4))
    q, k, v = _qkv(jax.random.PRNGKey(4), b=1, hq=4, hkv=2, s=64, d=16)
    spec = P(None, None, "sp", None)
    fn = shard_map(
        lambda q_, k_, v_: ring_attention(q_, k_, v_, axis="sp", causal=True),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False,
    )
    out = fn(q, k, v)
    ref = mha_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_rms_norm():
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 8))
    w = jnp.full((8,), 2.0)
    out = rms_norm(x, w)
    expected = 2.0 * x / np.sqrt((np.asarray(x) ** 2).mean(-1, keepdims=True) + 1e-6)
    np.testing.assert_allclose(out, expected, atol=1e-5, rtol=1e-5)


def test_rope_preserves_norm_and_zero_position():
    q = jax.random.normal(jax.random.PRNGKey(0), (1, 2, 8, 16))
    pos = jnp.arange(8, dtype=jnp.int32)
    out = apply_rope(q, pos)
    # rotation preserves per-pair norms
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(out), axis=-1),
        np.linalg.norm(np.asarray(q), axis=-1), rtol=1e-5)
    # position 0 is identity
    np.testing.assert_allclose(out[:, :, 0], q[:, :, 0], atol=1e-6)


def test_flash_attention_grads_match_reference():
    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    q = jax.random.normal(ks[0], (2, 4, 256, 32))
    k = jax.random.normal(ks[1], (2, 2, 256, 32))
    v = jax.random.normal(ks[2], (2, 2, 256, 32))

    def loss(f):
        return lambda q_, k_, v_: (f(q_, k_, v_) ** 2).sum()

    gf = jax.grad(loss(lambda a, b, c: flash_attention(a, b, c, causal=True)),
                  argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss(lambda a, b, c: mha_reference(a, b, c, causal=True)),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(a, b, atol=2e-4, rtol=2e-4)


def test_flash_block_fits_seq_divisors():
    """Default blocks (1024) must CLAMP to a divisor of odd-but-tileable
    seqs (1536 -> 768) so those shapes stay on the Pallas kernel instead
    of silently falling back to the unblocked reference."""
    import numpy as np

    from ray_tpu.ops.attention import _fit_block, flash_attention, mha_reference

    assert _fit_block(1024, 2048) == 1024
    assert _fit_block(1024, 1536) == 768
    assert _fit_block(1024, 512) == 512
    assert _fit_block(512, 48) == 48
    # ragged (not a multiple of 16): no divisor works -> caller falls back
    assert 100 % _fit_block(1024, 100) != 0

    q = jax.random.normal(jax.random.PRNGKey(0), (1, 2, 1536, 64), jnp.float32)
    k = jax.random.normal(jax.random.PRNGKey(1), (1, 1, 1536, 64), jnp.float32)
    v = jax.random.normal(jax.random.PRNGKey(2), (1, 1, 1536, 64), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(flash_attention(q, k, v)),
        np.asarray(mha_reference(q, k, v)), atol=2e-3)


def test_ulysses_attention_matches_full():
    """Ulysses SP (all-to-all heads<->sequence reshuffle + local flash)
    must match full attention exactly, including GQA head counts."""
    from ray_tpu.ops import ulysses_attention

    mesh = create_mesh(MeshConfig(dp=2, sp=4))
    spec = P(None, None, "sp", None)
    for hq, hkv in ((8, 8), (8, 4)):
        q, k, v = _qkv(jax.random.PRNGKey(5), b=2, hq=hq, hkv=hkv, s=128, d=32)
        fn = shard_map(
            lambda q_, k_, v_: ulysses_attention(q_, k_, v_, axis="sp", causal=True),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )
        out = fn(q, k, v)
        ref = mha_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)


def test_ulysses_in_model_forward():
    """attn_impl="ulysses" trains end-to-end over an sp mesh with the
    same loss as the reference attention (model-level parity)."""
    import dataclasses

    from ray_tpu.models import PRESETS, init_params, loss_fn

    mesh = create_mesh(MeshConfig(sp=4, dp=2))
    cfg = dataclasses.replace(PRESETS["debug"], dtype=jnp.float32,
                              attn_impl="ulysses")
    cfg_ref = dataclasses.replace(cfg, attn_impl="reference")
    params = init_params(cfg, jax.random.PRNGKey(0))
    batch = {"tokens": jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0,
                                          cfg.vocab_size)}
    l_u = loss_fn(params, batch, cfg, mesh=mesh)
    l_r = loss_fn(params, batch, cfg_ref, mesh=mesh)
    np.testing.assert_allclose(float(l_u), float(l_r), rtol=1e-5)


def _paged_dense_ref(q, kp, vp, bt, pos, page):
    """Dense ground truth for the paged decode kernel: gather the full
    block-table capacity, mask positions beyond ``pos``."""
    n, kh, g, d = q.shape
    max_pages = bt.shape[1]
    gk = jnp.swapaxes(kp[bt], 1, 2).reshape(n, kh, -1, d)
    gv = jnp.swapaxes(vp[bt], 1, 2).reshape(n, kh, -1, d)
    live = jnp.arange(max_pages * page)[None] <= pos[:, None]
    s = jnp.einsum("nkgd,nktd->nkgt", q, gk).astype(jnp.float32) * d ** -0.5
    s = jnp.where(live[:, None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, -1).astype(q.dtype)
    return jnp.einsum("nkgt,nktd->nkgd", p, gv)


def test_paged_decode_attention_matches_dense():
    from ray_tpu.ops.paged_attention import paged_decode_attention

    rng = np.random.default_rng(0)
    n, kh, g, d = 3, 2, 2, 32
    page, max_pages, pool = 16, 8, 32
    q = jnp.array(rng.standard_normal((n, kh, g, d)), jnp.float32)
    kp = jnp.array(rng.standard_normal((pool, kh, page, d)), jnp.float32)
    vp = jnp.array(rng.standard_normal((pool, kh, page, d)), jnp.float32)
    bt = jnp.array(rng.permutation(pool)[: n * max_pages].reshape(n, max_pages),
                   jnp.int32)
    # mixed fill levels incl. page-boundary edges and a full table
    pos = jnp.array([5, 40, 127], jnp.int32)
    ref = _paged_dense_ref(q, kp, vp, bt, pos, page)
    for ppb in (1, 3, None):  # incl. a ppb that does not divide max_pages
        out = paged_decode_attention(q, kp, vp, bt, pos, page_size=page,
                                     pages_per_block=ppb, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=1e-4)


def test_paged_decode_attention_edges_and_bf16():
    from ray_tpu.ops.paged_attention import paged_decode_attention

    rng = np.random.default_rng(1)
    n, kh, g, d = 4, 2, 3, 16     # G=3: exercises the sublane pad path
    page, max_pages, pool = 16, 4, 24
    q = jnp.array(rng.standard_normal((n, kh, g, d)), jnp.float32)
    kp = jnp.array(rng.standard_normal((pool, kh, page, d)), jnp.float32)
    vp = jnp.array(rng.standard_normal((pool, kh, page, d)), jnp.float32)
    bt = jnp.array(rng.permutation(pool)[: n * max_pages].reshape(n, max_pages),
                   jnp.int32)
    # first token, page boundary both sides, overflow (pos past capacity:
    # decode_loop's done-slots keep incrementing pos — their output is
    # unspecified garbage but must stay finite, never NaN-poisoning)
    pos = jnp.array([0, 15, 16, max_pages * page + 7], jnp.int32)
    ref = _paged_dense_ref(q, kp, vp, bt, jnp.minimum(pos, max_pages * page - 1),
                           page)
    out = paged_decode_attention(q, kp, vp, bt, pos, page_size=page,
                                 interpret=True)
    np.testing.assert_allclose(np.asarray(out[:3]), np.asarray(ref[:3]),
                               atol=2e-5, rtol=1e-4)
    assert np.isfinite(np.asarray(out[3])).all()

    ref16 = _paged_dense_ref(q.astype(jnp.bfloat16), kp.astype(jnp.bfloat16),
                             vp.astype(jnp.bfloat16), bt,
                             jnp.minimum(pos, max_pages * page - 1), page)
    out16 = paged_decode_attention(
        q.astype(jnp.bfloat16), kp.astype(jnp.bfloat16),
        vp.astype(jnp.bfloat16), bt, pos, page_size=page, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out16[:3], np.float32), np.asarray(ref16[:3], np.float32),
        atol=0.08)
