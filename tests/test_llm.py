"""Continuous-batching LLM inference engine + Serve integration.

Covers the engine half the reference delegates to vLLM
(``python/ray/llm/_internal/serve/deployments/llm/vllm_engine.py``) with
the TPU redesign: slot KV cache, bucketed prefill, batched fixed-shape
decode (SURVEY §7.2-7).
"""

import dataclasses
import json
import threading
import urllib.parse
import urllib.request

import jax
import jax.numpy as jnp
import pytest

from ray_tpu.llm.engine import InferenceEngine, Request
from ray_tpu.models.llama import PRESETS, forward, init_params


@pytest.fixture(scope="module")
def small_model():
    cfg = dataclasses.replace(PRESETS["debug"], dtype=jnp.float32, attn_impl="reference")
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def naive_greedy(params, cfg, prompt, n):
    toks, out = list(prompt), []
    for _ in range(n):
        logits = forward(params, jnp.asarray([toks]), cfg)[0, -1]
        t = int(jnp.argmax(logits))
        out.append(t)
        toks.append(t)
    return out


def test_cached_decode_matches_full_forward(small_model):
    """Slot-cache decode must be token-identical to recomputing the full
    forward each step (greedy)."""
    cfg, params = small_model
    eng = InferenceEngine(cfg, params, max_slots=4, max_len=64)
    prompts = [[1, 5, 9], [2, 4, 6, 8, 10, 12, 14], [3], list(range(1, 34))]
    reqs = [Request(f"r{i}", p, max_new_tokens=6) for i, p in enumerate(prompts)]
    for r in reqs:
        eng.add_request(r)
    while any(not r.done for r in reqs):
        eng.step()
    for r, p in zip(reqs, prompts):
        assert r.generated == naive_greedy(params, cfg, p, 6), r.request_id


def test_continuous_batching_oversubscribed(small_model):
    """More requests than slots: finished sequences free slots for waiting
    requests; every request completes with the right number of tokens."""
    cfg, params = small_model
    eng = InferenceEngine(cfg, params, max_slots=2, max_len=64)
    reqs = [Request(f"r{i}", [i + 1, i + 2], max_new_tokens=4) for i in range(7)]
    for r in reqs:
        eng.add_request(r)
    steps = 0
    while any(not r.done for r in reqs):
        eng.step()
        steps += 1
        assert steps < 500
    for r in reqs:
        assert len(r.generated) == 4
        assert r.finish_reason == "length"
    assert len(eng._free_slots) == 2 and not eng._active


def test_late_arrival_joins_running_batch(small_model):
    """A request added mid-decode is admitted without disturbing running
    sequences (continuous batching, not static batching)."""
    cfg, params = small_model
    eng = InferenceEngine(cfg, params, max_slots=4, max_len=64)
    first = Request("first", [1, 2, 3], max_new_tokens=10)
    eng.add_request(first)
    for _ in range(4):
        eng.step()
    late = Request("late", [7, 8], max_new_tokens=3)
    eng.add_request(late)
    while not (first.done and late.done):
        eng.step()
    assert first.generated == naive_greedy(params, cfg, [1, 2, 3], 10)
    assert late.generated == naive_greedy(params, cfg, [7, 8], 3)


def test_eos_and_cancel(small_model):
    cfg, params = small_model
    eng = InferenceEngine(cfg, params, max_slots=2, max_len=64)
    # eos: pick the model's actual first greedy token as the eos id
    first_token = naive_greedy(params, cfg, [5, 6], 1)[0]
    r = Request("eos", [5, 6], max_new_tokens=10, eos_id=first_token)
    eng.add_request(r)
    while not r.done:
        eng.step()
    assert r.finish_reason == "stop" and len(r.generated) == 1

    r2 = Request("cancel", [1, 2], max_new_tokens=100)
    eng.add_request(r2)
    eng.step()
    eng.cancel("cancel")
    assert r2.done and r2.finish_reason == "cancelled"
    assert len(eng._free_slots) == 2

    # Cancelling a request still in the waiting queue must mark it done too
    # (a blocked caller would otherwise wait forever).
    r3 = Request("queued", [9], max_new_tokens=5)
    eng.add_request(r3)
    eng.cancel("queued")
    assert r3.done and r3.finish_reason == "cancelled"
    assert not eng.has_work


def test_serve_llm_app_concurrent_http(ray_cluster):
    """An LLM app serves concurrent HTTP completions through the proxy
    (llm_server.py:415 acceptance surface)."""
    from ray_tpu import serve
    from ray_tpu.llm import build_llm_app

    try:
        app = build_llm_app("debug-128", max_slots=4, max_len=128)
        serve.run(app, name="llm")
        addr = serve.http_address()

        results: list[dict] = []
        errors: list[Exception] = []

        def one(i):
            q = urllib.parse.urlencode({"prompt": f"hello {i}", "max_new_tokens": 5})
            try:
                with urllib.request.urlopen(f"{addr}/?{q}", timeout=120) as resp:
                    results.append(json.loads(resp.read()))
            except Exception as e:  # pragma: no cover - surfaced by assert
                errors.append(e)

        threads = [threading.Thread(target=one, args=(i,)) for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        assert not errors, errors
        assert len(results) == 6
        for r in results:
            assert r["num_generated"] == 5
            assert r["finish_reason"] in ("length", "stop")
    finally:
        serve.shutdown()
