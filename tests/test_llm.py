"""Continuous-batching LLM inference engine + Serve integration.

Covers the engine half the reference delegates to vLLM
(``python/ray/llm/_internal/serve/deployments/llm/vllm_engine.py``) with
the TPU redesign: paged KV cache with static-shape block tables, chunked
prefill, prefix caching, batched fixed-shape decode, OpenAI-compatible
routes with SSE token streaming (SURVEY §7.2-7).
"""

import dataclasses
import json
import threading
import urllib.parse
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.llm.engine import InferenceEngine, Request
from ray_tpu.models.llama import PRESETS, forward, init_params
from conftest import requires_shard_map


@pytest.fixture(scope="module")
def small_model():
    cfg = dataclasses.replace(PRESETS["debug"], dtype=jnp.float32, attn_impl="reference")
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def naive_greedy(params, cfg, prompt, n):
    toks, out = list(prompt), []
    for _ in range(n):
        logits = forward(params, jnp.asarray([toks]), cfg)[0, -1]
        t = int(jnp.argmax(logits))
        out.append(t)
        toks.append(t)
    return out


def test_cached_decode_matches_full_forward(small_model):
    """Slot-cache decode must be token-identical to recomputing the full
    forward each step (greedy)."""
    cfg, params = small_model
    eng = InferenceEngine(cfg, params, max_slots=4, max_len=64)
    prompts = [[1, 5, 9], [2, 4, 6, 8, 10, 12, 14], [3], list(range(1, 34))]
    reqs = [Request(f"r{i}", p, max_new_tokens=6) for i, p in enumerate(prompts)]
    for r in reqs:
        eng.add_request(r)
    while any(not r.done for r in reqs):
        eng.step()
    for r, p in zip(reqs, prompts):
        assert r.generated == naive_greedy(params, cfg, p, 6), r.request_id


def test_continuous_batching_oversubscribed(small_model):
    """More requests than slots: finished sequences free slots for waiting
    requests; every request completes with the right number of tokens."""
    cfg, params = small_model
    eng = InferenceEngine(cfg, params, max_slots=2, max_len=64)
    reqs = [Request(f"r{i}", [i + 1, i + 2], max_new_tokens=4) for i in range(7)]
    for r in reqs:
        eng.add_request(r)
    steps = 0
    while any(not r.done for r in reqs):
        eng.step()
        steps += 1
        assert steps < 500
    for r in reqs:
        assert len(r.generated) == 4
        assert r.finish_reason == "length"
    assert len(eng._free_slots) == 2 and not eng._active


def test_late_arrival_joins_running_batch(small_model):
    """A request added mid-decode is admitted without disturbing running
    sequences (continuous batching, not static batching)."""
    cfg, params = small_model
    eng = InferenceEngine(cfg, params, max_slots=4, max_len=64)
    first = Request("first", [1, 2, 3], max_new_tokens=10)
    eng.add_request(first)
    for _ in range(4):
        eng.step()
    late = Request("late", [7, 8], max_new_tokens=3)
    eng.add_request(late)
    while not (first.done and late.done):
        eng.step()
    assert first.generated == naive_greedy(params, cfg, [1, 2, 3], 10)
    assert late.generated == naive_greedy(params, cfg, [7, 8], 3)


def test_eos_and_cancel(small_model):
    cfg, params = small_model
    eng = InferenceEngine(cfg, params, max_slots=2, max_len=64)
    # eos: pick the model's actual first greedy token as the eos id
    first_token = naive_greedy(params, cfg, [5, 6], 1)[0]
    r = Request("eos", [5, 6], max_new_tokens=10, eos_id=first_token)
    eng.add_request(r)
    while not r.done:
        eng.step()
    assert r.finish_reason == "stop" and len(r.generated) == 1

    r2 = Request("cancel", [1, 2], max_new_tokens=100)
    eng.add_request(r2)
    eng.step()
    eng.cancel("cancel")
    assert r2.done and r2.finish_reason == "cancelled"
    assert len(eng._free_slots) == 2

    # Cancelling a request still in the waiting queue must mark it done too
    # (a blocked caller would otherwise wait forever).
    r3 = Request("queued", [9], max_new_tokens=5)
    eng.add_request(r3)
    eng.cancel("queued")
    assert r3.done and r3.finish_reason == "cancelled"
    assert not eng.has_work


def test_chunked_prefill_parity(small_model):
    """A prompt spanning several prefill chunks must decode identically to
    the full forward (chunk attention over previously-written pages)."""
    cfg, params = small_model
    eng = InferenceEngine(cfg, params, max_slots=2, max_len=64, page_size=8,
                          prefill_chunk_size=16)
    prompt = list(range(1, 40))  # 39 tokens -> chunks 16+16+8
    r = Request("chunked", prompt, max_new_tokens=5)
    eng.add_request(r)
    while not r.done:
        eng.step()
    assert eng.metrics["prefill_chunks"] >= 3
    assert r.generated == naive_greedy(params, cfg, prompt, 5)


def test_prefix_cache_reuse(small_model):
    """A repeated prompt prefix reuses cached pages (no recompute) and
    still decodes identically."""
    cfg, params = small_model
    eng = InferenceEngine(cfg, params, max_slots=2, max_len=64, page_size=8)
    prompt = list(range(1, 20))  # 19 tokens -> 2 full pages cacheable
    a = Request("a", prompt, max_new_tokens=4)
    eng.add_request(a)
    while not a.done:
        eng.step()
    assert eng.metrics["prefix_hit_pages"] == 0
    b = Request("b", list(prompt), max_new_tokens=4)
    eng.add_request(b)
    while not b.done:
        eng.step()
    assert eng.metrics["prefix_hit_pages"] == 2
    assert b.generated == a.generated == naive_greedy(params, cfg, prompt, 4)


def test_cancel_mid_prefill_does_not_poison_prefix_cache(small_model):
    """Cancelling during chunked prefill must only prefix-register pages
    whose K/V was actually computed — a later identical prompt must not
    attend over garbage pages."""
    cfg, params = small_model
    eng = InferenceEngine(cfg, params, max_slots=2, max_len=64, page_size=8,
                          prefill_chunk_size=8)
    prompt = list(range(1, 30))  # 29 tokens -> 4 chunks of 8
    r = Request("x", prompt, max_new_tokens=4)
    eng.add_request(r)
    eng.step()  # admit + prefill first chunk only
    assert r.prefill_pos == 8 and not r.done
    eng.cancel("x")
    r2 = Request("y", list(prompt), max_new_tokens=4)
    eng.add_request(r2)
    while not r2.done:
        eng.step()
    assert eng.metrics["prefix_hit_pages"] <= 1  # only the computed page
    assert r2.generated == naive_greedy(params, cfg, prompt, 4)


def test_page_pool_admission_control(small_model):
    """With a tiny page pool, admission waits for pages instead of
    corrupting running sequences; everything still completes."""
    cfg, params = small_model
    eng = InferenceEngine(cfg, params, max_slots=4, max_len=64, page_size=8,
                          num_pages=8, enable_prefix_cache=False)
    # Each request needs ceil((6+20)/8)=4 pages; pool of 8 fits 2 at a time.
    reqs = [Request(f"r{i}", [i + 1] * 6, max_new_tokens=20) for i in range(5)]
    for r in reqs:
        eng.add_request(r)
    steps = 0
    while any(not r.done for r in reqs):
        eng.step()
        steps += 1
        assert steps < 2000
    for r in reqs:
        assert len(r.generated) == 20
    assert len(eng.allocator.free) == 8  # every page returned


def test_openai_completions_http(ray_cluster):
    """OpenAI-compatible /v1/completions + /v1/chat/completions + /v1/models
    through the real proxy (reference routers/router.py:173)."""
    from ray_tpu import serve
    from ray_tpu.llm import build_llm_app

    try:
        serve.run(build_llm_app("debug-128", max_slots=4, max_len=128), name="llm")
        addr = serve.http_address()

        models = json.loads(urllib.request.urlopen(addr + "/v1/models", timeout=60).read())
        assert models["data"][0]["id"] == "debug-128"

        body = json.dumps({"prompt": "hello", "max_tokens": 8}).encode()
        req = urllib.request.Request(addr + "/v1/completions", data=body,
                                     headers={"Content-Type": "application/json"})
        out = json.loads(urllib.request.urlopen(req, timeout=120).read())
        assert out["object"] == "text_completion"
        assert out["usage"]["completion_tokens"] == 8
        assert out["choices"][0]["finish_reason"] == "length"

        body = json.dumps({"messages": [{"role": "user", "content": "hi"}],
                           "max_tokens": 4}).encode()
        req = urllib.request.Request(addr + "/v1/chat/completions", data=body,
                                     headers={"Content-Type": "application/json"})
        out = json.loads(urllib.request.urlopen(req, timeout=120).read())
        assert out["object"] == "chat.completion"
        assert out["choices"][0]["message"]["role"] == "assistant"
    finally:
        serve.shutdown()


def test_openai_sse_streaming(ray_cluster):
    """stream=true responses arrive as SSE chunks (one per token, [DONE]
    terminated) through the proxy's chunked-transfer path."""
    from ray_tpu import serve
    from ray_tpu.llm import build_llm_app

    try:
        serve.run(build_llm_app("debug-128", max_slots=4, max_len=128), name="llm")
        addr = serve.http_address()
        body = json.dumps({"prompt": "hello", "max_tokens": 6, "stream": True}).encode()
        req = urllib.request.Request(addr + "/v1/completions", data=body,
                                     headers={"Content-Type": "application/json"})
        resp = urllib.request.urlopen(req, timeout=120)
        assert resp.headers.get("Content-Type") == "text/event-stream"
        events = []
        for line in resp:
            line = line.decode().strip()
            if line.startswith("data: "):
                events.append(line[len("data: "):])
        assert events[-1] == "[DONE]"
        tokens = [json.loads(e)["choices"][0]["text"] for e in events[:-1]]
        assert len(tokens) == 6

        # chat streaming: role delta first, then content deltas
        body = json.dumps({"messages": [{"role": "user", "content": "hi"}],
                           "max_tokens": 3, "stream": True}).encode()
        req = urllib.request.Request(addr + "/v1/chat/completions", data=body,
                                     headers={"Content-Type": "application/json"})
        chunks = [l.decode().strip()[len("data: "):] for l in urllib.request.urlopen(req, timeout=120)
                  if l.decode().strip().startswith("data: ")]
        assert chunks[-1] == "[DONE]"
        assert json.loads(chunks[0])["choices"][0]["delta"] == {"role": "assistant"}
    finally:
        serve.shutdown()


def test_serve_llm_app_concurrent_http(ray_cluster):
    """An LLM app serves concurrent HTTP completions through the proxy
    (llm_server.py:415 acceptance surface)."""
    from ray_tpu import serve
    from ray_tpu.llm import build_llm_app

    try:
        app = build_llm_app("debug-128", max_slots=4, max_len=128)
        serve.run(app, name="llm")
        addr = serve.http_address()

        results: list[dict] = []
        errors: list[Exception] = []

        def one(i):
            q = urllib.parse.urlencode({"prompt": f"hello {i}", "max_new_tokens": 5})
            try:
                with urllib.request.urlopen(f"{addr}/?{q}", timeout=120) as resp:
                    results.append(json.loads(resp.read()))
            except Exception as e:  # pragma: no cover - surfaced by assert
                errors.append(e)

        threads = [threading.Thread(target=one, args=(i,)) for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        assert not errors, errors
        assert len(results) == 6
        for r in results:
            assert r["num_generated"] == 5
            assert r["finish_reason"] in ("length", "stop")
    finally:
        serve.shutdown()


def test_batch_llm_processor(ray_cluster):
    """Data batch inference through the Processor pipeline (reference
    llm/_internal/batch/processor/base.py): rows in -> generated_text out,
    with per-row sampling columns and pre/postprocess stages."""
    from ray_tpu import data as rd
    from ray_tpu.llm import LLMProcessorConfig, build_llm_processor

    config = LLMProcessorConfig(preset="debug-128", concurrency=1, batch_size=8,
                                max_slots=4, max_len=128, max_tokens=6)
    processor = build_llm_processor(
        config,
        preprocess=lambda row: {"prompt": f"say {row['word']}",
                                "max_tokens": 4 + (row["id"] % 3),
                                "word": row["word"], "id": row["id"]},
        postprocess=lambda row: {"word": row["word"],
                                 "text": row["generated_text"],
                                 "n": row["num_generated_tokens"]},
    )
    rows = [{"id": i, "word": w} for i, w in enumerate(["alpha", "beta", "gamma",
                                                        "delta", "epsilon", "zeta"])]
    out = processor(rd.from_items(rows, parallelism=2)).take_all()
    assert len(out) == 6
    by_word = {r["word"]: r for r in out}
    assert set(by_word) == {w["word"] for w in rows}
    for i, w in enumerate(["alpha", "beta", "gamma", "delta", "epsilon", "zeta"]):
        assert by_word[w]["n"] == 4 + (i % 3)  # per-row max_tokens honored
        assert isinstance(by_word[w]["text"], str)


def test_tensor_parallel_engine_parity(small_model):
    """The engine sharded over a tp mesh (params by heads/kv_heads, pages
    by kv_heads; XLA inserts the collectives) decodes token-identically
    to the single-device engine — the multi-chip inference path the
    reference gets from vLLM's TP workers."""
    from ray_tpu.parallel import MeshConfig, create_mesh

    cfg, params = small_model
    prompt = list(range(1, 22))
    ref = InferenceEngine(cfg, params, max_slots=2, max_len=64, page_size=8)
    expected = ref.generate(list(prompt), max_new_tokens=6)

    n = len(jax.devices())
    mesh = create_mesh(MeshConfig(tp=2, dp=max(1, n // 2)))
    tp_eng = InferenceEngine(cfg, params, max_slots=2, max_len=64, page_size=8,
                             mesh=mesh)
    assert tp_eng.generate(list(prompt), max_new_tokens=6) == expected

    with pytest.raises(ValueError, match="not divisible"):
        InferenceEngine(cfg, params, mesh=create_mesh(MeshConfig(tp=8, dp=max(1, n // 8))),
                        max_slots=2, max_len=64, page_size=8)


@requires_shard_map
def test_pipeline_parallel_engine_parity(small_model):
    """The engine staged over a pp mesh (layers AND the page pool sharded
    by stage, activations rotating via ppermute, decode pipelined over
    slot groups — llm/pp_model.py) decodes token-identically to the
    single-device engine. The reference gets PP from vLLM workers with
    NCCL send/recv (vllm_models.py:117-168)."""
    from ray_tpu.parallel import MeshConfig, create_mesh

    cfg, params = small_model
    prompts = [list(range(1, 22)), [7, 3, 7, 3, 7],
               [2, 4, 6, 8, 10, 12, 14, 16, 18]]
    ref = InferenceEngine(cfg, params, max_slots=4, max_len=64, page_size=8)
    expected = [ref.generate(list(p), max_new_tokens=6) for p in prompts]

    n = len(jax.devices())
    mesh = create_mesh(MeshConfig(pp=2, dp=max(1, n // 2)))
    pp_eng = InferenceEngine(cfg, params, max_slots=4, max_len=64, page_size=8,
                             mesh=mesh)
    got = [pp_eng.generate(list(p), max_new_tokens=6) for p in prompts]
    assert got == expected

    # oversubscribed: more concurrent requests than slots, mid-flight EOS
    many = [ref.generate([5, 9, 13], max_new_tokens=4) for _ in range(6)]
    got_many = [pp_eng.generate([5, 9, 13], max_new_tokens=4) for _ in range(6)]
    assert got_many == many

    with pytest.raises(ValueError, match="max_slots"):
        InferenceEngine(cfg, params, mesh=mesh, max_slots=3, max_len=64,
                        page_size=8)


def test_paged_attention_engine_greedy_parity(small_model):
    """The Pallas paged-attention decode kernel (attention_impl="paged",
    interpreted off-TPU) must be token-identical to the dense gather path
    under greedy decoding — the engine-level guarantee behind flipping
    the kernel on for TPU serving (ops/paged_attention.py)."""
    cfg, params = small_model
    prompts = [[1, 5, 9], [2, 4, 6, 8, 10, 12, 14], list(range(1, 34))]

    def run(attention_impl):
        eng = InferenceEngine(cfg, params, max_slots=4, max_len=64,
                              attention_impl=attention_impl)
        reqs = [Request(f"r{i}", p, max_new_tokens=6) for i, p in enumerate(prompts)]
        for r in reqs:
            eng.add_request(r)
        while any(not r.done for r in reqs):
            eng.step()
        return [r.generated for r in reqs]

    assert run("paged") == run("dense")


# ------------------------------------------------------------------- LoRA

def _make_adapter(cfg, rng, scale=0.5):
    """Random rank-2 adapter arrays for every attention projection."""
    L, E, H, KH, D = (cfg.n_layers, cfg.hidden, cfg.n_heads,
                      cfg.n_kv_heads, cfg.head_dim)
    r = 2
    dims = {"wq": (E, H * D), "wk": (E, KH * D), "wv": (E, KH * D),
            "wo": (H * D, E)}
    out = {}
    for p, (ein, eout) in dims.items():
        out[f"{p}.A"] = (rng.standard_normal((L, ein, r)) * scale / ein ** 0.5
                         ).astype(np.float32)
        out[f"{p}.B"] = (rng.standard_normal((L, r, eout)) * scale
                         ).astype(np.float32)
    return out


def _merge_adapter(cfg, params, arrays):
    """Base params with the adapter folded in (ground truth)."""
    import jax.numpy as jnp

    L, E, H, KH, D = (cfg.n_layers, cfg.hidden, cfg.n_heads,
                      cfg.n_kv_heads, cfg.head_dim)
    layers = dict(params["layers"])
    for p, heads in (("wq", H), ("wk", KH), ("wv", KH)):
        delta = np.einsum("ler,lro->leo", arrays[f"{p}.A"], arrays[f"{p}.B"])
        layers[p] = layers[p] + jnp.asarray(
            delta.reshape(L, E, heads, D), layers[p].dtype)
    delta_o = np.einsum("lfr,lre->lfe", arrays["wo.A"], arrays["wo.B"])
    layers["wo"] = layers["wo"] + jnp.asarray(
        delta_o.reshape(L, H, D, E), layers["wo"].dtype)
    return {**params, "layers": layers}


def test_lora_mixed_batch_matches_merged_weights(small_model, tmp_path):
    """Multi-LoRA serving: a decode batch mixing the base model and two
    adapters must produce, per request, exactly the tokens of an engine
    whose weights have that adapter merged in (greedy). This is the
    capability the reference gets from vLLM's multi-LoRA kernels
    (lora_model_loader.py + per-request `model` routing)."""
    from ray_tpu.llm.lora import LoRAServingConfig, save_adapter

    cfg, params = small_model
    rng = np.random.default_rng(7)
    ad1 = _make_adapter(cfg, rng)
    ad2 = _make_adapter(cfg, rng)
    save_adapter(str(tmp_path / "ad1.npz"), ad1)
    save_adapter(str(tmp_path / "ad2.npz"), ad2)

    prompt = [3, 1, 4, 1, 5, 9, 2, 6]

    def run_engine(params_, model=None, lora=None):
        eng = InferenceEngine(cfg, params_, max_slots=4, max_len=64,
                              lora_config=lora)
        reqs = [Request(f"r{i}", prompt, max_new_tokens=6, model=m)
                for i, m in enumerate([model] if lora is None
                                      else [None, "ad1", "ad2"])]
        for r in reqs:
            eng.add_request(r)
        while any(not r.done for r in reqs):
            eng.step()
        return [r.generated for r in reqs]

    lora = LoRAServingConfig(max_loras=2, max_rank=4,
                             dynamic_lora_loading_path=str(tmp_path))
    base_toks, ad1_toks, ad2_toks = run_engine(params, lora=lora)

    assert base_toks == run_engine(params)[0], "identity slot changed base"
    assert ad1_toks == run_engine(_merge_adapter(cfg, params, ad1))[0]
    assert ad2_toks == run_engine(_merge_adapter(cfg, params, ad2))[0]
    assert ad1_toks != ad2_toks  # the adapters actually do something


@requires_shard_map
def test_lora_pp_decode_parity(small_model, tmp_path):
    """LoRA over a PIPELINE mesh (round 8): the adapter stacks shard over
    pp on their layer axis like the params, prefill carries the adapter
    into the chunk's K/V (pp_prefill_chunk lora path), and a decode
    batch mixing base and adapter requests must produce byte-identical
    greedy tokens to the single-device multi-LoRA engine."""
    from ray_tpu.llm.lora import LoRAServingConfig, save_adapter
    from ray_tpu.parallel import MeshConfig, create_mesh

    cfg, params = small_model
    rng = np.random.default_rng(11)
    save_adapter(str(tmp_path / "adp.npz"), _make_adapter(cfg, rng))
    lora = LoRAServingConfig(max_loras=2, max_rank=4,
                             dynamic_lora_loading_path=str(tmp_path))
    prompts = [([3, 1, 4, 1, 5, 9, 2, 6], None),
               ([3, 1, 4, 1, 5, 9, 2, 6], "adp"),
               ([2, 7, 1, 8], "adp"),
               ([2, 7, 1, 8], None)]

    def run(mesh):
        eng = InferenceEngine(cfg, params, max_slots=4, max_len=64,
                              page_size=8, lora_config=lora, mesh=mesh)
        reqs = [Request(f"r{i}", list(p), max_new_tokens=6, model=m)
                for i, (p, m) in enumerate(prompts)]
        for r in reqs:
            eng.add_request(r)
        while any(not r.done for r in reqs):
            eng.step()
        assert all(r.finish_reason != "admission_failed" for r in reqs)
        return [r.generated for r in reqs]

    expected = run(None)
    n = len(jax.devices())
    mesh = create_mesh(MeshConfig(pp=2, dp=max(1, n // 2)))
    assert run(mesh) == expected
    assert expected[0] != expected[1]  # the adapter actually does something


def test_lora_lru_eviction_and_prefix_isolation(small_model, tmp_path):
    from ray_tpu.llm.lora import LoRAServingConfig, save_adapter

    cfg, params = small_model
    rng = np.random.default_rng(11)
    save_adapter(str(tmp_path / "a.npz"), _make_adapter(cfg, rng))
    save_adapter(str(tmp_path / "b.npz"), _make_adapter(cfg, rng))
    eng = InferenceEngine(
        cfg, params, max_slots=2, max_len=64,
        lora_config=LoRAServingConfig(max_loras=1, max_rank=4,
                                      dynamic_lora_loading_path=str(tmp_path)))
    prompt = list(range(1, 9))

    def run(model):
        r = Request(f"r-{model}-{np.random.randint(1e9)}", prompt,
                    max_new_tokens=4, model=model)
        eng.add_request(r)
        while not r.done:
            eng.step()
        return r.generated

    a1 = run("a")
    b1 = run("b")   # evicts a (max_loras=1)
    a2 = run("a")   # reloads a
    base = run(None)
    assert a1 == a2, "adapter a changed across LRU reload"
    assert a1 != b1 and a1 != base
    # prefix cache must be adapter-scoped: same prompt, different model,
    # yet outputs stayed adapter-faithful above (a2 == a1 after b ran
    # with the identical prompt proves no cross-adapter KV reuse).
    assert eng.metrics["prefix_hit_pages"] >= 0


def test_lora_openai_route(small_model, tmp_path):
    """`model` field on /v1/completions selects the adapter (reference
    LLMRouter + multiplex routing), no cluster needed."""
    from ray_tpu.llm.lora import save_adapter
    from ray_tpu.llm.serving import LLMDeployment

    cfg, params = small_model
    rng = np.random.default_rng(3)
    save_adapter(str(tmp_path / "tone.npz"), _make_adapter(cfg, rng))
    dep = LLMDeployment(
        "debug-128", max_slots=2, max_len=64,
        lora_config={"max_loras": 2, "max_rank": 4,
                     "dynamic_lora_loading_path": str(tmp_path)})
    try:
        base = dep.completions({"prompt": "hi", "max_tokens": 4})
        assert base["choices"][0]["finish_reason"] in ("length", "stop")
        tuned = dep.completions({"prompt": "hi", "max_tokens": 4,
                                 "model": "tone"})
        assert tuned["model"] == "tone"
    finally:
        dep.close()


@requires_shard_map
def test_tp_pp_composed_engine_parity(small_model):
    """TP x PP inference: layers staged over pp with tp auto-partitioned
    INSIDE each stage (partial-manual shard_map, axis_names={"pp"}) must
    stay token-identical to the single-device engine — the composed
    placement the reference gets from vLLM (vllm_models.py:117-168)."""
    from ray_tpu.parallel import MeshConfig, create_mesh

    cfg, params = small_model
    prompts = [list(range(1, 22)), [7, 3, 7, 3, 7],
               [2, 4, 6, 8, 10, 12, 14, 16, 18]]
    ref = InferenceEngine(cfg, params, max_slots=4, max_len=64, page_size=8)
    expected = [ref.generate(list(p), max_new_tokens=6) for p in prompts]

    n = len(jax.devices())
    mesh = create_mesh(MeshConfig(pp=2, tp=2, dp=max(1, n // 4)))
    eng = InferenceEngine(cfg, params, max_slots=4, max_len=64, page_size=8,
                          mesh=mesh)
    got = [eng.generate(list(p), max_new_tokens=6) for p in prompts]
    assert got == expected


@requires_shard_map
def test_pp_chunk_pipelined_prefill_parity(small_model):
    """Long prompts prefill as a chunk WAVEFRONT through the pp stages
    (pp_model.pp_prefill_chunks): up to pp consecutive full-size chunks
    per dispatch, token-identical to the single-device engine."""
    from ray_tpu.parallel import MeshConfig, create_mesh

    cfg, params = small_model
    prompt = list(range(1, 41))                    # 40 tokens: 2 full + tail
    ref = InferenceEngine(cfg, params, max_slots=2, max_len=64, page_size=8,
                          prefill_chunk_size=16)
    expected = ref.generate(list(prompt), max_new_tokens=6)

    n = len(jax.devices())
    mesh = create_mesh(MeshConfig(pp=2, dp=max(1, n // 2)))
    eng = InferenceEngine(cfg, params, max_slots=2, max_len=64, page_size=8,
                          prefill_chunk_size=16, mesh=mesh)
    got = eng.generate(list(prompt), max_new_tokens=6)
    assert got == expected
    # the pipelined path actually ran: 40 tokens = 2 pipelined + 1 tail
    assert eng.metrics["prefill_chunks"] >= 3


def test_page_allocator_lru_eviction_order():
    """ISSUE 7 satellite: among refcount-0 cached pages the LRU victim is
    evicted first, and eviction unregisters the page's prefix hash."""
    from ray_tpu.llm.engine import PageAllocator

    alloc = PageAllocator(4)
    pages = alloc.alloc(4)
    assert pages is not None and not alloc.free
    # release all four into the prefix cache with distinct LRU stamps
    # (monotonic stamps: release order == recency order)
    for i, pid in enumerate(pages):
        alloc.register_prefix(pid, b"h%d" % i)
        alloc.release(pid)
    assert alloc.available() == 4 and not alloc.free  # all cached, evictable
    # allocation under pressure evicts in LRU order: pages[0] first
    (fresh,) = alloc.alloc(1)
    assert fresh == pages[0]
    assert alloc.lookup_prefix(b"h0") is None       # hash unregistered
    assert alloc.lookup_prefix(b"h1") == pages[1]   # newer entries intact
    (fresh2,) = alloc.alloc(1)
    assert fresh2 == pages[1]


def test_page_allocator_refcount_roundtrip():
    """register_prefix + share/release refcounting: a cached page revives
    through lookup, is pinned while shared, and only becomes evictable at
    refcount 0."""
    from ray_tpu.llm.engine import PageAllocator

    alloc = PageAllocator(2)
    (pid,) = alloc.alloc(1)
    alloc.register_prefix(pid, b"hash")
    alloc.release(pid)                      # cached, refcount 0
    assert alloc.lookup_prefix(b"hash") == pid
    alloc.share(pid)                        # a second sequence adopts it
    alloc.share(pid)
    assert alloc.refcount[pid] == 2
    # pinned: eviction must never pick it, so only the 1 free page remains
    assert alloc.available() == 1
    got = alloc.alloc(2)
    assert got is None                      # pool under pressure, pin holds
    alloc.release(pid)
    assert alloc.refcount[pid] == 1 and alloc.available() == 1
    alloc.release(pid)                      # back to cached-evictable
    assert alloc.available() == 2
    got = alloc.alloc(2)                    # now eviction may claim it
    assert got is not None and pid in got
    assert alloc.lookup_prefix(b"hash") is None


def test_page_allocator_alloc_under_pressure_prefers_free():
    """alloc() takes free pages before evicting cached ones, and a
    non-prefix page releases back to the free list (not the cache)."""
    from ray_tpu.llm.engine import PageAllocator

    alloc = PageAllocator(3)
    a, b = alloc.alloc(2)
    alloc.register_prefix(a, b"ha")
    alloc.release(a)          # cached
    alloc.release(b)          # plain free
    assert b in alloc.free and a not in alloc.free
    got = alloc.alloc(2)      # 2 free pages available: no eviction needed
    assert got is not None
    assert alloc.lookup_prefix(b"ha") == a  # cache entry survived
    (third,) = alloc.alloc(1)               # now eviction must claim `a`
    assert third == a and alloc.lookup_prefix(b"ha") is None


def test_page_allocator_cow_fork_refcount_roundtrip():
    """ISSUE 10: share -> write forks EXACTLY one page. fork() allocates
    one fresh refcount-1 page; the shared original keeps its refcount and
    cache entries for its other readers, and releasing the reader's ref
    returns it to cached-evictable, never the free list."""
    from ray_tpu.llm.engine import PageAllocator

    alloc = PageAllocator(4)
    (pid,) = alloc.alloc(1)
    alloc.register_partial(b"root", (7, 8, 9), pid)
    alloc.release(pid)                      # cached partial, refcount 0
    assert alloc.match_partial(b"root", (7, 8, 9, 1), cap=7) == (pid, 3)
    alloc.share(pid)                        # reader A maps it
    alloc.share(pid)                        # reader B maps it
    free_before = len(alloc.free)
    fork = alloc.fork(pid)
    assert fork is not None and fork != pid
    assert alloc.refcount[fork] == 1        # exactly one fresh page
    assert alloc.refcount[pid] == 2         # original untouched
    assert len(alloc.free) == free_before - 1
    alloc.release(pid)                      # A swapped to its fork
    alloc.release(pid)                      # B retired
    assert alloc.refcount.get(pid, 0) == 0
    assert pid not in alloc.free            # cached-evictable, not freed
    assert alloc.match_partial(b"root", (7, 8, 9), cap=7) == (pid, 3)


def test_page_allocator_shared_pin_survives_pressure():
    """Shared pages (full-block AND partial-tail) are pinned: allocation
    pressure may evict every refcount-0 cached page but never a pinned
    one."""
    from ray_tpu.llm.engine import PageAllocator

    alloc = PageAllocator(3)
    full, tail, spare = alloc.alloc(3)
    alloc.register_prefix(full, b"chain0", b"root")
    alloc.register_partial(b"chain0", (1, 2), tail)
    alloc.release(full)
    alloc.release(tail)
    alloc.release(spare)
    alloc.share(full)                       # pin both shared pages
    alloc.share(tail)
    assert alloc.available() == 1           # only the spare is claimable
    assert alloc.alloc(2) is None           # pins hold under pressure
    (got,) = alloc.alloc(1)
    assert got == spare
    assert alloc.lookup_prefix(b"chain0") == full
    assert alloc.match_partial(b"chain0", (1, 2, 3), cap=7) == (tail, 2)


def test_page_allocator_partial_match_boundaries():
    """Trie match on partial-block boundaries: the match is the longest
    common prefix of the cached tail and the request's remainder, capped
    by the caller; a diverging first row or a wrong parent yields none;
    the longest of several entries wins."""
    from ray_tpu.llm.engine import PageAllocator

    alloc = PageAllocator(4)
    a, b = alloc.alloc(2)
    alloc.register_partial(b"p", (5, 6, 7, 8), a)
    alloc.register_partial(b"p", (5, 6), b)
    # full 4-row entry matches but the cap clamps the usable rows
    assert alloc.match_partial(b"p", (5, 6, 7, 8, 9), cap=3) == (a, 3)
    # divergence mid-tail: only the common prefix is usable
    assert alloc.match_partial(b"p", (5, 6, 99), cap=7) == (a, 2)
    # first row diverges: no match at all
    assert alloc.match_partial(b"p", (4, 6, 7), cap=7) is None
    # parent scoping: same tokens under another chain never match
    assert alloc.match_partial(b"q", (5, 6, 7), cap=7) is None


def test_page_allocator_trie_eviction_unlinks_subtree():
    """Evicting an interior chain node makes its cached descendants
    unreachable: they are unlinked and returned to the free pool (leaf
    entries are preferred victims, so this only happens once every leaf
    is gone)."""
    from ray_tpu.llm.engine import PageAllocator

    alloc = PageAllocator(3)
    p0, p1, tail = alloc.alloc(3)
    alloc.register_prefix(p0, b"c0", b"root")
    alloc.register_prefix(p1, b"c1", b"c0")
    alloc.register_partial(b"c1", (3, 4), tail)
    for pid in (p0, p1, tail):
        alloc.release(pid)
    assert alloc.available() == 3
    # leaf-first: the partial tail (a leaf) goes before the chain nodes
    (first,) = alloc.alloc(1)
    assert first == tail
    # evicting c0 (interior: c1 still hangs under it) unlinks c1 too
    alloc.release(first)  # plain free page now
    got = alloc.alloc(3)
    assert got is not None and set(got) == {p0, p1, tail}
    assert alloc.lookup_prefix(b"c0") is None
    assert alloc.lookup_prefix(b"c1") is None
    assert alloc.match_partial(b"c1", (3, 4), cap=7) is None

    # CASCADE: an interior node evicted while its child is PINNED — the
    # child loses its (unreachable) cache entry but stays allocated to
    # its reader, and only frees on the reader's final release.
    alloc2 = PageAllocator(2)
    q0, q1 = alloc2.alloc(2)
    alloc2.register_prefix(q0, b"d0", b"root")
    alloc2.register_prefix(q1, b"d1", b"d0")
    alloc2.release(q0)        # cached, refcount 0 — the only victim
    alloc2.share(q1)
    alloc2.release(q1)        # refcount 1: pinned by its reader
    (got2,) = alloc2.alloc(1)
    assert got2 == q0
    assert alloc2.lookup_prefix(b"d1") is None   # unlinked with parent
    alloc2.release(q1)
    assert q1 in alloc2.free  # pinned child frees on final release


def test_engine_cached_vs_cold_greedy_parity(small_model):
    """ISSUE 10 acceptance: greedy decode is byte-identical between a
    prefix-cached engine (full-block hits + a partial-tail COW fork,
    including a mid-sequence divergence) and naive full recompute, on
    uniform and mixed-batch workloads."""
    cfg, params = small_model
    eng = InferenceEngine(cfg, params, max_slots=4, max_len=64, page_size=8)
    prompt_a = list(range(1, 20))           # 19 tokens: 2 full pages + 3
    a = Request("a", list(prompt_a), max_new_tokens=4)
    eng.add_request(a)
    while not a.done:
        eng.step()
    assert a.generated == naive_greedy(params, cfg, prompt_a, 4)
    # Retire registered pages 0,1 as full blocks and the partial tail
    # (prompt rows 16-18 + generated rows) for COW sharing.

    # Uniform resend: full hits + partial rows -> only the last prompt
    # token is computed; the first suffix write forks the shared tail.
    b = Request("b", list(prompt_a), max_new_tokens=4)
    eng.add_request(b)
    while not b.done:
        eng.step()
    assert b.generated == a.generated
    assert b.cached_prefix_tokens == 18     # 2 pages + 2 partial rows
    assert eng.metrics["cow_forks"] >= 1

    # Mixed batch with a COW DIVERGENCE mid-sequence: two prompts share
    # the cached chain but diverge inside the partial tail block; both
    # map the shared page, each forks its own copy, and both decode
    # byte-identically to full recompute.
    forks_before = eng.metrics["cow_forks"]
    prompt_c = prompt_a[:17] + [99, 98, 97]
    prompt_d = prompt_a[:17] + [77, 76, 75, 74]
    c = Request("c", list(prompt_c), max_new_tokens=5)
    d = Request("d", list(prompt_d), max_new_tokens=5)
    eng.add_request(c)
    eng.add_request(d)
    while not (c.done and d.done):
        eng.step()
    assert c.generated == naive_greedy(params, cfg, prompt_c, 5)
    assert d.generated == naive_greedy(params, cfg, prompt_d, 5)
    assert c.cached_prefix_tokens == 17 and d.cached_prefix_tokens == 17
    assert eng.metrics["cow_forks"] >= forks_before + 2
    assert eng.metrics["prefix_cached_tokens"] > 0
    assert 0.0 < eng.prefill_suffix_frac < 1.0

    # COLD control: identical workload on a cache-disabled engine.
    cold = InferenceEngine(cfg, params, max_slots=4, max_len=64, page_size=8,
                           enable_prefix_cache=False)
    for rid, p, n in (("a2", prompt_a, 4), ("b2", prompt_a, 4),
                      ("c2", prompt_c, 5), ("d2", prompt_d, 5)):
        r = Request(rid, list(p), max_new_tokens=n)
        cold.add_request(r)
        while not r.done:
            cold.step()
        hot = {"a2": a, "b2": b, "c2": c, "d2": d}[rid]
        assert r.generated == hot.generated, rid
    assert cold.metrics["prefix_cached_tokens"] == 0


@requires_shard_map
def test_pp_partial_block_cow_parity(small_model):
    """Round 15 (PR 10 residue a): pp engines admit PARTIAL-block prefix
    hits. The pp prefill scatters rows at (page, offset) granularity, so
    a cached suffix can start mid-page on a COW-forked shared page —
    `supports_prefix_cow` is no longer gated off the pp path. Cached
    resend and a mid-tail divergence must decode byte-identically to
    full recompute, with real COW forks on the trie."""
    from ray_tpu.parallel import MeshConfig, create_mesh

    cfg, params = small_model
    n = len(jax.devices())
    mesh = create_mesh(MeshConfig(pp=2, dp=max(1, n // 2)))
    eng = InferenceEngine(cfg, params, max_slots=4, max_len=64, page_size=8,
                          mesh=mesh)
    assert eng._cow_enabled, "pp executor must support prefix COW now"

    prompt_a = list(range(1, 20))           # 2 full pages + 3 partial rows
    a = Request("a", list(prompt_a), max_new_tokens=4)
    eng.add_request(a)
    while not a.done:
        eng.step()
    assert a.generated == naive_greedy(params, cfg, prompt_a, 4)

    # Uniform resend: full-block hits + partial tail rows -> the suffix
    # starts MID-PAGE and the first write COW-forks the shared tail.
    b = Request("b", list(prompt_a), max_new_tokens=4)
    eng.add_request(b)
    while not b.done:
        eng.step()
    assert b.generated == a.generated
    assert b.cached_prefix_tokens == 18     # 2 pages + 2 partial rows
    assert eng.metrics["cow_forks"] >= 1

    # Mid-tail divergence: shares the chain, diverges inside the partial
    # block — forks its own copy, decodes identically to recompute.
    forks_before = eng.metrics["cow_forks"]
    prompt_c = prompt_a[:17] + [99, 98, 97]
    c = Request("c", list(prompt_c), max_new_tokens=5)
    eng.add_request(c)
    while not c.done:
        eng.step()
    assert c.generated == naive_greedy(params, cfg, prompt_c, 5)
    assert c.cached_prefix_tokens == 17
    assert eng.metrics["cow_forks"] > forks_before


def test_engine_multiturn_session_reuse(small_model):
    """Multi-turn session: turn 2's prompt embeds turn 1's prompt AND
    generated answer verbatim — generated-token pages registered at
    retire make the whole previous exchange a cache hit."""
    cfg, params = small_model
    eng = InferenceEngine(cfg, params, max_slots=2, max_len=64, page_size=8)
    turn1 = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5]       # 11 tokens
    r1 = Request("t1", list(turn1), max_new_tokens=8)
    eng.add_request(r1)
    while not r1.done:
        eng.step()
    assert r1.generated == naive_greedy(params, cfg, turn1, 8)
    follow = turn1 + r1.generated + [8, 8, 8]        # turn-2 prompt
    r2 = Request("t2", list(follow), max_new_tokens=4)
    eng.add_request(r2)
    while not r2.done:
        eng.step()
    assert r2.generated == naive_greedy(params, cfg, follow, 4)
    # 11 + 8 = 19 tokens of context; everything the engine wrote K/V
    # for (up to the last generated token) is reusable.
    assert r2.cached_prefix_tokens >= 16             # ≥ the 2 full pages


def test_mixed_dispatch_bounds_inter_token_latency(small_model):
    """ISSUE 7 acceptance: with a 2k-ish prompt admitted mid-stream, the
    token-budget mixed schedule keeps every running stream's max
    inter-token step gap STRICTLY below the legacy prefill-first
    schedule's, with byte-identical generated tokens."""
    cfg, params = small_model

    def run(budget, starvation):
        eng = InferenceEngine(
            cfg, params, max_slots=4, max_len=128, page_size=8,
            prefill_chunk_size=16, decode_steps_per_dispatch=2,
            prefill_token_budget=budget,
            decode_starvation_limit=starvation)
        a = Request("a", [1, 2, 3], max_new_tokens=30)
        eng.add_request(a)
        step_idx = 0
        emits: dict[str, list[int]] = {}

        def tick():
            nonlocal step_idx
            step_idx += 1
            for e in eng.step():
                emits.setdefault(e["request_id"], []).append(step_idx)

        for _ in range(4):
            tick()  # `a` is streaming
        long_prompt = list(range(1, 100))  # 99 tokens -> 7 chunks of 16
        b = Request("b", long_prompt, max_new_tokens=4)
        eng.add_request(b)
        while not (a.done and b.done):
            tick()
            assert step_idx < 500
        gaps = [j - i for i, j in zip(emits["a"], emits["a"][1:])]
        return a.generated, b.generated, max(gaps), eng.metrics

    # budget 0 + guard off = the old strict prefill-first schedule
    gen_a_old, gen_b_old, gap_old, m_old = run(budget=0, starvation=0)
    gen_a_mix, gen_b_mix, gap_mix, m_mix = run(budget=None, starvation=8)
    assert gen_a_mix == gen_a_old       # byte-identical running stream
    assert gen_b_mix == gen_b_old       # byte-identical admitted prompt
    assert gap_mix < gap_old, (gap_mix, gap_old)
    assert m_mix["engine_step_mix"]["mixed"] > 0
    assert m_old["decode_stall_steps"] >= 7   # one per prefill chunk
    assert m_mix["decode_stall_steps"] == 0   # decode rode every dispatch
    # and both agree with the ground-truth forward
    assert gen_a_mix == naive_greedy(params, cfg, [1, 2, 3], 30)
    assert gen_b_mix == naive_greedy(params, cfg, list(range(1, 100)), 4)


def test_decode_starvation_guard_on_legacy_path(small_model):
    """With mixed dispatch disabled (budget 0) the starvation guard still
    bounds decode stalls: after `decode_starvation_limit` consecutive
    prefill-only steps a decode burst is forced."""
    cfg, params = small_model
    eng = InferenceEngine(
        cfg, params, max_slots=4, max_len=128, page_size=8,
        prefill_chunk_size=16, decode_steps_per_dispatch=2,
        prefill_token_budget=0, decode_starvation_limit=2)
    a = Request("a", [1, 2, 3], max_new_tokens=30)
    eng.add_request(a)
    step_idx = 0
    emits: list[int] = []

    def tick():
        nonlocal step_idx
        step_idx += 1
        for e in eng.step():
            if e["request_id"] == "a":
                emits.append(step_idx)

    for _ in range(4):
        tick()
    b = Request("b", list(range(1, 100)), max_new_tokens=4)
    eng.add_request(b)
    while not (a.done and b.done):
        tick()
        assert step_idx < 500
    gaps = [j - i for i, j in zip(emits, emits[1:])]
    # guard fires after 2 stalled steps: gap bounded by limit+1, far
    # below the 8-step head-of-line block of the unguarded schedule
    assert max(gaps) <= 3, gaps
    assert eng.metrics["engine_step_mix"]["mixed"] == 0
    assert a.generated == naive_greedy(params, cfg, [1, 2, 3], 30)
    assert b.generated == naive_greedy(params, cfg, list(range(1, 100)), 4)


def test_mixed_dispatch_multi_prompt_budget(small_model):
    """Several admitted prompts share one mixed dispatch up to
    max_prefill_seqs_per_step/prefill_token_budget, and the
    prefix-cache hit-rate metric tracks lookups vs hits."""
    cfg, params = small_model
    eng = InferenceEngine(
        cfg, params, max_slots=4, max_len=64, page_size=8,
        prefill_chunk_size=16, decode_steps_per_dispatch=2,
        prefill_token_budget=32, max_prefill_seqs_per_step=2)
    a = Request("a", [1, 2, 3], max_new_tokens=24)
    eng.add_request(a)
    for _ in range(3):
        eng.step()
    reqs = [Request(f"p{i}", [10 + i] * 20, max_new_tokens=3)
            for i in range(3)]
    for r in reqs:
        eng.add_request(r)
    n = 0
    while not all(r.done for r in reqs + [a]):
        eng.step()
        n += 1
        assert n < 500
    assert eng.metrics["engine_step_mix"]["mixed"] > 0
    for r, orig in zip(reqs, range(3)):
        assert r.generated == naive_greedy(params, cfg, [10 + orig] * 20, 3)
    assert a.generated == naive_greedy(params, cfg, [1, 2, 3], 24)
    # hit-rate plumbing: lookups recorded, rate in [0, 1]
    assert eng.metrics["prefix_lookup_pages"] > 0
    assert 0.0 <= eng.prefix_cache_hit_rate <= 1.0
