"""Data library tests (reference patterns: python/ray/data/tests/)."""

import builtins

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd


def test_range_count_take(ray_cluster):
    ds = rd.range(100, parallelism=4)
    assert ds.count() == 100
    rows = ds.take(5)
    assert [r["id"] for r in rows] == [0, 1, 2, 3, 4]


def test_map_batches_fusion(ray_cluster):
    ds = (
        rd.range(64, parallelism=4)
        .map_batches(lambda b: {"id": b["id"] * 2})
        .map_batches(lambda b: {"id": b["id"] + 1})
    )
    from ray_tpu.data.executor import plan

    ops = plan(ds._last_op)
    assert len(ops) == 2  # Read + one fused Map
    out = sorted(r["id"] for r in ds.take_all())
    assert out == sorted((i * 2) + 1 for i in range(64))


def test_map_filter_flat_map(ray_cluster):
    ds = rd.from_items([{"x": i} for i in range(10)], parallelism=2)
    out = (
        ds.map(lambda r: {"x": r["x"] * 10})
        .filter(lambda r: r["x"] >= 50)
        .flat_map(lambda r: [{"x": r["x"]}, {"x": r["x"] + 1}])
    )
    vals = sorted(r["x"] for r in out.take_all())
    assert vals == sorted(v for i in range(5, 10) for v in (i * 10, i * 10 + 1))


def test_repartition_and_shuffle(ray_cluster):
    ds = rd.range(50, parallelism=5).repartition(3)
    mat = ds.materialize()
    assert mat.num_blocks() == 3
    assert mat.count() == 50

    shuffled = rd.range(50, parallelism=5).random_shuffle(seed=0)
    vals = [r["id"] for r in shuffled.take_all()]
    assert sorted(vals) == list(range(50))
    assert vals != list(range(50))


def test_sort(ray_cluster):
    ds = rd.from_items([{"v": i % 7, "i": i} for i in range(30)], parallelism=3)
    out = [r["v"] for r in ds.sort("v").take_all()]
    assert out == sorted(out)
    out_desc = [r["v"] for r in ds.sort("v", descending=True).take_all()]
    assert out_desc == sorted(out, reverse=True)


def test_limit_streaming(ray_cluster):
    ds = rd.range(1000, parallelism=10).limit(17)
    assert ds.count() == 17


def test_iter_batches_sizes(ray_cluster):
    ds = rd.range(100, parallelism=4)
    batches = list(ds.iter_batches(batch_size=32))
    sizes = [len(b["id"]) for b in batches]
    assert sum(sizes) == 100
    assert sizes[:-1] == [32, 32, 32]
    b0 = batches[0]
    assert isinstance(b0["id"], np.ndarray)


def test_tensor_columns_roundtrip(ray_cluster):
    arr = np.arange(60, dtype=np.float32).reshape(20, 3)
    ds = rd.from_numpy(arr, column="feat")
    batch = next(iter(ds.iter_batches(batch_size=None)))
    np.testing.assert_array_equal(batch["feat"], arr)
    out = ds.map_batches(lambda b: {"feat": b["feat"] * 2.0}).take_all()
    np.testing.assert_allclose(out[0]["feat"], arr[0] * 2.0)


def test_parquet_roundtrip(ray_cluster, tmp_path):
    ds = rd.range(40, parallelism=2)
    ds.write_parquet(str(tmp_path / "pq"))
    back = rd.read_parquet(str(tmp_path / "pq"))
    assert back.count() == 40
    assert sorted(r["id"] for r in back.take_all()) == list(range(40))


def test_streaming_split_feeds_all_consumers(ray_cluster):
    ds = rd.range(60, parallelism=6)
    its = ds.streaming_split(2)
    seen = []
    for it in its:
        for batch in it.iter_batches(batch_size=None):
            seen.extend(batch["id"].tolist())
    assert sorted(seen) == list(range(60))


def test_map_batches_actor_pool_stateful(ray_cluster):
    """A class fn is constructed once per pool actor (the inference
    pattern); results are correct and block order is preserved."""
    from ray_tpu.data import ActorPoolStrategy

    class AddModel:
        def __init__(self, offset):
            import os

            self.offset = offset
            self.pid = os.getpid()

        def __call__(self, batch):
            return {"id": batch["id"] + self.offset, "pid": np.full(len(batch["id"]), self.pid)}

    ds = rd.range(40, parallelism=4)
    out = ds.map_batches(
        AddModel, compute=ActorPoolStrategy(size=2), fn_constructor_args=(100,)
    ).take_all()
    assert sorted(r["id"] for r in out) == list(builtins.range(100, 140))
    # constructed per-actor, not per-block: at most pool-size distinct pids
    assert len({r["pid"] for r in out}) <= 2


def test_read_text_and_binary(ray_cluster, tmp_path):
    (tmp_path / "a.txt").write_text("alpha\nbeta\n")
    (tmp_path / "b.txt").write_text("gamma\n")
    ds = rd.read_text([str(tmp_path / "a.txt"), str(tmp_path / "b.txt")])
    assert sorted(r["text"] for r in ds.take_all()) == ["alpha", "beta", "gamma"]

    (tmp_path / "blob.bin").write_bytes(b"\x00\x01\x02")
    rows = rd.read_binary_files(str(tmp_path / "blob.bin")).take_all()
    assert rows[0]["bytes"] == b"\x00\x01\x02"


def test_union_and_write_json(ray_cluster, tmp_path):
    import json

    a = rd.range(5, parallelism=1)
    b = rd.range(5, parallelism=1).map(lambda r: {"id": r["id"] + 10})
    u = a.union(b)
    assert sorted(r["id"] for r in u.take_all()) == [0, 1, 2, 3, 4, 10, 11, 12, 13, 14]

    u.write_json(str(tmp_path / "out"))
    rows = []
    for f in sorted((tmp_path / "out").iterdir()):
        rows += [json.loads(line) for line in f.read_text().splitlines()]
    assert sorted(r["id"] for r in rows) == [0, 1, 2, 3, 4, 10, 11, 12, 13, 14]


def test_groupby_aggregations(ray_cluster):
    """groupby().count/sum/min/max/mean through the hash exchange with
    map-side partial aggregation (reference grouped_data.py:21)."""
    rows = [{"k": i % 3, "v": float(i)} for i in range(30)]
    ds = rd.from_items(rows, parallelism=4)

    counts = {r["k"]: r["count()"] for r in ds.groupby("k").count().take_all()}
    assert counts == {0: 10, 1: 10, 2: 10}

    sums = {r["k"]: r["sum(v)"] for r in ds.groupby("k").sum("v").take_all()}
    assert sums == {k: sum(float(i) for i in range(30) if i % 3 == k) for k in range(3)}

    mins = {r["k"]: r["min(v)"] for r in ds.groupby("k").min("v").take_all()}
    assert mins == {0: 0.0, 1: 1.0, 2: 2.0}

    maxs = {r["k"]: r["max(v)"] for r in ds.groupby("k").max("v").take_all()}
    assert maxs == {0: 27.0, 1: 28.0, 2: 29.0}

    means = {r["k"]: r["mean(v)"] for r in ds.groupby("k").mean("v").take_all()}
    assert means == {k: sums[k] / 10 for k in range(3)}

    multi = ds.groupby("k").aggregate(("v", "sum"), ("v", "max")).take_all()
    assert {r["k"]: (r["sum(v)"], r["max(v)"]) for r in multi} == {
        k: (sums[k], maxs[k]) for k in range(3)}


def test_groupby_map_groups(ray_cluster):
    ds = rd.from_items([{"k": i % 2, "v": i} for i in range(10)], parallelism=3)

    def normalize(batch):
        v = batch["v"]
        return {"k": batch["k"][:1], "spread": [int(v.max() - v.min())]}

    out = ds.groupby("k").map_groups(normalize).take_all()
    assert sorted((r["k"], r["spread"]) for r in out) == [(0, 8), (1, 8)]


def test_join_inner_and_left(ray_cluster):
    left = rd.from_items([{"id": i, "a": i * 10} for i in range(8)], parallelism=3)
    right = rd.from_items([{"id": i, "b": i * 100} for i in range(0, 8, 2)], parallelism=2)

    inner = left.join(right, on="id").take_all()
    assert sorted((r["id"], r["a"], r["b"]) for r in inner) == [
        (i, i * 10, i * 100) for i in range(0, 8, 2)]

    outer = left.join(right, on="id", how="left outer").take_all()
    assert len(outer) == 8
    matched = {r["id"]: r["b"] for r in outer if r["b"] is not None}
    assert matched == {i: i * 100 for i in range(0, 8, 2)}


def test_zip(ray_cluster):
    a = rd.from_items([{"x": i} for i in range(12)], parallelism=3)
    b = rd.from_items([{"y": i * 2} for i in range(12)], parallelism=4)  # misaligned blocks
    out = a.zip(b).take_all()
    assert sorted((r["x"], r["y"]) for r in out) == [(i, i * 2) for i in range(12)]

    with pytest.raises(ValueError, match="equal row counts"):
        a.zip(rd.from_items([{"y": 1}], parallelism=1)).take_all()


def test_shuffle_exchange_is_partitioned(ray_cluster):
    """random_shuffle runs as a map-reduce exchange: output arrives as
    multiple partition blocks (not one consolidation block), preserves the
    multiset, and actually permutes."""
    ds = rd.range(2000, parallelism=8).random_shuffle(seed=7)
    refs = list(ds.iter_internal_ref_bundles())
    assert len(refs) > 1, "shuffle must emit one block per partition"
    rows = [r["id"] for r in ds.iter_rows()]
    assert sorted(rows) == list(builtins.range(2000))
    assert rows != sorted(rows)


def test_sort_exchange_range_partitioned(ray_cluster):
    """sort samples boundaries and range-partitions; the global stream is
    ordered across partition blocks."""
    import random

    vals = list(builtins.range(500))
    random.Random(3).shuffle(vals)
    ds = rd.from_items([{"v": v} for v in vals], parallelism=6).sort("v")
    refs = list(ds.iter_internal_ref_bundles())
    assert len(refs) > 1
    out = [r["v"] for r in ds.iter_rows()]
    assert out == sorted(vals)


def test_sort_string_keys(ray_cluster):
    """Range boundaries come from order statistics, so non-numeric (string)
    sort keys partition correctly (regression: np.quantile TypeError)."""
    import random

    words = [f"w{i:03d}" for i in builtins.range(120)]
    shuffled = list(words)
    random.Random(11).shuffle(shuffled)
    ds = rd.from_items([{"s": w} for w in shuffled], parallelism=5).sort("s")
    out = [r["s"] for r in ds.iter_rows()]
    assert out == sorted(words)
    out_desc = [r["s"] for r in rd.from_items(
        [{"s": w} for w in shuffled], parallelism=5).sort("s", descending=True).iter_rows()]
    assert out_desc == sorted(words, reverse=True)


def test_join_empty_left_side(ray_cluster):
    """A join whose left upstream produced zero blocks must not crash the
    reduce tasks (regression: _concat_keep_schema IndexError)."""
    left = rd.from_items([], parallelism=1)
    right = rd.from_items([{"id": i, "b": i} for i in builtins.range(6)], parallelism=2)
    out = left.join(right, on="id").take_all()
    assert out == []


def test_parquet_row_group_streaming_tasks(ray_cluster, tmp_path):
    """A parquet file with many row groups splits into row-group-granular
    read tasks (bounded memory for larger-than-RAM datasets) and streams
    the right rows through streaming_split consumers."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    path = tmp_path / "big"
    path.mkdir()
    n = 20_000
    table = pa.table({"x": np.arange(n, dtype=np.int64)})
    pq.write_table(table, str(path / "data.parquet"), row_group_size=1000)  # 20 groups

    ds = rd.read_parquet(str(path), row_groups_per_task=2)
    assert len(ds._last_op.read_tasks) == 10, "expected one task per 2 row groups"

    seen = []
    its = ds.streaming_split(2)

    def consume(it):
        for b in it.iter_batches(batch_size=4096):
            seen.extend(b["x"].tolist())

    import threading

    threads = [threading.Thread(target=consume, args=(it,)) for it in its]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert sorted(seen) == list(builtins.range(n))


def test_filesystem_uri_roundtrip(ray_cluster, tmp_path):
    """file:// URIs resolve through pyarrow.fs — the same code path as
    gs:// / s3:// buckets (zero-egress env: local fs stands in)."""
    uri = "file://" + str(tmp_path / "out")
    rd.range(100, parallelism=2).write_parquet(uri)
    back = rd.read_parquet(uri)
    assert back.count() == 100
    assert sorted(r["id"] for r in back.take_all()) == list(builtins.range(100))

    rd.from_items([{"a": 1}, {"a": 2}]).write_json("file://" + str(tmp_path / "j"))
    assert sorted(r["a"] for r in rd.read_json(
        "file://" + str(tmp_path / "j")).take_all()) == [1, 2]


def test_read_images(ray_cluster, tmp_path):
    from PIL import Image

    d = tmp_path / "imgs"
    d.mkdir()
    for i in builtins.range(5):
        arr = np.full((8, 6, 3), i * 10, np.uint8)
        Image.fromarray(arr).save(str(d / f"im{i}.png"))
    ds = rd.read_images(str(d), size=(4, 4), mode="RGB")
    rows = ds.take_all()
    assert len(rows) == 5
    imgs = sorted(rows, key=lambda r: r["path"])
    assert np.asarray(imgs[0]["image"]).shape == (4, 4, 3)
    assert int(np.asarray(imgs[3]["image"]).mean()) == 30


def test_preprocessors_scalers_encoders_chain(ray_cluster):
    """Preprocessor contract (fit -> transform -> transform_batch) and
    the standard library: scalers, encoders, imputer, concatenator,
    chain (reference python/ray/data/preprocessors/)."""
    import numpy as np
    import pytest as _pytest

    from ray_tpu import data
    from ray_tpu.data.preprocessors import (
        Chain, Concatenator, LabelEncoder, MinMaxScaler, OneHotEncoder,
        PreprocessorNotFittedError, SimpleImputer, StandardScaler)

    rows = [{"x": float(i), "y": float(i * 2), "cat": ["a", "b", "c"][i % 3],
             "label": ["pos", "neg"][i % 2]} for i in range(30)]
    ds = data.from_items(rows)

    with _pytest.raises(PreprocessorNotFittedError):
        StandardScaler(["x"]).transform(ds)

    # StandardScaler: mean ~0 std ~1
    sc = StandardScaler(["x", "y"]).fit(ds)
    out = sc.transform(ds).take_all()
    xs = np.asarray([r["x"] for r in out])
    assert abs(xs.mean()) < 1e-6 and abs(xs.std() - 1.0) < 1e-6

    # MinMaxScaler: [0, 1]
    mm = MinMaxScaler(["x"]).fit(ds)
    out = mm.transform(ds).take_all()
    xs = [r["x"] for r in out]
    assert min(xs) == 0.0 and max(xs) == 1.0

    # LabelEncoder: ints + inverse; unseen label raises
    le = LabelEncoder("label").fit(ds)
    out = le.transform(ds).take_all()
    assert {r["label"] for r in out} == {0, 1}
    back = le.inverse_transform_batch({"label": np.asarray([0, 1])})
    assert set(back["label"].tolist()) == {"neg", "pos"}
    with _pytest.raises(ValueError, match="not seen"):
        le.transform_batch({"label": np.asarray(["mystery"])})

    # OneHotEncoder: per-value 0/1 columns, source dropped, unseen -> zeros
    oh = OneHotEncoder(["cat"]).fit(ds)
    b = oh.transform_batch({"cat": np.asarray(["a", "zz"])})
    assert "cat" not in b
    assert b["cat_a"].tolist() == [1, 0]
    assert b["cat_b"].tolist() == [0, 0] and b["cat_c"].tolist() == [0, 0]

    # SimpleImputer: mean fill
    ds_nan = data.from_items([{"v": 1.0}, {"v": float("nan")}, {"v": 3.0}])
    imp = SimpleImputer(["v"]).fit(ds_nan)
    vals = sorted(r["v"] for r in imp.transform(ds_nan).take_all())
    assert vals == [1.0, 2.0, 3.0]

    # Concatenator: 2-D feature column
    cat = Concatenator(columns=["x", "y"], output_column_name="features")
    b = cat.transform_batch({"x": np.asarray([1.0, 2.0]),
                             "y": np.asarray([3.0, 4.0])})
    assert b["features"].shape == (2, 2)

    # Chain: scale -> encode -> concat, fit end-to-end, batch path too
    chain = Chain(StandardScaler(["x"]), LabelEncoder("label"),
                  Concatenator(columns=["x", "y"], output_column_name="f"))
    out = chain.fit_transform(ds).take_all()
    assert set(out[0]) == {"cat", "label", "f"}
    b = chain.transform_batch({"x": np.asarray([0.0]), "y": np.asarray([1.0]),
                               "cat": np.asarray(["a"]),
                               "label": np.asarray(["pos"])})
    assert b["f"].shape == (1, 2) and b["label"].tolist() == [1]


# ------------------------------------------------------- tfrecords / hf / stats

def test_tfrecords_roundtrip(ray_cluster, tmp_path):
    """Write tf.train.Example shards with the native codec, read them
    back through the streaming executor (reference
    tfrecords_datasource.py; no TensorFlow import)."""
    from ray_tpu import data

    rows = [{"idx": i, "name": f"row-{i}", "vec": [float(i), i + 0.5],
             "blob": bytes([i, i + 1])} for i in range(10)]
    ds1 = data.from_items(rows, parallelism=3)
    ds1.write_tfrecords(str(tmp_path))
    import glob
    shards = sorted(glob.glob(str(tmp_path / "*.tfrecords")))
    assert len(shards) >= 1

    back = data.read_tfrecords(str(tmp_path)).take_all()
    back.sort(key=lambda r: r["idx"])
    for orig, got in zip(rows, back):
        assert got["idx"] == orig["idx"]
        assert got["name"] == orig["name"].encode()  # bytes feature
        assert got["blob"] == orig["blob"]
        assert [round(v, 4) for v in got["vec"]] == orig["vec"]


def test_webdataset_roundtrip(ray_cluster, tmp_path):
    """Write tar shards in the webdataset layout (one member per column
    per row, grouped by stem), read them back through the streaming
    executor (reference webdataset_datasource.py; ROADMAP item 8)."""
    from ray_tpu import data

    rows = [{"cls": i, "txt": f"caption {i}", "json": {"i": i, "tag": "x"},
             "bin": bytes([i, 255 - i])} for i in range(10)]
    ds1 = data.from_items(rows, parallelism=3)
    ds1.write_webdataset(str(tmp_path))
    import glob
    shards = sorted(glob.glob(str(tmp_path / "*.tar")))
    assert len(shards) >= 1
    # shards are REAL tar files any webdataset consumer can open
    import tarfile
    with tarfile.open(shards[0]) as tf:
        names = tf.getnames()
    assert any(n.endswith(".txt") for n in names)

    back = data.read_webdataset(str(tmp_path)).take_all()
    back.sort(key=lambda r: r["cls"])
    for orig, got in zip(rows, back):
        assert got["cls"] == orig["cls"]          # int-decoded extension
        assert got["txt"] == orig["txt"]          # text-decoded
        assert got["json"] == orig["json"]        # parsed json
        assert got["bin"] == orig["bin"]          # raw bytes
        assert got["__key__"]                      # sample stem column


def test_webdataset_sample_grouping_and_key():
    """Members group into samples by stem in stream order; an explicit
    __key__ column round-trips as member basenames."""
    import io
    import tarfile

    from ray_tpu.data import webdataset as wds

    buf = io.BytesIO()
    wds.write_shard(buf, [{"__key__": "s/a", "txt": "one", "cls": 1},
                          {"__key__": "s/b", "txt": "two", "cls": 2}])
    buf.seek(0)
    with tarfile.open(fileobj=buf) as tf:
        assert sorted(tf.getnames()) == [
            "s/a.cls", "s/a.txt", "s/b.cls", "s/b.txt"]
    buf.seek(0)
    samples = wds.iter_samples(buf)
    assert samples == [{"__key__": "s/a", "txt": "one", "cls": 1},
                       {"__key__": "s/b", "txt": "two", "cls": 2}]


def test_tfrecords_interop_with_tensorflow_writer(tmp_path):
    """Cross-check the native TFRecord framing + Example codec against a
    record written byte-for-byte by the spec (masked crc32c vectors)."""
    from ray_tpu.data import tfrecords as tfr

    # crc32c known-answer test (Castagnoli): crc32c(b"123456789")
    assert tfr.crc32c(b"123456789") == 0xE3069283
    payload = tfr.encode_example({"a": 1, "b": "x"})
    import io

    buf = io.BytesIO()
    tfr.write_record(buf, payload)
    buf.seek(0)
    records = list(tfr.read_records(buf))
    assert records == [payload]
    assert tfr.parse_example(payload) == {"a": 1, "b": b"x"}


def test_from_huggingface_and_stats(ray_cluster):
    from ray_tpu import data
    import pyarrow as pa

    # duck-typed HF dataset: .data exposes the arrow table
    class FakeHF:
        def __init__(self, table):
            self.data = table

    table = pa.table({"x": list(range(100)), "y": [i * 2 for i in range(100)]})
    ds1 = data.from_huggingface(FakeHF(table), parallelism=4)
    out = ds1.map_batches(lambda b: {"z": b["x"] + b["y"]}).take_all()
    assert [r["z"] for r in out] == [i * 3 for i in range(100)]

    # per-op stats surfaced after execution (reference _internal/stats.py)
    ds2 = data.from_huggingface(table, parallelism=4).map_batches(
        lambda b: {"x2": b["x"] * 2})
    ds2.take_all()
    report = ds2.stats()
    assert "Read" in report and "tasks" in report and "wall" in report


def test_avro_roundtrip(ray_cluster, tmp_path):
    """Write Avro Object Container File shards with the native codec,
    read them back through the streaming executor (reference
    read_api.read_avro; ROADMAP item 8, closing the readers backlog)."""
    import glob

    from ray_tpu import data

    rows = [{"id": i, "score": i * 0.5, "name": f"row {i}",
             "blob": bytes([i, 7]), "flag": i % 2 == 0,
             "vec": [i, i + 1, i + 2],
             "maybe": None if i % 3 == 0 else f"v{i}"}
            for i in range(20)]
    ds1 = data.from_items(rows, parallelism=3)
    ds1.write_avro(str(tmp_path))
    shards = sorted(glob.glob(str(tmp_path / "*.avro")))
    assert len(shards) >= 1
    # shards carry the spec'd container magic + self-describing schema
    with open(shards[0], "rb") as f:
        head = f.read(256)
    assert head.startswith(b"Obj\x01") and b"avro.schema" in head

    back = data.read_avro(str(tmp_path)).take_all()
    back.sort(key=lambda r: r["id"])
    assert len(back) == len(rows)
    for orig, got in zip(rows, back):
        assert got["id"] == orig["id"]
        assert got["score"] == orig["score"]
        assert got["name"] == orig["name"]
        assert got["blob"] == orig["blob"]
        assert got["flag"] == orig["flag"]
        assert list(got["vec"]) == orig["vec"]
        assert got["maybe"] == orig["maybe"]          # nullable union


def test_avro_codec_units():
    """Container-level invariants: zig-zag longs, schema inference
    (nullable unions, arrays, long+double merge), sync-marker check, and
    numpy normalization."""
    import io

    import numpy as np
    import pytest

    from ray_tpu.data import avro

    # zig-zag longs round-trip across the signed range
    for v in (0, -1, 1, 63, -64, 2**40, -(2**40)):
        buf = bytearray()
        avro._write_long(buf, v)
        assert avro._read_long(io.BytesIO(bytes(buf))) == v

    schema = avro.infer_schema([
        {"a": 1, "b": [1.5], "c": None}, {"a": 2.5, "b": [], "c": "x"}])
    by_name = {f["name"]: f["type"] for f in schema["fields"]}
    assert by_name["a"] == "double"                     # long+double merge
    assert by_name["b"] == {"type": "array", "items": "double"}
    assert by_name["c"] == ["null", "string"]

    # numpy arrays/scalars normalize through tolist
    buf = io.BytesIO()
    avro.write_container(buf, [{"x": np.int64(3), "y": np.arange(4)}])
    buf.seek(0)
    (row,) = avro.read_container(buf)
    assert row == {"x": 3, "y": [0, 1, 2, 3]}

    # corrupt sync marker fails loudly, not with garbage rows
    data_bytes = bytearray(buf.getvalue())
    data_bytes[-1] ^= 0xFF
    with pytest.raises(ValueError, match="sync"):
        avro.read_container(io.BytesIO(bytes(data_bytes)))
