"""Zygote-pool worker spawn (ISSUE 14 tentpole a).

Env-hash keying is the safety net: a pooled worker must NEVER be handed
to a lease with a different ``_env_hash`` (a silently wrong interpreter/
env is worse than a slow spawn), interpreter-level envs must always pay
the cold spawn (the PR 1 enforcement path), and a pool key falling off
the LRU must take its zygote AND its idle workers with it.
"""

from __future__ import annotations

import sys
import time

import pytest

import ray_tpu
from ray_tpu.core.config import get_config
from ray_tpu.core.raylet import Raylet


@pytest.fixture()
def _pool_knobs():
    cfg = get_config()
    keys = ("zygote_pool_size", "zygote_pool_refill_batch",
            "zygote_pool_max_keys", "enable_worker_zygote",
            "idle_worker_killing_time_threshold_ms", "num_prestart_workers")
    saved = {k: getattr(cfg, k) for k in keys}
    yield cfg
    for k, v in saved.items():
        setattr(cfg, k, v)


def _raylet() -> Raylet:
    from ray_tpu.core import api as core_api

    return core_api._node.raylet


# ------------------------------------------------------------ eligibility


def test_interp_envs_never_zygote_eligible():
    """conda / py_executable / container / image_uri can never fork from
    a zygote of THIS interpreter — those must cold-spawn."""
    assert Raylet._zygote_eligible(None)
    assert Raylet._zygote_eligible({})
    assert Raylet._zygote_eligible({"env_vars": {"A": "1"}})
    assert Raylet._zygote_eligible({"working_dir": "/tmp"})
    assert Raylet._zygote_eligible({"pip": ["x"]})
    assert not Raylet._zygote_eligible({"py_executable": sys.executable})
    assert not Raylet._zygote_eligible({"conda": "base"})
    assert not Raylet._zygote_eligible({"container": {"image": "x"}})
    assert not Raylet._zygote_eligible({"image_uri": "img:tag"})


def test_interp_env_spawn_is_cold_and_untracked(ray_cluster, _pool_knobs):
    """A py_executable spawn takes the direct path: spawn_mode 'cold',
    no zygote booted for its env key, no pool key tracked."""
    raylet = _raylet()
    renv = {"py_executable": sys.executable}
    env_hash = raylet._env_hash(renv)
    before_keys = set(raylet._zygotes)
    handle = raylet._start_worker(renv)
    try:
        assert handle.spawn_mode == "cold"
        assert env_hash not in raylet._zygotes
        assert env_hash not in raylet._pool_keys
        assert set(raylet._zygotes) == before_keys
    finally:
        handle.proc.terminate()
        raylet._workers.pop(handle.worker_id, None)


# --------------------------------------------------------- env-hash match


def test_pooled_worker_never_handed_to_mismatched_lease(ray_cluster,
                                                        _pool_knobs):
    """Raylet-level contract: an idle pooled worker of env A is invisible
    to a lease wanting env B (and to the default env), in _get_idle_worker
    AND in the multiplexed extra-grant scan."""
    raylet = _raylet()
    env_a = {"env_vars": {"POOL_TEST_ENV": "a"}}
    env_b = {"env_vars": {"POOL_TEST_ENV": "b"}}
    hash_a, hash_b = raylet._env_hash(env_a), raylet._env_hash(env_b)
    assert hash_a != hash_b != ""

    @ray_tpu.remote(runtime_env=env_a)
    def probe_a():
        import os

        return os.environ.get("POOL_TEST_ENV")

    @ray_tpu.remote(runtime_env=env_b)
    def probe_b():
        import os

        return os.environ.get("POOL_TEST_ENV")

    # Workers of each env exist and are keyed correctly end to end: the
    # env var actually differs inside the processes.
    assert ray_tpu.get([probe_a.remote(), probe_b.remote()],
                       timeout=120) == ["a", "b"]
    by_hash = {}
    for w in raylet._workers.values():
        if w.state in ("idle", "leased"):
            by_hash.setdefault(w.env_hash, 0)
            by_hash[w.env_hash] += 1
    assert by_hash.get(hash_a, 0) >= 1
    assert by_hash.get(hash_b, 0) >= 1

    async def _mismatch_scan():
        # env-B lease must not pop an idle env-A worker even when only
        # env-A workers are idle: give it a near-zero timeout and check
        # the worker it returns (if any) is env-B keyed.
        w = await raylet._get_idle_worker(0.05, env_b)
        return w

    from ray_tpu.core import api as core_api

    w = core_api._node.services_loop.run_sync(_mismatch_scan(), timeout=30)
    if w is not None:
        assert w.env_hash == hash_b
        w.state = "idle"
        raylet._idle.append(w.worker_id)


# ------------------------------------------------------------ pool/evict


def test_pool_eviction_on_env_mismatch(ray_cluster, _pool_knobs):
    """Over zygote_pool_max_keys the LRU env key is evicted: pool key
    gone, its zygote killed, its idle workers reaped."""
    cfg = _pool_knobs
    cfg.zygote_pool_max_keys = 2
    raylet = _raylet()
    envs = [{"env_vars": {"POOL_EVICT_TEST": str(i)}} for i in range(3)]
    hashes = [raylet._env_hash(e) for e in envs]

    @ray_tpu.remote
    def mk(i):
        return i

    # Touch three env keys in order via the lease path.
    for i, env in enumerate(envs):
        ray_tpu.get(mk.options(runtime_env=env).remote(i), timeout=120)
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and hashes[0] in raylet._pool_keys:
        time.sleep(0.1)
    # Key 0 (least recently leased) was evicted; 1 and 2 survive.
    assert hashes[0] not in raylet._pool_keys
    assert hashes[1] in raylet._pool_keys
    assert hashes[2] in raylet._pool_keys
    assert hashes[0] not in raylet._zygotes
    # ... and no idle worker of the evicted env remains.
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        stale = [wid for wid in raylet._idle
                 if (w := raylet._workers.get(wid))
                 and w.env_hash == hashes[0]]
        if not stale:
            break
        time.sleep(0.1)
    assert not stale


def test_idle_pool_shrinks_to_target(ray_cluster, _pool_knobs):
    """Idle worker killing: a burst that balloons the default pool is
    reaped back toward the prestart/pool target after the idle
    threshold."""
    cfg = _pool_knobs
    cfg.idle_worker_killing_time_threshold_ms = 300
    raylet = _raylet()

    @ray_tpu.remote
    def burst(i):
        time.sleep(0.05)
        return i

    ray_tpu.get([burst.remote(i) for i in range(12)], timeout=120)
    target = max(cfg.num_prestart_workers, cfg.zygote_pool_size)
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        idle_default = sum(1 for wid in raylet._idle
                           if (w := raylet._workers.get(wid))
                           and w.env_hash == "")
        if idle_default <= target:
            break
        time.sleep(0.1)
    assert idle_default <= target, (idle_default, target)


# ----------------------------------------------------------- spawn modes


def test_spawn_histogram_records_pooled_and_cold(ray_cluster, _pool_knobs):
    """The ray_tpu_worker_spawn_ms histogram carries both modes, and the
    raylet's spawn counters saw pooled forks (the zygote is live in this
    suite)."""
    raylet = _raylet()

    @ray_tpu.remote
    def touch():
        return 1

    ray_tpu.get([touch.remote() for _ in range(8)], timeout=120)
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline and not raylet._spawn_stats.get("pooled"):
        ray_tpu.get(touch.remote(), timeout=60)
        time.sleep(0.2)
    assert raylet._spawn_stats.get("pooled", 0) >= 1
    from ray_tpu.core.raylet import _spawn_hist

    snap = _spawn_hist().snapshot()
    modes = {row["tags"].get("mode") for row in snap}
    assert "pooled" in modes
    pooled = next(r for r in snap if r["tags"].get("mode") == "pooled")
    assert pooled["count"] >= 1
