import numpy as np
import pytest

from ray_tpu.core import serialization
from ray_tpu.core.status import RayTaskError


def roundtrip(value):
    meta, blob, refs = serialization.serialize(value)
    return serialization.deserialize(meta, blob)


def test_basic_types():
    for v in [1, "x", None, [1, 2, {"a": (3, 4)}], b"bytes", 3.5]:
        assert roundtrip(v) == v


def test_numpy_zero_copy_framing():
    arr = np.arange(10000, dtype=np.float64)
    meta, blob, _ = serialization.serialize(arr)
    out = serialization.deserialize(meta, memoryview(blob))
    np.testing.assert_array_equal(out, arr)
    # The array buffer must be stored out-of-band (not doubled into pickle).
    assert len(blob) < arr.nbytes + 4096


def test_alignment():
    arr = np.ones(1000, dtype=np.float32)
    meta, blob, _ = serialization.serialize(arr)
    bufs = serialization._unframe(blob)
    for b in bufs:
        # offsets are 64-byte aligned within the blob
        pass
    assert len(bufs) >= 2


def test_error_objects():
    err = RayTaskError("f", "traceback here", ValueError("x"))
    meta, blob, _ = serialization.serialize_error(err)
    assert meta == serialization.META_ERROR
    out = serialization.deserialize(meta, blob)
    assert isinstance(out, RayTaskError)
    assert isinstance(out.cause, ValueError)


def test_nested_object_ref_capture():
    import ray_tpu  # ensures ObjectRef serializer registered
    from ray_tpu.core.ids import ObjectID
    from ray_tpu.core.object_ref import ObjectRef

    ref = ObjectRef(ObjectID.from_random(), "addr:1", _add_local_ref=False)
    meta, blob, contained = serialization.serialize({"inner": ref})
    assert len(contained) == 1
    assert contained[0].id() == ref.id()
