"""Serve: controller/replica FSM, router, rolling update, autoscaling,
HTTP ingress, replica-kill recovery.

Mirrors the reference's ``python/ray/serve/tests/`` acceptance surface
(controller.py:84, deployment_state.py:1249, pow_2_scheduler.py:52,
long_poll.py:204).
"""

import json
import textwrap
import threading
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture()
def serve_instance(ray_cluster):
    yield
    serve.shutdown()


def _http_get(url, timeout=30):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return json.loads(r.read())
    except urllib.error.HTTPError as e:
        return json.loads(e.read())


@serve.deployment(num_replicas=2, max_ongoing_requests=4)
class Echo:
    def __init__(self, prefix=""):
        self.prefix = prefix

    def __call__(self, request):
        return {"echo": self.prefix + request.query_params.get("msg", "")}


def test_echo_http_and_handle(serve_instance):
    handle = serve.run(Echo.bind("p:"), name="default", route_prefix="/")
    assert handle.remote(serve.Request(query={"msg": "x"})).result(timeout=60) == {"echo": "p:x"}
    addr = serve.http_address()
    assert _http_get(addr + "/?msg=y") == {"echo": "p:y"}
    assert _http_get(addr + "/-/healthz") == "ok"


def test_concurrent_http_traffic(serve_instance):
    serve.run(Echo.bind(), name="default", route_prefix="/")
    addr = serve.http_address()
    results, errors = [], []

    def worker(i):
        try:
            results.append(_http_get(f"{addr}/?msg={i}", timeout=60))
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=90)
    assert not errors
    assert sorted(r["echo"] for r in results) == sorted(str(i) for i in range(16))


def test_model_composition(serve_instance):
    """Ingress deployment calling a downstream deployment by handle."""

    @serve.deployment
    class Doubler:
        def double(self, x):
            return x * 2

    @serve.deployment
    class Ingress:
        def __init__(self, doubler):
            self.doubler = doubler

        def __call__(self, request):
            v = int(request.query_params.get("x", "0"))
            return {"doubled": self.doubler.double.remote(v).result(timeout=30)}

    serve.run(Ingress.bind(Doubler.bind()), name="compose", route_prefix="/compose")
    addr = serve.http_address()
    assert _http_get(addr + "/compose?x=21") == {"doubled": 42}
    serve.delete("compose")


def test_rolling_update_changes_version(serve_instance):
    serve.run(Echo.bind("v1:"), name="default", route_prefix="/")
    addr = serve.http_address()
    assert _http_get(addr + "/?msg=a") == {"echo": "v1:a"}
    # redeploy with new init args → new version → rolling replica swap
    serve.run(Echo.bind("v2:"), name="default", route_prefix="/")
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if _http_get(addr + "/?msg=a") == {"echo": "v2:a"}:
            break
        time.sleep(0.2)
    assert _http_get(addr + "/?msg=b") == {"echo": "v2:b"}
    # service stayed up during the roll: every request must succeed
    for _ in range(5):
        assert _http_get(addr + "/?msg=c")["echo"].endswith(":c")


def test_replica_kill_recovery(serve_instance):
    @serve.deployment(num_replicas=1)
    class Pid:
        def __call__(self, request):
            import os

            return {"pid": os.getpid()}

        def die(self):
            import os

            os._exit(1)

    handle = serve.run(Pid.bind(), name="pid", route_prefix="/pid")
    pid1 = handle.remote(serve.Request()).result(timeout=60)["pid"]
    try:
        handle.die.remote().result(timeout=10)
    except Exception:
        pass
    # controller must detect the dead replica and start a replacement
    deadline = time.monotonic() + 90
    pid2 = None
    while time.monotonic() < deadline:
        try:
            pid2 = handle.remote(serve.Request()).result(timeout=15)["pid"]
            if pid2 != pid1:
                break
        except Exception:
            time.sleep(0.5)
    assert pid2 is not None and pid2 != pid1
    serve.delete("pid")


def test_autoscaling_up(serve_instance):
    @serve.deployment(
        max_ongoing_requests=2,
        autoscaling_config={
            "min_replicas": 1,
            "max_replicas": 3,
            "target_ongoing_requests": 1.0,
            "upscale_delay_s": 0.5,
            "downscale_delay_s": 60.0,
        },
    )
    class Slow:
        def __call__(self, request):
            time.sleep(1.5)
            return {"ok": True}

    serve.run(Slow.bind(), name="auto", route_prefix="/auto")
    addr = serve.http_address()

    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            try:
                _http_get(addr + "/auto", timeout=30)
            except Exception:
                pass

    threads = [threading.Thread(target=hammer) for _ in range(6)]
    for t in threads:
        t.start()
    try:
        deadline = time.monotonic() + 60
        scaled = False
        while time.monotonic() < deadline:
            st = serve.status()["auto"]["Slow"]
            if st["running_replicas"] >= 2:
                scaled = True
                break
            time.sleep(0.5)
        assert scaled, f"never scaled above 1 replica: {serve.status()}"
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
    serve.delete("auto")


def test_autoscaling_latency_slo_up_and_down(serve_instance, capsys):
    """ISSUE 7 acceptance: `latency_slo` mode scales replicas from the
    windowed p95 of the replicas' own serve_ttft_ms histograms — up when
    the SLO is breached, back down once the quantile clears the headroom
    band — with each decision visible in the status history (`cli serve
    status`) and as a serve.autoscale span."""

    @serve.deployment(
        max_ongoing_requests=8,
        user_config={"ttft_ms": 400.0},
        autoscaling_config={
            "min_replicas": 1,
            "max_replicas": 3,
            "mode": "latency_slo",
            "target_ttft_ms": 100.0,
            "latency_window_s": 2.0,
            "slo_quantile": 0.95,
            "downscale_headroom": 0.5,
            "breach_cycles": 2,
            "upscale_delay_s": 0.5,
            "downscale_delay_s": 0.5,
        },
    )
    class FakeEngine:
        """Stands in for the LLM engine: records a configurable TTFT into
        the same serve_ttft_ms histogram the engine feeds, so the test
        drives the autoscaler's actual signal path deterministically."""

        def __init__(self):
            from ray_tpu.serve.replica import get_replica_context
            from ray_tpu.util.metrics import Histogram

            self._dep = (get_replica_context() or {}).get(
                "deployment", "FakeEngine")
            self._hist = Histogram(
                "serve_ttft_ms", "test ttft", tag_keys=("deployment",))
            self._ttft = 400.0

        def reconfigure(self, cfg):
            if cfg:
                self._ttft = float(cfg.get("ttft_ms", 400.0))

        def __call__(self, request):
            self._hist.observe(self._ttft, tags={"deployment": self._dep})
            return {"ttft": self._ttft}

    app = FakeEngine.bind()
    serve.run(app, name="slo", route_prefix="/slo")
    addr = serve.http_address()

    stop = threading.Event()

    def hammer():
        while not stop.is_set():
            try:
                _http_get(addr + "/slo", timeout=30)
            except Exception:
                pass
            time.sleep(0.2)

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    for t in threads:
        t.start()
    try:
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            st = serve.status()["slo"]["FakeEngine"]
            if st["target_replicas"] >= 2:
                break
            time.sleep(0.25)
        assert st["target_replicas"] >= 2, f"never scaled up: {st}"
        up_events = [e for e in st["autoscale_events"] if e["to"] > e["from"]]
        assert up_events and up_events[0]["trigger"].startswith(
            "serve_ttft_ms_p95"), st["autoscale_events"]
        assert up_events[0]["value"] > 100.0  # the breaching p95 itself

        # Flip the simulated engine fast (config-only change, applied via
        # in-place reconfigure) and keep the traffic flowing: the
        # windowed p95 must clear the 50 ms headroom band and walk the
        # deployment back down to min_replicas.
        serve.run(app.deployment.options(
            user_config={"ttft_ms": 5.0}).bind(), name="slo",
            route_prefix="/slo")
        deadline = time.monotonic() + 90
        while time.monotonic() < deadline:
            st = serve.status()["slo"]["FakeEngine"]
            if st["target_replicas"] == 1 and any(
                    e["to"] < e["from"] for e in st["autoscale_events"]):
                break
            time.sleep(0.25)
        down_events = [e for e in st["autoscale_events"] if e["to"] < e["from"]]
        assert st["target_replicas"] == 1 and down_events, st["autoscale_events"]
        assert down_events[-1]["trigger"].startswith("serve_ttft_ms_p95")
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)

    # The decision history is the `cli serve status` surface verbatim.
    from ray_tpu.cli import main as cli_main

    capsys.readouterr()
    assert cli_main(["serve", "status"]) == 0
    cli_out = capsys.readouterr().out
    assert "autoscaling=latency_slo" in cli_out
    assert "scale 1 -> 2" in cli_out and "scale 2 -> 1" in cli_out
    assert "serve_ttft_ms_p95" in cli_out

    # Every decision is also a span (flushed to the GCS span store).
    from ray_tpu.util.state import list_spans

    deadline = time.monotonic() + 30
    autoscale_spans = []
    while time.monotonic() < deadline:
        # High limit: the hammer phase floods the store with per-request
        # spans and the default most-recent-1000 window would cut the
        # handful of autoscale spans recorded mid-run.
        autoscale_spans = [s for s in list_spans(limit=50_000)
                           if s.get("name", "").startswith("serve.autoscale")]
        directions = {s.get("attrs", {}).get("to", 0)
                      - s.get("attrs", {}).get("from", 0)
                      for s in autoscale_spans}
        if any(d > 0 for d in directions) and any(d < 0 for d in directions):
            break
        time.sleep(1.0)
    assert any(s.get("attrs", {}).get("to", 0)
               > s.get("attrs", {}).get("from", 0) for s in autoscale_spans)
    assert any(s.get("attrs", {}).get("to", 0)
               < s.get("attrs", {}).get("from", 0) for s in autoscale_spans)
    serve.delete("slo")


def test_latency_slo_windowed_quantile_units():
    """Controller-internal SLO math, no cluster: probe histograms merge
    across replicas, the windowed quantile is a cumulative delta vs the
    snapshot preceding the window, and replica restarts (shrinking
    counts) clamp instead of going negative."""
    from ray_tpu.serve.controller import ServeController, _DeploymentState

    bounds = [10.0, 100.0, 1000.0]

    def row(buckets, count):
        return {"name": "serve_ttft_ms", "buckets": list(buckets),
                "boundaries": bounds, "count": count}

    merged = ServeController._merge_latency_rows({
        "r1": {"latency": [row([1, 2, 0, 0], 3)]},
        "r2": {"latency": [row([0, 1, 4, 0], 5)]},
        "r3": {"latency": []},
    })
    assert merged["serve_ttft_ms"][0] == [1, 3, 4, 0]
    assert merged["serve_ttft_ms"][2] == 8

    state = _DeploymentState("app", {"name": "d", "version": "v",
                                     "num_replicas": 1, "max_ongoing": 8})
    qtile = ServeController._windowed_quantile
    now = 1000.0
    # t=900: 10 slow observations; t=999: those plus 20 fast ones
    state.latency_history = [
        (900.0, {"serve_ttft_ms": ([0, 0, 10, 0], bounds, 10)}),
        (999.0, {"serve_ttft_ms": ([20, 0, 10, 0], bounds, 30)}),
    ]
    # window 30s: delta vs the t=900 snapshot = 20 fast obs -> p95 <= 10ms
    p95 = qtile(None, state, "serve_ttft_ms", 0.95, 30.0, now)
    assert p95 is not None and p95 <= 10.0
    # window covering everything: cumulative includes the slow bucket
    p95_all = qtile(None, state, "serve_ttft_ms", 0.95, 500.0, now)
    assert p95_all > 100.0
    # empty delta (no traffic since the pre-window snapshot) -> None
    full = ([20, 0, 10, 0], bounds, 30)
    state.latency_history = [(969.0, {"serve_ttft_ms": full}),
                             (999.5, {"serve_ttft_ms": full}),
                             (now, {"serve_ttft_ms": full})]
    assert qtile(None, state, "serve_ttft_ms", 0.95, 30.0, now) is None
    # replica restart: counts shrink below the base -> clamp, not negative
    state.latency_history = [
        (900.0, {"serve_ttft_ms": ([50, 0, 0, 0], bounds, 50)}),
        (now, {"serve_ttft_ms": ([5, 0, 0, 0], bounds, 5)}),
    ]
    assert qtile(None, state, "serve_ttft_ms", 0.95, 30.0, now) is None


def test_delete_application(serve_instance):
    serve.run(Echo.bind(), name="gone", route_prefix="/gone")
    addr = serve.http_address()
    assert _http_get(addr + "/gone?msg=z") == {"echo": "z"}
    serve.delete("gone")
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        result = _http_get(addr + "/gone?msg=z")
        if "error" in result:
            break
        time.sleep(0.2)
    assert "error" in _http_get(addr + "/gone?msg=z")


def test_serve_batch_decorator(serve_instance):
    """@serve.batch groups concurrent calls into one execution
    (reference batching.py:80)."""

    class Batched:
        def __init__(self):
            self.batch_sizes = []

        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.2)
        def handle(self, items):
            self.batch_sizes.append(len(items))
            return [x * 2 for x in items]

        def __call__(self, request):
            return self.handle(int(request.query_params.get("x", 0)))

        def sizes(self, request=None):
            return self.batch_sizes

    serve.run(serve.deployment(Batched, max_ongoing_requests=16).bind(),
              name="default", route_prefix="/")
    handle = serve.get_app_handle("default")

    results = {}

    def call(i):
        results[i] = handle.remote(serve.Request(query={"x": str(i)})).result(timeout=60)

    threads = [threading.Thread(target=call, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=90)
    assert results == {i: i * 2 for i in range(8)}
    sizes = handle.options(method_name="sizes").remote(None).result(timeout=60)
    # 8 calls with max_batch_size=4 must have been grouped (not 8x size-1).
    assert sum(sizes) == 8 and max(sizes) > 1, sizes


def test_serve_multiplexed_models(serve_instance):
    """@serve.multiplexed loads per-model state on demand, LRU-evicts
    beyond the cap, and routes by the request header
    (reference multiplex.py:22)."""

    class MultiModel:
        def __init__(self):
            self.loads = []

        @serve.multiplexed(max_num_models_per_replica=2)
        def get_model(self, model_id: str):
            self.loads.append(model_id)
            return {"id": model_id, "scale": len(model_id)}

        def __call__(self, request):
            model_id = serve.get_multiplexed_model_id()
            model = self.get_model(model_id)
            return {"model": model["id"], "loads": list(self.loads)}

    serve.run(serve.deployment(MultiModel, max_ongoing_requests=8).bind(),
              name="default", route_prefix="/")
    addr = serve.http_address()

    def call(model_id):
        req = urllib.request.Request(
            addr + "/", headers={"serve_multiplexed_model_id": model_id})
        return json.loads(urllib.request.urlopen(req, timeout=60).read())

    assert call("m1")["model"] == "m1"
    assert call("m1")["loads"].count("m1") == 1  # cached, not reloaded
    assert call("m2")["model"] == "m2"
    out = call("m3")  # cap 2: evicts LRU (m1)
    assert out["loads"] == ["m1", "m2", "m3"]
    out = call("m1")  # m1 was evicted: loads again
    assert out["loads"].count("m1") == 2


def test_declarative_config_deploy(serve_instance, tmp_path):
    """Apps described as data (YAML schema: import_path + args +
    per-deployment overrides) deploy without touching Python, and the
    dashboard exposes the Serve REST surface (reference serve/schema.py +
    PUT/GET /api/serve/applications)."""
    import sys
    import urllib.request as _rq

    mod_dir = tmp_path / "apps"
    mod_dir.mkdir()
    (mod_dir / "my_serve_app.py").write_text(textwrap.dedent("""
        from ray_tpu import serve

        class Echo2:
            def __init__(self, greeting="hi"):
                self.greeting = greeting

            def __call__(self, request):
                return {"msg": f"{self.greeting} {request.query_params.get('who', '')}"}

        def build(greeting="hi"):
            return serve.deployment(Echo2).bind(greeting)
    """))
    sys.path.insert(0, str(mod_dir))
    try:
        config = {
            "applications": [{
                "name": "cfg_app",
                "route_prefix": "/cfg",
                "import_path": "my_serve_app:build",
                "args": {"greeting": "hello"},
                # ship the module to replicas (reference schema runtime_env)
                "runtime_env": {"py_modules": [str(mod_dir / "my_serve_app.py")]},
                "deployments": [{"name": "Echo2", "num_replicas": 2,
                                 "max_ongoing_requests": 4}],
            }],
        }
        deployed = serve.deploy_config(config)
        assert deployed == {"cfg_app": "/cfg"}
        addr = serve.http_address()
        body = json.loads(_rq.urlopen(addr + "/cfg?who=world", timeout=60).read())
        assert body == {"msg": "hello world"}

        status = serve.serve_status()
        assert status["applications"]["cfg_app"]["status"] == "RUNNING"

        # YAML string form works too
        yaml_config = textwrap.dedent(f"""
            applications:
              - name: cfg_app2
                route_prefix: /cfg2
                import_path: my_serve_app:build
                args: {{greeting: yo}}
                runtime_env:
                  py_modules: ["{mod_dir / 'my_serve_app.py'}"]
        """)
        serve.deploy_config(yaml_config)
        body = json.loads(_rq.urlopen(addr + "/cfg2?who=x", timeout=60).read())
        assert body == {"msg": "yo x"}

        # REST surface via the dashboard
        from ray_tpu.dashboard import start_dashboard

        url = start_dashboard()
        rest = json.loads(_rq.urlopen(url + "/api/serve/applications", timeout=30).read())
        assert "cfg_app" in rest["applications"]
        req = _rq.Request(url + "/api/serve/applications/cfg_app2", method="DELETE")
        assert json.loads(_rq.urlopen(req, timeout=60).read()) == {"deleted": True}
    finally:
        sys.path.remove(str(mod_dir))


def test_grpc_proxy(serve_instance):
    """Unary gRPC calls route /<app>/<method> onto replicas through the
    shared router (reference proxy.py:534 gRPC proxy)."""
    import cloudpickle
    import grpc

    class MathService:
        def __call__(self, x):
            return x + 1

        def mul(self, a, b):
            return a * b

    serve.run(serve.deployment(MathService).bind(), name="math", route_prefix="/math")
    address = serve.start_grpc()

    channel = grpc.insecure_channel(address)
    call = channel.unary_unary("/math/__call__",
                               request_serializer=lambda b: b,
                               response_deserializer=lambda b: b)
    out = cloudpickle.loads(call(cloudpickle.dumps(((41,), {})), timeout=60))
    assert out == 42

    mul = channel.unary_unary("/math/mul",
                              request_serializer=lambda b: b,
                              response_deserializer=lambda b: b)
    assert cloudpickle.loads(mul(cloudpickle.dumps(((6, 7), {})), timeout=60)) == 42

    # unknown app -> INTERNAL error, not a hang
    bad = channel.unary_unary("/nope/__call__",
                              request_serializer=lambda b: b,
                              response_deserializer=lambda b: b)
    with pytest.raises(grpc.RpcError):
        bad(cloudpickle.dumps(((), {})), timeout=30)
    channel.close()


def test_serve_request_metrics(serve_instance):
    """Handle traffic shows up in the serve_* metrics family (reference:
    serve_num_router_requests / processing-latency metrics)."""
    app = Echo.bind()
    h = serve.run(app, name="metrics-app")
    for _ in range(3):
        assert "echo" in h.remote(serve.Request(query={"msg": "m"})).result(timeout=60)

    from ray_tpu.util.metrics import snapshot_all

    deadline = time.time() + 30
    found = {}
    while time.time() < deadline:
        found = {m["name"]: m for m in snapshot_all()
                 if m.get("tags", {}).get("deployment") == "Echo"}
        if "serve_num_requests_total" in found and "serve_request_latency_ms" in found:
            break
        time.sleep(0.2)
    assert found["serve_num_requests_total"]["value"] >= 3
    lat = found["serve_request_latency_ms"]
    assert lat["count"] >= 3 and sum(lat["buckets"]) >= 3


def test_serve_error_metrics(serve_instance):
    """Replica-side exceptions count in serve_num_errors_total."""

    @serve.deployment()
    class Boom:
        def __call__(self, request):
            raise RuntimeError("boom")

    h = serve.run(Boom.bind(), name="boom-app")
    with pytest.raises(Exception):
        h.remote(serve.Request(query={})).result(timeout=60)

    from ray_tpu.util.metrics import snapshot_all

    deadline = time.time() + 30
    while time.time() < deadline:
        errs = [m for m in snapshot_all()
                if m["name"] == "serve_num_errors_total"
                and m.get("tags", {}).get("deployment") == "Boom"]
        if errs and errs[0]["value"] >= 1:
            return
        time.sleep(0.2)
    raise AssertionError("replica error never counted in serve_num_errors_total")


def test_serve_microbench_components(serve_instance):
    """The microbenchmark suite's building blocks run against the SAME
    no-op app the module's __main__ measures (tiny sizes here)."""
    import urllib.request

    from ray_tpu.serve import microbench

    serve.run(microbench.build_noop_app(), name="default", route_prefix="/")
    handle = serve.get_app_handle("default").options(method_name="noop")
    addr = serve.http_address()
    with urllib.request.urlopen(addr + "/", timeout=60) as r:
        assert r.read() == b'"ok"'

    h = microbench.bench_handle_noop(handle, n_seq=10, n_conc=20, concurrency=4)
    assert h["p50_ms"] > 0 and h["rps"] > 0
    http = microbench.bench_http_noop(addr, n_seq=10, n_conc=20, concurrency=4)
    assert http["p50_ms"] >= h["p50_ms"] * 0.1 and http["rps"] > 0
    s = microbench.bench_streaming(addr, chunks=50, runs=2)
    assert s["chunks_per_s"] > 0 and s["first_chunk_ms"] > 0


# ---------------------------------------------------------------- local mode

def test_local_testing_mode_basic_and_composition():
    """In-process deployments without a cluster (reference
    serve/_private/local_testing_mode.py): same handler semantics as a
    real replica — composition, method routing, function deployments —
    at unit-test speed."""
    from ray_tpu import serve

    @serve.deployment
    class Doubler:
        def __call__(self, x):
            return 2 * x

        def triple(self, x):
            return 3 * x

    @serve.deployment
    class Ingress:
        def __init__(self, doubler):
            self.doubler = doubler

        def __call__(self, x):
            return self.doubler.remote(x).result() + 1

    handle = serve.run(Ingress.bind(Doubler.bind()), _local_testing_mode=True)
    assert handle.remote(10).result() == 21
    # direct method routing on a local handle
    d = serve.make_local_deployment_handle(Doubler.bind())
    assert d.remote(4).result() == 8
    assert d.triple.remote(4).result() == 12
    assert d.options(method_name="triple").remote(5).result() == 15

    @serve.deployment
    def add_one(x):
        return x + 1

    f = serve.make_local_deployment_handle(add_one.bind())
    assert f.remote(1).result() == 2


def _bare_router(replicas: dict[str, int]):
    """Router skeleton for affinity-policy unit tests: real
    assign/release/remove logic, no controller or long-poll behind it."""
    from collections import OrderedDict

    from ray_tpu.serve.router import Router

    r = Router.__new__(Router)
    r._key = "replicas::app::dep"
    r._lock = threading.Lock()
    r._cond = threading.Condition(r._lock)
    r._replicas = {rid: {"actor": f"actor-{rid}", "max_ongoing": cap}
                   for rid, cap in replicas.items()}
    r._inflight = {rid: 0 for rid in replicas}
    r._model_affinity = {}
    r._group_affinity = OrderedDict()
    r.affinity_stats = {"hits": 0, "misses": 0, "spills": 0,
                        "new_groups": 0}
    r._init_overload_state()
    return r


def test_router_affinity_sticky_under_steady_load():
    """ISSUE 10: requests carrying a prefix-group key stick to one
    replica while load is balanced; groupless requests still spread."""
    router = _bare_router({"r1": 8, "r2": 8})
    first, _ = router.assign_replica(prefix_group="sess:a")
    router.release(first)
    for _ in range(10):
        rid, _ = router.assign_replica(prefix_group="sess:a")
        assert rid == first
        router.release(rid)
    assert router.affinity_stats["hits"] == 10
    assert router.affinity_stats["new_groups"] == 1  # first-seen lookup
    assert router.affinity_stats["misses"] == 0      # no replica vanished
    assert router.affinity_stats["spills"] == 0


def test_router_affinity_spills_under_imbalance():
    """Load-aware spill: once the affine replica runs hotter than the
    coolest candidate by more than the margin, the group's request goes
    elsewhere (and the group remaps to the spill target, which now holds
    the freshest KV)."""
    from ray_tpu.core.config import get_config

    cfg = get_config()
    saved = cfg.serve_affinity_spill_margin
    cfg.serve_affinity_spill_margin = 2
    try:
        router = _bare_router({"r1": 16, "r2": 16})
        affine, _ = router.assign_replica(prefix_group="sess:s")
        other = "r2" if affine == "r1" else "r1"
        # run the affine replica hot: 3 extra in-flight vs 0 elsewhere
        with router._cond:
            router._inflight[affine] += 3
        rid, _ = router.assign_replica(prefix_group="sess:s")
        assert rid == other
        assert router.affinity_stats["spills"] == 1
        assert router._group_affinity["sess:s"] == other  # remapped
        # a saturated affine replica also spills rather than queueing
        with router._cond:
            router._inflight[other] = 16  # at its cap now
        rid2, _ = router.assign_replica(prefix_group="sess:s")
        assert rid2 == affine
        assert router.affinity_stats["spills"] == 2
    finally:
        cfg.serve_affinity_spill_margin = saved


def test_router_affinity_map_bounded_and_purged_on_death():
    """The group→replica map is bounded LRU, and a dead replica's groups
    are purged immediately (retries must cold-prefill elsewhere, never
    wait for the corpse)."""
    from ray_tpu.core.config import get_config

    cfg = get_config()
    saved = cfg.serve_affinity_map_size
    cfg.serve_affinity_map_size = 8
    try:
        router = _bare_router({"r1": 1000, "r2": 1000})
        for i in range(30):
            rid, _ = router.assign_replica(prefix_group=f"pfx:{i}")
            router.release(rid)
        assert len(router._group_affinity) <= 8
        assert "pfx:29" in router._group_affinity  # newest survive
        victim = router._group_affinity["pfx:29"]
        router.remove_replica(victim)
        assert all(rid != victim
                   for rid in router._group_affinity.values())
        # the group re-routes to a live replica and re-establishes
        rid, _ = router.assign_replica(prefix_group="pfx:29")
        assert rid != victim
        assert router._group_affinity["pfx:29"] == rid
    finally:
        cfg.serve_affinity_map_size = saved


def test_llm_serve_prefix_affinity_end_to_end(serve_instance):
    """Session-keyed HTTP requests through the real proxy land on one
    replica, hit its prefix cache on the follow-up, and the controller's
    app status reports the residency/affinity rates from the replica
    probes."""
    from ray_tpu.llm import build_llm_app

    app = build_llm_app("debug-128", num_replicas=2, max_slots=4,
                        max_len=128, page_size=16)
    serve.run(app, name="llm-affinity", route_prefix="/llm-aff")
    addr = serve.http_address()
    body = {"prompt": "You are a helpful assistant. Answer: hi",
            "max_tokens": 4, "session_id": "sess-42"}
    req = urllib.request.Request(
        f"{addr}/llm-aff/v1/completions",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=120) as r:
            first = json.loads(r.read())
        with urllib.request.urlopen(req, timeout=120) as r:
            second = json.loads(r.read())
        # greedy byte-parity across the cached re-send
        assert first["choices"][0]["text"] == second["choices"][0]["text"]
        # the controller folds the replicas' residency probes into status
        def affinity_status():
            st = serve.status().get("llm-affinity", {})
            dep = next(iter(st.values()), {})
            pa = dep.get("prefix_affinity") or {}
            return pa if pa.get("requests", 0) >= 2 else None

        deadline = time.monotonic() + 30
        pa = None
        while time.monotonic() < deadline and pa is None:
            pa = affinity_status()
            time.sleep(0.5)
        assert pa, "prefix_affinity never reached app status"
        # both session requests counted; the re-send hit the cache on
        # the SAME replica (affinity), so at least one cache hit
        assert pa["requests"] >= 2
        assert pa["cache_hits"] >= 1
        assert pa["groups"] >= 1
    finally:
        serve.delete("llm-affinity")


def test_local_testing_mode_streaming_multiplex_reconfigure():
    from ray_tpu import serve

    @serve.deployment(user_config={"k": 3})
    class Gen:
        def __init__(self):
            self.k = 1

        def reconfigure(self, cfg):
            self.k = cfg["k"]

        def stream(self, n):
            for i in range(n):
                yield i * self.k

        def which_model(self):
            return serve.get_multiplexed_model_id()

    h = serve.make_local_deployment_handle(Gen.bind())
    # The streaming path speaks the same wire messages as a real replica
    # (start head + chunks); user_config (k=3) applied through the real
    # ReplicaActor reconfigure path.
    msgs = list(h.options(method_name="stream").remote_streaming(3))
    assert msgs[0]["kind"] == "start"
    chunks = [int(m["data"]) for m in msgs[1:] if m["kind"] == "chunk"]
    assert chunks == [0, 3, 6]
    got = h.options(multiplexed_model_id="m7").which_model.remote().result()
    assert got == "m7"
