"""Always-warm serving fleet (round 19): standby demote/promote round
trips, the chunked weight-broadcast wire, the fleet policy pure
functions, and the serve-level scale-to-zero → first-request wake loop.

The regime under test: replica capacity as a WARM resource. A standby
replica keeps its weights in host RAM with the compile cache warm, so
promotion is one host→device transfer instead of minutes of init; N
cold replicas stream weights from one donor's broadcast instead of N
independent loads; an idle deployment parks at zero running replicas
and the first request promotes a standby back.
"""

import dataclasses
import json
import threading
import time
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.llm.engine import InferenceEngine, Request
from ray_tpu.llm.weights import (WeightBroadcastSource, host_to_device,
                                 params_fingerprint, receive_weight_stream,
                                 tree_bytes, tree_to_host)
from ray_tpu.models.llama import PRESETS, init_params
from ray_tpu.serve import fleet


@pytest.fixture(scope="module")
def small_model():
    cfg = dataclasses.replace(PRESETS["debug"], dtype=jnp.float32,
                              attn_impl="reference")
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _make_engine(small_model, **kw):
    cfg, params = small_model
    return InferenceEngine(cfg, params, max_slots=2, max_len=64,
                           enable_prefix_cache=False, **kw)


def _generate(eng, prompt, n=6):
    r = Request(f"r{time.time_ns()}", list(prompt), max_new_tokens=n)
    eng.add_request(r)
    while not r.done:
        eng.step()
    return list(r.generated)


# ------------------------------------------------------------ fleet policy
def test_scheduled_floor_picks_covering_window_max():
    now = 1000.0
    entries = [
        {"start": 900, "end": 1100, "min_replicas": 2},
        {"start": 990, "end": 1010, "min_replicas": 5},
        {"start": 1100, "end": 1200, "min_replicas": 9},  # not yet
        {"start": 800, "end": 1000, "min_replicas": 7},   # end-exclusive
    ]
    assert fleet.scheduled_floor(entries, now) == 5
    assert fleet.scheduled_floor(entries, 1150.0) == 9
    assert fleet.scheduled_floor(entries, 1500.0) == 0
    assert fleet.scheduled_floor(None, now) == 0


def test_scheduled_floor_skips_malformed_entries():
    entries = [{"start": "bad"}, None and {}, {"min_replicas": 3},
               {"start": 0, "end": 2e9, "min_replicas": "4"}]
    assert fleet.scheduled_floor(entries, 1000.0) == 4


def test_slope_projection_extrapolates_trend():
    # TTFT rising 10 ms/s: projecting 5 s ahead from the last sample.
    samples = [(t, 100.0 + 10.0 * t) for t in range(6)]
    proj = fleet.slope_projection(samples, 5.0)
    assert proj == pytest.approx(150.0 + 50.0, abs=1e-6)
    # Too few points / degenerate spread → no prediction.
    assert fleet.slope_projection(samples[:2], 5.0) is None
    assert fleet.slope_projection([(1.0, 5.0)] * 4, 5.0) is None
    # None values (no-traffic windows) are filtered, not crashed on.
    assert fleet.slope_projection([(0, None), (1, None)], 5.0) is None


def test_desired_standby_scale_to_zero_implies_one():
    assert fleet.desired_standby(None) == 0
    assert fleet.desired_standby({"standby_replicas": 3}) == 3
    # scale-to-zero without a standby would make the first request pay a
    # full cold start — the policy floors the pool at 1.
    assert fleet.desired_standby({"scale_to_zero_idle_s": 5.0}) == 1
    assert fleet.desired_standby(
        {"standby_replicas": 2, "scale_to_zero_idle_s": 5.0}) == 2

    class Obj:
        standby_replicas = 2
        scale_to_zero_idle_s = None

    assert fleet.desired_standby(Obj()) == 2


def test_should_scale_to_zero_threshold_and_unknowns():
    auto = {"scale_to_zero_idle_s": 10.0}
    assert fleet.should_scale_to_zero(11.0, auto)
    assert not fleet.should_scale_to_zero(9.0, auto)
    assert not fleet.should_scale_to_zero(None, auto)  # unknown idleness
    assert not fleet.should_scale_to_zero(11.0, {})    # feature off
    assert not fleet.should_scale_to_zero(11.0, None)


def test_fold_fleet_rows_min_idle_and_unknown_poisons():
    rows = [
        {"idle_s": 30.0, "residency_capable": True, "weights_on_host": False},
        {"idle_s": 5.0, "residency_capable": True, "weights_on_host": True},
    ]
    folded = fleet.fold_fleet_rows(rows)
    # The fleet is only as idle as its busiest replica.
    assert folded == {"idle_s": 5.0, "replicas": 2, "residency_capable": 2,
                      "host_resident": 1}
    # One replica with unknown idle age must block scale-to-zero.
    rows.append({"idle_s": None})
    assert fleet.fold_fleet_rows(rows)["idle_s"] is None
    assert fleet.fold_fleet_rows([]) is None


# -------------------------------------------------------- weight broadcast
def test_host_round_trip_preserves_bytes(small_model):
    _, params = small_model
    host = tree_to_host(params)
    back = host_to_device(host)
    want = params_fingerprint(params)
    assert params_fingerprint(host) == want
    assert params_fingerprint(back) == want
    assert tree_bytes(host) == tree_bytes(params)


def test_broadcast_parity_two_concurrent_readers(small_model):
    """The fan-out delivery path: TWO readers of one source both get a
    byte-identical copy of the donor's pytree."""
    _, params = small_model
    want = params_fingerprint(params)
    src = WeightBroadcastSource(params, model="m", n_readers=2)
    got: list = [None, None]

    def read(i):
        got[i] = receive_weight_stream(src.address, timeout_s=60.0)

    ts = [threading.Thread(target=read, args=(i,)) for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=90)
    src.join(timeout=10)
    for res in got:
        assert res is not None and res["complete"], res and res["status"]
        assert res["fingerprint"] == want
        assert params_fingerprint(res["params"]) == want
        # Leaf-level byte parity, not just the digest.
        want_leaves = jax.tree_util.tree_leaves(params)
        got_leaves = jax.tree_util.tree_leaves(res["params"])
        assert len(want_leaves) == len(got_leaves)
        for a, b in zip(want_leaves, got_leaves):
            assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


def test_broadcast_source_death_mid_stream_reports_incomplete(small_model):
    """Chaos: the donor dies after 2 chunks — the reader must come back
    with params=None and an honest status, never a half-built pytree."""
    _, params = small_model
    src = WeightBroadcastSource(params, model="m", n_readers=1,
                                chunk_bytes=64 << 10, _die_after_chunks=2)
    res = receive_weight_stream(src.address, timeout_s=30.0)
    src.join(timeout=10)
    assert res["params"] is None
    assert not res["complete"]
    assert res["status"] != "ok"


# --------------------------------------------------------- engine residency
def test_engine_demote_promote_round_trip(small_model):
    eng = _make_engine(small_model)
    prompt = [3, 1, 4, 1, 5, 9]
    before = _generate(eng, prompt)
    res = eng.demote_weights_to_host()
    assert res["ok"] and res["bytes"] > 0
    assert not eng.weights_resident()
    assert eng.executor.params is None
    assert eng.metrics["weights_demoted"] == 1
    out = eng.promote_weights_from_host()
    assert out["ok"] and not out.get("already")
    assert eng.weights_resident()
    assert eng.metrics["weights_promoted"] == 1
    assert eng.metrics["weight_promote_ms"] > 0
    # Promotion restored the exact weights: greedy decode is bit-stable.
    assert _generate(eng, prompt) == before


def test_engine_demote_refused_while_busy(small_model):
    eng = _make_engine(small_model)
    r = Request("busy", [1, 2, 3], max_new_tokens=4)
    eng.add_request(r)
    res = eng.demote_weights_to_host()
    assert not res["ok"] and res["reason"] == "busy"
    while not r.done:
        eng.step()
    assert eng.demote_weights_to_host()["ok"]
    eng.promote_weights_from_host()


def test_first_request_auto_promotes(small_model):
    """Scale-to-zero's wake at the engine layer: a request arriving at a
    demoted engine promotes the weights transparently."""
    eng = _make_engine(small_model)
    prompt = [2, 7, 1, 8]
    before = _generate(eng, prompt)
    assert eng.demote_weights_to_host()["ok"]
    assert not eng.weights_resident()
    assert _generate(eng, prompt) == before
    assert eng.weights_resident()
    assert eng.metrics["weights_promoted"] == 1


def test_install_weights_streams_into_demoted_engine(small_model):
    cfg, params = small_model
    eng = _make_engine(small_model)
    assert eng.demote_weights_to_host()["ok"]
    host = tree_to_host(params)
    out = eng.install_weights(host)
    assert out["ok"]
    assert eng.weights_resident()
    assert params_fingerprint(eng.executor.params) == \
        params_fingerprint(params)


# ------------------------------------------------------- promotion ladder
@pytest.fixture(scope="module")
def llm_replica():
    from ray_tpu.llm.serving import LLMDeployment

    dep = LLMDeployment("debug-128", max_slots=2, max_len=64, page_size=8,
                        prefill_chunk_size=32, attention_impl="dense",
                        use_compiled_loop=False)
    yield dep


def test_fleet_stats_idle_clock_and_residency(llm_replica):
    dep = llm_replica
    assert dep.generate("hi", max_new_tokens=4)
    row = dep.fleet_stats()
    assert row["residency_capable"]
    assert not row["weights_on_host"]
    assert row["idle_s"] >= 0.0
    assert dep.fleet_demote()["ok"]
    assert dep.fleet_stats()["weights_on_host"]
    out = dep.fleet_promote()
    assert out["ok"] and out["path"] == "host"
    assert dep.fleet_promote()["path"] == "resident"  # idempotent


def test_promote_via_broadcast_stream(llm_replica):
    """The controller's fan-out path: a donor stream feeds a demoted
    replica; the streamed install must reproduce the donor's bytes."""
    dep = llm_replica
    donor = dep.open_weight_stream(n_readers=1)
    assert donor and donor["weight_address"]
    assert dep.fleet_demote()["ok"]
    out = dep.fleet_promote(donor["weight_address"])
    assert out["ok"] and out["path"] == "stream"
    assert params_fingerprint(dep.engine.executor.params) == \
        donor["fingerprint"]


@pytest.mark.chaos
def test_promotion_survives_donor_death_via_host_fallback(llm_replica):
    """Chaos: the donor's broadcast dies after 1 chunk mid-promotion.
    The ladder degrades to the host-RAM copy — promotion still lands."""
    dep = llm_replica
    donor = dep.open_weight_stream(n_readers=1, _die_after_chunks=1)
    assert dep.fleet_demote()["ok"]
    out = dep.fleet_promote(donor["weight_address"])
    assert out["ok"] and out["path"] == "host"
    assert out["ladder"] and out["ladder"][0].startswith("stream:")
    assert dep.generate("ok", max_new_tokens=4)


@pytest.mark.chaos
def test_promotion_survives_dead_address_and_lost_host_copy(llm_replica):
    """Worst case: the donor address is unreachable AND the host copy is
    gone — the last rung re-inits from the deployment seed and still
    serves (weights are seed-derived in this repo, so the re-init is
    bit-exact)."""
    dep = llm_replica
    want = params_fingerprint(dep.engine.executor.params)
    assert dep.fleet_demote()["ok"]
    dep.engine._host_params = None  # simulate host-tier loss
    out = dep.fleet_promote("127.0.0.1:1")
    assert out["ok"] and out["path"] == "cold_init"
    assert params_fingerprint(dep.engine.executor.params) == want


# ----------------------------------------------------------- serve e2e
def _get(addr, path, timeout=90.0):
    try:
        with urllib.request.urlopen(addr + path, timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()
    except Exception as e:
        return type(e).__name__, b""


def _dep_status(app="fleet"):
    return next(iter((serve.status().get(app) or {}).values()), None) or {}


def _wait_for(pred, timeout=120.0, period=0.25):
    deadline = time.time() + timeout
    while time.time() < deadline:
        st = _dep_status()
        if pred(st):
            return st
        time.sleep(period)
    return None


def test_scale_to_zero_and_first_request_wake_e2e(ray_cluster):
    """THE acceptance loop: deploy → serve → idle past the threshold →
    the deployment parks (0 running, warm standbys, still 'healthy') →
    the next request wakes it via the router poke → standby promotion
    (host path, no cold start) serves the request."""
    from ray_tpu.llm import build_llm_app

    serve.run(
        build_llm_app(
            "debug-128", max_slots=2, max_len=64, page_size=8,
            prefill_chunk_size=32, num_replicas=1, max_ongoing_requests=2,
            attention_impl="dense", use_compiled_loop=False,
            autoscaling_config={"min_replicas": 1, "max_replicas": 2,
                                "scale_to_zero_idle_s": 2.0}),
        name="fleet", route_prefix="/fleet", timeout_s=360.0)
    addr = serve.http_address()
    try:
        status, body = _get(addr, "/fleet?prompt=hi&max_new_tokens=4")
        assert status == 200, (status, body[:200])

        # Park: idle crosses the threshold → 0 running, ≥1 warm standby,
        # and the deployment still reports healthy.
        st = _wait_for(lambda s: s.get("scaled_to_zero")
                       and s.get("running_replicas") == 0
                       and s.get("standby_replicas", 0) >= 1
                       and s.get("fleet", {}).get("host_resident", 0) >= 1,
                       timeout=150.0)
        assert st is not None, _dep_status()
        assert st["healthy"]

        # Wake: the request lands on an empty table, the router pokes
        # the controller, a standby promotes, and the request completes.
        status, body = _get(addr, "/fleet?prompt=again&max_new_tokens=4")
        assert status == 200, (status, body[:200])
        st = _wait_for(lambda s: not s.get("scaled_to_zero")
                       and s.get("running_replicas", 0) >= 1)
        assert st is not None, _dep_status()
        promote = st.get("last_promote") or {}
        # Promotion came from the warm pool, not a cold start.
        assert promote.get("path") in ("host", "stream", "resident"), st
        triggers = [e["trigger"] for e in st.get("autoscale_events", [])]
        assert "scale_to_zero" in triggers and "wake" in triggers
    finally:
        serve.shutdown()


def test_standby_pool_demotes_excess_e2e(ray_cluster):
    """standby_replicas keeps a warm pool behind the active set: the
    controller starts one extra replica and demotes it to STANDBY
    instead of leaving it routable."""
    from ray_tpu.llm import build_llm_app

    serve.run(
        build_llm_app(
            "debug-128", max_slots=2, max_len=64, page_size=8,
            prefill_chunk_size=32, num_replicas=1, max_ongoing_requests=2,
            attention_impl="dense", use_compiled_loop=False,
            autoscaling_config={"min_replicas": 1, "max_replicas": 2,
                                "standby_replicas": 1}),
        name="fleet", route_prefix="/fleet", timeout_s=360.0)
    addr = serve.http_address()
    try:
        status, body = _get(addr, "/fleet?prompt=hi&max_new_tokens=4")
        assert status == 200, (status, body[:200])
        # Wait for the SETTLED pool shape (one running, one warm standby
        # whose host-RAM residency shows in the fold) — point-in-time
        # snapshots mid-reconcile can catch the pool half-built.
        st = _wait_for(lambda s: s.get("standby_replicas", 0) >= 1
                       and s.get("running_replicas", 0) >= 1
                       and (s.get("fleet") or {}).get("host_resident", 0) >= 1,
                       timeout=150.0)
        assert st is not None, _dep_status()
        # Traffic still lands on the running replica only.
        status, _ = _get(addr, "/fleet?prompt=more&max_new_tokens=4")
        assert status == 200
    finally:
        serve.shutdown()


def test_util_state_serve_fleet_surface(ray_cluster):
    """util.state.serve_fleet(): the fleet view reaches the GCS-state
    surface (and degrades to {} with no Serve instance)."""
    from ray_tpu.llm import build_llm_app
    from ray_tpu.util import state as util_state

    serve.run(
        build_llm_app(
            "debug-128", max_slots=2, max_len=64, page_size=8,
            prefill_chunk_size=32, num_replicas=1, max_ongoing_requests=2,
            attention_impl="dense", use_compiled_loop=False),
        name="fleet", route_prefix="/fleet", timeout_s=360.0)
    try:
        view = util_state.serve_fleet()
        row = next((v for k, v in view.items() if k.startswith("fleet#")),
                   None)
        assert row is not None, view
        assert row["running"] >= 1 and row["standby"] == 0
        assert row["scaled_to_zero"] is False
    finally:
        serve.shutdown()
    assert util_state.serve_fleet() == {}
