"""Bench-regression guard (``python -m ray_tpu.bench_check``)."""

import json

from ray_tpu import bench_check


def test_direction_inference():
    assert bench_check._direction("serve_p50_ttft_ms") == "down"
    assert bench_check._direction("framework_overhead_pct") == "down"
    assert bench_check._direction("peak_hbm_used_bytes") == "down"
    assert bench_check._direction("flash_fwdbwd_tflops_s4096") == "up"
    assert bench_check._direction("raw_tokens_per_sec") == "up"
    # throughput rates trump the "_s" lower-better suffix
    assert bench_check._direction("core_tasks_per_s") == "up"
    assert bench_check._direction("core_actor_calls_per_s") == "up"
    assert bench_check._direction("core_obj_roundtrip_per_s") == "up"
    assert bench_check._direction("serve_tokens_per_sec") == "up"
    # lease-stage latencies stay lower-better
    assert bench_check._direction("core_lease_submit_to_lease_p50_ms") == "down"


def test_core_metrics_guarded():
    """ISSUE 6 satellite: a >10% core-metric drop or a silently-vanished
    core metric fails the bench; config echoes (_cfg) are never tracked."""
    old = {"core_tasks_per_s": 3439.4, "core_actor_calls_per_s": 1973.8,
           "core_obj_roundtrip_per_s": 27682.9, "core_tasks_cfg": 20000}
    # a 20% tasks drop regresses; cfg echo resized without complaint
    new = {"core_tasks_per_s": 2751.5, "core_actor_calls_per_s": 1990.0,
           "core_obj_roundtrip_per_s": 27000.0, "core_tasks_cfg": 50000}
    result = bench_check.compare(old, new)
    assert {r["metric"] for r in result["regressions"]} == {"core_tasks_per_s"}
    assert not result["missing"]
    # a vanished core metric is flagged even when the others improved
    new2 = {"core_tasks_per_s": 5000.0, "core_actor_calls_per_s": 2500.0}
    result2 = bench_check.compare(old, new2)
    assert {r["metric"] for r in result2["missing"]} == {
        "core_obj_roundtrip_per_s"}
    # an INCREASE in a rate is an improvement, never a regression
    assert {r["metric"] for r in result2["improvements"]} == {
        "core_tasks_per_s", "core_actor_calls_per_s"}


def test_compare_flags_drops_and_missing():
    old = {"flash_fwdbwd_tflops_s4096": 26.16, "serve_p50_ttft_ms": 272.1,
           "value": 11363.9, "serve_preset": "llama3-1b", "n": 4}
    new = {"flash_fwdbwd_tflops_s4096": 22.99, "value": 11349.5,
           "serve_error": "TimeoutError: not healthy", "n": 5}
    result = bench_check.compare(old, new)
    regressed = {r["metric"] for r in result["regressions"]}
    assert regressed == {"flash_fwdbwd_tflops_s4096"}   # -12.1% > 10%
    missing = {r["metric"] for r in result["missing"]}
    assert missing == {"serve_p50_ttft_ms"}             # silently vanished
    ok = {r["metric"] for r in result["ok"]}
    assert ok == {"value"}                               # -0.1% is fine
    # non-numeric / bookkeeping fields never tracked
    assert not any("preset" in r["metric"] for rows in result.values()
                   for r in rows)


def test_lower_better_regresses_up():
    old = {"serve_p50_ttft_ms": 272.1}
    new = {"serve_p50_ttft_ms": 320.0}
    result = bench_check.compare(old, new)
    assert [r["metric"] for r in result["regressions"]] == ["serve_p50_ttft_ms"]
    # and an improvement in latency is an improvement
    result = bench_check.compare(old, {"serve_p50_ttft_ms": 200.0})
    assert [r["metric"] for r in result["improvements"]] == ["serve_p50_ttft_ms"]


def test_cli_exit_codes_and_wrapper_format(tmp_path):
    """Accepts both bare metrics and the driver's BENCH_rNN wrapper;
    exit 1 on regression, 0 when clean."""
    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    old.write_text(json.dumps(
        {"n": 4, "cmd": "python bench.py", "rc": 0,
         "parsed": {"flash_fwdbwd_tflops_s4096": 26.16}}))
    new.write_text(json.dumps({"flash_fwdbwd_tflops_s4096": 22.99}))
    assert bench_check.main([str(old), str(new)]) == 1
    # within a generous threshold the same pair passes
    assert bench_check.main([str(old), str(new), "--threshold", "0.2"]) == 0
    new.write_text(json.dumps({"flash_fwdbwd_tflops_s4096": 26.5}))
    assert bench_check.main([str(old), str(new)]) == 0
    assert bench_check.main([str(old)]) == 2  # usage error


def test_latest_bench_json(tmp_path):
    assert bench_check.latest_bench_json(str(tmp_path)) is None
    (tmp_path / "BENCH_r04.json").write_text("{}")
    (tmp_path / "BENCH_r05.json").write_text("{}")
    latest = bench_check.latest_bench_json(str(tmp_path))
    assert latest is not None and latest.endswith("BENCH_r05.json")
