"""Bench-regression guard (``python -m ray_tpu.bench_check``)."""

import json

from ray_tpu import bench_check


def test_direction_inference():
    assert bench_check._direction("serve_p50_ttft_ms") == "down"
    assert bench_check._direction("framework_overhead_pct") == "down"
    assert bench_check._direction("peak_hbm_used_bytes") == "down"
    assert bench_check._direction("flash_fwdbwd_tflops_s4096") == "up"
    assert bench_check._direction("raw_tokens_per_sec") == "up"
    # throughput rates trump the "_s" lower-better suffix
    assert bench_check._direction("core_tasks_per_s") == "up"
    assert bench_check._direction("core_actor_calls_per_s") == "up"
    assert bench_check._direction("core_obj_roundtrip_per_s") == "up"
    assert bench_check._direction("serve_tokens_per_sec") == "up"
    # lease-stage latencies stay lower-better
    assert bench_check._direction("core_lease_submit_to_lease_p50_ms") == "down"
    # round-8 dag metrics: dispatch overheads (µs) are lower-better,
    # decode/tick rates higher-better
    assert bench_check._direction("dag_tick_dispatch_overhead_us") == "down"
    assert bench_check._direction(
        "dag_tick_dispatch_overhead_dynamic_us") == "down"
    assert bench_check._direction("dag_loop_ticks_per_s") == "up"
    assert bench_check._direction("pp_decode_tok_s_dynamic") == "up"
    assert bench_check._direction("pp_decode_tok_s_compiled") == "up"


def test_dag_metrics_skip_markers():
    """pp decode cells may be intentionally skipped on hosts that can't
    run the pp shard_map — the markers route the absence to the
    non-failing skipped bucket, exactly like serve matrix cells."""
    old = {"pp_decode_tok_s_dynamic": 100.0, "pp_decode_tok_s_compiled": 120.0,
           "dag_tick_dispatch_overhead_us": 900.0}
    new = {"dag_tick_dispatch_overhead_us": 850.0,
           "pp_decode_tok_s_dynamic_skipped": True,
           "pp_decode_tok_s_compiled_skipped": True}
    result = bench_check.compare(old, new)
    assert not result["missing"]
    assert {r["metric"] for r in result["skipped"]} == {
        "pp_decode_tok_s_dynamic", "pp_decode_tok_s_compiled"}


def test_core_metrics_guarded():
    """ISSUE 6 satellite: a >10% core-metric drop or a silently-vanished
    core metric fails the bench; config echoes (_cfg) are never tracked."""
    old = {"core_tasks_per_s": 3439.4, "core_actor_calls_per_s": 1973.8,
           "core_obj_roundtrip_per_s": 27682.9, "core_tasks_cfg": 20000}
    # a 20% tasks drop regresses; cfg echo resized without complaint
    new = {"core_tasks_per_s": 2751.5, "core_actor_calls_per_s": 1990.0,
           "core_obj_roundtrip_per_s": 27000.0, "core_tasks_cfg": 50000}
    result = bench_check.compare(old, new)
    assert {r["metric"] for r in result["regressions"]} == {"core_tasks_per_s"}
    assert not result["missing"]
    # a vanished core metric is flagged even when the others improved
    new2 = {"core_tasks_per_s": 5000.0, "core_actor_calls_per_s": 2500.0}
    result2 = bench_check.compare(old, new2)
    assert {r["metric"] for r in result2["missing"]} == {
        "core_obj_roundtrip_per_s"}
    # an INCREASE in a rate is an improvement, never a regression
    assert {r["metric"] for r in result2["improvements"]} == {
        "core_tasks_per_s", "core_actor_calls_per_s"}


def test_core_scale_metric_directions():
    """ISSUE 14 recurring audit: the new creation/scale rates must never
    fall into the lower-better `_s` suffix (they end in `_per_s`), the
    pooled-spawn fraction is a pointwise higher-better rate, and the
    harness-size echoes (`_cfg`) are never tracked."""
    assert bench_check._direction("core_actor_creations_per_s") == "up"
    assert bench_check._direction("core_scale_tasks_per_s") == "up"
    assert bench_check._direction("core_scale_actor_creations_per_s") == "up"
    assert bench_check._direction("core_scale_pooled_spawn_frac") == "up"
    # spawn latencies stay lower-better
    assert bench_check._direction("core_lease_worker_spawn_p50_ms") == "down"
    for echo in ("core_scale_raylets_cfg", "core_scale_tasks_cfg",
                 "core_scale_actors_cfg", "core_zygote_pool_cfg",
                 "core_scale_pool_cfg", "core_scale_chaos_storm_cfg"):
        assert not bench_check._tracked(echo, 8)
    # ... and a real drop in the new rates is flagged as a regression
    old = {"core_actor_creations_per_s": 80.0, "core_scale_tasks_per_s": 2000.0}
    new = {"core_actor_creations_per_s": 40.0, "core_scale_tasks_per_s": 2100.0}
    result = bench_check.compare(old, new)
    assert {r["metric"] for r in result["regressions"]} == {
        "core_actor_creations_per_s"}


def test_core_scale_skip_marker():
    """`core_scale_skipped: true` (the 1-core-sandbox escape hatch)
    routes every absent core_scale_* cell to the non-failing skipped
    bucket instead of `missing`."""
    old = {"core_scale_tasks_per_s": 2372.8,
           "core_scale_actor_creations_per_s": 22.8,
           "core_scale_pooled_spawn_frac": 1.0,
           "core_tasks_per_s": 2000.0}
    new = {"core_scale_skipped": True, "core_tasks_per_s": 2100.0}
    result = bench_check.compare(old, new)
    assert not result["missing"]
    assert {r["metric"] for r in result["skipped"]} == {
        "core_scale_tasks_per_s", "core_scale_actor_creations_per_s",
        "core_scale_pooled_spawn_frac"}


def test_compare_flags_drops_and_missing():
    old = {"flash_fwdbwd_tflops_s4096": 26.16, "serve_p50_ttft_ms": 272.1,
           "value": 11363.9, "serve_preset": "llama3-1b", "n": 4}
    new = {"flash_fwdbwd_tflops_s4096": 22.99, "value": 11349.5,
           "serve_error": "TimeoutError: not healthy", "n": 5}
    result = bench_check.compare(old, new)
    regressed = {r["metric"] for r in result["regressions"]}
    assert regressed == {"flash_fwdbwd_tflops_s4096"}   # -12.1% > 10%
    missing = {r["metric"] for r in result["missing"]}
    assert missing == {"serve_p50_ttft_ms"}             # silently vanished
    ok = {r["metric"] for r in result["ok"]}
    assert ok == {"value"}                               # -0.1% is fine
    # non-numeric / bookkeeping fields never tracked
    assert not any("preset" in r["metric"] for rows in result.values()
                   for r in rows)


def test_matrix_metrics_directions():
    """ISSUE 7 satellite: every serve-matrix cell metric compares
    lower-better — `*_ttft_ms` and the new `*_itl_ms` inter-token
    latency both regress UP."""
    for cell in ("c8_short", "c8_2k", "c32_short", "c32_2k"):
        assert bench_check._direction(f"serve_{cell}_p50_ttft_ms") == "down"
        assert bench_check._direction(f"serve_{cell}_p95_ttft_ms") == "down"
        assert bench_check._direction(f"serve_{cell}_p95_itl_ms") == "down"
    old = {"serve_c32_2k_p95_itl_ms": 120.0, "serve_c32_2k_p95_ttft_ms": 800.0}
    worse = {"serve_c32_2k_p95_itl_ms": 200.0, "serve_c32_2k_p95_ttft_ms": 1200.0}
    result = bench_check.compare(old, worse)
    assert {r["metric"] for r in result["regressions"]} == set(old)
    better = {"serve_c32_2k_p95_itl_ms": 60.0, "serve_c32_2k_p95_ttft_ms": 500.0}
    result = bench_check.compare(old, better)
    assert {r["metric"] for r in result["improvements"]} == set(old)


def test_skipped_matrix_cells_not_missing(tmp_path):
    """A matrix cell the new run INTENTIONALLY skipped (its
    `serve_<cell>_skipped` marker is recorded) must not be flagged as a
    silently-vanished metric; an uncovered absence still is."""
    old = {"serve_c8_short_p50_ttft_ms": 150.0,
           "serve_c8_short_p95_itl_ms": 90.0,
           "serve_c32_2k_p95_ttft_ms": 900.0,
           "serve_p50_ttft_ms": 250.0}
    new = {"serve_c8_short_skipped": True,
           "serve_c32_2k_p95_ttft_ms": 850.0,
           "serve_p50_ttft_ms": 240.0}
    result = bench_check.compare(old, new)
    assert {r["metric"] for r in result["skipped"]} == {
        "serve_c8_short_p50_ttft_ms", "serve_c8_short_p95_itl_ms"}
    assert not result["missing"] and not result["regressions"]
    # a false marker covers nothing
    new_false = dict(new, serve_c8_short_skipped=False)
    result = bench_check.compare(old, new_false)
    assert {r["metric"] for r in result["missing"]} == {
        "serve_c8_short_p50_ttft_ms", "serve_c8_short_p95_itl_ms"}
    # and an absence without a marker still fails the CLI
    import json

    o, n = tmp_path / "o.json", tmp_path / "n.json"
    o.write_text(json.dumps(old))
    n.write_text(json.dumps(new))
    assert bench_check.main([str(o), str(n)]) == 0   # skipped: clean exit
    n.write_text(json.dumps({k: v for k, v in new.items()
                             if not k.endswith("_skipped")}))
    assert bench_check.main([str(o), str(n)]) == 1   # vanished: fails


def test_recovery_metrics_directions():
    """ISSUE 9 satellite: recovery SLOs are lower-better — seconds via
    the `_s` suffix, checkpoint lag via the new `_lag_steps` suffix, and
    failed-request counts via the `failed` substring."""
    assert bench_check._direction("recovery_train_resume_s") == "down"
    assert bench_check._direction("recovery_serve_reroute_s") == "down"
    assert bench_check._direction("recovery_ckpt_lag_steps") == "down"
    assert bench_check._direction("recovery_serve_failed_requests") == "down"
    old = {"recovery_train_resume_s": 2.0, "recovery_ckpt_lag_steps": 1.0}
    worse = {"recovery_train_resume_s": 4.0, "recovery_ckpt_lag_steps": 3.0}
    result = bench_check.compare(old, worse)
    assert {r["metric"] for r in result["regressions"]} == set(old)
    better = {"recovery_train_resume_s": 1.0, "recovery_ckpt_lag_steps": 0.0}
    result = bench_check.compare(old, better)
    # lag going to 0 is fine (0-new never regresses a lower-better)
    assert not result["regressions"]


def test_recovery_skip_markers_honored():
    """A recovery scenario that cannot run records `<metric>_skipped`
    markers — routed to the non-failing skipped bucket, exactly like the
    serve matrix cells; an uncovered absence still fails."""
    old = {"recovery_train_resume_s": 2.0, "recovery_serve_reroute_s": 0.8,
           "recovery_ckpt_lag_steps": 1.0}
    new = {"recovery_serve_reroute_s": 0.7,
           "recovery_train_resume_s_skipped": True,
           "recovery_ckpt_lag_steps_skipped": True}
    result = bench_check.compare(old, new)
    assert not result["missing"] and not result["regressions"]
    assert {r["metric"] for r in result["skipped"]} == {
        "recovery_train_resume_s", "recovery_ckpt_lag_steps"}
    # marker gone -> the absence is a failure again
    bare = {"recovery_serve_reroute_s": 0.7}
    result = bench_check.compare(old, bare)
    assert {r["metric"] for r in result["missing"]} == {
        "recovery_train_resume_s", "recovery_ckpt_lag_steps"}


def test_prefix_hit_rate_direction():
    # higher-better: more prompt pages served from the prefix cache
    assert bench_check._direction("serve_prefix_cache_hit_rate") == "up"
    assert bench_check._direction("serve_prefix_affinity_hit_rate") == "up"
    assert bench_check._direction("serve_prefill_suffix_frac") == "up"


def test_hit_rate_and_frac_compare_in_points():
    """ISSUE 10 satellite: 0-1 rate metrics (_hit_rate/_frac) compare
    higher-better in POINTS — small absolute moves on a tiny base are
    noise, big point drops fail, and a 0 -> positive move improves
    (the relative path would have skipped ov == 0 entirely)."""
    old = {"serve_prefix_cache_hit_rate": 0.02,
           "serve_prefix_affinity_hit_rate": 0.90}
    # 0.02 -> 0.01 is a -50% relative move but only -1 point: OK
    result = bench_check.compare(
        old, {"serve_prefix_cache_hit_rate": 0.01,
              "serve_prefix_affinity_hit_rate": 0.89})
    assert not result["regressions"] and not result["missing"]
    # a real point collapse regresses
    result = bench_check.compare(
        old, {"serve_prefix_cache_hit_rate": 0.02,
              "serve_prefix_affinity_hit_rate": 0.45})
    assert [r["metric"] for r in result["regressions"]] == [
        "serve_prefix_affinity_hit_rate"]
    assert result["regressions"][0]["change"] == -0.45
    # 0 -> 0.5 is an improvement, not an ov==0 skip
    result = bench_check.compare({"serve_prefix_cache_hit_rate": 0.0},
                                 {"serve_prefix_cache_hit_rate": 0.5})
    assert [r["metric"] for r in result["improvements"]] == [
        "serve_prefix_cache_hit_rate"]
    # skip markers cover rates too
    result = bench_check.compare(
        old, {"serve_prefix_cache_hit_rate_skipped": True,
              "serve_prefix_affinity_hit_rate": 0.9})
    assert not result["missing"]
    assert [r["metric"] for r in result["skipped"]] == [
        "serve_prefix_cache_hit_rate"]


def test_cached_cold_ttft_directions_and_markers():
    """The cached/cold serve TTFT cells are _ms lower-better metrics and
    honor their skip markers."""
    assert bench_check._direction("serve_ttft_cached_ms") == "down"
    assert bench_check._direction("serve_ttft_cold_ms") == "down"
    old = {"serve_ttft_cached_ms": 80.0, "serve_ttft_cold_ms": 400.0}
    result = bench_check.compare(old, {"serve_ttft_cached_ms": 300.0,
                                       "serve_ttft_cold_ms": 410.0})
    assert [r["metric"] for r in result["regressions"]] == [
        "serve_ttft_cached_ms"]
    result = bench_check.compare(old, {"serve_ttft_cached_skipped": True,
                                       "serve_ttft_cold_skipped": True})
    assert not result["missing"]
    assert {r["metric"] for r in result["skipped"]} == set(old)


def test_lower_better_regresses_up():
    old = {"serve_p50_ttft_ms": 272.1}
    new = {"serve_p50_ttft_ms": 320.0}
    result = bench_check.compare(old, new)
    assert [r["metric"] for r in result["regressions"]] == ["serve_p50_ttft_ms"]
    # and an improvement in latency is an improvement
    result = bench_check.compare(old, {"serve_p50_ttft_ms": 200.0})
    assert [r["metric"] for r in result["improvements"]] == ["serve_p50_ttft_ms"]


def test_cli_exit_codes_and_wrapper_format(tmp_path):
    """Accepts both bare metrics and the driver's BENCH_rNN wrapper;
    exit 1 on regression, 0 when clean."""
    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    old.write_text(json.dumps(
        {"n": 4, "cmd": "python bench.py", "rc": 0,
         "parsed": {"flash_fwdbwd_tflops_s4096": 26.16}}))
    new.write_text(json.dumps({"flash_fwdbwd_tflops_s4096": 22.99}))
    assert bench_check.main([str(old), str(new)]) == 1
    # within a generous threshold the same pair passes
    assert bench_check.main([str(old), str(new), "--threshold", "0.2"]) == 0
    new.write_text(json.dumps({"flash_fwdbwd_tflops_s4096": 26.5}))
    assert bench_check.main([str(old), str(new)]) == 0
    assert bench_check.main([str(old)]) == 2  # usage error


def test_latest_bench_json(tmp_path):
    assert bench_check.latest_bench_json(str(tmp_path)) is None
    (tmp_path / "BENCH_r04.json").write_text("{}")
    (tmp_path / "BENCH_r05.json").write_text("{}")
    latest = bench_check.latest_bench_json(str(tmp_path))
    assert latest is not None and latest.endswith("BENCH_r05.json")


def test_migration_metrics_directions_and_markers():
    """Round-11 KV-migration cells: migrated TTFT is lower-better,
    kv_migration_mb_s is a throughput (the `_mb_s` suffix must trump
    the `_s` lower-better suffix), and the skip markers route chip-box
    absences to the non-failing skipped bucket."""
    assert bench_check._direction("serve_ttft_migrated_ms") == "down"
    assert bench_check._direction("serve_ttft_cold_ms") == "down"
    assert bench_check._direction("kv_migration_mb_s") == "up"
    assert bench_check._direction("serve_spill_migrations") == "up"

    old = {"serve_ttft_migrated_ms": 50.0, "serve_ttft_cold_ms": 300.0,
           "kv_migration_mb_s": 60.0}
    # regressions in the right directions
    worse = {"serve_ttft_migrated_ms": 80.0, "serve_ttft_cold_ms": 310.0,
             "kv_migration_mb_s": 20.0}
    result = bench_check.compare(old, worse)
    names = {r["metric"] for r in result["regressions"]}
    assert "serve_ttft_migrated_ms" in names
    assert "kv_migration_mb_s" in names
    # skip markers: intentionally absent cells are not "missing"
    skipped = {"serve_ttft_migrated_skipped": True,
               "kv_migration_mb_s_skipped": True,
               "serve_ttft_cold_ms": 290.0}
    result = bench_check.compare(old, skipped)
    assert not result["missing"]
    assert {r["metric"] for r in result["skipped"]} == {
        "serve_ttft_migrated_ms", "kv_migration_mb_s"}


def test_overload_metrics_directions_and_markers():
    """Round-12 overload cells (ISSUE 12 satellite): goodput fractions
    compare higher-better in POINTS (the `_frac` suffix), the shed
    fast-fail latency is lower-better (the `fast_fail` substring — an
    honest rejection must stay cheap), and the shed/expired COUNTS are
    bookkeeping (protection ON sheds more than the unprotected baseline
    by design, so neither direction is a regression)."""
    assert bench_check._direction("serve_goodput_frac") == "up"
    assert bench_check._direction("serve_goodput_frac_unprotected") == "up"
    assert bench_check._direction("serve_shed_fast_fail_p95_ms") == "down"
    assert bench_check._direction("serve_admitted_p95_ttft_ms") == "down"
    assert not bench_check._tracked("serve_shed_requests", 12)
    assert not bench_check._tracked("serve_deadline_expired", 3)
    assert not bench_check._tracked("serve_overload_offered", 160)
    assert not bench_check._tracked("serve_overload_completed", 80)
    assert not bench_check._tracked("serve_capacity_rps_cfg", 9.5)

    old = {"serve_goodput_frac": 0.62, "serve_shed_fast_fail_p95_ms": 40.0,
           "serve_admitted_p95_ttft_ms": 600.0, "serve_shed_requests": 50}
    # goodput collapse is a POINTS regression; slow sheds regress UP
    worse = {"serve_goodput_frac": 0.31,
             "serve_shed_fast_fail_p95_ms": 400.0,
             "serve_admitted_p95_ttft_ms": 2500.0,
             "serve_shed_requests": 5}
    result = bench_check.compare(old, worse)
    names = {r["metric"] for r in result["regressions"]}
    assert names == {"serve_goodput_frac", "serve_shed_fast_fail_p95_ms",
                     "serve_admitted_p95_ttft_ms"}
    # a goodput wobble inside the point budget is noise, not a 10%+ move
    result = bench_check.compare({"serve_goodput_frac": 0.62},
                                 {"serve_goodput_frac": 0.55})
    assert not result["regressions"]


def test_overload_skip_markers_honored():
    """RAY_TPU_BENCH_SKIP_OVERLOAD leaves `*_skipped` markers: the
    overload cells read as intentionally skipped, never as silently
    vanished."""
    from ray_tpu._overload_bench import SKIP_MARKERS

    old = {"serve_goodput_frac": 0.62, "serve_goodput_frac_unprotected": 0.2,
           "serve_shed_fast_fail_p95_ms": 40.0,
           "serve_admitted_p95_ttft_ms": 600.0}
    result = bench_check.compare(old, dict(SKIP_MARKERS))
    assert not result["missing"], result["missing"]
    assert {r["metric"] for r in result["skipped"]} == set(old)


def test_speculative_metrics_directions():
    """Round-13 cells: decode tok/s higher-better, the accept rate is a
    pointwise 0-1 rate, and tokens-per-dispatch (amortized forwards)
    regresses DOWN — plus the audited "_tok_s" shadow: a bare token-
    throughput suffix must not fall into the lower-better "_s" bucket
    (the exact trap _mb_s hit before PR 11)."""
    assert bench_check._direction("decode_tok_s_plain") == "up"
    assert bench_check._direction("decode_tok_s_speculative") == "up"
    assert bench_check._direction("spec_tokens_per_dispatch") == "up"
    assert bench_check._direction("spec_accept_rate") == "up"
    assert bench_check._direction("spec_parity") == "up"
    # the audit find: metrics literally ending in _tok_s were shadowed
    assert bench_check._direction("pp_decode_tok_s") == "up"
    assert bench_check._direction("train_tok_s") == "up"
    # a tokens-per-dispatch slide is a regression, not an improvement
    old = {"spec_tokens_per_dispatch": 2.0, "decode_tok_s_speculative": 400.0}
    new = {"spec_tokens_per_dispatch": 1.1, "decode_tok_s_speculative": 430.0}
    result = bench_check.compare(old, new)
    assert {r["metric"] for r in result["regressions"]} == {
        "spec_tokens_per_dispatch"}


def test_spec_accept_rate_compares_in_points():
    """A 0.9 -> 0.45 accept-rate collapse is a 45-point regression; a
    0.02 -> 0.01 wiggle is noise, not a 50% drop."""
    result = bench_check.compare({"spec_accept_rate": 0.9},
                                 {"spec_accept_rate": 0.45})
    assert [r["metric"] for r in result["regressions"]] == [
        "spec_accept_rate"]
    result2 = bench_check.compare({"spec_accept_rate": 0.02},
                                  {"spec_accept_rate": 0.01})
    assert not result2["regressions"]
    # and 0 -> 0.5 counts as an improvement instead of an ov==0 skip
    result3 = bench_check.compare({"spec_accept_rate": 0.0},
                                  {"spec_accept_rate": 0.5})
    assert [r["metric"] for r in result3["improvements"]] == [
        "spec_accept_rate"]


def test_speculative_skip_markers_honored():
    """RAY_TPU_BENCH_SKIP_SPECULATIVE=1 leaves *_skipped markers: the
    absent cells land in the skipped bucket, never in missing; draft
    volume / dispatch counts are untracked bookkeeping."""
    old = {"decode_tok_s_plain": 600.0, "decode_tok_s_speculative": 380.0,
           "spec_accept_rate": 0.25, "spec_tokens_per_dispatch": 1.6,
           "spec_drafted_tokens": 1100, "spec_dispatches": 60,
           "spec_draft_k_cfg": 6}
    new = {"decode_tok_s_plain_skipped": True,
           "decode_tok_s_speculative_skipped": True,
           "spec_accept_rate_skipped": True,
           "spec_tokens_per_dispatch_skipped": True}
    result = bench_check.compare(old, new)
    assert not result["missing"] and not result["regressions"]
    assert {r["metric"] for r in result["skipped"]} == {
        "decode_tok_s_plain", "decode_tok_s_speculative",
        "spec_accept_rate", "spec_tokens_per_dispatch"}


def test_train_loop_metrics_directions():
    """Round-15 cells: dispatch overhead regresses UP (µs, and the
    "overhead" substring), MFU/overlap-frac are pointwise 0-1
    higher-better, tok/s cells ride the audited _tok_s suffix, and the
    ckpt save-block is a latency. Shadow audit: no train-loop cell ends
    in a bare "_s", so none can fall into the lower-better "_s" bucket
    (the pre-PR-11 _mb_s trap)."""
    assert bench_check._direction("train_step_dispatch_overhead_us") == "down"
    assert bench_check._direction(
        "train_step_dispatch_overhead_eager_us") == "down"
    assert bench_check._direction("train_mfu_eager") == "up"
    assert bench_check._direction("train_mfu_loop") == "up"
    assert bench_check._direction("train_mfu_1b_seq8k") == "up"
    assert bench_check._direction("mfu") == "up"
    assert bench_check._direction("mfu_8b_proxy") == "up"
    assert bench_check._direction("train_ckpt_overlap_frac") == "up"
    assert bench_check._direction("train_loop_tok_s") == "up"
    assert bench_check._direction("train_eager_tok_s") == "up"
    assert bench_check._direction("train_loop_ckpt_save_block_ms") == "down"
    # a dispatch-overhead GROWTH is the regression
    old = {"train_step_dispatch_overhead_us": 300.0,
           "train_ckpt_overlap_frac": 0.75}
    new = {"train_step_dispatch_overhead_us": 900.0,
           "train_ckpt_overlap_frac": 0.78}
    result = bench_check.compare(old, new)
    assert {r["metric"] for r in result["regressions"]} == {
        "train_step_dispatch_overhead_us"}


def test_mfu_compares_in_points():
    """MFU is a 0-1 fraction whose cell tag follows the unit
    (train_mfu_eager), so it is matched by SUBSTRING and compared in
    points: a 0.45 -> 0.30 collapse regresses, a CPU-sandbox
    0.00005 -> 0.00002 wiggle is noise — a relative compare would have
    flagged the wiggle as a 60% regression."""
    result = bench_check.compare({"train_mfu_loop": 0.45},
                                 {"train_mfu_loop": 0.30})
    assert [r["metric"] for r in result["regressions"]] == ["train_mfu_loop"]
    result2 = bench_check.compare({"train_mfu_loop": 5e-05},
                                  {"train_mfu_loop": 2e-05})
    assert not result2["regressions"]
    # config echoes stay untracked bookkeeping
    result3 = bench_check.compare({"train_loop_bench_ticks_cfg": 150},
                                  {"train_loop_bench_ticks_cfg": 50})
    assert not result3["regressions"] and not result3["missing"]


def test_train_loop_skip_markers_honored():
    """RAY_TPU_BENCH_SKIP_TRAIN_LOOP=1 leaves the three *_skipped
    markers; every train-loop cell lands in skipped, never missing."""
    old = {"train_step_dispatch_overhead_eager_us": 6400.0,
           "train_step_dispatch_overhead_us": 320.0,
           "train_mfu_eager": 5e-05, "train_mfu_loop": 6e-05,
           "train_ckpt_overlap_frac": 0.75}
    new = {"train_mfu_skipped": True,
           "train_step_dispatch_overhead_skipped": True,
           "train_ckpt_overlap_frac_skipped": True}
    result = bench_check.compare(old, new)
    assert not result["missing"] and not result["regressions"]
    assert {r["metric"] for r in result["skipped"]} == set(old)


def test_tenancy_metrics_directions():
    """Round-16 cells: the quiet-tenant p95 pair and the adapter hot-load
    are latencies ("_ms", plus the "ttft" substring on the p95 pair),
    goodput fractions are pointwise 0-1, and both parity cells ride the
    "_parity" suffix (1.0-or-broken invariants). Shadow audit: no
    tenancy cell ends in a bare "_s", so the lower-better "_s" bucket
    (the pre-PR-11 _mb_s trap) cannot shadow any of them."""
    assert bench_check._direction("tenant_quiet_p95_ttft_ms_solo") == "down"
    assert bench_check._direction("tenant_quiet_p95_ttft_ms_noisy") == "down"
    assert bench_check._direction("adapter_hot_load_ms") == "down"
    assert bench_check._direction("tenant_goodput_frac_hot") == "up"
    assert bench_check._direction("tenant_goodput_frac_cold") == "up"
    assert bench_check._direction("tenant_mixed_batch_parity") == "up"
    assert bench_check._direction("tenant_mixed_dispatch_parity") == "up"
    # a quiet-p95 GROWTH under the noisy storm is the regression the
    # isolation cells exist to catch
    old = {"tenant_quiet_p95_ttft_ms_noisy": 80.0,
           "tenant_goodput_frac_hot": 0.9}
    new = {"tenant_quiet_p95_ttft_ms_noisy": 160.0,
           "tenant_goodput_frac_hot": 0.92}
    result = bench_check.compare(old, new)
    assert {r["metric"] for r in result["regressions"]} == {
        "tenant_quiet_p95_ttft_ms_noisy"}


def test_tenancy_parity_and_goodput_compare_in_points():
    """A parity cell slipping 1.0 -> 0.0 (mixed batch no longer byte-
    identical) is a 100-point regression; a goodput 0.05 -> 0.04 wiggle
    is noise, not a 20% drop. Dispatch counts and storm sizes are _cfg
    bookkeeping, never tracked."""
    result = bench_check.compare({"tenant_mixed_batch_parity": 1.0},
                                 {"tenant_mixed_batch_parity": 0.0})
    assert [r["metric"] for r in result["regressions"]] == [
        "tenant_mixed_batch_parity"]
    result2 = bench_check.compare({"tenant_goodput_frac_cold": 0.05},
                                  {"tenant_goodput_frac_cold": 0.04})
    assert not result2["regressions"]
    result3 = bench_check.compare(
        {"tenant_mixed_decode_dispatches_cfg": 8,
         "tenant_storm_offered_cfg": 64,
         "tenant_noisy_quota_429_cfg": 12},
        {"tenant_mixed_decode_dispatches_cfg": 24,
         "tenant_storm_offered_cfg": 16,
         "tenant_noisy_quota_429_cfg": 0})
    assert not result3["regressions"] and not result3["missing"]


def test_tenancy_skip_markers_honored():
    """RAY_TPU_BENCH_SKIP_TENANCY=1 leaves the module's SKIP_MARKERS:
    every tenancy cell lands in skipped, never missing."""
    from ray_tpu._tenancy_bench import SKIP_MARKERS

    old = {"tenant_quiet_p95_ttft_ms_solo": 60.0,
           "tenant_quiet_p95_ttft_ms_noisy": 66.0,
           "tenant_goodput_frac_hot": 0.9,
           "tenant_goodput_frac_cold": 0.7,
           "tenant_mixed_batch_parity": 1.0,
           "tenant_mixed_dispatch_parity": 1.0,
           "adapter_hot_load_ms": 50.0}
    result = bench_check.compare(old, dict(SKIP_MARKERS))
    assert not result["missing"] and not result["regressions"]
    assert {r["metric"] for r in result["skipped"]} == set(old)


def test_round18_obs_metric_directions():
    """Round-18 shadow-suffix audit: pointwise cells now carry their own
    direction. Before _POINTWISE_DOWN_SUBSTR, the "_frac" suffix check
    ran ahead of the "overhead" substring, so the recorder-cost gate
    loop_obs_overhead_frac was guarded BACKWARDS (a cost blowup read as
    an improvement). Stall WAIT splits regress up; compute split stays
    higher-better; raw per-tick cells end in "_us" (lower-better)."""
    assert bench_check._pointwise("loop_obs_overhead_frac")
    assert bench_check._direction("loop_obs_overhead_frac") == "down"
    assert bench_check._direction("dag_loop_stall_wait_up_frac") == "down"
    assert bench_check._direction("dag_loop_stall_wait_down_frac") == "down"
    assert bench_check._direction("dag_loop_stall_compute_frac") == "up"
    assert bench_check._direction("loop_obs_tick_recording_us") == "down"
    assert bench_check._direction("loop_obs_tick_baseline_us") == "down"
    # representative earlier names keep their directions (shadow audit)
    assert bench_check._direction("kv_migration_mb_s") == "up"
    assert bench_check._direction("dag_tick_dispatch_overhead_us") == "down"
    assert bench_check._direction("tenant_goodput_frac_hot") == "up"
    assert bench_check._direction("train_ckpt_overlap_frac") == "up"
    assert bench_check._direction("serve_goodput_frac_unprotected") == "up"


def test_obs_overhead_frac_regresses_up_in_points():
    """The recorder-cost fraction compares in POINTS and lower-better:
    0.01 -> 0.18 is a 17-point cost blowup (regression); the inverse is
    an improvement; a 2-point compute-frac wiggle stays within budget."""
    old = {"loop_obs_overhead_frac": 0.01,
           "dag_loop_stall_wait_up_frac": 0.20,
           "dag_loop_stall_compute_frac": 0.60}
    new = {"loop_obs_overhead_frac": 0.18,
           "dag_loop_stall_wait_up_frac": 0.35,
           "dag_loop_stall_compute_frac": 0.58}
    result = bench_check.compare(old, new)
    assert {r["metric"] for r in result["regressions"]} == {
        "loop_obs_overhead_frac", "dag_loop_stall_wait_up_frac"}
    assert {r["metric"] for r in result["ok"]} == {
        "dag_loop_stall_compute_frac"}
    result2 = bench_check.compare(
        {"loop_obs_overhead_frac": 0.18}, {"loop_obs_overhead_frac": 0.01})
    assert {r["metric"] for r in result2["improvements"]} == {
        "loop_obs_overhead_frac"}


def test_fleet_metrics_directions():
    """Round-19 cells: standby promote and cold start are wall-clock
    seconds (the bare "_s" suffix, lower-better), the promote speedup is
    a ratio (higher-better default), broadcast parity rides the
    "_parity" suffix and the step goodput the "goodput_frac" substring —
    both pointwise 0-1 higher-better. Shadow audit: "speedup" must NOT
    fall into the lower-better "_s" bucket."""
    assert bench_check._direction("serve_replica_cold_start_s") == "down"
    assert bench_check._direction("serve_replica_promote_s") == "down"
    assert bench_check._direction("serve_replica_promote_speedup") == "up"
    assert bench_check._pointwise("fleet_broadcast_parity")
    assert bench_check._direction("fleet_broadcast_parity") == "up"
    assert bench_check._pointwise("fleet_goodput_frac_step")
    assert bench_check._direction("fleet_goodput_frac_step") == "up"
    # A promote-time blowup (warm pool no longer warm) and a speedup
    # collapse are exactly the regressions these cells exist to catch.
    old = {"serve_replica_promote_s": 0.005,
           "serve_replica_promote_speedup": 600.0}
    new = {"serve_replica_promote_s": 0.5,
           "serve_replica_promote_speedup": 7.0}
    result = bench_check.compare(old, new)
    assert {r["metric"] for r in result["regressions"]} == set(old)


def test_fleet_parity_and_goodput_compare_in_points():
    """Parity 1.0 -> 0.0 (broadcast no longer byte-identical) is a
    100-point regression; a small goodput wiggle through the step is
    noise; warm-pool/step bookkeeping (_cfg) is never tracked."""
    result = bench_check.compare({"fleet_broadcast_parity": 1.0},
                                 {"fleet_broadcast_parity": 0.0})
    assert [r["metric"] for r in result["regressions"]] == [
        "fleet_broadcast_parity"]
    result2 = bench_check.compare({"fleet_goodput_frac_step": 0.30},
                                  {"fleet_goodput_frac_step": 0.27})
    assert not result2["regressions"]
    result3 = bench_check.compare(
        {"fleet_standby_warm_cfg": True, "fleet_step_offered_cfg": 24,
         "fleet_step_promote_path_cfg": "host",
         "fleet_broadcast_bytes_cfg": 429137, "fleet_step_running_cfg": 2},
        {"fleet_step_offered_cfg": 12})
    assert not result3["regressions"] and not result3["missing"]


def test_fleet_skip_markers_honored():
    """RAY_TPU_BENCH_SKIP_FLEET=1 leaves the module's SKIP_MARKERS: the
    fleet_ prefix marker covers every fleet_* cell and the per-metric
    markers cover the serve_replica_* cells — skipped, never missing."""
    from ray_tpu._fleet_bench import SKIP_MARKERS

    old = {"serve_replica_cold_start_s": 3.4,
           "serve_replica_promote_s": 0.004,
           "serve_replica_promote_speedup": 800.0,
           "fleet_broadcast_parity": 1.0,
           "fleet_goodput_frac_step": 0.3}
    result = bench_check.compare(old, dict(SKIP_MARKERS))
    assert not result["missing"] and not result["regressions"]
    assert {r["metric"] for r in result["skipped"]} == set(old)
