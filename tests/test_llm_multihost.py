"""Multi-host LLM engine: an engine that SPANS hosts via per-host shard
actors + jax.distributed (reference ``vllm_models.py:117-168`` places
TP×PP engines across nodes with placement groups; SURVEY §7.1 calls this
SPMD↔actor bridge *the* architectural delta).

Multi-host is simulated the way the reference's tests simulate multi-node:
each shard actor is a real worker process with ONE local CPU device
(``xla_force_host_platform_device_count=1``), joined into one global
2-device mesh by ``jax.distributed.initialize`` with gloo cross-process
collectives — the same code path a v5e pod takes over ICI.
"""

import dataclasses

import jax
import jax.numpy as jnp
import pytest

import ray_tpu
from ray_tpu.llm import InferenceEngine, create_sharded_executor
from ray_tpu.llm.serving import LLMDeployment
from ray_tpu.models.llama import PRESETS
from conftest import requires_shard_map

# Each shard process sees exactly one local CPU device; two shards form
# the 2-device global mesh.
SHARD_ENV = {"env_vars": {"XLA_FLAGS": "--xla_force_host_platform_device_count=1"}}


@pytest.fixture(scope="module")
def small_cfg():
    return dataclasses.replace(
        PRESETS["debug"], dtype=jnp.float32, attn_impl="reference")


def test_multihost_engine_token_parity(ray_cluster, small_cfg):
    """2 shard processes × 1 device each == one 2-device tp mesh: decoded
    tokens must match the single-process engine exactly (greedy)."""
    prompts = [list(range(1, 22)), [7, 3, 7, 3, 7], [2, 4, 6, 8, 10, 12, 14, 16, 18]]

    ref = InferenceEngine(small_cfg, max_slots=2, max_len=64, page_size=8, seed=0)
    expected = [ref.generate(list(p), max_new_tokens=6) for p in prompts]

    executor = create_sharded_executor(
        small_cfg, 2,
        max_slots=2,
        num_pages=InferenceEngine.total_pages(2, 64, 8),
        page_size=8,
        seed=0,
        runtime_env=SHARD_ENV,
    )
    try:
        eng = InferenceEngine(small_cfg, max_slots=2, max_len=64, page_size=8,
                              executor=executor, seed=0)
        got = [eng.generate(list(p), max_new_tokens=6) for p in prompts]
        assert got == expected
    finally:
        executor.shutdown()


def test_multihost_compiled_loop_token_parity(ray_cluster, small_cfg):
    """The compiled-loop tick path (round 8): the SAME shard fleet driven
    through a persistent dag/loop.py pipeline — one owner-side submit per
    shard, then every engine operation is a channel write/read with zero
    per-tick RPC — must decode byte-identically to the per-call dynamic
    path (channel FIFO ordering preserves the SPMD invariant exactly as
    per-caller actor ordering did)."""
    prompts = [list(range(1, 22)), [7, 3, 7, 3, 7]]

    ref = InferenceEngine(small_cfg, max_slots=2, max_len=64, page_size=8, seed=0)
    expected = [ref.generate(list(p), max_new_tokens=6) for p in prompts]

    executor = create_sharded_executor(
        small_cfg, 2,
        max_slots=2,
        num_pages=InferenceEngine.total_pages(2, 64, 8),
        page_size=8,
        seed=0,
        runtime_env=SHARD_ENV,
        use_compiled_loop=True,
    )
    try:
        assert executor.use_compiled_loop and executor._loop is not None
        eng = InferenceEngine(small_cfg, max_slots=2, max_len=64, page_size=8,
                              executor=executor, seed=0)
        got = [eng.generate(list(p), max_new_tokens=6) for p in prompts]
        assert got == expected
        # every prefill/sample/decode streamed through the loop, and the
        # engine surfaces the count
        assert executor.loop_ticks > 0
        assert eng.metrics["dag_loop_ticks"] == executor.loop_ticks
    finally:
        executor.shutdown()


@requires_shard_map
def test_multihost_pp_token_parity(ray_cluster, small_cfg):
    """Pipeline parallelism across hosts: 2 shard processes × 1 device
    each form a pp=2 mesh — each host holds HALF the layers and half the
    page pool, activations cross hosts via ppermute (llm/pp_model.py).
    Tokens must match the single-process engine exactly (greedy)."""
    prompts = [list(range(1, 22)), [7, 3, 7, 3, 7]]

    ref = InferenceEngine(small_cfg, max_slots=2, max_len=64, page_size=8, seed=0)
    expected = [ref.generate(list(p), max_new_tokens=6) for p in prompts]

    executor = create_sharded_executor(
        small_cfg, 2,
        max_slots=2,
        num_pages=InferenceEngine.total_pages(2, 64, 8),
        page_size=8,
        pp=2,
        seed=0,
        runtime_env=SHARD_ENV,
    )
    try:
        eng = InferenceEngine(small_cfg, max_slots=2, max_len=64, page_size=8,
                              executor=executor, seed=0)
        got = [eng.generate(list(p), max_new_tokens=6) for p in prompts]
        assert got == expected
    finally:
        executor.shutdown()


def test_multihost_deployment_generates(ray_cluster):
    """The Serve deployment path: ``num_hosts=2`` builds the shard fleet
    behind one replica-facing engine; requests flow scheduler -> shards."""
    cfg = dataclasses.replace(
        PRESETS["debug-128"], dtype=jnp.float32, attn_impl="reference")
    dep = LLMDeployment(
        cfg, max_slots=2, max_len=64, page_size=8,
        prefill_chunk_size=16, decode_steps_per_dispatch=4,
        num_hosts=2, shard_resources={"CPU": 0.5},
        shard_runtime_env=SHARD_ENV,
    )
    try:
        out = dep.generate("ab", max_new_tokens=4)
        assert out["num_generated"] == 4
        assert out["finish_reason"] in ("length", "stop")
    finally:
        dep.close()
