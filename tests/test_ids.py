from ray_tpu.core.ids import ActorID, JobID, NodeID, ObjectID, PlacementGroupID, TaskID


def test_job_id_roundtrip():
    j = JobID.from_int(7)
    assert j.int_value() == 7
    assert JobID.from_hex(j.hex()) == j


def test_task_id_embeds_actor_and_job():
    job = JobID.from_int(3)
    driver = TaskID.for_driver_task(job)
    t = TaskID.for_normal_task(job, driver, 1)
    assert t.job_id() == job
    assert t.actor_id().is_nil() is False or t.actor_id().job_id() == job


def test_object_id_embeds_task():
    job = JobID.from_int(1)
    driver = TaskID.for_driver_task(job)
    t = TaskID.for_normal_task(job, driver, 5)
    o = ObjectID.for_task_return(t, 2)
    assert o.task_id() == t
    assert o.index() == 2
    assert not o.is_put()
    p = ObjectID.for_put(t, 1)
    assert p.is_put()
    assert p.task_id() == t


def test_deterministic_lineage():
    """Same (parent, counter) must regenerate the same IDs — required for
    lineage reconstruction."""
    job = JobID.from_int(1)
    driver = TaskID.for_driver_task(job)
    assert TaskID.for_normal_task(job, driver, 9) == TaskID.for_normal_task(job, driver, 9)
    assert TaskID.for_normal_task(job, driver, 9) != TaskID.for_normal_task(job, driver, 10)


def test_actor_id():
    job = JobID.from_int(2)
    driver = TaskID.for_driver_task(job)
    a = ActorID.of(job, driver, 1)
    assert a.job_id() == job
    creation = TaskID.for_actor_creation_task(a)
    assert creation.actor_id() == a


def test_random_and_nil():
    n = NodeID.from_random()
    assert not n.is_nil()
    assert NodeID.nil().is_nil()
    assert len(PlacementGroupID.of(JobID.from_int(1)).binary()) == 18
