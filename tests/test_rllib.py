"""RL stack: Learner, LearnerGroup, EnvRunnerGroup, PPO.

Acceptance per VERDICT #10 / SURVEY: PPO on a toy env must actually
learn; learner-group data parallelism must keep replicas in lockstep
(reference ``rllib/core/learner/learner_group.py`` sync-update
semantics).
"""

import numpy as np
import pytest

from ray_tpu.rllib import CartPole, GridWorld, PPO, PPOConfig
from ray_tpu.rllib import models
from ray_tpu.rllib.env_runner import EnvRunner
from ray_tpu.rllib.learner import Learner
from ray_tpu.rllib.ppo import compute_gae, make_ppo_loss


def test_cartpole_env_contract():
    env = CartPole(num_envs=3, seed=0)
    obs = env.reset()
    assert obs.shape == (3, 4)
    for _ in range(10):
        obs, rew, done, info = env.step(np.array([1, 0, 1]))
        assert obs.shape == (3, 4) and rew.shape == (3,) and done.shape == (3,)
        assert (rew == 1.0).all()
        assert set(info) >= {"terminated", "truncated", "terminal_obs"}


def test_auto_reset_returns_fresh_obs_and_truncation_split():
    """On done, step() must return the NEW episode's obs (the policy acts
    on it next), with the terminal obs preserved in info; hitting the time
    limit must report truncated, not terminated."""
    env = GridWorld(num_envs=1, seed=0)
    env.reset()
    done = np.array([False])
    for _ in range(env.max_steps):
        obs, rew, done, info = env.step(np.array([1]))  # move away from goal
        if done[0]:
            break
    assert done[0] and info["truncated"][0] and not info["terminated"][0]
    np.testing.assert_array_equal(obs[0], [0.0, 0.0])  # fresh episode obs


def test_gae_bootstraps_truncation():
    """A truncated boundary must bootstrap V(terminal_obs); a terminated
    one must not."""
    base = {
        "rewards": np.array([[1.0]], np.float32),
        "values": np.array([[0.0]], np.float32),
        "dones": np.array([[True]], np.bool_),
        "last_value": np.array([0.0], np.float32),
    }
    gamma = 0.9
    adv_term, _ = compute_gae({**base, "trunc_values": np.zeros((1, 1), np.float32)}, gamma, 0.95)
    adv_trunc, _ = compute_gae({**base, "trunc_values": np.array([[2.0]], np.float32)}, gamma, 0.95)
    np.testing.assert_allclose(adv_term[0, 0], 1.0)
    np.testing.assert_allclose(adv_trunc[0, 0], 1.0 + gamma * 2.0)


def test_gae_matches_hand_computation():
    sample = {
        "rewards": np.array([[1.0], [1.0]], np.float32),
        "values": np.array([[0.5], [0.4]], np.float32),
        "dones": np.array([[False], [True]], np.bool_),
        "last_value": np.array([9.9], np.float32),  # masked by done
    }
    gamma, lam = 0.9, 0.8
    adv, ret = compute_gae(sample, gamma, lam)
    # t=1 (terminal): delta = 1 - 0.4 = 0.6 ; adv = 0.6
    # t=0: delta = 1 + 0.9*0.4 - 0.5 = 0.86 ; adv = 0.86 + 0.9*0.8*0.6 = 1.292
    np.testing.assert_allclose(adv[:, 0], [1.292, 0.6], rtol=1e-5)
    np.testing.assert_allclose(ret[:, 0], adv[:, 0] + sample["values"][:, 0], rtol=1e-5)


def test_learner_update_reduces_loss():
    rng = np.random.default_rng(0)
    batch = {
        "obs": rng.normal(size=(256, 4)).astype(np.float32),
        "actions": rng.integers(0, 2, 256),
        "logp_old": np.full(256, -0.69, np.float32),
        "advantages": rng.normal(size=256).astype(np.float32),
        "returns": rng.normal(size=256).astype(np.float32),
    }
    lrn = Learner(make_ppo_loss(0.2, 0.5, 0.01),
                  lambda k: models.init_policy(k, 4, 2, 32), lr=1e-2)
    first = lrn.update(batch)["total_loss"]
    for _ in range(20):
        last = lrn.update(batch)["total_loss"]
    assert last < first


def test_env_runner_sample_shapes_and_episodes():
    runner = EnvRunner(GridWorld, num_envs=4, rollout_len=60, seed=0)
    weights = models.init_policy(__import__("jax").random.PRNGKey(0), 2, 4, 16)
    s = runner.sample(weights)
    assert s["obs"].shape == (60, 4, 2)
    assert s["actions"].shape == (60, 4)
    assert s["episode_returns"].size > 0  # GridWorld episodes cap at 50 steps


def test_ppo_cartpole_learns():
    """The acceptance test: mean episode return must clearly improve over
    a few dozen in-process iterations."""
    algo = (
        PPOConfig()
        .environment(CartPole)
        .env_runners(num_env_runners=0, num_envs_per_runner=16, rollout_len=128)
        .training(lr=3e-3, num_epochs=4, minibatch_size=512)
        .seeding(0)
        .build()
    )
    first = algo.train()["episode_return_mean"]
    result = {}
    for _ in range(29):
        result = algo.train()
    algo.stop()
    assert result["episode_return_mean"] > max(60.0, 2 * first), (
        f"no learning: {first} -> {result['episode_return_mean']}"
    )


def test_ppo_checkpoint_roundtrip(tmp_path):
    algo = (
        PPOConfig().environment(GridWorld)
        .env_runners(num_envs_per_runner=4, rollout_len=20)
        .training(minibatch_size=80).build()
    )
    algo.train()
    algo.save(str(tmp_path))
    w_before = algo.learner_group.get_weights()
    it_before = algo.iteration

    algo2 = (
        PPOConfig().environment(GridWorld)
        .env_runners(num_envs_per_runner=4, rollout_len=20)
        .training(minibatch_size=80).build()
    )
    algo2.restore(str(tmp_path))
    assert algo2.iteration == it_before
    w_after = algo2.learner_group.get_weights()
    for a, b in zip(
        __import__("jax").tree.leaves(w_before), __import__("jax").tree.leaves(w_after)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    algo.stop()
    algo2.stop()


def test_learner_group_parallel_matches_local(ray_cluster):
    """Two learner actors, batch sharded, grads averaged, applied on both:
    the resulting weights must equal a single local learner updating on
    the full batch (synchronous data parallelism)."""
    from ray_tpu.rllib.learner_group import LearnerGroup

    rng = np.random.default_rng(1)
    half = {
        "obs": rng.normal(size=(64, 4)).astype(np.float32),
        "actions": rng.integers(0, 2, 64),
        "logp_old": np.full(64, -0.69, np.float32),
        "advantages": rng.normal(size=64).astype(np.float32),
        "returns": rng.normal(size=64).astype(np.float32),
    }
    # Both shards identical: per-shard statistics (advantage norm) equal the
    # full-batch statistics, so sharded-averaged grads == full-batch grads
    # exactly and the comparison is tight.
    batch = {k: np.concatenate([v, v]) for k, v in half.items()}
    kwargs = dict(lr=1e-2, seed=7)
    loss = make_ppo_loss(0.2, 0.5, 0.01)

    def init_fn(k):
        return models.init_policy(k, 4, 2, 16)

    local = LearnerGroup(loss, init_fn, num_learners=0, **kwargs)
    group = LearnerGroup(loss, init_fn, num_learners=2, **kwargs)
    try:
        local.update(batch)
        group.update(batch)
        wl, wg = local.get_weights(), group.get_weights()
        import jax

        for a, b in zip(jax.tree.leaves(wl), jax.tree.leaves(wg)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-3)
    finally:
        group.shutdown()


def test_ppo_distributed_smoke(ray_cluster):
    """PPO with remote env runners and a remote learner completes
    iterations and reports sane metrics."""
    algo = (
        PPOConfig()
        .environment(GridWorld)
        .env_runners(num_env_runners=2, num_envs_per_runner=4, rollout_len=20)
        .learners(num_learners=1)
        .training(minibatch_size=80)
        .build()
    )
    try:
        m = algo.train()
        assert m["num_env_steps_sampled"] == 2 * 4 * 20
        assert "total_loss" in m and "episode_return_mean" in m
        m2 = algo.train()
        assert m2["training_iteration"] == 2
    finally:
        algo.stop()


def test_replay_buffer_ring_and_sampling():
    from ray_tpu.rllib import ReplayBuffer

    buf = ReplayBuffer(capacity=100, obs_dim=2, seed=0)
    obs = np.arange(240, dtype=np.float32).reshape(120, 2)
    buf.add_batch(obs, np.arange(120) % 4, np.ones(120, np.float32), obs + 1,
                  np.zeros(120, np.float32))
    assert len(buf) == 100  # ring wrapped
    batch = buf.sample(32)
    assert batch["obs"].shape == (32, 2) and batch["actions"].shape == (32,)
    # wrapped entries are the most recent 100 (rows 20..119)
    assert batch["obs"].min() >= 40.0


def test_dqn_loss_targets():
    """Double-DQN target: r + gamma * Q_target(s', argmax_a Q_online(s', a)),
    zeroed on termination."""
    import jax
    from ray_tpu.rllib import models
    from ray_tpu.rllib.dqn import make_dqn_loss

    params = models.init_policy(jax.random.PRNGKey(0), 2, 3, hidden=8)
    target = models.init_policy(jax.random.PRNGKey(1), 2, 3, hidden=8)
    batch = {
        "obs": np.zeros((4, 2), np.float32),
        "actions": np.array([0, 1, 2, 0]),
        "rewards": np.ones(4, np.float32),
        "next_obs": np.ones((4, 2), np.float32),
        "terminated": np.array([0, 0, 1, 1], np.float32),
        "target_params": target,
    }
    loss, metrics = make_dqn_loss(0.9, double_q=True)(params, batch)
    assert np.isfinite(float(loss)) and "td_error_mean" in metrics
    # terminated rows must not bootstrap: recompute by hand
    q_all, _ = models.forward(params, batch["obs"])
    q_sa = np.take_along_axis(np.asarray(q_all), batch["actions"][:, None], 1)[:, 0]
    qn_on, _ = models.forward(params, batch["next_obs"])
    qn_tg, _ = models.forward(target, batch["next_obs"])
    a_sel = np.asarray(qn_on).argmax(1)
    qn = np.take_along_axis(np.asarray(qn_tg), a_sel[:, None], 1)[:, 0]
    tgt = batch["rewards"] + 0.9 * (1 - batch["terminated"]) * qn
    td = q_sa - tgt
    expected = np.mean(np.where(np.abs(td) < 1, 0.5 * td**2, np.abs(td) - 0.5))
    np.testing.assert_allclose(float(loss), expected, rtol=1e-5)


def test_dqn_learns_cartpole():
    """Off-policy DQN learns CartPole in-process — the Learner/EnvRunner
    stack generalizes beyond PPO (reference rllib/algorithms/dqn)."""
    from ray_tpu.rllib import DQNConfig
    from ray_tpu.rllib.env import CartPole

    algo = (DQNConfig()
            .environment(CartPole)
            .env_runners(num_env_runners=0, num_envs_per_runner=16, rollout_len=32)
            .training(lr=1e-3, learning_starts=500, updates_per_iteration=48,
                      target_update_freq=100, eps_decay_steps=6000, batch_size=128)
            .seeding(0)
            .build())
    best = 0.0
    for _ in range(70):
        m = algo.train()
        best = max(best, m["episode_return_mean"])
        if best > 150:
            break
    assert best > 150, f"DQN did not learn: best={best}"


def test_dqn_distributed_runners(ray_cluster):
    """DQN with remote EnvRunner actors: transitions flow through the
    object store, learning still progresses."""
    from ray_tpu.rllib import DQNConfig
    from ray_tpu.rllib.env import GridWorld

    algo = (DQNConfig()
            .environment(GridWorld)
            .env_runners(num_env_runners=2, num_envs_per_runner=8, rollout_len=25)
            .training(lr=2e-3, learning_starts=300, updates_per_iteration=24,
                      eps_decay_steps=2500, batch_size=64)
            .seeding(1)
            .build())
    best = -1e9
    for _ in range(40):
        m = algo.train()
        best = max(best, m["episode_return_mean"])
    algo.stop()
    # optimal GridWorld return ~ +1 - 8*0.01; random wandering is deeply negative
    assert best > 0.5, f"distributed DQN did not learn GridWorld: best={best}"


def test_sac_learns_pendulum():
    """SAC (squashed Gaussian + twin Q + auto alpha) on continuous
    control: Pendulum return must rise far above the random-policy level
    (reference rllib/algorithms/sac)."""
    from ray_tpu.rllib import Pendulum, SACConfig

    algo = (SACConfig()
            .environment(Pendulum)
            .env_runners(num_env_runners=0, num_envs_per_runner=16,
                         rollout_len=32)
            .seeding(0)
            .build())
    best = -1e9
    for _ in range(80):
        m = algo.train()
        r = m["episode_return_mean"]
        # the mean is a 0.0 placeholder until the first 200-step episodes
        # complete — only trust it after real episodes are in the window
        if m["num_env_steps_sampled"] >= 4000 and r != 0.0:
            best = max(best, r)
        if best > -350:
            break
    algo.stop()
    # random policy sits near -1200; swing-up control clears -350
    assert best > -350, f"SAC did not learn Pendulum: best={best}"
    assert 0.0 < m["alpha"] < 1.0, f"alpha never adapted: {m['alpha']}"


def test_sac_checkpoint_roundtrip(tmp_path):
    from ray_tpu.rllib import Pendulum, SACConfig

    algo = (SACConfig().environment(Pendulum)
            .env_runners(num_env_runners=0, num_envs_per_runner=4, rollout_len=8)
            .training(learning_starts=64, updates_per_iteration=4, batch_size=32)
            .seeding(3).build())
    for _ in range(3):
        algo.train()
    ckpt_dir = str(tmp_path / "sac")
    algo.save(ckpt_dir)
    restored = (SACConfig().environment(Pendulum)
                .env_runners(num_env_runners=0, num_envs_per_runner=4, rollout_len=8)
                .training(learning_starts=64, updates_per_iteration=4, batch_size=32)
                .seeding(99).build())
    restored.restore(ckpt_dir)
    assert restored.iteration == algo.iteration
    import numpy as np

    a = algo.get_state()["state"]["log_alpha"]
    b = restored.get_state()["state"]["log_alpha"]
    assert np.allclose(a, b)
    restored.train()  # resumes cleanly
    algo.stop(); restored.stop()


def test_multiagent_ppo_independent_policies():
    """One PPO policy per agent over a simultaneous-move multi-agent env
    (reference rllib/env/multi_agent_env_runner.py): every policy's
    return improves, and per-policy metrics are reported."""
    from ray_tpu.rllib import MultiAgentCartPole, MultiAgentPPOConfig

    algo = (MultiAgentPPOConfig()
            .environment(MultiAgentCartPole)
            .env_runners(num_env_runners=0, num_envs_per_runner=8,
                         rollout_len=64)
            .training(lr=3e-3)
            .multi_agent(env_kwargs={"num_agents": 2})
            .seeding(0)
            .build())
    first = algo.train()["episode_return_mean"]
    m = {}
    for _ in range(24):
        m = algo.train()
    algo.stop()
    assert m["episode_return_mean"] > max(40.0, 1.5 * first), (
        f"no multi-agent learning: {first} -> {m['episode_return_mean']}")
    assert "agent_0" in m and "agent_1" in m
    assert m["agent_0"]["episode_return_mean"] > 0


def test_multiagent_shared_policy_and_mapping(ray_cluster):
    """policy_mapping_fn routes several agents to ONE shared policy; the
    shared policy trains on all agents' fragments; remote runner actors
    carry the mapping function (cloudpickle) across the actor boundary."""
    import pytest

    from ray_tpu.rllib import MultiAgentCartPole, MultiAgentPPOConfig

    algo = (MultiAgentPPOConfig()
            .environment(MultiAgentCartPole)
            .env_runners(num_env_runners=2, num_envs_per_runner=4,
                         rollout_len=32)
            .multi_agent(policies=["shared"],
                         policy_mapping_fn=lambda aid: "shared",
                         env_kwargs={"num_agents": 3})
            .seeding(1)
            .build())
    m = {}
    for _ in range(3):
        m = algo.train()
    algo.stop()
    assert set(k for k in m if isinstance(m[k], dict)) == {"shared"}
    # 3 agents x 2 runners x 4 envs x 32 steps flow into the one policy
    assert m["num_env_steps_sampled"] == 3 * 2 * 4 * 32

    # a policy with no mapped agents is a config error
    with pytest.raises(ValueError, match="no mapped agents"):
        (MultiAgentPPOConfig()
         .environment(MultiAgentCartPole)
         .multi_agent(policies=["shared", "orphan"],
                      policy_mapping_fn=lambda aid: "shared",
                      env_kwargs={"num_agents": 2})
         .build())


def test_multiagent_unmapped_agent_is_config_error():
    from ray_tpu.rllib import MultiAgentCartPole, MultiAgentPPOConfig

    with pytest.raises(ValueError, match="absent from"):
        (MultiAgentPPOConfig()
         .environment(MultiAgentCartPole)
         .multi_agent(policies=["agent_0"], env_kwargs={"num_agents": 2})
         .build())


# --------------------------------------------------- connectors / evaluation

def test_connector_pipeline_pieces():
    import numpy as np

    from ray_tpu.rllib.connectors import (
        ClipRewards, ConnectorPipelineV2, NormalizeObservations,
        ScaleObservations, make_pipeline)

    norm = NormalizeObservations(clip=5.0)
    rng = np.random.default_rng(0)
    obs = rng.standard_normal((64, 4)).astype(np.float32) * 10 + 3
    out = norm({"obs": obs})["obs"]
    out2 = norm({"obs": obs})["obs"]
    assert abs(out2.mean()) < 1.0 and 0.3 < out2.std() < 3.0
    # state roundtrip
    st = norm.get_state()
    norm2 = NormalizeObservations()
    norm2.set_state(st)
    np.testing.assert_allclose(norm2({"obs": obs})["obs"],
                               norm({"obs": obs})["obs"], atol=1e-4)

    pipe = make_pipeline([ScaleObservations(0.5), ClipRewards(1.0)])
    b = pipe({"obs": np.full((2, 3), 4.0), "rewards": np.asarray([3.0, -2.0])})
    assert (b["obs"] == 2.0).all() and list(b["rewards"]) == [1.0, -1.0]
    assert isinstance(pipe, ConnectorPipelineV2)


def test_ppo_with_connectors_and_evaluate(ray_cluster):
    """PPO trains through an env-to-module normalizer pipeline (rollouts
    record TRANSFORMED observations — the ConnectorV2 invariant) and the
    evaluation harness (Algorithm.evaluate, reference
    algorithms/algorithm.py:199) reports dedicated-runner returns with
    frozen normalizer stats."""
    from ray_tpu.rllib import NormalizeObservations, PPOConfig
    from ray_tpu.rllib.env import CartPole

    config = (
        PPOConfig()
        .environment(CartPole)
        .env_runners(num_env_runners=0, num_envs_per_runner=8, rollout_len=64)
        .training(lr=3e-3, num_epochs=4, minibatch_size=256)
        .connectors(env_to_module=lambda: NormalizeObservations())
        .evaluation(num_episodes=5, num_envs=4)
        .seeding(0)
    )
    algo = config.build()
    try:
        first = None
        for _ in range(12):
            m = algo.train()
            if first is None and m.get("episode_return_mean") is not None:
                first = m["episode_return_mean"]
        ev = algo.evaluate()["evaluation"]
        assert ev["num_episodes"] == 5
        assert ev["episode_return_mean"] > 25.0  # better than random (~20)
        # eval runner's normalizer must be frozen
        for p in algo._eval_runner.env_to_module.pieces:
            assert p.update is False
    finally:
        algo.stop()
