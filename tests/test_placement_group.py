"""Placement group API tests (reference: test patterns around
``python/ray/tests/test_placement_group*.py``)."""

import pytest

import ray_tpu
from ray_tpu.util import (
    PlacementGroupSchedulingStrategy,
    placement_group,
    remove_placement_group,
)


def test_placement_group_create_and_schedule(ray_cluster):
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
    assert pg.wait(timeout_seconds=30)
    assert pg.ready()

    @ray_tpu.remote
    def where():
        import os

        return os.getpid()

    ref = where.options(
        num_cpus=1,
        scheduling_strategy=PlacementGroupSchedulingStrategy(
            placement_group=pg, placement_group_bundle_index=0
        ),
    ).remote()
    assert isinstance(ray_tpu.get(ref, timeout=60), int)
    remove_placement_group(pg)


def test_placement_group_infeasible(ray_cluster):
    pg = placement_group([{"CPU": 10_000}], strategy="STRICT_PACK")
    from ray_tpu.core.status import PlacementGroupUnschedulableError

    with pytest.raises(PlacementGroupUnschedulableError):
        # infeasibility is only declared after a ~10s grace window (late-
        # registering raylets must not doom a group)
        pg.wait(timeout_seconds=20)


def test_placement_group_actor(ray_cluster):
    pg = placement_group([{"CPU": 1}], strategy="PACK")
    assert pg.wait(timeout_seconds=30)

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def inc(self):
            self.n += 1
            return self.n

    c = Counter.options(
        num_cpus=1,
        scheduling_strategy=PlacementGroupSchedulingStrategy(
            placement_group=pg, placement_group_bundle_index=0
        ),
    ).remote()
    assert ray_tpu.get(c.inc.remote(), timeout=60) == 1
    ray_tpu.kill(c)
    remove_placement_group(pg)
