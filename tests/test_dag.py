"""Compiled graphs (aDAG) over mutable shm channels.

Reference surface: ``python/ray/dag/compiled_dag_node.py:795`` +
mutable-object channels. Acceptance: repeated execute() with zero
per-call task submission, fan-out/fan-in, error propagation, teardown
returning the actors to normal use.
"""

import threading

import pytest

import ray_tpu
from ray_tpu.dag import InputNode, MultiOutputNode
from ray_tpu.dag.channel import Channel, ChannelClosed


def test_channel_roundtrip_and_stop(tmp_path):
    path = str(tmp_path / "ch")
    ch = Channel(path, 1024, create=True)
    reader = Channel(path, 1024)
    ch.write(b"one")
    payload, seq = reader.read(0, timeout=5)
    assert payload == b"one"
    ch.write(b"two")
    payload, seq = reader.read(seq, timeout=5)
    assert payload == b"two"
    with pytest.raises(ValueError):
        ch.write(b"x" * 2048)
    ch.close_writer()
    with pytest.raises(ChannelClosed):
        reader.read(seq, timeout=5)
    ch.close()
    reader.close()


def test_channel_concurrent_writer_reader(tmp_path):
    """A spinning reader never observes a torn message (seqlock). The
    channel is latest-value (writers overwrite), so the reader may skip
    versions but must always read internally-consistent payloads."""
    import time

    path = str(tmp_path / "ch2")
    w = Channel(path, 4096, create=True)
    r = Channel(path, 4096)
    n, got = 200, []
    final = (n - 1) % 251
    caught_up = threading.Event()

    def produce():
        for i in range(n):
            w.write(bytes([i % 251]) * (1 + i % 97))
            time.sleep(0.0002)
        caught_up.wait(10)  # don't overwrite the final value with STOP early
        w.close_writer()

    t = threading.Thread(target=produce)
    t.start()
    seq = 0
    try:
        while True:
            payload, seq = r.read(seq, timeout=10)
            assert len(set(payload)) == 1, "torn read"
            got.append(payload[0])
            if payload[0] == final:
                caught_up.set()
    except ChannelClosed:
        pass
    t.join()
    assert got and got[-1] == final
    w.close()
    r.close()


@ray_tpu.remote
class Adder:
    def __init__(self, k):
        self.k = k
        self.calls = 0

    def add(self, x):
        self.calls += 1
        return x + self.k

    def boom(self, x):
        if x == 13:
            raise ValueError("unlucky")
        return x * 2

    def call_count(self):
        return self.calls


def test_linear_pipeline_repeated_execute(ray_cluster):
    a, b = Adder.remote(1), Adder.remote(10)
    with InputNode() as inp:
        dag = b.add.bind(a.add.bind(inp))
    compiled = dag.experimental_compile()
    try:
        for i in range(20):
            assert compiled.execute(i) == i + 11
    finally:
        compiled.teardown()
    # After teardown the actors serve normal calls again, and the loop ran
    # as ONE task: 20 executes never submitted per-call tasks.
    assert ray_tpu.get(a.call_count.remote(), timeout=60) == 20


def test_fan_out_fan_in(ray_cluster):
    a, b, c = Adder.remote(1), Adder.remote(100), Adder.remote(1000)
    with InputNode() as inp:
        mid = a.add.bind(inp)
        dag = MultiOutputNode([b.add.bind(mid), c.add.bind(mid)])
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(5) == (106, 1006)
        assert compiled.execute(7) == (108, 1008)
    finally:
        compiled.teardown()


def test_error_propagates_and_pipeline_survives(ray_cluster):
    a, b = Adder.remote(0), Adder.remote(5)
    with InputNode() as inp:
        dag = b.add.bind(a.boom.bind(inp))
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(2) == 9  # 2*2 + 5
        with pytest.raises(ValueError, match="unlucky"):
            compiled.execute(13)
        assert compiled.execute(3) == 11  # loop survived the error
    finally:
        compiled.teardown()


def test_multi_output_error_does_not_desync_later_rounds(ray_cluster):
    """An error on one output branch must not leave the other branch's
    cursor behind (all outputs drain before the raise)."""
    a, b, c = Adder.remote(0), Adder.remote(0), Adder.remote(100)
    with InputNode() as inp:
        mid = a.add.bind(inp)
        dag = MultiOutputNode([b.boom.bind(mid), c.add.bind(mid)])
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(1) == (2, 101)
        with pytest.raises(ValueError, match="unlucky"):
            compiled.execute(13)
        assert compiled.execute(2) == (4, 102)  # fresh, not round-13 leftovers
    finally:
        compiled.teardown()


def test_unpicklable_result_propagates_as_error(ray_cluster):
    """A result the serializer can't encode must surface as a task error,
    not kill the resident loop and time out the driver."""

    @ray_tpu.remote
    class Bad:
        def make(self, x):
            if x == 1:
                return threading.Lock()  # unpicklable
            return x

    bad = Bad.remote()
    with InputNode() as inp:
        dag = bad.make.bind(inp)
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(0) == 0
        with pytest.raises(Exception) as exc_info:
            compiled.execute(1, timeout=30)
        assert "lock" in str(exc_info.value).lower() or "pickle" in str(exc_info.value).lower()
        # Break the exc_info→traceback→frame cycle NOW: it captures `bad`
        # in its frame locals, and until the cycle GC runs the actor handle
        # stays alive — holding its dedicated CPU lease and starving
        # whatever test runs next (the round-2 "starvation" flake).
        del exc_info
        assert compiled.execute(5) == 5  # loop survived
    finally:
        compiled.teardown()


def test_compile_rejects_const_only_node(ray_cluster):
    a = Adder.remote(1)
    dag = a.add.bind(41)  # no InputNode anywhere
    with pytest.raises(ValueError, match="upstream"):
        dag.experimental_compile()


def test_compile_rejects_actor_reuse(ray_cluster):
    """Two nodes on one actor would deadlock (each node parks a resident
    loop task; a serialized actor can only run one) — must fail fast."""
    a = Adder.remote(1)
    with InputNode() as inp:
        dag = a.add.bind(a.add.bind(inp))
    with pytest.raises(ValueError, match="one node per actor"):
        dag.experimental_compile()


def test_allreduce_collective_node(ray_cluster):
    """A collective node reduces N actors' outputs inside the compiled
    graph (reference dag/collective_node.py): the hidden reducer actor is
    wired into the channel graph and torn down with the DAG."""
    import numpy as np

    from ray_tpu.dag import collective

    @ray_tpu.remote
    class Shard:
        def __init__(self, scale):
            self.scale = scale

        def grad(self, x):
            return np.asarray(x, dtype=np.float64) * self.scale

    shards = [Shard.remote(s) for s in (1.0, 2.0, 3.0)]
    with InputNode() as inp:
        partials = [s.grad.bind(inp) for s in shards]
        dag = collective.allreduce.bind(partials, op="mean")
    compiled = dag.experimental_compile()
    try:
        out = compiled.execute([1.0, 2.0])
        np.testing.assert_allclose(out, [2.0, 4.0])  # mean of 1x,2x,3x
        out = compiled.execute([3.0, 0.0])
        np.testing.assert_allclose(out, [6.0, 0.0])
    finally:
        compiled.teardown()
