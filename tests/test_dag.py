"""Compiled graphs (aDAG) over mutable shm channels.

Reference surface: ``python/ray/dag/compiled_dag_node.py:795`` +
mutable-object channels. Acceptance: repeated execute() with zero
per-call task submission, fan-out/fan-in, error propagation, teardown
returning the actors to normal use.
"""

import threading

import pytest

import ray_tpu
from ray_tpu.dag import InputNode, MultiOutputNode
from ray_tpu.dag.channel import Channel, ChannelClosed


def test_channel_roundtrip_and_stop(tmp_path):
    path = str(tmp_path / "ch")
    ch = Channel(path, 1024, create=True)
    reader = Channel(path, 1024)
    ch.write(b"one")
    payload, seq = reader.read(0, timeout=5)
    assert payload == b"one"
    ch.write(b"two")
    payload, seq = reader.read(seq, timeout=5)
    assert payload == b"two"
    with pytest.raises(ValueError):
        ch.write(b"x" * 2048)
    ch.close_writer()
    with pytest.raises(ChannelClosed):
        reader.read(seq, timeout=5)
    ch.close()
    reader.close()


def test_channel_concurrent_writer_reader(tmp_path):
    """A spinning reader never observes a torn message (seqlock). The
    channel is latest-value (writers overwrite), so the reader may skip
    versions but must always read internally-consistent payloads."""
    import time

    path = str(tmp_path / "ch2")
    w = Channel(path, 4096, create=True)
    r = Channel(path, 4096)
    n, got = 200, []
    final = (n - 1) % 251
    caught_up = threading.Event()

    def produce():
        for i in range(n):
            w.write(bytes([i % 251]) * (1 + i % 97))
            time.sleep(0.0002)
        caught_up.wait(10)  # don't overwrite the final value with STOP early
        w.close_writer()

    t = threading.Thread(target=produce)
    t.start()
    seq = 0
    try:
        while True:
            payload, seq = r.read(seq, timeout=10)
            assert len(set(payload)) == 1, "torn read"
            got.append(payload[0])
            if payload[0] == final:
                caught_up.set()
    except ChannelClosed:
        pass
    t.join()
    assert got and got[-1] == final
    w.close()
    r.close()


@ray_tpu.remote
class Adder:
    def __init__(self, k):
        self.k = k
        self.calls = 0

    def add(self, x):
        self.calls += 1
        return x + self.k

    def boom(self, x):
        if x == 13:
            raise ValueError("unlucky")
        return x * 2

    def call_count(self):
        return self.calls


def test_linear_pipeline_repeated_execute(ray_cluster):
    a, b = Adder.remote(1), Adder.remote(10)
    with InputNode() as inp:
        dag = b.add.bind(a.add.bind(inp))
    compiled = dag.experimental_compile()
    try:
        for i in range(20):
            assert compiled.execute(i) == i + 11
    finally:
        compiled.teardown()
    # After teardown the actors serve normal calls again, and the loop ran
    # as ONE task: 20 executes never submitted per-call tasks.
    assert ray_tpu.get(a.call_count.remote(), timeout=60) == 20


def test_fan_out_fan_in(ray_cluster):
    a, b, c = Adder.remote(1), Adder.remote(100), Adder.remote(1000)
    with InputNode() as inp:
        mid = a.add.bind(inp)
        dag = MultiOutputNode([b.add.bind(mid), c.add.bind(mid)])
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(5) == (106, 1006)
        assert compiled.execute(7) == (108, 1008)
    finally:
        compiled.teardown()


def test_error_propagates_and_pipeline_survives(ray_cluster):
    a, b = Adder.remote(0), Adder.remote(5)
    with InputNode() as inp:
        dag = b.add.bind(a.boom.bind(inp))
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(2) == 9  # 2*2 + 5
        with pytest.raises(ValueError, match="unlucky"):
            compiled.execute(13)
        assert compiled.execute(3) == 11  # loop survived the error
    finally:
        compiled.teardown()


def test_multi_output_error_does_not_desync_later_rounds(ray_cluster):
    """An error on one output branch must not leave the other branch's
    cursor behind (all outputs drain before the raise)."""
    a, b, c = Adder.remote(0), Adder.remote(0), Adder.remote(100)
    with InputNode() as inp:
        mid = a.add.bind(inp)
        dag = MultiOutputNode([b.boom.bind(mid), c.add.bind(mid)])
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(1) == (2, 101)
        with pytest.raises(ValueError, match="unlucky"):
            compiled.execute(13)
        assert compiled.execute(2) == (4, 102)  # fresh, not round-13 leftovers
    finally:
        compiled.teardown()


def test_unpicklable_result_propagates_as_error(ray_cluster):
    """A result the serializer can't encode must surface as a task error,
    not kill the resident loop and time out the driver."""

    @ray_tpu.remote
    class Bad:
        def make(self, x):
            if x == 1:
                return threading.Lock()  # unpicklable
            return x

    bad = Bad.remote()
    with InputNode() as inp:
        dag = bad.make.bind(inp)
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(0) == 0
        with pytest.raises(Exception) as exc_info:
            compiled.execute(1, timeout=30)
        assert "lock" in str(exc_info.value).lower() or "pickle" in str(exc_info.value).lower()
        # Break the exc_info→traceback→frame cycle NOW: it captures `bad`
        # in its frame locals, and until the cycle GC runs the actor handle
        # stays alive — holding its dedicated CPU lease and starving
        # whatever test runs next (the round-2 "starvation" flake).
        del exc_info
        assert compiled.execute(5) == 5  # loop survived
    finally:
        compiled.teardown()


def test_compile_rejects_const_only_node(ray_cluster):
    a = Adder.remote(1)
    dag = a.add.bind(41)  # no InputNode anywhere
    with pytest.raises(ValueError, match="upstream"):
        dag.experimental_compile()


def test_compile_rejects_actor_reuse(ray_cluster):
    """Two nodes on one actor would deadlock (each node parks a resident
    loop task; a serialized actor can only run one) — must fail fast."""
    a = Adder.remote(1)
    with InputNode() as inp:
        dag = a.add.bind(a.add.bind(inp))
    with pytest.raises(ValueError, match="one node per actor"):
        dag.experimental_compile()


def test_execute_timeout_tears_down_instead_of_wedging(ray_cluster):
    """Satellite regression (round 8): a timed-out execute() used to
    leave the parked executor blocked mid-round — the next execute()
    would consume the LATE result of the timed-out round (silent desync)
    or hang. Now a timeout poisons the DAG: it tears down and every
    later execute() raises ChannelClosed promptly — never hangs, never
    returns a stale round."""
    import time

    @ray_tpu.remote
    class Sleeper:
        def work(self, x):
            if x == "slow":
                time.sleep(5.0)
            return x

    s = Sleeper.remote()
    with InputNode() as inp:
        dag = s.work.bind(inp)
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute("fast") == "fast"
        with pytest.raises(TimeoutError):
            compiled.execute("slow", timeout=0.5)
        t0 = time.monotonic()
        with pytest.raises(ChannelClosed):
            compiled.execute("after", timeout=10.0)
        assert time.monotonic() - t0 < 5.0, "post-timeout execute hung"
    finally:
        compiled.teardown()


# ------------------------------------------------------------ compiled loops


def test_ring_channel_streaming_and_credits(tmp_path):
    """RingChannel delivers EVERY message exactly once per reader (not
    latest-wins) and blocks the writer once n_slots ahead of the slowest
    reader — the credit-based backpressure compiled loops ride."""
    path = str(tmp_path / "ring")
    from ray_tpu.dag import RingChannel

    w = RingChannel(path, 256, n_slots=4, n_readers=2, create=True)
    r0 = RingChannel(path, 256, n_slots=4, reader_index=0)
    r1 = RingChannel(path, 256, n_slots=4, reader_index=1)
    for i in range(4):
        w.write(bytes([i]))
    assert w.occupancy() == 4
    with pytest.raises(TimeoutError):
        w.write(b"x", timeout=0.2)  # ring full: no credit
    assert [r0.read(timeout=5)[0] for _ in range(4)] == [0, 1, 2, 3]
    with pytest.raises(TimeoutError):
        w.write(b"x", timeout=0.2)  # r1 is the slowest reader: still full
    assert [r1.read(timeout=5)[0] for _ in range(3)] == [0, 1, 2]
    w.write(b"\xff")  # credit released -> write succeeds
    w.close_writer()
    assert r1.read(timeout=5)[0] == 3  # close-after-drain: queue first
    assert r0.read(timeout=5) == r1.read(timeout=5) == b"\xff"
    with pytest.raises(ChannelClosed):
        r1.read(timeout=5)  # then STOP
    with pytest.raises(ChannelClosed):
        r1.read(timeout=5)  # STOP is sticky
    for ch in (w, r0, r1):
        ch.close()


def test_compiled_loop_streams_iterations(ray_cluster):
    """compile_loop: one owner-side submit per stage starts resident tick
    executors; put()/get() then stream iterations with ZERO per-tick task
    submission, in order, surviving per-iteration stage errors. Every
    tick counts in ray_tpu_dag_loop_ticks_total and a dag.loop.tick span
    is sampled every dag_loop_span_every ticks."""
    import time

    from ray_tpu.core.config import get_config
    from ray_tpu.dag import compile_loop

    cfg = get_config()
    saved = cfg.dag_loop_span_every
    cfg.dag_loop_span_every = 2  # shipped to the stage executors at compile
    try:
        a, b = Adder.remote(1), Adder.remote(10)
        with InputNode() as inp:
            dag = b.add.bind(a.add.bind(inp))
        loop = compile_loop(dag)
        try:
            for i in range(5):  # pipelined: puts ahead of gets
                loop.put(i)
            assert [loop.get() for _ in range(5)] == [11, 12, 13, 14, 15]
            assert loop.run(100) == 111
        finally:
            loop.teardown()
    finally:
        cfg.dag_loop_span_every = saved
    # the loop ran as ONE task per stage: 6 iterations, zero per-tick
    # submissions — the actor served every tick inside its parked loop
    assert ray_tpu.get(a.call_count.remote(), timeout=60) == 6
    # observability: tick counter + sampled spans reach the GCS (the
    # stage workers' metric/span flushers run on ~5s cadences)
    from ray_tpu.util import state
    from ray_tpu.util.metrics import get_metrics

    deadline = time.monotonic() + 20.0
    ticks, spans = 0, []
    while time.monotonic() < deadline and (ticks < 12 or not spans):
        ticks = sum(m["value"] for m in get_metrics()
                    if m["name"] == "ray_tpu_dag_loop_ticks_total")
        spans = [s for s in state.list_spans(limit=5000)
                 if s.get("name") == "dag.loop.tick"]
        time.sleep(0.5)
    assert ticks >= 12, ticks  # 6 iterations x 2 stages
    assert spans and spans[0]["attrs"].get("stage") in ("add",)


def test_compiled_loop_error_and_fan_out_ordering(ray_cluster):
    """A stage error surfaces on ITS iteration's get() and the loop keeps
    streaming; fan-out outputs stay cursor-aligned across the error."""
    from ray_tpu.dag import compile_loop

    a, b, c = Adder.remote(0), Adder.remote(5), Adder.remote(100)
    with InputNode() as inp:
        mid = a.boom.bind(inp)
        dag = MultiOutputNode([b.add.bind(mid), c.add.bind(mid)])
    loop = compile_loop(dag, credits=3)
    try:
        assert loop.run(2) == (9, 104)
        loop.put(13)
        loop.put(3)
        with pytest.raises(ValueError, match="unlucky"):
            loop.get()
        assert loop.get() == (11, 106)  # round after the error, aligned
    finally:
        loop.teardown()


def test_compiled_loop_backpressure_bounds_in_flight(ray_cluster):
    """With nobody consuming outputs, put() must stop accepting after a
    bounded number of iterations (credits per hop) instead of queueing
    unboundedly — the credit protocol IS the backpressure."""
    from ray_tpu.dag import compile_loop

    a, b = Adder.remote(1), Adder.remote(1)
    with InputNode() as inp:
        dag = b.add.bind(a.add.bind(inp))
    loop = compile_loop(dag, credits=2)
    try:
        accepted = 0
        with pytest.raises(TimeoutError):
            for _ in range(50):
                loop.put(0, timeout=1.0)
                accepted += 1
        # capacity = credits per channel hop (+ one in flight per stage):
        # 3 channels x 2 credits + 2 stages = 8, far below 50
        assert 2 <= accepted <= 10, accepted
        for _ in range(accepted):
            assert loop.get() == 2
    finally:
        loop.teardown()


def test_compiled_loop_pins_and_unpins_stage_workers(ray_cluster):
    """Loop stages park never-returning executors on their workers: the
    raylet must know (loop_pinned) so the orphan-lease watchdog never
    reclaims them as stranded grants; teardown unpins."""
    from ray_tpu.core import api as core_api
    from ray_tpu.dag import compile_loop

    raylet = core_api._node.raylet
    base = sum(1 for w in raylet._workers.values() if w.loop_pinned)
    a, b = Adder.remote(1), Adder.remote(2)
    with InputNode() as inp:
        dag = b.add.bind(a.add.bind(inp))
    loop = compile_loop(dag)
    try:
        assert loop.run(0) == 3
        pinned = [w for w in raylet._workers.values() if w.loop_pinned]
        assert len(pinned) - base == 2
        # the orphan scan must skip pinned workers even when un-acked and
        # unprobeable (the chaos scenario that motivated pinning)
        victim = pinned[0]
        victim.lease_acked = False
        victim.lease_granted_at = 1.0  # ancient
        saved_addr, victim.address = victim.address, ""  # probe impossible
        orphans_before = raylet._orphan_leases_total
        try:
            from ray_tpu.core.config import get_config

            node = core_api._node
            node.services_loop.run_sync(
                raylet._scan_orphan_leases(get_config()), timeout=30)
            assert victim.state != "dead"
            assert raylet._orphan_leases_total == orphans_before
        finally:
            victim.address = saved_addr
            victim.lease_acked = True
    finally:
        loop.teardown()
    assert sum(1 for w in raylet._workers.values()
               if w.loop_pinned) == base
    assert loop.torn_down_in_s < 30.0


def test_compiled_loop_stage_death_cascades_teardown(ray_cluster):
    """Killing a stage actor mid-loop must surface on the driver promptly
    and teardown must unwedge the surviving stages (force-closed rings),
    returning their actors... to the dead pool with the loop — never a
    hang."""
    import time

    from ray_tpu.dag import compile_loop

    a, b = Adder.remote(1), Adder.remote(10)
    with InputNode() as inp:
        dag = b.add.bind(a.add.bind(inp))
    loop = compile_loop(dag, credits=2)
    try:
        assert loop.run(1) == 12
        ray_tpu.kill(a)  # SIGKILL lands via GCS->raylet, asynchronously
        t0 = time.monotonic()
        with pytest.raises(Exception):
            # the death may surface as the actor error or as the broken
            # pipeline — either way bounded, never a hang
            while time.monotonic() - t0 < 60.0:
                loop.put(3, timeout=10.0)
                loop.get(timeout=10.0)
                time.sleep(0.05)
            raise AssertionError("stage death never surfaced")
        assert time.monotonic() - t0 < 60.0
    finally:
        loop.teardown()
    assert loop.torn_down_in_s < 30.0


def test_run_dag_bench_tick_phase(ray_cluster):
    """The dag bench's tick-overhead phase (cli `bench dag`) produces the
    guarded metrics with sane values inside an existing cluster."""
    from ray_tpu._dag_bench import _bench_tick_overhead

    out = {}
    _bench_tick_overhead(out, 10)
    assert out["dag_tick_dispatch_overhead_us"] > 0
    assert out["dag_tick_dispatch_overhead_dynamic_us"] > 0
    assert out["dag_loop_ticks_per_s"] > 0
    assert out["dag_bench_ticks_cfg"] == 10


def test_allreduce_collective_node(ray_cluster):
    """A collective node reduces N actors' outputs inside the compiled
    graph (reference dag/collective_node.py): the hidden reducer actor is
    wired into the channel graph and torn down with the DAG."""
    import numpy as np

    from ray_tpu.dag import collective

    @ray_tpu.remote
    class Shard:
        def __init__(self, scale):
            self.scale = scale

        def grad(self, x):
            return np.asarray(x, dtype=np.float64) * self.scale

    shards = [Shard.remote(s) for s in (1.0, 2.0, 3.0)]
    with InputNode() as inp:
        partials = [s.grad.bind(inp) for s in shards]
        dag = collective.allreduce.bind(partials, op="mean")
    compiled = dag.experimental_compile()
    try:
        out = compiled.execute([1.0, 2.0])
        np.testing.assert_allclose(out, [2.0, 4.0])  # mean of 1x,2x,3x
        out = compiled.execute([3.0, 0.0])
        np.testing.assert_allclose(out, [6.0, 0.0])
    finally:
        compiled.teardown()


def test_compiled_loop_stall_attribution_and_loop_top(ray_cluster, capsys):
    """ISSUE 18 tentpole: every resident stage records per-tick
    wait_up/compute/wait_down splits into its in-process ring and
    flushes a node-local snapshot on the ``dag_loop_span_every``
    cadence. ``stats()`` aggregates them with ZERO actor RPC (a resident
    stage's actor is parked in ``_loop_tick`` and could never answer
    one), names the bottleneck stage, and survives teardown via
    ``final_stats``; ``cli loop top --once`` renders the same rows."""
    from ray_tpu.cli import main
    from ray_tpu.core.config import get_config
    from ray_tpu.dag import compile_loop
    from ray_tpu.dag.loop import live_loop_stats

    cfg = get_config()
    saved = cfg.dag_loop_span_every
    cfg.dag_loop_span_every = 4  # stall snapshots flush every 4 ticks
    try:
        a, b = Adder.remote(1), Adder.remote(10)
        with InputNode() as inp:
            dag = b.add.bind(a.add.bind(inp))
        loop = compile_loop(dag)
        try:
            for i in range(12):
                assert loop.run(i) == i + 11
            # run() round-trips, so the tick-12 flush has already landed
            # in the node-local snapshot files — no GCS fallback needed.
            stats = loop.stats(fallback_gcs=False)
            assert stats["recording"] and len(stats["stages"]) == 2
            for snap in stats["stages"].values():
                # the first span-cadence flush always writes the file;
                # later writes are time-gated (teardown forces the last)
                assert snap["ticks"] >= 4
                assert abs(sum(snap["frac"].values()) - 1.0) < 0.02
                assert snap["state"] in ("compute_bound", "starved",
                                         "backpressured")
            assert stats["bottleneck"] in stats["stages"]
            assert stats["puts"] == stats["gets"] == 12
            # the driver-local registry backs state.loop_stats() (and
            # through it `cli loop top` + the dashboard /api/loops)
            assert any(row["loop_id"] == loop.loop_id
                       for row in live_loop_stats())
            capsys.readouterr()
            assert main(["loop", "top", "--once"]) == 0
            out = capsys.readouterr().out
            assert loop.loop_id[:12] in out and "bottleneck" in out
        finally:
            loop.teardown()
        # teardown drained a final flush and snapshotted the aggregates
        # before deleting the channel dir
        final = loop.final_stats
        assert final is not None and final["bottleneck"] in final["stages"]
        assert all(s["ticks"] >= 12 for s in final["stages"].values())
        assert not any(row["loop_id"] == loop.loop_id
                       for row in live_loop_stats())
        capsys.readouterr()
        assert main(["loop", "top", "--once"]) == 0  # empty table is fine
    finally:
        cfg.dag_loop_span_every = saved


def test_compiled_loop_stall_recording_disabled(ray_cluster):
    """``dag_loop_stall_recording=False`` (the bench's baseline mode)
    compiles a loop whose ticks skip the recorder entirely — stats()
    still answers, with empty stages and ``recording: False``."""
    from ray_tpu.core.config import get_config
    from ray_tpu.dag import compile_loop

    cfg = get_config()
    saved = cfg.dag_loop_stall_recording
    cfg.dag_loop_stall_recording = False
    try:
        a, b = Adder.remote(1), Adder.remote(10)
        with InputNode() as inp:
            dag = b.add.bind(a.add.bind(inp))
        loop = compile_loop(dag)
        try:
            for i in range(6):
                assert loop.run(i) == i + 11
            stats = loop.stats(fallback_gcs=False)
            assert stats["recording"] is False
            assert all(s["ticks"] == 0 for s in stats["stages"].values())
        finally:
            loop.teardown()
    finally:
        cfg.dag_loop_stall_recording = saved
