"""Runtime-env dependency plugins: py_modules + pip with URI caching
(reference ``python/ray/_private/runtime_env/{py_modules.py,pip.py,
uri_cache.py}``)."""

import glob
import os
import tempfile
import textwrap

import pytest

import ray_tpu


@pytest.fixture(autouse=True)
def _cluster(ray_cluster):
    yield


def _make_py_module(tmp_path, name: str, body: str) -> str:
    pkg = os.path.join(str(tmp_path), name)
    os.makedirs(pkg, exist_ok=True)
    with open(os.path.join(pkg, "__init__.py"), "w") as f:
        f.write(body)
    return pkg


def test_py_modules_staged_on_worker_path(tmp_path):
    pkg = _make_py_module(tmp_path, "renv_mod_a", "MAGIC = 41\n")

    @ray_tpu.remote
    def use_module():
        import renv_mod_a

        return renv_mod_a.MAGIC + 1

    assert ray_tpu.get(
        use_module.options(runtime_env={"py_modules": [pkg]}).remote(),
        timeout=120) == 42


def test_py_modules_content_hash_invalidates(tmp_path):
    """Editing the module produces a fresh URI: workers see the new code,
    not a stale cache entry."""
    pkg = _make_py_module(tmp_path, "renv_mod_b", "VALUE = 1\n")

    @ray_tpu.remote
    def read_value():
        import renv_mod_b

        return renv_mod_b.VALUE

    assert ray_tpu.get(
        read_value.options(runtime_env={"py_modules": [pkg]}).remote(),
        timeout=120) == 1
    with open(os.path.join(pkg, "__init__.py"), "w") as f:
        f.write("VALUE = 2\n")
    assert ray_tpu.get(
        read_value.options(runtime_env={"py_modules": [pkg]}).remote(),
        timeout=120) == 2


def test_pip_local_package_installed_once(tmp_path):
    """pip requirements install into a cached --target dir exactly once;
    a second task with the same spec reuses the URI (reference
    uri_cache.py create-once semantics)."""
    pip_pkg = str(tmp_path / "pipsrc")
    os.makedirs(os.path.join(pip_pkg, "renv_pipmod"))
    with open(os.path.join(pip_pkg, "renv_pipmod", "__init__.py"), "w") as f:
        f.write("VALUE = 'installed'\n")
    with open(os.path.join(pip_pkg, "pyproject.toml"), "w") as f:
        f.write(textwrap.dedent("""
            [build-system]
            requires = ["setuptools"]
            build-backend = "setuptools.build_meta"
            [project]
            name = "renv-pipmod"
            version = "0.1"
            [tool.setuptools]
            packages = ["renv_pipmod"]
        """))

    @ray_tpu.remote
    def use_pip():
        import renv_pipmod

        return renv_pipmod.VALUE

    renv = {"pip": [pip_pkg]}
    assert ray_tpu.get(use_pip.options(runtime_env=renv).remote(), timeout=300) == "installed"
    before = set(glob.glob("/tmp/ray_tpu/runtime_env/pip/*"))
    assert ray_tpu.get(use_pip.options(runtime_env=renv).remote(), timeout=300) == "installed"
    after = set(glob.glob("/tmp/ray_tpu/runtime_env/pip/*"))
    assert before == after  # cached URI reused, no reinstall


def test_mismatched_envs_never_share_a_worker(tmp_path):
    """Two tasks with identical resources but different py_modules must
    run on different workers (the lease pipeline keys on the FULL runtime
    env; a reused lease would import the wrong world)."""
    pkg_a = _make_py_module(tmp_path, "renv_only_a", "X = 'a'\n")

    @ray_tpu.remote
    def has_module(name):
        import importlib

        try:
            importlib.import_module(name)
            return True
        except ImportError:
            return False

    assert ray_tpu.get(
        has_module.options(runtime_env={"py_modules": [pkg_a]}).remote("renv_only_a"),
        timeout=120) is True
    # plain-env task right after: must NOT land on the py_modules worker
    assert ray_tpu.get(has_module.remote("renv_only_a"), timeout=120) is False


def test_py_executable_plugin(ray_cluster):
    """runtime_env py_executable picks the worker's interpreter
    (reference runtime_env/py_executable.py) — here the same python via
    its real path, proving the plumb reaches the spawn."""
    import sys

    import ray_tpu

    @ray_tpu.remote(runtime_env={"py_executable": sys.executable})
    def which_python():
        import sys as s

        return s.executable

    out = ray_tpu.get(which_python.remote(), timeout=120)
    assert out == sys.executable


def test_conda_and_container_gated_errors(ray_cluster):
    """conda/container plugins fail the LEASE with a clear setup error
    when the node lacks the tooling (this image has neither), instead of
    crash-looping a worker (reference runtime_env setup-error surface)."""
    import pytest as _pytest

    import ray_tpu
    from ray_tpu.core.runtime_env import (
        RuntimeEnvSetupError, resolve_python_executable, wrap_worker_command)

    import shutil

    if shutil.which("conda") or shutil.which("micromamba"):
        _pytest.skip("conda present on this host")
    with _pytest.raises(RuntimeEnvSetupError, match="conda"):
        resolve_python_executable({"conda": "myenv"})
    if shutil.which("docker") or shutil.which("podman"):
        _pytest.skip("container runtime present on this host")
    with _pytest.raises(RuntimeEnvSetupError, match="podman or docker"):
        wrap_worker_command(["python"], {"image_uri": "img:latest"})

    @ray_tpu.remote(runtime_env={"conda": "myenv"})
    def f():
        return 1

    with _pytest.raises(Exception, match="conda"):
        ray_tpu.get(f.remote(), timeout=120)


def test_conda_plugin_resolves_existing_env(tmp_path, monkeypatch):
    """With a (stubbed) conda on PATH, a string spec resolves to the
    named env's interpreter."""
    import stat
    import sys

    from ray_tpu.core.runtime_env import resolve_python_executable

    base = tmp_path / "conda_base"
    envpy = base / "envs" / "myenv" / "bin"
    envpy.mkdir(parents=True)
    (envpy / "python").write_text("#!/bin/sh\n")
    stub = tmp_path / "bin" / "conda"
    stub.parent.mkdir()
    stub.write_text(f"#!/bin/sh\necho {base}\n")
    stub.chmod(stub.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("PATH", f"{stub.parent}:{os.environ['PATH']}")
    monkeypatch.delenv("CONDA_EXE", raising=False)
    py = resolve_python_executable({"conda": "myenv"})
    assert py == str(envpy / "python")
