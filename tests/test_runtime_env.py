"""Runtime-env dependency plugins: py_modules + pip with URI caching
(reference ``python/ray/_private/runtime_env/{py_modules.py,pip.py,
uri_cache.py}``)."""

import glob
import os
import tempfile
import textwrap

import pytest

import ray_tpu


@pytest.fixture(autouse=True)
def _cluster(ray_cluster):
    yield


def _make_py_module(tmp_path, name: str, body: str) -> str:
    pkg = os.path.join(str(tmp_path), name)
    os.makedirs(pkg, exist_ok=True)
    with open(os.path.join(pkg, "__init__.py"), "w") as f:
        f.write(body)
    return pkg


def test_py_modules_staged_on_worker_path(tmp_path):
    pkg = _make_py_module(tmp_path, "renv_mod_a", "MAGIC = 41\n")

    @ray_tpu.remote
    def use_module():
        import renv_mod_a

        return renv_mod_a.MAGIC + 1

    assert ray_tpu.get(
        use_module.options(runtime_env={"py_modules": [pkg]}).remote(),
        timeout=120) == 42


def test_py_modules_content_hash_invalidates(tmp_path):
    """Editing the module produces a fresh URI: workers see the new code,
    not a stale cache entry."""
    pkg = _make_py_module(tmp_path, "renv_mod_b", "VALUE = 1\n")

    @ray_tpu.remote
    def read_value():
        import renv_mod_b

        return renv_mod_b.VALUE

    assert ray_tpu.get(
        read_value.options(runtime_env={"py_modules": [pkg]}).remote(),
        timeout=120) == 1
    with open(os.path.join(pkg, "__init__.py"), "w") as f:
        f.write("VALUE = 2\n")
    assert ray_tpu.get(
        read_value.options(runtime_env={"py_modules": [pkg]}).remote(),
        timeout=120) == 2


def test_pip_local_package_installed_once(tmp_path):
    """pip requirements install into a cached --target dir exactly once;
    a second task with the same spec reuses the URI (reference
    uri_cache.py create-once semantics)."""
    pip_pkg = str(tmp_path / "pipsrc")
    os.makedirs(os.path.join(pip_pkg, "renv_pipmod"))
    with open(os.path.join(pip_pkg, "renv_pipmod", "__init__.py"), "w") as f:
        f.write("VALUE = 'installed'\n")
    with open(os.path.join(pip_pkg, "pyproject.toml"), "w") as f:
        f.write(textwrap.dedent("""
            [build-system]
            requires = ["setuptools"]
            build-backend = "setuptools.build_meta"
            [project]
            name = "renv-pipmod"
            version = "0.1"
            [tool.setuptools]
            packages = ["renv_pipmod"]
        """))

    @ray_tpu.remote
    def use_pip():
        import renv_pipmod

        return renv_pipmod.VALUE

    renv = {"pip": [pip_pkg]}
    assert ray_tpu.get(use_pip.options(runtime_env=renv).remote(), timeout=300) == "installed"
    before = set(glob.glob("/tmp/ray_tpu/runtime_env/pip/*"))
    assert ray_tpu.get(use_pip.options(runtime_env=renv).remote(), timeout=300) == "installed"
    after = set(glob.glob("/tmp/ray_tpu/runtime_env/pip/*"))
    assert before == after  # cached URI reused, no reinstall


def test_mismatched_envs_never_share_a_worker(tmp_path):
    """Two tasks with identical resources but different py_modules must
    run on different workers (the lease pipeline keys on the FULL runtime
    env; a reused lease would import the wrong world)."""
    pkg_a = _make_py_module(tmp_path, "renv_only_a", "X = 'a'\n")

    @ray_tpu.remote
    def has_module(name):
        import importlib

        try:
            importlib.import_module(name)
            return True
        except ImportError:
            return False

    assert ray_tpu.get(
        has_module.options(runtime_env={"py_modules": [pkg_a]}).remote("renv_only_a"),
        timeout=120) is True
    # plain-env task right after: must NOT land on the py_modules worker
    assert ray_tpu.get(has_module.remote("renv_only_a"), timeout=120) is False
