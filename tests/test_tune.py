"""Tune tests: search spaces, Tuner end-to-end, ASHA early stopping, PBT
exploit (reference patterns: python/ray/tune/tests/)."""

import os

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.train import RunConfig


def test_basic_variant_grid_and_sampling():
    gen = tune.BasicVariantGenerator(seed=0)
    cfgs = gen.generate(
        {"lr": tune.grid_search([0.1, 0.01]), "b": tune.choice([1, 2]), "c": 7},
        num_samples=3,
    )
    assert len(cfgs) == 6  # 3 samples x 2 grid values
    assert all(c["c"] == 7 for c in cfgs)
    assert {c["lr"] for c in cfgs} == {0.1, 0.01}
    assert all(c["b"] in (1, 2) for c in cfgs)


def test_tuner_finds_best(ray_cluster, tmp_path):
    def trainable(config):
        # quadratic bowl: best at x=3
        score = -((config["x"] - 3.0) ** 2)
        tune.report({"score": score, "x": config["x"]})

    tuner = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([0.0, 1.0, 3.0, 5.0])},
        tune_config=tune.TuneConfig(metric="score", mode="max", max_concurrent_trials=2),
        run_config=RunConfig(name="grid", storage_path=str(tmp_path)),
    )
    results = tuner.fit()
    assert len(results) == 4
    best = results.get_best_result(metric="score", mode="max")
    assert best.metrics["x"] == 3.0


def test_asha_stops_bad_trials(ray_cluster, tmp_path):
    def trainable(config):
        import time

        for it in range(1, 9):
            tune.report({"training_iteration": it, "acc": config["quality"] * it})
            time.sleep(0.25)  # let the controller poll between iterations

    sched = tune.ASHAScheduler(metric="acc", max_t=8, grace_period=2, reduction_factor=2)
    tuner = tune.Tuner(
        trainable,
        param_space={"quality": tune.grid_search([1.0, 0.9, 0.2, 0.1])},
        tune_config=tune.TuneConfig(scheduler=sched, max_concurrent_trials=4),
        run_config=RunConfig(name="asha", storage_path=str(tmp_path)),
    )
    results = tuner.fit()
    # good trials ran to completion
    best = results.get_best_result(metric="acc", mode="max")
    assert best.metrics["training_iteration"] == 8
    # at least one poor trial was cut early
    iters = [r.metrics.get("training_iteration", 0) for r in results]
    assert min(iters) < 8


def test_tuner_trial_error_isolated(ray_cluster, tmp_path):
    def trainable(config):
        if config["x"] == 1:
            raise ValueError("bad trial")
        tune.report({"ok": config["x"]})

    tuner = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([0, 1, 2])},
        run_config=RunConfig(name="err", storage_path=str(tmp_path)),
    )
    results = tuner.fit()
    assert len(results.errors) == 1
    oks = sorted(r.metrics["ok"] for r in results if r.metrics)
    assert oks == [0, 2]


def test_pbt_exploit_logic():
    from ray_tpu.tune.schedulers import PopulationBasedTraining

    class T:
        _n = 0

        def __init__(self, cfg):
            T._n += 1
            self.trial_id = f"t{T._n}"
            self.config = cfg

    pbt = PopulationBasedTraining(
        metric="score", perturbation_interval=2,
        hyperparam_mutations={"lr": [0.1, 0.01]}, seed=0,
    )
    good, bad = T({"lr": 0.1}), T({"lr": 0.5})
    pbt.on_result(good, {"training_iteration": 2, "score": 10.0})
    pbt.on_result(bad, {"training_iteration": 2, "score": 1.0})
    # bad trial at the perturbation interval exploits the good trial
    new_cfg = pbt.maybe_exploit(bad, {"training_iteration": 2, "score": 1.0}, [good, bad])
    assert new_cfg is not None
    assert new_cfg["_pbt_exploit_from"] == good.trial_id
    assert new_cfg["lr"] in (0.1, 0.01)
    # good trial does not exploit
    assert pbt.maybe_exploit(good, {"training_iteration": 2, "score": 10.0}, [good, bad]) is None


def test_tuner_restore_reruns_only_incomplete(ray_cluster, tmp_path):
    """Tuner.restore: finished trials keep their results without
    re-running; the failed trial retries (reference Tuner.restore)."""
    from ray_tpu import train
    from ray_tpu.tune import TuneConfig, Tuner
    from ray_tpu.train.config import RunConfig

    marker_dir = str(tmp_path / "markers")
    os.makedirs(marker_dir, exist_ok=True)

    def trainable(config):
        import os as _os

        mark = _os.path.join(config["marker_dir"], f"ran-{config['x']}")
        with open(mark, "a") as f:
            f.write("x")
        if config["x"] == 2 and not _os.path.exists(
            _os.path.join(config["marker_dir"], "fixed")
        ):
            raise RuntimeError("flaky trial")
        train.report({"score": float(config["x"] * 10)})

    exp_name = "restore_exp"
    tuner = tune.Tuner(
        trainable,
        param_space={"x": {"grid_search": [1, 2, 3]}, "marker_dir": marker_dir},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(name=exp_name, storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert len(grid.errors) == 1  # trial x=2 failed

    open(os.path.join(marker_dir, "fixed"), "w").close()
    restored = Tuner.restore(str(tmp_path / exp_name), trainable)
    grid2 = restored.fit()
    assert not grid2.errors
    assert grid2.get_best_result("score").metrics["score"] == 30.0
    # completed trials ran exactly once; the flaky one ran twice
    assert os.path.getsize(os.path.join(marker_dir, "ran-1")) == 1
    assert os.path.getsize(os.path.join(marker_dir, "ran-3")) == 1
    assert os.path.getsize(os.path.join(marker_dir, "ran-2")) == 2


def test_tpe_searcher_converges_on_quadratic(ray_cluster, tmp_path):
    """Sequential TPE search concentrates samples near the optimum of a
    known objective — later suggestions beat random's expected quality
    (reference OptunaSearch role, optuna_search.py:81)."""
    from ray_tpu.tune import TPESearcher

    def objective(config):
        x = config["x"]
        tune.report({"score": -((x - 3.0) ** 2)})

    tuner = tune.Tuner(
        objective,
        param_space={"x": tune.uniform(-10.0, 10.0)},
        tune_config=tune.TuneConfig(metric="score", mode="max", num_samples=28,
                               max_concurrent_trials=2,
                               search_alg=TPESearcher("score", "max", seed=0)),
        run_config=RunConfig(name="tpe", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    best = grid.get_best_result(metric="score", mode="max")
    # found a decent optimum (random-only over 28 draws on [-10,10] has
    # ~25% chance of doing this poorly; guided search concentrates)
    assert best.metrics["score"] > -6.0, best.metrics
    obs = [s for _, s in tuner._tune_config.search_alg._observations]
    # guided phase concentrates: mean of later observations beats the
    # random-startup mean (the estimator is actually steering)
    import statistics
    assert statistics.mean(obs[-10:]) > statistics.mean(obs[:6]), obs


def test_hyperband_multi_bracket_stops_bad_trials(ray_cluster, tmp_path):
    from ray_tpu.tune import HyperBandScheduler

    def trainable(config):
        for step in range(1, 10):
            tune.report({"training_iteration": step, "acc": config["q"] * step})

    tuner = tune.Tuner(
        trainable,
        param_space={"q": tune.grid_search([0.1, 0.2, 0.9, 1.0, 0.15, 0.85])},
        tune_config=tune.TuneConfig(metric="acc", mode="max", num_samples=1,
                               max_concurrent_trials=3,
                               scheduler=HyperBandScheduler(metric="acc", mode="max",
                                                            max_t=9,
                                                            reduction_factor=3)),
        run_config=RunConfig(name="hb", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    best = grid.get_best_result(metric="acc", mode="max")
    assert best.metrics["acc"] >= 8.0  # a good trial ran to completion


def test_median_stopping_rule(ray_cluster, tmp_path):
    from ray_tpu.tune import MedianStoppingRule

    def trainable(config):
        for step in range(1, 12):
            tune.report({"training_iteration": step, "acc": config["q"] * step})

    tuner = tune.Tuner(
        trainable,
        param_space={"q": tune.grid_search([0.1, 1.0, 0.9, 0.95, 0.05])},
        tune_config=tune.TuneConfig(metric="acc", mode="max", num_samples=1,
                               max_concurrent_trials=4,
                               scheduler=MedianStoppingRule(metric="acc", mode="max",
                                                            grace_period=3)),
        run_config=RunConfig(name="med", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    # bad trials (q=0.1, 0.05) stop early: fewer than 11 iterations
    histories = [len(r.metrics_history) for r in grid._results]
    assert min(histories) < 11, histories
    best = grid.get_best_result(metric="acc", mode="max")
    assert best.metrics["acc"] >= 9.0


def test_callbacks_and_file_loggers(ray_cluster, tmp_path):
    """Callback lifecycle hooks fire in order and the bundled loggers
    write result.json / progress.csv / TB event files per trial
    (reference tune/callback.py + tune/logger/)."""
    import csv
    import glob
    import json
    import os

    from ray_tpu import train, tune
    from ray_tpu.tune import (CSVLoggerCallback, Callback,
                              JsonLoggerCallback, TBXLoggerCallback)

    events = []

    class Recorder(Callback):
        def setup(self, **info):
            events.append(("setup", info.get("experiment_dir")))

        def on_trial_start(self, trial):
            events.append(("start", trial.trial_id))

        def on_trial_result(self, trial, result):
            events.append(("result", trial.trial_id, result["score"]))

        def on_trial_complete(self, trial):
            events.append(("complete", trial.trial_id))

        def on_experiment_end(self, trials):
            events.append(("end", len(trials)))

    def trainable(config):
        for i in range(3):
            tune.report({"score": config["x"] * (i + 1),
                         "training_iteration": i + 1})

    tuner = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([1.0, 2.0])},
        tune_config=tune.TuneConfig(metric="score", mode="max"),
        run_config=train.RunConfig(
            name="cbtest", storage_path=str(tmp_path),
            callbacks=[Recorder(), JsonLoggerCallback(),
                       CSVLoggerCallback(), TBXLoggerCallback()]),
    )
    results = tuner.fit()
    assert len(results) == 2 and not results.errors

    kinds = [e[0] for e in events]
    assert kinds[0] == "setup" and kinds[-1] == "end"
    assert kinds.count("start") == 2 and kinds.count("complete") == 2
    assert kinds.count("result") == 6  # 2 trials x 3 reports

    trial_dirs = sorted(glob.glob(str(tmp_path / "cbtest" / "trial_*")))
    assert len(trial_dirs) == 2
    for d in trial_dirs:
        lines = [json.loads(l) for l in open(os.path.join(d, "result.json"))]
        assert len(lines) == 3 and "score" in lines[0]
        with open(os.path.join(d, "progress.csv")) as f:
            rows = list(csv.DictReader(f))
        assert len(rows) == 3 and float(rows[-1]["score"]) > 0
        assert glob.glob(os.path.join(d, "events.out.tfevents.*"))


def test_searcher_protocol_external_adapter(ray_cluster):
    """Any object with the three-method Searcher surface plugs into the
    Tuner (the adapter seam OptunaSearch uses; reference
    tune/search/searcher.py)."""
    from ray_tpu import tune
    from ray_tpu.tune.search import Searcher

    class CountingSearcher(Searcher):
        def __init__(self):
            self.completed = []
            self._i = 0

        def set_space(self, space):
            self.space = space

        def suggest(self):
            self._i += 1
            return {"x": float(self._i)}

        def on_trial_complete(self, config, metrics):
            self.completed.append((config["x"], metrics["score"]))

    searcher = CountingSearcher()

    def objective(config):
        tune.report({"score": -(config["x"] - 3.0) ** 2})

    tuner = tune.Tuner(
        objective,
        param_space={"x": tune.uniform(0, 6)},
        tune_config=tune.TuneConfig(metric="score", mode="max",
                                    num_samples=5, search_alg=searcher),
    )
    results = tuner.fit()
    assert len(searcher.completed) == 5
    best = results.get_best_result(metric="score", mode="max")
    assert best.metrics["score"] == 0.0  # suggestion x=3 is optimal
    assert any(x == 3.0 and s == 0.0 for x, s in searcher.completed)


def test_optuna_search_gated_import():
    from ray_tpu.tune import OptunaSearch

    try:
        import optuna  # noqa: F401
        has_optuna = True
    except ImportError:
        has_optuna = False
    if has_optuna:
        s = OptunaSearch("score", "max", seed=0)
        s.set_space({"x": __import__("ray_tpu.tune", fromlist=["uniform"]).uniform(0, 1)})
        cfg = s.suggest()
        assert 0 <= cfg["x"] <= 1
    else:
        import pytest as _pytest

        with _pytest.raises(ImportError, match="optuna"):
            OptunaSearch("score", "max")


def test_pb2_converges_faster_than_random_perturbation(ray_cluster):
    """PB2's GP-UCB explore should find the lr optimum of a quadratic
    bandit at least as well as a fixed-seed PBT random perturbation
    (reference tune/schedulers/pb2.py convergence claim, scaled down)."""
    import numpy as np

    from ray_tpu import tune
    from ray_tpu.tune import PB2, PopulationBasedTraining

    def trainable(config):
        # iterative objective: reward peaks at lr = 0.3
        from ray_tpu import tune as t

        lr = config["lr"]
        for i in range(6):
            reward = 10 - 40 * (lr - 0.3) ** 2 + 0.01 * i
            t.report({"reward": reward, "training_iteration": i + 1})

    def run(scheduler):
        tuner = tune.Tuner(
            trainable,
            param_space={"lr": tune.uniform(0.0, 1.0)},
            tune_config=tune.TuneConfig(metric="reward", mode="max",
                                        num_samples=4, scheduler=scheduler),
        )
        res = tuner.fit()
        return res.get_best_result(metric="reward", mode="max").metrics["reward"]

    pb2 = PB2(metric="reward", mode="max", perturbation_interval=2,
              hyperparam_bounds={"lr": (0.0, 1.0)}, seed=0)
    best = run(pb2)
    assert best > 8.0  # within ~0.22 of the optimum lr
