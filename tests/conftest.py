"""Test configuration.

TPU sharding tests run on a virtual 8-device CPU mesh
(``xla_force_host_platform_device_count``); real-TPU benchmarks live in
``bench.py``, not here.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

# The container's sitecustomize force-registers the TPU PJRT plugin and wins
# over JAX_PLATFORMS=cpu in the env, so pin the platform via jax.config
# (effective because no backend has initialized yet at conftest import time).
if os.environ.get("RAY_TPU_TEST_ON_TPU") != "1":
    # assignment (not setdefault): spawned ray workers inherit this env and
    # must not grab the real TPU during the CPU suite
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")

import jax
import pytest

# Sandbox env gap (jax 0.4.37 has no jax.shard_map; the driver runs
# >= 0.6): tests that need shard_map — tp/pp manual meshes, the paged
# kernel's tp fan-out, multihost pp, speculative multihost parity —
# share ONE guard instead of a copy-pasted skipif per file.
HAS_SHARD_MAP = hasattr(jax, "shard_map")
requires_shard_map = pytest.mark.skipif(
    not HAS_SHARD_MAP,
    reason="jax.shard_map (jax >= 0.6) required; known sandbox env gap")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from the tier-1 run")
    config.addinivalue_line(
        "markers",
        "chaos: deterministic fault-injection tests (fast cases run tier-1; "
        "randomized seed sweeps are additionally marked slow)")


def pytest_sessionstart(session):
    # shm segments leaked by previously killed runs exhaust /dev/shm and
    # poison every store allocation in this run — clear them up front
    import glob
    import shutil

    for f in glob.glob("/dev/shm/raytpu_*"):
        try:
            if os.path.isdir(f):
                shutil.rmtree(f, ignore_errors=True)
            else:
                os.unlink(f)
        except OSError:
            pass


@pytest.fixture()
def ray_cluster():
    """One shared local cluster for API-level tests (reference
    ``ray_start_shared_local_modes`` style). Function-scoped but lazily
    shared: init() is a no-op while the cluster from a previous test is
    still up; tests that tear the global cluster down (multinode harness)
    simply cause the next user to boot a fresh one."""
    import ray_tpu

    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield


def pytest_sessionfinish(session, exitstatus):
    import ray_tpu

    try:
        ray_tpu.shutdown()
    except Exception:
        pass
