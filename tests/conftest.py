"""Test configuration.

TPU sharding tests run on a virtual 8-device CPU mesh
(``xla_force_host_platform_device_count``); real-TPU benchmarks live in
``bench.py``, not here.
"""

import os

# Must be set before jax import anywhere in the test process.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import pytest


@pytest.fixture(scope="session")
def ray_cluster():
    """One shared local cluster for API-level tests (reference
    ``ray_start_shared_local_modes`` style)."""
    import ray_tpu

    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()
