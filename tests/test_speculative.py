"""Speculative decoding: draft-K / verify-in-one-dispatch (ROADMAP 5).

The acceptance bar is LOSSLESSNESS: greedy speculative output must be
byte-identical to plain decode in every batch shape — uniform, skewed,
mixed draft quality, COW-shared prefixes, rejections landing mid-page,
EOS inside an accepted run — and a fully rejected draft still advances
one token per verify (speculation never yields less per forward than a
plain decode step). The multihost case drives the SAME verify fan-out
through the compiled-loop channel path.
"""

import dataclasses
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.llm.engine import InferenceEngine, Request
from ray_tpu.llm.speculative import Drafter, NgramDrafter, SpeculationConfig
from ray_tpu.models.llama import PRESETS, init_params
from conftest import requires_shard_map


@pytest.fixture(scope="module")
def small_model():
    cfg = dataclasses.replace(PRESETS["debug"], dtype=jnp.float32,
                              attn_impl="reference")
    params = init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


# Prompts ending mid-pattern so the n-gram lookup drafts from step one.
REPETITIVE = [7, 2, 9, 7, 2, 9, 7, 2, 9, 7]


class WrongDrafter(Drafter):
    """Always proposes SOMETHING (so verify runs every decode tick);
    with ``impossible=True`` the proposals are out-of-vocab, which the
    greedy accept (argmax equality) can never match — a guaranteed
    accept-length-0 round every time."""

    def __init__(self, k: int = 3, impossible: bool = False, vocab: int = 0):
        self.k = k
        self.base = vocab if impossible else 0

    def draft(self, tokens, k):
        if self.base:
            return [self.base + i for i in range(min(k, self.k))]
        return [(tokens[-1] + 97 + i) % 199 + 1 for i in range(min(k, self.k))]


class OracleDrafter(Drafter):
    """Drafts the model's TRUE continuation (recorded from a plain run)
    — the deterministic high-accept case that drives accepted runs
    across page boundaries, shared prefixes, and EOS positions."""

    def __init__(self, seqs):
        self.seqs = [list(s) for s in seqs]

    def draft(self, tokens, k):
        n = len(tokens)
        for s in self.seqs:
            if len(s) > n and s[:n] == list(tokens):
                return s[n:n + k]
        return []


def _generate(cfg, params, prompts, *, speculation=None, max_new=10,
              eos_id=None, temps=None, max_slots=None, max_len=64,
              page_size=8, attention_impl="dense", executor=None,
              engine_out=False, **kw):
    eng = InferenceEngine(
        cfg, params if executor is None else None,
        max_slots=max_slots or max(2, len(prompts)), max_len=max_len,
        page_size=page_size, attention_impl=attention_impl,
        speculation_config=speculation, executor=executor, seed=0, **kw)
    mn = max_new if isinstance(max_new, list) else [max_new] * len(prompts)
    ts = temps or [0.0] * len(prompts)
    reqs = [Request(f"r{i}", list(p), mn[i], ts[i], eos_id=eos_id)
            for i, p in enumerate(prompts)]
    for r in reqs:
        eng.add_request(r)
    steps = 0
    while any(not r.done for r in reqs):
        eng.step()
        steps += 1
        assert steps < 2000
    out = [list(r.generated) for r in reqs]
    return (out, eng) if engine_out else out


# --------------------------------------------------------------- drafter
def test_ngram_drafter_lookup():
    d = NgramDrafter(ngram_max=3, ngram_min=1)
    # trailing 3-gram [2,9,7]: MOST RECENT earlier occurrence is at
    # 4..6, whose continuation [2,9,7] runs to the end of the sequence
    assert d.draft(REPETITIVE, 3) == [2, 9, 7]
    assert d.draft(REPETITIVE, 8) == [2, 9, 7]  # capped by the seq end
    assert d.draft([1, 2, 3, 4, 5], 4) == []             # no repetition
    assert d.draft([5], 4) == []                         # too short
    assert d.draft(REPETITIVE, 0) == []
    # most RECENT earlier occurrence wins
    assert d.draft([1, 9, 2, 8, 9, 3, 9], 2) == [3, 9]


def test_speculation_config_normalize():
    assert SpeculationConfig.normalize(None) is None
    c = SpeculationConfig.normalize({"num_draft_tokens": 6})
    assert c.num_draft_tokens == 6
    assert isinstance(c.build_drafter(), NgramDrafter)
    assert SpeculationConfig.normalize(c) is c
    wrong = WrongDrafter()
    assert SpeculationConfig(drafter=wrong).build_drafter() is wrong
    with pytest.raises(TypeError):
        SpeculationConfig.normalize("ngram")


# ---------------------------------------------------------------- parity
def test_greedy_parity_uniform(small_model):
    cfg, params = small_model
    prompts = [list(REPETITIVE) for _ in range(4)]
    plain = _generate(cfg, params, prompts)
    spec, eng = _generate(cfg, params, prompts,
                          speculation={"num_draft_tokens": 4},
                          engine_out=True)
    assert spec == plain
    assert eng.metrics["spec_dispatches"] > 0  # speculation actually ran
    assert eng.metrics["spec_drafted_tokens"] > 0


def test_greedy_parity_skewed_mixed_batch(small_model):
    """Mixed draft quality and skewed lengths in ONE batch: repetitive
    prompts draft well, arbitrary ones draft badly or not at all, and
    per-slot accept lengths diverge inside each verify dispatch."""
    cfg, params = small_model
    prompts = [list(REPETITIVE), [3, 1, 4, 1, 5, 9, 2, 6], [11] * 14,
               [2, 7]]
    max_new = [12, 6, 9, 4]
    plain = _generate(cfg, params, prompts, max_new=max_new)
    spec = _generate(cfg, params, prompts, max_new=max_new,
                     speculation={"num_draft_tokens": 5})
    assert spec == plain


def test_greedy_parity_cow_shared_prefix(small_model):
    """Speculation over COW-shared prefix pages: warm the prefix trie
    (retiring full blocks AND a partial tail), then decode a batch
    whose prompts map shared pages — the partial-tail hit COW-forks at
    the first suffix write, and accepted speculative runs write past
    the fork. Byte parity with plain decode, and the shared pages stay
    byte-stable (same trie hit/fork counts in both runs)."""
    cfg, params = small_model
    # 19 prompt + 4 generated -> 22 valid rows: 2 full pages + a
    # 6-row partial tail enters the trie at warm-request retire.
    warm = list(range(1, 20))
    batch = [warm[:17] + [31, 32], warm[:12] + [41, 42, 43], list(warm)]

    def run(spec):
        eng = InferenceEngine(cfg, params, max_slots=4, max_len=64,
                              page_size=8, speculation_config=spec, seed=0)
        first = eng.generate(list(warm), max_new_tokens=4)
        reqs = [Request(f"c{i}", list(p), 8) for i, p in enumerate(batch)]
        for r in reqs:
            eng.add_request(r)
        while any(not r.done for r in reqs):
            eng.step()
        hits = eng.metrics["prefix_hit_pages"]
        forks = eng.metrics["cow_forks"]
        return first, [list(r.generated) for r in reqs], hits, forks, eng

    p_first, p_out, p_hits, p_forks, _ = run(None)
    oracle = OracleDrafter([list(warm) + p_first]
                           + [list(p) + o for p, o in zip(batch, p_out)])
    s_first, s_out, s_hits, s_forks, eng = run(
        SpeculationConfig(num_draft_tokens=4, drafter=oracle))
    assert (s_first, s_out) == (p_first, p_out)
    assert s_hits == p_hits and s_hits > 0      # shared pages really mapped
    assert s_forks == p_forks and s_forks > 0   # and the COW fork fired
    assert eng.metrics["spec_accepted_tokens"] > 0


def test_greedy_parity_mid_page_rejection(small_model):
    """Rejections landing mid-page: a wrong-by-construction drafter is
    rejected at EVERY position offset as decode sweeps page
    boundaries; the trash-redirected commits must never corrupt the
    slot's real pages (parity over a full multi-page generation)."""
    cfg, params = small_model
    prompts = [[5, 9, 2], [6, 6, 6, 6, 6]]
    plain = _generate(cfg, params, prompts, max_new=21)
    spec, eng = _generate(
        cfg, params, prompts, max_new=21,
        speculation=SpeculationConfig(num_draft_tokens=3,
                                      drafter=WrongDrafter()),
        engine_out=True)
    assert spec == plain
    assert eng.metrics["spec_rollbacks"] > 0


def test_greedy_parity_eos_inside_accepted_run(small_model):
    """EOS emitted INSIDE an accepted draft run (the oracle drafts the
    true continuation, so the EOS position is mid-run) ends the stream
    exactly where plain decode ends it, discarding the verified
    surplus."""
    cfg, params = small_model
    prompt = [1, 2, 3, 4, 1, 2, 3, 4, 1, 2]  # varied greedy continuation
    probe = _generate(cfg, params, [list(prompt)], max_new=12)[0]
    # EOS = a token first emitted at position >= 3: the oracle's draft
    # reaches it only after accepted tokens, so EOS lands mid-run.
    idx = next(p for p in range(3, len(probe))
               if probe[p] not in probe[:p] and probe[p] not in prompt)
    eos = probe[idx]
    plain = _generate(cfg, params, [list(prompt)], max_new=12, eos_id=eos)
    assert len(plain[0]) == idx + 1
    oracle = OracleDrafter([list(prompt) + probe])
    spec, eng = _generate(
        cfg, params, [list(prompt)], max_new=12, eos_id=eos,
        speculation=SpeculationConfig(num_draft_tokens=6, drafter=oracle),
        engine_out=True)
    assert spec == plain
    assert spec[0][-1] == eos and len(spec[0]) == len(plain[0])
    assert eng.metrics["spec_dispatches"] > 0
    assert eng.metrics["spec_accepted_tokens"] > 0


def test_accept_zero_still_advances(small_model):
    """The progress floor: a draft rejected wholesale still emits one
    (corrected) token per slot per verify — tokens-per-dispatch can
    never drop below 1.0, so speculation never does worse per forward
    than plain decode."""
    cfg, params = small_model
    spec, eng = _generate(
        cfg, params, [[3, 1, 4, 1, 5], [2, 7, 1, 8]], max_new=9,
        speculation=SpeculationConfig(
            num_draft_tokens=4,
            drafter=WrongDrafter(impossible=True, vocab=cfg.vocab_size)),
        engine_out=True)
    plain = _generate(cfg, params, [[3, 1, 4, 1, 5], [2, 7, 1, 8]],
                      max_new=9)
    assert spec == plain
    assert eng.metrics["spec_dispatches"] > 0
    assert eng.metrics["spec_accepted_tokens"] == 0
    assert eng.spec_tokens_per_dispatch == 1.0


def test_tokens_per_dispatch_beats_plain_on_repetitive(small_model):
    """The sandbox acceptance cell: on repetitive traffic the n-gram
    drafter gets real accepts, so emitted tokens per slot per verify
    strictly beat the 1-token-per-forward plain baseline."""
    cfg, params = small_model
    prompts = [[5 + i, 9, 2, 5 + i, 9, 2, 5 + i, 9, 2, 5 + i]
               for i in range(4)]
    out, eng = _generate(cfg, params, prompts, max_new=60, max_len=128,
                         page_size=8,
                         speculation={"num_draft_tokens": 6},
                         engine_out=True)
    assert eng.spec_tokens_per_dispatch > 1.0
    assert eng.spec_accept_rate > 0.0
    assert 0.0 <= eng.spec_accept_rate <= 1.0
    plain = _generate(cfg, params, prompts, max_new=60, max_len=128,
                      page_size=8)
    assert out == plain


def test_paged_kernel_verify_parity(small_model):
    """The verify program's paged path (Pallas kernel folding staged
    rows [0, j] per chunk position, interpret mode here) matches the
    dense plain-decode ground truth byte for byte."""
    cfg, params = small_model
    prompts = [list(REPETITIVE), [4, 8, 4, 8, 4]]
    plain = _generate(cfg, params, prompts, max_new=8)
    oracle = OracleDrafter([list(p) + o for p, o in zip(prompts, plain)])
    spec, eng = _generate(
        cfg, params, prompts, max_new=8, attention_impl="paged",
        speculation=SpeculationConfig(num_draft_tokens=3, drafter=oracle),
        engine_out=True)
    assert spec == plain
    assert eng.metrics["spec_dispatches"] > 0
    assert eng.metrics["spec_accepted_tokens"] > 0


def test_temperature_rejection_sampling_sane(small_model):
    """temp > 0 runs the rejection-sampling path: requests complete
    with valid token ids (never a -1 pad) and full lengths. (Exact
    byte parity is a greedy-only guarantee — sampled runs consume RNG
    differently but preserve the target distribution.)"""
    cfg, params = small_model
    out, eng = _generate(
        cfg, params, [list(REPETITIVE), [1, 3, 1, 3, 1]],
        max_new=10, temps=[0.8, 0.6],
        speculation=SpeculationConfig(num_draft_tokens=3,
                                      drafter=WrongDrafter()),
        engine_out=True)
    assert all(len(t) == 10 for t in out)
    assert all(0 <= tok < cfg.vocab_size for t in out for tok in t)
    assert eng.metrics["spec_dispatches"] > 0


def test_plain_path_untouched_without_config(small_model):
    """speculation_config=None must leave the decode path bit-for-bit
    alone: no drafter, no verify dispatches, spec metrics zero."""
    cfg, params = small_model
    out, eng = _generate(cfg, params, [list(REPETITIVE)], engine_out=True)
    assert not eng.speculation_enabled and eng._drafter is None
    assert eng.metrics["spec_dispatches"] == 0
    assert eng.metrics["spec_drafted_tokens"] == 0
    assert eng.spec_tokens_per_dispatch == 0.0
    assert out == _generate(cfg, params, [list(REPETITIVE)])


def test_speculation_gated_off_unsupported_executor(small_model):
    """An executor without the verify entry point (here: faked) keeps
    the engine on plain decode even with a config set."""
    cfg, params = small_model
    eng = InferenceEngine(cfg, params, max_slots=2, max_len=64, page_size=8,
                          speculation_config={"num_draft_tokens": 4})
    assert eng.speculation_enabled
    eng.executor.__dict__["_verify"] = None  # simulate a pp-style executor
    assert not eng.executor.supports_speculation
    assert not eng.speculation_enabled
    assert eng.generate(list(REPETITIVE), max_new_tokens=6)  # plain path


def test_deployment_threads_speculation_config(small_model):
    """speculation_config rides LLMDeployment → engine, and the engine
    metrics surface accept rate / tokens-per-dispatch for the probe."""
    from ray_tpu.llm.serving import LLMDeployment

    cfg, _ = small_model
    cfg128 = dataclasses.replace(PRESETS["debug-128"], dtype=jnp.float32,
                                 attn_impl="reference")
    dep = LLMDeployment(cfg128, max_slots=2, max_len=64, page_size=8,
                        prefill_chunk_size=16,
                        speculation_config={"num_draft_tokens": 3})
    try:
        assert dep.engine.speculation_enabled
        out = dep.generate("abcabcabc", max_new_tokens=6)
        assert out["num_generated"] == 6
        m = dep.engine_metrics()
        assert m["speculation_enabled"] is True
        assert "spec_accept_rate" in m and "spec_tokens_per_dispatch" in m
    finally:
        dep.close()


def test_concurrent_adds_during_speculation(small_model):
    """Late arrivals join mid-speculation: prefill interleaves with
    verify ticks and every request's greedy output still matches its
    own single-request plain reference (greedy is batch-independent)."""
    cfg, params = small_model
    eng = InferenceEngine(cfg, params, max_slots=4, max_len=64, page_size=8,
                          speculation_config={"num_draft_tokens": 4})
    first = Request("a", list(REPETITIVE), 16)
    eng.add_request(first)
    for _ in range(3):
        eng.step()
    late = Request("b", [4, 8, 4, 8, 4], 8)
    eng.add_request(late)
    steps = 0
    while not (first.done and late.done):
        eng.step()
        steps += 1
        assert steps < 500
    assert first.generated == _generate(cfg, params, [list(REPETITIVE)],
                                        max_new=16)[0]
    assert late.generated == _generate(cfg, params, [[4, 8, 4, 8, 4]],
                                       max_new=8)[0]


# ----------------------------------------------- multihost / compiled loop
@requires_shard_map
def test_multihost_compiled_loop_speculative_parity(ray_cluster):
    """The verify fan-out through BOTH sharded dispatch modes — dynamic
    actor calls and the compiled-loop channel (one resident tick
    executor per shard, verify rides ``tick(("verify", ...))``) — must
    match the single-process plain engine byte for byte."""
    from ray_tpu.llm import create_sharded_executor

    cfg = dataclasses.replace(PRESETS["debug"], dtype=jnp.float32,
                              attn_impl="reference")
    prompts = [list(REPETITIVE), [7, 3, 7, 3, 7]]
    ref = InferenceEngine(cfg, max_slots=2, max_len=64, page_size=8, seed=0)
    expected = [ref.generate(list(p), max_new_tokens=8) for p in prompts]
    # The drafter is DRIVER-side state (the shards only see verify
    # dispatches), so the oracle works unchanged across the fan-out —
    # and guarantees accepted runs stream through the channel path.
    oracle = OracleDrafter([list(p) + o for p, o in zip(prompts, expected)])

    shard_env = {"env_vars": {
        "XLA_FLAGS": "--xla_force_host_platform_device_count=1"}}
    for use_loop in (False, True):
        executor = create_sharded_executor(
            cfg, 2, max_slots=2,
            num_pages=InferenceEngine.total_pages(2, 64, 8), page_size=8,
            seed=0, runtime_env=shard_env, use_compiled_loop=use_loop)
        try:
            assert executor.supports_speculation
            eng = InferenceEngine(
                cfg, max_slots=2, max_len=64, page_size=8,
                executor=executor, seed=0,
                speculation_config=SpeculationConfig(num_draft_tokens=3,
                                                     drafter=oracle))
            assert eng.speculation_enabled
            got = [eng.generate(list(p), max_new_tokens=8) for p in prompts]
            assert got == expected, f"use_compiled_loop={use_loop}"
            assert eng.metrics["spec_dispatches"] > 0
            assert eng.metrics["spec_accepted_tokens"] > 0
            if use_loop:
                assert executor.loop_ticks > 0
        finally:
            executor.shutdown()
