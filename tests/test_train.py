"""Train library: controller/worker-group/report/checkpoint/failure
semantics (reference: python/ray/train/v2/tests/)."""

import os

import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.train import (
    Checkpoint,
    CheckpointConfig,
    DataParallelTrainer,
    FailureConfig,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
)


def test_single_worker_reports_metrics(ray_cluster, tmp_path):
    def train_fn(config):
        ctx = train.get_context()
        for step in range(3):
            train.report({"step": step, "rank": ctx.get_world_rank()})

    trainer = JaxTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="t1", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["step"] == 2
    assert len(result.metrics_history) == 3


def test_two_workers_context(ray_cluster, tmp_path):
    def train_fn(config):
        ctx = train.get_context()
        train.report({"world_size": ctx.get_world_size(), "rank": ctx.get_world_rank()})

    trainer = JaxTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="t2", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["world_size"] == 2
    assert result.metrics["rank"] == 0  # controller keeps rank-0 metrics


def test_checkpoint_roundtrip(ray_cluster, tmp_path):
    def train_fn(config):
        import tempfile

        resumed = train.get_checkpoint()
        start = 0
        if resumed:
            with resumed.as_directory() as d:
                start = int(open(os.path.join(d, "step.txt")).read())
        with tempfile.TemporaryDirectory() as d:
            with open(os.path.join(d, "step.txt"), "w") as f:
                f.write(str(start + 5))
            train.report({"final_step": start + 5}, checkpoint=Checkpoint.from_directory(d))

    run_cfg = RunConfig(
        name="ckpt", storage_path=str(tmp_path),
        checkpoint_config=CheckpointConfig(num_to_keep=2),
    )
    trainer = JaxTrainer(
        train_fn, scaling_config=ScalingConfig(num_workers=1), run_config=run_cfg,
    )
    result = trainer.fit()
    assert result.error is None
    assert result.checkpoint is not None
    with result.checkpoint.as_directory() as d:
        assert open(os.path.join(d, "step.txt")).read() == "5"

    # resume from the produced checkpoint
    trainer2 = JaxTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="ckpt2", storage_path=str(tmp_path)),
        resume_from_checkpoint=result.checkpoint,
    )
    r2 = trainer2.fit()
    assert r2.error is None
    assert r2.metrics["final_step"] == 10


def test_failure_policy_restarts_group(ray_cluster, tmp_path):
    marker = str(tmp_path / "attempted_once")

    def train_fn(config):
        if not os.path.exists(config["marker"]):
            open(config["marker"], "w").write("x")
            raise RuntimeError("injected first-attempt failure")
        train.report({"ok": 1})

    trainer = JaxTrainer(
        train_fn,
        train_loop_config={"marker": marker},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            name="ft", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=1),
        ),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics == {"ok": 1}


def test_train_on_dataset(ray_cluster, tmp_path):
    """datasets= flows to workers as per-rank streaming_split iterators
    (reference: dataset.py:1598 + get_dataset_shard)."""
    from ray_tpu import data

    def train_fn(config):
        shard = train.get_dataset_shard("train")
        seen = sum(batch["id"].shape[0] for batch in shard.iter_batches(batch_size=8))
        train.report({"rows_seen": seen})

    trainer = JaxTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="ds", storage_path=str(tmp_path)),
        datasets={"train": data.range(64, parallelism=4)},
    )
    result = trainer.fit()
    assert result.error is None
    # split streams partition all 64 rows across the 2 workers
    assert result.metrics["rows_seen"] > 0
    assert result.metrics["rows_seen"] < 64


def test_train_dataset_worker_kill_resume(ray_cluster, tmp_path):
    """Worker dies mid-epoch → whole group restarts with a FRESH stream and
    resumes from the latest checkpoint (VERDICT round 1 #6)."""
    from ray_tpu import data

    marker = str(tmp_path / "killed_once")

    def train_fn(config):
        import os as _os

        resumed = train.get_checkpoint()
        start = 0
        if resumed:
            with resumed.as_directory() as d:
                start = int(open(_os.path.join(d, "start.txt")).read())
        shard = train.get_dataset_shard("train")
        rows = 0
        for batch in shard.iter_batches(batch_size=8):
            rows += batch["id"].shape[0]
            if rows >= 8 and not _os.path.exists(config["marker"]) and start == 0:
                open(config["marker"], "w").write("x")
                import tempfile

                with tempfile.TemporaryDirectory() as d:
                    open(_os.path.join(d, "start.txt"), "w").write("1")
                    train.report({"rows": rows}, checkpoint=Checkpoint.from_directory(d))
                raise RuntimeError("injected mid-epoch death")
        train.report({"rows": rows, "resumed_from": start})

    trainer = JaxTrainer(
        train_fn,
        train_loop_config={"marker": marker},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            name="dsft", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=1),
        ),
        datasets={"train": data.range(32, parallelism=4)},
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["resumed_from"] == 1
    assert result.metrics["rows"] == 32  # fresh stream on restart


def test_failure_policy_exhausted(ray_cluster, tmp_path):
    def train_fn(config):
        raise RuntimeError("always fails")

    trainer = JaxTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            name="fail", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=0),
        ),
    )
    result = trainer.fit()
    assert result.error is not None
    assert "always fails" in str(result.error)


class _CallCountClock:
    """Fake clock for ElasticScalingPolicy: advances one "second" per
    call, so the resize debounce is driven by monitor() call counts
    instead of wall time — full-suite load cannot flake it."""

    def __init__(self):
        self.t = 0.0

    def __call__(self) -> float:
        self.t += 1.0
        return self.t


def test_elastic_scaling_upscale(tmp_path):
    import time
    """Elastic policy (min_workers set): the run starts at the feasible
    size, and when capacity grows mid-run the controller restarts the
    group slice-atomically at the larger size from the latest checkpoint
    (reference v2 scaling_policy ResizeDecision)."""
    import ray_tpu
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.train import ElasticScalingPolicy

    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 1})
    ray_tpu.init(address=c.address, num_cpus=0)
    try:
        def train_fn(config):
            import os
            import tempfile
            import time

            ctx = train.get_context()
            start = 0
            ckpt = train.get_checkpoint()
            if ckpt is not None:
                with open(os.path.join(ckpt.path, "step.txt")) as f:
                    start = int(f.read())
            for step in range(start, 48):
                d = tempfile.mkdtemp()
                with open(os.path.join(d, "step.txt"), "w") as f:
                    f.write(str(step + 1))
                train.report(
                    {"step": step, "world": ctx.get_world_size()},
                    checkpoint=Checkpoint.from_directory(d),
                )
                time.sleep(0.25)

        scaling = ScalingConfig(num_workers=3, min_workers=1,
                                resources_per_worker={"CPU": 1})
        trainer = DataParallelTrainer(
            train_fn,
            scaling_config=scaling,
            run_config=RunConfig(name="elastic", storage_path=str(tmp_path)),
            scaling_policy=ElasticScalingPolicy(
                scaling, check_interval_s=2.0, clock=_CallCountClock()),
        )

        import threading

        result_box = {}

        def run():
            result_box["result"] = trainer.fit()

        t = threading.Thread(target=run)
        t.start()
        time.sleep(3.0)  # let the 1-worker attempt make progress
        c.add_node(num_cpus=2)  # capacity for 2 more workers
        t.join(timeout=180)
        assert not t.is_alive(), "elastic fit() did not finish"
        result = result_box["result"]
        assert result.error is None, result.error
        worlds = [m["world"] for m in result.metrics_history]
        # started small, resized up to the full 3 once capacity appeared
        assert worlds[0] == 1 and 3 in worlds, worlds
        # steps progressed across the resize (checkpoint resume, not restart)
        steps = [m["step"] for m in result.metrics_history]
        assert steps[-1] == 47 and steps[0] == 0
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def test_elastic_scaling_downscale_on_node_death(tmp_path):
    """Losing a node mid-run shrinks the next attempt to the remaining
    capacity (slice-atomic restart from checkpoint) instead of failing
    the run or waiting for the lost capacity."""
    import time

    import ray_tpu
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.train import ElasticScalingPolicy

    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 1},
                _system_config={"health_check_failure_threshold": 2})
    n2 = c.add_node(num_cpus=2)
    ray_tpu.init(address=c.address, num_cpus=0)
    try:
        deadline = time.time() + 30
        while ray_tpu.cluster_resources().get("CPU", 0) < 3 and time.time() < deadline:
            time.sleep(0.2)  # node2 must be visible so the run STARTS at 3
        def train_fn(config):
            import os
            import tempfile
            import time as _t

            ctx = train.get_context()
            start = 0
            ckpt = train.get_checkpoint()
            if ckpt is not None:
                with open(os.path.join(ckpt.path, "step.txt")) as f:
                    start = int(f.read())
            for step in range(start, 16):
                d = tempfile.mkdtemp()
                with open(os.path.join(d, "step.txt"), "w") as f:
                    f.write(str(step + 1))
                train.report(
                    {"step": step, "world": ctx.get_world_size()},
                    checkpoint=Checkpoint.from_directory(d),
                )
                _t.sleep(0.25)

        scaling = ScalingConfig(num_workers=3, min_workers=1,
                                resources_per_worker={"CPU": 1})
        trainer = DataParallelTrainer(
            train_fn,
            scaling_config=scaling,
            run_config=RunConfig(name="elastic_down", storage_path=str(tmp_path),
                                 failure_config=FailureConfig(max_failures=2)),
            scaling_policy=ElasticScalingPolicy(
                scaling, check_interval_s=2.0, clock=_CallCountClock()),
        )

        import threading

        box = {}
        t = threading.Thread(target=lambda: box.update(result=trainer.fit()))
        t.start()
        # Wait for EVIDENCE the 3-worker attempt is underway (its first
        # checkpoint landing in storage) instead of a wall-clock sleep —
        # under full-suite load on one core a fixed sleep races the
        # worker-group start and flakes.
        import glob as _glob

        deadline = time.time() + 60
        while time.time() < deadline:
            if _glob.glob(str(tmp_path / "elastic_down" / "**" / "step.txt"),
                          recursive=True):
                break
            time.sleep(0.2)
        else:
            raise AssertionError("3-worker attempt never checkpointed")
        c.remove_node(n2)  # kill 2 of 3 workers' node
        t.join(timeout=240)
        assert not t.is_alive(), "fit() did not finish after node loss"
        result = box["result"]
        assert result.error is None, result.error
        worlds = [m["world"] for m in result.metrics_history]
        assert worlds[0] == 3 and worlds[-1] == 1, worlds
        assert result.metrics["step"] == 15, result.metrics
    finally:
        ray_tpu.shutdown()
        c.shutdown()
