"""Train library: controller/worker-group/report/checkpoint/failure
semantics (reference: python/ray/train/v2/tests/)."""

import os

import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.train import (
    Checkpoint,
    CheckpointConfig,
    FailureConfig,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
)


def test_single_worker_reports_metrics(ray_cluster, tmp_path):
    def train_fn(config):
        ctx = train.get_context()
        for step in range(3):
            train.report({"step": step, "rank": ctx.get_world_rank()})

    trainer = JaxTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="t1", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["step"] == 2
    assert len(result.metrics_history) == 3


def test_two_workers_context(ray_cluster, tmp_path):
    def train_fn(config):
        ctx = train.get_context()
        train.report({"world_size": ctx.get_world_size(), "rank": ctx.get_world_rank()})

    trainer = JaxTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="t2", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["world_size"] == 2
    assert result.metrics["rank"] == 0  # controller keeps rank-0 metrics


def test_checkpoint_roundtrip(ray_cluster, tmp_path):
    def train_fn(config):
        import tempfile

        resumed = train.get_checkpoint()
        start = 0
        if resumed:
            with resumed.as_directory() as d:
                start = int(open(os.path.join(d, "step.txt")).read())
        with tempfile.TemporaryDirectory() as d:
            with open(os.path.join(d, "step.txt"), "w") as f:
                f.write(str(start + 5))
            train.report({"final_step": start + 5}, checkpoint=Checkpoint.from_directory(d))

    run_cfg = RunConfig(
        name="ckpt", storage_path=str(tmp_path),
        checkpoint_config=CheckpointConfig(num_to_keep=2),
    )
    trainer = JaxTrainer(
        train_fn, scaling_config=ScalingConfig(num_workers=1), run_config=run_cfg,
    )
    result = trainer.fit()
    assert result.error is None
    assert result.checkpoint is not None
    with result.checkpoint.as_directory() as d:
        assert open(os.path.join(d, "step.txt")).read() == "5"

    # resume from the produced checkpoint
    trainer2 = JaxTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="ckpt2", storage_path=str(tmp_path)),
        resume_from_checkpoint=result.checkpoint,
    )
    r2 = trainer2.fit()
    assert r2.error is None
    assert r2.metrics["final_step"] == 10


def test_failure_policy_restarts_group(ray_cluster, tmp_path):
    marker = str(tmp_path / "attempted_once")

    def train_fn(config):
        if not os.path.exists(config["marker"]):
            open(config["marker"], "w").write("x")
            raise RuntimeError("injected first-attempt failure")
        train.report({"ok": 1})

    trainer = JaxTrainer(
        train_fn,
        train_loop_config={"marker": marker},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            name="ft", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=1),
        ),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics == {"ok": 1}


def test_train_on_dataset(ray_cluster, tmp_path):
    """datasets= flows to workers as per-rank streaming_split iterators
    (reference: dataset.py:1598 + get_dataset_shard)."""
    from ray_tpu import data

    def train_fn(config):
        shard = train.get_dataset_shard("train")
        seen = sum(batch["id"].shape[0] for batch in shard.iter_batches(batch_size=8))
        train.report({"rows_seen": seen})

    trainer = JaxTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="ds", storage_path=str(tmp_path)),
        datasets={"train": data.range(64, parallelism=4)},
    )
    result = trainer.fit()
    assert result.error is None
    # split streams partition all 64 rows across the 2 workers
    assert result.metrics["rows_seen"] > 0
    assert result.metrics["rows_seen"] < 64


def test_train_dataset_worker_kill_resume(ray_cluster, tmp_path):
    """Worker dies mid-epoch → whole group restarts with a FRESH stream and
    resumes from the latest checkpoint (VERDICT round 1 #6)."""
    from ray_tpu import data

    marker = str(tmp_path / "killed_once")

    def train_fn(config):
        import os as _os

        resumed = train.get_checkpoint()
        start = 0
        if resumed:
            with resumed.as_directory() as d:
                start = int(open(_os.path.join(d, "start.txt")).read())
        shard = train.get_dataset_shard("train")
        rows = 0
        for batch in shard.iter_batches(batch_size=8):
            rows += batch["id"].shape[0]
            if rows >= 8 and not _os.path.exists(config["marker"]) and start == 0:
                open(config["marker"], "w").write("x")
                import tempfile

                with tempfile.TemporaryDirectory() as d:
                    open(_os.path.join(d, "start.txt"), "w").write("1")
                    train.report({"rows": rows}, checkpoint=Checkpoint.from_directory(d))
                raise RuntimeError("injected mid-epoch death")
        train.report({"rows": rows, "resumed_from": start})

    trainer = JaxTrainer(
        train_fn,
        train_loop_config={"marker": marker},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            name="dsft", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=1),
        ),
        datasets={"train": data.range(32, parallelism=4)},
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["resumed_from"] == 1
    assert result.metrics["rows"] == 32  # fresh stream on restart


def test_failure_policy_exhausted(ray_cluster, tmp_path):
    def train_fn(config):
        raise RuntimeError("always fails")

    trainer = JaxTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            name="fail", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=0),
        ),
    )
    result = trainer.fit()
    assert result.error is not None
    assert "always fails" in str(result.error)
