"""Train library: controller/worker-group/report/checkpoint/failure
semantics (reference: python/ray/train/v2/tests/)."""

import os

import pytest

import ray_tpu
from ray_tpu import train
from ray_tpu.train import (
    Checkpoint,
    CheckpointConfig,
    FailureConfig,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
)


def test_single_worker_reports_metrics(ray_cluster, tmp_path):
    def train_fn(config):
        ctx = train.get_context()
        for step in range(3):
            train.report({"step": step, "rank": ctx.get_world_rank()})

    trainer = JaxTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="t1", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["step"] == 2
    assert len(result.metrics_history) == 3


def test_two_workers_context(ray_cluster, tmp_path):
    def train_fn(config):
        ctx = train.get_context()
        train.report({"world_size": ctx.get_world_size(), "rank": ctx.get_world_rank()})

    trainer = JaxTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="t2", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["world_size"] == 2
    assert result.metrics["rank"] == 0  # controller keeps rank-0 metrics


def test_checkpoint_roundtrip(ray_cluster, tmp_path):
    def train_fn(config):
        import tempfile

        resumed = train.get_checkpoint()
        start = 0
        if resumed:
            with resumed.as_directory() as d:
                start = int(open(os.path.join(d, "step.txt")).read())
        with tempfile.TemporaryDirectory() as d:
            with open(os.path.join(d, "step.txt"), "w") as f:
                f.write(str(start + 5))
            train.report({"final_step": start + 5}, checkpoint=Checkpoint.from_directory(d))

    run_cfg = RunConfig(
        name="ckpt", storage_path=str(tmp_path),
        checkpoint_config=CheckpointConfig(num_to_keep=2),
    )
    trainer = JaxTrainer(
        train_fn, scaling_config=ScalingConfig(num_workers=1), run_config=run_cfg,
    )
    result = trainer.fit()
    assert result.error is None
    assert result.checkpoint is not None
    with result.checkpoint.as_directory() as d:
        assert open(os.path.join(d, "step.txt")).read() == "5"

    # resume from the produced checkpoint
    trainer2 = JaxTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="ckpt2", storage_path=str(tmp_path)),
        resume_from_checkpoint=result.checkpoint,
    )
    r2 = trainer2.fit()
    assert r2.error is None
    assert r2.metrics["final_step"] == 10


def test_failure_policy_restarts_group(ray_cluster, tmp_path):
    marker = str(tmp_path / "attempted_once")

    def train_fn(config):
        if not os.path.exists(config["marker"]):
            open(config["marker"], "w").write("x")
            raise RuntimeError("injected first-attempt failure")
        train.report({"ok": 1})

    trainer = JaxTrainer(
        train_fn,
        train_loop_config={"marker": marker},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            name="ft", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=1),
        ),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics == {"ok": 1}


def test_failure_policy_exhausted(ray_cluster, tmp_path):
    def train_fn(config):
        raise RuntimeError("always fails")

    trainer = JaxTrainer(
        train_fn,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            name="fail", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=0),
        ),
    )
    result = trainer.fit()
    assert result.error is not None
    assert "always fails" in str(result.error)
