"""util layer: ActorPool, distributed Queue, multiprocessing.Pool.

Reference surfaces: ``python/ray/util/actor_pool.py``, ``util/queue.py``,
``util/multiprocessing/pool.py``.
"""

import pytest

import ray_tpu
from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.multiprocessing import Pool
from ray_tpu.util.queue import Empty, Full, Queue


@ray_tpu.remote
class Doubler:
    def double(self, x):
        return 2 * x


def test_actor_pool_ordered(ray_cluster):
    pool = ActorPool([Doubler.remote(), Doubler.remote()])
    results = list(pool.map(lambda a, v: a.double.remote(v), range(8)))
    assert results == [2 * i for i in range(8)]


def test_actor_pool_unordered_and_backpressure(ray_cluster):
    pool = ActorPool([Doubler.remote(), Doubler.remote()])
    for i in range(6):  # more submits than actors: queued internally
        pool.submit(lambda a, v: a.double.remote(v), i)
    out = set()
    while pool.has_next():
        out.add(pool.get_next_unordered(timeout=60))
    assert out == {2 * i for i in range(6)}


def test_actor_pool_survives_task_errors(ray_cluster):
    """A raising task must surface its error AND return the actor to the
    pool; later submits still run (no actor leak / deadlock)."""

    @ray_tpu.remote
    class Worker:
        def run(self, x):
            if x == 1:
                raise ValueError("boom")
            return x

    pool = ActorPool([Worker.remote()])
    for i in range(3):
        pool.submit(lambda a, v: a.run.remote(v), i)
    results, errors = [], 0
    while pool.has_next():
        try:
            results.append(pool.get_next(timeout=60))
        except ValueError:
            errors += 1
    assert errors == 1 and results == [0, 2]


def test_queue_batch_ops_are_all_or_nothing(ray_cluster):
    q = Queue(maxsize=3)
    q.put(0)
    with pytest.raises(Full):
        q.put_nowait_batch([1, 2, 3])  # would exceed maxsize
    assert q.qsize() == 1  # nothing partially inserted
    q.put_nowait_batch([1, 2])
    with pytest.raises(Empty):
        q.get_nowait_batch(4)  # only 3 available
    assert q.qsize() == 3  # nothing discarded
    assert q.get_nowait_batch(3) == [0, 1, 2]
    q.shutdown()


def test_queue_fifo_and_batches(ray_cluster):
    q = Queue()
    for i in range(5):
        q.put(i)
    assert q.qsize() == 5
    assert [q.get(timeout=10) for _ in range(5)] == [0, 1, 2, 3, 4]
    with pytest.raises(Empty):
        q.get_nowait()
    q.put_nowait_batch([1, 2, 3])
    assert q.get_nowait_batch(3) == [1, 2, 3]
    q.shutdown()


def test_queue_maxsize(ray_cluster):
    q = Queue(maxsize=2)
    q.put(1)
    q.put(2)
    with pytest.raises(Full):
        q.put(3, block=False)
    assert q.get(timeout=10) == 1
    q.put(3, timeout=10)  # space freed
    q.shutdown()


def test_queue_cross_actor(ray_cluster):
    """The queue handle pickles into actors; producer and consumer see one
    FIFO order."""
    q = Queue()

    @ray_tpu.remote
    def producer(q, n):
        for i in range(n):
            q.put(i)
        return True

    ray_tpu.get(producer.remote(q, 4), timeout=60)
    assert [q.get(timeout=10) for _ in range(4)] == [0, 1, 2, 3]
    q.shutdown()


def test_mp_pool_map_and_imap(ray_cluster):
    # closure (not module-level): cloudpickle ships it by value, the pool
    # workers need no importable test module
    def sq(x):
        return x * x

    with Pool(2) as p:
        assert p.map(sq, range(6)) == [0, 1, 4, 9, 16, 25]
        assert sorted(p.imap_unordered(sq, range(6), chunksize=2)) == [0, 1, 4, 9, 16, 25]
        r = p.apply_async(sq, (7,))
        assert r.get(timeout=60) == 49
        assert p.apply(sq, (3,)) == 9
