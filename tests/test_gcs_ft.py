"""GCS fault tolerance: durable tables + restart recovery.

Reference: ``src/ray/gcs/store_client/redis_store_client.h:107`` (GCS
state survives in Redis; gcs_server restarts and clients reconnect).
Redesign under test: atomic-snapshot FileStorage + raylet heartbeat
re-registration + RetryableRpcClient reconnection on the same port.
"""

import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.core.gcs_storage import FileStorage, pack_tables, unpack_tables


def test_file_storage_roundtrip_and_atomicity(tmp_path):
    st = FileStorage(str(tmp_path / "snap.msgpack"))
    tables = {"kv": {"a": b"\x00\x01"}, "jobs": {}, "next_job": 3,
              "actors": {}, "named_actors": {"n": "deadbeef"}, "placement_groups": {}}
    st.save_blob(pack_tables(tables))
    assert st.load() == tables
    # corrupt file -> load returns None, never raises
    (tmp_path / "snap.msgpack").write_bytes(b"garbage")
    assert FileStorage(str(tmp_path / "snap.msgpack")).load() is None


@pytest.fixture()
def ft_cluster():
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    c = Cluster(
        initialize_head=True,
        head_node_args={"num_cpus": 4},
        enable_gcs_ft=True,
        _system_config={"health_check_failure_threshold": 3},
    )
    ray_tpu.init(address=c.address, num_cpus=0)
    yield c
    ray_tpu.shutdown()
    c.shutdown()


def test_gcs_restart_recovers_cluster(ft_cluster):
    """Named detached actor, KV (function exports), and node membership all
    survive a GCS crash + restart; new work schedules afterwards."""

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

    counter = Counter.options(name="survivor", lifetime="detached").remote()
    assert ray_tpu.get(counter.incr.remote(), timeout=60) == 1
    time.sleep(0.6)  # let the persist loop snapshot the actor record

    ft_cluster.crash_gcs()
    ft_cluster.restart_gcs()

    # Raylets re-register within a heartbeat period.
    ft_cluster.wait_for_nodes(2, timeout=30)  # head + driver node

    # The named actor record was restored; the actor process never died.
    handle = ray_tpu.get_actor("survivor")
    assert ray_tpu.get(handle.incr.remote(), timeout=60) == 2

    # New tasks schedule on the recovered cluster (function defs in KV).
    @ray_tpu.remote
    def after_restart():
        return "scheduled"

    assert ray_tpu.get(after_restart.remote(), timeout=90) == "scheduled"


def test_actor_death_during_gcs_outage_reported_after_restart(ft_cluster):
    """An actor worker that dies while the GCS is down must still be
    reported once the GCS returns (queued death reports), not restored as
    a ghost ALIVE record."""
    import os
    import signal

    @ray_tpu.remote
    class Victim:
        def pid(self):
            return os.getpid()

    victim = Victim.options(name="victim", lifetime="detached").remote()
    pid = ray_tpu.get(victim.pid.remote(), timeout=60)
    time.sleep(0.6)  # snapshot the ALIVE record

    ft_cluster.crash_gcs()
    os.kill(pid, signal.SIGKILL)  # dies while the GCS is down
    time.sleep(1.0)
    ft_cluster.restart_gcs()

    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        record = ft_cluster.gcs._actors.get(victim._actor_id.hex())
        if record is not None and record["state"] == "DEAD":
            break
        time.sleep(0.2)
    assert record is not None and record["state"] == "DEAD", record and record["state"]


def test_gcs_restart_without_ft_loses_state():
    """Control: with the default memory storage, a restarted GCS comes back
    empty (documents why enable_gcs_ft matters)."""
    if ray_tpu.is_initialized():
        ray_tpu.shutdown()
    c = Cluster(initialize_head=True, head_node_args={"num_cpus": 2})
    try:
        ray_tpu.init(address=c.address, num_cpus=0)

        @ray_tpu.remote
        class A:
            def ping(self):
                return "pong"

        A.options(name="gone", lifetime="detached").remote()
        time.sleep(0.5)
        c.crash_gcs()
        c.restart_gcs()
        c.wait_for_nodes(2, timeout=30)
        with pytest.raises(ValueError):
            ray_tpu.get_actor("gone")
    finally:
        ray_tpu.shutdown()
        c.shutdown()


def test_gcs_crash_during_actor_creation(ft_cluster):
    """The GCS dies WHILE actor creations are in flight: after restart,
    every creation either completes (restored PENDING records reschedule)
    or the caller gets a clean failure — never a silent hang (reference
    test_gcs_fault_tolerance.py actor-creation races)."""

    @ray_tpu.remote
    class Slow:
        def __init__(self):
            time.sleep(0.3)

        def ping(self):
            return "pong"

    actors = [Slow.options(num_cpus=0.1).remote() for _ in range(6)]
    time.sleep(0.15)  # mid-creation
    ft_cluster.crash_gcs()
    time.sleep(0.5)
    ft_cluster.restart_gcs()

    ok, dead = 0, 0
    for a in actors:
        try:
            assert ray_tpu.get(a.ping.remote(), timeout=120) == "pong"
            ok += 1
        except Exception:
            dead += 1
    # no hangs; the restored GCS must still be able to create NEW actors
    assert ok + dead == 6
    fresh = Slow.options(num_cpus=0.1).remote()
    assert ray_tpu.get(fresh.ping.remote(), timeout=120) == "pong"


def test_gcs_crash_during_pg_commit(ft_cluster):
    """The GCS dies in the middle of placement-group 2PC: after restart,
    creating placement groups works and the cluster's resources are not
    leaked by half-committed bundles."""
    from ray_tpu.util import placement_group, remove_placement_group

    pgs = [placement_group([{"CPU": 1}], strategy="PACK") for _ in range(3)]
    ft_cluster.crash_gcs()
    time.sleep(0.3)
    ft_cluster.restart_gcs()

    # Old PGs: ready or not, removal must not wedge anything.
    for pg in pgs:
        try:
            pg.wait(timeout_seconds=15)
        except Exception:
            pass
        try:
            remove_placement_group(pg)
        except Exception:
            pass
    # The full capacity must be allocatable again (no leaked reservations).
    fresh = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
    assert fresh.wait(timeout_seconds=60)
    remove_placement_group(fresh)


def test_gcs_crash_during_long_poll(ft_cluster, capfd):
    """A worker-log long-poll (driver side) survives a GCS restart: lines
    printed AFTER the restart actually reach the driver echo (cursor
    clamping on the restarted publisher), not just the task result."""

    @ray_tpu.remote
    def speak(tag):
        print(f"LOGLINE-{tag}")
        return tag

    assert ray_tpu.get(speak.remote("before"), timeout=60) == "before"
    ft_cluster.crash_gcs()
    time.sleep(0.3)
    ft_cluster.restart_gcs()
    assert ray_tpu.get(speak.remote("after"), timeout=90) == "after"
    # the driver's log-echo poller must deliver the post-restart line
    seen = ""
    deadline = time.time() + 30
    while "LOGLINE-after" not in seen and time.time() < deadline:
        time.sleep(0.5)
        out = capfd.readouterr()
        seen += out.out + out.err
    assert "LOGLINE-after" in seen, "post-restart worker log never reached the driver"
