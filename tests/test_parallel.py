"""Mesh/sharding/collectives tests on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import PartitionSpec as P

from ray_tpu.parallel import (
    MeshConfig,
    all_gather,
    all_reduce,
    create_mesh,
    logical_sharding,
    ppermute,
    reduce_scatter,
)
from ray_tpu.parallel.sharding import spec_for, DEFAULT_RULES


def test_mesh_resolve():
    cfg = MeshConfig(dp=-1, tp=2).resolve(8)
    assert cfg.dp == 4 and cfg.tp == 2
    with pytest.raises(ValueError):
        MeshConfig(dp=3).resolve(8)


def test_create_mesh_shapes():
    mesh = create_mesh(MeshConfig(dp=2, fsdp=2, tp=2))
    assert mesh.shape == {"dcn": 1, "pp": 1, "dp": 2, "fsdp": 2, "sp": 1, "ep": 1, "tp": 2}


def test_spec_for_dedup():
    # batch maps to (dcn, dp, fsdp); embed maps to fsdp -> no repeat fsdp
    spec = spec_for(("batch", "embed"), DEFAULT_RULES)
    assert spec == P(("dcn", "dp", "fsdp"),)


def test_dcn_multislice_mesh_train_step():
    """Multi-slice: dcn=2 x fsdp=2 x tp=2 — batch shards across slices and
    the sharded loss matches the unsharded model (CPU virtual devices)."""
    import dataclasses

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import PRESETS, init_params, loss_fn

    mesh = create_mesh(MeshConfig(dcn=2, fsdp=2, tp=2))
    assert mesh.shape["dcn"] == 2
    cfg = dataclasses.replace(PRESETS["debug"], dtype=jnp.float32, attn_impl="reference")
    params = init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.asarray(np.random.default_rng(0).integers(0, cfg.vocab_size, (4, 32)), jnp.int32)

    ref = float(loss_fn(params, {"tokens": tokens}, cfg))
    from ray_tpu.models.llama import param_axes
    from ray_tpu.parallel import shard_params

    sharded = shard_params(params, param_axes(cfg), mesh)
    out = float(jax.jit(
        lambda p, t: loss_fn(p, {"tokens": t}, cfg, mesh=mesh)
    )(sharded, tokens))
    np.testing.assert_allclose(out, ref, rtol=1e-4)


def test_logical_sharding_places_array():
    mesh = create_mesh(MeshConfig(dp=4, tp=2))
    s = logical_sharding(mesh, ("batch", "embed_act"))
    x = jax.device_put(jnp.zeros((8, 16)), s)
    assert x.sharding.is_equivalent_to(s, ndim=2)


def test_collectives_inside_shard_map():
    mesh = create_mesh(MeshConfig(dp=8))
    x = jnp.arange(8.0)

    def body(xs):
        return all_reduce(xs, "dp", op="sum")

    out = shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))(x)
    np.testing.assert_allclose(out, np.full(8, 28.0))


def test_all_gather_and_reduce_scatter_roundtrip():
    mesh = create_mesh(MeshConfig(dp=8))
    x = jnp.arange(16.0).reshape(8, 2)

    def body(xs):
        full = all_gather(xs, "dp")          # [8, 2] on every device
        return reduce_scatter(full, "dp")     # back to [1, 2], scaled by nothing

    out = shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))(x)
    # reduce_scatter(all_gather(x)) = sum over devices of each row's copy = 8*x? No:
    # all_gather replicates the full array; psum_scatter sums the 8 replicas and
    # hands each device its slice -> 8 * x.
    np.testing.assert_allclose(out, 8.0 * np.arange(16.0).reshape(8, 2))


def test_ppermute_ring():
    mesh = create_mesh(MeshConfig(sp=8))
    x = jnp.arange(8.0)

    def body(xs):
        return ppermute(xs, "sp", shift=1)

    out = shard_map(body, mesh=mesh, in_specs=P("sp"), out_specs=P("sp"))(x)
    np.testing.assert_allclose(out, np.roll(np.arange(8.0), 1))
