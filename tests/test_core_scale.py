"""Many-raylet scale harness + actor-storm chaos (ISSUE 14).

Tier-1 runs a 4-raylet / shrunk-storm variant of exactly the code path
the full-size bench drives (``cli bench core --scale``); the full
8-raylet / 100k-task / 1k-actor acceptance run is marked ``slow``.
"""

from __future__ import annotations

import time

import pytest

from ray_tpu.core.config import get_config


@pytest.fixture()
def _fresh_cluster_slot():
    """The scale harness owns init/shutdown of a multi-raylet cluster:
    tear down any shared test cluster first, and leave nothing behind."""
    import ray_tpu

    try:
        ray_tpu.shutdown()
    except Exception:
        pass
    yield
    try:
        ray_tpu.shutdown()
    except Exception:
        pass


def test_scale_harness_smoke(_fresh_cluster_slot):
    """4-raylet shrunk variant: tasks spill across raylets, the actor
    storm lands on zygote pools, every core_scale_* cell is recorded."""
    from ray_tpu._core_scale_bench import run_core_scale_bench

    out = run_core_scale_bench(raylets=4, num_tasks=600, num_actors=24)
    assert out["core_scale_raylets_cfg"] == 4
    assert out["core_scale_tasks_per_s"] > 0
    assert out["core_scale_actor_creations_per_s"] > 0
    # the storm actually exercised the pool path on this box
    assert 0.0 <= out.get("core_scale_pooled_spawn_frac", 0.0) <= 1.0


def test_actor_storm_chaos_green(_fresh_cluster_slot):
    """Reduced actor-storm chaos smoke (the tier-1 half of the 1k-actor
    acceptance run): 4 raylets, a creation storm under the bundled
    `actor-storm` plan (kill-on-Nth-lease + mid-storm preemption notice),
    RecoveryVerifier green, zygote pools drained/refilled to baseline."""
    import ray_tpu
    from ray_tpu import chaos
    from ray_tpu.cluster_utils import Cluster

    cfg = get_config()
    saved = {k: getattr(cfg, k) for k in (
        "worker_register_timeout_s", "lease_orphan_timeout_s",
        "preempt_grace_s", "zygote_pool_size", "zygote_pool_refill_batch",
        "health_check_period_ms")}
    cfg.worker_register_timeout_s = 15.0
    cfg.lease_orphan_timeout_s = 2.0
    cfg.preempt_grace_s = 2.0
    cfg.zygote_pool_size = 4
    cfg.zygote_pool_refill_batch = 4
    # Fast heartbeats: the plan's preempt_slice rule fires on the
    # targeted node's 3rd heartbeat tick — it must land INSIDE the
    # shrunk storm window, not 3 wall-seconds into a 5-second test.
    cfg.health_check_period_ms = 250
    cluster = Cluster(initialize_head=False)
    try:
        for _ in range(4):
            cluster.add_node(wait=False, num_cpus=40)
        cluster.wait_for_nodes(4)
        ray_tpu.init(address=cluster.address, num_cpus=0)

        @ray_tpu.remote(max_restarts=3)
        class Storm:
            def ping(self, i):
                return i

        @ray_tpu.remote
        def warm():
            return None

        ray_tpu.get([warm.remote() for _ in range(16)], timeout=120)
        time.sleep(1.0)
        baseline_pools = _pool_sizes(cluster)

        def workload():
            actors = [Storm.remote() for _ in range(100)]
            ok = failures = 0
            for a in actors:
                try:
                    ray_tpu.get(a.ping.remote(1), timeout=120)
                    ok += 1
                except Exception:
                    failures += 1
            for a in actors:
                try:
                    ray_tpu.kill(a)
                except Exception:
                    pass
            del actors
            return {"ok": ok, "failures": failures}

        report = chaos.run_plan("actor-storm", seed=14, workload=workload,
                                verify_timeout_s=120)
        assert report["verify"]["ok"], report["verify"]["violations"]
        # the plan actually fired: worker kills and (4 nodes exist) the
        # mid-storm preemption notice
        assert report["injections"].get("kill_worker:kill_worker", 0) >= 1
        assert report["injections"].get("preempt_slice:preempt_slice", 0) >= 1
        # storm survived the chaos: restarts absorbed the kills
        assert report["workload"]["ok"] >= 95, report["workload"]

        # Zygote pools drained back to baseline: no dedicated workers
        # left, idle pools back at their per-key targets on every
        # NON-DRAINING raylet (the preempted node is drained by design).
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if _pools_at_baseline(cluster) is None:
                break
            time.sleep(0.5)
        assert _pools_at_baseline(cluster) is None, (
            _pools_at_baseline(cluster), baseline_pools,
            _pool_sizes(cluster))
    finally:
        for k, v in saved.items():
            setattr(cfg, k, v)
        try:
            ray_tpu.shutdown()
        except Exception:
            pass
        cluster.shutdown()


def _pool_sizes(cluster) -> dict:
    out = {}
    for raylet in cluster.nodes:
        idle, _starting = raylet._pool_counts("")
        out[raylet.node_id.hex()] = idle
    return out


def _pools_at_baseline(cluster) -> str | None:
    """None when every live raylet is back at baseline; else a reason."""
    cfg = get_config()
    target = max(cfg.num_prestart_workers, cfg.zygote_pool_size)
    for raylet in cluster.nodes:
        if raylet._draining or raylet._shutdown:
            continue  # preempted mid-storm by the plan: drained by design
        nid = raylet.node_id.hex()[:8]
        stuck = [(w.worker_id[:8], w.actor_id[:8])
                 for w in raylet._workers.values() if w.state == "dedicated"]
        if stuck:
            return f"node {nid}: leaked dedicated workers {stuck}"
        idle, starting = raylet._pool_counts("")
        if idle + starting < target:  # drained: never refilled
            return f"node {nid}: pool {idle}+{starting} < target {target}"
    return None


@pytest.mark.slow
def test_scale_harness_full_acceptance(_fresh_cluster_slot):
    """The 10x-PR-6 acceptance run: >= 8 raylets, 100k tasks, 1k actors,
    plus the actor-storm chaos phase — hours-class on a laptop, so it
    rides the slow marker; ``cli bench core --scale`` runs the same code
    with env-tunable sizes."""
    from ray_tpu._core_scale_bench import run_core_scale_bench

    out = run_core_scale_bench(chaos=True)
    assert out["core_scale_raylets_cfg"] >= 8
    assert out["core_scale_tasks_cfg"] >= 100_000
    assert out["core_scale_actors_cfg"] >= 1000
    assert out["core_scale_tasks_per_s"] > 0
    assert out["core_scale_actor_creations_per_s"] > 0
    assert out.get("core_scale_chaos_verify_ok") == 1.0
