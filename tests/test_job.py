"""Job submission + runtime_env.

Reference surfaces: ``dashboard/modules/job/job_manager.py`` (submit,
status FSM, stop, logs) and runtime_env ``working_dir``/``env_vars``
(``python/ray/_private/runtime_env/``).
"""

import os
import textwrap
import time

import pytest

import ray_tpu
from ray_tpu.job import JobStatus, JobSubmissionClient


def test_job_succeeds_and_logs(ray_cluster):
    client = JobSubmissionClient()
    jid = client.submit_job(entrypoint="echo hello-from-job && echo line2")
    status = client.wait_until_terminal(jid, timeout=60)
    assert status == JobStatus.SUCCEEDED
    logs = client.get_job_logs(jid)
    assert "hello-from-job" in logs and "line2" in logs
    jobs = {j.submission_id: j for j in client.list_jobs()}
    assert jobs[jid].status == JobStatus.SUCCEEDED
    assert jobs[jid].end_time >= jobs[jid].start_time > 0


def test_job_failure_reported(ray_cluster):
    client = JobSubmissionClient()
    jid = client.submit_job(entrypoint="python -c 'import sys; sys.exit(3)'")
    assert client.wait_until_terminal(jid, timeout=60) == JobStatus.FAILED
    assert "code 3" in client.get_job_info(jid).message


def test_job_env_vars_and_working_dir(ray_cluster, tmp_path):
    (tmp_path / "helper_mod.py").write_text("VALUE = 'from-working-dir'\n")
    script = textwrap.dedent(
        """
        import os, helper_mod
        print("env:", os.environ["MY_JOB_VAR"])
        print("mod:", helper_mod.VALUE)
        """
    )
    (tmp_path / "main.py").write_text(script)
    client = JobSubmissionClient()
    jid = client.submit_job(
        entrypoint="python main.py",
        runtime_env={"working_dir": str(tmp_path), "env_vars": {"MY_JOB_VAR": "42"}},
    )
    assert client.wait_until_terminal(jid, timeout=60) == JobStatus.SUCCEEDED
    logs = client.get_job_logs(jid)
    assert "env: 42" in logs and "mod: from-working-dir" in logs


def test_job_driver_connects_to_cluster(ray_cluster, tmp_path):
    """The entrypoint is a real cluster driver: it connects via
    RAY_TPU_ADDRESS and runs a remote task on the shared cluster."""
    script = textwrap.dedent(
        """
        import ray_tpu
        ray_tpu.init()  # picks up RAY_TPU_ADDRESS
        @ray_tpu.remote
        def f():
            return "task-ran-on-cluster"
        print(ray_tpu.get(f.remote(), timeout=60))
        ray_tpu.shutdown()
        """
    )
    (tmp_path / "driver.py").write_text(script)
    client = JobSubmissionClient()
    jid = client.submit_job(
        entrypoint="python driver.py", runtime_env={"working_dir": str(tmp_path)}
    )
    status = client.wait_until_terminal(jid, timeout=120)
    logs = client.get_job_logs(jid)
    assert status == JobStatus.SUCCEEDED, logs
    assert "task-ran-on-cluster" in logs


def test_job_stop(ray_cluster):
    client = JobSubmissionClient()
    jid = client.submit_job(entrypoint="sleep 60")
    deadline = time.monotonic() + 30
    while client.get_job_status(jid) == JobStatus.PENDING:
        assert time.monotonic() < deadline
        time.sleep(0.1)
    assert client.stop_job(jid)
    assert client.wait_until_terminal(jid, timeout=30) == JobStatus.STOPPED


def test_task_runtime_env_working_dir(ray_cluster, tmp_path):
    """Per-task runtime_env working_dir: the worker imports modules from it."""
    (tmp_path / "task_helper.py").write_text("def ping():\n    return 'imported'\n")

    @ray_tpu.remote(runtime_env={"working_dir": str(tmp_path)})
    def uses_helper():
        import task_helper

        return task_helper.ping() + ":" + os.path.basename(os.getcwd())

    out = ray_tpu.get(uses_helper.remote(), timeout=120)
    assert out == f"imported:{os.path.basename(tmp_path)}"
