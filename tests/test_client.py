"""Ray Client (`ray://`): remote drivers proxied through a cluster-side
server (reference ``python/ray/util/client/__init__.py:200``)."""

import subprocess
import sys
import textwrap

import pytest

import ray_tpu


@pytest.fixture(autouse=True)
def _cluster(ray_cluster):
    yield


def test_client_mode_end_to_end():
    """A SEPARATE python process connects via ray:// and uses the normal
    API: tasks, puts/gets, ref args, actors, named actors, wait."""
    from ray_tpu.util.client import ClientServer

    server = ClientServer(host="127.0.0.1", port=0)
    try:
        # a named actor created cluster-side, visible to the client
        @ray_tpu.remote
        class Registry:
            def __init__(self):
                self.items = []

            def add(self, x):
                self.items.append(x)
                return len(self.items)

        reg = Registry.options(name="client_registry", lifetime="detached").remote()
        assert ray_tpu.get(reg.add.remote("seed"), timeout=60) == 1

        code = textwrap.dedent(f"""
            import ray_tpu
            ray_tpu.init(address="ray://{server.address}")

            @ray_tpu.remote
            def double(x):
                return x * 2

            # tasks + ref args
            a = double.remote(21)
            b = double.remote(a)
            assert ray_tpu.get(b, timeout=120) == 84

            # put/get + wait
            ref = ray_tpu.put({{"k": [1, 2, 3]}})
            assert ray_tpu.get(ref, timeout=60) == {{"k": [1, 2, 3]}}
            ready, not_ready = ray_tpu.wait([a, b], num_returns=2, timeout=60)
            assert len(ready) == 2 and not not_ready

            # client-created actor
            @ray_tpu.remote
            class Counter:
                def __init__(self):
                    self.n = 0
                def inc(self, k):
                    self.n += k
                    return self.n
            c = Counter.remote()
            assert ray_tpu.get(c.inc.remote(5), timeout=120) == 5
            assert ray_tpu.get(c.inc.remote(2), timeout=60) == 7

            # named actor created by the CLUSTER driver
            reg = ray_tpu.get_actor("client_registry")
            assert ray_tpu.get(reg.add.remote("from-client"), timeout=60) == 2

            # error propagation
            @ray_tpu.remote(max_retries=0)
            def boom():
                raise ValueError("client boom")
            try:
                ray_tpu.get(boom.remote(), timeout=60)
                raise SystemExit("no error raised")
            except ValueError as e:
                assert "client boom" in str(e)

            ray_tpu.shutdown()
            print("CLIENT_OK")
        """)
        proc = subprocess.run([sys.executable, "-c", code], capture_output=True,
                              text=True, timeout=300, cwd="/root/repo")
        assert "CLIENT_OK" in proc.stdout, proc.stderr[-2000:]

        # cluster-side state mutated by the client is visible here
        assert ray_tpu.get(reg.add.remote("post"), timeout=60) == 3
    finally:
        server.stop()
        # detached actors outlive handles: kill explicitly or the held CPU
        # starves every later test in the shared cluster
        try:
            ray_tpu.kill(reg)
        except Exception:
            pass
