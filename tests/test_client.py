"""Ray Client (`ray://`): remote drivers proxied through a cluster-side
server (reference ``python/ray/util/client/__init__.py:200``)."""

import subprocess
import sys
import textwrap

import pytest

import ray_tpu


@pytest.fixture(autouse=True)
def _cluster(ray_cluster):
    yield


def test_client_mode_end_to_end():
    """A SEPARATE python process connects via ray:// and uses the normal
    API: tasks, puts/gets, ref args, actors, named actors, wait."""
    from ray_tpu.util.client import ClientServer

    server = ClientServer(host="127.0.0.1", port=0)
    try:
        # a named actor created cluster-side, visible to the client
        @ray_tpu.remote
        class Registry:
            def __init__(self):
                self.items = []

            def add(self, x):
                self.items.append(x)
                return len(self.items)

        reg = Registry.options(name="client_registry", lifetime="detached").remote()
        assert ray_tpu.get(reg.add.remote("seed"), timeout=60) == 1

        code = textwrap.dedent(f"""
            import ray_tpu
            ray_tpu.init(address="ray://{server.address}")

            @ray_tpu.remote
            def double(x):
                return x * 2

            # tasks + ref args
            a = double.remote(21)
            b = double.remote(a)
            assert ray_tpu.get(b, timeout=120) == 84

            # put/get + wait
            ref = ray_tpu.put({{"k": [1, 2, 3]}})
            assert ray_tpu.get(ref, timeout=60) == {{"k": [1, 2, 3]}}
            ready, not_ready = ray_tpu.wait([a, b], num_returns=2, timeout=60)
            assert len(ready) == 2 and not not_ready

            # client-created actor
            @ray_tpu.remote
            class Counter:
                def __init__(self):
                    self.n = 0
                def inc(self, k):
                    self.n += k
                    return self.n
            c = Counter.remote()
            assert ray_tpu.get(c.inc.remote(5), timeout=120) == 5
            assert ray_tpu.get(c.inc.remote(2), timeout=60) == 7

            # named actor created by the CLUSTER driver
            reg = ray_tpu.get_actor("client_registry")
            assert ray_tpu.get(reg.add.remote("from-client"), timeout=60) == 2

            # error propagation
            @ray_tpu.remote(max_retries=0)
            def boom():
                raise ValueError("client boom")
            try:
                ray_tpu.get(boom.remote(), timeout=60)
                raise SystemExit("no error raised")
            except ValueError as e:
                assert "client boom" in str(e)

            ray_tpu.shutdown()
            print("CLIENT_OK")
        """)
        proc = subprocess.run([sys.executable, "-c", code], capture_output=True,
                              text=True, timeout=300, cwd="/root/repo")
        assert "CLIENT_OK" in proc.stdout, proc.stderr[-2000:]

        # cluster-side state mutated by the client is visible here
        assert ray_tpu.get(reg.add.remote("post"), timeout=60) == 3
    finally:
        server.stop()
        # detached actors outlive handles: kill explicitly or the held CPU
        # starves every later test in the shared cluster
        try:
            ray_tpu.kill(reg)
        except Exception:
            pass


def test_client_streaming_generator():
    """num_returns="streaming" works over ray://: the proxy holds the
    real ObjectRefGenerator, the client iterates refs one round trip at
    a time, and close() cancels the producer."""
    from ray_tpu.util.client import ClientServer

    server = ClientServer(host="127.0.0.1", port=0)
    try:
        code = textwrap.dedent(f"""
            import ray_tpu
            ray_tpu.init(address="ray://{server.address}")

            @ray_tpu.remote(num_returns="streaming")
            def counter(n):
                for i in range(n):
                    yield i * 10

            gen = counter.remote(5)
            values = [ray_tpu.get(ref, timeout=60) for ref in gen]
            assert values == [0, 10, 20, 30, 40], values

            # early close: iteration stops, no error
            gen2 = counter.remote(1000)
            first = ray_tpu.get(next(gen2), timeout=60)
            assert first == 0
            gen2.close()

            # actor streaming method over the client boundary
            @ray_tpu.remote
            class Streamer:
                def gen(self, n):
                    for i in range(n):
                        yield i + 100
            st = Streamer.remote()
            g = st.gen.options(num_returns="streaming").remote(3)
            vals = [ray_tpu.get(r, timeout=60) for r in g]
            assert vals == [100, 101, 102], vals

            ray_tpu.shutdown()
            print("STREAM_OK")
        """)
        proc = subprocess.run([sys.executable, "-c", code], capture_output=True,
                              text=True, timeout=300, cwd="/root/repo")
        assert "STREAM_OK" in proc.stdout, proc.stderr[-2000:]
    finally:
        server.stop()


def test_client_actor_method_concurrency_group():
    """Regression (round-5 breakage): ``ActorMethod.remote`` always passes
    ``concurrency_group=`` to ``submit_actor_task`` — the client worker
    must accept AND forward it, including an explicit group selected via
    ``.options(concurrency_group=...)``."""
    from ray_tpu.util.client import ClientServer

    server = ClientServer(host="127.0.0.1", port=0)
    try:
        code = textwrap.dedent(f"""
            import ray_tpu
            ray_tpu.init(address="ray://{server.address}")

            @ray_tpu.remote(concurrency_groups={{"io": 2}})
            class Grouped:
                def plain(self):
                    return "ok"
                def fetch(self):
                    return "io-ok"

            g = Grouped.remote()
            assert ray_tpu.get(g.plain.remote(), timeout=120) == "ok"
            assert ray_tpu.get(
                g.fetch.options(concurrency_group="io").remote(),
                timeout=60) == "io-ok"
            ray_tpu.shutdown()
            print("CG_OK")
        """)
        proc = subprocess.run([sys.executable, "-c", code], capture_output=True,
                              text=True, timeout=300, cwd="/root/repo")
        assert "CG_OK" in proc.stdout, proc.stderr[-2000:]
    finally:
        server.stop()


def test_client_crash_reaps_session():
    """A client that dies WITHOUT disconnecting stops pinging; the proxy
    reaps the session: its actors are killed and its job finishes
    (reference: client reconnect-grace expiry)."""
    import time

    from ray_tpu.core.config import get_config
    from ray_tpu.core.worker import global_worker
    from ray_tpu.util.client import ClientServer

    old = get_config().client_session_timeout_s
    get_config().client_session_timeout_s = 3.0
    server = ClientServer(host="127.0.0.1", port=0)
    try:
        code = textwrap.dedent(f"""
            import os
            import ray_tpu
            ray_tpu.init(address="ray://{server.address}")

            @ray_tpu.remote
            class Held:
                def ping(self):
                    return "alive"

            h = Held.remote()
            assert ray_tpu.get(h.ping.remote(), timeout=120) == "alive"
            print("ACTOR_UP")
            os._exit(1)  # crash: no disconnect, no more pings
        """)
        proc = subprocess.run([sys.executable, "-c", code], capture_output=True,
                              text=True, timeout=300, cwd="/root/repo")
        assert "ACTOR_UP" in proc.stdout, proc.stderr[-2000:]

        # the per-client job was registered
        worker = global_worker()
        jobs = worker._gcs_call("GetAllJobs", {})["jobs"]
        client_jobs = [j for j in jobs
                       if str(j.get("driver_address", "")).startswith("ray-client:")]
        assert client_jobs, jobs

        # after the timeout, the session is reaped: actor dead, job done
        deadline = time.time() + 30
        while time.time() < deadline:
            actors = worker._gcs_call("ListActors", {}).get("actors", [])
            held = [a for a in actors
                    if a.get("class_name") == "Held" and a.get("state") == "ALIVE"]
            jobs = worker._gcs_call("GetAllJobs", {})["jobs"]
            cj = [j for j in jobs
                  if str(j.get("driver_address", "")).startswith("ray-client:")]
            if not held and all(j.get("state") == "FINISHED" for j in cj):
                break
            time.sleep(0.5)
        assert not held, f"session actor survived the reap: {held}"
        assert all(j.get("state") == "FINISHED" for j in cj), cj
    finally:
        get_config().client_session_timeout_s = old
        server.stop()


def test_client_session_expiry_fails_fast():
    """A client partitioned past the session timeout is NOT silently
    resurrected: the proxy rejects its next call with 'session expired'
    instead of letting it run against destroyed state."""
    from ray_tpu.core.config import get_config
    from ray_tpu.util.client import ClientServer

    cfg = get_config()
    old_t, old_p = cfg.client_session_timeout_s, cfg.client_ping_interval_s
    cfg.client_session_timeout_s = 2.0
    cfg.client_ping_interval_s = 30.0  # the client will not ping in time
    server = ClientServer(host="127.0.0.1", port=0)
    try:
        code = textwrap.dedent(f"""
            import time
            import ray_tpu
            ray_tpu.init(address="ray://{server.address}")
            ray_tpu.put(1)
            time.sleep(7)  # reaped server-side meanwhile
            try:
                ray_tpu.put(2)
                raise SystemExit("no error raised")
            except Exception as e:
                assert "session expired" in str(e), str(e)
            print("EXPIRED_OK")
        """)
        proc = subprocess.run([sys.executable, "-c", code], capture_output=True,
                              text=True, timeout=300, cwd="/root/repo")
        assert "EXPIRED_OK" in proc.stdout, proc.stderr[-2000:]
    finally:
        cfg.client_session_timeout_s = old_t
        cfg.client_ping_interval_s = old_p
        server.stop()
