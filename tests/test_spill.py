"""Object spilling, primary-copy pinning, and the node memory monitor.

Reference behaviors being matched (TPU-native redesign, not a port):
  - primary copies are pinned and never silently evicted
    (src/ray/raylet/local_object_manager.h:110);
  - under memory pressure pinned objects spill to disk and restore on Get
    (python/ray/_private/external_storage.py:72);
  - the memory watcher kills the newest retriable lease instead of letting
    the OS OOM-kill the node (src/ray/common/memory_monitor.h:52,
    worker_killing_policy.cc).
"""

import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.native.store import ShmStore


def _raylet():
    from ray_tpu.core import api

    return api._node.raylet


# ---------------------------------------------------------------- store unit


def test_pinned_object_survives_eviction(tmp_path):
    store = ShmStore(str(tmp_path / "arena"), 1 << 20)
    a, b = b"a" * 8, b"b" * 8
    store.put_sealed(a, b"payload-a")
    store.put_sealed(b, b"payload-b")
    store.pin(a)
    store.evict(1 << 20)
    assert store.contains(a) == 2  # pinned: survives
    assert store.contains(b) == 0  # unpinned: evicted
    store.unpin(a)
    store.evict(1 << 20)
    assert store.contains(a) == 0
    store.close()


def test_refcounted_object_not_evictable(tmp_path):
    store = ShmStore(str(tmp_path / "arena"), 1 << 20)
    a = b"a" * 8
    store.put_sealed(a, b"payload")
    store.add_ref(a)
    assert store.ref_count(a) == 1
    store.evict(1 << 20)
    assert store.contains(a) == 2
    store.release(a)
    store.evict(1 << 20)
    assert store.contains(a) == 0
    store.close()


# ------------------------------------------------------------ spill e2e


def test_ingest_2x_store_capacity_without_data_loss():
    """VERDICT #9 acceptance: put 2x the store's capacity while keeping every
    ObjectRef live; nothing may be lost — cold primaries spill to disk and
    restore on get."""
    ray_tpu.shutdown()
    capacity = 8 * 1024 * 1024
    ray_tpu.init(num_cpus=2, object_store_memory=capacity)
    try:
        n, size = 16, 1024 * 1024  # 16 MiB total = 2x capacity
        arrays = [np.full(size // 8, i, dtype=np.int64) for i in range(n)]
        refs = [ray_tpu.put(a) for a in arrays]

        raylet = _raylet()
        assert raylet._spilled, "expected spilling at 2x capacity"
        debug = {"spilled_bytes_total": raylet._spilled_bytes_total}
        assert debug["spilled_bytes_total"] > 0

        for i, ref in enumerate(refs):
            out = ray_tpu.get(ref)
            np.testing.assert_array_equal(out, arrays[i])
        assert raylet._restored_bytes_total > 0
    finally:
        ray_tpu.shutdown()


def test_task_returns_spill_and_restore():
    """Task returns are sealed through the raylet and therefore pinned;
    overflowing the store with returns must spill, not drop them."""
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2, object_store_memory=8 * 1024 * 1024)
    try:

        @ray_tpu.remote
        def make(i):
            return np.full(256 * 1024, i, dtype=np.int64)  # 2 MiB each

        refs = [make.remote(i) for i in range(8)]  # 16 MiB total
        for i, ref in enumerate(refs):
            np.testing.assert_array_equal(ray_tpu.get(ref), np.full(256 * 1024, i, dtype=np.int64))
    finally:
        ray_tpu.shutdown()


def test_spilled_state_visible_in_list_objects():
    import asyncio

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=1, object_store_memory=8 * 1024 * 1024)
    try:
        refs = [ray_tpu.put(np.zeros(1024 * 1024 // 8, dtype=np.int64)) for _ in range(12)]
        raylet = _raylet()
        assert raylet._spilled
        listing = asyncio.run(raylet.handle_ListObjects({}))
        states = {o["object_id"]: o["state"] for o in listing["objects"]}
        assert "SPILLED" in states.values(), f"no SPILLED state in {set(states.values())}"
        assert "SEALED" in states.values()
        del refs
    finally:
        ray_tpu.shutdown()


def test_live_zero_copy_view_survives_spill_pressure():
    """A deserialized array aliases the shm arena; while it is alive the
    raylet holds a read ref (plasma Buffer lifetime semantics), so spilling
    under pressure must neither corrupt nor relocate it."""
    import gc

    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=1, object_store_memory=8 * 1024 * 1024)
    try:
        src = np.arange(256 * 1024, dtype=np.int64)  # 2 MiB
        ref0 = ray_tpu.put(src)
        out0 = ray_tpu.get(ref0)  # zero-copy view into the arena

        # Flood the store with 2x capacity: everything spillable spills.
        refs = [ray_tpu.put(np.zeros(1024 * 1024 // 8, dtype=np.int64)) for _ in range(16)]
        raylet = _raylet()
        assert raylet._spilled
        np.testing.assert_array_equal(out0, src)  # view never corrupted
        assert ref0.id().binary() not in raylet._spilled

        oid = ref0.id().binary()
        del out0
        gc.collect()
        deadline = time.monotonic() + 10
        while raylet.store.ref_count(oid) > 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        # Released: refcount drops to 0 — or to -1 (absent) if the proactive
        # spiller already moved the now-unreferenced object to disk.
        assert raylet.store.ref_count(oid) <= 0, "read ref leaked after view GC"
        if raylet.store.ref_count(oid) == -1:
            assert oid in raylet._spilled, "object vanished instead of spilling"
        np.testing.assert_array_equal(ray_tpu.get(ref0), src)  # still retrievable
        del refs
    finally:
        ray_tpu.shutdown()


# ----------------------------------------------------------- memory monitor


def test_oom_killer_kills_newest_retriable_lease_and_task_retries(tmp_path):
    ray_tpu.shutdown()
    ray_tpu.init(num_cpus=2)
    try:
        raylet = _raylet()
        fired = []

        def fake_usage():
            if not fired and any(
                w.state == "leased" and w.retriable for w in raylet._workers.values()
            ):
                fired.append(1)
                return 0.99
            return 0.0

        raylet._memory_usage_fn = fake_usage

        marker = str(tmp_path / "attempts")

        @ray_tpu.remote(max_retries=2)
        def flaky():
            with open(marker, "a") as f:
                f.write("x")
            attempts = os.path.getsize(marker)
            if attempts == 1:
                time.sleep(10)  # killed by the memory monitor mid-sleep
            return 42

        result = ray_tpu.get(flaky.remote(), timeout=60)
        assert result == 42
        assert fired, "memory monitor never fired"
        assert os.path.getsize(marker) >= 2, "task was not retried"
    finally:
        ray_tpu.shutdown()
