"""Experiment harness for train-step throughput tuning (not the official bench)."""
import functools, json, sys, time
import jax, jax.numpy as jnp
import optax

from ray_tpu.models import PRESETS, init_params, loss_fn, param_axes
from ray_tpu.models import llama as llama_mod
from ray_tpu.parallel import MeshConfig, create_mesh
from ray_tpu.parallel.sharding import shard_params

def run(preset="llama3-1b", batch=8, seq=2048, chunk=512, remat="full", opt_name="adafactor", steps=8):
    n_dev = len(jax.devices())
    print("device:", jax.devices()[0].device_kind, file=sys.stderr)
    mesh = create_mesh(MeshConfig(dp=n_dev))
    cfg = PRESETS[preset]
    import dataclasses
    if remat == "none":
        cfg = dataclasses.replace(cfg, remat=False)
    elif remat in ("dots", "attn"):
        cfg = dataclasses.replace(cfg, remat=True, remat_policy=remat)
    if getattr(run, "_attn", None):
        cfg = dataclasses.replace(cfg, attn_impl=run._attn)
    import os as _os
    bq, bk = _os.environ.get("FLASH_BQ"), _os.environ.get("FLASH_BK")
    if bq or bk:
        from ray_tpu.ops import attention as _att
        import functools as _ft
        orig = _att.flash_attention
        _att_wrapped = _ft.partial(orig, block_q=int(bq or 512), block_k=int(bk or 512))
        llama_mod.flash_attention = _att_wrapped
    params = init_params(cfg, jax.random.PRNGKey(0))
    params = shard_params(params, param_axes(cfg), mesh)
    opt = optax.adafactor(1e-3) if opt_name == "adafactor" else optax.adamw(1e-3)
    opt_state = jax.jit(opt.init)(params)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch * n_dev, seq), 0, cfg.vocab_size)
    b = {"tokens": tokens}

    mode = getattr(run, "_mode", "step")
    if mode == "hidden":
        from ray_tpu.models.llama import forward_hidden
        @jax.jit
        def train_step(params, opt_state, b):
            h = forward_hidden(params, b["tokens"], cfg, mesh=mesh)
            return params, opt_state, jnp.sum(h).astype(jnp.float32)
    elif mode == "fwd":
        @jax.jit
        def train_step(params, opt_state, b):
            return params, opt_state, loss_fn(params, b, cfg, mesh=mesh, chunk_tokens=chunk)
    elif mode == "grad":
        @jax.jit
        def train_step(params, opt_state, b):
            loss, grads = jax.value_and_grad(lambda p: loss_fn(p, b, cfg, mesh=mesh, chunk_tokens=chunk))(params)
            return params, opt_state, loss + sum(jnp.sum(g).astype(jnp.float32) * 0 for g in jax.tree_util.tree_leaves(grads))
    elif mode == "noembedgrad":
        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def train_step(params, opt_state, b):
            def lf(p):
                p = dict(p); p["embed"] = jax.lax.stop_gradient(p["embed"])
                return loss_fn(p, b, cfg, mesh=mesh, chunk_tokens=chunk)
            loss, grads = jax.value_and_grad(lf)(params)
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss
    else:
        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def train_step(params, opt_state, b):
            loss, grads = jax.value_and_grad(lambda p: loss_fn(p, b, cfg, mesh=mesh, chunk_tokens=chunk))(params)
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

    for _ in range(2):
        params, opt_state, loss = train_step(params, opt_state, b)
    float(jax.device_get(loss))
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = train_step(params, opt_state, b)
    float(jax.device_get(loss))
    dt = time.perf_counter() - t0
    tps = batch * seq * steps / dt
    # 6N model flops (layers + lm_head + embed-as-matmul excluded)
    c = cfg
    n_params = c.n_layers * (c.hidden * c.head_dim * (c.n_heads * 2 + c.n_kv_heads * 2) + 3 * c.hidden * c.intermediate) + c.hidden * c.vocab_size
    attn_flops = 6 * c.n_layers * c.n_heads * c.head_dim * seq  # per token, causal ~ /2*... keep simple 6*L*H*D*S/2*2
    flops_per_tok = 6 * n_params + attn_flops
    mfu = tps * flops_per_tok / 197e12
    print(json.dumps({"preset": preset, "batch": batch, "chunk": chunk, "remat": remat, "opt": opt_name,
                      "mode": mode, "tok_s": round(tps, 1), "mfu": round(mfu, 4)}))

if __name__ == "__main__":
    import argparse
    p = argparse.ArgumentParser()
    p.add_argument("--preset", default="llama3-1b")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=2048)
    p.add_argument("--chunk", type=int, default=512)
    p.add_argument("--remat", default="full")
    p.add_argument("--opt", default="adafactor")
    p.add_argument("--attn", default="")
    p.add_argument("--mode", default="step")
    a = p.parse_args()
    if a.attn:
        run._attn = a.attn
    run._mode = a.mode
    run(a.preset, a.batch, a.seq, a.chunk, a.remat, a.opt)
