"""Workflow engine: step DAG -> checkpointed cluster execution.

Reference: ``python/ray/workflow/api.py`` (run/resume),
``workflow_executor.py`` (step scheduling), ``workflow_storage.py``
(checkpoint layout). Redesign: steps persist to a local/NFS directory
as pickled results keyed by deterministic step ids (DFS order + name);
the executor is a synchronous driver loop — workflow control flow does
not need an actor of its own at this scale, and crash recovery falls
out of storage alone.
"""

from __future__ import annotations

import json
import os
import pickle
import time
from typing import Any, Callable

import cloudpickle

_DEFAULT_STORAGE = os.path.expanduser("~/.ray_tpu_workflows")

STATUS_RUNNING = "RUNNING"
STATUS_SUCCESSFUL = "SUCCESSFUL"
STATUS_FAILED = "FAILED"


class StepNode:
    def __init__(self, fn: Callable, args: tuple, kwargs: dict, name: str | None = None):
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.name = name or getattr(fn, "__name__", "step")

    def options(self, name: str) -> "StepNode":
        return StepNode(self.fn, self.args, self.kwargs, name)


def step(fn: Callable):
    """``workflow.step(fn)(*args)`` builds a StepNode; args may contain
    other StepNodes (upstream dependencies)."""

    def bind(*args, **kwargs) -> StepNode:
        return StepNode(fn, args, kwargs)

    return bind


class _Storage:
    def __init__(self, base: str, workflow_id: str, create: bool = True):
        self.dir = os.path.join(base, workflow_id)
        if create:
            os.makedirs(self.dir, exist_ok=True)

    def _step_path(self, step_id: str) -> str:
        return os.path.join(self.dir, f"step-{step_id}.pkl")

    def has_step(self, step_id: str) -> bool:
        return os.path.exists(self._step_path(step_id))

    def load_step(self, step_id: str):
        with open(self._step_path(step_id), "rb") as f:
            return pickle.load(f)

    def save_step(self, step_id: str, result: Any) -> None:
        tmp = self._step_path(step_id) + ".tmp"
        with open(tmp, "wb") as f:
            cloudpickle.dump(result, f)
        os.replace(tmp, self._step_path(step_id))  # atomic: no torn checkpoints

    def set_status(self, status: str, error: str = "") -> None:
        blob = {"status": status, "error": error, "ts": time.time()}
        tmp = os.path.join(self.dir, "status.json.tmp")
        with open(tmp, "w") as f:
            json.dump(blob, f)
        os.replace(tmp, os.path.join(self.dir, "status.json"))

    def get_status(self) -> dict | None:
        try:
            with open(os.path.join(self.dir, "status.json")) as f:
                return json.load(f)
        except OSError:
            return None

    def save_dag(self, root: StepNode) -> None:
        path = os.path.join(self.dir, "dag.pkl")
        if not os.path.exists(path):
            with open(path, "wb") as f:
                cloudpickle.dump(root, f)

    def load_dag(self) -> StepNode:
        with open(os.path.join(self.dir, "dag.pkl"), "rb") as f:
            return pickle.load(f)


_WF_REF = "__wf_dep_ref__"


def _run_step(fn, args_spec, kwargs_spec, *dep_values):
    """Execute one step on a worker: dependency refs arrive as resolved
    VALUES (top-level args); placeholders in the specs splice them back
    into the original argument tree."""

    def fill(value):
        if isinstance(value, dict):
            if set(value) == {_WF_REF}:
                return dep_values[value[_WF_REF]]
            return {k: fill(v) for k, v in value.items()}
        if isinstance(value, list):
            return [fill(v) for v in value]
        if isinstance(value, tuple):
            return tuple(fill(v) for v in value)
        return value

    return fn(*[fill(a) for a in args_spec],
              **{k: fill(v) for k, v in kwargs_spec.items()})


def _execute(root: StepNode, storage: _Storage, step_timeout_s: float | None) -> Any:
    """Submit the whole step DAG as tasks wired by ObjectRefs: independent
    branches run CONCURRENTLY (reference ``workflow_executor.py:32``
    schedules every ready step), and results checkpoint as they complete.
    Step ids are assigned in deterministic DFS order, so a resumed run
    maps steps to the same checkpoints."""
    from ..core import api as ray

    counter = [0]
    memo: dict[int, Any] = {}
    pending: dict[Any, str] = {}  # ref -> step_id awaiting checkpoint

    def build(node: StepNode):
        """Returns the node's ObjectRef (children submitted first; ids
        follow argument order — stable across runs)."""
        if id(node) in memo:
            return memo[id(node)]
        dep_refs: list = []

        def transform(value):
            if isinstance(value, StepNode):
                dep_refs.append(build(value))
                return {_WF_REF: len(dep_refs) - 1}
            if isinstance(value, list):
                return [transform(v) for v in value]
            if isinstance(value, tuple):
                return tuple(transform(v) for v in value)
            if isinstance(value, dict):
                return {k: transform(v) for k, v in value.items()}
            return value

        args_spec = [transform(a) for a in node.args]
        kwargs_spec = {k: transform(v) for k, v in node.kwargs.items()}
        step_id = f"{counter[0]:04d}-{node.name}"
        counter[0] += 1
        if storage.has_step(step_id):
            ref = ray.put(storage.load_step(step_id))
        else:
            opts = {"name": node.name}
            fn = node.fn
            if isinstance(fn, ray.RemoteFunction):
                # Preserve the step's remote options (num_tpus, resources,
                # retries...): the wrapper task must schedule exactly as
                # the user-configured remote function would.
                opts = {**fn._options, **opts}
                fn = fn._fn
            ref = ray.remote(_run_step).options(**opts).remote(
                fn, args_spec, kwargs_spec, *dep_refs)
            pending[ref] = step_id
        memo[id(node)] = ref
        return ref

    root_ref = build(root)
    # Checkpoint steps AS they complete (any order); a step failure
    # surfaces on its get and fails the workflow — already-completed
    # siblings keep their checkpoints for resume.
    while pending:
        ready, _ = ray.wait(list(pending), num_returns=1, timeout=step_timeout_s)
        if not ready:
            raise TimeoutError(
                f"no workflow step completed within step_timeout_s={step_timeout_s}")
        ref = ready[0]
        step_id = pending.pop(ref)
        storage.save_step(step_id, ray.get(ref, timeout=step_timeout_s))
    return ray.get(root_ref, timeout=step_timeout_s)


def run(dag: StepNode, *, workflow_id: str, storage: str | None = None,
        step_timeout_s: float | None = None) -> Any:
    """Run (or continue) a workflow to completion; returns the root step's
    result. Completed steps are skipped — side effects happen once.
    ``step_timeout_s`` bounds each step (default: unbounded — training
    steps legitimately run for hours)."""
    st = _Storage(storage or _DEFAULT_STORAGE, workflow_id)
    st.save_dag(dag)
    st.set_status(STATUS_RUNNING)
    try:
        result = _execute(dag, st, step_timeout_s)
    except Exception as e:
        st.set_status(STATUS_FAILED, error=f"{type(e).__name__}: {e}")
        raise
    # Output BEFORE status: a crash between the two must never yield a
    # SUCCESSFUL workflow whose output is missing.
    st.save_step("__output__", result)
    st.set_status(STATUS_SUCCESSFUL)
    return result


def resume(workflow_id: str, *, storage: str | None = None,
           step_timeout_s: float | None = None) -> Any:
    """Continue a crashed/failed workflow from its persisted DAG and
    checkpoints (reference ``workflow.resume``)."""
    st = _Storage(storage or _DEFAULT_STORAGE, workflow_id, create=False)
    dag = st.load_dag()
    return run(dag, workflow_id=workflow_id, storage=storage,
               step_timeout_s=step_timeout_s)


def get_output(workflow_id: str, *, storage: str | None = None) -> Any:
    st = _Storage(storage or _DEFAULT_STORAGE, workflow_id, create=False)
    if not st.has_step("__output__"):
        raise ValueError(f"workflow {workflow_id} has no output (not finished?)")
    return st.load_step("__output__")


def get_status(workflow_id: str, *, storage: str | None = None) -> str | None:
    st = _Storage(storage or _DEFAULT_STORAGE, workflow_id, create=False)
    blob = st.get_status()
    return blob["status"] if blob else None


def list_all(*, storage: str | None = None) -> list[tuple[str, str]]:
    base = storage or _DEFAULT_STORAGE
    out = []
    try:
        entries = os.listdir(base)
    except OSError:
        return out
    for wf_id in sorted(entries):
        if not os.path.isdir(os.path.join(base, wf_id)):
            continue  # stray files in the storage dir are not workflows
        status = get_status(wf_id, storage=base)
        if status is not None:
            out.append((wf_id, status))
    return out
