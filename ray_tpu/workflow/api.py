"""Workflow engine: step DAG -> checkpointed cluster execution.

Reference: ``python/ray/workflow/api.py`` (run/resume,
``workflow.continuation``), ``workflow_executor.py`` (step scheduling),
``workflow_storage.py`` (checkpoint layout), ``event_listener.py``
(event steps). Redesign: steps persist to a local/NFS directory as
pickled results keyed by deterministic step ids (DFS order + name); the
executor is a synchronous driver loop — workflow control flow does not
need an actor of its own at this scale, and crash recovery falls out of
storage alone.

Dynamic workflows: a step may return ``workflow.continuation(sub_dag)``
— the engine records the continuation durably, executes the sub-DAG in
the step's checkpoint namespace, and hands the SUB-DAG's result to the
step's parents; a crash between the step finishing and its continuation
completing resumes INSIDE the continuation (the step's own side effects
never re-run). Event steps (``workflow.wait_for_event``) park a step on
an ``EventListener`` whose poll blocks until the event arrives; the
received payload checkpoints like any result (exactly-once), and
``workflow.trigger_event`` feeds the built-in KV listener through the
cluster's GCS.
"""

from __future__ import annotations

import json
import os
import pickle
import time
from typing import Any, Callable

import cloudpickle

_DEFAULT_STORAGE = os.path.expanduser("~/.ray_tpu_workflows")

STATUS_RUNNING = "RUNNING"
STATUS_SUCCESSFUL = "SUCCESSFUL"
STATUS_FAILED = "FAILED"


class StepNode:
    def __init__(self, fn: Callable, args: tuple, kwargs: dict, name: str | None = None):
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.name = name or getattr(fn, "__name__", "step")

    def options(self, name: str) -> "StepNode":
        return StepNode(self.fn, self.args, self.kwargs, name)


def step(fn: Callable):
    """``workflow.step(fn)(*args)`` builds a StepNode; args may contain
    other StepNodes (upstream dependencies)."""

    def bind(*args, **kwargs) -> StepNode:
        return StepNode(fn, args, kwargs)

    return bind


class Continuation:
    """Returned BY a step to dynamically extend the workflow: the engine
    executes ``dag`` (in the step's checkpoint namespace) and the sub-DAG's
    result becomes the step's result (reference ``workflow.continuation``).
    Continuations may return continuations (recursion)."""

    def __init__(self, dag: StepNode):
        if not isinstance(dag, StepNode):
            raise TypeError("continuation(...) takes a workflow step DAG")
        self.dag = dag


def continuation(dag: StepNode) -> Continuation:
    return Continuation(dag)


class EventListener:
    """Event-step provider (reference ``workflow/event_listener.py``):
    ``poll_for_event`` BLOCKS until the event arrives and returns its
    payload — which checkpoints as the step's result (exactly-once)."""

    def poll_for_event(self, *args, **kwargs):  # pragma: no cover - abstract
        raise NotImplementedError


class KVEventListener(EventListener):
    """Built-in listener on the cluster KV: blocks until someone calls
    ``workflow.trigger_event(key, payload)`` (any driver/worker/external
    process attached to the GCS)."""

    def __init__(self, poll_interval_s: float = 0.2):
        self.poll_interval_s = poll_interval_s

    # Set by the event step's driver (wall-clock deadline); the loop
    # raises on expiry so a failed/abandoned workflow can't leak an
    # immortal polling task (there is no task-cancel API yet).
    deadline: float | None = None

    def poll_for_event(self, key: str):
        from ..core.worker import global_worker

        w = global_worker()
        while True:
            reply = w._gcs_call("KvGet", {"key": f"wf_event:{key}"})
            if reply.get("found"):
                return pickle.loads(reply["value"])
            if self.deadline is not None and time.time() > self.deadline:
                raise TimeoutError(f"event {key!r} did not arrive in time")
            time.sleep(self.poll_interval_s)


def trigger_event(key: str, payload: Any = True) -> None:
    """Fire an event: every ``wait_for_event`` step listening on ``key``
    (across workflows) unblocks with ``payload``."""
    from ..core.worker import global_worker

    global_worker()._gcs_call(
        "KvPut", {"key": f"wf_event:{key}", "value": cloudpickle.dumps(payload),
                  "overwrite": True})


def _poll_event(listener_cls, args, kwargs, timeout_s):
    listener = listener_cls()
    if timeout_s is not None:
        listener.deadline = time.time() + timeout_s
    return listener.poll_for_event(*args, **kwargs)


def wait_for_event(listener_cls: type | str, *args, name: str | None = None,
                   timeout_s: float | None = 3600.0, **kwargs) -> StepNode:
    """An event step: completes when the listener's poll returns. Pass an
    ``EventListener`` subclass, or a string key as shorthand for the KV
    listener (``wait_for_event("deploy-approved")``). ``timeout_s`` bounds
    the listen (the step fails on expiry): without a task-cancel API, an
    unbounded listener whose workflow failed for other reasons would poll
    on a worker forever."""
    if isinstance(listener_cls, str):
        args = (listener_cls, *args)
        listener_cls = KVEventListener
    if not (isinstance(listener_cls, type) and issubclass(listener_cls, EventListener)):
        raise TypeError("wait_for_event needs an EventListener subclass or a key string")
    node = StepNode(_poll_event, (listener_cls, args, kwargs, timeout_s), {},
                    name=name or f"event-{getattr(listener_cls, '__name__', 'listener')}")
    return node


class _Storage:
    def __init__(self, base: str, workflow_id: str, create: bool = True):
        self.dir = os.path.join(base, workflow_id)
        if create:
            os.makedirs(self.dir, exist_ok=True)

    def _step_path(self, step_id: str) -> str:
        return os.path.join(self.dir, f"step-{step_id}.pkl")

    def has_step(self, step_id: str) -> bool:
        return os.path.exists(self._step_path(step_id))

    def load_step(self, step_id: str):
        with open(self._step_path(step_id), "rb") as f:
            return pickle.load(f)

    def save_step(self, step_id: str, result: Any) -> None:
        tmp = self._step_path(step_id) + ".tmp"
        with open(tmp, "wb") as f:
            cloudpickle.dump(result, f)
        os.replace(tmp, self._step_path(step_id))  # atomic: no torn checkpoints

    def set_status(self, status: str, error: str = "") -> None:
        blob = {"status": status, "error": error, "ts": time.time()}
        tmp = os.path.join(self.dir, "status.json.tmp")
        with open(tmp, "w") as f:
            json.dump(blob, f)
        os.replace(tmp, os.path.join(self.dir, "status.json"))

    def get_status(self) -> dict | None:
        try:
            with open(os.path.join(self.dir, "status.json")) as f:
                return json.load(f)
        except OSError:
            return None

    def save_dag(self, root: StepNode) -> None:
        path = os.path.join(self.dir, "dag.pkl")
        if not os.path.exists(path):
            with open(path, "wb") as f:
                cloudpickle.dump(root, f)

    def load_dag(self) -> StepNode:
        with open(os.path.join(self.dir, "dag.pkl"), "rb") as f:
            return pickle.load(f)


_WF_REF = "__wf_dep_ref__"


def _run_step(fn, args_spec, kwargs_spec, *dep_values):
    """Execute one step on a worker: dependency refs arrive as resolved
    VALUES (top-level args); placeholders in the specs splice them back
    into the original argument tree."""

    def fill(value):
        if isinstance(value, dict):
            if set(value) == {_WF_REF}:
                return dep_values[value[_WF_REF]]
            return {k: fill(v) for k, v in value.items()}
        if isinstance(value, list):
            return [fill(v) for v in value]
        if isinstance(value, tuple):
            return tuple(fill(v) for v in value)
        return value

    return fn(*[fill(a) for a in args_spec],
              **{k: fill(v) for k, v in kwargs_spec.items()})


def _execute(root: StepNode, storage: _Storage, step_timeout_s: float | None,
             prefix: str = "") -> Any:
    """Stepwise driver: every READY step (all deps resolved) is submitted
    as a task, so independent branches run CONCURRENTLY (reference
    ``workflow_executor.py:32``); results checkpoint as they complete.
    Step ids are assigned in deterministic DFS order, so a resumed run
    maps steps to the same checkpoints. A step returning a
    ``Continuation`` records it durably, executes the sub-DAG in its
    checkpoint namespace (``<step_id>:``), and exposes the sub-DAG's
    result to its parents."""
    from ..core import api as ray

    # ---- graph state (grows as continuations extend the DAG) -----------
    order: list[StepNode] = []
    node_deps: dict[int, list[StepNode]] = {}
    node_specs: dict[int, tuple] = {}
    step_ids: dict[int, str] = {}
    seen: set[int] = set()
    # Sub-DAG root -> the step whose continuation it is: resolving the
    # root resolves that step (iteratively — chains never recurse).
    cont_parent: dict[int, StepNode] = {}

    def build(node: StepNode, ns: str) -> None:
        """Assemble ``node``'s subtree into the scheduling state with ids
        in DFS order under namespace ``ns`` (stable across runs)."""
        if id(node) in seen:
            return
        seen.add(id(node))
        counter = _ns_counters.setdefault(ns, [0])
        deps: list[StepNode] = []

        def transform(value):
            if isinstance(value, StepNode):
                build(value, ns)
                deps.append(value)
                return {_WF_REF: len(deps) - 1}
            if isinstance(value, list):
                return [transform(v) for v in value]
            if isinstance(value, tuple):
                return tuple(transform(v) for v in value)
            if isinstance(value, dict):
                return {k: transform(v) for k, v in value.items()}
            return value

        args_spec = [transform(a) for a in node.args]
        kwargs_spec = {k: transform(v) for k, v in node.kwargs.items()}
        sid = f"{ns}{counter[0]:04d}-{node.name}"
        if len(sid) > 100:
            # Deep continuation chains concatenate namespaces per level;
            # fold long ids to a stable digest (same DAG -> same id, so
            # resume still maps to the same checkpoint files) before they
            # exceed filesystem name limits.
            import hashlib

            sid = (f"h{hashlib.sha1(sid.encode()).hexdigest()[:24]}"
                   f"-{node.name[:40]}")
        step_ids[id(node)] = sid
        counter[0] += 1
        node_deps[id(node)] = deps
        node_specs[id(node)] = (args_spec, kwargs_spec)
        order.append(node)

    _ns_counters: dict[str, list] = {}
    build(root, prefix)

    # ---- stepwise scheduling -------------------------------------------
    result_ref: dict[int, Any] = {}      # node -> final ObjectRef
    submitted: set[int] = set()
    pending: dict[Any, StepNode] = {}    # running task ref -> node

    def attach_continuation(node: StepNode, dag: StepNode) -> None:
        """Graft a step's continuation sub-DAG into the RUNNING driver
        loop: its steps schedule alongside every other ready step (sibling
        branches keep checkpointing — no nested executor), and resolving
        its root resolves ``node``."""
        build(dag, f"{step_ids[id(node)]}:c:")
        cont_parent[id(dag)] = node

    def finish(node: StepNode, value: Any) -> None:
        # Iterative: a FINAL value propagates up the continuation chain in
        # a loop; a Continuation grafts its sub-DAG and leaves `node`
        # unresolved until the sub-root finishes.
        while True:
            sid = step_ids[id(node)]
            if isinstance(value, Continuation):
                # Durable BEFORE execution: a crash mid-continuation
                # resumes inside the sub-DAG without re-running the step.
                if not storage.has_step(f"{sid}:cont"):
                    storage.save_step(f"{sid}:cont", value.dag)
                # Park the step on its continuation: without this,
                # maybe_submit's resume branch would graft a SECOND copy
                # of the sub-DAG on every pass (2^depth blowup).
                submitted.add(id(node))
                attach_continuation(node, value.dag)
                return
            storage.save_step(sid, value)
            result_ref[id(node)] = ray.put(value)
            parent = cont_parent.pop(id(node), None)
            if parent is None:
                return
            node = parent  # the chain's final value resolves each level

    def maybe_submit() -> None:
        for node in list(order):
            nid = id(node)
            if nid in result_ref or nid in submitted:
                continue
            sid = step_ids[nid]
            if storage.has_step(sid):
                finish(node, storage.load_step(sid))
                continue
            if storage.has_step(f"{sid}:cont"):
                # Crashed mid-continuation: graft the recorded sub-DAG;
                # the step body itself never re-runs.
                submitted.add(nid)  # parked on its continuation
                attach_continuation(node, storage.load_step(f"{sid}:cont"))
                continue
            deps = node_deps[nid]
            if any(id(d) not in result_ref for d in deps):
                continue  # not ready yet
            args_spec, kwargs_spec = node_specs[nid]
            opts = {"name": node.name}
            fn = node.fn
            if isinstance(fn, ray.RemoteFunction):
                # Preserve the step's remote options (num_tpus, resources,
                # retries...): the wrapper task must schedule exactly as
                # the user-configured remote function would.
                opts = {**fn._options, **opts}
                fn = fn._fn
            dep_refs = [result_ref[id(d)] for d in deps]
            ref = ray.remote(_run_step).options(**opts).remote(
                fn, args_spec, kwargs_spec, *dep_refs)
            pending[ref] = node
            submitted.add(nid)

    maybe_submit()
    while id(root) not in result_ref:
        # A continuation graft can make new steps ready (or finish steps
        # straight from checkpoints) without anything pending.
        if not pending:
            # Progress = anything resolved OR the graph growing (a resume
            # deep in a continuation chain grafts one level per pass, and
            # maybe_submit's order snapshot misses same-pass grafts).
            before = (len(result_ref), len(order))
            maybe_submit()
            if not pending and (len(result_ref), len(order)) == before:
                raise RuntimeError("workflow stalled: no runnable steps")  # pragma: no cover
            continue
        ready, _ = ray.wait(list(pending), num_returns=1, timeout=step_timeout_s)
        if not ready:
            raise TimeoutError(
                f"no workflow step completed within step_timeout_s={step_timeout_s}")
        ref = ready[0]
        node = pending.pop(ref)
        submitted.discard(id(node))
        finish(node, ray.get(ref, timeout=step_timeout_s))
        maybe_submit()
    return storage.load_step(step_ids[id(root)])


def run(dag: StepNode, *, workflow_id: str, storage: str | None = None,
        step_timeout_s: float | None = None) -> Any:
    """Run (or continue) a workflow to completion; returns the root step's
    result. Completed steps are skipped — side effects happen once.
    ``step_timeout_s`` bounds each step (default: unbounded — training
    steps legitimately run for hours)."""
    st = _Storage(storage or _DEFAULT_STORAGE, workflow_id)
    st.save_dag(dag)
    st.set_status(STATUS_RUNNING)
    try:
        result = _execute(dag, st, step_timeout_s)
    except Exception as e:
        st.set_status(STATUS_FAILED, error=f"{type(e).__name__}: {e}")
        raise
    # Output BEFORE status: a crash between the two must never yield a
    # SUCCESSFUL workflow whose output is missing.
    st.save_step("__output__", result)
    st.set_status(STATUS_SUCCESSFUL)
    return result


def resume(workflow_id: str, *, storage: str | None = None,
           step_timeout_s: float | None = None) -> Any:
    """Continue a crashed/failed workflow from its persisted DAG and
    checkpoints (reference ``workflow.resume``)."""
    st = _Storage(storage or _DEFAULT_STORAGE, workflow_id, create=False)
    dag = st.load_dag()
    return run(dag, workflow_id=workflow_id, storage=storage,
               step_timeout_s=step_timeout_s)


def get_output(workflow_id: str, *, storage: str | None = None) -> Any:
    st = _Storage(storage or _DEFAULT_STORAGE, workflow_id, create=False)
    if not st.has_step("__output__"):
        raise ValueError(f"workflow {workflow_id} has no output (not finished?)")
    return st.load_step("__output__")


def get_status(workflow_id: str, *, storage: str | None = None) -> str | None:
    st = _Storage(storage or _DEFAULT_STORAGE, workflow_id, create=False)
    blob = st.get_status()
    return blob["status"] if blob else None


def list_all(*, storage: str | None = None) -> list[tuple[str, str]]:
    base = storage or _DEFAULT_STORAGE
    out = []
    try:
        entries = os.listdir(base)
    except OSError:
        return out
    for wf_id in sorted(entries):
        if not os.path.isdir(os.path.join(base, wf_id)):
            continue  # stray files in the storage dir are not workflows
        status = get_status(wf_id, storage=base)
        if status is not None:
            out.append((wf_id, status))
    return out
