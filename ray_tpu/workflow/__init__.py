"""Workflows: durable DAG execution with exactly-once step semantics.

Equivalent of the reference's ``python/ray/workflow/``: a DAG of steps
runs as cluster tasks with every step result checkpointed to storage;
re-running (``resume``) after a crash skips completed steps, so side
effects execute once per workflow id. Dynamic workflows — a step
returning ``continuation(sub_dag)`` extends the DAG at runtime
(reference ``workflow.continuation``) — checkpoint level by level, and
event steps (``wait_for_event`` / ``EventListener`` /
``trigger_event``) park a step until an external event arrives.
"""

from .api import (
    Continuation,
    EventListener,
    KVEventListener,
    StepNode,
    continuation,
    get_output,
    get_status,
    list_all,
    resume,
    run,
    step,
    trigger_event,
    wait_for_event,
)

__all__ = [
    "step",
    "run",
    "resume",
    "get_output",
    "get_status",
    "list_all",
    "StepNode",
    "Continuation",
    "continuation",
    "EventListener",
    "KVEventListener",
    "trigger_event",
    "wait_for_event",
]
