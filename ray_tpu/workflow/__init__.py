"""Workflows: durable DAG execution with exactly-once step semantics.

Equivalent of the reference's ``python/ray/workflow/``: a DAG of steps
runs as cluster tasks with every step result checkpointed to storage;
re-running (``resume``) after a crash skips completed steps, so side
effects execute once per workflow id. Dynamic workflows (steps that
return more steps) are intentionally out of scope — static DAGs cover
the checkpoint/resume contract the reference's tests exercise.
"""

from .api import StepNode, get_output, get_status, list_all, resume, run, step

__all__ = ["step", "run", "resume", "get_output", "get_status", "list_all", "StepNode"]
