"""DQN (double Q-learning + target network) on the Learner/EnvRunner stack.

Equivalent of ``rllib/algorithms/dqn/dqn.py`` + ``dqn_rainbow_learner.py``
(minus the rainbow extras): epsilon-greedy transition collection through
the shared EnvRunnerGroup, a uniform ReplayBuffer, and a jitted double-DQN
Huber loss on the shared Learner — the algorithm proves the
Learner/EnvRunner abstractions generalize beyond on-policy PPO.

The Q-network reuses the actor-critic MLP (``models.forward``): the ``pi``
head's logits ARE the Q-values; the ``vf`` head is simply unused. The
target network rides into the jitted loss as part of the batch pytree.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from . import models
from .algorithm import Algorithm, AlgorithmConfig
from .env_runner import EnvRunnerGroup, _np_forward, _softmax  # noqa: F401
from .learner_group import LearnerGroup
from .replay import ReplayBuffer


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.gamma = 0.99
        self.lr = 5e-4
        self.hidden = 64
        self.buffer_size = 50_000
        self.batch_size = 64
        self.learning_starts = 1_000
        self.updates_per_iteration = 32
        self.target_update_freq = 200   # learner updates between target syncs
        self.double_q = True
        self.eps_start = 1.0
        self.eps_end = 0.05
        self.eps_decay_steps = 10_000
        self.rollout_len = 32

    def training(self, *, gamma=None, buffer_size=None, batch_size=None,
                 learning_starts=None, updates_per_iteration=None,
                 target_update_freq=None, double_q=None, eps_start=None,
                 eps_end=None, eps_decay_steps=None, hidden=None, **kwargs):
        for name, val in (("gamma", gamma), ("buffer_size", buffer_size),
                          ("batch_size", batch_size), ("learning_starts", learning_starts),
                          ("updates_per_iteration", updates_per_iteration),
                          ("target_update_freq", target_update_freq),
                          ("double_q", double_q), ("eps_start", eps_start),
                          ("eps_end", eps_end), ("eps_decay_steps", eps_decay_steps),
                          ("hidden", hidden)):
            if val is not None:
                setattr(self, name, val)
        return super().training(**kwargs)


def make_dqn_loss(gamma: float, double_q: bool):
    """batch: obs, actions, rewards, next_obs, terminated, target_params."""

    def loss_fn(params, batch):
        q_all, _ = models.forward(params, batch["obs"])          # [B, A]
        q_sa = jnp.take_along_axis(q_all, batch["actions"][:, None], axis=1)[:, 0]
        q_next_target, _ = models.forward(batch["target_params"], batch["next_obs"])
        if double_q:
            # Double DQN: online net selects, target net evaluates.
            q_next_online, _ = models.forward(params, batch["next_obs"])
            a_sel = jnp.argmax(q_next_online, axis=1)
        else:
            a_sel = jnp.argmax(q_next_target, axis=1)
        q_next = jnp.take_along_axis(q_next_target, a_sel[:, None], axis=1)[:, 0]
        target = batch["rewards"] + gamma * (1.0 - batch["terminated"]) * q_next
        td = q_sa - jax.lax.stop_gradient(target)
        loss = jnp.mean(jnp.where(jnp.abs(td) < 1.0, 0.5 * td**2, jnp.abs(td) - 0.5))
        metrics = {
            "td_error_mean": jnp.mean(jnp.abs(td)),
            "q_mean": jnp.mean(q_sa),
        }
        return loss, metrics

    return loss_fn


class QEnvRunner:
    """Epsilon-greedy transition collector over the shared vectorized-env
    protocol: emits flat (s, a, r, s', terminated) fragments plus episode
    returns. Auto-reset envs: s' at a done step is the TERMINAL obs from
    ``info``, not the freshly reset state."""

    def __init__(self, env_cls, num_envs: int = 8, rollout_len: int = 32, seed: int = 0):
        self.env = env_cls(num_envs=num_envs, seed=seed)
        self.num_envs = num_envs
        self.rollout_len = rollout_len
        self.rng = np.random.default_rng(seed ^ 0xD0)
        self.obs = self.env.reset()
        self._ep_return = np.zeros(num_envs, np.float32)
        self._completed: list[float] = []

    def sample(self, weights, epsilon: float = 0.05) -> dict:
        T, N = self.rollout_len, self.num_envs
        obs_b = np.zeros((T, N, self.env.obs_dim), np.float32)
        act_b = np.zeros((T, N), np.int64)
        rew_b = np.zeros((T, N), np.float32)
        next_b = np.zeros((T, N, self.env.obs_dim), np.float32)
        term_b = np.zeros((T, N), np.float32)
        for t in range(T):
            q, _ = _np_forward(weights, self.obs)
            greedy = q.argmax(axis=1)
            random_a = self.rng.integers(0, self.env.n_actions, N)
            explore = self.rng.random(N) < epsilon
            actions = np.where(explore, random_a, greedy)
            obs_b[t], act_b[t] = self.obs, actions
            self.obs, rewards, dones, info = self.env.step(actions)
            rew_b[t] = rewards
            # next state: terminal obs where the episode just ended
            next_b[t] = np.where(dones[:, None], info["terminal_obs"], self.obs)
            term_b[t] = info["terminated"].astype(np.float32)  # truncation bootstraps
            self._ep_return += rewards
            for i in np.nonzero(dones)[0]:
                self._completed.append(float(self._ep_return[i]))
                self._ep_return[i] = 0.0
        completed, self._completed = self._completed, []
        return {
            "obs": obs_b.reshape(T * N, -1),
            "actions": act_b.reshape(-1),
            "rewards": rew_b.reshape(-1),
            "next_obs": next_b.reshape(T * N, -1),
            "terminated": term_b.reshape(-1),
            "episode_returns": np.asarray(completed, np.float32),
        }


class DQN(Algorithm):
    def _setup(self) -> None:
        c: DQNConfig = self.config  # type: ignore[assignment]
        env_probe = c.env_cls(num_envs=1)
        obs_dim, n_actions = env_probe.obs_dim, env_probe.n_actions

        def init_params_fn(key):
            return models.init_policy(key, obs_dim, n_actions, c.hidden)

        self.learner_group = LearnerGroup(
            make_dqn_loss(c.gamma, c.double_q),
            init_params_fn,
            num_learners=c.num_learners,
            lr=c.lr,
            max_grad_norm=c.max_grad_norm,
            seed=c.seed,
        )
        self.env_runner_group = EnvRunnerGroup(
            c.env_cls,
            num_env_runners=c.num_env_runners,
            num_envs_per_runner=c.num_envs_per_runner,
            rollout_len=c.rollout_len,
            seed=c.seed,
            runner_cls=QEnvRunner,
        )
        self.buffer = ReplayBuffer(c.buffer_size, obs_dim, seed=c.seed)
        self.target_params = self.learner_group.get_weights()
        self._env_steps = 0
        self._updates = 0
        self._recent_returns: list[float] = []

    def _epsilon(self) -> float:
        c: DQNConfig = self.config  # type: ignore[assignment]
        frac = min(1.0, self._env_steps / max(1, c.eps_decay_steps))
        return c.eps_start + frac * (c.eps_end - c.eps_start)

    def training_step(self) -> dict:
        c: DQNConfig = self.config  # type: ignore[assignment]
        weights = self.learner_group.get_weights()
        samples = self.env_runner_group.sample(weights, epsilon=self._epsilon())
        for s in samples:
            self.buffer.add_batch(s["obs"], s["actions"], s["rewards"],
                                  s["next_obs"], s["terminated"])
            self._env_steps += len(s["actions"])
            self._recent_returns.extend(s["episode_returns"].tolist())

        metrics: dict = {}
        if len(self.buffer) >= c.learning_starts:
            for _ in range(c.updates_per_iteration):
                batch = self.buffer.sample(c.batch_size)
                batch["target_params"] = self.target_params
                metrics = self.learner_group.update(batch)
                self._updates += 1
                if self._updates % c.target_update_freq == 0:
                    self.target_params = self.learner_group.get_weights()

        self._recent_returns = self._recent_returns[-100:]
        metrics["episode_return_mean"] = (
            float(np.mean(self._recent_returns)) if self._recent_returns else 0.0
        )
        metrics["num_env_steps_sampled"] = self._env_steps
        metrics["epsilon"] = self._epsilon()
        metrics["buffer_size"] = len(self.buffer)
        return metrics

    def get_state(self) -> dict:
        return {
            "iteration": self.iteration,
            "learner": self.learner_group.get_state(),
            "target_params": self.target_params,
            "env_steps": self._env_steps,
            "updates": self._updates,
        }

    def set_state(self, state: dict) -> None:
        self.iteration = state["iteration"]
        self.learner_group.set_state(state["learner"])
        self.target_params = state["target_params"]
        self._env_steps = state["env_steps"]
        self._updates = state["updates"]


DQNConfig.algo_cls = DQN
