"""Algorithm base: config builder + train-iteration loop + checkpointing.

Equivalent of ``rllib/algorithms/algorithm.py:199`` /
``algorithm_config.py``: the fluent config (``.environment()``,
``.training()``, ``.env_runners()``, ``.learners()``) builds an Algorithm
that iterates sample → update and can save/restore its full state.
"""

from __future__ import annotations

import os
import pickle
import time
from typing import Any, Type


class AlgorithmConfig:
    def __init__(self):
        self.env_cls: Any = None
        self.num_env_runners = 0
        self.num_envs_per_runner = 8
        self.rollout_len = 64
        self.num_learners = 0
        self.lr = 3e-4
        self.max_grad_norm = 0.5
        self.seed = 0
        self.train_kwargs: dict = {}

    # ----------------------------------------------------- fluent builders
    def environment(self, env_cls) -> "AlgorithmConfig":
        self.env_cls = env_cls
        return self

    def env_runners(self, num_env_runners: int = 0, num_envs_per_runner: int = 8,
                    rollout_len: int = 64) -> "AlgorithmConfig":
        self.num_env_runners = num_env_runners
        self.num_envs_per_runner = num_envs_per_runner
        self.rollout_len = rollout_len
        return self

    def learners(self, num_learners: int = 0) -> "AlgorithmConfig":
        self.num_learners = num_learners
        return self

    def training(self, *, lr: float | None = None, max_grad_norm: float | None = None,
                 **kwargs) -> "AlgorithmConfig":
        if lr is not None:
            self.lr = lr
        if max_grad_norm is not None:
            self.max_grad_norm = max_grad_norm
        self.train_kwargs.update(kwargs)
        return self

    def seeding(self, seed: int) -> "AlgorithmConfig":
        self.seed = seed
        return self

    def build(self) -> "Algorithm":
        return self.algo_cls(self)  # set by subclass

    algo_cls: Type["Algorithm"] = None  # type: ignore[assignment]


class Algorithm:
    def __init__(self, config: AlgorithmConfig):
        self.config = config
        self.iteration = 0
        self._setup()

    def _setup(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    def training_step(self) -> dict:  # pragma: no cover - abstract
        raise NotImplementedError

    def train(self) -> dict:
        """One training iteration (reference ``Algorithm.train``)."""
        start = time.monotonic()
        metrics = self.training_step()
        self.iteration += 1
        metrics["training_iteration"] = self.iteration
        metrics["time_this_iter_s"] = time.monotonic() - start
        return metrics

    # --------------------------------------------------------- checkpointing
    def get_state(self) -> dict:  # pragma: no cover - overridden
        return {"iteration": self.iteration}

    def set_state(self, state: dict) -> None:  # pragma: no cover - overridden
        self.iteration = state["iteration"]

    def save(self, checkpoint_dir: str) -> str:
        os.makedirs(checkpoint_dir, exist_ok=True)
        path = os.path.join(checkpoint_dir, "algorithm_state.pkl")
        with open(path, "wb") as f:
            pickle.dump(self.get_state(), f)
        return path

    def restore(self, checkpoint_dir: str) -> None:
        with open(os.path.join(checkpoint_dir, "algorithm_state.pkl"), "rb") as f:
            self.set_state(pickle.load(f))

    def stop(self) -> None:
        for group in ("learner_group", "env_runner_group"):
            g = getattr(self, group, None)
            if g is not None:
                g.shutdown()
