"""Algorithm base: config builder + train-iteration loop + checkpointing.

Equivalent of ``rllib/algorithms/algorithm.py:199`` /
``algorithm_config.py``: the fluent config (``.environment()``,
``.training()``, ``.env_runners()``, ``.learners()``) builds an Algorithm
that iterates sample → update and can save/restore its full state.
"""

from __future__ import annotations

import os
import pickle
import time

import numpy as np
from typing import Any, Type


class AlgorithmConfig:
    def __init__(self):
        self.env_cls: Any = None
        self.num_env_runners = 0
        self.num_envs_per_runner = 8
        self.rollout_len = 64
        self.num_learners = 0
        self.lr = 3e-4
        self.max_grad_norm = 0.5
        self.seed = 0
        self.train_kwargs: dict = {}
        # ConnectorV2 factories (called per runner so state is per-runner)
        self.env_to_module_connector = None
        self.learner_connector = None
        # evaluation harness settings
        self.evaluation_num_episodes = 10
        self.evaluation_num_envs = 8

    # ----------------------------------------------------- fluent builders
    def environment(self, env_cls) -> "AlgorithmConfig":
        self.env_cls = env_cls
        return self

    def env_runners(self, num_env_runners: int = 0, num_envs_per_runner: int = 8,
                    rollout_len: int = 64) -> "AlgorithmConfig":
        self.num_env_runners = num_env_runners
        self.num_envs_per_runner = num_envs_per_runner
        self.rollout_len = rollout_len
        return self

    def learners(self, num_learners: int = 0) -> "AlgorithmConfig":
        self.num_learners = num_learners
        return self

    def training(self, *, lr: float | None = None, max_grad_norm: float | None = None,
                 **kwargs) -> "AlgorithmConfig":
        if lr is not None:
            self.lr = lr
        if max_grad_norm is not None:
            self.max_grad_norm = max_grad_norm
        self.train_kwargs.update(kwargs)
        return self

    def seeding(self, seed: int) -> "AlgorithmConfig":
        self.seed = seed
        return self

    def connectors(self, *, env_to_module=None, learner=None) -> "AlgorithmConfig":
        """ConnectorV2 pipelines (reference connectors/connector_v2.py):
        ``env_to_module`` preprocesses observations before the policy
        (and the rollout records the TRANSFORMED obs); ``learner``
        preprocesses each sampled batch before the update. Pass a
        factory (zero-arg callable) so every env-runner gets its own
        stateful copy."""
        if env_to_module is not None:
            self.env_to_module_connector = env_to_module
        if learner is not None:
            self.learner_connector = learner
        return self

    def evaluation(self, *, num_episodes: int = 10,
                   num_envs: int = 8) -> "AlgorithmConfig":
        self.evaluation_num_episodes = num_episodes
        self.evaluation_num_envs = num_envs
        return self

    def build(self) -> "Algorithm":
        return self.algo_cls(self)  # set by subclass

    algo_cls: Type["Algorithm"] = None  # type: ignore[assignment]


class Algorithm:
    def __init__(self, config: AlgorithmConfig):
        self.config = config
        self.iteration = 0
        self._setup()

    def _setup(self) -> None:  # pragma: no cover - abstract
        raise NotImplementedError

    def training_step(self) -> dict:  # pragma: no cover - abstract
        raise NotImplementedError

    def train(self) -> dict:
        """One training iteration (reference ``Algorithm.train``)."""
        start = time.monotonic()
        metrics = self.training_step()
        self.iteration += 1
        metrics["training_iteration"] = self.iteration
        metrics["time_this_iter_s"] = time.monotonic() - start
        return metrics

    def get_weights(self):
        """Current policy weights for inference (eval, export). Default:
        the learner group's weights; algorithms without one override."""
        group = getattr(self, "learner_group", None)
        if group is None:
            raise NotImplementedError(f"{type(self).__name__}.get_weights")
        return group.get_weights()

    def evaluate(self) -> dict:
        """Run evaluation episodes on a DEDICATED env-runner with the
        current weights (reference ``Algorithm.evaluate``,
        ``algorithms/algorithm.py:199``): the eval runner never feeds
        training, its connector state is cloned from training (frozen
        stats — evaluating under a different normalization than the
        policy was trained with would skew returns)."""
        from .env_runner import EnvRunner

        cfg = self.config
        if getattr(self, "_eval_runner", None) is None:
            conn = None
            if cfg.env_to_module_connector is not None:
                from .connectors import make_pipeline

                conn = make_pipeline(cfg.env_to_module_connector)
            self._eval_runner = EnvRunner(
                cfg.env_cls, cfg.evaluation_num_envs, cfg.rollout_len,
                seed=cfg.seed ^ 0xE7A1, env_to_module=conn)
        runner = self._eval_runner
        if runner.env_to_module is not None:
            # freeze + sync normalizer stats from a training runner
            group = getattr(self, "env_runner_group", None)
            state = group.connector_states()[0] if group is not None else None
            if state:
                runner.env_to_module.set_state(state)
            for p in runner.env_to_module.pieces:
                if hasattr(p, "update"):
                    p.update = False
        weights = self.get_weights()
        returns: list[float] = []
        lengths = 0
        while len(returns) < cfg.evaluation_num_episodes:
            batch = runner.sample(weights)
            returns.extend(batch["episode_returns"].tolist())
            lengths += batch["rewards"].size
        returns = returns[: cfg.evaluation_num_episodes]
        return {
            "evaluation": {
                "episode_return_mean": float(np.mean(returns)),
                "episode_return_min": float(np.min(returns)),
                "episode_return_max": float(np.max(returns)),
                "num_episodes": len(returns),
                "env_steps": int(lengths),
            }
        }

    # --------------------------------------------------------- checkpointing
    def get_state(self) -> dict:  # pragma: no cover - overridden
        return {"iteration": self.iteration}

    def set_state(self, state: dict) -> None:  # pragma: no cover - overridden
        self.iteration = state["iteration"]

    def save(self, checkpoint_dir: str) -> str:
        os.makedirs(checkpoint_dir, exist_ok=True)
        path = os.path.join(checkpoint_dir, "algorithm_state.pkl")
        with open(path, "wb") as f:
            pickle.dump(self.get_state(), f)
        return path

    def restore(self, checkpoint_dir: str) -> None:
        with open(os.path.join(checkpoint_dir, "algorithm_state.pkl"), "rb") as f:
            self.set_state(pickle.load(f))

    def stop(self) -> None:
        for group in ("learner_group", "env_runner_group"):
            g = getattr(self, group, None)
            if g is not None:
                g.shutdown()
