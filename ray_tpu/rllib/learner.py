"""Learner: owns params + optimizer state, applies jitted updates.

Equivalent of the reference's ``rllib/core/learner/learner.py:111``
(``Learner.update_from_batch``): the algorithm supplies a loss function;
the Learner differentiates it, applies Adam, and reports metrics. Where
the reference builds a torch autograd graph per call, here the whole
loss→grad→optimizer chain is one XLA-compiled function, so a minibatch
update is a single device dispatch.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax


class Learner:
    def __init__(
        self,
        loss_fn: Callable,
        init_params_fn: Callable[[jax.Array], dict],
        *,
        lr: float = 3e-4,
        max_grad_norm: float = 0.5,
        seed: int = 0,
    ):
        self._loss_fn = loss_fn
        self.params = init_params_fn(jax.random.PRNGKey(seed))
        self.tx = optax.chain(
            optax.clip_by_global_norm(max_grad_norm),
            optax.adam(lr),
        )
        self.opt_state = self.tx.init(self.params)

        @jax.jit
        def _update(params, opt_state, batch):
            (loss, metrics), grads = jax.value_and_grad(self._loss_fn, has_aux=True)(
                params, batch
            )
            updates, new_opt = self.tx.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            metrics = dict(metrics)
            metrics["total_loss"] = loss
            metrics["grad_norm"] = optax.global_norm(grads)
            return new_params, new_opt, metrics

        @jax.jit
        def _grads(params, batch):
            (loss, metrics), grads = jax.value_and_grad(self._loss_fn, has_aux=True)(
                params, batch
            )
            metrics = dict(metrics)
            metrics["total_loss"] = loss
            return grads, metrics

        @jax.jit
        def _apply(params, opt_state, grads):
            updates, new_opt = self.tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), new_opt

        self._update_jit = _update
        self._grads_jit = _grads
        self._apply_jit = _apply

    # ------------------------------------------------------------- local API
    def update(self, batch: dict) -> dict:
        """Full local update; returns float metrics."""
        self.params, self.opt_state, metrics = self._update_jit(
            self.params, self.opt_state, batch
        )
        return {k: float(v) for k, v in metrics.items()}

    # --------------------------------------------------- distributed pieces
    def compute_gradients(self, batch: dict):
        """Half of a data-parallel step: grads on this learner's shard
        (LearnerGroup averages them across learners)."""
        grads, metrics = self._grads_jit(self.params, batch)
        return jax.device_get(grads), {k: float(v) for k, v in metrics.items()}

    def apply_gradients(self, grads) -> None:
        self.params, self.opt_state = self._apply_jit(self.params, self.opt_state, grads)

    def get_weights(self):
        return jax.device_get(self.params)

    def set_weights(self, params) -> None:
        self.params = jax.tree.map(jnp.asarray, params)

    def get_state(self) -> dict:
        return {
            "params": jax.device_get(self.params),
            "opt_state": jax.device_get(self.opt_state),
        }

    def set_state(self, state: dict) -> None:
        self.params = jax.tree.map(jnp.asarray, state["params"])
        self.opt_state = jax.tree.map(jnp.asarray, state["opt_state"])


def average_gradients(grad_list: list) -> Any:
    """Mean over learners' gradient pytrees (the all-reduce the reference
    does with torch DDP/NCCL, here over the object store)."""
    return jax.tree.map(lambda *gs: sum(gs) / len(gs), *grad_list)
