"""Offline RL: datasets of recorded transitions + BC / CQL training.

Equivalent of the reference's offline stack
(``rllib/offline/offline_data.py`` — Datasets of recorded experience fed
to offline algorithms; ``rllib/algorithms/bc/bc.py``,
``rllib/algorithms/cql/cql.py``): experience is recorded to parquet via
``collect_offline_data`` (the reference records through RolloutWorker
output writers), read back as a ``ray_tpu.data.Dataset``, and consumed by

  * **BC** — behavior cloning: supervised ``-log pi(a|s)``;
  * **CQL** — conservative Q-learning (discrete): double-DQN TD loss plus
    the CQL regularizer ``alpha * (logsumexp_a Q(s,a) - Q(s, a_data))``
    that penalizes out-of-distribution action optimism.

Both train WITHOUT an environment; evaluation rolls the learned policy
in a live env only when one is configured.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from . import models
from .algorithm import Algorithm, AlgorithmConfig
from .learner_group import LearnerGroup


def collect_offline_data(env_cls, n_steps: int, path: str, *,
                         num_envs: int = 8, seed: int = 0,
                         policy_weights=None, policy_fn=None,
                         epsilon: float = 0.3) -> int:
    """Roll a behavior policy — MLP ``policy_weights``, a callable
    ``policy_fn(obs) -> actions``, or uniformly random — epsilon-greedily
    and write ``(obs, action, reward, next_obs, terminated)`` transitions
    to a parquet dataset at ``path``. Returns rows written."""
    from .env_runner import _np_forward

    rng = np.random.default_rng(seed)
    env = env_cls(num_envs=num_envs, seed=seed)
    obs = env.reset()
    rows = {"obs": [], "action": [], "reward": [], "next_obs": [], "terminated": []}
    steps = 0
    while steps < n_steps:
        if policy_fn is not None:
            greedy = np.asarray(policy_fn(obs))
            explore = rng.random(num_envs) < epsilon
            actions = np.where(explore, rng.integers(0, env.n_actions, num_envs), greedy)
        elif policy_weights is None:
            actions = rng.integers(0, env.n_actions, num_envs)
        else:
            logits, _ = _np_forward(policy_weights, obs)
            greedy = logits.argmax(axis=1)
            explore = rng.random(num_envs) < epsilon
            actions = np.where(explore, rng.integers(0, env.n_actions, num_envs), greedy)
        nxt, rewards, dones, info = env.step(actions)
        terminal_obs = info.get("terminal_obs")
        for i in range(num_envs):
            # At episode end `nxt` is the auto-reset obs; record the true
            # successor state for the TD target.
            succ = terminal_obs[i] if (dones[i] and terminal_obs is not None) else nxt[i]
            rows["obs"].append(np.asarray(obs[i], np.float32))
            rows["action"].append(int(actions[i]))
            rows["reward"].append(float(rewards[i]))
            rows["next_obs"].append(np.asarray(succ, np.float32))
            terminated = bool(dones[i]) and not bool(info["truncated"][i])
            rows["terminated"].append(terminated)
        obs = nxt
        steps += num_envs
    from .. import data as rd

    ds = rd.from_items([
        {k: rows[k][i] for k in rows} for i in range(len(rows["action"]))
    ], parallelism=4)
    ds.write_parquet(path)
    return len(rows["action"])


class OfflineConfig(AlgorithmConfig):
    """Shared config for env-free algorithms: the data source replaces
    the env; obs/action space comes from the data (or an optional
    eval env)."""

    def __init__(self):
        super().__init__()
        self.dataset = None            # ray_tpu.data.Dataset of transitions
        self.dataset_path: str | None = None  # or a parquet path
        self.batch_size = 256
        self.updates_per_iteration = 32
        self.hidden = 64
        self.eval_env_cls = None       # optional: rollout eval per iteration
        self.eval_episodes = 4

    def offline_data(self, *, dataset=None, dataset_path=None, batch_size=None,
                     updates_per_iteration=None) -> "OfflineConfig":
        if dataset is not None:
            self.dataset = dataset
        if dataset_path is not None:
            self.dataset_path = dataset_path
        if batch_size is not None:
            self.batch_size = batch_size
        if updates_per_iteration is not None:
            self.updates_per_iteration = updates_per_iteration
        return self

    def evaluation(self, *, eval_env_cls=None, eval_episodes=None) -> "OfflineConfig":
        if eval_env_cls is not None:
            self.eval_env_cls = eval_env_cls
        if eval_episodes is not None:
            self.eval_episodes = eval_episodes
        return self


class _OfflineAlgorithm(Algorithm):
    """Shared setup: resolve the dataset, infer dims, loop minibatches."""

    def _dataset(self):
        c: OfflineConfig = self.config  # type: ignore[assignment]
        if c.dataset is not None:
            return c.dataset
        if c.dataset_path is None:
            raise ValueError("offline algorithms need .offline_data(dataset=|dataset_path=)")
        from .. import data as rd

        return rd.read_parquet(c.dataset_path)

    def _load_transitions(self) -> dict:
        """Materialize the (bounded) dataset into flat numpy arrays once;
        iteration then shuffles minibatches from host RAM (the reference
        maps Dataset batches through the learner the same way)."""
        rows = self._dataset().take_all()
        obs = np.stack([np.asarray(r["obs"], np.float32) for r in rows])
        out = {
            "obs": obs,
            "actions": np.asarray([r["action"] for r in rows], np.int64),
        }
        if "reward" in rows[0]:
            out["rewards"] = np.asarray([r["reward"] for r in rows], np.float32)
            out["next_obs"] = np.stack(
                [np.asarray(r["next_obs"], np.float32) for r in rows])
            out["terminated"] = np.asarray(
                [float(r["terminated"]) for r in rows], np.float32)
        return out

    def _build_learner_group(self, loss_fn) -> None:
        """Shared by every offline algorithm: infer obs/action dims from
        the materialized transitions (eval env may widen the action
        space) and construct the LearnerGroup."""
        c: OfflineConfig = self.config  # type: ignore[assignment]
        obs_dim = self._transitions["obs"].shape[1]
        n_actions = int(self._transitions["actions"].max()) + 1
        if c.eval_env_cls is not None:
            n_actions = max(n_actions, c.eval_env_cls(num_envs=1).n_actions)
        self.learner_group = LearnerGroup(
            loss_fn,
            lambda key: models.init_policy(key, obs_dim, n_actions, c.hidden),
            num_learners=c.num_learners, lr=c.lr,
            max_grad_norm=c.max_grad_norm, seed=c.seed,
        )

    def _evaluate(self) -> float | None:
        c: OfflineConfig = self.config  # type: ignore[assignment]
        if c.eval_env_cls is None:
            return None
        from .env_runner import _np_forward

        weights = self.learner_group.get_weights()
        env = c.eval_env_cls(num_envs=c.eval_episodes, seed=c.seed + 1)
        obs = env.reset()
        done = np.zeros(c.eval_episodes, bool)
        returns = np.zeros(c.eval_episodes, np.float32)
        for _ in range(env.max_steps if hasattr(env, "max_steps") else 500):
            logits, _ = _np_forward(weights, obs)
            obs, rewards, dones, _ = env.step(logits.argmax(axis=1))
            returns += rewards * ~done
            done |= dones
            if done.all():
                break
        return float(returns.mean())


class BCConfig(OfflineConfig):
    pass


def make_bc_loss():
    def loss_fn(params, batch):
        logits, _ = models.forward(params, batch["obs"])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(logp_all, batch["actions"][:, None], axis=1)[:, 0]
        loss = -logp.mean()
        acc = (jnp.argmax(logits, axis=1) == batch["actions"]).mean()
        return loss, {"bc_loss": loss, "action_accuracy": acc}

    return loss_fn


class BC(_OfflineAlgorithm):
    def _setup(self) -> None:
        c: BCConfig = self.config  # type: ignore[assignment]
        self._transitions = self._load_transitions()
        self._build_learner_group(make_bc_loss())
        self.rng = np.random.default_rng(c.seed)

    def training_step(self) -> dict:
        c: BCConfig = self.config  # type: ignore[assignment]
        data, metrics = self._transitions, {}
        n = len(data["actions"])
        for _ in range(c.updates_per_iteration):
            idx = self.rng.integers(0, n, min(c.batch_size, n))
            metrics = self.learner_group.update(
                {"obs": data["obs"][idx], "actions": data["actions"][idx]})
        ret = self._evaluate()
        if ret is not None:
            metrics["episode_return_mean"] = ret
        return metrics

    def get_state(self) -> dict:
        return {"iteration": self.iteration, "learner": self.learner_group.get_state()}

    def set_state(self, state: dict) -> None:
        self.iteration = state["iteration"]
        self.learner_group.set_state(state["learner"])


BCConfig.algo_cls = BC


class CQLConfig(OfflineConfig):
    def __init__(self):
        super().__init__()
        self.gamma = 0.99
        self.cql_alpha = 1.0
        self.target_update_freq = 100
        self.lr = 5e-4

    def training(self, *, gamma=None, cql_alpha=None, target_update_freq=None,
                 **kwargs):
        for name, val in (("gamma", gamma), ("cql_alpha", cql_alpha),
                          ("target_update_freq", target_update_freq)):
            if val is not None:
                setattr(self, name, val)
        return super().training(**kwargs)


def make_cql_loss(gamma: float, cql_alpha: float):
    """Discrete CQL: double-DQN TD + conservative regularizer."""

    def loss_fn(params, batch):
        q_all, _ = models.forward(params, batch["obs"])
        q_sa = jnp.take_along_axis(q_all, batch["actions"][:, None], axis=1)[:, 0]
        q_next_t, _ = models.forward(batch["target_params"], batch["next_obs"])
        q_next_o, _ = models.forward(params, batch["next_obs"])
        a_sel = jnp.argmax(q_next_o, axis=1)
        q_next = jnp.take_along_axis(q_next_t, a_sel[:, None], axis=1)[:, 0]
        target = batch["rewards"] + gamma * (1.0 - batch["terminated"]) * q_next
        td = q_sa - jax.lax.stop_gradient(target)
        td_loss = jnp.mean(jnp.where(jnp.abs(td) < 1.0, 0.5 * td**2, jnp.abs(td) - 0.5))
        # Conservative term: push down Q on unseen actions relative to the
        # dataset's actions.
        cql_term = jnp.mean(jax.scipy.special.logsumexp(q_all, axis=1) - q_sa)
        loss = td_loss + cql_alpha * cql_term
        return loss, {
            "td_loss": td_loss,
            "cql_regularizer": cql_term,
            "q_data_mean": q_sa.mean(),
        }

    return loss_fn


class CQL(_OfflineAlgorithm):
    def _setup(self) -> None:
        c: CQLConfig = self.config  # type: ignore[assignment]
        if c.num_learners > 0:
            # The batch carries target_params (a pytree), which the
            # data-parallel shard-by-row path cannot split.
            raise ValueError("CQL supports num_learners=0 (single learner)")
        self._transitions = self._load_transitions()
        if "rewards" not in self._transitions:
            raise ValueError("CQL needs full transitions (reward/next_obs/terminated)")
        self._build_learner_group(make_cql_loss(c.gamma, c.cql_alpha))
        self.rng = np.random.default_rng(c.seed)
        self._target_params = self.learner_group.get_weights()
        self._updates = 0

    def training_step(self) -> dict:
        c: CQLConfig = self.config  # type: ignore[assignment]
        data, metrics = self._transitions, {}
        n = len(data["actions"])
        for _ in range(c.updates_per_iteration):
            idx = self.rng.integers(0, n, min(c.batch_size, n))
            metrics = self.learner_group.update({
                "obs": data["obs"][idx],
                "actions": data["actions"][idx],
                "rewards": data["rewards"][idx],
                "next_obs": data["next_obs"][idx],
                "terminated": data["terminated"][idx],
                "target_params": self._target_params,
            })
            self._updates += 1
            if self._updates % c.target_update_freq == 0:
                self._target_params = self.learner_group.get_weights()
        ret = self._evaluate()
        if ret is not None:
            metrics["episode_return_mean"] = ret
        return metrics

    def get_state(self) -> dict:
        return {"iteration": self.iteration, "learner": self.learner_group.get_state()}

    def set_state(self, state: dict) -> None:
        self.iteration = state["iteration"]
        self.learner_group.set_state(state["learner"])


CQLConfig.algo_cls = CQL


class MARWILConfig(OfflineConfig):
    """Monotonic advantage re-weighted imitation learning (reference
    ``rllib/algorithms/marwil/marwil.py``): BC where each action's
    log-prob is weighted by exp(beta * normalized advantage) — beta=0 IS
    plain BC; beta>0 imitates good actions preferentially."""

    def __init__(self):
        super().__init__()
        self.beta = 1.0
        self.gamma = 0.99
        self.vf_coeff = 1.0
        # moving-average horizon for the advantage-norm c^2 (the
        # reference's moving_average_sqd_adv_norm_update_rate)
        self.adv_norm_update_rate = 1e-3

    def training(self, *, beta=None, gamma=None, vf_coeff=None,
                 adv_norm_update_rate=None, **kwargs):
        for name, val in (("beta", beta), ("gamma", gamma),
                          ("vf_coeff", vf_coeff),
                          ("adv_norm_update_rate", adv_norm_update_rate)):
            if val is not None:
                setattr(self, name, val)
        return super().training(**kwargs)


def make_marwil_loss(beta: float, gamma: float, vf_coeff: float):
    """batch: obs, actions, rewards, next_obs, terminated, adv_norm
    (scalar: the moving c = sqrt(E[adv^2]) maintained by the algorithm).
    One-step TD advantage against the learned value head; weight =
    exp(beta * adv / c), clipped for stability."""

    def loss_fn(params, batch):
        logits, v = models.forward(params, batch["obs"])
        _, v_next = models.forward(params, batch["next_obs"])
        td_target = batch["rewards"] + gamma * (
            1.0 - batch["terminated"]) * jax.lax.stop_gradient(v_next)
        adv = jax.lax.stop_gradient(td_target) - v
        vf_loss = (adv ** 2).mean()
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["actions"][:, None], axis=1)[:, 0]
        c = jnp.maximum(batch["adv_norm"], 1e-8)
        weight = jnp.exp(jnp.clip(
            beta * jax.lax.stop_gradient(adv) / c, -20.0, 2.0))
        policy_loss = -(weight * logp).mean()
        loss = policy_loss + vf_coeff * vf_loss
        acc = (jnp.argmax(logits, axis=1) == batch["actions"]).mean()
        return loss, {
            "marwil_loss": loss,
            "policy_loss": policy_loss,
            "vf_loss": vf_loss,
            "action_accuracy": acc,
            "mean_sqd_adv": (adv ** 2).mean(),
        }

    return loss_fn


class MARWIL(_OfflineAlgorithm):
    def _setup(self) -> None:
        c: MARWILConfig = self.config  # type: ignore[assignment]
        self._transitions = self._load_transitions()
        if "rewards" not in self._transitions:
            raise ValueError(
                "MARWIL needs reward/next_obs/terminated columns in the "
                "offline dataset (collect_offline_data writes them)")
        self._build_learner_group(make_marwil_loss(c.beta, c.gamma, c.vf_coeff))
        self.rng = np.random.default_rng(c.seed)
        self._ma_sqd_adv = 1.0  # moving E[adv^2]; c = sqrt of this

    def training_step(self) -> dict:
        c: MARWILConfig = self.config  # type: ignore[assignment]
        data, metrics = self._transitions, {}
        n = len(data["actions"])
        for _ in range(c.updates_per_iteration):
            idx = self.rng.integers(0, n, min(c.batch_size, n))
            batch = {
                "obs": data["obs"][idx],
                "actions": data["actions"][idx],
                "rewards": data["rewards"][idx],
                "next_obs": data["next_obs"][idx],
                "terminated": data["terminated"][idx],
                # per-ROW so LearnerGroup._shard_batch can index it
                "adv_norm": np.full(len(idx),
                                    max(self._ma_sqd_adv, 1e-8) ** 0.5,
                                    np.float32),
            }
            metrics = self.learner_group.update(batch)
            rate = c.adv_norm_update_rate
            self._ma_sqd_adv += rate * (
                float(metrics["mean_sqd_adv"]) - self._ma_sqd_adv)
        ret = self._evaluate()
        if ret is not None:
            metrics["episode_return_mean"] = ret
        metrics["adv_norm"] = self._ma_sqd_adv ** 0.5
        return metrics

    def get_state(self) -> dict:
        return {"iteration": self.iteration,
                "learner": self.learner_group.get_state(),
                "ma_sqd_adv": self._ma_sqd_adv}

    def set_state(self, state: dict) -> None:
        self.iteration = state["iteration"]
        self.learner_group.set_state(state["learner"])
        self._ma_sqd_adv = state["ma_sqd_adv"]


MARWILConfig.algo_cls = MARWIL
