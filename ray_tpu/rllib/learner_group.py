"""LearnerGroup: one local Learner or N Learner actors, data-parallel.

Equivalent of ``rllib/core/learner/learner_group.py``: ``num_learners=0``
runs the Learner in-process (debug / single host); ``num_learners>=1``
spawns Learner actors, shards each batch across them, averages their
gradients, and applies the averaged update on every learner so weights
stay bit-identical (synchronous DDP semantics without NCCL — gradients
ride the object store).
"""

from __future__ import annotations

import numpy as np

from .learner import Learner, average_gradients


class _LearnerActor:
    """Remote wrapper: built from pickled constructor pieces so the actor
    process never imports algorithm modules."""

    def __init__(self, loss_fn, init_params_fn, lr, max_grad_norm, seed):
        self.learner = Learner(
            loss_fn, init_params_fn, lr=lr, max_grad_norm=max_grad_norm, seed=seed
        )

    def compute_gradients(self, batch):
        return self.learner.compute_gradients(batch)

    def apply_gradients(self, grads):
        self.learner.apply_gradients(grads)
        return True

    def get_weights(self):
        return self.learner.get_weights()

    def set_state(self, state):
        self.learner.set_state(state)
        return True

    def get_state(self):
        return self.learner.get_state()


class LearnerGroup:
    def __init__(
        self,
        loss_fn,
        init_params_fn,
        *,
        num_learners: int = 0,
        lr: float = 3e-4,
        max_grad_norm: float = 0.5,
        seed: int = 0,
    ):
        self.num_learners = num_learners
        if num_learners == 0:
            self._local = Learner(
                loss_fn, init_params_fn, lr=lr, max_grad_norm=max_grad_norm, seed=seed
            )
            self._actors = []
        else:
            from ..core import api as ray

            self._local = None
            cls = ray.remote(_LearnerActor)
            # Same seed everywhere: learners must start (and stay) identical.
            self._actors = [
                cls.remote(loss_fn, init_params_fn, lr, max_grad_norm, seed)
                for _ in range(num_learners)
            ]
            ray.get([a.get_weights.remote() for a in self._actors], timeout=120)

    def update(self, batch: dict) -> dict:
        """One synchronous data-parallel update over the full batch."""
        if self._local is not None:
            return self._local.update(batch)
        from ..core import api as ray

        # Never hand an actor an empty shard (empty-mean NaNs would poison
        # the average); idle actors still apply the averaged grads so all
        # replicas stay identical.
        size = len(next(iter(batch.values())))
        n = max(1, min(len(self._actors), size))
        shards = _shard_batch(batch, n)
        outs = ray.get(
            [a.compute_gradients.remote(s) for a, s in zip(self._actors[:n], shards)],
            timeout=300,
        )
        grads = average_gradients([g for g, _ in outs])
        ray.get([a.apply_gradients.remote(grads) for a in self._actors], timeout=300)
        metrics_list = [m for _, m in outs]
        return {k: float(np.mean([m[k] for m in metrics_list])) for k in metrics_list[0]}

    def get_weights(self):
        if self._local is not None:
            return self._local.get_weights()
        from ..core import api as ray

        return ray.get(self._actors[0].get_weights.remote(), timeout=120)

    def get_state(self) -> dict:
        if self._local is not None:
            return self._local.get_state()
        from ..core import api as ray

        return ray.get(self._actors[0].get_state.remote(), timeout=120)

    def set_state(self, state: dict) -> None:
        if self._local is not None:
            self._local.set_state(state)
            return
        from ..core import api as ray

        ray.get([a.set_state.remote(state) for a in self._actors], timeout=120)

    def shutdown(self) -> None:
        from ..core import api as ray

        for a in self._actors:
            try:
                ray.kill(a)
            except Exception:
                pass
        self._actors = []


def _shard_batch(batch: dict, n: int) -> list[dict]:
    size = len(next(iter(batch.values())))
    idx = np.array_split(np.arange(size), n)
    return [{k: v[i] for k, v in batch.items()} for i in idx]
