"""Actor-critic MLP in plain JAX pytrees.

Plays the role of RLlib's RLModule (``rllib/core/rl_module/rl_module.py``):
``forward(params, obs) -> (logits, value)``. Kept framework-free (no
flax/haiku) to match the rest of the repo's param-tree convention — the
Learner shards these trees with the same machinery as the Llama models.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def init_policy(key: jax.Array, obs_dim: int, n_actions: int, hidden: int = 64) -> dict:
    k1, k2, k3, k4 = jax.random.split(key, 4)

    def dense(k, i, o):
        return {
            "w": jax.random.normal(k, (i, o), jnp.float32) * (2.0 / i) ** 0.5,
            "b": jnp.zeros((o,), jnp.float32),
        }

    return {
        "torso": [dense(k1, obs_dim, hidden), dense(k2, hidden, hidden)],
        "pi": dense(k3, hidden, n_actions),
        "vf": dense(k4, hidden, 1),
    }


def forward(params: dict, obs: jax.Array):
    """obs [B, obs_dim] -> (logits [B, A], value [B])."""
    x = obs
    for layer in params["torso"]:
        x = jnp.tanh(x @ layer["w"] + layer["b"])
    logits = x @ params["pi"]["w"] + params["pi"]["b"]
    value = (x @ params["vf"]["w"] + params["vf"]["b"])[:, 0]
    return logits, value
