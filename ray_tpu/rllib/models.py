"""Actor-critic MLP in plain JAX pytrees.

Plays the role of RLlib's RLModule (``rllib/core/rl_module/rl_module.py``):
``forward(params, obs) -> (logits, value)``. Kept framework-free (no
flax/haiku) to match the rest of the repo's param-tree convention — the
Learner shards these trees with the same machinery as the Llama models.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def init_policy(key: jax.Array, obs_dim: int, n_actions: int, hidden: int = 64) -> dict:
    k1, k2, k3, k4 = jax.random.split(key, 4)

    def dense(k, i, o):
        return {
            "w": jax.random.normal(k, (i, o), jnp.float32) * (2.0 / i) ** 0.5,
            "b": jnp.zeros((o,), jnp.float32),
        }

    return {
        "torso": [dense(k1, obs_dim, hidden), dense(k2, hidden, hidden)],
        "pi": dense(k3, hidden, n_actions),
        "vf": dense(k4, hidden, 1),
    }


def forward(params: dict, obs: jax.Array):
    """obs [B, obs_dim] -> (logits [B, A], value [B])."""
    x = obs
    for layer in params["torso"]:
        x = jnp.tanh(x @ layer["w"] + layer["b"])
    logits = x @ params["pi"]["w"] + params["pi"]["b"]
    value = (x @ params["vf"]["w"] + params["vf"]["b"])[:, 0]
    return logits, value


# ---------------------------------------------------------------- SAC nets
# Continuous control (reference: rllib/algorithms/sac/sac_catalog.py):
# a squashed-Gaussian policy head and twin Q networks over (obs, action).


def _dense_stack(key, dims):
    layers = []
    for k, (i, o) in zip(jax.random.split(key, len(dims) - 1),
                         zip(dims[:-1], dims[1:])):
        layers.append({
            "w": jax.random.normal(k, (i, o), jnp.float32) * (2.0 / i) ** 0.5,
            "b": jnp.zeros((o,), jnp.float32),
        })
    return layers


def init_gaussian_policy(key, obs_dim: int, action_dim: int, hidden: int = 64) -> dict:
    kt, kh = jax.random.split(key)
    return {
        "torso": _dense_stack(kt, (obs_dim, hidden, hidden)),
        # one head emits [mean, log_std] stacked
        "head": _dense_stack(kh, (hidden, 2 * action_dim))[0],
    }


def gaussian_forward(policy: dict, obs: jax.Array):
    """obs [B, D] -> (mean [B, A], log_std [B, A]), log_std clamped to
    the SAC-standard [-20, 2]."""
    x = obs
    for layer in policy["torso"]:
        x = jnp.tanh(x @ layer["w"] + layer["b"])
    out = x @ policy["head"]["w"] + policy["head"]["b"]
    mean, log_std = jnp.split(out, 2, axis=-1)
    return mean, jnp.clip(log_std, -20.0, 2.0)


def init_q(key, obs_dim: int, action_dim: int, hidden: int = 64) -> list:
    return _dense_stack(key, (obs_dim + action_dim, hidden, hidden, 1))


def q_forward(qnet: list, obs: jax.Array, action: jax.Array) -> jax.Array:
    """(obs [B, D], action [B, A]) -> q [B]."""
    x = jnp.concatenate([obs, action], axis=-1)
    for layer in qnet[:-1]:
        x = jnp.tanh(x @ layer["w"] + layer["b"])
    return (x @ qnet[-1]["w"] + qnet[-1]["b"])[:, 0]
