"""SAC (soft actor-critic) for continuous control.

Equivalent of ``rllib/algorithms/sac/sac.py`` + ``sac_learner`` (torch):
squashed-Gaussian policy, twin Q networks with polyak-averaged targets,
and automatic entropy-temperature tuning. TPU redesign: the whole update
— critic step, actor step, alpha step, polyak — is ONE jitted function
over a state pytree, so a training iteration dispatches once per
minibatch instead of the reference's per-loss-term optimizer round
trips; rollouts stay numpy on the env runners (same split as PPO/DQN).
"""

from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp
import optax

from .algorithm import Algorithm, AlgorithmConfig
from .env_runner import EnvRunnerGroup
from .models import gaussian_forward, init_gaussian_policy, init_q, q_forward
from .replay import ReplayBuffer

_LOG_2PI = math.log(2.0 * math.pi)


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        # Defaults solve Pendulum in ~50 iterations (~30k env steps):
        # ~1 update per 2 env steps, 128-wide nets (the reference's SAC
        # tuned-example ballpark).
        self.gamma = 0.99
        self.lr = 1e-3
        self.alpha_lr = 1e-3
        self.hidden = 128
        self.buffer_size = 100_000
        self.batch_size = 128
        self.learning_starts = 500
        self.updates_per_iteration = 128
        self.tau = 0.005               # polyak rate for the target critics
        self.target_entropy = None     # default: -action_dim
        self.init_alpha = 1.0
        self.rollout_len = 16

    def training(self, *, gamma=None, buffer_size=None, batch_size=None,
                 learning_starts=None, updates_per_iteration=None, tau=None,
                 target_entropy=None, init_alpha=None, alpha_lr=None,
                 hidden=None, **kwargs):
        for name, val in (("gamma", gamma), ("buffer_size", buffer_size),
                          ("batch_size", batch_size),
                          ("learning_starts", learning_starts),
                          ("updates_per_iteration", updates_per_iteration),
                          ("tau", tau), ("target_entropy", target_entropy),
                          ("init_alpha", init_alpha), ("alpha_lr", alpha_lr),
                          ("hidden", hidden)):
            if val is not None:
                setattr(self, name, val)
        return super().training(**kwargs)


def _sample_squashed(policy, obs, key, max_action: float):
    """Reparameterized tanh-Gaussian sample with its log-prob (the
    change-of-variables correction included)."""
    mean, log_std = gaussian_forward(policy, obs)
    eps = jax.random.normal(key, mean.shape)
    pre = mean + jnp.exp(log_std) * eps
    tanh_a = jnp.tanh(pre)
    logp_gauss = (-0.5 * (eps**2 + _LOG_2PI) - log_std).sum(axis=-1)
    correction = jnp.log(
        max_action * (1.0 - tanh_a**2) + 1e-6).sum(axis=-1)
    return tanh_a * max_action, logp_gauss - correction


def make_sac_update(*, gamma: float, tau: float, target_entropy: float,
                    max_action: float, lr: float, alpha_lr: float):
    """Build (init_opt_states, jitted update). State pytree:
    {params: {policy, q1, q2}, target: {q1, q2}, log_alpha, opt: {...}}."""
    pi_opt = optax.adam(lr)
    q_opt = optax.adam(lr)
    a_opt = optax.adam(alpha_lr)

    def init_opt(params, log_alpha):
        return {
            "pi": pi_opt.init(params["policy"]),
            "q": q_opt.init({"q1": params["q1"], "q2": params["q2"]}),
            "alpha": a_opt.init(log_alpha),
        }

    @jax.jit
    def update(state, batch, key):
        params, target = state["params"], state["target"]
        log_alpha, opt = state["log_alpha"], state["opt"]
        alpha = jnp.exp(log_alpha)
        k_next, k_cur = jax.random.split(key)

        # ---- critic: y = r + γ(1-term)(min Q'(s', a') - α log π(a'|s'))
        a_next, logp_next = _sample_squashed(
            params["policy"], batch["next_obs"], k_next, max_action)
        q_next = jnp.minimum(
            q_forward(target["q1"], batch["next_obs"], a_next),
            q_forward(target["q2"], batch["next_obs"], a_next),
        ) - alpha * logp_next
        y = batch["rewards"] + gamma * (1.0 - batch["terminated"]) * q_next
        y = jax.lax.stop_gradient(y)

        def critic_loss(qs):
            q1 = q_forward(qs["q1"], batch["obs"], batch["actions"])
            q2 = q_forward(qs["q2"], batch["obs"], batch["actions"])
            return ((q1 - y) ** 2 + (q2 - y) ** 2).mean(), q1.mean()

        (closs, q_mean), cgrads = jax.value_and_grad(critic_loss, has_aux=True)(
            {"q1": params["q1"], "q2": params["q2"]})
        qup, opt_q = q_opt.update(cgrads, opt["q"])
        new_qs = optax.apply_updates({"q1": params["q1"], "q2": params["q2"]}, qup)

        # ---- actor: α log π(a|s) - min Q(s, a), a reparameterized
        def actor_loss(policy):
            a, logp = _sample_squashed(policy, batch["obs"], k_cur, max_action)
            q = jnp.minimum(q_forward(new_qs["q1"], batch["obs"], a),
                            q_forward(new_qs["q2"], batch["obs"], a))
            return (alpha * logp - q).mean(), logp.mean()

        (aloss, logp_mean), pgrads = jax.value_and_grad(actor_loss, has_aux=True)(
            params["policy"])
        pup, opt_pi = pi_opt.update(pgrads, opt["pi"])
        new_policy = optax.apply_updates(params["policy"], pup)

        # ---- temperature: drive E[log π] toward -target_entropy
        def alpha_loss(la):
            return -(la * jax.lax.stop_gradient(logp_mean + target_entropy))

        alps, agrads = jax.value_and_grad(alpha_loss)(log_alpha)
        aup, opt_a = a_opt.update(agrads, opt["alpha"])
        new_log_alpha = optax.apply_updates(log_alpha, aup)

        # ---- polyak target tracking
        new_target = jax.tree.map(
            lambda t, o: (1.0 - tau) * t + tau * o, target, new_qs)

        new_state = {
            "params": {"policy": new_policy, **new_qs},
            "target": new_target,
            "log_alpha": new_log_alpha,
            "opt": {"pi": opt_pi, "q": opt_q, "alpha": opt_a},
        }
        metrics = {
            "critic_loss": closs,
            "actor_loss": aloss,
            "alpha_loss": alps,
            "alpha": alpha,
            "q_mean": q_mean,
            "logp_mean": logp_mean,
        }
        return new_state, metrics

    return init_opt, update


def _np_gaussian(policy, obs: np.ndarray):
    x = obs
    for layer in policy["torso"]:
        x = np.tanh(x @ np.asarray(layer["w"]) + np.asarray(layer["b"]))
    out = x @ np.asarray(policy["head"]["w"]) + np.asarray(policy["head"]["b"])
    mean, log_std = np.split(out, 2, axis=-1)
    return mean, np.clip(log_std, -20.0, 2.0)


class SACEnvRunner:
    """Continuous-action transition collector: samples from the
    squashed Gaussian in numpy (no device round trip per env step)."""

    def __init__(self, env_cls, num_envs: int = 8, rollout_len: int = 32,
                 seed: int = 0):
        self.env = env_cls(num_envs=num_envs, seed=seed)
        self.num_envs = num_envs
        self.rollout_len = rollout_len
        self.rng = np.random.default_rng(seed ^ 0x5AC)
        self.obs = self.env.reset()
        self._ep_return = np.zeros(num_envs, np.float32)
        self._completed: list[float] = []

    def sample(self, weights, random_actions: bool = False) -> dict:
        T, N = self.rollout_len, self.num_envs
        A = self.env.action_dim
        max_a = self.env.max_action
        obs_b = np.zeros((T, N, self.env.obs_dim), np.float32)
        act_b = np.zeros((T, N, A), np.float32)
        rew_b = np.zeros((T, N), np.float32)
        next_b = np.zeros((T, N, self.env.obs_dim), np.float32)
        term_b = np.zeros((T, N), np.float32)
        for t in range(T):
            if random_actions:  # warmup: uniform exploration
                actions = self.rng.uniform(-max_a, max_a, (N, A)).astype(np.float32)
            else:
                mean, log_std = _np_gaussian(weights, self.obs)
                pre = mean + np.exp(log_std) * self.rng.standard_normal(mean.shape)
                actions = (np.tanh(pre) * max_a).astype(np.float32)
            obs_b[t], act_b[t] = self.obs, actions
            self.obs, rewards, dones, info = self.env.step(actions[:, 0] if A == 1 else actions)
            rew_b[t] = rewards
            next_b[t] = np.where(dones[:, None], info["terminal_obs"], self.obs)
            term_b[t] = info["terminated"].astype(np.float32)
            self._ep_return += rewards
            for i in np.nonzero(dones)[0]:
                self._completed.append(float(self._ep_return[i]))
                self._ep_return[i] = 0.0
        completed, self._completed = self._completed, []
        return {
            "obs": obs_b.reshape(T * N, -1),
            "actions": act_b.reshape(T * N, A),
            "rewards": rew_b.reshape(-1),
            "next_obs": next_b.reshape(T * N, -1),
            "terminated": term_b.reshape(-1),
            "episode_returns": np.asarray(completed, np.float32),
        }


class SAC(Algorithm):
    def _setup(self) -> None:
        c: SACConfig = self.config  # type: ignore[assignment]
        env_probe = c.env_cls(num_envs=1)
        obs_dim, act_dim = env_probe.obs_dim, env_probe.action_dim
        self._max_action = float(env_probe.max_action)
        target_entropy = (c.target_entropy if c.target_entropy is not None
                          else -float(act_dim))

        key = jax.random.PRNGKey(c.seed)
        kp, k1, k2, self._key = jax.random.split(key, 4)
        params = {
            "policy": init_gaussian_policy(kp, obs_dim, act_dim, c.hidden),
            "q1": init_q(k1, obs_dim, act_dim, c.hidden),
            "q2": init_q(k2, obs_dim, act_dim, c.hidden),
        }
        log_alpha = jnp.asarray(math.log(c.init_alpha), jnp.float32)
        init_opt, self._update = make_sac_update(
            gamma=c.gamma, tau=c.tau, target_entropy=target_entropy,
            max_action=self._max_action, lr=c.lr, alpha_lr=c.alpha_lr)
        self.state = {
            "params": params,
            "target": {"q1": params["q1"], "q2": params["q2"]},
            "log_alpha": log_alpha,
            "opt": init_opt(params, log_alpha),
        }
        self.env_runner_group = EnvRunnerGroup(
            c.env_cls,
            num_env_runners=c.num_env_runners,
            num_envs_per_runner=c.num_envs_per_runner,
            rollout_len=c.rollout_len,
            seed=c.seed,
            runner_cls=SACEnvRunner,
        )
        self.buffer = ReplayBuffer(c.buffer_size, obs_dim, seed=c.seed,
                                   action_dim=act_dim)
        self._env_steps = 0
        self._recent_returns: list[float] = []

    def _weights(self):
        return jax.tree.map(np.asarray, self.state["params"]["policy"])

    def training_step(self) -> dict:
        c: SACConfig = self.config  # type: ignore[assignment]
        warmup = len(self.buffer) < c.learning_starts
        samples = self.env_runner_group.sample(
            self._weights(), random_actions=warmup)
        for s in samples:
            self.buffer.add_batch(s["obs"], s["actions"], s["rewards"],
                                  s["next_obs"], s["terminated"])
            self._env_steps += len(s["actions"])
            self._recent_returns.extend(s["episode_returns"].tolist())

        metrics: dict = {}
        if len(self.buffer) >= c.learning_starts:
            for _ in range(c.updates_per_iteration):
                batch = self.buffer.sample(c.batch_size)
                self._key, sub = jax.random.split(self._key)
                self.state, m = self._update(self.state, batch, sub)
            metrics = {k: float(v) for k, v in m.items()}

        self._recent_returns = self._recent_returns[-100:]
        metrics["episode_return_mean"] = (
            float(np.mean(self._recent_returns)) if self._recent_returns else 0.0
        )
        metrics["num_env_steps_sampled"] = self._env_steps
        metrics["buffer_size"] = len(self.buffer)
        return metrics

    def get_state(self) -> dict:
        return {
            "iteration": self.iteration,
            "state": jax.tree.map(np.asarray, self.state),
            "env_steps": self._env_steps,
        }

    def set_state(self, state: dict) -> None:
        self.iteration = state["iteration"]
        self.state = jax.tree.map(jnp.asarray, state["state"])
        self._env_steps = state["env_steps"]


SACConfig.algo_cls = SAC
