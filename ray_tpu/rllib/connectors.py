"""ConnectorV2: composable data-transform pipelines for RL.

Reference: ``rllib/connectors/connector_v2.py`` — the new-API-stack
abstraction for everything that happens to data BETWEEN the env, the
module, and the learner: observation preprocessing before action
computation (env-to-module), and batch preprocessing before an update
(learner). Instead of hand-rolling normalization inside every
algorithm, a pipeline of small pieces is configured once and applied at
the two seams:

  * ``EnvRunner`` applies the env-to-module pipeline to every
    observation it feeds the policy AND records the TRANSFORMED
    observation in the rollout, so the learner trains on exactly what
    the policy saw (the invariant the reference's connector design
    exists to guarantee).
  * Algorithms apply the learner pipeline to each sampled batch before
    the update.

Pieces are stateful (e.g. running mean/std) and checkpointable via
``get_state``/``set_state``; each env-runner owns its own instance, as
in the reference.
"""

from __future__ import annotations

from typing import Any

import numpy as np


class ConnectorV2:
    """One transform piece: ``batch`` is a dict of arrays; return the
    (possibly mutated) dict."""

    def __call__(self, batch: dict, **kwargs) -> dict:
        raise NotImplementedError

    def get_state(self) -> dict:
        return {}

    def set_state(self, state: dict) -> None:
        pass


class ConnectorPipelineV2(ConnectorV2):
    """Ordered composition of pieces (reference ConnectorPipelineV2)."""

    def __init__(self, pieces: list[ConnectorV2] | None = None):
        self.pieces = list(pieces or [])

    def __call__(self, batch: dict, **kwargs) -> dict:
        for p in self.pieces:
            batch = p(batch, **kwargs)
        return batch

    def append(self, piece: ConnectorV2) -> "ConnectorPipelineV2":
        self.pieces.append(piece)
        return self

    def get_state(self) -> dict:
        return {i: p.get_state() for i, p in enumerate(self.pieces)}

    def set_state(self, state: dict) -> None:
        for i, p in enumerate(self.pieces):
            if i in state:
                p.set_state(state[i])


class NormalizeObservations(ConnectorV2):
    """Running mean/std observation normalizer (Welford accumulation),
    the standard MuJoCo-style preprocessing (reference
    ``connectors/env_to_module/mean_std_filter.py``)."""

    def __init__(self, clip: float | None = 10.0, update: bool = True):
        self.clip = clip
        self.update = update
        self._count = 0.0
        self._mean: np.ndarray | None = None
        self._m2: np.ndarray | None = None

    def __call__(self, batch: dict, **kwargs) -> dict:
        obs = np.asarray(batch["obs"], np.float32)
        flat = obs.reshape(-1, obs.shape[-1])
        if self._mean is None:
            self._mean = np.zeros(obs.shape[-1], np.float64)
            self._m2 = np.ones(obs.shape[-1], np.float64)
        if self.update:
            for row in flat:
                self._count += 1.0
                d = row - self._mean
                self._mean += d / self._count
                self._m2 += d * (row - self._mean)
        std = np.sqrt(self._m2 / max(self._count, 1.0)) + 1e-8
        out = (obs - self._mean.astype(np.float32)) / std.astype(np.float32)
        if self.clip is not None:
            out = np.clip(out, -self.clip, self.clip)
        batch = dict(batch)
        batch["obs"] = out.astype(np.float32)
        return batch

    def get_state(self) -> dict:
        return {"count": self._count,
                "mean": None if self._mean is None else self._mean.copy(),
                "m2": None if self._m2 is None else self._m2.copy()}

    def set_state(self, state: dict) -> None:
        self._count = state["count"]
        self._mean = state["mean"]
        self._m2 = state["m2"]


class ClipRewards(ConnectorV2):
    """Learner-side reward clipping (reference Atari-style preprocessing)."""

    def __init__(self, limit: float = 1.0):
        self.limit = limit

    def __call__(self, batch: dict, **kwargs) -> dict:
        if "rewards" in batch:
            batch = dict(batch)
            batch["rewards"] = np.clip(batch["rewards"], -self.limit, self.limit)
        return batch


class ScaleObservations(ConnectorV2):
    """Fixed affine observation scaling (e.g. pixel / 255)."""

    def __init__(self, scale: float, offset: float = 0.0):
        self.scale = scale
        self.offset = offset

    def __call__(self, batch: dict, **kwargs) -> dict:
        batch = dict(batch)
        batch["obs"] = (np.asarray(batch["obs"], np.float32) - self.offset) * self.scale
        return batch


class LambdaConnector(ConnectorV2):
    """Wrap a plain function as a piece."""

    def __init__(self, fn):
        self._fn = fn

    def __call__(self, batch: dict, **kwargs) -> dict:
        return self._fn(batch)


def make_pipeline(spec: Any) -> ConnectorPipelineV2 | None:
    """None | piece | list | factory -> pipeline instance (a factory is
    called with no args so each env-runner gets its OWN stateful copy)."""
    if spec is None:
        return None
    if callable(spec) and not isinstance(spec, ConnectorV2):
        spec = spec()
    if isinstance(spec, ConnectorPipelineV2):
        return spec
    if isinstance(spec, ConnectorV2):
        return ConnectorPipelineV2([spec])
    return ConnectorPipelineV2(list(spec))
