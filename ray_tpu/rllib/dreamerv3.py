"""DreamerV3: model-based RL via an RSSM world model + imagination.

Equivalent of ``rllib/algorithms/dreamerv3/dreamerv3.py`` (+
``dreamerv3_learner``, ``utils/``): a recurrent state-space world model
(GRU deterministic path + categorical stochastic latents) trained on
replayed sequences, and an actor-critic trained entirely on imagined
rollouts through the model's prior dynamics. The paper's robustness
kit is kept: symlog observation/reward targets, twohot reward/value
distributions on symexp-spaced bins, 1% unimix on every categorical,
KL free bits with the dyn/rep split, percentile-EMA return
normalization, and a slow critic regularizer.

TPU redesign vs the reference (torch, per-module optimizer steps):

- The ENTIRE training step — posterior scan over the sequence batch,
  world-model losses, imagination scan, actor + critic losses, all
  three optimizer updates, the slow-critic polyak, and the return-scale
  EMA — is ONE jitted function over a single state pytree: one dispatch
  per update, both scans are ``lax.scan`` (static shapes, MXU-friendly
  batched matmuls), no host round trips inside the step.
- Acting is a second small jitted function carrying (h, z, prev_action)
  per env, so collection costs one dispatch per vector-env step.

Simplifications vs the reference, stated: vector observations only (the
encoder/decoder are MLPs; the reference adds CNN towers for pixels) and
the imagination horizon is a config constant. One deliberate deviation:
the reward and continue heads are ACTION-CONDITIONED — they predict
r(s, a) / c(s, a) at departure instead of the paper's r(s') at arrival.
With auto-resetting vector envs the terminal observation is never part
of the stored stream (the step after a termination carries the NEXT
episode's first obs), so an arrival-reward head can never observe a
cont=0 state and imagination learns to hallucinate immortal episodes;
conditioning on (state, action) puts the targets exactly on what each
replay record stores and keeps every termination in the training
signal.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
import optax

from .algorithm import Algorithm, AlgorithmConfig
from .models import _dense_stack

# ------------------------------------------------------------------ symlog


def symlog(x):
    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def symexp(x):
    return jnp.sign(x) * jnp.expm1(jnp.abs(x))


# ------------------------------------------------------------------ twohot
# Bins are uniform in symlog space (= symexp-spaced in raw space, the
# paper's layout). Encode clips to the support.

_NBINS = 63
_BMAX = 15.0
_BINS = jnp.linspace(-_BMAX, _BMAX, _NBINS)  # symlog-space bin centers


def twohot(y):
    """Symlog-space scalar ``y [...]`` -> soft two-hot target [..., NBINS]."""
    y = jnp.clip(y, -_BMAX, _BMAX)
    pos = (y + _BMAX) / (2 * _BMAX) * (_NBINS - 1)
    k0 = jnp.clip(jnp.floor(pos).astype(jnp.int32), 0, _NBINS - 2)
    frac = pos - k0
    lo = jax.nn.one_hot(k0, _NBINS) * (1.0 - frac)[..., None]
    hi = jax.nn.one_hot(k0 + 1, _NBINS) * frac[..., None]
    return lo + hi


def twohot_decode(logits):
    """Distribution logits [..., NBINS] -> raw-space scalar [...]."""
    return symexp(jax.nn.softmax(logits, -1) @ _BINS)


def _ce(logits, target):
    """Cross-entropy of a twohot target against logits, last dim."""
    return -(target * jax.nn.log_softmax(logits, -1)).sum(-1)


# ------------------------------------------------------------------ layers


def _mlp(key, sizes):
    return _dense_stack(key, tuple(sizes))


def _dense(key, i, o):
    return _dense_stack(key, (i, o))[0]


def _mlp_fwd(layers, x, out_linear=True):
    for i, l in enumerate(layers):
        x = x @ l["w"] + l["b"]
        if i < len(layers) - 1 or not out_linear:
            x = jax.nn.silu(x)
    return x


def _gru_init(key, in_dim, deter):
    k1, k2, k3 = jax.random.split(key, 3)
    return {"r": _dense(k1, in_dim + deter, deter),
            "u": _dense(k2, in_dim + deter, deter),
            "c": _dense(k3, in_dim + deter, deter)}


def _gru(p, x, h):
    xh = jnp.concatenate([x, h], -1)
    r = jax.nn.sigmoid(xh @ p["r"]["w"] + p["r"]["b"])
    u = jax.nn.sigmoid(xh @ p["u"]["w"] + p["u"]["b"])
    xrh = jnp.concatenate([x, r * h], -1)
    c = jnp.tanh(xrh @ p["c"]["w"] + p["c"]["b"])
    return u * h + (1.0 - u) * c


def _unimix(logits, classes):
    """1% uniform mixture on a categorical (paper §'unimix')."""
    probs = 0.99 * jax.nn.softmax(logits, -1) + 0.01 / classes
    return jnp.log(probs)


def _sample_st(key, logits, classes):
    """Straight-through one-hot sample from unimixed logits [..., G, C]."""
    logits = _unimix(logits, classes)
    idx = jax.random.categorical(key, logits)
    onehot = jax.nn.one_hot(idx, classes)
    probs = jax.nn.softmax(logits, -1)
    return onehot + probs - jax.lax.stop_gradient(probs)


# ------------------------------------------------------------------ config


class DreamerV3Config(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        # Model sizes (the paper's XS-ish preset, scaled for vector obs).
        self.deter = 256
        self.stoch_groups = 8
        self.stoch_classes = 8
        self.hidden = 256
        # Training.
        self.gamma = 0.997
        self.lam = 0.95
        self.seq_len = 32
        self.batch_size = 16
        self.imag_horizon = 15
        self.wm_lr = 6e-4
        self.actor_lr = 3e-4
        self.critic_lr = 3e-4
        self.free_bits = 1.0
        self.kl_dyn = 0.5
        self.kl_rep = 0.1
        self.entropy_scale = 3e-4
        self.slow_critic_tau = 0.02
        self.slow_critic_scale = 0.3
        self.buffer_size = 4096       # steps kept per env stream
        self.learning_starts = 256    # total env steps before updating
        self.updates_per_iteration = 8
        self.rollout_len = 64

    def training(self, **kw):
        known = {k for k in vars(self) if not k.startswith("_")}
        passthrough = {}
        for name, val in kw.items():
            if name in known:
                setattr(self, name, val)
            else:
                passthrough[name] = val
        return super().training(**passthrough)


DreamerV3Config.algo_cls = None  # set below


# ------------------------------------------------------------------ model


def _init_world_model(key, cfg, obs_dim, n_actions):
    G, C = cfg.stoch_groups, cfg.stoch_classes
    stoch = G * C
    feat = cfg.deter + stoch
    ks = jax.random.split(key, 8)
    return {
        "enc": _mlp(ks[0], [obs_dim, cfg.hidden, cfg.hidden]),
        "gru": _gru_init(ks[1], stoch + n_actions, cfg.deter),
        "prior": _mlp(ks[2], [cfg.deter, cfg.hidden, G * C]),
        "post": _mlp(ks[3], [cfg.deter + cfg.hidden, cfg.hidden, G * C]),
        "dec": _mlp(ks[4], [feat, cfg.hidden, cfg.hidden, obs_dim]),
        # r(s, a) / c(s, a): departure heads (see module docstring).
        "rew": _mlp(ks[5], [feat + n_actions, cfg.hidden, _NBINS]),
        "cont": _mlp(ks[6], [feat + n_actions, cfg.hidden, 1]),
    }


def _observe(wm, cfg, obs, actions, is_first, n_actions, key):
    """Posterior scan over a [B, L, ...] sequence batch.

    Returns feats [B, L, F], prior/post logits [B, L, G, C], and the
    final (h, z) carry. ``actions[t]`` is the action taken AT step t, so
    the GRU consumes the shifted action (zeros at t=0 / episode starts).
    """
    B, L = obs.shape[:2]
    G, C = cfg.stoch_groups, cfg.stoch_classes
    embed = _mlp_fwd(wm["enc"], symlog(obs), out_linear=False)  # [B, L, H]
    a_onehot = jax.nn.one_hot(actions, n_actions)               # [B, L, A]
    prev_a = jnp.concatenate(
        [jnp.zeros_like(a_onehot[:, :1]), a_onehot[:, :-1]], 1)

    def step(carry, xs):
        h, z, key = carry
        emb_t, pa_t, first_t = xs
        key, sub = jax.random.split(key)
        # Episode boundary: reset the recurrent state and drop the
        # cross-episode action.
        keep = (1.0 - first_t)[:, None]
        h, z, pa_t = h * keep, z * keep, pa_t * keep
        h = _gru(wm["gru"], jnp.concatenate([z, pa_t], -1), h)
        prior_log = _mlp_fwd(wm["prior"], h).reshape(B, G, C)
        post_log = _mlp_fwd(
            wm["post"], jnp.concatenate([h, emb_t], -1)).reshape(B, G, C)
        z = _sample_st(sub, post_log, C).reshape(B, G * C)
        return (h, z, key), (jnp.concatenate([h, z], -1), prior_log, post_log)

    h0 = jnp.zeros((B, cfg.deter))
    z0 = jnp.zeros((B, G * C))
    xs = (embed.transpose(1, 0, 2), prev_a.transpose(1, 0, 2),
          is_first.transpose(1, 0))
    (h, z, _), (feats, prior, post) = jax.lax.scan(step, (h0, z0, key), xs)
    to_bl = lambda x: jnp.moveaxis(x, 0, 1)
    return to_bl(feats), to_bl(prior), to_bl(post), (h, z)


def _kl_cat(p_logits, q_logits, classes):
    """KL(p || q) between unimixed categoricals, summed over groups."""
    p = jax.nn.softmax(_unimix(p_logits, classes), -1)
    logp = jax.nn.log_softmax(_unimix(p_logits, classes), -1)
    logq = jax.nn.log_softmax(_unimix(q_logits, classes), -1)
    return (p * (logp - logq)).sum(-1).sum(-1)  # [B, L]


def _imagine(wm, actor, cfg, h, z, n_actions, key, horizon):
    """Roll the prior dynamics ``horizon`` steps under the actor.

    Starts from flattened posterior states [N, ...] (gradients stopped).
    Returns feats [H+1, N, F], actions [H, N], action log-probs/entropy
    [H, N], and TRANSITION rewards/continues [H, N]: ``rews[t]`` /
    ``conts[t]`` are r(s_t, a_t) and the probability the episode
    survives the step into s_{t+1}.
    """
    G, C = cfg.stoch_groups, cfg.stoch_classes
    N = h.shape[0]

    def step(carry, _):
        h, z, key = carry
        key, ka, kz = jax.random.split(key, 3)
        feat = jnp.concatenate([h, z], -1)
        a_logits = _unimix(_mlp_fwd(actor, feat), n_actions)
        a = jax.random.categorical(ka, a_logits)
        logp = jnp.take_along_axis(
            jax.nn.log_softmax(a_logits, -1), a[:, None], -1)[:, 0]
        ent = -(jax.nn.softmax(a_logits, -1)
                * jax.nn.log_softmax(a_logits, -1)).sum(-1)
        a_1h = jax.nn.one_hot(a, n_actions)
        feat_a = jnp.concatenate([feat, a_1h], -1)
        rew = twohot_decode(_mlp_fwd(wm["rew"], feat_a))
        cont = jax.nn.sigmoid(_mlp_fwd(wm["cont"], feat_a))[:, 0]
        h2 = _gru(wm["gru"], jnp.concatenate([z, a_1h], -1), h)
        prior_log = _mlp_fwd(wm["prior"], h2).reshape(N, G, C)
        z2 = _sample_st(kz, prior_log, C).reshape(N, G * C)
        feat2 = jnp.concatenate([h2, z2], -1)
        return (h2, z2, key), (feat, a, logp, ent, rew, cont, feat2)

    (hH, zH, _), (feats, acts, logps, ents, rews, conts, feats2) = \
        jax.lax.scan(step, (h, z, key), None, length=horizon)
    all_feats = jnp.concatenate([feats, feats2[-1:]], 0)      # [H+1, N, F]
    return all_feats, acts, logps, ents, rews, conts


def _lambda_returns(rewards, conts, values, gamma, lam):
    """TD(λ) returns. ``rewards``/``conts`` [H, N] are per-TRANSITION
    (``conts[t]`` gates the bootstrap into state t+1); ``values``
    [H+1, N]. Returns [H, N]."""

    def step(nxt, xs):
        r, c, v_next = xs
        ret = r + gamma * c * ((1 - lam) * v_next + lam * nxt)
        return ret, ret

    _, rets = jax.lax.scan(
        step, values[-1], (rewards, conts, values[1:]), reverse=True)
    return rets


# --------------------------------------------------------------- algorithm


class DreamerV3(Algorithm):
    def _setup(self):
        cfg = self.config
        env = cfg.env_cls(cfg.num_envs_per_runner, seed=cfg.seed)
        self.env = env
        self.obs_dim = env.obs_dim
        self.n_actions = env.n_actions
        key = jax.random.PRNGKey(cfg.seed)
        kw, ka, kc, self._key = jax.random.split(key, 4)
        feat = cfg.deter + cfg.stoch_groups * cfg.stoch_classes
        wm = _init_world_model(kw, cfg, self.obs_dim, self.n_actions)
        actor = _mlp(ka, [feat, cfg.hidden, cfg.hidden, self.n_actions])
        critic = _mlp(kc, [feat, cfg.hidden, cfg.hidden, _NBINS])
        self._wm_opt = optax.chain(optax.clip_by_global_norm(100.0),
                                   optax.adam(cfg.wm_lr))
        self._ac_opt = optax.chain(optax.clip_by_global_norm(100.0),
                                   optax.adam(cfg.actor_lr))
        self._cr_opt = optax.chain(optax.clip_by_global_norm(100.0),
                                   optax.adam(cfg.critic_lr))
        self.state = {
            "wm": wm, "actor": actor, "critic": critic,
            "slow_critic": jax.tree.map(jnp.copy, critic),
            "wm_opt": self._wm_opt.init(wm),
            "ac_opt": self._ac_opt.init(actor),
            "cr_opt": self._cr_opt.init(critic),
            # Percentile-EMA return scale (paper: 5th..95th percentile).
            "ret_lo": jnp.zeros(()), "ret_hi": jnp.ones(()),
        }
        # Sequence replay: per-env streams so subsequences are contiguous.
        n, cap = cfg.num_envs_per_runner, cfg.buffer_size
        self._buf = {
            "obs": np.zeros((n, cap, self.obs_dim), np.float32),
            "act": np.zeros((n, cap), np.int32),
            # Departure semantics: record t holds r(s_t, a_t) and
            # whether a_t TERMINATED the episode.
            "rew": np.zeros((n, cap), np.float32),
            "cont": np.ones((n, cap), np.float32),
            "first": np.zeros((n, cap), np.float32),
        }
        self._buf_pos = 0
        self._buf_size = 0
        self._rng = np.random.default_rng(cfg.seed ^ 0xD3)
        # Per-env recurrent act state.
        self._h = jnp.zeros((n, cfg.deter))
        self._z = jnp.zeros((n, cfg.stoch_groups * cfg.stoch_classes))
        self._prev_a = np.zeros(n, np.int32)
        self._obs = env.reset()
        self._is_first = np.ones(n, np.float32)
        self._ep_ret = np.zeros(n, np.float32)
        self._recent_returns: list[float] = []
        self._steps_sampled = 0
        self._policy_step = jax.jit(self._policy_step_impl)
        self._update = jax.jit(self._update_impl)

    # ------------------------------------------------------------- acting

    def _policy_step_impl(self, state, h, z, prev_a, obs, is_first, key):
        cfg = self.config
        wm = state["wm"]
        G, C = cfg.stoch_groups, cfg.stoch_classes
        keep = (1.0 - is_first)[:, None]
        h, z = h * keep, z * keep
        pa = jax.nn.one_hot(prev_a, self.n_actions) * keep
        h = _gru(wm["gru"], jnp.concatenate([z, pa], -1), h)
        emb = _mlp_fwd(wm["enc"], symlog(obs), out_linear=False)
        post = _mlp_fwd(wm["post"], jnp.concatenate([h, emb], -1))
        k1, k2 = jax.random.split(key)
        z = _sample_st(k1, post.reshape(-1, G, C), C).reshape(h.shape[0], -1)
        a_logits = _unimix(
            _mlp_fwd(state["actor"], jnp.concatenate([h, z], -1)),
            self.n_actions)
        return jax.random.categorical(k2, a_logits), h, z

    def _collect(self, n_steps: int) -> None:
        cfg = self.config
        n, cap = cfg.num_envs_per_runner, cfg.buffer_size
        for _ in range(n_steps):
            self._key, sub = jax.random.split(self._key)
            a, self._h, self._z = self._policy_step(
                self.state, self._h, self._z, jnp.asarray(self._prev_a),
                jnp.asarray(self._obs), jnp.asarray(self._is_first), sub)
            a = np.asarray(a)
            obs_now = self._obs
            first_now = self._is_first
            obs, rew, done, info = self.env.step(a)
            p = self._buf_pos
            self._buf["obs"][:, p] = obs_now
            self._buf["act"][:, p] = a
            self._buf["rew"][:, p] = rew
            self._buf["cont"][:, p] = 1.0 - info["terminated"]
            self._buf["first"][:, p] = first_now
            self._buf_pos = (p + 1) % cap
            self._buf_size = min(self._buf_size + 1, cap)
            self._ep_ret += rew
            for i in np.nonzero(done)[0]:
                self._recent_returns.append(float(self._ep_ret[i]))
                self._ep_ret[i] = 0.0
            self._obs = obs
            self._prev_a = a
            self._is_first = done.astype(np.float32)
            self._steps_sampled += n
        self._recent_returns = self._recent_returns[-100:]

    def _sample_batch(self):
        cfg = self.config
        B, L = cfg.batch_size, cfg.seq_len
        n = cfg.num_envs_per_runner
        envs = self._rng.integers(0, n, B)
        # Valid starts per stream: 0..size-L inclusive (training_step
        # gates updates on size >= L so hi is never negative here).
        hi = self._buf_size - L
        starts = self._rng.integers(0, hi + 1, B)
        if self._buf_size == cfg.buffer_size:  # ring wrapped: oldest = pos
            starts = (starts + self._buf_pos) % cfg.buffer_size
        idx = (starts[:, None] + np.arange(L)) % cfg.buffer_size
        return {k: jnp.asarray(v[envs[:, None], idx])
                for k, v in self._buf.items()}

    # ------------------------------------------------------------- update

    def _update_impl(self, state, batch, key):
        cfg = self.config
        C = cfg.stoch_classes
        k_wm, k_im = jax.random.split(key)

        def wm_loss(wm):
            feats, prior, post, _ = _observe(
                wm, cfg, batch["obs"], batch["act"], batch["first"],
                self.n_actions, k_wm)
            recon = _mlp_fwd(wm["dec"], feats)
            l_rec = ((recon - symlog(batch["obs"])) ** 2).sum(-1)
            feat_a = jnp.concatenate(
                [feats, jax.nn.one_hot(batch["act"], self.n_actions)], -1)
            l_rew = _ce(_mlp_fwd(wm["rew"], feat_a),
                        twohot(symlog(batch["rew"])))
            l_cont = optax.sigmoid_binary_cross_entropy(
                _mlp_fwd(wm["cont"], feat_a)[..., 0], batch["cont"])
            sg = jax.lax.stop_gradient
            kl_dyn = jnp.maximum(
                _kl_cat(sg(post), prior, C), cfg.free_bits)
            kl_rep = jnp.maximum(
                _kl_cat(post, sg(prior), C), cfg.free_bits)
            loss = (l_rec + l_rew + l_cont + cfg.kl_dyn * kl_dyn
                    + cfg.kl_rep * kl_rep).mean()
            aux = {"wm_loss": loss, "recon_loss": l_rec.mean(),
                   "reward_loss": l_rew.mean(), "kl_dyn": kl_dyn.mean(),
                   "feats": feats}
            return loss, aux

        (_, wm_aux), wm_grads = jax.value_and_grad(
            wm_loss, has_aux=True)(state["wm"])
        upd, wm_opt = self._wm_opt.update(wm_grads, state["wm_opt"])
        wm = optax.apply_updates(state["wm"], upd)

        # Imagination starts: every posterior state, flattened, detached.
        feats = jax.lax.stop_gradient(wm_aux.pop("feats"))
        F = feats.shape[-1]
        h0 = feats.reshape(-1, F)[:, : cfg.deter]
        z0 = feats.reshape(-1, F)[:, cfg.deter:]

        def ac_loss(actor, critic):
            imag_f, acts, logps, ents, rews, conts = _imagine(
                wm, actor, cfg, h0, z0, self.n_actions, k_im,
                cfg.imag_horizon)
            v_logits = _mlp_fwd(critic, imag_f)                # [H+1, N, K]
            values = twohot_decode(v_logits)
            sg = jax.lax.stop_gradient
            rets = _lambda_returns(
                rews, conts, sg(values), cfg.gamma, cfg.lam)   # [H, N]
            # Trajectory weights: probability imagination reached s_t
            # alive (w_0 = 1; later steps discount by survival so far).
            ones = jnp.ones_like(conts[:1])
            w = sg(jnp.cumprod(jnp.concatenate([ones, conts[:-1]], 0), 0))
            # Percentile-EMA return normalization (paper: S = EMA of
            # Per(R,95)-Per(R,5), advantages divided by max(1, S)).
            lo = jnp.percentile(rets, 5.0)
            hi = jnp.percentile(rets, 95.0)
            ret_lo = 0.99 * state["ret_lo"] + 0.01 * lo
            ret_hi = 0.99 * state["ret_hi"] + 0.01 * hi
            scale = jnp.maximum(1.0, ret_hi - ret_lo)
            adv = sg((rets - values[:-1]) / scale)
            l_actor = -(w * (logps * adv + cfg.entropy_scale * ents)).mean()
            # Critic: twohot CE to λ-returns + slow-critic regularizer.
            tgt = twohot(symlog(sg(rets)))
            l_val = (w * _ce(v_logits[:-1], tgt)).mean()
            slow_probs = jax.nn.softmax(
                _mlp_fwd(state["slow_critic"], imag_f[:-1]), -1)
            l_slow = (w * _ce(v_logits[:-1], sg(slow_probs))).mean()
            l_critic = l_val + cfg.slow_critic_scale * l_slow
            aux = {"actor_loss": l_actor, "critic_loss": l_critic,
                   "imag_return": rets.mean(), "actor_entropy": ents.mean(),
                   "ret_lo": ret_lo, "ret_hi": ret_hi}
            return l_actor + l_critic, aux

        (_, ac_aux), (a_grads, c_grads) = jax.value_and_grad(
            ac_loss, argnums=(0, 1), has_aux=True)(
                state["actor"], state["critic"])
        upd, ac_opt = self._ac_opt.update(a_grads, state["ac_opt"])
        actor = optax.apply_updates(state["actor"], upd)
        upd, cr_opt = self._cr_opt.update(c_grads, state["cr_opt"])
        critic = optax.apply_updates(state["critic"], upd)
        tau = cfg.slow_critic_tau
        slow = jax.tree.map(lambda s, c: (1 - tau) * s + tau * c,
                            state["slow_critic"], critic)
        new_state = {"wm": wm, "actor": actor, "critic": critic,
                     "slow_critic": slow, "wm_opt": wm_opt,
                     "ac_opt": ac_opt, "cr_opt": cr_opt,
                     "ret_lo": ac_aux.pop("ret_lo"),
                     "ret_hi": ac_aux.pop("ret_hi")}
        return new_state, {**wm_aux, **ac_aux}

    def training_step(self) -> dict:
        cfg = self.config
        self._collect(cfg.rollout_len)
        metrics: dict = {}
        # Both gates matter: total experience AND per-env stream depth
        # (sampling needs a full seq_len window in every stream).
        if (self._steps_sampled >= cfg.learning_starts
                and self._buf_size >= cfg.seq_len):
            m: dict = {}
            for _ in range(cfg.updates_per_iteration):
                self._key, sub = jax.random.split(self._key)
                self.state, m = self._update(
                    self.state, self._sample_batch(), sub)
            metrics = {k: float(v) for k, v in m.items()}
        metrics["num_env_steps_sampled"] = self._steps_sampled
        if self._recent_returns:
            metrics["episode_return_mean"] = float(
                np.mean(self._recent_returns))
        return metrics

    # --------------------------------------------------------- evaluation

    def evaluate(self) -> dict:
        """Recurrent-policy evaluation (the base harness assumes a
        stateless policy): fresh envs, RSSM state threaded per env."""
        cfg = self.config
        env = cfg.env_cls(cfg.evaluation_num_envs, seed=cfg.seed ^ 0xE7A1)
        n = cfg.evaluation_num_envs
        h = jnp.zeros((n, cfg.deter))
        z = jnp.zeros((n, cfg.stoch_groups * cfg.stoch_classes))
        prev_a = np.zeros(n, np.int32)
        obs = env.reset()
        first = np.ones(n, np.float32)
        ep_ret = np.zeros(n, np.float32)
        returns: list[float] = []
        key = jax.random.PRNGKey(cfg.seed ^ 0x5EED)
        while len(returns) < cfg.evaluation_num_episodes:
            key, sub = jax.random.split(key)
            a, h, z = self._policy_step(
                self.state, h, z, jnp.asarray(prev_a), jnp.asarray(obs),
                jnp.asarray(first), sub)
            a = np.asarray(a)
            obs, rew, done, _ = env.step(a)
            ep_ret += rew
            for i in np.nonzero(done)[0]:
                returns.append(float(ep_ret[i]))
                ep_ret[i] = 0.0
            prev_a = a
            first = done.astype(np.float32)
        returns = returns[: cfg.evaluation_num_episodes]
        return {"evaluation": {
            "episode_return_mean": float(np.mean(returns)),
            "episode_return_min": float(np.min(returns)),
            "episode_return_max": float(np.max(returns)),
            "num_episodes": len(returns)}}

    # ------------------------------------------------------- checkpointing

    def get_state(self) -> dict:
        return {"iteration": self.iteration,
                "model": jax.device_get(self.state)}

    def set_state(self, state: dict) -> None:
        self.iteration = state["iteration"]
        self.state = jax.tree.map(jnp.asarray, state["model"])


DreamerV3Config.algo_cls = DreamerV3
