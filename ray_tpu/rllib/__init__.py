"""RL training stack: Algorithm / Learner / LearnerGroup / EnvRunner.

TPU-native equivalent of the reference's RLlib new API stack
(``rllib/algorithms/algorithm.py:199``, ``rllib/core/learner/learner.py:111``,
``rllib/core/learner/learner_group.py``, ``rllib/env/env_runner_group.py``).
Differences by design, not omission: the Learner's update is one jitted
JAX function (loss + grad + optimizer fused by XLA) rather than a torch
module graph, learners data-parallelize with gradient averaging over the
object store (ray collectives stand in for NCCL), and environments are
vectorized numpy — rollouts stay on CPU actors while updates go to the
accelerator.
"""

from .algorithm import Algorithm, AlgorithmConfig
from .connectors import (
    ClipRewards,
    ConnectorPipelineV2,
    ConnectorV2,
    LambdaConnector,
    NormalizeObservations,
    ScaleObservations,
)
from .env import CartPole, GridWorld, Pendulum
from .env_runner import EnvRunner, EnvRunnerGroup
from .impala import APPO, APPOConfig, IMPALA, IMPALAConfig
from .learner import Learner
from .learner_group import LearnerGroup
from .dqn import DQN, DQNConfig
from .dreamerv3 import DreamerV3, DreamerV3Config
from .offline import (BC, BCConfig, CQL, CQLConfig, MARWIL, MARWILConfig,
                      collect_offline_data)
from .multi_agent import (MultiAgentCartPole, MultiAgentEnvRunner,
                          MultiAgentPPO, MultiAgentPPOConfig)
from .ppo import PPO, PPOConfig
from .replay import ReplayBuffer
from .sac import SAC, SACConfig

__all__ = [
    "ClipRewards",
    "ConnectorPipelineV2",
    "ConnectorV2",
    "LambdaConnector",
    "NormalizeObservations",
    "ScaleObservations",
    "Algorithm",
    "AlgorithmConfig",
    "CartPole",
    "GridWorld",
    "EnvRunner",
    "EnvRunnerGroup",
    "Learner",
    "LearnerGroup",
    "PPO",
    "PPOConfig",
    "DQN",
    "DQNConfig",
    "IMPALA",
    "IMPALAConfig",
    "APPO",
    "APPOConfig",
    "BC",
    "BCConfig",
    "CQL",
    "CQLConfig",
    "collect_offline_data",
    "DreamerV3",
    "DreamerV3Config",
    "MARWIL",
    "MARWILConfig",
    "MultiAgentCartPole",
    "MultiAgentEnvRunner",
    "MultiAgentPPO",
    "MultiAgentPPOConfig",
    "Pendulum",
    "ReplayBuffer",
    "SAC",
    "SACConfig",
]
