"""PPO (clipped surrogate) on the Learner/EnvRunner stack.

Equivalent of ``rllib/algorithms/ppo/ppo.py`` + ``ppo_learner.py``: GAE
on the host (cheap, sequential over time), the clipped policy + value +
entropy loss as one jitted function on the Learner, several epochs of
shuffled minibatches per iteration.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from . import models
from .algorithm import Algorithm, AlgorithmConfig
from .env_runner import EnvRunnerGroup
from .learner_group import LearnerGroup


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.gamma = 0.99
        self.gae_lambda = 0.95
        self.clip_eps = 0.2
        self.vf_coeff = 0.5
        self.entropy_coeff = 0.01
        self.num_epochs = 4
        self.minibatch_size = 256
        self.hidden = 64

    def training(self, *, gamma=None, gae_lambda=None, clip_eps=None, vf_coeff=None,
                 entropy_coeff=None, num_epochs=None, minibatch_size=None,
                 hidden=None, **kwargs):
        for name, val in (("gamma", gamma), ("gae_lambda", gae_lambda),
                          ("clip_eps", clip_eps), ("vf_coeff", vf_coeff),
                          ("entropy_coeff", entropy_coeff), ("num_epochs", num_epochs),
                          ("minibatch_size", minibatch_size), ("hidden", hidden)):
            if val is not None:
                setattr(self, name, val)
        return super().training(**kwargs)


def make_ppo_loss(clip_eps: float, vf_coeff: float, entropy_coeff: float):
    """Build the jittable PPO loss. batch: obs, actions, logp_old,
    advantages, returns — all flat [B, ...]."""

    def loss_fn(params, batch):
        logits, value = models.forward(params, batch["obs"])
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(logp_all, batch["actions"][:, None], axis=1)[:, 0]
        ratio = jnp.exp(logp - batch["logp_old"])
        adv = batch["advantages"]
        adv = (adv - adv.mean()) / (adv.std() + 1e-8)
        surr = jnp.minimum(
            ratio * adv, jnp.clip(ratio, 1 - clip_eps, 1 + clip_eps) * adv
        )
        policy_loss = -surr.mean()
        vf_loss = jnp.mean((value - batch["returns"]) ** 2)
        entropy = -jnp.sum(jnp.exp(logp_all) * logp_all, axis=1).mean()
        total = policy_loss + vf_coeff * vf_loss - entropy_coeff * entropy
        metrics = {
            "policy_loss": policy_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
            "clip_frac": (jnp.abs(ratio - 1.0) > clip_eps).mean(),
        }
        return total, metrics

    return loss_fn


def compute_gae(sample: dict, gamma: float, lam: float):
    """Generalized advantage estimation over a [T, N] fragment. Done
    boundaries cut the recursion (auto-reset envs); time-limit truncations
    still bootstrap with V(terminal_obs) (``trunc_values``) — only true
    terminations zero the tail value."""
    rewards, values, dones = sample["rewards"], sample["values"], sample["dones"]
    trunc_values = sample.get("trunc_values")
    if trunc_values is None:
        trunc_values = np.zeros_like(rewards)
    T, N = rewards.shape
    adv = np.zeros((T, N), np.float32)
    last_gae = np.zeros(N, np.float32)
    next_value = sample["last_value"]
    for t in reversed(range(T)):
        not_done = 1.0 - dones[t].astype(np.float32)
        bootstrap = next_value * not_done + trunc_values[t]
        delta = rewards[t] + gamma * bootstrap - values[t]
        last_gae = delta + gamma * lam * not_done * last_gae
        adv[t] = last_gae
        next_value = values[t]
    returns = adv + values
    return adv, returns


class PPO(Algorithm):
    def _setup(self) -> None:
        c: PPOConfig = self.config  # type: ignore[assignment]
        env_probe = c.env_cls(num_envs=1)
        obs_dim, n_actions = env_probe.obs_dim, env_probe.n_actions
        hidden = c.hidden

        def init_params_fn(key):
            return models.init_policy(key, obs_dim, n_actions, hidden)

        self.learner_group = LearnerGroup(
            make_ppo_loss(c.clip_eps, c.vf_coeff, c.entropy_coeff),
            init_params_fn,
            num_learners=c.num_learners,
            lr=c.lr,
            max_grad_norm=c.max_grad_norm,
            seed=c.seed,
        )
        self.env_runner_group = EnvRunnerGroup(
            c.env_cls,
            num_env_runners=c.num_env_runners,
            num_envs_per_runner=c.num_envs_per_runner,
            rollout_len=c.rollout_len,
            seed=c.seed,
            runner_kwargs=(
                {"env_to_module": c.env_to_module_connector}
                if c.env_to_module_connector is not None else None),
        )
        self.rng = np.random.default_rng(c.seed)
        self._recent_returns: list[float] = []

    def training_step(self) -> dict:
        c: PPOConfig = self.config  # type: ignore[assignment]
        weights = self.learner_group.get_weights()
        samples = self.env_runner_group.sample(weights)
        if c.learner_connector is not None:
            from .connectors import make_pipeline

            if not hasattr(self, "_learner_conn"):
                self._learner_conn = make_pipeline(c.learner_connector)
            samples = [self._learner_conn(s) for s in samples]

        flat = {"obs": [], "actions": [], "logp_old": [], "advantages": [], "returns": []}
        for s in samples:
            adv, ret = compute_gae(s, c.gamma, c.gae_lambda)
            T, N = s["rewards"].shape
            flat["obs"].append(s["obs"].reshape(T * N, -1))
            flat["actions"].append(s["actions"].reshape(-1))
            flat["logp_old"].append(s["logp"].reshape(-1))
            flat["advantages"].append(adv.reshape(-1))
            flat["returns"].append(ret.reshape(-1))
            self._recent_returns.extend(s["episode_returns"].tolist())
        batch = {k: np.concatenate(v) for k, v in flat.items()}
        size = len(batch["actions"])

        metrics: dict = {}
        for _ in range(c.num_epochs):
            order = self.rng.permutation(size)
            for start in range(0, size, c.minibatch_size):
                idx = order[start : start + c.minibatch_size]
                mb = {k: v[idx] for k, v in batch.items()}
                metrics = self.learner_group.update(mb)

        self._recent_returns = self._recent_returns[-100:]
        metrics["episode_return_mean"] = (
            float(np.mean(self._recent_returns)) if self._recent_returns else 0.0
        )
        metrics["num_env_steps_sampled"] = size
        return metrics

    def get_state(self) -> dict:
        return {"iteration": self.iteration, "learner": self.learner_group.get_state()}

    def set_state(self, state: dict) -> None:
        self.iteration = state["iteration"]
        self.learner_group.set_state(state["learner"])


PPOConfig.algo_cls = PPO
