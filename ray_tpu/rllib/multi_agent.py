"""Multi-agent training: per-agent policies over a shared environment.

Equivalent of the reference's multi-agent stack —
``rllib/env/multi_agent_env.py`` (dict-keyed per-agent obs/actions),
``rllib/env/multi_agent_env_runner.py`` (routes each agent through its
mapped policy module), and the ``policies`` / ``policy_mapping_fn``
config surface (``rllib/algorithms/algorithm_config.py`` multi_agent()).
Design here: the env exposes fixed agent ids with per-agent vectorized
arrays, the runner samples EVERY agent each step (simultaneous-move
games), groups fragments BY POLICY, and MultiAgentPPO keeps one
LearnerGroup per policy — shared policies simply receive the
concatenated fragments of all agents mapped to them.
"""

from __future__ import annotations

import numpy as np

from .algorithm import Algorithm
from .env import CartPole
from .env_runner import EnvRunnerGroup, _np_forward, _softmax
from .learner_group import LearnerGroup
from .ppo import PPOConfig, compute_gae, make_ppo_loss
from . import models


class MultiAgentCartPole:
    """N independent cart-poles, one per agent id — the reference's
    standard multi-agent smoke env (``rllib/examples/envs/classes/
    multi_agent/``). Agents step simultaneously; each has its own
    episode lifecycle."""

    def __init__(self, num_agents: int = 2, num_envs: int = 1, seed: int = 0):
        self.agent_ids = [f"agent_{i}" for i in range(num_agents)]
        self._envs = {
            aid: CartPole(num_envs=num_envs, seed=seed + 7919 * i)
            for i, aid in enumerate(self.agent_ids)
        }
        self.n = num_envs

    @property
    def obs_dims(self) -> dict:
        return {aid: e.obs_dim for aid, e in self._envs.items()}

    @property
    def n_actions_map(self) -> dict:
        return {aid: e.n_actions for aid, e in self._envs.items()}

    def reset(self) -> dict:
        return {aid: e.reset() for aid, e in self._envs.items()}

    def step(self, action_dict: dict):
        obs, rewards, dones, infos = {}, {}, {}, {}
        for aid, env in self._envs.items():
            obs[aid], rewards[aid], dones[aid], infos[aid] = env.step(
                action_dict[aid])
        return obs, rewards, dones, infos


class MultiAgentEnvRunner:
    """Samples every agent through its mapped policy each step and
    returns fragments grouped by POLICY id (concatenated over the agents
    that share a policy, along the env axis)."""

    def __init__(self, env_cls, num_envs: int = 8, rollout_len: int = 64,
                 seed: int = 0, *, policy_mapping_fn=None, env_kwargs=None):
        self.env = env_cls(num_envs=num_envs, seed=seed, **(env_kwargs or {}))
        self.mapping = policy_mapping_fn or (lambda aid: aid)
        self.num_envs = num_envs
        self.rollout_len = rollout_len
        self.rng = np.random.default_rng(seed ^ 0x3A)
        self.obs = self.env.reset()
        self._ep_return = {a: np.zeros(num_envs, np.float32)
                           for a in self.env.agent_ids}
        self._completed: dict[str, list[float]] = {a: [] for a in self.env.agent_ids}

    def sample(self, weights: dict) -> dict:
        """weights: {policy_id: params}. Returns {policy_id: fragment}
        with the same keys PPO's single-agent fragment carries."""
        T, N = self.rollout_len, self.num_envs
        agents = self.env.agent_ids
        obs_dims = self.env.obs_dims
        bufs = {
            a: {
                "obs": np.zeros((T, N, obs_dims[a]), np.float32),
                "actions": np.zeros((T, N), np.int64),
                "logp": np.zeros((T, N), np.float32),
                "values": np.zeros((T, N), np.float32),
                "rewards": np.zeros((T, N), np.float32),
                "dones": np.zeros((T, N), np.bool_),
                "trunc_values": np.zeros((T, N), np.float32),
            }
            for a in agents
        }
        for t in range(T):
            action_dict = {}
            for a in agents:
                w = weights[self.mapping(a)]
                logits, value = _np_forward(w, self.obs[a])
                probs = _softmax(logits)
                acts = (probs.cumsum(axis=1) > self.rng.random((N, 1))).argmax(axis=1)
                bufs[a]["obs"][t] = self.obs[a]
                bufs[a]["actions"][t] = acts
                bufs[a]["logp"][t] = np.log(probs[np.arange(N), acts] + 1e-10)
                bufs[a]["values"][t] = value
                action_dict[a] = acts
            self.obs, rewards, dones, infos = self.env.step(action_dict)
            for a in agents:
                bufs[a]["rewards"][t] = rewards[a]
                bufs[a]["dones"][t] = dones[a]
                truncated = infos[a]["truncated"]
                if truncated.any():
                    _, v_term = _np_forward(
                        weights[self.mapping(a)], infos[a]["terminal_obs"])
                    bufs[a]["trunc_values"][t, truncated] = v_term[truncated]
                self._ep_return[a] += rewards[a]
                for i in np.nonzero(dones[a])[0]:
                    self._completed[a].append(float(self._ep_return[a][i]))
                    self._ep_return[a][i] = 0.0

        # bootstrap values + episode stats, then group agents by policy
        for a in agents:
            _, bufs[a]["last_value"] = _np_forward(
                weights[self.mapping(a)], self.obs[a])
            bufs[a]["episode_returns"] = np.asarray(
                self._completed[a], np.float32)
            self._completed[a] = []
        by_policy: dict[str, dict] = {}
        for a in agents:
            pid = self.mapping(a)
            by_policy.setdefault(pid, []).append(bufs[a])
        out = {}
        for pid, frags in by_policy.items():
            out[pid] = {
                k: np.concatenate([f[k] for f in frags],
                                  axis=1 if np.ndim(frags[0][k]) >= 2 else 0)
                for k in ("obs", "actions", "logp", "values", "rewards",
                          "dones", "trunc_values")
            }
            out[pid]["last_value"] = np.concatenate(
                [f["last_value"] for f in frags])
            out[pid]["episode_returns"] = np.concatenate(
                [f["episode_returns"] for f in frags])
        return out


class MultiAgentPPOConfig(PPOConfig):
    def __init__(self):
        super().__init__()
        self.policies: list[str] | None = None       # default: one per agent
        self.policy_mapping_fn = None                # default: aid -> aid
        self.env_kwargs: dict = {}

    def multi_agent(self, *, policies=None, policy_mapping_fn=None,
                    env_kwargs=None) -> "MultiAgentPPOConfig":
        if policies is not None:
            self.policies = list(policies)
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        if env_kwargs is not None:
            self.env_kwargs = dict(env_kwargs)
        return self


class MultiAgentPPO(Algorithm):
    """Independent PPO per policy (the reference's default multi-agent
    mode): one LearnerGroup per policy id, updates driven from that
    policy's own fragments."""

    def _setup(self) -> None:
        c: MultiAgentPPOConfig = self.config  # type: ignore[assignment]
        probe = c.env_cls(num_envs=1, **c.env_kwargs)
        mapping = c.policy_mapping_fn or (lambda aid: aid)
        policies = c.policies or sorted({mapping(a) for a in probe.agent_ids})
        # each policy's obs/action space: taken from any agent mapped to it
        spec: dict[str, tuple[int, int]] = {}
        for a in probe.agent_ids:
            pid = mapping(a)
            dims = (probe.obs_dims[a], probe.n_actions_map[a])
            if pid in spec and spec[pid] != dims:
                raise ValueError(
                    f"policy {pid!r} shared by agents with different spaces "
                    f"{spec[pid]} vs {dims}")
            spec[pid] = dims
        missing = [p for p in policies if p not in spec]
        if missing:
            raise ValueError(f"policies {missing} have no mapped agents")
        unmapped = sorted({mapping(a) for a in probe.agent_ids} - set(policies))
        if unmapped:
            raise ValueError(
                f"agents map to policy ids {unmapped} absent from "
                f"policies={policies}")

        self.policy_ids = policies
        self.learner_groups = {}
        for i, pid in enumerate(policies):
            obs_dim, n_actions = spec[pid]
            self.learner_groups[pid] = LearnerGroup(
                make_ppo_loss(c.clip_eps, c.vf_coeff, c.entropy_coeff),
                (lambda od, na: lambda key: models.init_policy(
                    key, od, na, c.hidden))(obs_dim, n_actions),
                num_learners=c.num_learners,
                lr=c.lr,
                max_grad_norm=c.max_grad_norm,
                seed=c.seed + i,
            )
        self.env_runner_group = EnvRunnerGroup(
            c.env_cls,
            num_env_runners=c.num_env_runners,
            num_envs_per_runner=c.num_envs_per_runner,
            rollout_len=c.rollout_len,
            seed=c.seed,
            runner_cls=MultiAgentEnvRunner,
            runner_kwargs={"policy_mapping_fn": c.policy_mapping_fn,
                           "env_kwargs": c.env_kwargs},
        )
        self.rng = np.random.default_rng(c.seed)
        self._recent_returns: dict[str, list[float]] = {p: [] for p in policies}

    def training_step(self) -> dict:
        c: MultiAgentPPOConfig = self.config  # type: ignore[assignment]
        weights = {pid: lg.get_weights() for pid, lg in self.learner_groups.items()}
        samples = self.env_runner_group.sample(weights)

        metrics: dict = {}
        total_steps = 0
        for pid in self.policy_ids:
            flat = {"obs": [], "actions": [], "logp_old": [],
                    "advantages": [], "returns": []}
            for per_runner in samples:
                s = per_runner.get(pid)
                if s is None:
                    continue
                adv, ret = compute_gae(s, c.gamma, c.gae_lambda)
                T, N = s["rewards"].shape
                flat["obs"].append(s["obs"].reshape(T * N, -1))
                flat["actions"].append(s["actions"].reshape(-1))
                flat["logp_old"].append(s["logp"].reshape(-1))
                flat["advantages"].append(adv.reshape(-1))
                flat["returns"].append(ret.reshape(-1))
                self._recent_returns[pid].extend(s["episode_returns"].tolist())
            if not flat["obs"]:
                continue
            batch = {k: np.concatenate(v) for k, v in flat.items()}
            size = len(batch["actions"])
            total_steps += size
            lg = self.learner_groups[pid]
            for _ in range(c.num_epochs):
                order = self.rng.permutation(size)
                for start in range(0, size, c.minibatch_size):
                    idx = order[start:start + c.minibatch_size]
                    m = lg.update({k: v[idx] for k, v in batch.items()})
            self._recent_returns[pid] = self._recent_returns[pid][-100:]
            metrics[pid] = {
                **{k: float(v) for k, v in m.items()},
                "episode_return_mean": (
                    float(np.mean(self._recent_returns[pid]))
                    if self._recent_returns[pid] else 0.0),
            }
        all_ret = [r for rs in self._recent_returns.values() for r in rs]
        metrics["episode_return_mean"] = (
            float(np.mean(all_ret)) if all_ret else 0.0)
        metrics["num_env_steps_sampled"] = total_steps
        return metrics

    def get_state(self) -> dict:
        return {
            "iteration": self.iteration,
            "learners": {p: lg.get_state() for p, lg in self.learner_groups.items()},
        }

    def set_state(self, state: dict) -> None:
        self.iteration = state["iteration"]
        for p, s in state["learners"].items():
            self.learner_groups[p].set_state(s)

    def stop(self) -> None:
        # Algorithm.stop() only knows the single-policy attribute names;
        # shut down every policy's learner group too.
        for lg in getattr(self, "learner_groups", {}).values():
            try:
                lg.shutdown()
            except Exception:
                pass
        super().stop()


MultiAgentPPOConfig.algo_cls = MultiAgentPPO
