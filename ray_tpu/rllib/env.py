"""Vectorized toy environments (no gym dependency).

API mirrors gymnasium's vector env closely enough that a user can adapt
real envs: ``reset() -> obs [N, obs_dim]``, ``step(actions [N]) ->
(obs, rewards, dones, info)`` with auto-reset on done — ``obs`` for done
envs is the NEW episode's first observation (the policy acts on it next
step); the terminal observation and the terminated/truncated split live
in ``info`` (``terminal_obs``, ``terminated``, ``truncated``) so GAE can
bootstrap time-limit truncations instead of zeroing them. The
reference's RLlib wraps gymnasium (``rllib/env/env_runner.py``); these
numpy envs keep the stack self-contained and the tests hermetic.
"""

from __future__ import annotations

import numpy as np


class CartPole:
    """Classic cart-pole balance task, vectorized over N copies.

    Physics constants match the canonical implementation so learning
    curves are comparable to published PPO results.
    """

    obs_dim = 4
    n_actions = 2
    max_steps = 500

    def __init__(self, num_envs: int = 1, seed: int = 0):
        self.n = num_envs
        self.rng = np.random.default_rng(seed)
        self.state = np.zeros((num_envs, 4), np.float32)
        self.steps = np.zeros(num_envs, np.int32)

    def reset(self) -> np.ndarray:
        self.state = self.rng.uniform(-0.05, 0.05, (self.n, 4)).astype(np.float32)
        self.steps[:] = 0
        return self.state.copy()

    def _reset_where(self, mask: np.ndarray) -> None:
        k = int(mask.sum())
        if k:
            self.state[mask] = self.rng.uniform(-0.05, 0.05, (k, 4)).astype(np.float32)
            self.steps[mask] = 0

    def step(self, actions: np.ndarray):
        gravity, masscart, masspole = 9.8, 1.0, 0.1
        total_mass, length = masscart + masspole, 0.5
        polemass_length = masspole * length
        force_mag, tau = 10.0, 0.02

        x, x_dot, theta, theta_dot = self.state.T
        force = np.where(actions == 1, force_mag, -force_mag)
        costheta, sintheta = np.cos(theta), np.sin(theta)
        temp = (force + polemass_length * theta_dot**2 * sintheta) / total_mass
        thetaacc = (gravity * sintheta - costheta * temp) / (
            length * (4.0 / 3.0 - masspole * costheta**2 / total_mass)
        )
        xacc = temp - polemass_length * thetaacc * costheta / total_mass
        x = x + tau * x_dot
        x_dot = x_dot + tau * xacc
        theta = theta + tau * theta_dot
        theta_dot = theta_dot + tau * thetaacc
        self.state = np.stack([x, x_dot, theta, theta_dot], axis=1).astype(np.float32)
        self.steps += 1

        terminated = (np.abs(x) > 2.4) | (np.abs(theta) > 12 * np.pi / 180)
        truncated = (self.steps >= self.max_steps) & ~terminated
        done = terminated | truncated
        rewards = np.ones(self.n, np.float32)
        terminal_obs = self.state.copy()
        self._reset_where(done)
        info = {"terminated": terminated, "truncated": truncated,
                "terminal_obs": terminal_obs}
        return self.state.copy(), rewards, done, info


class Pendulum:
    """Classic torque-controlled pendulum swing-up, vectorized over N
    copies — the canonical continuous-control task (SAC's smoke test in
    the reference: ``rllib/algorithms/sac/sac.py`` tuned examples).

    obs = [cos θ, sin θ, θ̇]; action = torque in [-2, 2] (continuous);
    reward = -(θ² + 0.1 θ̇² + 0.001 a²); episodes truncate at 200 steps
    (never terminate), matching the canonical dynamics so learning curves
    are comparable to published SAC results.
    """

    obs_dim = 3
    action_dim = 1
    max_action = 2.0
    n_actions = None  # continuous
    max_steps = 200

    def __init__(self, num_envs: int = 1, seed: int = 0):
        self.n = num_envs
        self.rng = np.random.default_rng(seed)
        self.theta = np.zeros(num_envs, np.float32)
        self.theta_dot = np.zeros(num_envs, np.float32)
        self.steps = np.zeros(num_envs, np.int32)

    def _obs(self) -> np.ndarray:
        return np.stack(
            [np.cos(self.theta), np.sin(self.theta), self.theta_dot],
            axis=1).astype(np.float32)

    def _reset_where(self, mask: np.ndarray) -> None:
        k = int(mask.sum())
        if k:
            self.theta[mask] = self.rng.uniform(-np.pi, np.pi, k)
            self.theta_dot[mask] = self.rng.uniform(-1.0, 1.0, k)
            self.steps[mask] = 0

    def reset(self) -> np.ndarray:
        self._reset_where(np.ones(self.n, bool))
        return self._obs()

    def step(self, actions: np.ndarray):
        g, m, l, dt = 10.0, 1.0, 1.0, 0.05
        u = np.clip(np.asarray(actions, np.float32).reshape(self.n), -2.0, 2.0)
        th = ((self.theta + np.pi) % (2 * np.pi)) - np.pi  # normalize
        costs = th**2 + 0.1 * self.theta_dot**2 + 0.001 * u**2
        new_dot = self.theta_dot + (
            3 * g / (2 * l) * np.sin(self.theta) + 3.0 / (m * l**2) * u) * dt
        new_dot = np.clip(new_dot, -8.0, 8.0)
        self.theta = self.theta + new_dot * dt
        self.theta_dot = new_dot.astype(np.float32)
        self.steps += 1

        truncated = self.steps >= self.max_steps
        terminated = np.zeros(self.n, bool)
        done = truncated
        rewards = (-costs).astype(np.float32)
        terminal_obs = self._obs()
        self._reset_where(done)
        info = {"terminated": terminated, "truncated": truncated,
                "terminal_obs": terminal_obs}
        return self._obs(), rewards, done, info


class GridWorld:
    """5x5 grid, reach the goal corner; -0.01 per step, +1 at goal.
    Cheap deterministic env for unit tests of the rollout plumbing."""

    obs_dim = 2
    n_actions = 4
    max_steps = 50
    size = 5

    def __init__(self, num_envs: int = 1, seed: int = 0):
        self.n = num_envs
        self.rng = np.random.default_rng(seed)
        self.pos = np.zeros((num_envs, 2), np.int32)
        self.steps = np.zeros(num_envs, np.int32)

    def _obs(self) -> np.ndarray:
        return (self.pos / (self.size - 1)).astype(np.float32)

    def reset(self) -> np.ndarray:
        self.pos[:] = 0
        self.steps[:] = 0
        return self._obs()

    def step(self, actions: np.ndarray):
        moves = np.array([[0, 1], [0, -1], [1, 0], [-1, 0]], np.int32)
        self.pos = np.clip(self.pos + moves[actions], 0, self.size - 1)
        self.steps += 1
        at_goal = (self.pos == self.size - 1).all(axis=1)
        truncated = (self.steps >= self.max_steps) & ~at_goal
        done = at_goal | truncated
        rewards = np.where(at_goal, 1.0, -0.01).astype(np.float32)
        terminal_obs = self._obs()
        if done.any():
            self.pos[done] = 0
            self.steps[done] = 0
        info = {"terminated": at_goal, "truncated": truncated,
                "terminal_obs": terminal_obs}
        return self._obs(), rewards, done, info
