"""IMPALA / APPO: asynchronous actor-learner RL with V-trace.

Equivalent of ``rllib/algorithms/impala/impala.py`` and
``rllib/algorithms/appo/appo.py``: EnvRunner actors sample continuously
— the learner consumes whichever rollout finishes first (``ray.wait``)
instead of barriering on the whole fleet, so slow runners never stall
training and fast ones never idle. Because consumed rollouts were
collected under a LAGGED policy, the advantage estimator is V-trace
(Espeholt et al. 2018): truncated importance weights correct the
off-policy value targets and policy gradient. APPO layers PPO's clipped
surrogate on top of the V-trace advantages (the reference's APPO is
exactly IMPALA + clipping).

TPU shape: V-trace's reverse recursion runs INSIDE the jitted loss as a
``lax.scan`` over time — one fused device program per update (the
reference splits this across torch ops); rollouts stream through the
object store from runner actors to the learner.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from . import models
from .algorithm import Algorithm, AlgorithmConfig
from .env_runner import EnvRunnerGroup
from .learner_group import LearnerGroup


class IMPALAConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.gamma = 0.99
        self.vf_coeff = 0.5
        self.entropy_coeff = 0.01
        self.rho_clip = 1.0          # V-trace rho-bar (importance clip)
        self.c_clip = 1.0            # V-trace c-bar (trace-cutting clip)
        self.num_batches_per_iteration = 4
        self.hidden = 64
        self.lr = 1e-3
        # APPO extra: clipped surrogate on V-trace advantages (None = off).
        self.clip_eps: float | None = None

    def training(self, *, gamma=None, vf_coeff=None, entropy_coeff=None,
                 rho_clip=None, c_clip=None, num_batches_per_iteration=None,
                 hidden=None, clip_eps=None, **kwargs):
        for name, val in (("gamma", gamma), ("vf_coeff", vf_coeff),
                          ("entropy_coeff", entropy_coeff), ("rho_clip", rho_clip),
                          ("c_clip", c_clip),
                          ("num_batches_per_iteration", num_batches_per_iteration),
                          ("hidden", hidden), ("clip_eps", clip_eps)):
            if val is not None:
                setattr(self, name, val)
        return super().training(**kwargs)


def make_vtrace_loss(gamma: float, vf_coeff: float, entropy_coeff: float,
                     rho_clip: float, c_clip: float,
                     clip_eps: float | None = None):
    """V-trace actor-critic loss over a [T, N] rollout fragment.

    batch: obs [T,N,D], actions [T,N], logp_old [T,N] (behavior policy),
    rewards [T,N], dones [T,N], trunc_values [T,N] (V(terminal) at
    time-limit truncations under the BEHAVIOR policy — bootstrap, not a
    true termination), last_obs [N,D].
    With ``clip_eps`` the policy term is APPO's clipped surrogate.
    """

    def loss_fn(params, batch):
        T, N = batch["actions"].shape
        obs = batch["obs"]
        logits, values = models.forward(params, obs.reshape(T * N, -1))
        logits = logits.reshape(T, N, -1)
        values = values.reshape(T, N)
        logp_all = jax.nn.log_softmax(logits)
        logp = jnp.take_along_axis(
            logp_all, batch["actions"][..., None], axis=2)[..., 0]
        ratio = jnp.exp(logp - batch["logp_old"])
        rho = jax.lax.stop_gradient(jnp.minimum(rho_clip, ratio))
        c = jax.lax.stop_gradient(jnp.minimum(c_clip, ratio))

        _, last_value = models.forward(params, batch["last_obs"])  # [N]
        not_done = 1.0 - batch["dones"].astype(jnp.float32)
        # V(x_{t+1}) with episode boundaries: zero at terminations, the
        # behavior-policy bootstrap at time-limit truncations.
        v_tp1 = jnp.concatenate([values[1:], last_value[None]], axis=0)
        v_next = v_tp1 * not_done + batch["trunc_values"]
        v_fixed = jax.lax.stop_gradient(values)
        v_next_fixed = jax.lax.stop_gradient(v_next)
        deltas = rho * (batch["rewards"] + gamma * v_next_fixed - v_fixed)

        # vs_t - V(x_t) by reverse scan:
        #   a_t = delta_t + gamma * c_t * not_done_t * a_{t+1}
        def body(acc, xs):
            delta_t, c_t, nd_t = xs
            acc = delta_t + gamma * c_t * nd_t * acc
            return acc, acc

        _, adv_rev = jax.lax.scan(
            body, jnp.zeros_like(last_value),
            (deltas[::-1], c[::-1], not_done[::-1]))
        vs_minus_v = adv_rev[::-1]
        vs = v_fixed + vs_minus_v
        # vs_{t+1} for the policy-gradient target (zero past terminations).
        vs_tp1 = jnp.concatenate(
            [vs[1:], jax.lax.stop_gradient(last_value)[None]], axis=0)
        vs_next = vs_tp1 * not_done + batch["trunc_values"]
        pg_adv = rho * (batch["rewards"] + gamma * vs_next - v_fixed)

        if clip_eps is not None:
            # APPO: PPO's clipped surrogate with V-trace advantages.
            surr = jnp.minimum(
                ratio * pg_adv,
                jnp.clip(ratio, 1 - clip_eps, 1 + clip_eps) * pg_adv)
            policy_loss = -surr.mean()
        else:
            policy_loss = -(logp * pg_adv).mean()
        vf_loss = 0.5 * jnp.mean((values - vs) ** 2)
        entropy = -jnp.sum(jnp.exp(logp_all) * logp_all, axis=2).mean()
        total = policy_loss + vf_coeff * vf_loss - entropy_coeff * entropy
        metrics = {
            "policy_loss": policy_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
            "mean_rho": rho.mean(),
            "clipped_rho_frac": (ratio > rho_clip).mean(),
        }
        return total, metrics

    return loss_fn


class IMPALA(Algorithm):
    """Async actor-learner loop. Every runner always has one sample
    request in flight; ``training_step`` drains whichever complete first
    (up to ``num_batches_per_iteration``), updates on each, and refreshes
    the weights the NEXT requests will use — rollout and update overlap,
    the V-trace correction absorbs the policy lag."""

    def _setup(self) -> None:
        c: IMPALAConfig = self.config  # type: ignore[assignment]
        if c.num_learners > 0:
            # The data-parallel LearnerGroup shards batches over axis 0 —
            # that is TIME for a V-trace rollout, which would truncate the
            # trace recursion at shard boundaries. Canonical IMPALA is one
            # learner + many async actors anyway.
            raise ValueError(
                "IMPALA/APPO scale via async env runners (num_env_runners); "
                "use num_learners=0 (single in-process learner)")
        env_probe = c.env_cls(num_envs=1)
        obs_dim, n_actions = env_probe.obs_dim, env_probe.n_actions

        def init_params_fn(key):
            return models.init_policy(key, obs_dim, n_actions, c.hidden)

        self.learner_group = LearnerGroup(
            make_vtrace_loss(c.gamma, c.vf_coeff, c.entropy_coeff,
                             c.rho_clip, c.c_clip, c.clip_eps),
            init_params_fn,
            num_learners=c.num_learners,
            lr=c.lr,
            max_grad_norm=c.max_grad_norm,
            seed=c.seed,
        )
        self.env_runner_group = EnvRunnerGroup(
            c.env_cls,
            num_env_runners=c.num_env_runners,
            num_envs_per_runner=c.num_envs_per_runner,
            rollout_len=c.rollout_len,
            seed=c.seed,
        )
        self._inflight: dict = {}  # sample ref -> runner actor
        self._recent_returns: list[float] = []
        self._env_steps = 0

    # ------------------------------------------------------------ async loop
    def _refill(self, weights) -> None:
        from ..core import api as ray

        busy = set(self._inflight.values())
        for actor in self.env_runner_group._actors:
            if actor not in busy:
                self._inflight[actor.sample.remote(weights)] = actor

    def _await_one(self, timeout: float = 300.0):
        """Pop ONE completed rollout (and the runner that produced it);
        runners without an in-flight request get one first."""
        from ..core import api as ray

        if not self.env_runner_group._actors:
            # Degenerate local mode: synchronous (still V-trace-corrected —
            # lag is simply zero).
            return self.env_runner_group._local.sample(
                self.learner_group.get_weights()), None
        self._refill(self.learner_group.get_weights())
        ready, _ = ray.wait(list(self._inflight), num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("no rollout completed within the timeout")
        ref = ready[0]
        return ray.get(ref, timeout=60), self._inflight.pop(ref)

    def training_step(self) -> dict:
        from ..core import api as ray

        c: IMPALAConfig = self.config  # type: ignore[assignment]
        metrics: dict = {}
        for _ in range(c.num_batches_per_iteration):
            sample, actor = self._await_one()
            batch = {
                "obs": sample["obs"],
                "actions": sample["actions"],
                "logp_old": sample["logp"],
                "rewards": sample["rewards"],
                "dones": sample["dones"],
                "trunc_values": sample["trunc_values"],
                "last_obs": sample["last_obs"],
            }
            metrics = self.learner_group.update(batch)
            if actor is not None:
                # Resubmit with the JUST-updated weights: the runner never
                # idles and its next rollout lags by at most one update.
                self._inflight[actor.sample.remote(
                    self.learner_group.get_weights())] = actor
            self._recent_returns.extend(sample["episode_returns"].tolist())
            self._env_steps += sample["rewards"].size

        self._recent_returns = self._recent_returns[-100:]
        metrics["episode_return_mean"] = (
            float(np.mean(self._recent_returns)) if self._recent_returns else 0.0
        )
        metrics["num_env_steps_sampled"] = self._env_steps
        return metrics

    def get_state(self) -> dict:
        return {"iteration": self.iteration, "learner": self.learner_group.get_state()}

    def set_state(self, state: dict) -> None:
        self.iteration = state["iteration"]
        self.learner_group.set_state(state["learner"])


IMPALAConfig.algo_cls = IMPALA


class APPOConfig(IMPALAConfig):
    """APPO = IMPALA's async architecture + PPO's clipped surrogate
    (reference ``rllib/algorithms/appo/appo.py``)."""

    def __init__(self):
        super().__init__()
        self.clip_eps = 0.2


class APPO(IMPALA):
    pass


APPOConfig.algo_cls = APPO
