"""EnvRunner / EnvRunnerGroup: parallel rollout collection.

Equivalent of ``rllib/env/env_runner.py`` + ``env_runner_group.py``:
each runner owns a vectorized env and a CPU copy of the policy, samples
fixed-length fragments, and the group gathers them in parallel actors.
Policy forward during rollout is numpy (batch of N envs, 2-layer MLP) —
shipping obs to an accelerator per step would be all latency, no math.
"""

from __future__ import annotations

import numpy as np

from . import models


def _softmax(x: np.ndarray) -> np.ndarray:
    z = x - x.max(axis=-1, keepdims=True)
    e = np.exp(z)
    return e / e.sum(axis=-1, keepdims=True)


def _np_forward(params, obs: np.ndarray):
    x = obs
    for layer in params["torso"]:
        x = np.tanh(x @ np.asarray(layer["w"]) + np.asarray(layer["b"]))
    logits = x @ np.asarray(params["pi"]["w"]) + np.asarray(params["pi"]["b"])
    value = (x @ np.asarray(params["vf"]["w"]) + np.asarray(params["vf"]["b"]))[:, 0]
    return logits, value


class EnvRunner:
    """Collects fragments of ``rollout_len`` steps from ``num_envs``
    parallel env copies. Returns flat arrays plus episode-return stats."""

    def __init__(self, env_cls, num_envs: int = 8, rollout_len: int = 64, seed: int = 0,
                 env_to_module=None):
        from .connectors import make_pipeline

        self.env = env_cls(num_envs=num_envs, seed=seed)
        self.num_envs = num_envs
        self.rollout_len = rollout_len
        self.rng = np.random.default_rng(seed ^ 0xA5)
        # ConnectorV2 pipeline between env observations and the module
        # (each runner owns its stateful copy — reference connector_v2.py)
        self.env_to_module = make_pipeline(env_to_module)
        self.obs = self._connect(self.env.reset())
        self._ep_return = np.zeros(num_envs, np.float32)
        self._completed: list[float] = []

    def _connect(self, obs: np.ndarray) -> np.ndarray:
        if self.env_to_module is None:
            return obs
        return self.env_to_module({"obs": obs})["obs"]

    def connector_state(self) -> dict:
        return self.env_to_module.get_state() if self.env_to_module else {}

    def sample(self, weights) -> dict:
        T, N = self.rollout_len, self.num_envs
        obs_buf = np.zeros((T, N, self.env.obs_dim), np.float32)
        act_buf = np.zeros((T, N), np.int64)
        logp_buf = np.zeros((T, N), np.float32)
        val_buf = np.zeros((T, N), np.float32)
        rew_buf = np.zeros((T, N), np.float32)
        done_buf = np.zeros((T, N), np.bool_)
        # V(terminal_obs) at time-limit truncations (0 elsewhere): GAE
        # bootstraps these instead of zeroing them — a balanced pole at the
        # 500-step cap is worth ~1/(1-gamma), not 1.
        trunc_val_buf = np.zeros((T, N), np.float32)

        for t in range(T):
            logits, value = _np_forward(weights, self.obs)
            probs = _softmax(logits)
            actions = (probs.cumsum(axis=1) > self.rng.random((N, 1))).argmax(axis=1)
            logp = np.log(probs[np.arange(N), actions] + 1e-10)
            obs_buf[t], act_buf[t] = self.obs, actions
            logp_buf[t], val_buf[t] = logp, value
            raw_obs, rewards, dones, info = self.env.step(actions)
            self.obs = self._connect(raw_obs)
            rew_buf[t], done_buf[t] = rewards, dones
            truncated = info["truncated"]
            if truncated.any():
                _, v_term = _np_forward(weights, self._connect(info["terminal_obs"]))
                trunc_val_buf[t, truncated] = v_term[truncated]
            self._ep_return += rewards
            for i in np.nonzero(dones)[0]:
                self._completed.append(float(self._ep_return[i]))
                self._ep_return[i] = 0.0

        _, last_value = _np_forward(weights, self.obs)
        completed, self._completed = self._completed, []
        return {
            "obs": obs_buf,
            "actions": act_buf,
            "logp": logp_buf,
            "values": val_buf,
            "rewards": rew_buf,
            "dones": done_buf,
            "trunc_values": trunc_val_buf,
            "last_value": last_value,
            # Bootstrap observation: off-policy consumers (V-trace) must
            # evaluate V(x_T) under the TARGET params, not the behavior
            # policy's value above.
            "last_obs": self.obs.copy(),
            "episode_returns": np.asarray(completed, np.float32),
        }


class EnvRunnerGroup:
    """N runner actors sampling in parallel (``num_env_runners=0`` runs
    one local runner in-process). ``runner_cls`` lets algorithms swap the
    action-selection/recording policy (PPO's distribution sampler, DQN's
    epsilon-greedy transition collector) while reusing the group
    machinery — the reference's EnvRunner polymorphism."""

    def __init__(self, env_cls, *, num_env_runners: int = 0, num_envs_per_runner: int = 8,
                 rollout_len: int = 64, seed: int = 0, runner_cls: type | None = None,
                 runner_kwargs: dict | None = None):
        runner_cls = runner_cls or EnvRunner
        kw = runner_kwargs or {}
        if num_env_runners == 0:
            self._local = runner_cls(env_cls, num_envs_per_runner, rollout_len, seed, **kw)
            self._actors = []
        else:
            from ..core import api as ray

            self._local = None
            cls = ray.remote(runner_cls)
            self._actors = [
                cls.remote(env_cls, num_envs_per_runner, rollout_len,
                           seed + 1000 * i, **kw)
                for i in range(num_env_runners)
            ]

    def connector_states(self) -> list[dict]:
        """Per-runner env-to-module connector states (stats sync for
        evaluation / checkpointing)."""
        if self._local is not None:
            c = self._local.env_to_module
            return [c.get_state() if c is not None else {}]
        from ..core import api as ray

        def _state(r):
            return r.connector_state.remote()

        try:
            return ray.get([_state(a) for a in self._actors], timeout=60)
        except Exception:
            return [{} for _ in self._actors]

    def sample(self, weights, **kwargs) -> list[dict]:
        if self._local is not None:
            return [self._local.sample(weights, **kwargs)]
        from ..core import api as ray

        return ray.get([a.sample.remote(weights, **kwargs) for a in self._actors],
                       timeout=300)

    def shutdown(self) -> None:
        from ..core import api as ray

        for a in self._actors:
            try:
                ray.kill(a)
            except Exception:
                pass
        self._actors = []
