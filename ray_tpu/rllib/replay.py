"""Replay buffer for off-policy algorithms.

Equivalent of the reference's
``rllib/utils/replay_buffers/replay_buffer.py`` (uniform
EpisodeReplayBuffer storage): a fixed-capacity ring of transitions with
uniform sampling. Stored as preallocated numpy columns — adds are
vectorized fragment appends, samples are one fancy-index per column.
"""

from __future__ import annotations

import numpy as np


class ReplayBuffer:
    def __init__(self, capacity: int, obs_dim: int, *, seed: int = 0,
                 action_dim: int | None = None):
        """``action_dim=None`` stores discrete int actions; an int stores
        continuous float32 action vectors (SAC)."""
        self.capacity = capacity
        self._obs = np.zeros((capacity, obs_dim), np.float32)
        self._next_obs = np.zeros((capacity, obs_dim), np.float32)
        self._actions = (np.zeros(capacity, np.int64) if action_dim is None
                         else np.zeros((capacity, action_dim), np.float32))
        self._rewards = np.zeros(capacity, np.float32)
        # 1.0 only for TRUE terminations: time-limit truncations bootstrap.
        self._terminated = np.zeros(capacity, np.float32)
        self._size = 0
        self._pos = 0
        self._rng = np.random.default_rng(seed ^ 0xB0FF)

    def __len__(self) -> int:
        return self._size

    def add_batch(self, obs, actions, rewards, next_obs, terminated) -> None:
        n = len(actions)
        idx = (self._pos + np.arange(n)) % self.capacity
        self._obs[idx] = obs
        self._actions[idx] = actions
        self._rewards[idx] = rewards
        self._next_obs[idx] = next_obs
        self._terminated[idx] = terminated
        self._pos = int((self._pos + n) % self.capacity)
        self._size = int(min(self._size + n, self.capacity))

    def sample(self, batch_size: int) -> dict:
        idx = self._rng.integers(0, self._size, batch_size)
        return {
            "obs": self._obs[idx],
            "actions": self._actions[idx],
            "rewards": self._rewards[idx],
            "next_obs": self._next_obs[idx],
            "terminated": self._terminated[idx],
        }
