"""CLI: cluster state inspection (`python -m ray_tpu.cli ...`).

Equivalent of the reference's `ray list ...` state CLI
(``python/ray/util/state/state_cli.py``) and `ray timeline`
(``python/ray/scripts/scripts.py``). Connects to a running cluster via
``--address`` (GCS address).
"""

from __future__ import annotations

import argparse
import json
import sys


def _connect(address: str | None) -> None:
    import ray_tpu

    if address:
        ray_tpu.init(address=address, num_cpus=0)
    elif not ray_tpu.is_initialized():
        print("error: pass --address GCS_HOST:PORT of a running cluster", file=sys.stderr)
        raise SystemExit(2)


def _print_table(rows: list[dict], columns: list[str]) -> None:
    if not rows:
        print("(none)")
        return
    widths = {c: max(len(c), *(len(str(r.get(c, ""))[:48]) for r in rows)) for c in columns}
    print("  ".join(c.upper().ljust(widths[c]) for c in columns))
    for r in rows:
        print("  ".join(str(r.get(c, ""))[:48].ljust(widths[c]) for c in columns))


def _cmd_chaos(args) -> int:
    from ray_tpu import chaos as chaos_mod

    if args.chaos_cmd == "plans":
        rows = [{"name": name, "description": p.get("description", "")}
                for name, p in chaos_mod.BUILTIN_PLANS.items()]
        if args.as_json:
            print(json.dumps(rows, indent=2))
        else:
            _print_table(rows, ["name", "description"])
        return 0
    # chaos run
    plan = chaos_mod.load_plan(args.plan)
    schedule = plan.compile(args.seed)
    if args.dry_run:
        # Canonical bytes: two runs with the same plan + seed must print
        # identical output (the reproducibility contract).
        sys.stdout.write(schedule.canonical_bytes().decode() + "\n")
        return 0
    _connect(args.address)
    try:
        report = chaos_mod.run_plan(
            plan, seed=args.seed, verify=not args.no_verify,
            verify_timeout_s=args.verify_timeout)
    except chaos_mod.ChaosVerificationError as e:
        print(f"RECOVERY VERIFICATION FAILED: {e}", file=sys.stderr)
        return 1
    print(json.dumps(report, indent=2, default=str))
    return 0


def _cmd_bench(args) -> int:
    # Runs against its OWN local cluster (no --address needed): the suite
    # saturates the task path, which would be rude to a shared cluster.
    if args.bench_cmd == "dag":
        from ray_tpu._dag_bench import run_dag_bench

        result = run_dag_bench(ticks=args.ticks, bursts=args.bursts)
        ok = bool(result.get("dag_tick_dispatch_overhead_us"))
        prefixes = ("dag_", "pp_decode_", "loop_obs_")
    elif args.bench_cmd == "recovery":
        from ray_tpu._recovery_bench import run_recovery_bench

        result = run_recovery_bench(train_steps=args.train_steps,
                                    grace_s=args.grace)
        ok = bool(result.get("recovery_train_resume_s") is not None
                  or result.get("recovery_serve_reroute_s") is not None)
        prefixes = ("recovery_",)
    elif args.bench_cmd == "migration":
        from ray_tpu._migration_bench import run_migration_bench

        result = run_migration_bench(samples=args.samples)
        ok = bool(result.get("serve_ttft_migrated_ms") is not None)
        prefixes = ("serve_ttft_migrated", "serve_ttft_cold",
                    "kv_migration_")
    elif args.bench_cmd == "overload":
        from ray_tpu._overload_bench import run_overload_bench

        result = run_overload_bench(storm_s=args.storm,
                                    deadline_ms=args.deadline_ms)
        on = result.get("serve_goodput_frac")
        off = result.get("serve_goodput_frac_unprotected")
        # Acceptance: protection ON strictly beats the unprotected
        # baseline cell, and admitted work keeps byte parity.
        ok = bool(on is not None and off is not None and on > off
                  and result.get("serve_overload_parity", 1.0) == 1.0)
        prefixes = ("serve_goodput_", "serve_shed_", "serve_admitted_",
                    "serve_overload_")
    elif args.bench_cmd == "train":
        from ray_tpu._train_loop_bench import run_train_loop_bench

        result = run_train_loop_bench(ticks=args.ticks, steps=args.steps)
        # Acceptance: the compiled loop kills ≥ 5x of the eager per-step
        # dispatch, keeps MFU no worse, and genuinely overlaps the
        # checkpoint commit with step compute.
        eager_us = result.get("train_step_dispatch_overhead_eager_us")
        loop_us = result.get("train_step_dispatch_overhead_us")
        ok = bool(
            eager_us and loop_us and eager_us >= 5.0 * loop_us
            and result.get("train_mfu_loop", 0)
            >= 0.95 * result.get("train_mfu_eager", 0)
            and (result.get("train_ckpt_overlap_frac") or 0) > 0.5
        ) or bool(result.get("train_mfu_skipped"))
        prefixes = ("train_mfu", "train_step_dispatch_", "train_ckpt_",
                    "train_loop_", "train_eager_")
    elif args.bench_cmd == "speculative":
        from ray_tpu._speculative_bench import run_speculative_bench

        result = run_speculative_bench(slots=args.slots,
                                       max_new=args.new_tokens,
                                       draft_k=args.draft_k)
        # Acceptance: speculation amortizes target forwards (> 1 token
        # per slot per verify dispatch) AND stays lossless.
        ok = bool(result.get("spec_tokens_per_dispatch", 0) > 1.0
                  and result.get("spec_parity", 1.0) == 1.0) \
            or bool(result.get("decode_tok_s_speculative_skipped"))
        prefixes = ("decode_tok_s_", "spec_")
    elif args.bench_cmd == "tenancy":
        from ray_tpu._tenancy_bench import run_tenancy_bench

        result = run_tenancy_bench(storm_s=args.storm)
        # Acceptance (ISSUE 16): mixed-adapter decode is byte-exact AND
        # one dispatch carries the whole adapter mix (dispatch count
        # flat vs a single-adapter batch); the noisy tenant's storm
        # moves the quiet tenant's p95 TTFT ≤ 15%; per-tenant goodput
        # under the mixed hot/cold storm is recorded.
        solo = result.get("tenant_quiet_p95_ttft_ms_solo")
        noisy = result.get("tenant_quiet_p95_ttft_ms_noisy")
        ok = bool(
            result.get("tenant_mixed_batch_parity", 0.0) == 1.0
            and result.get("tenant_mixed_dispatch_parity", 0.0) == 1.0
            and solo and noisy is not None and noisy <= 1.15 * solo
            and result.get("tenant_goodput_frac_hot") is not None
            and result.get("tenant_goodput_frac_cold") is not None
        ) or bool(result.get("tenant_mixed_batch_parity_skipped"))
        prefixes = ("tenant_", "adapter_")
    elif args.bench_cmd == "fleet":
        from ray_tpu._fleet_bench import run_fleet_bench

        result = run_fleet_bench(step_s=args.step)
        # Acceptance (ISSUE 19): standby promotion ≥ 10× faster than a
        # cold replica start, the fan-out weight broadcast is
        # byte-identical to direct load, and goodput through the 10×
        # offered-rate step is recorded.
        ok = bool(
            result.get("serve_replica_promote_speedup", 0.0) >= 10.0
            and result.get("fleet_broadcast_parity", 0.0) == 1.0
            and result.get("fleet_goodput_frac_step") is not None
        ) or bool(result.get("fleet_skipped"))
        prefixes = ("fleet_", "serve_replica_")
    elif args.bench_cmd == "core" and getattr(args, "scale", False):
        import os

        prefixes = ("core_scale_",)
        if os.environ.get("RAY_TPU_BENCH_SKIP_CORE_SCALE") == "1":
            # Declared skip: bench_check reports the cells as
            # intentionally skipped instead of silently vanished.
            result = {"core_scale_skipped": True}
            ok = True
        else:
            from ray_tpu._core_scale_bench import run_core_scale_bench

            result = run_core_scale_bench(raylets=args.raylets,
                                          num_tasks=args.tasks,
                                          num_actors=args.actors,
                                          chaos=args.chaos)
            ok = bool(result.get("core_scale_tasks_per_s")) and \
                result.get("core_scale_chaos_verify_ok", 1.0) == 1.0
    else:
        from ray_tpu._core_bench import run_core_bench

        result = run_core_bench(num_tasks=args.tasks, num_actors=args.actors,
                                calls_per_actor=args.calls,
                                num_objects=args.objects)
        ok = bool(result.get("core_tasks_per_s"))
        prefixes = ("core_",)
    print(json.dumps(result, indent=None if args.as_json else 2))
    if args.check_against:
        from ray_tpu import bench_check

        # A recorded BENCH_r*.json carries train/serve/flash metrics this
        # standalone run never produces — compare this suite's slice only.
        old = {k: v for k, v in
               bench_check.load_metrics(args.check_against).items()
               if k.startswith(prefixes)}
        report = bench_check.compare(old, result)
        print(bench_check.format_report(report, args.check_against,
                                        "this run"), file=sys.stderr)
        if report["regressions"] or report["missing"]:
            return 1
    return 0 if ok else 1


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="ray_tpu", description=__doc__)
    parser.add_argument("--address", help="GCS address of a running cluster")
    parser.add_argument("--json", action="store_true", dest="as_json")
    sub = parser.add_subparsers(dest="cmd", required=True)

    list_p = sub.add_parser("list", help="list cluster entities")
    list_p.add_argument("what", choices=["nodes", "actors", "tasks", "workers",
                                         "objects", "placement-groups", "errors"])
    sub.add_parser("summary", help="task counts by name and state")
    tl = sub.add_parser("timeline", help="dump a chrome://tracing file")
    tl.add_argument("-o", "--output", default="timeline.json")
    tr = sub.add_parser(
        "trace", help="list recent traces, or show one trace's span tree")
    tr.add_argument("trace_id", nargs="?",
                    help="trace id (omit to list recent traces)")
    tr.add_argument("--limit", type=int, default=20)
    tr.add_argument("--request", default=None, metavar="REQUEST_ID",
                    help="show the flight-recorder timeline dumped for "
                         "one LLM request on SLO breach (deadline "
                         "expiry, shed, TTFT-SLO breach)")
    loop_p = sub.add_parser(
        "loop", help="compiled-loop stall attribution")
    loop_sub = loop_p.add_subparsers(dest="loop_cmd", required=True)
    ltop = loop_sub.add_parser(
        "top", help="live per-stage wait_up/compute/wait_down splits and "
                    "the bottleneck stage for every compiled loop this "
                    "process owns (loops are driver-local; run in the "
                    "driver, or point a dashboard at /api/loops)")
    ltop.add_argument("--once", action="store_true",
                      help="print one snapshot and exit (no live refresh)")
    ltop.add_argument("--interval", type=float, default=2.0,
                      help="refresh period in seconds (default 2)")
    sub.add_parser("metrics", help="aggregated metrics (Prometheus text format)")
    sub.add_parser("status", help="cluster resource overview")
    doctor_p = sub.add_parser(
        "doctor", help="aggregate per-node debug state + recent error events")
    doctor_p.add_argument("--errors", type=int, default=10,
                          help="recent error events to show")
    mem_p = sub.add_parser(
        "memory", help="`ray memory`-style cluster view: per-worker object "
                       "refs with size, ref type, and creation callsite")
    mem_p.add_argument("--group-by-callsite", action="store_true",
                       help="aggregate holders per creation callsite")
    prof_p = sub.add_parser(
        "profile", help="capture an on-demand jax.profiler trace on a worker")
    prof_p.add_argument("--node", default=None,
                        help="node id prefix (default: the driver's node)")
    prof_p.add_argument("--worker", default=None, help="specific worker id")
    prof_p.add_argument("--duration", type=float, default=5.0,
                        help="capture length in seconds")
    prof_p.add_argument("--list", action="store_true", dest="list_profiles",
                        help="list previously captured artifacts instead")
    bench_p = sub.add_parser(
        "bench", help="run a benchmark suite standalone")
    bench_sub = bench_p.add_subparsers(dest="bench_cmd", required=True)
    bcore = bench_sub.add_parser(
        "core", help="core task-path throughput: no-op tasks, actor calls, "
                     "object put/get round trips (records core_*_per_s + "
                     "lease-stage p50s; guarded by ray_tpu.bench_check)")
    bcore.add_argument("--tasks", type=int, default=None,
                       help="no-op tasks (default $RAY_TPU_CORE_BENCH_TASKS "
                            "or 100000)")
    bcore.add_argument("--actors", type=int, default=None,
                       help="actor pool size (default 100)")
    bcore.add_argument("--calls", type=int, default=None,
                       help="calls per actor (default 100)")
    bcore.add_argument("--objects", type=int, default=None,
                       help="put/get round trips (default 10000)")
    bcore.add_argument("--scale", action="store_true",
                       help="run the MANY-RAYLET scale harness instead: "
                            "N in-process raylets, a cross-node task storm "
                            "and a 1k-actor creation storm on zygote pools "
                            "(records core_scale_*; "
                            "RAY_TPU_BENCH_SKIP_CORE_SCALE=1 emits the "
                            "core_scale_skipped marker)")
    bcore.add_argument("--raylets", type=int, default=None,
                       help="scale-harness raylet count (default "
                            "$RAY_TPU_CORE_SCALE_RAYLETS or 8)")
    bcore.add_argument("--chaos", action="store_true",
                       help="with --scale: also run the bundled "
                            "`actor-storm` FaultPlan against a reduced "
                            "storm and record core_scale_chaos_verify_ok")
    bcore.add_argument("--check-against", default=None, metavar="BENCH_JSON",
                       help="run ray_tpu.bench_check against a recorded "
                            "BENCH_r*.json and exit non-zero on regression")
    bdag = bench_sub.add_parser(
        "dag", help="compiled-loop dispatch suite: per-tick overhead "
                    "dynamic vs compiled (dag_tick_dispatch_overhead*_us, "
                    "dag_loop_ticks_per_s) + pp=2 engine decode tok/s "
                    "through both paths (pp_decode_tok_s_*; skip markers "
                    "where the pp shard_map can't run)")
    bdag.add_argument("--ticks", type=int, default=None,
                      help="tick-overhead iterations (default "
                           "$RAY_TPU_DAG_BENCH_TICKS or 300)")
    bdag.add_argument("--bursts", type=int, default=None,
                      help="timed decode bursts per mode (default "
                           "$RAY_TPU_DAG_BENCH_DECODE_BURSTS or 12)")
    bdag.add_argument("--check-against", default=None, metavar="BENCH_JSON",
                      help="run ray_tpu.bench_check against a recorded "
                           "BENCH_r*.json and exit non-zero on regression")
    brec = bench_sub.add_parser(
        "recovery", help="preemption recovery SLO suite: preempt-mid-train "
                         "and preempt-mid-serve through the real notice→"
                         "drain→kill path (recovery_train_resume_s, "
                         "recovery_serve_reroute_s, recovery_ckpt_lag_steps;"
                         " *_skipped markers where a scenario can't run)")
    brec.add_argument("--train-steps", type=int, default=None,
                      help="train steps in the preempt-mid-train scenario "
                           "(default $RAY_TPU_RECOVERY_BENCH_TRAIN_STEPS "
                           "or 24)")
    brec.add_argument("--grace", type=float, default=None,
                      help="preemption grace window in seconds (default "
                           "$RAY_TPU_RECOVERY_BENCH_GRACE_S or 0.5)")
    brec.add_argument("--check-against", default=None, metavar="BENCH_JSON",
                      help="run ray_tpu.bench_check against a recorded "
                           "BENCH_r*.json and exit non-zero on regression")
    bmig = bench_sub.add_parser(
        "migration", help="KV-migration cells: migrated vs cold TTFT at "
                          "the 2k-prompt cell (serve_ttft_migrated_ms must "
                          "beat 0.7x serve_ttft_cold_ms), greedy byte "
                          "parity, and raw page-transfer throughput "
                          "(kv_migration_mb_s); *_skipped markers where "
                          "a cell can't run")
    bmig.add_argument("--samples", type=int, default=None,
                      help="cold/migrated prompt pairs (default "
                           "$RAY_TPU_MIGRATION_SAMPLES or 3)")
    bmig.add_argument("--check-against", default=None, metavar="BENCH_JSON",
                      help="run ray_tpu.bench_check against a recorded "
                           "BENCH_r*.json and exit non-zero on regression")
    bovl = bench_sub.add_parser(
        "overload", help="overload-protection cells: a 2x-capacity "
                         "thundering herd with request deadlines + "
                         "bounded queues vs an unprotected baseline "
                         "(serve_goodput_frac must strictly beat "
                         "serve_goodput_frac_unprotected; "
                         "serve_shed_fast_fail_p95_ms is the time-to-503;"
                         " admitted requests keep greedy byte parity)")
    bovl.add_argument("--storm", type=float, default=None,
                      help="storm window in seconds (default "
                           "$RAY_TPU_OVERLOAD_STORM_S or 8)")
    bovl.add_argument("--deadline-ms", type=float, default=None,
                      help="per-request deadline in the protected phase "
                           "(default $RAY_TPU_OVERLOAD_DEADLINE_MS or "
                           "2500)")
    bovl.add_argument("--check-against", default=None, metavar="BENCH_JSON",
                      help="run ray_tpu.bench_check against a recorded "
                           "BENCH_r*.json and exit non-zero on regression")
    btrain = bench_sub.add_parser(
        "train", help="train compiled-loop cells: per-step dispatch "
                      "overhead eager vs compiled "
                      "(train_step_dispatch_overhead{_eager,}_us, "
                      "compiled must be ≥ 5x lower), real-step MFU both "
                      "ways (train_mfu_{eager,loop}, loop ≥ eager), and "
                      "the checkpoint-commit overlap fraction "
                      "(train_ckpt_overlap_frac > 0.5); "
                      "RAY_TPU_BENCH_SKIP_TRAIN_LOOP=1 emits *_skipped "
                      "markers")
    btrain.add_argument("--loop", action="store_true",
                        help="run the compiled-loop suite (the default — "
                             "the suite always measures BOTH drive modes; "
                             "the flag documents intent)")
    btrain.add_argument("--ticks", type=int, default=None,
                        help="dispatch-overhead steps per mode (default "
                             "$RAY_TPU_TRAIN_LOOP_BENCH_TICKS or 150)")
    btrain.add_argument("--steps", type=int, default=None,
                        help="MFU-phase train steps per mode (default "
                             "$RAY_TPU_TRAIN_LOOP_BENCH_STEPS or 24)")
    btrain.add_argument("--check-against", default=None, metavar="BENCH_JSON",
                        help="run ray_tpu.bench_check against a recorded "
                             "BENCH_r*.json and exit non-zero on regression")
    bspec = bench_sub.add_parser(
        "speculative", help="speculative-decoding cells: plain vs "
                            "draft-K/verify decode tok/s on repetitive "
                            "traffic (decode_tok_s_{plain,speculative}), "
                            "n-gram drafter accept rate, tokens per slot "
                            "per verify dispatch (must beat 1.0), and "
                            "greedy byte parity (spec_parity must be "
                            "1.0); *_skipped markers via "
                            "RAY_TPU_BENCH_SKIP_SPECULATIVE=1")
    bspec.add_argument("--slots", type=int, default=None,
                       help="batch slots (default $RAY_TPU_SPEC_BENCH_SLOTS "
                            "or 8)")
    bspec.add_argument("--new-tokens", type=int, default=None,
                       help="generated tokens per request (default "
                            "$RAY_TPU_SPEC_BENCH_NEW or 96)")
    bspec.add_argument("--draft-k", type=int, default=None,
                       help="drafted tokens per verify dispatch (default "
                            "$RAY_TPU_SPEC_BENCH_K or 6)")
    bspec.add_argument("--check-against", default=None, metavar="BENCH_JSON",
                       help="run ray_tpu.bench_check against a recorded "
                            "BENCH_r*.json and exit non-zero on regression")
    bten = bench_sub.add_parser(
        "tenancy", help="multi-tenant multiplexing cells: quiet-tenant "
                        "TTFT p95 solo vs under a quota-shed noisy "
                        "storm (must move ≤ 15%), per-tenant goodput "
                        "with a hot (resident) vs cold (LRU hot-load) "
                        "adapter under a mixed 2x storm, mixed-adapter "
                        "greedy byte parity + one-dispatch decode "
                        "(tenant_mixed_{batch,dispatch}_parity must be "
                        "1.0), and adapter_hot_load_ms; *_skipped "
                        "markers via RAY_TPU_BENCH_SKIP_TENANCY=1")
    bten.add_argument("--storm", type=float, default=None,
                      help="mixed hot/cold storm seconds (default "
                           "$RAY_TPU_TENANCY_STORM_S or 6)")
    bten.add_argument("--check-against", default=None, metavar="BENCH_JSON",
                      help="run ray_tpu.bench_check against a recorded "
                           "BENCH_r*.json and exit non-zero on regression")
    bfleet = bench_sub.add_parser(
        "fleet", help="always-warm fleet cells: standby promotion vs "
                      "cold replica start (serve_replica_promote_s, "
                      "speedup must be ≥ 10x), fan-out weight-broadcast "
                      "byte parity (fleet_broadcast_parity must be 1.0), "
                      "and goodput through a 10x offered-rate step "
                      "against a 1-running + 1-standby deployment; "
                      "*_skipped markers via RAY_TPU_BENCH_SKIP_FLEET=1")
    bfleet.add_argument("--step", type=float, default=None,
                        help="traffic-step seconds (default "
                             "$RAY_TPU_FLEET_STEP_S or 6)")
    bfleet.add_argument("--check-against", default=None,
                        metavar="BENCH_JSON",
                        help="run ray_tpu.bench_check against a recorded "
                             "BENCH_r*.json and exit non-zero on regression")
    serve_p = sub.add_parser(
        "serve", help="Serve control-plane inspection")
    serve_sub = serve_p.add_subparsers(dest="serve_cmd", required=True)
    serve_sub.add_parser(
        "status", help="apps, deployments, replica counts, autoscaling "
                       "mode and the recent scale decisions with their "
                       "trigger metric (TTFT p95 etc.)")
    chaos_p = sub.add_parser(
        "chaos", help="deterministic fault injection (seeded FaultPlans)")
    chaos_sub = chaos_p.add_subparsers(dest="chaos_cmd", required=True)
    crun = chaos_sub.add_parser(
        "run", help="run a fault plan against the cluster, then verify "
                    "recovery (tasks terminal, lease queues drained, "
                    "refcounts at baseline)")
    crun.add_argument("plan", help="plan YAML path or a bundled plan name "
                                   "(see `chaos plans`)")
    crun.add_argument("--seed", type=int, default=0,
                      help="schedule seed — same plan+seed compiles to a "
                           "byte-identical fault schedule")
    crun.add_argument("--dry-run", action="store_true",
                      help="print the compiled fault schedule (canonical "
                           "JSON) without touching a cluster")
    crun.add_argument("--no-verify", action="store_true")
    crun.add_argument("--verify-timeout", type=float, default=60.0)
    chaos_sub.add_parser("plans", help="list bundled fault plans")

    args = parser.parse_args(argv)
    if args.cmd == "bench":
        return _cmd_bench(args)
    if args.cmd == "chaos":
        return _cmd_chaos(args)
    _connect(args.address)
    import ray_tpu
    from ray_tpu.util import state as st

    if args.cmd == "list":
        what = args.what
        if what == "nodes":
            rows, cols = st.list_nodes(), ["node_id", "address", "state"]
        elif what == "actors":
            rows, cols = st.list_actors(), ["actor_id", "name", "state", "address"]
        elif what == "tasks":
            rows, cols = st.list_tasks(), ["task_id", "name", "state", "node_id"]
        elif what == "workers":
            rows, cols = st.list_workers(), ["worker_id", "state", "pid", "node_id"]
        elif what == "objects":
            rows, cols = st.list_objects(), ["object_id", "size", "state",
                                             "ref_type", "callsite", "node_id"]
        elif what == "errors":
            rows, cols = st.list_errors(), ["type", "source", "node_id", "message"]
        else:
            rows, cols = st.list_placement_groups(), ["pg_id", "state", "strategy"]
        print(json.dumps(rows, indent=2, default=str) if args.as_json else "", end="")
        if not args.as_json:
            _print_table(rows, cols)
    elif args.cmd == "summary":
        print(json.dumps(st.summarize_tasks(), indent=2))
    elif args.cmd == "timeline":
        path = ray_tpu.timeline(args.output)
        print(f"wrote {path}")
    elif args.cmd == "trace":
        from ray_tpu.observability import format_trace_tree

        if args.request:
            span = st.find_request_timeline(args.request)
            if span is None:
                print(f"no llm.request_timeline dump for request "
                      f"{args.request!r} (dumps fire on SLO breach: "
                      f"deadline expiry, shed, or TTFT-SLO breach)")
                return 1
            if args.as_json:
                print(json.dumps(span, indent=2, default=str))
            else:
                attrs = span.get("attrs") or {}
                print(f"request {args.request}  reason={attrs.get('reason')}"
                      f"  events={attrs.get('n_events')}"
                      f"  dropped={attrs.get('dropped')}")
                t0 = None
                for ev in attrs.get("events") or []:
                    t = float(ev.get("t", 0.0))
                    if t0 is None:
                        t0 = t
                    pin = " (pinned)" if ev.get("pinned") else ""
                    print(f"  +{1000 * (t - t0):9.3f} ms  "
                          f"{str(ev.get('ev', '?')):16s} "
                          f"value={ev.get('v', 0)}{pin}")
        elif args.trace_id:
            spans = st.list_spans(trace_id=args.trace_id)
            if args.as_json:
                print(json.dumps(spans, indent=2, default=str))
            else:
                print(format_trace_tree(spans))
        else:
            rows = st.list_traces(limit=args.limit)
            if args.as_json:
                print(json.dumps(rows, indent=2, default=str))
            else:
                _print_table(rows, ["trace_id", "root", "spans", "duration_ms"])
    elif args.cmd == "loop":
        import time as _time

        def _loop_rows():
            rows = []
            for loop in st.loop_stats():
                for name, s in (loop.get("stages") or {}).items():
                    frac = s.get("frac") or {}
                    rows.append({
                        "loop": loop.get("loop_id", "")[:12],
                        "stage": name,
                        "ticks": s.get("ticks", 0),
                        "wait_up": f"{frac.get('wait_up', 0.0):.0%}",
                        "compute": f"{frac.get('compute', 0.0):.0%}",
                        "wait_down": f"{frac.get('wait_down', 0.0):.0%}",
                        "state": s.get("state", ""),
                        "bottleneck": ("<-- bottleneck"
                                       if loop.get("bottleneck") == name
                                       else ""),
                    })
            return rows

        cols = ["loop", "stage", "ticks", "wait_up", "compute",
                "wait_down", "state", "bottleneck"]
        while True:
            rows = _loop_rows()
            if args.as_json:
                print(json.dumps(st.loop_stats(), indent=2, default=str))
            elif rows:
                _print_table(rows, cols)
            else:
                print("no live compiled loops in this process "
                      "(loops are driver-local; run inside the driver or "
                      "query the dashboard's /api/loops)")
            if args.once:
                break
            try:
                _time.sleep(max(0.1, args.interval))
            except KeyboardInterrupt:
                break
            print("\x1b[2J\x1b[H", end="")  # clear + home for the refresh
    elif args.cmd == "metrics":
        from ray_tpu.util.metrics import get_metrics, prometheus_text

        print(prometheus_text(get_metrics()), end="")
    elif args.cmd == "status":
        total = ray_tpu.cluster_resources()
        avail = ray_tpu.available_resources()
        nodes = st.list_nodes()
        print(f"nodes: {sum(1 for n in nodes if n['state'] == 'ALIVE')} alive / {len(nodes)}")
        for k in sorted(total):
            print(f"  {k}: {avail.get(k, 0.0):g} / {total[k]:g} available")
    elif args.cmd == "doctor":
        diag = st.cluster_diagnostics(error_limit=args.errors)
        if args.as_json:
            print(json.dumps(diag, indent=2, default=str))
            return 0
        gcs = diag["gcs"]
        print("GCS: nodes=%s actors=%s placement_groups=%s errors_buffered=%s" % (
            gcs.get("nodes_by_state", {}), gcs.get("actors_by_state", {}),
            gcs.get("placement_groups_by_state", {}), gcs.get("errors_buffered", 0)))
        plan = diag.get("active_fault_plan")
        if plan:
            print("ACTIVE FAULT PLAN: %s (seed=%s, digest=%s) — failures "
                  "below may be chaos-injected" % (
                      plan.get("name"), plan.get("seed"), plan.get("digest")))
        rows = []
        for snap in diag["nodes"]:
            queue = snap.get("lease_queue") or []
            store = snap.get("store") or {}
            rows.append({
                "node_id": snap.get("node_id", ""),
                "lease_queue": snap.get("lease_queue_depth", "?"),
                "oldest_wait_s": max((e["age_s"] for e in queue), default=0.0),
                "workers": snap.get("num_workers", "?"),
                "idle": snap.get("idle_workers", "?"),
                "store_used": store.get("used", "?"),
                "wedges": snap.get("wedge_events_total", 0),
                "orphans": snap.get("orphan_leases_total", 0),
                "oom_kills": snap.get("oom_kills_total", 0),
            })
        print("per-node lease queues / worker pools:")
        _print_table(rows, ["node_id", "lease_queue", "oldest_wait_s", "workers",
                            "idle", "store_used", "wedges", "orphans",
                            "oom_kills"])
        errors = diag["errors"]
        print(f"recent errors ({len(errors)}):")
        for e in errors:
            print("  [%s/%s] node=%s %s" % (
                e.get("source", "?"), e.get("type", "?"),
                (e.get("node_id") or "")[:8],
                str(e.get("message", "")).splitlines()[0][:120] if e.get("message") else ""))
    elif args.cmd == "memory":
        summary = st.memory_summary()
        if args.as_json:
            print(json.dumps(summary, indent=2, default=str))
            return 0
        if args.group_by_callsite:
            from ray_tpu.observability.memory import _top_holders

            entries = [e for w in summary.get("workers", [])
                       for e in w.get("entries", [])]
            print("%-52s %8s %12s  %s" % ("CALLSITE", "REFS", "BYTES", "REF_TYPES"))
            for h in _top_holders(entries, top_k=50):
                print("%-52s %8d %12d  %s" % (
                    h["callsite"][:52], h["count"], h["bytes"],
                    ",".join(h["ref_types"])))
            return 0
        from ray_tpu.observability import format_memory_summary

        print(format_memory_summary(summary, st.list_nodes()))
    elif args.cmd == "serve":
        from ray_tpu import serve as serve_api

        try:
            status = serve_api.status()
        except ValueError:
            print("no Serve instance running")
            return 1
        if args.as_json:
            print(json.dumps(status, indent=2, default=str))
            return 0
        if not status:
            print("no Serve applications deployed")
            return 0
        import datetime

        for app, deps in status.items():
            for name, st in deps.items():
                mode = st.get("autoscaling_mode") or "static"
                line = (f"{app}/{name}: {st['running_replicas']}/"
                        f"{st['target_replicas']} replicas "
                        f"[{'healthy' if st['healthy'] else 'UNHEALTHY'}] "
                        f"autoscaling={mode}")
                if st.get("last_start_failure"):
                    line += (" last_start_failure="
                             + str(st["last_start_failure"]).splitlines()[0][:80])
                print(line)
                ovl = dict(st.get("overload") or {})
                router_ovl = ovl.pop("router", None) or {}
                parts = [f"{k}={v}" for k, v in sorted(ovl.items())
                         if k != "replicas" and v]
                shed = router_ovl.get("shed") or {}
                parts += [f"shed_{k}={v}" for k, v in sorted(shed.items())]
                if router_ovl.get("deadline_expired_queued"):
                    parts.append("router_deadline_expired="
                                 + str(router_ovl["deadline_expired_queued"]))
                circuit = router_ovl.get("circuit") or {}
                if router_ovl.get("circuit_opens"):
                    parts.append(f"circuit_opens={router_ovl['circuit_opens']}")
                for rid, cst in sorted(circuit.items()):
                    parts.append(f"circuit[{rid}]={cst}")
                if parts:
                    print("  overload: " + " ".join(parts))
                # Always-warm fleet: standby pool, scale-to-zero park,
                # and the last standby promotion with its path/timing.
                if st.get("standby_replicas") or st.get("scaled_to_zero") \
                        or st.get("last_promote"):
                    fparts = [f"standby={st.get('standby_replicas', 0)}"]
                    if st.get("scaled_to_zero"):
                        fparts.append("scaled_to_zero")
                    fl = st.get("fleet") or {}
                    if fl.get("idle_s") is not None:
                        fparts.append(f"idle_s={round(fl['idle_s'], 1)}")
                    if fl.get("host_resident"):
                        fparts.append(f"host_resident={fl['host_resident']}")
                    lp = st.get("last_promote") or {}
                    if lp:
                        fparts.append(
                            f"last_promote={lp.get('path')}"
                            f"/{round(float(lp.get('seconds') or 0), 3)}s")
                    print("  fleet: " + " ".join(fparts))
                ten = dict(st.get("tenancy") or {})
                resident = ten.get("resident_adapters") or []
                if resident or ten.get("adapter_defers"):
                    line = "  adapters: resident=" + (",".join(resident) or "-")
                    if ten.get("adapter_defers"):
                        line += f" defers={ten['adapter_defers']}"
                    print(line)
                scope = ten.get("scope")
                for tenant, row in sorted((ten.get("tenants") or {}).items()):
                    tparts = [f"admitted={row.get('admitted', 0)}"]
                    for k in ("shed", "quota_rejects"):
                        if row.get(k):
                            tparts.append(f"{k}={row[k]}")
                    if row.get("quota_remaining") is not None:
                        tparts.append(
                            f"quota_remaining={row['quota_remaining']}")
                    if row.get("p95_ttft_ms") is not None:
                        tparts.append(
                            f"p95_ttft_ms={round(float(row['p95_ttft_ms']), 1)}")
                    if row.get("slo_burn_frac") is not None:
                        tparts.append(
                            f"slo_burn={float(row['slo_burn_frac']):.0%}")
                    if row.get("cost_correction") is not None:
                        tparts.append(
                            f"cost_corr={row['cost_correction']}")
                    if scope:
                        tparts.append(f"scope={scope}")
                    print(f"  tenant[{tenant}]: " + " ".join(tparts))
                for b in (ten.get("last_breaches") or [])[-3:]:
                    ts = datetime.datetime.fromtimestamp(
                        b.get("ts", 0.0)).strftime("%H:%M:%S")
                    print(f"  breach[{ts}] request={b.get('request_id')} "
                          f"reason={b.get('reason')} "
                          f"events={b.get('n_events')} "
                          f"(full dump: cli trace --request "
                          f"{b.get('request_id')})")
                for e in st.get("autoscale_events") or []:
                    ts = datetime.datetime.fromtimestamp(e["ts"]).strftime(
                        "%H:%M:%S")
                    print(f"  [{ts}] scale {e['from']} -> {e['to']} "
                          f"({e['trigger']}={e['value']} vs target "
                          f"{e['target']})")
    elif args.cmd == "profile":
        if args.list_profiles:
            rows = st.list_profiles()
            if args.as_json:
                print(json.dumps(rows, indent=2, default=str))
            else:
                _print_table(rows, ["path", "node_id", "worker_id", "duration"])
            return 0
        reply = st.capture_profile(node_id=args.node, duration=args.duration,
                                   worker_id=args.worker)
        if reply.get("error"):
            print(f"error: {reply['error']}", file=sys.stderr)
            return 1
        print(json.dumps(reply, indent=2, default=str) if args.as_json
              else f"wrote {reply['path']} (worker {reply.get('worker_id', '')[:12]}, "
                   f"{reply.get('duration')}s) — open with XProf/TensorBoard")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
