"""Core task-path throughput suite (ROADMAP item 3).

Single-node edition of the Ray reference's many-tasks / many-actors /
many-objects release tests: the numbers that make "millions of users"
claims checkable, because serve routers, Data pipelines, and the
chaos/diagnostics subsystems all ride the same
``submit_task → RequestWorkerLease → push → ReturnWorker`` path this
suite saturates.

Three phases, each reported as a throughput metric guarded by
``ray_tpu.bench_check``:

  * ``core_tasks_per_s``          — no-op task round trips (submit 100k,
                                    get all)
  * ``core_actor_calls_per_s``    — actor method round trips across a
                                    pool of actors
  * ``core_obj_roundtrip_per_s``  — ``put``/``get`` fan-out of small
                                    objects

plus the p50 of every ``ray_tpu_lease_stage_ms`` stage observed during
the run (``core_lease_<stage>_p50_ms``) — the evidence trail for
attacking the owner→raylet→GCS hot path (PERF.md "core task path").

Sizes are env-tunable (``RAY_TPU_CORE_BENCH_{TASKS,ACTORS,CALLS,OBJECTS}``);
the defaults finish in a couple of minutes on a laptop-class node. Run
standalone via ``python -m ray_tpu.cli bench core`` or as part of
``bench.py``.
"""

from __future__ import annotations

import os
import time


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, default))


def _merge_lease_stage_p50s() -> dict:
    """p50 per lease stage, buckets merged across nodes. Best-effort:
    the histograms ride the task-event flush, so poll briefly for the
    counts to land before reading."""
    try:
        from ray_tpu.util.metrics import get_metrics, histogram_quantile
    except Exception:
        return {}
    merged: dict[str, dict] = {}
    deadline = time.perf_counter() + 8.0
    while time.perf_counter() < deadline:
        rows = [m for m in get_metrics()
                if m.get("name") == "ray_tpu_lease_stage_ms" and m.get("count")]
        if rows:
            break
        time.sleep(0.5)
    else:
        rows = []
    for m in rows:
        stage = (m.get("tags") or {}).get("stage", "")
        agg = merged.get(stage)
        if agg is None:
            merged[stage] = {"buckets": list(m.get("buckets") or []),
                             "boundaries": list(m.get("boundaries") or []),
                             "count": m.get("count", 0)}
        else:
            for i, b in enumerate(m.get("buckets") or []):
                if i < len(agg["buckets"]):
                    agg["buckets"][i] += b
            agg["count"] += m.get("count", 0)
    out = {}
    for stage, agg in merged.items():
        q = histogram_quantile(agg, 0.5)
        if q is not None:
            out[f"core_lease_{stage}_p50_ms"] = round(q, 2)
            out[f"core_lease_{stage}_count_cfg"] = agg["count"]
    return out


def run_core_bench(*, num_tasks: int | None = None, num_actors: int | None = None,
                   calls_per_actor: int | None = None,
                   num_objects: int | None = None,
                   connect: bool = True) -> dict:
    """Run the three core phases and return the metrics dict. With
    ``connect`` (default) a local cluster is started and shut down; pass
    False to run inside an already-initialized driver."""
    import ray_tpu

    num_tasks = num_tasks or _env_int("RAY_TPU_CORE_BENCH_TASKS", 100_000)
    num_actors = num_actors or _env_int("RAY_TPU_CORE_BENCH_ACTORS", 100)
    calls_per_actor = calls_per_actor or _env_int("RAY_TPU_CORE_BENCH_CALLS", 100)
    num_objects = num_objects or _env_int("RAY_TPU_CORE_BENCH_OBJECTS", 10_000)
    # Zygote pool sized for the actor phase (how an operator expecting
    # this churn would run it): the creation storm binds pre-forked
    # registered workers instead of spawning at grant time. Echoed as a
    # _cfg input; restored after the run so later bench phases in the
    # same process don't inherit a storm-sized idle pool.
    pool = _env_int("RAY_TPU_CORE_BENCH_POOL", min(num_actors, 64))

    if connect:
        # Every actor pins a dedicated 1.0-CPU lease for its lifetime, so
        # the logical pool must cover the whole actor pool plus headroom
        # for the task pipelines (CPU here is a scheduling token, not a
        # core count).
        ray_tpu.init(num_cpus=_env_int(
            "RAY_TPU_CORE_BENCH_CPUS",
            max(num_actors + 16, os.cpu_count() or 8)),
            ignore_reinit_error=True)

    @ray_tpu.remote
    def _noop():
        return None

    @ray_tpu.remote
    class _Counter:
        def __init__(self):
            self.n = 0

        def ping(self, i):
            self.n += 1
            return i

    out: dict = {
        "core_tasks_cfg": num_tasks,
        "core_actors_cfg": num_actors,
        "core_actor_calls_cfg": num_actors * calls_per_actor,
        "core_objects_cfg": num_objects,
        "core_zygote_pool_cfg": pool,
    }

    try:
        _run_phases(out, _noop, _Counter, num_tasks=num_tasks,
                    num_actors=num_actors, calls_per_actor=calls_per_actor,
                    num_objects=num_objects, pool=pool)
    finally:
        if connect:
            ray_tpu.shutdown()
    return out


def _settle_workers(timeout_s: float = 20.0) -> None:
    """Wait until the local raylet's worker table stops churning (storm
    workers reaped, idle pool shrunk back toward target) so the next
    timed phase doesn't measure against a node busy burying processes.
    Best-effort: falls back to a fixed sleep off-process."""
    try:
        from ray_tpu.core import api as core_api

        raylet = core_api._node.raylet
    except Exception:
        time.sleep(2.0)
        return
    deadline = time.perf_counter() + timeout_s
    stable_since, last = None, None
    while time.perf_counter() < deadline:
        count = sum(1 for w in raylet._workers.values() if w.state != "dead")
        if count != last:
            last, stable_since = count, time.perf_counter()
        elif time.perf_counter() - stable_since > 1.5:
            return
        time.sleep(0.2)


def _prewarm_pool(pool: int, timeout_s: float = 30.0) -> None:
    """Size the zygote pool for the coming storm and wait (bounded) for
    the refill loop to fill it — the storm then measures pool binding,
    not fork backlog. In-process raylet only; silently best-effort."""
    try:
        from ray_tpu.core import api as core_api

        raylet = core_api._node.raylet
    except Exception:
        time.sleep(2.0)
        return
    deadline = time.perf_counter() + timeout_s
    while time.perf_counter() < deadline:
        idle = sum(1 for wid in raylet._idle
                   if (w := raylet._workers.get(wid)) and w.env_hash == "")
        if idle >= pool:
            return
        time.sleep(0.1)


def _run_phases(out: dict, _noop, _Counter, *, num_tasks: int,
                num_actors: int, calls_per_actor: int,
                num_objects: int, pool: int) -> None:
    import ray_tpu
    from ray_tpu.core.config import get_config

    # Warmup: boot the worker pool / zygote and compile the submit path
    # so the timed window measures the steady state, not cold start.
    ray_tpu.get([_noop.remote() for _ in range(64)])

    # --- phase 1: no-op task throughput ---------------------------------
    t0 = time.perf_counter()
    refs = [_noop.remote() for _ in range(num_tasks)]
    submit_dt = time.perf_counter() - t0
    ray_tpu.get(refs)
    dt = time.perf_counter() - t0
    del refs
    out["core_tasks_per_s"] = round(num_tasks / dt, 1)
    out["core_task_submit_per_s"] = round(num_tasks / submit_dt, 1)

    # --- phase 2: actor creation + call throughput -----------------------
    # The creation storm runs against a storm-sized zygote pool (scoped
    # to THIS phase: the pool knobs are restored right after the timed
    # window, and the idle-shrink reaper returns the node to baseline
    # before the call/object phases measure).
    cfg = get_config()
    saved_pool = {k: getattr(cfg, k)
                  for k in ("zygote_pool_size", "zygote_pool_refill_batch")}
    cfg.zygote_pool_size = pool
    cfg.zygote_pool_refill_batch = 8
    _prewarm_pool(pool)
    # The pool now covers the whole storm: drop the refill rate so
    # replacement forks don't compete with the storm for CPU inside the
    # timed window (they resume at full rate once the knobs restore).
    cfg.zygote_pool_refill_batch = 1
    t0 = time.perf_counter()
    actors = [_Counter.remote() for _ in range(num_actors)]
    # An actor is "created" once its first call returns.
    ray_tpu.get([a.ping.remote(0) for a in actors])
    create_dt = time.perf_counter() - t0
    # Canonical guarded name (round 14, the zygote-pool gate); the
    # original spelling stays for BENCH continuity across rounds.
    out["core_actor_creations_per_s"] = round(num_actors / create_dt, 1)
    out["core_actor_creates_per_s"] = out["core_actor_creations_per_s"]
    for k, v in saved_pool.items():
        setattr(cfg, k, v)
    # Let the idle-shrink reaper drain the storm pool back to baseline
    # so the call phase isn't measured against a node full of residents.
    _settle_workers()
    t0 = time.perf_counter()
    refs = [a.ping.remote(i)
            for i in range(calls_per_actor) for a in actors]
    ray_tpu.get(refs)
    call_dt = time.perf_counter() - t0
    out["core_actor_calls_per_s"] = round(
        num_actors * calls_per_actor / call_dt, 1)
    for a in actors:
        try:
            ray_tpu.kill(a)
        except Exception:
            pass
    del actors, refs
    # Let the killed actor workers actually exit before timing phase 3 —
    # 100 dying processes reaping mid-measurement is noise, not signal.
    time.sleep(2.0)
    _settle_workers()

    # --- phase 3: object put/get round trips ----------------------------
    payload = os.urandom(256)  # small: the inline (in-process store) path
    t0 = time.perf_counter()
    orefs = [ray_tpu.put((i, payload)) for i in range(num_objects)]
    ray_tpu.get(orefs)
    dt = time.perf_counter() - t0
    del orefs
    out["core_obj_roundtrip_per_s"] = round(num_objects / dt, 1)

    out.update(_merge_lease_stage_p50s())


def main() -> int:
    import json
    import sys

    result = run_core_bench()
    print(json.dumps(result))
    return 0 if result.get("core_tasks_per_s") else 1


if __name__ == "__main__":
    raise SystemExit(main())
