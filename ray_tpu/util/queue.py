"""Distributed FIFO queue backed by a named actor.

Equivalent of the reference's ``python/ray/util/queue.py``: a ``Queue``
handle is cheap to pickle into tasks/actors; all operations go through
one queue actor, so producers and consumers anywhere in the cluster see
one total order. Blocking get/put are implemented with bounded polling
from the caller side (the actor itself never blocks its event loop).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any

from ..core import api as ray


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int):
        self.maxsize = maxsize
        self.items: deque = deque()

    def qsize(self) -> int:
        return len(self.items)

    def put(self, item) -> bool:
        if self.maxsize > 0 and len(self.items) >= self.maxsize:
            return False
        self.items.append(item)
        return True

    def put_many(self, items: list) -> bool:
        """All-or-nothing: never partially inserts (a retry after Full
        must not duplicate a prefix)."""
        if self.maxsize > 0 and len(self.items) + len(items) > self.maxsize:
            return False
        self.items.extend(items)
        return True

    def get(self):
        if not self.items:
            return False, None
        return True, self.items.popleft()

    def get_many(self, n: int) -> tuple[bool, list]:
        """All-or-nothing: items stay queued unless n are available (a
        failed batch get must not discard data)."""
        if len(self.items) < n:
            return False, []
        return True, [self.items.popleft() for _ in range(n)]


class Queue:
    def __init__(self, maxsize: int = 0, *, actor_options: dict | None = None):
        opts = {"num_cpus": 0, **(actor_options or {})}
        self._actor = ray.remote(_QueueActor).options(**opts).remote(maxsize)
        self.maxsize = maxsize

    def qsize(self) -> int:
        return ray.get(self._actor.qsize.remote(), timeout=60)

    def empty(self) -> bool:
        return self.qsize() == 0

    def full(self) -> bool:
        return self.maxsize > 0 and self.qsize() >= self.maxsize

    def put(self, item: Any, block: bool = True, timeout: float | None = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if ray.get(self._actor.put.remote(item), timeout=60):
                return
            if not block:
                raise Full
            if deadline is not None and time.monotonic() > deadline:
                raise Full
            time.sleep(0.01)

    def get(self, block: bool = True, timeout: float | None = None) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            ok, item = ray.get(self._actor.get.remote(), timeout=60)
            if ok:
                return item
            if not block:
                raise Empty
            if deadline is not None and time.monotonic() > deadline:
                raise Empty
            time.sleep(0.01)

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def put_nowait_batch(self, items: list) -> None:
        items = list(items)
        if not ray.get(self._actor.put_many.remote(items), timeout=60):
            raise Full(f"batch of {len(items)} items does not fit")

    def get_nowait_batch(self, num_items: int) -> list:
        ok, out = ray.get(self._actor.get_many.remote(num_items), timeout=60)
        if not ok:
            raise Empty(f"fewer than {num_items} items available")
        return out

    def shutdown(self) -> None:
        try:
            ray.kill(self._actor)
        except Exception:
            pass
