"""State API: list/summarize cluster entities.

Equivalent of the reference's ``python/ray/util/state/api.py:110``
(``StateApiClient``, list_actors:784, summarize_tasks:1368) minus the
dashboard hop: queries go straight to the GCS, which is the single source
of truth for nodes/actors/tasks/placement groups in this runtime.
"""

from __future__ import annotations

from typing import Any

from ..core.worker import global_worker


def _gcs(method: str, payload: dict | None = None) -> dict:
    return global_worker()._gcs_call(method, payload or {})


def list_nodes() -> list[dict]:
    return _gcs("GetAllNodes")["nodes"]


def list_actors() -> list[dict]:
    return _gcs("ListActors")["actors"]


def list_tasks(limit: int = 1000) -> list[dict]:
    return _gcs("ListTaskEvents", {"limit": limit})["tasks"]


def list_placement_groups() -> list[dict]:
    return _gcs("ListPlacementGroups")["placement_groups"]


def list_spans(trace_id: str | None = None, limit: int = 1000) -> list[dict]:
    """Trace spans retained by the GCS span store (observability/):
    task submit/lease/spawn/execute hops plus the serve request path
    (http → router → replica batch → llm prefill/decode), connected by
    ``trace_id``/``parent_id``."""
    return _gcs("ListSpans", {"trace_id": trace_id, "limit": limit})["spans"]


def list_traces(limit: int = 100) -> list[dict]:
    """Per-trace summaries (root span, span count, duration)."""
    return _gcs("ListTraces", {"limit": limit})["traces"]


def loop_stats() -> list[dict]:
    """Per-loop stall attribution for every compiled loop THIS process
    compiled (loops are driver-owned objects — there is no cluster-wide
    loop registry): one row per live loop with per-stage
    wait_up/compute/wait_down splits and the bottleneck stage. Stats
    come from node-local snapshot files the resident stages flush on the
    span cadence (no RPC to the parked stage actors)."""
    from ..dag.loop import live_loop_stats

    return live_loop_stats()


def serve_fleet() -> dict:
    """Always-warm fleet view per serve deployment: running vs standby
    replica counts, the scale-to-zero latch, folded replica residency
    (idle age, host-resident weight copies), and the last standby
    promotion with its path and timing — pulled from the controller's
    ``get_app_status`` so ``cli serve status`` and tests see one truth."""
    from ..serve import api as serve_api

    out: dict = {}
    try:
        status = serve_api.status()
    except Exception:
        return out
    for app, deps in (status or {}).items():
        for name, dep in (deps or {}).items():
            out[f"{app}#{name}"] = {
                "running": dep.get("running_replicas"),
                "standby": dep.get("standby_replicas"),
                "target": dep.get("target_replicas"),
                "scaled_to_zero": dep.get("scaled_to_zero"),
                "fleet": dep.get("fleet") or {},
                "last_promote": dep.get("last_promote"),
            }
    return out


def find_request_timeline(request_id: str, limit: int = 200) -> dict | None:
    """The most recent ``llm.request_timeline`` breach dump for one
    request id: scans this process's local span buffer first (standalone
    engines), then recent traces in the GCS span store. Returns the span
    dict (attrs carry the event list) or None."""
    from ..observability import tracing

    def _match(spans):
        hits = [s for s in spans
                if s.get("name") == "llm.request_timeline"
                and (s.get("attrs") or {}).get("request_id") == request_id]
        return max(hits, key=lambda s: s.get("end", 0.0)) if hits else None

    hit = _match(tracing.local_spans())
    if hit is not None:
        return hit
    try:
        for row in list_traces(limit=limit):
            hit = _match(list_spans(trace_id=row["trace_id"]))
            if hit is not None:
                return hit
    except Exception:
        return None
    return None


def _fanout_raylets(method: str, payload: dict, result_key: str) -> list[dict]:
    """Call a raylet RPC on every alive node concurrently; tag each row
    with its node_id. Nodes that fail to answer are skipped."""
    import asyncio

    from ..core.rpc import RpcClient

    nodes = [n for n in list_nodes() if n["state"] == "ALIVE"]
    worker = global_worker()

    async def _one(node):
        client = RpcClient(node["address"])
        try:
            reply = await client.call(method, payload, timeout=10.0)
            rows = reply.get(result_key, [])
            for r in rows:
                r["node_id"] = node["node_id"]
            return rows
        except Exception:
            return []
        finally:
            await client.close()

    async def _all():
        return await asyncio.gather(*(_one(n) for n in nodes))

    return [row for rows in worker.io.run_sync(_all()) for row in rows]


def list_workers() -> list[dict]:
    """Workers across all alive nodes (raylet worker-pool fan-out)."""
    return _fanout_raylets("ListWorkers", {}, "workers")


def list_objects(limit: int = 1000) -> list[dict]:
    """Objects in each node's plasma store, enriched with the owner-side
    reference view (ref type + creation callsite + age from the workers'
    memory summaries). Warns — never silently truncates — when any node's
    listing hit ``limit``."""
    import asyncio
    import warnings

    from ..core.rpc import RpcClient

    nodes = [n for n in list_nodes() if n["state"] == "ALIVE"]
    worker = global_worker()

    async def _one(node):
        client = RpcClient(node["address"])
        try:
            reply = await client.call("ListObjects", {"limit": limit}, timeout=10.0)
            for r in reply.get("objects", []):
                r["node_id"] = node["node_id"]
            return reply
        except Exception:
            return {"objects": []}
        finally:
            await client.close()

    async def _all():
        return await asyncio.gather(*(_one(n) for n in nodes))

    replies = worker.io.run_sync(_all())
    rows = [row for reply in replies for row in reply.get("objects", [])]
    truncated = [r for r in replies if r.get("truncated")]
    if truncated:
        warnings.warn(
            f"list_objects(limit={limit}) truncated: "
            f"{sum(r.get('total', 0) for r in truncated)} objects exist on "
            f"{len(truncated)} node(s); raise limit for the full view",
            stacklevel=2)
    # Merge in the reference-debugging fields reported by owners.
    by_oid: dict[str, dict] = {}
    try:
        for w in memory_summary().get("workers", []):
            for e in w.get("entries", []):
                by_oid.setdefault(e.get("object_id", ""), e)
    except Exception:
        pass
    for row in rows:
        ref = by_oid.get(row.get("object_id", ""))
        if ref:
            row.setdefault("size", ref.get("size", 0))
            row["ref_type"] = ref.get("ref_type", "")
            row["callsite"] = ref.get("callsite", "")
            row["age_s"] = round(ref.get("age_s", 0.0), 1)
    return rows


def memory_summary() -> dict:
    """Cluster memory view (reference ``ray memory`` /
    ``memory_summary()``): per-worker reference tables with object sizes,
    ref types (LOCAL_REFERENCE / USED_BY_PENDING_TASK / ...), creation
    callsites, and ages, aggregated by the GCS from the workers' periodic
    reports on the task-event flush path."""
    return _gcs("MemorySummary")["summary"]


def capture_profile(node_id: str | None = None, duration: float = 2.0,
                    worker_id: str | None = None) -> dict:
    """Trigger an on-demand ``jax.profiler`` trace capture on a worker of
    ``node_id`` (prefix match; default: this node) and return the artifact
    info (``{"path", "worker_id", "node_id", "duration"}`` or
    ``{"error"}``). The artifact is also registered under
    ``list_profiles()`` / dashboard ``/api/profiles``."""
    import asyncio

    from ..core.rpc import RpcClient

    worker = global_worker()
    nodes = [n for n in list_nodes() if n["state"] == "ALIVE"]
    if node_id:
        nodes = [n for n in nodes if n["node_id"].startswith(node_id)]
        if not nodes:
            return {"error": f"no alive node matching {node_id!r}"}
    else:
        nodes = [n for n in nodes if n["node_id"] == worker.node_id] or nodes
    node = nodes[0]

    async def _call():
        client = RpcClient(node["address"])
        try:
            return await client.call(
                "CaptureProfile",
                {"duration": duration, "worker_id": worker_id or ""},
                timeout=duration + 150.0)
        finally:
            await client.close()

    return worker.io.run_sync(_call())


def list_profiles() -> list[dict]:
    """Profiler artifacts captured via ``capture_profile`` / ``cli
    profile``, most recent last."""
    return _gcs("ListProfiles")["profiles"]


def summarize_tasks() -> dict:
    """Counts by (name, state) — reference summarize_tasks:1368."""
    summary: dict[str, dict[str, int]] = {}
    for t in list_tasks(limit=100_000):
        entry = summary.setdefault(t["name"], {})
        entry[t["state"]] = entry.get(t["state"], 0) + 1
    return summary


# ------------------------------------------------------------- diagnostics
def list_errors(source: str | None = None, error_type: str | None = None,
                limit: int = 100) -> list[dict]:
    """Structured ErrorEvents retained by the GCS error-info channel:
    raising tasks, failed actor/replica starts, OOM kills, lease-wedge
    watchdog reports (reference: the driver's error-message listener over
    RAY_ERROR_INFO_CHANNEL, surfaced as a state API)."""
    return _gcs("ListErrors", {
        "source": source, "type": error_type, "limit": limit,
    })["errors"]


def cluster_diagnostics(error_limit: int = 50) -> dict:
    """One aggregated doctor view: the GCS control-plane snapshot, every
    alive raylet's debug state (lease queue with ages, worker pool,
    store/spill/OOM counters), and the most recent ErrorEvents."""
    import asyncio

    from ..core.rpc import RpcClient

    nodes = [n for n in list_nodes() if n["state"] == "ALIVE"]
    worker = global_worker()

    async def _one(node):
        client = RpcClient(node["address"])
        try:
            reply = await client.call("GetDebugState", {}, timeout=10.0)
            snap = reply.get("debug_state") or {}
            snap.setdefault("node_id", node["node_id"])
            return snap
        except Exception as e:
            return {"node_id": node["node_id"], "unreachable": str(e)}
        finally:
            await client.close()

    async def _all():
        return await asyncio.gather(*(_one(n) for n in nodes))

    from ..chaos.runner import active_plan

    return {
        "gcs": _gcs("GetDebugState").get("debug_state", {}),
        "nodes": list(worker.io.run_sync(_all())),
        "errors": list_errors(limit=error_limit),
        # Registered FaultPlan, if chaos is running — operators must be
        # able to tell injected pain from real pain.
        "active_fault_plan": active_plan(),
    }
