"""State API: list/summarize cluster entities.

Equivalent of the reference's ``python/ray/util/state/api.py:110``
(``StateApiClient``, list_actors:784, summarize_tasks:1368) minus the
dashboard hop: queries go straight to the GCS, which is the single source
of truth for nodes/actors/tasks/placement groups in this runtime.
"""

from __future__ import annotations

from typing import Any

from ..core.worker import global_worker


def _gcs(method: str, payload: dict | None = None) -> dict:
    return global_worker()._gcs_call(method, payload or {})


def list_nodes() -> list[dict]:
    return _gcs("GetAllNodes")["nodes"]


def list_actors() -> list[dict]:
    return _gcs("ListActors")["actors"]


def list_tasks(limit: int = 1000) -> list[dict]:
    return _gcs("ListTaskEvents", {"limit": limit})["tasks"]


def list_placement_groups() -> list[dict]:
    return _gcs("ListPlacementGroups")["placement_groups"]


def list_spans(trace_id: str | None = None, limit: int = 1000) -> list[dict]:
    """Trace spans retained by the GCS span store (observability/):
    task submit/lease/spawn/execute hops plus the serve request path
    (http → router → replica batch → llm prefill/decode), connected by
    ``trace_id``/``parent_id``."""
    return _gcs("ListSpans", {"trace_id": trace_id, "limit": limit})["spans"]


def list_traces(limit: int = 100) -> list[dict]:
    """Per-trace summaries (root span, span count, duration)."""
    return _gcs("ListTraces", {"limit": limit})["traces"]


def _fanout_raylets(method: str, payload: dict, result_key: str) -> list[dict]:
    """Call a raylet RPC on every alive node concurrently; tag each row
    with its node_id. Nodes that fail to answer are skipped."""
    import asyncio

    from ..core.rpc import RpcClient

    nodes = [n for n in list_nodes() if n["state"] == "ALIVE"]
    worker = global_worker()

    async def _one(node):
        client = RpcClient(node["address"])
        try:
            reply = await client.call(method, payload, timeout=10.0)
            rows = reply.get(result_key, [])
            for r in rows:
                r["node_id"] = node["node_id"]
            return rows
        except Exception:
            return []
        finally:
            await client.close()

    async def _all():
        return await asyncio.gather(*(_one(n) for n in nodes))

    return [row for rows in worker.io.run_sync(_all()) for row in rows]


def list_workers() -> list[dict]:
    """Workers across all alive nodes (raylet worker-pool fan-out)."""
    return _fanout_raylets("ListWorkers", {}, "workers")


def list_objects(limit: int = 1000) -> list[dict]:
    """Objects in each node's plasma store (store-level view)."""
    return _fanout_raylets("ListObjects", {"limit": limit}, "objects")


def summarize_tasks() -> dict:
    """Counts by (name, state) — reference summarize_tasks:1368."""
    summary: dict[str, dict[str, int]] = {}
    for t in list_tasks(limit=100_000):
        entry = summary.setdefault(t["name"], {})
        entry[t["state"]] = entry.get(t["state"], 0) + 1
    return summary


# ------------------------------------------------------------- diagnostics
def list_errors(source: str | None = None, error_type: str | None = None,
                limit: int = 100) -> list[dict]:
    """Structured ErrorEvents retained by the GCS error-info channel:
    raising tasks, failed actor/replica starts, OOM kills, lease-wedge
    watchdog reports (reference: the driver's error-message listener over
    RAY_ERROR_INFO_CHANNEL, surfaced as a state API)."""
    return _gcs("ListErrors", {
        "source": source, "type": error_type, "limit": limit,
    })["errors"]


def cluster_diagnostics(error_limit: int = 50) -> dict:
    """One aggregated doctor view: the GCS control-plane snapshot, every
    alive raylet's debug state (lease queue with ages, worker pool,
    store/spill/OOM counters), and the most recent ErrorEvents."""
    import asyncio

    from ..core.rpc import RpcClient

    nodes = [n for n in list_nodes() if n["state"] == "ALIVE"]
    worker = global_worker()

    async def _one(node):
        client = RpcClient(node["address"])
        try:
            reply = await client.call("GetDebugState", {}, timeout=10.0)
            snap = reply.get("debug_state") or {}
            snap.setdefault("node_id", node["node_id"])
            return snap
        except Exception as e:
            return {"node_id": node["node_id"], "unreachable": str(e)}
        finally:
            await client.close()

    async def _all():
        return await asyncio.gather(*(_one(n) for n in nodes))

    return {
        "gcs": _gcs("GetDebugState").get("debug_state", {}),
        "nodes": list(worker.io.run_sync(_all())),
        "errors": list_errors(limit=error_limit),
    }
