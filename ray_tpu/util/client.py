"""Ray Client: remote drivers over ``ray://host:port``.

Equivalent of the reference's client mode
(``python/ray/util/client/__init__.py:200``): a thin proxy server runs
next to the cluster; remote Python processes connect with
``ray_tpu.init(address="ray://host:port")`` and use the NORMAL API —
``@remote``, ``put/get/wait``, actors — while every operation executes
in the proxy's driver on the cluster. The client worker duck-types the
``CoreWorker`` surface the public API calls, so no separate client API
exists (the reference generates the same illusion with a gRPC proxy).
"""

from __future__ import annotations

import threading
import uuid
from typing import Any

import cloudpickle

from ..core import serialization
from ..core.ids import JobID, ObjectID, TaskID
from ..core.object_ref import ObjectRef, install_refcount_hooks
from ..core.rpc import EventLoopThread, RetryableRpcClient, RpcServer
from ..core.status import RayTpuError

CLIENT_PREFIX = "ray://"


class ClientServer:
    """Cluster-side proxy: executes client requests as this process's
    driver (it must run in a connected driver process — e.g. the head
    bootstrap or any ``ray_tpu.init()``'d process)."""

    def __init__(self, host: str = "0.0.0.0", port: int = 10001):
        from ..core.worker import global_worker

        self._worker = global_worker()
        self._io = EventLoopThread("raytpu-client-server")
        self._server = RpcServer(host, port)
        self._server.register_service(self)
        # Per-client object registries: client ref id -> real ObjectRef
        # (dropping a client drops its refs).
        self._refs: dict[str, dict[str, ObjectRef]] = {}
        # Actors each client session OWNS (non-detached, unnamed): killed
        # on disconnect, like handle-GC in a local driver.
        self._client_actors: dict[str, list[bytes]] = {}
        self._lock = threading.Lock()
        self._io.run_sync(self._server.start())
        self.address = self._server.address

    def stop(self) -> None:
        try:
            self._io.run_sync(self._server.stop())
        except Exception:
            pass
        self._io.stop()

    # ------------------------------------------------------------- helpers
    def _client(self, p: dict) -> dict:
        with self._lock:
            return self._refs.setdefault(p["client_id"], {})

    def _resolve(self, p: dict, wire_args: list) -> tuple[tuple, dict]:
        refs = self._client(p)
        args, kwargs = [], {}

        def fix(v):
            if isinstance(v, dict) and v.get("__client_ref__"):
                return refs[v["id"]]
            return v

        for entry in wire_args:
            value = fix(cloudpickle.loads(entry["blob"]))
            if "key" in entry:
                kwargs[entry["key"]] = value
            else:
                args.append(value)
        return tuple(args), kwargs

    def _track(self, p: dict, ref: ObjectRef) -> str:
        rid = uuid.uuid4().hex
        self._client(p)[rid] = ref
        return rid

    # ------------------------------------------------------------ handlers
    async def handle_ClientPut(self, p: dict) -> dict:
        import asyncio

        value = cloudpickle.loads(p["blob"])
        ref = await asyncio.get_running_loop().run_in_executor(
            None, self._worker.put, value)
        return {"ref": self._track(p, ref)}

    async def handle_ClientGet(self, p: dict) -> dict:
        import asyncio

        refs = self._client(p)
        try:
            targets = [refs[r] for r in p["refs"]]
        except KeyError as e:
            return {"error": cloudpickle.dumps(RayTpuError(f"unknown client ref {e}"))}
        loop = asyncio.get_running_loop()
        try:
            values = await loop.run_in_executor(
                None, lambda: self._worker.get(targets, p.get("timeout")))
        except Exception as e:
            # The as_instanceof_cause wrapper class is process-local: ship
            # the inner RayTaskError; the client re-wraps.
            inner = getattr(e, "_inner", e)
            return {"error": cloudpickle.dumps(inner)}
        return {"blob": cloudpickle.dumps(values)}

    async def handle_ClientWait(self, p: dict) -> dict:
        import asyncio

        refs = self._client(p)
        targets = [refs[r] for r in p["refs"]]
        loop = asyncio.get_running_loop()
        ready, not_ready = await loop.run_in_executor(
            None, lambda: self._worker.wait(
                targets, p["num_returns"], p.get("timeout")))
        ready_ids = [p["refs"][targets.index(r)] for r in ready]
        return {"ready": ready_ids,
                "not_ready": [r for r in p["refs"] if r not in ready_ids]}

    async def handle_ClientSubmitTask(self, p: dict) -> dict:
        import asyncio

        fn = cloudpickle.loads(p["fn"])
        args, kwargs = self._resolve(p, p["args"])
        opts = p.get("options") or {}
        loop = asyncio.get_running_loop()
        refs = await loop.run_in_executor(
            None, lambda: self._worker.submit_task(fn, args, kwargs, **opts))
        if not isinstance(refs, list):  # streaming unsupported over client v1
            return {"error": cloudpickle.dumps(
                RayTpuError("streaming tasks are not supported over ray:// yet"))}
        return {"refs": [self._track(p, r) for r in refs]}

    async def handle_ClientCreateActor(self, p: dict) -> dict:
        import asyncio

        cls = cloudpickle.loads(p["cls"])
        args, kwargs = self._resolve(p, p["args"])
        opts = p.get("options") or {}
        loop = asyncio.get_running_loop()
        try:
            actor_id = await loop.run_in_executor(
                None, lambda: self._worker.create_actor(cls, args, kwargs, **opts))
        except Exception as e:
            return {"error": cloudpickle.dumps(e)}
        if not opts.get("detached") and not opts.get("name"):
            with self._lock:
                self._client_actors.setdefault(p["client_id"], []).append(actor_id)
        return {"actor_id": actor_id.hex()}

    async def handle_ClientActorCall(self, p: dict) -> dict:
        import asyncio

        args, kwargs = self._resolve(p, p["args"])
        loop = asyncio.get_running_loop()
        refs = await loop.run_in_executor(
            None, lambda: self._worker.submit_actor_task(
                bytes.fromhex(p["actor_id"]), p["method"], args, kwargs,
                num_returns=p.get("num_returns", 1)))
        return {"refs": [self._track(p, r) for r in refs]}

    async def handle_ClientKillActor(self, p: dict) -> dict:
        self._worker.kill_actor(bytes.fromhex(p["actor_id"]))
        return {}

    async def handle_ClientGetActorByName(self, p: dict) -> dict:
        found = self._worker.get_actor_by_name(p["name"])
        if found is None:
            return {"found": False}
        return {"found": True, "actor_id": found[0].hex()}

    async def handle_ClientGcsCall(self, p: dict) -> dict:
        # read-only control-plane passthrough (cluster_resources, nodes...)
        if p["method"] not in ("GetAllNodes", "Timeline"):
            return {"error": cloudpickle.dumps(
                RayTpuError(f"GCS method {p['method']!r} not allowed over ray://"))}
        return self._worker._gcs_call(p["method"], p.get("payload") or {})

    async def handle_ClientDisconnect(self, p: dict) -> dict:
        with self._lock:
            self._refs.pop(p["client_id"], None)
            actors = self._client_actors.pop(p["client_id"], [])
        for actor_id in actors:
            # Session-owned actors die with the session (the handle-GC
            # semantics a local driver would have given them).
            try:
                self._worker.kill_actor(actor_id)
            except Exception:
                pass
        return {}


class ClientWorker:
    """Client-side stand-in for ``CoreWorker``: implements the method
    surface the public API uses, forwarding everything to the proxy."""

    def __init__(self, address: str):
        host_port = address[len(CLIENT_PREFIX):]
        self.client_id = uuid.uuid4().hex
        self.io = EventLoopThread("raytpu-client")
        self.rpc = RetryableRpcClient(host_port)
        self.node_id = "client"
        self.worker_id = f"client-{self.client_id[:12]}"
        self.job_id = JobID.from_int(0)
        self.actor_id = b""
        self.mode = "client"
        self._ref_lock = threading.Lock()
        self._local_refs: dict[bytes, str] = {}  # ObjectID binary -> server rid
        install_refcount_hooks(lambda r: None, lambda r: None)

    # ------------------------------------------------------------ plumbing
    def _call(self, method: str, payload: dict, timeout: float | None = 300.0) -> dict:
        from ..core.status import RayTaskError

        payload = {**payload, "client_id": self.client_id}
        reply = self.io.run_sync(self.rpc.call(method, payload, timeout))
        if reply.get("error"):
            err = cloudpickle.loads(reply["error"])
            if isinstance(err, RayTaskError):
                raise err.as_instanceof_cause()
            raise err
        return reply

    def _make_ref(self, rid: str) -> ObjectRef:
        # Client-side ObjectRefs carry a synthetic id; the server rid maps
        # back to the real ref.
        oid = ObjectID(bytes.fromhex(rid) + b"\x00" * (28 - len(rid) // 2))
        with self._ref_lock:
            self._local_refs[oid.binary()] = rid
        return ObjectRef(oid, owner_address="", _add_local_ref=False)

    def _rid(self, ref: ObjectRef) -> str:
        with self._ref_lock:
            rid = self._local_refs.get(ref.binary())
        if rid is None:
            raise RayTpuError("ObjectRef does not belong to this client session")
        return rid

    def _wire_args(self, args: tuple, kwargs: dict) -> list:
        out = []
        for kind, item in [(None, a) for a in args] + list(kwargs.items()):
            if isinstance(item, ObjectRef):
                blob = cloudpickle.dumps({"__client_ref__": True, "id": self._rid(item)})
            else:
                blob = cloudpickle.dumps(item)
            entry = {"blob": blob}
            if kind is not None:
                entry["key"] = kind
            out.append(entry)
        return out

    # ------------------------------------------------------------- surface
    def put(self, value: Any) -> ObjectRef:
        reply = self._call("ClientPut", {"blob": cloudpickle.dumps(value)})
        return self._make_ref(reply["ref"])

    def get(self, refs, timeout: float | None = None):
        reply = self._call("ClientGet", {
            "refs": [self._rid(r) for r in refs], "timeout": timeout,
        }, timeout=None if timeout is None else timeout + 30.0)
        return cloudpickle.loads(reply["blob"])

    def wait(self, refs, num_returns: int, timeout: float | None):
        rids = [self._rid(r) for r in refs]
        reply = self._call("ClientWait", {
            "refs": rids, "num_returns": num_returns, "timeout": timeout,
        }, timeout=None if timeout is None else timeout + 30.0)
        by_rid = dict(zip(rids, refs))
        return ([by_rid[r] for r in reply["ready"]],
                [by_rid[r] for r in reply["not_ready"]])

    def submit_task(self, fn, args, kwargs, **options) -> list[ObjectRef]:
        if options.get("num_returns") == "streaming":
            raise RayTpuError("streaming tasks are not supported over ray:// yet")
        reply = self._call("ClientSubmitTask", {
            "fn": cloudpickle.dumps(fn),
            "args": self._wire_args(args, kwargs),
            "options": options,
        })
        return [self._make_ref(r) for r in reply["refs"]]

    def create_actor(self, cls, args, kwargs, **options) -> bytes:
        reply = self._call("ClientCreateActor", {
            "cls": cloudpickle.dumps(cls),
            "args": self._wire_args(args, kwargs),
            "options": options,
        })
        return bytes.fromhex(reply["actor_id"])

    def submit_actor_task(self, actor_id: bytes, method: str, args, kwargs,
                          *, num_returns=1, generator_backpressure: int = 0):
        if num_returns == "streaming":
            raise RayTpuError("streaming actor calls are not supported over ray:// yet")
        reply = self._call("ClientActorCall", {
            "actor_id": actor_id.hex(), "method": method,
            "args": self._wire_args(args, kwargs), "num_returns": num_returns,
        })
        return [self._make_ref(r) for r in reply["refs"]]

    def kill_actor(self, actor_id: bytes) -> None:
        self._call("ClientKillActor", {"actor_id": actor_id.hex()})

    def get_actor_by_name(self, name: str):
        reply = self._call("ClientGetActorByName", {"name": name})
        if not reply.get("found"):
            return None
        return bytes.fromhex(reply["actor_id"]), reply

    def register_actor_handle(self, actor_id: bytes, owned: bool) -> None:
        pass  # client handles never own cluster actors

    def deregister_actor_handle(self, actor_id: bytes) -> None:
        pass

    def _gcs_call(self, method: str, payload: dict, timeout: float | None = 30.0) -> dict:
        return self._call("ClientGcsCall", {"method": method, "payload": payload})

    def shutdown(self) -> None:
        try:
            self._call("ClientDisconnect", {}, timeout=5.0)
        except Exception:
            pass
        try:
            self.io.run_sync(self.rpc.close(), timeout=5)
        except Exception:
            pass
        self.io.stop()

    @property
    def current_task_id(self):
        return TaskID.nil()


def connect(address: str) -> ClientWorker:
    """``ray_tpu.init(address="ray://...")`` entry point."""
    worker = ClientWorker(address)
    # round-trip to fail fast on a bad address
    worker._call("ClientGetActorByName", {"name": "__probe__"}, timeout=15.0)
    return worker
